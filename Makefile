# Convenience lanes. The python package needs no build step — these are
# the test/guard entry points CI and humans share.

PYTHON ?= python

.PHONY: test check-bench sentinel-scan

# tier-1: the full default test lane (see ROADMAP.md for the canonical
# driver invocation with its timeout/log plumbing)
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

# the bench regression sentinel, end to end on a tiny CPU config
# (tests/test_sentinel.py::test_bench_check_lane): baseline capture, a
# clean re-run of bench.py --check that must stay quiet, and a
# deterministically injected +10% slowdown (faults delay injector) that
# must exit non-zero.  ~30s wall.
check-bench:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_sentinel.py -q -m sentinel

# stat-band-aware walk over the committed driver artifacts: fails when
# the LATEST BENCH_r*.json regressed against its predecessor
sentinel-scan:
	JAX_PLATFORMS=cpu $(PYTHON) -m dlnetbench_tpu.sentinel .
