# Convenience lanes. The python package needs no build step — these are
# the test/guard entry points CI and humans share.

PYTHON ?= python

.PHONY: test check-bench check-resilience check-serving check-tuning \
	check-longcontext check-decode check-density check-telemetry \
	check-moe check-disagg check-fleet check-sampling sentinel-scan

# tier-1: the full default test lane (see ROADMAP.md for the canonical
# driver invocation with its timeout/log plumbing)
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

# the bench regression sentinel, end to end on a tiny CPU config
# (tests/test_sentinel.py::test_bench_check_lane): baseline capture, a
# clean re-run of bench.py --check that must stay quiet, and a
# deterministically injected +10% slowdown (faults delay injector) that
# must exit non-zero.  ~30s wall.
check-bench:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_sentinel.py -q -m sentinel

# the resilience lane (docs/RESILIENCE.md): fault plans + policies,
# the preempt->restore->rejoin arc on both tiers (native cases skip
# without cmake/ninja), checkpoint backends + the in-loop snapshot
# checkpointer, watchdog integration, the degraded/rejoin merge
# pathways with their committed fixtures, the Daly-interval validation
# against the committed elastic study, and the sentinel tiny baseline.
# ~2 min wall on a dev box.
check-resilience:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'not slow' \
	    tests/test_faults.py tests/test_native_faults.py \
	    tests/test_checkpoint.py tests/test_watchdog.py \
	    tests/test_goodput.py tests/test_merge.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_sentinel.py -q \
	    -m sentinel

# the serving lane (docs/SERVING.md): arrival-plan schema + fixtures,
# the paged KV cache, decode-vs-forward parity, the continuous-batching
# engine, fault composition (straggler p99 inflation, crash+shrink SLO
# dip/recovery), the committed record fixture round-trip, and the
# serving_decode bench-line schema + sentinel comparability.  The
# heavyweight load sweeps stay in the slow lane.  ~1 min wall.
check-serving:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'serving and not slow' \
	    tests/test_serving.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_serving_decode_line_schema_locked \
	    tests/test_sentinel.py::test_serving_latency_line_is_comparable

# the autotuner lane (docs/PERF.md "Autotuning"): TuningDB durability
# (torn writes, schema refusal, the writer claim/retry race), the
# seeded band-aware search, every consult site's empty-DB bit-identity,
# the committed fixture round-trip, and the tune CLI proving
# search -> commit -> consult -> hit end to end with a tiny-CPU
# 2-candidate search.  Seconds of search inside ~1 min of lane wall.
check-tuning:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_tuning.py -q \
	    -m tuning
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_tuned_ab_line_schema_locked \
	    tests/test_sentinel.py::test_tuned_ab_line_is_comparable

# the long-context lane (docs/PERF.md r13 "Block-sparse attention"):
# mask-builder verdict tables vs brute force, splash-vs-dense kernel
# parity (causal bit-identity + masked specs), sparse ring hop gating
# vs the gathered reference, the windowed serving prefill parity, and
# the longcontext_ab bench-line schema + sentinel comparability.  The
# S=64k cases live in the slow lane (pytest -m 'longcontext and slow').
# ~1 min wall.
check-longcontext:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'longcontext and not slow' \
	    tests/
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_longcontext_line_schema_locked \
	    tests/test_sentinel.py::test_longcontext_line_is_comparable

# the decode-loop lane (docs/SERVING.md "The multi-step loop"): fused
# N-step-vs-1-step token parity, speculative greedy parity (both
# drafters), the verify pass, the host/device state split's sync
# contract + round-trip property, adaptive-N policy + TTFT guard,
# config guards, CompiledLoop, the record/attribution pathway, and the
# serving A/B line schema + sentinel comparability.  The full
# 3-engine bench e2e rides the slow lane (pytest -m 'decode and
# slow').  ~1 min wall.
check-decode:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'decode and not slow' \
	    tests/
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_serving_decode_line_schema_locked \
	    tests/test_bench_aux.py::test_serving_decode_ab_schema_locked \
	    tests/test_sentinel.py::test_decode_ab_line_is_comparable

# the serving-density lane (docs/SERVING.md "Cache density"):
# quantized paged-KV config validation + pool-bytes accounting, the
# int8/fp8 decode-parity bars on the CPU mesh, the dequantizing Pallas
# kernel (interpret mode; the on-chip case stays collectable via
# tpu_only), the refcount/COW allocator property test, prefix-sharing
# losslessness + record globals, the arrival-plan prefix knobs, and
# the kv_density_ab bench-line schema + sentinel comparability.
# ~1 min wall.
check-density:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'density and not slow' \
	    tests/test_kv_density.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_kv_density_line_schema_locked \
	    tests/test_sentinel.py::test_kv_density_line_is_comparable

# the continuous-telemetry lane (docs/OBSERVABILITY.md "Continuous
# telemetry & the flight recorder"): the flight-recorder ring + anomaly
# engine contracts (disabled-path zero overhead, byte-identical
# records, step-time band detection, dump cooldowns), the serving
# SLO-breach e2e (flight_slo.json + anomalies through parser -> merge),
# the committed record_telemetry.jsonl round trip into the bandwidth
# blame columns, the critical-path blame validation (straggler ->
# injected rank, clean -> no suspect), the watchdog ring-trend
# breadcrumb, and the live-metrics line schema.  ~1 min wall.
check-telemetry:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'telemetry and not slow' \
	    tests/test_telemetry.py tests/test_critical_path.py \
	    tests/test_watchdog.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_live_metrics_line_schema_locked

# the expert-parallel MoE lane (ISSUE 15, docs/PERF.md "Expert-parallel
# MoE" / docs/SERVING.md "MoE decode"): seeded grouped routing
# (determinism, shard invariance, the capacity-factor drop closed
# form), the grouped Pallas expert-FFN kernels (count-aware skipping,
# int8 exactness, tuning-DB site), the decomposed-a2a dispatch/combine
# loop vs the monolithic pair, SPMD step parity across the knob matrix,
# the native-vs-SPMD a2a schedule-parity formula, MoE decode in the
# serving tier (per-expert batching, overflow rounds, seeded skew ->
# p99, imbalance telemetry + record/parser round trip), and the moe_ab
# bench-line schema + sentinel comparability.  ~2 min wall.
check-moe:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'moe and not slow' \
	    tests/test_moe.py tests/test_moe_serving.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_moe_ab_line_schema_locked \
	    tests/test_sentinel.py::test_moe_ab_line_is_comparable

# the disaggregated-serving lane (ISSUE 16, docs/SERVING.md
# "Disaggregated prefill/decode"): the page-migration channel's
# bit-exact quantized wire + closed-form byte accounting + overlap-leg
# discipline, the replica config guards, the adaptive-N migration-ETA
# cap, int8 token parity vs the monolithic engine, the committed
# two-replica record fixture round trip, and the disagg_ab bench-line
# schema.  The bf16 parity and prefill-crash e2e cases ride the slow
# lane (pytest -m 'disagg and slow').  ~30s wall.
check-disagg:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'disagg and not slow' \
	    tests/test_disagg.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_disagg_line_schema_locked

# the fleet-serving lane (ISSUE 18, docs/SERVING.md "Fleet serving"):
# the seeded router's policy semantics (round_robin cycling, p2c
# tie/draw rules, prefix-affinity's read-only trie probe), the diurnal
# arrival shape + committed fixture, the shared re-queue arc,
# fleet-vs-single-engine token parity + assignment replay determinism,
# the committed record_fleet.jsonl parser -> merge round trip, and the
# fleet_ab bench-line schema + sentinel comparability.  The autoscale
# and replica-crash e2e cases ride the slow lane (pytest -m 'fleet and
# slow').  ~40s wall.
check-fleet:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'fleet and not slow' \
	    tests/test_fleet.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_fleet_line_schema_locked \
	    tests/test_sentinel.py::test_fleet_ab_line_is_comparable

# the sampling lane (ISSUE 19, docs/SERVING.md "Sampling, speculation
# & constrained decode"): the fmix32 key-derivation golden values, the
# filter pipeline + inverse-CDF math, the JSON grammar automaton, the
# N-step==1-step bit-identity lock, the crash-shrink replay property,
# the chi-square distribution-equality locks (plain draws AND the
# rejection-sampling verify rule), composition with speculative decode
# and prefix sharing, the committed record_sampling.jsonl parser ->
# merge round trip (comparable identity vs volatile acceptance curve),
# the CLI flag surface, and the sampling_ab bench-line schema +
# sentinel comparability.  ~90s wall.
check-sampling:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q -m 'sampling and not slow' \
	    tests/test_sampling.py
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest -q \
	    tests/test_bench_aux.py::test_sampling_ab_line_schema_locked \
	    tests/test_sentinel.py::test_sampling_ab_line_is_comparable

# stat-band-aware walk over the committed driver artifacts: fails when
# the LATEST BENCH_r*.json regressed against its predecessor
sentinel-scan:
	JAX_PLATFORMS=cpu $(PYTHON) -m dlnetbench_tpu.sentinel .
