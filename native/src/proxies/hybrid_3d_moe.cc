// Native DP+PP+EP (MoE) proxy — reference
// cpp/hybrid_parallel/hybrid_3d_moe.cpp.  Adds expert parallelism to the
// GPipe engine: per microbatch, 2 x layers_per_stage token
// dispatch/combine all-to-alls per direction (hybrid_3d_moe.cpp:161-165)
// and a two-level gradient sync (non-expert params over EP, expert stage
// shard over DP, :202-208).  top_k comes from the model card, not a
// hardcoded 2 (reference quirk, :354-359).
#include "pipeline_engine.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args("hybrid_3d_moe — DP + PP + expert-parallel proxy (native shm)");
  add_common_args(args);
  args.required_int("num_stages", "pipeline stages")
      .required_int("num_microbatches", "microbatches per iteration")
      .required_int("num_expert_shards", "expert-parallel degree")
      .optional_int("dp", 0, "data-parallel degree (0 = infer from world)");
  add_schedule_arg(args);
  args.parse(argc, argv);

  try {
    ProxyEnv env = make_env(args);
    // no step-boundary fault driver here: refuse plans whose
    // events could only fire at step boundaries, so a record
    // never stamps fault provenance onto an actually-clean run
    // (collective-scoped and drop plans still apply via the
    // fabric hooks; fault_session.hpp)
    fault::require_collective_scope_only("hybrid_3d_moe");
    ModelCard card = load_card_for(env);
    if (card.num_experts <= 1)
      throw std::runtime_error(card.name +
                               " has no moe_params; the MoE proxy needs an "
                               "MoE architecture card");
    i64 stages = args.integer("num_stages");
    i64 mbs = args.integer("num_microbatches");
    i64 ep = args.integer("num_expert_shards");
    i64 dp = infer_dp(env.world, stages * ep, args.integer("dp"),
                      "num_stages*ep");

    MoESchedule moe = moe_schedule(env.stats, card, stages, mbs, ep, dp);
    HybridSpec spec;
    spec.pipe = moe.pipe;
    set_schedule(spec, args);
    spec.is_moe = true;
    spec.ep = ep;
    spec.a2a_elems = moe.a2a_elems;
    spec.a2a_per_direction = moe.a2a_per_direction;
    spec.nonexpert_sync = moe.nonexpert_sync_elems;
    spec.expert_sync = moe.expert_sync_elems;

    Json meta = Json::object();
    meta["proxy"] = "hybrid_3d_moe";
    meta["top_k"] = moe.top_k;
    hybrid_meta(meta, spec, env.dtype, env.cfg.size_scale, env.procs);

    return run_proxy_main(
        "hybrid_3d_moe", env, meta,
        [&](int r, Fabric& fab, TimerSet& ts, RankRun& run) {
          return hybrid_rank_body(spec, env, r, fab, ts, run);
        });
  } catch (const std::exception& e) {
    std::cerr << "hybrid_3d_moe: " << e.what() << "\n";
    return 1;
  }
}
