// pjrt_probe — diagnostic + program-emission tool for the PJRT backend.
//
// Modes:
//   --emit <op>        print the generated StableHLO module for a
//                      collective (op = all_reduce | all_gather |
//                      reduce_scatter | all_to_all | collective_permute).
//                      tests/test_pjrt_programs.py compiles and EXECUTES
//                      every emitted program on a multi-device CPU client
//                      and checks the math — the semantic validation loop
//                      for the generator.
//   --options_proto N  print the serialized CompileOptionsProto for
//                      num_replicas=N as hex (cross-checked against the
//                      real proto parser in the same pytest).
//   (default)          probe mode: resolve the PJRT plugin (libtpu.so or
//                      $DLNB_PJRT_PLUGIN), create a client, list devices,
//                      and run one end-to-end bf16 allreduce through the
//                      compile cache.  Prints a one-line JSON report;
//                      exits 0 with {"available": false} when no plugin
//                      or no devices are present (dev boxes).
#include <cstdio>
#include <iostream>
#include <string>
#include <vector>

#include "dlnb/args.hpp"
#include "dlnb/json.hpp"
#include "dlnb/pjrt_backend.hpp"
#include "dlnb/stablehlo_gen.hpp"
#include "dlnb/tensor.hpp"

using namespace dlnb;

static CollOp op_from_name(const std::string& s) {
  if (s == "all_reduce") return CollOp::AllReduce;
  if (s == "all_gather") return CollOp::AllGather;
  if (s == "reduce_scatter") return CollOp::ReduceScatter;
  if (s == "all_to_all") return CollOp::AllToAll;
  if (s == "collective_permute") return CollOp::CollectivePermute;
  throw std::runtime_error("unknown collective op '" + s + "'");
}

// "0,1;2,3" -> {{0,1},{2,3}}
static std::vector<std::vector<int>> parse_groups(const std::string& s) {
  std::vector<std::vector<int>> out;
  std::vector<int> cur;
  std::string num;
  auto flush_num = [&] {
    if (!num.empty()) {
      cur.push_back(std::stoi(num));
      num.clear();
    }
  };
  for (char c : s) {
    if (c == ',') flush_num();
    else if (c == ';') {
      flush_num();
      if (!cur.empty()) out.push_back(cur);
      cur.clear();
    } else num += c;
  }
  flush_num();
  if (!cur.empty()) out.push_back(cur);
  return out;
}

// "0>1;1>2" -> {{0,1},{1,2}}
static std::vector<std::pair<int, int>> parse_pairs(const std::string& s) {
  std::vector<std::pair<int, int>> out;
  for (const auto& grp : parse_groups(
           [&] {
             std::string t = s;
             for (char& c : t)
               if (c == '>') c = ',';
             return t;
           }())) {
    if (grp.size() == 2) out.emplace_back(grp[0], grp[1]);
  }
  return out;
}

int main(int argc, char** argv) {
  Args args("pjrt_probe — PJRT backend diagnostics and program emission");
  args.optional_str("emit", "", "emit StableHLO for this collective op")
      .optional_str("dtype", "f32", "element type: f32 | bfloat16 | float8")
      .optional_int("count", 8, "per-replica input element count")
      .optional_int("replicas", 4, "num_replicas")
      .optional_str("groups", "", "replica groups, e.g. '0,1;2,3'")
      .optional_str("pairs", "", "permute pairs, e.g. '0>1;1>2;2>0'")
      .optional_int("options_proto", 0,
                    "print CompileOptionsProto hex for N replicas")
      .optional_str("plugin", "", "PJRT plugin path override");
  args.parse(argc, argv);

  try {
    if (long long n = args.integer("options_proto"); n > 0) {
      std::string proto = compile_options_proto(static_cast<int>(n));
      for (unsigned char c : proto) std::printf("%02x", c);
      std::printf("\n");
      return 0;
    }

    if (std::string op = args.str("emit"); !op.empty()) {
      if (op == "burn") {  // the device compute-burn module (fabric.burn)
        std::cout << generate_burn_stablehlo(
            static_cast<int>(args.integer("count")));
        return 0;
      }
      CollectiveProgram prog;
      prog.op = op_from_name(op);
      prog.dtype = dtype_from_name(args.str("dtype"));
      prog.in_count = args.integer("count");
      prog.num_replicas = static_cast<int>(args.integer("replicas"));
      prog.groups = parse_groups(args.str("groups"));
      prog.pairs = parse_pairs(args.str("pairs"));
      std::cout << generate_stablehlo(prog);
      return 0;
    }

    // ---- probe mode ----
    Json report = Json::object();
    std::string plugin = args.str("plugin");
    if (plugin.empty()) plugin = default_pjrt_plugin_path();
    report["plugin"] = plugin;
#ifndef DLNB_HAVE_PJRT
    report["available"] = false;
    report["reason"] = "built without pjrt_c_api.h (DLNB_HAVE_PJRT unset)";
    std::cout << report.dump() << std::endl;
    return 0;
#else
    if (plugin.empty()) {
      report["available"] = false;
      report["reason"] = "no PJRT plugin found (set DLNB_PJRT_PLUGIN)";
      std::cout << report.dump() << std::endl;
      return 0;
    }
    // single-host topology defaults for standalone libtpu (VERDICT r2
    // #5): applied before dlopen, only for env vars that are unset; the
    // report records which ones were defaulted so the init outcome is
    // reproducible
    {
      Json applied = Json::array();
      for (const auto& name : apply_libtpu_single_host_env_defaults())
        applied.push_back(name);
      report["libtpu_env_defaults"] = applied;
    }
    try {
      PjrtContext ctx(plugin);
      report["platform"] = ctx.platform_name();
      report["num_devices"] = ctx.num_devices();
      int n = ctx.num_devices();
      if (n > 0) {
        // end-to-end: bf16 allreduce over all devices, twice (second hit
        // must come from the executable cache)
        CollectiveProgram prog;
        prog.op = CollOp::AllReduce;
        prog.dtype = DType::BF16;
        prog.in_count = 128;
        prog.num_replicas = n;
        std::vector<Tensor> src(n), dst(n);
        std::vector<const void*> sp(n);
        std::vector<void*> dp(n);
        for (int d = 0; d < n; ++d) {
          src[d] = Tensor(128, DType::BF16);
          dst[d] = Tensor(128, DType::BF16);
          src[d].fill(static_cast<float>(d + 1));
          sp[d] = src[d].data();
          dp[d] = dst[d].data();
        }
        PjrtCollectiveRunner runner{ctx};
        runner.run(prog, sp, dp, DType::BF16);
        runner.run(prog, sp, dp, DType::BF16);
        float expect = n * (n + 1) / 2.0f;
        report["allreduce_ok"] = dst[0].get(0) == expect;
        report["cache_hits"] = ctx.cache_hits();
        report["cache_misses"] = ctx.cache_misses();
      }
      report["available"] = n > 0;
    } catch (const std::exception& e) {
      report["available"] = false;
      report["reason"] = std::string(e.what());
    }
    std::cout << report.dump() << std::endl;
    return 0;
#endif
  } catch (const std::exception& e) {
    std::cerr << "pjrt_probe: " << e.what() << "\n";
    return 1;
  }
}
