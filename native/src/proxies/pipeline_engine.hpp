// Shared GPipe pipeline engine for the native hybrid proxies.
//
// One engine serves DP+PP (hybrid_2d), DP+PP+TP (hybrid_3d) and DP+PP+EP
// (hybrid_3d_moe), mirroring the reference's three near-identical inner
// loops (reference cpp/hybrid_parallel/hybrid_2d.cpp:90-169,
// hybrid_3d.cpp:142-183, hybrid_3d_moe.cpp:161-208):
//
//   phase 1  all microbatches FORWARD: stage-position-dependent
//            recv/compute/send over the pipeline axis
//            (+ per-mb TP allreduces | MoE dispatch/combine all-to-alls)
//   phase 2  all microbatches BACKWARD, directions mirrored
//   phase 3  gradient sync: DP allreduce of the stage shard
//            (MoE: two-level — non-expert over EP, then stage shard over DP)
//
// Rank grids use the Grid3D color math (tp/ep fastest-varying,
// hybrid_3d.cpp:283-300); pipeline neighbors are group-rank +-1 because
// members are ordered by world rank, which makes group rank == stage id.
#pragma once

#include "proxy_runner.hpp"

#include "dlnb/schedule.hpp"
#include "dlnb/tensor.hpp"

namespace dlnb {

struct HybridSpec {
  PipelineSchedule pipe;
  // "gpipe" (reference parity), "1f1b" (rebuild extra: per-stage warmup
  // of S-1-stage forwards, steady fwd/bwd pairs with slot-indexed Isend so
  // opposite-direction hops are in flight together, backward cooldown), or
  // "zb" (rebuild extra: ZB-H1 zero-bubble, schedule.hpp zb_ops)
  std::string schedule = "gpipe";
  // MoE extras (zero/unused unless is_moe)
  bool is_moe = false;
  i64 ep = 1;
  i64 a2a_elems = 0;          // total per-rank all-to-all buffer, elements
  i64 a2a_per_direction = 0;  // A2As per microbatch per direction
  i64 nonexpert_sync = 0;     // level-1 grad sync elems (EP group)
  i64 expert_sync = 0;        // level-2 expert stage shard elems (DP group)
};

// Fill the record's shared pipeline metadata.  `procs` is the hier
// fabric's OS-process count (1 on single-process fabrics): allreduce
// comm-model components get their split's real spanning process count
// stamped so the busbw full-mesh refusal keys on the actual DCN mesh
// width (advisor r4; analysis/bandwidth.py).
inline void hybrid_meta(Json& meta, const HybridSpec& spec, DType dtype,
                        double size_scale, i64 procs = 1) {
  const auto& p = spec.pipe;
  // the grid the engine actually runs (see run body): MoE replaces the
  // tp axis with ep, so splits/colors — and spans — follow that grid
  const Grid3D rg = spec.is_moe ? Grid3D{p.grid.dp, p.grid.pp, spec.ep}
                                : p.grid;
  const i64 world = rg.world_size();
  const i64 dp_span = procs > 1 ? axis_span_procs(
      world, procs, [&](i64 r) { return rg.dp_color(r); }) : 0;
  const i64 axis_span = procs > 1 ? axis_span_procs(
      world, procs, [&](i64 r) { return rg.tp_color(r); }) : 0;
  meta["num_stages"] = p.grid.pp;
  meta["num_microbatches"] = p.num_microbatches;
  meta["schedule"] = spec.schedule;
  // fill/drain bubble clock: stage s's first compute serializes behind s
  // upstream computes through the blocking rendezvous send/recv chain
  // (reference hybrid_2d.cpp:106-133), so measured runtime spans
  // (M + S - 1) ticks per direction, not M — same clock as the JAX tier
  meta["ticks_per_direction"] = p.num_microbatches + p.grid.pp - 1;
  // pipeline clock in UNIT ticks (1 unit = one fwd): the 2-phase
  // schedules span (1 + r)(M+S-1) and zb its greedy table's REAL
  // weighted makespan, where r = the stats' bwd/fwd ratio (2.0 for the
  // stat model — derived, not hardcoded, so a stats file breaking the
  // 2x convention reweights instead of skewing comparisons; matches the
  // JAX tier's ticks_total so cross-tier analyses divide alike)
  const double bwd_units = p.fwd_us_per_stage_mb > 0
                               ? p.bwd_us_per_stage_mb / p.fwd_us_per_stage_mb
                               : 2.0;
  meta["ticks_total"] =
      spec.schedule == "zb"
          ? zb_unit_ticks(p.grid.pp, p.num_microbatches, bwd_units)
          : (1.0 + bwd_units) * (p.num_microbatches + p.grid.pp - 1);
  meta["dp"] = p.grid.dp;
  meta["layers_per_stage"] = p.layers_per_stage;
  meta["pipe_msg_bytes"] = static_cast<i64>(
      scale_count(p.pipe_msg_elems, size_scale) * dtype_bytes(dtype));
  meta["schedule_pipe_msg_bytes"] =
      static_cast<i64>(p.pipe_msg_elems * p.bytes_per_element);
  meta["dp_sync_bytes"] = static_cast<i64>(
      scale_count(p.dp_sync_elems, size_scale) * dtype_bytes(dtype));
  if (p.grid.tp > 1) {
    meta["tp"] = p.grid.tp;
    meta["tp_msg_bytes"] = static_cast<i64>(
        scale_count(p.tp_msg_elems, size_scale) * dtype_bytes(dtype));
  }
  if (spec.is_moe) {
    meta["num_expert_shards"] = spec.ep;
    meta["a2a_bytes"] = static_cast<i64>(
        scale_count(spec.a2a_elems, size_scale) * dtype_bytes(dtype));
    meta["a2a_per_direction"] = spec.a2a_per_direction;
    meta["nonexpert_sync_bytes"] = static_cast<i64>(
        scale_count(spec.nonexpert_sync, size_scale) * dtype_bytes(dtype));
    meta["expert_sync_bytes"] = static_cast<i64>(
        scale_count(spec.expert_sync, size_scale) * dtype_bytes(dtype));
  }
  {
    // per-iteration bytes per blocking timer (analysis/bandwidth.py).
    // pp_comm: one activation message per microbatch per edge per
    // direction; middle stages bracket BOTH their recv and their send in
    // the timer, so their per-rank busbw reads conservatively (time
    // spans 2x the declared one-direction bytes) — declared as a LOWER
    // bound so the emitted table carries the caveat, not just this
    // comment.
    const i64 esz = static_cast<i64>(dtype_bytes(dtype));
    const i64 M = p.num_microbatches;
    Json cm = Json::object();
    cm["pp_comm"] = comm_timer(comm_component(
        "p2p", p.grid.pp,
        2 * M * scale_count(p.pipe_msg_elems, size_scale) * esz,
        /*bound=*/"lower", /*ops=*/2 * M));
    if (spec.is_moe) {
      cm["ep_comm"] = comm_timer(comm_component(
          "alltoall", spec.ep,
          2 * M * spec.a2a_per_direction *
              scale_count(spec.a2a_elems, size_scale) * esz,
          /*bound=*/"", /*ops=*/2 * M * spec.a2a_per_direction));
      cm["dp_ep_comm"] = comm_timer(comm_component(
          "allreduce", spec.ep,
          scale_count(spec.nonexpert_sync, size_scale) * esz,
          /*bound=*/"", /*ops=*/1, /*span=*/axis_span));
      cm["dp_comm"] = comm_timer(comm_component(
          "allreduce", p.grid.dp,
          scale_count(spec.expert_sync, size_scale) * esz,
          /*bound=*/"", /*ops=*/1, /*span=*/dp_span));
    } else {
      cm["dp_comm"] = comm_timer(comm_component(
          "allreduce", p.grid.dp,
          scale_count(p.dp_sync_elems, size_scale) * esz,
          /*bound=*/"", /*ops=*/1, /*span=*/dp_span));
      if (p.grid.tp > 1)
        cm["tp_comm"] = comm_timer(comm_component(
            "allreduce", p.grid.tp,
            4 * M * scale_count(p.tp_msg_elems, size_scale) * esz,
            /*bound=*/"", /*ops=*/4 * M, /*span=*/axis_span));
    }
    meta["comm_model"] = cm;
  }
}

// The per-rank body shared by all three hybrid proxies.
inline Json hybrid_rank_body(const HybridSpec& spec, const ProxyEnv& env,
                             int r, Fabric& fab, TimerSet& ts,
                             RankRun& run) {
  const PipelineSchedule& p = spec.pipe;
  Grid3D grid = spec.is_moe
                    ? Grid3D{p.grid.dp, p.grid.pp, spec.ep}
                    : p.grid;
  auto c = grid.coords(r);
  const int S = static_cast<int>(grid.pp);
  const int M = static_cast<int>(p.num_microbatches);
  const bool has_axis = grid.tp > 1;  // TP or EP axis present

  auto world = fab.world_comm(r);
  auto burn = [&](double us) { fab.burn(r, us, env.cfg.time_scale); };
  auto pp_comm = fab.split(r, static_cast<int>(grid.pp_color(r)), "pp_comm");
  auto dp_comm = fab.split(r, static_cast<int>(grid.dp_color(r)), "dp_comm");
  std::unique_ptr<ProxyCommunicator> axis_comm;
  // MoE always needs the EP communicator, even at ep=1 (the dispatch/
  // combine all-to-alls and the non-expert sync still run, degenerating
  // to local copies)
  if (has_axis || spec.is_moe)
    axis_comm = fab.split(r, static_cast<int>(grid.tp_color(r)),
                          spec.is_moe ? "ep_comm" : "tp_comm");

  const int stage = static_cast<int>(c.pp_id);
  const bool first = stage == 0, last = stage == S - 1;

  // buffers (zero-init RAII tensors, reference dp.cpp:227-232 style)
  i64 pipe_elems = scale_count(p.pipe_msg_elems, env.cfg.size_scale);
  i64 dp_elems = scale_count(
      spec.is_moe ? spec.expert_sync : p.dp_sync_elems, env.cfg.size_scale);
  Tensor act_out(pipe_elems, env.dtype), act_in(pipe_elems, env.dtype);
  Tensor dp_src(dp_elems, env.dtype), dp_dst(dp_elems, env.dtype);
  i64 tp_elems = 0, a2a_per_rank = 0;
  Tensor tp_src, tp_dst, a2a_src, a2a_dst, ne_src, ne_dst;
  if (has_axis && !spec.is_moe) {
    tp_elems = scale_count(p.tp_msg_elems, env.cfg.size_scale);
    tp_src = Tensor(tp_elems, env.dtype);
    tp_dst = Tensor(tp_elems, env.dtype);
  }
  if (spec.is_moe) {
    i64 total = scale_count(spec.a2a_elems, env.cfg.size_scale);
    a2a_per_rank = (total + spec.ep - 1) / spec.ep;
    a2a_src = Tensor(a2a_per_rank * spec.ep, env.dtype);
    a2a_dst = Tensor(a2a_per_rank * spec.ep, env.dtype);
    i64 ne = scale_count(spec.nonexpert_sync, env.cfg.size_scale);
    ne_src = Tensor(ne, env.dtype);
    ne_dst = Tensor(ne, env.dtype);
  }

  auto axis_traffic = [&](TimerSet& t) {
    if (spec.is_moe) {
      // dispatch + combine per MoE layer (hybrid_3d_moe.cpp:161-165)
      for (i64 a = 0; a < spec.a2a_per_direction; ++a) {
        auto sc = t.scoped("ep_comm");
        axis_comm->Alltoall(a2a_src.data(), a2a_dst.data(), a2a_per_rank);
      }
    } else if (has_axis) {
      // column+row parallel linear allreduces (hybrid_3d.cpp:142-148)
      for (int i = 0; i < 2; ++i) {
        auto sc = t.scoped("tp_comm");
        axis_comm->Allreduce(tp_src.data(), tp_dst.data(), tp_elems);
      }
    }
  };

  // 1f1b and zb use slot-indexed Isend (slot 0 = up, slot 1 = down) so
  // the two directions can be in flight together; the slot is drained
  // (untimed) right before reuse, and each direction has its own out
  // buffer (allocated for every non-gpipe schedule).
  Tensor act_out2(spec.schedule != "gpipe" ? pipe_elems : 0,
                  env.dtype);
  bool up_pending = false, down_pending = false;

  auto fwd_mb = [&](TimerSet& t) {
    if (S == 1) {
      burn(p.fwd_us_per_stage_mb);
      return;
    }
    if (!first) {
      auto sc = t.scoped("pp_comm");
      pp_comm->Recv(act_in.data(), pipe_elems, stage - 1);
    }
    burn(p.fwd_us_per_stage_mb);
    if (!last) {
      if (spec.schedule == "gpipe") {
        auto sc = t.scoped("pp_comm");
        pp_comm->Send(act_out.data(), pipe_elems, stage + 1);
      } else {
        if (up_pending) pp_comm->Wait(0);
        auto sc = t.scoped("pp_comm");
        pp_comm->Isend(act_out.data(), pipe_elems, stage + 1, 0, /*tag=*/0);
        up_pending = true;
      }
    }
  };
  auto bwd_mb = [&](TimerSet& t, bool half = false) {
    double bwd_us = p.bwd_us_per_stage_mb * (half ? 0.5 : 1.0);
    if (S == 1) {
      burn(bwd_us);
      return;
    }
    if (!last) {
      auto sc = t.scoped("pp_comm");
      pp_comm->Recv(act_in.data(), pipe_elems, stage + 1);
    }
    burn(bwd_us);
    if (!first) {
      if (spec.schedule == "gpipe") {
        auto sc = t.scoped("pp_comm");
        pp_comm->Send(act_out.data(), pipe_elems, stage - 1);
      } else {
        if (down_pending) pp_comm->Wait(1);
        auto sc = t.scoped("pp_comm");
        pp_comm->Isend(act_out2.data(), pipe_elems, stage - 1, 1, /*tag=*/0);
        down_pending = true;
      }
    }
  };

  // zb's op program is a pure function of (S, M, stage): built once,
  // outside the measured region (the greedy is O(S x ticks))
  const std::vector<ZBOp> zb_program =
      spec.schedule == "zb" ? zb_ops(S, M, stage) : std::vector<ZBOp>{};
  run = run_measured(env.cfg, *world, ts, [&](TimerSet& t) {
    if (spec.schedule == "gpipe") {
      // ---- phase 1: all microbatches forward (hybrid_2d.cpp:106-133),
      //      phase 2: all backward, mirrored (hybrid_2d.cpp:135-161) ----
      for (int mb = 0; mb < M; ++mb) {
        fwd_mb(t);
        axis_traffic(t);
      }
      for (int mb = 0; mb < M; ++mb) {
        bwd_mb(t);
        axis_traffic(t);
      }
    } else if (spec.schedule == "zb") {
      // ---- ZB-H1 zero-bubble: execute this stage's op program from the
      // shared greedy tables (schedule.hpp zb_ops, mirroring the JAX
      // tier's core/schedule.py zb_tables).  F hops up, the input-grad
      // half B hops down (slot-indexed Isends as in 1f1b), and the local
      // weight-grad half W burns without any hop — the op that fills the
      // 1f1b drain bubble. ----
      for (const ZBOp& op : zb_program) {
        if (op.kind == 'F') {
          fwd_mb(t);
          axis_traffic(t);
        } else if (op.kind == 'B') {
          bwd_mb(t, /*half=*/true);
          axis_traffic(t);
        } else {
          burn(p.bwd_us_per_stage_mb / 2);
        }
      }
      if (up_pending) { pp_comm->Wait(0); up_pending = false; }
      if (down_pending) { pp_comm->Wait(1); down_pending = false; }
    } else {
      // ---- 1f1b: per-stage warmup, steady pairs, cooldown ----
      const int warm = std::min(S - 1 - stage, M);
      for (int i = 0; i < warm; ++i) {
        fwd_mb(t);
        axis_traffic(t);
      }
      for (int i = 0; i < M - warm; ++i) {
        fwd_mb(t);
        axis_traffic(t);
        bwd_mb(t);
        axis_traffic(t);
      }
      for (int i = 0; i < warm; ++i) {
        bwd_mb(t);
        axis_traffic(t);
      }
      if (up_pending) { pp_comm->Wait(0); up_pending = false; }
      if (down_pending) { pp_comm->Wait(1); down_pending = false; }
    }
    // ---- phase 3: gradient sync ----
    if (spec.is_moe) {
      // two-level: non-expert params over EP, expert stage shard over DP
      // (hybrid_3d_moe.cpp:202-208)
      {
        auto sc = t.scoped("dp_ep_comm");
        axis_comm->Allreduce(ne_src.data(), ne_dst.data(), ne_src.count());
      }
      auto sc = t.scoped("dp_comm");
      dp_comm->Allreduce(dp_src.data(), dp_dst.data(), dp_elems);
    } else {
      // blocking DP allreduce of this stage's shard (hybrid_2d.cpp:163-166)
      auto sc = t.scoped("dp_comm");
      dp_comm->Allreduce(dp_src.data(), dp_dst.data(), dp_elems);
    }
  });

  // one entry per run for every timer (reference merge,
  // hybrid_2d.cpp:416-439): edge stages make 2M pp entries per iteration,
  // middle stages 4M
  if (S > 1) ts.merge_entries("pp_comm", (first || last) ? 2 * M : 4 * M);
  if (has_axis && !spec.is_moe) ts.merge_entries("tp_comm", 4 * M);
  if (spec.is_moe)
    ts.merge_entries("ep_comm",
                     2 * M * static_cast<std::size_t>(spec.a2a_per_direction));

  Json extra = Json::object();
  extra["stage_id"] = stage;
  extra["dp_id"] = c.dp_id;
  if (has_axis) extra[spec.is_moe ? "ep_id" : "tp_id"] = c.tp_id;
  return extra;
}

// Shared --schedule flag registration + validated assignment (keeps the
// three proxy mains in lockstep).
inline void add_schedule_arg(Args& args) {
  args.optional_str("schedule", "gpipe",
                    "pipeline schedule: gpipe (reference parity), 1f1b, "
                    "or zb (ZB-H1 zero-bubble)");
}

inline void set_schedule(HybridSpec& spec, const Args& args) {
  spec.schedule = args.str("schedule");
  if (spec.schedule != "gpipe" && spec.schedule != "1f1b" &&
      spec.schedule != "zb")
    throw std::runtime_error("unknown schedule: " + spec.schedule);
}

// Infer dp from world when not given (matches the Python tier's _infer_dp).
inline i64 infer_dp(i64 world, i64 inner, i64 dp_flag,
                    const std::string& label) {
  if (dp_flag > 0) {
    if (dp_flag * inner != world)
      throw std::runtime_error("world " + std::to_string(world) +
                               " != dp " + std::to_string(dp_flag) + " x " +
                               label + " " + std::to_string(inner));
    return dp_flag;
  }
  if (world % inner != 0)
    throw std::runtime_error("world " + std::to_string(world) +
                             " not divisible by " + label + " " +
                             std::to_string(inner));
  return world / inner;
}

}  // namespace dlnb
