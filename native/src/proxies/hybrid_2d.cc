// Native DP+PP proxy (GPipe) — reference cpp/hybrid_parallel/hybrid_2d.cpp.
#include "pipeline_engine.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args("hybrid_2d — DP + GPipe pipeline proxy (native shm backend)");
  add_common_args(args);
  args.required_int("num_stages", "pipeline stages")
      .required_int("num_microbatches", "microbatches per iteration")
      .optional_int("dp", 0, "data-parallel degree (0 = infer from world)");
  add_schedule_arg(args);
  args.parse(argc, argv);

  try {
    ProxyEnv env = make_env(args);
    // no step-boundary fault driver here: refuse plans whose
    // events could only fire at step boundaries, so a record
    // never stamps fault provenance onto an actually-clean run
    // (collective-scoped and drop plans still apply via the
    // fabric hooks; fault_session.hpp)
    fault::require_collective_scope_only("hybrid_2d");
    ModelCard card = load_card_for(env);
    i64 stages = args.integer("num_stages");
    i64 mbs = args.integer("num_microbatches");
    i64 dp = infer_dp(env.world, stages, args.integer("dp"), "num_stages");

    HybridSpec spec;
    spec.pipe = pipeline_schedule(env.stats, card, stages, mbs, dp, 1);
    set_schedule(spec, args);

    Json meta = Json::object();
    meta["proxy"] = "hybrid_2d";
    hybrid_meta(meta, spec, env.dtype, env.cfg.size_scale, env.procs);

    return run_proxy_main(
        "hybrid_2d", env, meta,
        [&](int r, Fabric& fab, TimerSet& ts, RankRun& run) {
          return hybrid_rank_body(spec, env, r, fab, ts, run);
        });
  } catch (const std::exception& e) {
    std::cerr << "hybrid_2d: " << e.what() << "\n";
    return 1;
  }
}
