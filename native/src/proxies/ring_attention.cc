// Native ring-attention (context-parallel) proxy — rebuild extension.
//
// No reference counterpart (SURVEY.md §2.5/§5.7: the reference has no
// sequence parallelism).  Schedule mirrors the Python tier's
// proxies/ring_attention.py: the sequence axis is sharded over `sp` ranks;
// each attention layer rotates K/V blocks around the ring (sp-1 hops of
// Isend/Irecv with the next/prev rank) while computing block-local
// attention, so communication hides behind compute; backward mirrors the
// ring at ~2x compute; MLP compute burns between layers; dp > 1 closes the
// step with a gradient allreduce.
#include "pipeline_engine.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args("ring_attention — context-parallel KV-ring proxy (native shm)");
  add_common_args(args);
  args.required_int("sp", "sequence-parallel (ring) degree")
      .optional_int("dp", 0, "data-parallel degree (0 = infer from world)")
      .optional_int("max_layers", 0, "cap simulated layers (0 = all)");
  args.parse(argc, argv);

  try {
    ProxyEnv env = make_env(args);
    // no step-boundary fault driver here: refuse plans whose
    // events could only fire at step boundaries, so a record
    // never stamps fault provenance onto an actually-clean run
    // (collective-scoped and drop plans still apply via the
    // fabric hooks; fault_session.hpp)
    fault::require_collective_scope_only("ring_attention");
    ModelCard card = load_card_for(env);
    i64 sp = args.integer("sp");
    i64 dp = infer_dp(env.world, sp, args.integer("dp"), "sp");
    SequenceSchedule sched = sequence_schedule(env.stats, card, sp);
    i64 max_layers = args.integer("max_layers");
    i64 layers = max_layers > 0 ? std::min(sched.layers, max_layers)
                                : sched.layers;
    double mlp_us_per_layer =
        (env.stats.ffn_fwd_us / std::max<i64>(sched.layers, 1)) / sp;

    i64 kv_elems = scale_count(sched.kv_block_elems, env.cfg.size_scale);
    i64 grad_elems = scale_count(env.stats.model_size / std::max<i64>(sp, 1),
                                 env.cfg.size_scale);

    Json meta = Json::object();
    meta["proxy"] = "ring_attention";
    meta["sp"] = sp;
    meta["dp"] = dp;
    meta["layers"] = layers;
    meta["num_ring_hops"] = sched.num_ring_hops;
    meta["kv_block_bytes"] =
        static_cast<i64>(kv_elems * dtype_bytes(env.dtype));
    meta["schedule_kv_block_bytes"] =
        static_cast<i64>(sched.kv_block_elems * sched.bytes_per_element);
    meta["attn_us_per_block"] = sched.attn_us_per_block * env.cfg.time_scale;
    meta["attn_time_source"] = sched.attn_time_source;
    {
      i64 shifts = 2 * layers * (sp - 1);  // fwd + bwd ring passes
      const Grid3D mg{dp, 1, sp};  // same grid the rank body runs
      Json cm = Json::object();
      cm["ring_comm"] = comm_timer(comm_component(
          "p2p", sp, shifts * kv_elems *
                         static_cast<i64>(dtype_bytes(env.dtype))));
      if (dp > 1)
        cm["dp_comm"] = comm_timer(comm_component(
            "allreduce", dp,
            grad_elems * static_cast<i64>(dtype_bytes(env.dtype)),
            /*bound=*/"", /*ops=*/1,
            /*span=*/env.procs > 1
                ? axis_span_procs(env.world, env.procs,
                                  [&](i64 r) { return mg.dp_color(r); })
                : 0));
      meta["comm_model"] = cm;
    }

    return run_proxy_main(
        "ring_attention", env, meta,
        [&](int r, Fabric& fab, TimerSet& ts, RankRun& run) {
          // sp fastest-varying: ring peers are consecutive world ranks
          Grid3D grid{dp, 1, sp};
          auto c = grid.coords(r);
          auto world = fab.world_comm(r);
          auto sp_comm =
              fab.split(r, static_cast<int>(grid.tp_color(r)), "sp_comm");
          std::unique_ptr<ProxyCommunicator> dp_comm;
          if (dp > 1)
            dp_comm =
                fab.split(r, static_cast<int>(grid.dp_color(r)), "dp_comm");

          auto burn = [&](double us) { fab.burn(r, us, env.cfg.time_scale); };
          Tensor kv_out(kv_elems, env.dtype), kv_in(kv_elems, env.dtype);
          Tensor g_src(grad_elems, env.dtype), g_dst(grad_elems, env.dtype);

          auto ring_pass = [&](TimerSet& t, double block_us) {
            for (i64 hop = 0; hop < sp; ++hop) {
              burn(block_us);
              if (hop < sp - 1) {
                auto sc = t.scoped("ring_comm");
                // rotate every rank's KV block to its successor — the
                // ppermute idiom; a native collective_permute on the pjrt
                // backend, paired Isend/Irecv on shm
                sp_comm->RingShift(kv_out.data(), kv_in.data(), kv_elems);
              }
            }
          };

          run = run_measured(env.cfg, *world, ts, [&](TimerSet& t) {
            for (i64 l = 0; l < layers; ++l) {  // forward
              ring_pass(t, sched.attn_us_per_block);
              burn(mlp_us_per_layer);
            }
            for (i64 l = 0; l < layers; ++l) {  // backward ~2x
              ring_pass(t, 2 * sched.attn_us_per_block);
              burn(2 * mlp_us_per_layer);
            }
            if (dp_comm) {
              auto sc = t.scoped("dp_comm");
              dp_comm->Allreduce(g_src.data(), g_dst.data(), grad_elems);
            }
          });
          if (sp > 1)
            ts.merge_entries("ring_comm", 2 * layers * (sp - 1));

          Json extra = Json::object();
          extra["sp_id"] = c.tp_id;
          extra["dp_id"] = c.dp_id;
          return extra;
        });
  } catch (const std::exception& e) {
    std::cerr << "ring_attention: " << e.what() << "\n";
    return 1;
  }
}
