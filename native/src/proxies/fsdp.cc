// Native FSDP proxy: ZeRO-3 unit allgather prefetch + reduce-scatter.
//
// Schedule (reference cpp/data_parallel/fsdp.cpp:73-163): the model is
// split into units, each sharded across `sharding_factor` ranks; world =
// sharding_factor x num_replicas (fsdp.cpp:217,258).  Forward allgathers
// unit u+1 asynchronously while computing unit u (prefetch); backward
// prefetches unit u-1, reduce-scatters unit u's gradients, and — with
// replicas — cross-replica Iallreduces each gradient shard, drained by a
// final WaitAll timed as "barrier_time".  Two communicators: intra-shard
// `unit_comm` (color = rank / sharding_factor) and inter-replica
// `allreduce_comm` (color = rank % sharding_factor) (fsdp.cpp:257-265).
#include "proxy_runner.hpp"

#include "dlnb/schedule.hpp"
#include "dlnb/tensor.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args("fsdp — ZeRO-3 allgather/reduce-scatter proxy (native shm)");
  add_common_args(args);
  args.required_int("num_units", "model units (allgather granularity)");
  args.optional_int("sharding_factor", 0,
                    "ranks per shard group (0 = whole world, no replicas)");
  args.parse(argc, argv);

  try {
    ProxyEnv env = make_env(args);
    i64 num_units = args.integer("num_units");
    i64 sf = args.integer("sharding_factor");
    FSDPSchedule sched =
        fsdp_schedule(env.stats, num_units, env.world, sf);
    bool has_replicas = sched.num_replicas > 1;

    Json meta = Json::object();
    meta["proxy"] = "fsdp";
    meta["num_units"] = num_units;
    meta["sharding_factor"] = sched.sharding_factor;
    meta["num_replicas"] = sched.num_replicas;
    i64 shard_elems = scale_count(sched.shard_size, env.cfg.size_scale);
    meta["shard_bytes"] =
        static_cast<i64>(shard_elems * dtype_bytes(env.dtype));
    meta["schedule_shard_bytes"] = static_cast<i64>(
        sched.shard_size * env.stats.bytes_per_element);
    i64 unit_bytes = static_cast<i64>(
        shard_elems * sched.sharding_factor * dtype_bytes(env.dtype));
    meta["unit_bytes"] = unit_bytes;
    meta["fwd_us_per_unit"] = sched.fwd_us_per_unit * env.cfg.time_scale;
    meta["bwd_us_per_unit"] = sched.bwd_us_per_unit * env.cfg.time_scale;
    {
      // blocking timers only: "allgather" brackets the one initial
      // blocking gather, "reduce_scatter" all U scatters; the
      // allgather_wait_* timers measure exposed tails of async gathers
      // (bandwidth from a wait would read as infinite under overlap)
      Json cm = Json::object();
      cm["allgather"] = comm_timer(comm_component(
          "allgather", sched.sharding_factor, unit_bytes));
      cm["reduce_scatter"] = comm_timer(comm_component(
          "reduce_scatter", sched.sharding_factor,
          sched.num_units * unit_bytes, /*bound=*/"",
          /*ops=*/sched.num_units));
      meta["comm_model"] = cm;
    }

    // fault plans apply (step/collective delay, drop, crash fail-fast),
    // but the ZeRO grid cannot regroup around a dead rank: refuse a
    // crash+shrink plan instead of half-applying it (dp supports it)
    fault::require_no_shrink("fsdp");

    return run_proxy_main(
        "fsdp", env, meta,
        [&](int r, Fabric& fab, TimerSet& ts, RankRun& run) {
          // grid colors (reference fsdp.cpp:257-265)
          int unit_color = r / static_cast<int>(sched.sharding_factor);
          int repl_color = r % static_cast<int>(sched.sharding_factor);
          auto world = fab.world_comm(r);
          auto unit_comm = fab.split(r, unit_color, "unit_comm");
          auto ar_comm = fab.split(r, repl_color, "allreduce_comm");

          const int U = static_cast<int>(sched.num_units);
          i64 unit_elems = shard_elems * sched.sharding_factor;
          // per-unit: local shard, gathered full unit, grad shard out
          std::vector<Tensor> shards, fulls, grad_shards;
          for (int u = 0; u < U; ++u) {
            shards.emplace_back(shard_elems, env.dtype);
            fulls.emplace_back(unit_elems, env.dtype);
            grad_shards.emplace_back(shard_elems, env.dtype);
          }
          std::vector<Tensor> repl_sums;
          if (has_replicas)
            for (int u = 0; u < U; ++u)
              repl_sums.emplace_back(shard_elems, env.dtype);

          auto burn = [&](double us) { fab.burn(r, us, env.cfg.time_scale); };
          run = run_measured(env.cfg, *world, ts, [&](TimerSet& t) {
            // step-boundary fault injection (delay/jitter sleeps,
            // crash fail-fast); no-op without an active plan
            fault::step_guard(fab, r);
            // initial blocking allgather of unit 0 (fsdp.cpp:86-91)
            {
              auto sc = t.scoped("allgather");
              unit_comm->Allgather(shards[0].data(), fulls[0].data(),
                                   shard_elems);
            }
            // forward: prefetch next unit while computing (fsdp.cpp:95-108)
            for (int u = 0; u < U - 1; ++u) {
              unit_comm->Iallgather(shards[u + 1].data(), fulls[u + 1].data(),
                                    shard_elems, u + 1);
              burn(sched.fwd_us_per_unit);
              auto sc = t.scoped("allgather_wait_fwd");
              unit_comm->Wait(u + 1);
            }
            burn(sched.fwd_us_per_unit);  // last unit

            // backward: prefetch prev, compute, reduce-scatter grads
            // (fsdp.cpp:111-140)
            for (int u = U - 1; u >= 1; --u) {
              unit_comm->Iallgather(shards[u - 1].data(), fulls[u - 1].data(),
                                    shard_elems, u - 1);
              burn(sched.bwd_us_per_unit);
              {
                auto sc = t.scoped("reduce_scatter");
                unit_comm->ReduceScatterBlock(fulls[u].data(),
                                              grad_shards[u].data(),
                                              shard_elems);
              }
              if (has_replicas)
                ar_comm->Iallreduce(grad_shards[u].data(),
                                    repl_sums[u].data(), shard_elems, u);
              auto sc = t.scoped("allgather_wait_bwd");
              unit_comm->Wait(u - 1);
            }
            // unit 0 backward + reduce-scatter (fsdp.cpp:143-152)
            burn(sched.bwd_us_per_unit);
            {
              auto sc = t.scoped("reduce_scatter");
              unit_comm->ReduceScatterBlock(fulls[0].data(),
                                            grad_shards[0].data(),
                                            shard_elems);
            }
            if (has_replicas) {
              ar_comm->Iallreduce(grad_shards[0].data(), repl_sums[0].data(),
                                  shard_elems, 0);
              // drain cross-replica syncs (fsdp.cpp:153-162)
              auto sc = t.scoped("barrier_time");
              ar_comm->WaitAll(U);
            }
          });

          // collapse per-unit entries into per-iteration totals so every
          // timer has one value per run (the reference does the same merge
          // for middle-stage PP timers, hybrid_2d.cpp:416-439)
          if (U > 1) {
            ts.merge_entries("allgather_wait_fwd", U - 1);
            ts.merge_entries("allgather_wait_bwd", U - 1);
          }
          ts.merge_entries("reduce_scatter", U);

          Json extra = Json::object();
          extra["shard_group"] = unit_color;
          extra["replica_id"] = repl_color;
          return extra;
        });
  } catch (const std::exception& e) {
    std::cerr << "fsdp: " << e.what() << "\n";
    return 1;
  }
}
