// hier_selftest — correctness check of the hierarchical ICI×DCN fabric.
//
// Launched once per OS process; each process runs world/procs local rank
// threads over its own CollectiveExecutor (HostExecutor in CI, the PJRT
// plugin on a real TPU host) and the processes compose over the TCP
// mesh.  Every collective, both split orientations (groups contained in
// one process and groups spanning processes), and cross-process p2p are
// verified by every global rank — the "correct sums" proof for the
// native multi-host DEVICE path (reference role: multi-node NCCL,
// cpp/data_parallel/dp.cpp:166-189).
//
//   hier_selftest --world 4 --procs 2 --rank 0 --coordinator 127.0.0.1:9310
#include <cstdio>
#include <iostream>

#include "dlnb/args.hpp"
#include "dlnb/hier_fabric.hpp"
#include "dlnb/tensor.hpp"

using namespace dlnb;

namespace {

#define REQUIRE(cond)                                                    \
  do {                                                                   \
    if (!(cond)) {                                                       \
      throw std::runtime_error(std::string("check failed: ") + #cond +   \
                               " (" __FILE__ ":" + std::to_string(__LINE__) + \
                               ")");                                     \
    }                                                                    \
  } while (0)

void rank_body(int g, int world, int procs, HierFabric& fab) {
  // balanced contiguous layout, uneven-aware (schedule.hpp): colors
  // below are derived from (proc, local index) so the selftest covers
  // worlds that do NOT divide procs (ragged last hosts)
  auto proc_of = [&](int r) {
    return static_cast<int>(balanced_proc_of(world, procs, r));
  };
  auto lidx_of = [&](int r) {
    return r - static_cast<int>(balanced_start(world, procs, proc_of(r)));
  };
  auto comm = fab.world_comm(g);
  REQUIRE(comm->rank() == g);
  REQUIRE(comm->size() == world);

  // world allreduce: sum of (g+1)
  {
    Tensor src(8, DType::F32), dst(8, DType::F32);
    src.fill(static_cast<float>(g + 1));
    comm->Allreduce(src.data(), dst.data(), 8);
    float expect = world * (world + 1) / 2.0f;
    REQUIRE(dst.get(0) == expect && dst.get(7) == expect);
  }
  // world allgather: blocks land at GLOBAL rank offsets
  {
    Tensor src(2, DType::F32), dst(2 * world, DType::F32);
    src.set(0, static_cast<float>(g));
    src.set(1, static_cast<float>(10 * g));
    comm->Allgather(src.data(), dst.data(), 2);
    for (int r = 0; r < world; ++r) {
      REQUIRE(dst.get(2 * r) == static_cast<float>(r));
      REQUIRE(dst.get(2 * r + 1) == static_cast<float>(10 * r));
    }
  }
  // reduce-scatter-block: each block sums all ranks' g
  {
    Tensor src(2 * world, DType::F32), dst(2, DType::F32);
    src.fill(static_cast<float>(g));
    comm->ReduceScatterBlock(src.data(), dst.data(), 2);
    float expect = world * (world - 1) / 2.0f;
    REQUIRE(dst.get(0) == expect && dst.get(1) == expect);
  }
  // alltoall: dst block q = 100*q + g
  {
    Tensor src(world, DType::F32), dst(world, DType::F32);
    for (int q = 0; q < world; ++q)
      src.set(q, static_cast<float>(100 * g + q));
    comm->Alltoall(src.data(), dst.data(), 1);
    for (int q = 0; q < world; ++q)
      REQUIRE(dst.get(q) == static_cast<float>(100 * q + g));
  }
  // async slot discipline: two in-flight Iallreduce ride distinct slots
  // through BOTH levels (local device rendezvous + TCP frames)
  {
    Tensor a(4, DType::F32), b(4, DType::F32);
    Tensor oa(4, DType::F32), ob(4, DType::F32);
    a.fill(1.0f);
    b.fill(2.0f);
    comm->Iallreduce(a.data(), oa.data(), 4, 0);
    comm->Iallreduce(b.data(), ob.data(), 4, 1);
    comm->WaitAll(2);
    REQUIRE(oa.get(0) == static_cast<float>(world));
    REQUIRE(ob.get(0) == static_cast<float>(2 * world));
  }
  // ring rotation crossing the process boundary
  if (world > 1) {
    Tensor out(4, DType::F32), in(4, DType::F32);
    out.fill(static_cast<float>(g));
    comm->RingShift(out.data(), in.data(), 4);
    REQUIRE(in.get(0) == static_cast<float>((g + world - 1) % world));
  }
  // split with groups SPANNING processes (color = local index: members
  // stride across the process boundary — the DCN-active orientation;
  // with uneven locals the higher indices exist only on the larger
  // processes, so the spanning groups are themselves uneven)
  {
    auto span = fab.split(g, lidx_of(g), "span");
    int G = span->size();
    Tensor src(2, DType::F32), dst(2, DType::F32);
    src.fill(static_cast<float>(g));
    span->Allreduce(src.data(), dst.data(), 2);
    float expect = 0;  // sum over {r : lidx_of(r) == lidx_of(g)}
    for (int r = 0; r < world; ++r)
      if (lidx_of(r) == lidx_of(g)) expect += static_cast<float>(r);
    REQUIRE(dst.get(0) == expect);
    // reduce-scatter on the spanning group
    Tensor rs_src(G, DType::F32), rs_dst(1, DType::F32);
    rs_src.fill(static_cast<float>(g));
    span->ReduceScatterBlock(rs_src.data(), rs_dst.data(), 1);
    REQUIRE(rs_dst.get(0) == expect);
  }
  // split with groups of UNEVEN per-process membership spanning strict
  // SUBSETS of the processes.  At world 12 / procs 3: group 0 = two of
  // proc 0's ranks + all four of proc 1's (2+4 members), group 1 = two
  // of proc 0's + two of proc 2's (procs {0,2} — a NON-adjacent
  // subset), group 2 contained in proc 2.  Each process's LOCAL color
  // partition stays uniform — the XLA SPMD replica_groups constraint
  // (pjrt_fabric.hpp header) — while the DCN routing sees every
  // uneven/subset shape.
  {
    auto uneven_color = [&](int r) {
      int p = proc_of(r), i = lidx_of(r);
      if (world == 12 && procs == 3)
        return p == 0 ? (i < 2 ? 0 : 1) : (p == 1 ? 0 : (i < 2 ? 1 : 2));
      return 0;  // elsewhere: one group spanning every process
    };
    auto unev = fab.split(g, uneven_color(g), "uneven");
    std::vector<int> mem;
    for (int r = 0; r < world; ++r)
      if (uneven_color(r) == uneven_color(g)) mem.push_back(r);
    int G = static_cast<int>(mem.size());
    int gr = -1;
    for (int k = 0; k < G; ++k)
      if (mem[k] == g) gr = k;
    REQUIRE(unev->size() == G && unev->rank() == gr);
    // allgather: exact-size packed blocks, global group-rank order
    Tensor one(1, DType::F32), ag(G, DType::F32);
    one.set(0, static_cast<float>(g));
    unev->Allgather(one.data(), ag.data(), 1);
    for (int k = 0; k < G; ++k)
      REQUIRE(ag.get(k) == static_cast<float>(mem[k]));
    // alltoall: per-destination block routing
    Tensor as(G, DType::F32), ad(G, DType::F32);
    for (int q = 0; q < G; ++q)
      as.set(q, static_cast<float>(100 * g + q));
    unev->Alltoall(as.data(), ad.data(), 1);
    for (int q = 0; q < G; ++q)
      REQUIRE(ad.get(q) == static_cast<float>(100 * mem[q] + gr));
    // reduce-scatter: summed partials routed to each block's owner
    Tensor rs(G, DType::F32), rd(1, DType::F32);
    rs.fill(static_cast<float>(g));
    unev->ReduceScatterBlock(rs.data(), rd.data(), 1);
    float expect = 0;
    for (int r : mem) expect += static_cast<float>(r);
    REQUIRE(rd.get(0) == expect);
    // ring rotation: boundary-only block routing
    if (G > 1) {
      Tensor ro(2, DType::F32), ri(2, DType::F32);
      ro.fill(static_cast<float>(g));
      unev->RingShift(ro.data(), ri.data(), 2);
      REQUIRE(ri.get(0) == static_cast<float>(mem[(gr + G - 1) % G]));
    }
  }
  // RAGGED split (color = g % 2): with uneven locals (e.g. world 5 /
  // procs 2 -> locals 3,2) a process's restriction has UNEQUAL color
  // groups — no uniform replica_groups exists for a local device
  // module, so the fabric must take the host-local path
  // (GroupSet::local_uniform == false) while processes with uniform
  // restrictions may stay on the device path; the DCN wire format is
  // shared.  At even worlds this split degenerates to the device path
  // — same checks, both routes.
  if (world > 2) {
    auto rag = fab.split(g, g % 2, "ragged");
    std::vector<int> mem;
    for (int r = 0; r < world; ++r)
      if (r % 2 == g % 2) mem.push_back(r);
    int G = static_cast<int>(mem.size());
    int gr = -1;
    for (int k = 0; k < G; ++k)
      if (mem[k] == g) gr = k;
    REQUIRE(rag->size() == G && rag->rank() == gr);
    // allreduce: sum over the group's members
    Tensor src(3, DType::F32), dst(3, DType::F32);
    src.fill(static_cast<float>(g));
    rag->Allreduce(src.data(), dst.data(), 3);
    float expect = 0;
    for (int r : mem) expect += static_cast<float>(r);
    REQUIRE(dst.get(0) == expect && dst.get(2) == expect);
    // allgather in group-rank order
    Tensor one(1, DType::F32), ag(G, DType::F32);
    one.set(0, static_cast<float>(g));
    rag->Allgather(one.data(), ag.data(), 1);
    for (int k = 0; k < G; ++k)
      REQUIRE(ag.get(k) == static_cast<float>(mem[k]));
    // reduce-scatter-block: every block gets the member sum
    Tensor rs(G, DType::F32), rd(1, DType::F32);
    rs.fill(static_cast<float>(g));
    rag->ReduceScatterBlock(rs.data(), rd.data(), 1);
    REQUIRE(rd.get(0) == expect);
    // alltoall block routing
    Tensor as(G, DType::F32), ad(G, DType::F32);
    for (int q = 0; q < G; ++q)
      as.set(q, static_cast<float>(100 * g + q));
    rag->Alltoall(as.data(), ad.data(), 1);
    for (int q = 0; q < G; ++q)
      REQUIRE(ad.get(q) == static_cast<float>(100 * mem[q] + gr));
    // ring rotation
    if (G > 1) {
      Tensor ro(2, DType::F32), ri(2, DType::F32);
      ro.fill(static_cast<float>(g));
      rag->RingShift(ro.data(), ri.data(), 2);
      REQUIRE(ri.get(0) == static_cast<float>(mem[(gr + G - 1) % G]));
    }
    // p2p ring WITHIN the group: local pairs ride the host mailbox
    // when the split has no device sub, cross-process pairs ride TCP
    if (G > 1) {
      Tensor po(2, DType::F32), pi(2, DType::F32);
      po.fill(static_cast<float>(2000 + g));
      rag->Isend(po.data(), 2, (gr + 1) % G, 0, /*tag=*/9);
      rag->Irecv(pi.data(), 2, (gr + G - 1) % G, 1, /*tag=*/9);
      rag->WaitAll(2);
      REQUIRE(pi.get(0) ==
              static_cast<float>(2000 + mem[(gr + G - 1) % G]));
    }
  }
  // split with groups CONTAINED in one process (color = owning proc:
  // the DCN leg must stay silent; group sums still correct)
  {
    auto ici = fab.split(g, proc_of(g), "ici_only");
    Tensor src(2, DType::F32), dst(2, DType::F32);
    src.fill(1.0f);
    ici->Allreduce(src.data(), dst.data(), 2);
    REQUIRE(dst.get(0) == static_cast<float>(ici->size()));
    Tensor ag(ici->size(), DType::F32), one(1, DType::F32);
    one.set(0, static_cast<float>(g));
    ici->Allgather(one.data(), ag.data(), 1);
    int base = static_cast<int>(balanced_start(world, procs, proc_of(g)));
    for (int k = 0; k < ici->size(); ++k)
      REQUIRE(ag.get(k) == static_cast<float>(base + k));
  }
  // p2p ring over the world: local pairs ride the mailbox, cross-process
  // pairs ride TCP frames (Isend/Irecv so the synchronous local mailbox
  // cannot deadlock the ring; explicit tag because the send and recv sit
  // on different slots)
  if (world > 1) {
    Tensor out(3, DType::F32), in(3, DType::F32);
    out.fill(static_cast<float>(1000 + g));
    comm->Isend(out.data(), 3, (g + 1) % world, 0, /*tag=*/5);
    comm->Irecv(in.data(), 3, (g + world - 1) % world, 1, /*tag=*/5);
    comm->WaitAll(2);
    REQUIRE(in.get(0) == static_cast<float>(1000 + (g + world - 1) % world));
  }
  comm->Barrier();
}

}  // namespace

int main(int argc, char** argv) {
  Args args("hier_selftest — hierarchical ICI×DCN fabric correctness");
  args.required_int("world", "total GLOBAL rank count")
      .required_int("procs", "number of OS processes")
      .required_int("rank", "this process's rank")
      .optional_str("coordinator", "127.0.0.1:0", "rank 0 listen host:port");
  args.parse(argc, argv);
  int world = static_cast<int>(args.integer("world"));
  int procs = static_cast<int>(args.integer("procs"));
  int prank = static_cast<int>(args.integer("rank"));

  try {
    // this process's share of the balanced (possibly uneven) layout
    int local = static_cast<int>(balanced_local(world, procs, prank));
    HierFabric fab(args.str("coordinator"), procs, prank, world, DType::F32,
                   make_pjrt_executor(local, "", {}, std::cerr));
    fab.launch([&](int g) { rank_body(g, world, procs, fab); });
    std::printf("hier_selftest process %d OK\n", prank);
    return 0;
  } catch (const std::exception& e) {
    std::cerr << "hier_selftest process " << prank << ": " << e.what()
              << "\n";
    return 1;
  }
}
