// Native Ulysses (sequence-parallel) proxy — rebuild extension.
//
// No reference counterpart (SURVEY.md §5.7).  Mirrors the Python tier's
// proxies/ulysses.py: attention heads<->sequence resharding via two
// all-to-alls per attention layer forward (scatter heads / gather
// sequence, then back), two more backward, with attention + MLP compute
// between; dp > 1 closes the step with a gradient allreduce.
#include "pipeline_engine.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args("ulysses — sequence-parallel all-to-all proxy (native shm)");
  add_common_args(args);
  args.required_int("sp", "sequence-parallel degree")
      .optional_int("dp", 0, "data-parallel degree (0 = infer from world)")
      .optional_int("max_layers", 0, "cap simulated layers (0 = all)");
  args.parse(argc, argv);

  try {
    ProxyEnv env = make_env(args);
    // no step-boundary fault driver here: refuse plans whose
    // events could only fire at step boundaries, so a record
    // never stamps fault provenance onto an actually-clean run
    // (collective-scoped and drop plans still apply via the
    // fabric hooks; fault_session.hpp)
    fault::require_collective_scope_only("ulysses");
    ModelCard card = load_card_for(env);
    i64 sp = args.integer("sp");
    i64 dp = infer_dp(env.world, sp, args.integer("dp"), "sp");
    SequenceSchedule sched = sequence_schedule(env.stats, card, sp);
    i64 max_layers = args.integer("max_layers");
    i64 layers = max_layers > 0 ? std::min(sched.layers, max_layers)
                                : sched.layers;
    // per-layer compute: whole-layer attention (all sp^2 block pairs
    // land on this rank's heads) + MLP share
    double attn_us_per_layer = sched.attn_us_per_block * sp * sp;
    double mlp_us_per_layer =
        (env.stats.ffn_fwd_us / std::max<i64>(sched.layers, 1)) / sp;

    i64 a2a_total = scale_count(sched.a2a_elems, env.cfg.size_scale);
    i64 a2a_per_rank = (a2a_total + sp - 1) / sp;
    i64 grad_elems = scale_count(env.stats.model_size / std::max<i64>(sp, 1),
                                 env.cfg.size_scale);

    Json meta = Json::object();
    meta["proxy"] = "ulysses";
    meta["sp"] = sp;
    meta["dp"] = dp;
    meta["layers"] = layers;
    meta["attn_time_source"] = sched.attn_time_source;
    meta["a2a_bytes"] =
        static_cast<i64>(a2a_per_rank * sp * dtype_bytes(env.dtype));
    meta["schedule_a2a_bytes"] =
        static_cast<i64>(sched.a2a_elems * sched.bytes_per_element);
    {
      i64 n_a2a = 4 * layers;  // 2 reshards per layer, fwd + bwd
      const Grid3D mg{dp, 1, sp};  // same grid the rank body runs
      Json cm = Json::object();
      cm["a2a_comm"] = comm_timer(comm_component(
          "alltoall", sp,
          n_a2a * a2a_per_rank * sp *
              static_cast<i64>(dtype_bytes(env.dtype))));
      if (dp > 1)
        cm["dp_comm"] = comm_timer(comm_component(
            "allreduce", dp,
            grad_elems * static_cast<i64>(dtype_bytes(env.dtype)),
            /*bound=*/"", /*ops=*/1,
            /*span=*/env.procs > 1
                ? axis_span_procs(env.world, env.procs,
                                  [&](i64 r) { return mg.dp_color(r); })
                : 0));
      meta["comm_model"] = cm;
    }

    return run_proxy_main(
        "ulysses", env, meta,
        [&](int r, Fabric& fab, TimerSet& ts, RankRun& run) {
          Grid3D grid{dp, 1, sp};
          auto c = grid.coords(r);
          auto world = fab.world_comm(r);
          auto sp_comm =
              fab.split(r, static_cast<int>(grid.tp_color(r)), "sp_comm");
          std::unique_ptr<ProxyCommunicator> dp_comm;
          if (dp > 1)
            dp_comm =
                fab.split(r, static_cast<int>(grid.dp_color(r)), "dp_comm");

          auto burn = [&](double us) { fab.burn(r, us, env.cfg.time_scale); };
          Tensor a2a_src(a2a_per_rank * sp, env.dtype);
          Tensor a2a_dst(a2a_per_rank * sp, env.dtype);
          Tensor g_src(grad_elems, env.dtype), g_dst(grad_elems, env.dtype);

          auto layer_pass = [&](TimerSet& t, double scale) {
            {  // reshard seq -> heads
              auto sc = t.scoped("a2a_comm");
              sp_comm->Alltoall(a2a_src.data(), a2a_dst.data(), a2a_per_rank);
            }
            burn(attn_us_per_layer * scale);
            {  // reshard heads -> seq
              auto sc = t.scoped("a2a_comm");
              sp_comm->Alltoall(a2a_dst.data(), a2a_src.data(), a2a_per_rank);
            }
            burn(mlp_us_per_layer * scale);
          };

          run = run_measured(env.cfg, *world, ts, [&](TimerSet& t) {
            for (i64 l = 0; l < layers; ++l) layer_pass(t, 1.0);  // fwd
            for (i64 l = 0; l < layers; ++l) layer_pass(t, 2.0);  // bwd
            if (dp_comm) {
              auto sc = t.scoped("dp_comm");
              dp_comm->Allreduce(g_src.data(), g_dst.data(), grad_elems);
            }
          });
          ts.merge_entries("a2a_comm", 4 * layers);

          Json extra = Json::object();
          extra["sp_id"] = c.tp_id;
          extra["dp_id"] = c.dp_id;
          return extra;
        });
  } catch (const std::exception& e) {
    std::cerr << "ulysses: " << e.what() << "\n";
    return 1;
  }
}
