// Native DP proxy: bucketed data-parallel gradient sync.
//
// Schedule (reference cpp/data_parallel/dp.cpp:87-106): per iteration,
// simulated forward compute, then per bucket simulated backward compute
// followed by an async Iallreduce on that bucket's slot — overlapping
// communication with the remaining backward — and a final WaitAll timed
// as "barrier_time": the communication NOT hidden by compute, the
// benchmark's core signal (dp.cpp:191).
#include "proxy_runner.hpp"

#include "dlnb/schedule.hpp"
#include "dlnb/tensor.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args(
      "dp — bucketed data-parallel allreduce proxy (native shm backend)");
  add_common_args(args);
  args.required_int("num_buckets", "gradient buckets per iteration");
  args.parse(argc, argv);

  try {
    ProxyEnv env = make_env(args);
    auto num_buckets = args.integer("num_buckets");
    DPSchedule sched = dp_schedule(env.stats, num_buckets);

    Json meta = Json::object();
    meta["proxy"] = "dp";
    meta["num_buckets"] = num_buckets;
    {
      Json bb = Json::array();
      for (i64 b : sched.bucket_bytes()) bb.push_back(b);
      meta["schedule_bucket_bytes"] = bb;
      Json sb = Json::array();
      for (i64 s : sched.bucket_sizes)
        sb.push_back(static_cast<i64>(scale_count(s, env.cfg.size_scale) *
                                      dtype_bytes(env.dtype)));
      meta["bucket_bytes"] = sb;
    }
    meta["fwd_us"] = sched.fwd_us * env.cfg.time_scale;
    meta["bwd_us_per_bucket"] = sched.bwd_us_per_bucket * env.cfg.time_scale;

    return run_proxy_main(
        "dp", env, meta,
        [&](int r, Fabric& fab, TimerSet& ts, RankRun& run) {
          auto comm = fab.world_comm(r);
          // fault harness (no-op without --fault): step-boundary
          // delay/jitter/crash injection + the shrink policy's
          // pre-split survivor group (fault_session.hpp)
          fault::Session fses(fab, r);
          // every rank holds full buckets (allreduce semantics,
          // dp.cpp:227-232); grads zero-init like the reference Tensor
          std::vector<Tensor> grads, sums;
          std::vector<i64> counts;
          for (i64 s : sched.bucket_sizes) {
            i64 c = scale_count(s, env.cfg.size_scale);
            counts.push_back(c);
            grads.emplace_back(c, env.dtype);
            sums.emplace_back(c, env.dtype);
          }

          // device-backed fabrics burn real device cycles, others sleep
          auto burn = [&](double us) { fab.burn(r, us, env.cfg.time_scale); };
          run = run_measured(env.cfg, *comm, ts, [&](TimerSet& t) {
            fses.step(t, *comm, [&](ProxyCommunicator& c) {
              burn(sched.fwd_us);
              for (i64 b = 0; b < sched.num_buckets; ++b) {
                burn(sched.bwd_us_per_bucket);
                c.Iallreduce(grads[b].data(), sums[b].data(), counts[b],
                             static_cast<int>(b));
              }
              auto sc = t.scoped("barrier_time");
              c.WaitAll(static_cast<int>(sched.num_buckets));
            });
          });
          return Json::object();
        });
  } catch (const std::exception& e) {
    std::cerr << "dp: " << e.what() << "\n";
    return 1;
  }
}
