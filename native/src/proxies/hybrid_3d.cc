// Native DP+PP+TP proxy — reference cpp/hybrid_parallel/hybrid_3d.cpp.
// Adds Megatron-style tensor parallelism to the GPipe engine: two TP
// allreduces per microbatch per direction (column+row parallel linear,
// hybrid_3d.cpp:142-148, 177-183), per-microbatch compute divided by tp.
#include "pipeline_engine.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args("hybrid_3d — DP + PP + tensor-parallel proxy (native shm)");
  add_common_args(args);
  args.required_int("num_stages", "pipeline stages")
      .required_int("num_microbatches", "microbatches per iteration")
      .required_int("tp", "tensor-parallel degree")
      .optional_int("dp", 0, "data-parallel degree (0 = infer from world)");
  add_schedule_arg(args);
  args.parse(argc, argv);

  try {
    ProxyEnv env = make_env(args);
    // no step-boundary fault driver here: refuse plans whose
    // events could only fire at step boundaries, so a record
    // never stamps fault provenance onto an actually-clean run
    // (collective-scoped and drop plans still apply via the
    // fabric hooks; fault_session.hpp)
    fault::require_collective_scope_only("hybrid_3d");
    ModelCard card = load_card_for(env);
    i64 stages = args.integer("num_stages");
    i64 mbs = args.integer("num_microbatches");
    i64 tp = args.integer("tp");
    i64 dp = infer_dp(env.world, stages * tp, args.integer("dp"),
                      "num_stages*tp");

    HybridSpec spec;
    spec.pipe = pipeline_schedule(env.stats, card, stages, mbs, dp, tp);
    set_schedule(spec, args);

    Json meta = Json::object();
    meta["proxy"] = "hybrid_3d";
    hybrid_meta(meta, spec, env.dtype, env.cfg.size_scale, env.procs);

    return run_proxy_main(
        "hybrid_3d", env, meta,
        [&](int r, Fabric& fab, TimerSet& ts, RankRun& run) {
          return hybrid_rank_body(spec, env, r, fab, ts, run);
        });
  } catch (const std::exception& e) {
    std::cerr << "hybrid_3d: " << e.what() << "\n";
    return 1;
  }
}
