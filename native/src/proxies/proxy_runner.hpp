// Shared native-proxy skeleton: args, data discovery, fabric launch,
// measurement, record emission.
//
// Counterpart of the reference's per-binary main() skeleton (reference
// cpp/data_parallel/dp.cpp:127-302, traced in SURVEY.md §3.0): parse args,
// locate the repo data, load the model stats (+card for hybrids), print
// the fabric topology, build communicators, run warmup + measured runs per
// rank, and emit one structured JSON record that
// dlnetbench_tpu.metrics.parser ingests directly.
//
// Build-time DLNB_PROXY_LOOP produces the `_loop` congestor binaries
// (reference -DPROXY_LOOP, Makefile.common:96-109); at runtime --loop does
// the same.
#pragma once

#include <algorithm>
#include <cctype>
#include <fstream>
#include <functional>
#include <set>
#include <iostream>
#include <string>
#include <sys/stat.h>
#include <vector>

#include "dlnb/args.hpp"
#include "dlnb/fabric.hpp"
#include "dlnb/fault_session.hpp"
#include "dlnb/harness.hpp"
#include "dlnb/hier_fabric.hpp"
#include "dlnb/model_data.hpp"
#include "dlnb/pjrt_fabric.hpp"
#include "dlnb/shm_backend.hpp"
#include "dlnb/tcp_backend.hpp"
#include "dlnb/timers.hpp"
#include "dlnb/topology.hpp"

namespace dlnb {

inline bool path_exists(const std::string& p) {
  struct stat st;
  return ::stat(p.c_str(), &st) == 0;
}

// Locate the repo data dir (reference get_dnnproxy_base_path,
// cpp/utils.hpp:44-59): --base_path flag, DLNB_BASE_PATH env, else walk up
// from cwd looking for dlnetbench_tpu/data.
inline std::string find_base_path(const std::string& flag_value) {
  if (!flag_value.empty()) return flag_value;
  if (const char* env = std::getenv("DLNB_BASE_PATH"); env && *env)
    return env;
  std::string prefix = ".";
  for (int depth = 0; depth < 6; ++depth) {
    if (path_exists(prefix + "/dlnetbench_tpu/data/model_stats")) return prefix;
    prefix += "/..";
  }
  throw std::runtime_error(
      "cannot locate dlnetbench_tpu/data — pass --base_path or set "
      "DLNB_BASE_PATH to the repo root");
}

struct ProxyEnv {
  HarnessConfig cfg;
  ModelStats stats;
  std::string base_path;
  int world = 0;
  DType dtype = DType::BF16;
  std::string model_name;
  std::string out_path;  // empty -> stdout
  bool no_topology = false;
  std::string backend = "shm";      // shm | pjrt | tcp
  std::string pjrt_plugin;          // --pjrt_plugin override
  std::vector<int> devices;         // --devices list (reference -d)
  std::string coordinator;          // tcp/hier: rank 0's host:port
  int proc_rank = 0;                // tcp/hier: this process's rank
  int procs = 1;                    // pjrt: OS processes (hier fabric if >1)
};

// "0,2,3" -> {0,2,3} (reference parse_devices, cpp/utils.hpp:62-71).
// Every token must be a plain decimal number — std::stoi's silent prefix
// parsing would turn a "0-3" range typo into {0}.
inline std::vector<int> parse_device_list(const std::string& s) {
  std::vector<int> out;
  std::string num;
  auto flush = [&] {
    if (num.empty()) return;
    for (char c : num)
      if (c < '0' || c > '9')
        throw std::runtime_error("--devices: bad device index '" + num +
                                 "' (expected e.g. 0,2,3)");
    out.push_back(std::stoi(num));
    num.clear();
  };
  for (char c : s) {
    if (c == ',')
      flush();
    else if (c != ' ')
      num += c;
  }
  flush();
  return out;
}

inline void add_common_args(Args& args) {
  args.required_str("model", "stats-file name, e.g. gpt2_l_16_bfloat16")
      .required_int("world", "number of ranks (threads) to run")
      .optional_int("warmup", 3, "warm-up iterations")
      .optional_int("runs", 5, "measured iterations")
      .optional_double("min_exectime", 0.0,
                       "seconds; when >0, runs are estimated from warmup")
      .optional_double("time_scale", 1.0, "scale simulated compute durations")
      .optional_double("size_scale", 1.0, "scale communication buffer sizes")
      .optional_str("base_path", "", "repo root containing dlnetbench_tpu/data")
      .optional_str("out", "", "append the JSON record here instead of stdout")
      .optional_str("backend", "shm",
                    "rank fabric: shm (threaded fake) or pjrt (XLA runtime)")
      .optional_str("pjrt_plugin", "",
                    "PJRT plugin path override (default: $DLNB_PJRT_PLUGIN "
                    "or libtpu.so)")
      .optional_str("devices", "",
                    "device-index list for the pjrt backend, e.g. 0,2,3 "
                    "(reference -d)")
      .optional_str("coordinator", "",
                    "tcp/multi-process pjrt: rank 0's listen address "
                    "host:port (the ncclUniqueId bootstrap role, "
                    "dp.cpp:183-188)")
      .optional_int("rank", 0, "tcp/multi-process pjrt: this process's rank")
      .optional_int("procs", 1,
                    "pjrt backend: number of OS processes; >1 composes "
                    "per-process devices (ICI) with a TCP mesh (DCN) — "
                    "the reference's multi-node NCCL mode, dp.cpp:166-189")
      .optional_str("fault", "",
                    "JSON fault plan (fault_plan.hpp schema: delay/"
                    "jitter/drop/crash/partition events with rank "
                    "targets and iteration triggers); also honored "
                    "from $DLNB_FAULT_PLAN")
      .optional_str("fault_policy", "",
                    "degradation policy on a detected failure: "
                    "fail_fast (default — every survivor raises), "
                    "retry (dropped frames re-sent with exponential "
                    "backoff), shrink (survivors regroup without the "
                    "dead rank(s) and finish the run degraded)")
      .flag("loop", "run the schedule forever (congestor mode)")
      .flag("no_topology", "skip the startup fabric-topology graph");
}

inline ProxyEnv make_env(const Args& args) {
  ProxyEnv env;
  env.model_name = args.str("model");
  env.world = static_cast<int>(args.integer("world"));
  env.cfg.warmup = static_cast<int>(args.integer("warmup"));
  env.cfg.runs = static_cast<int>(args.integer("runs"));
  env.cfg.min_exectime_s = args.number("min_exectime");
  env.cfg.time_scale = args.number("time_scale");
  env.cfg.size_scale = args.number("size_scale");
  env.cfg.loop = args.flag_set("loop");
#ifdef DLNB_PROXY_LOOP
  env.cfg.loop = true;
#endif
  env.base_path = find_base_path(args.str("base_path"));
  env.stats = load_model_stats(env.base_path + "/dlnetbench_tpu/data/" +
                                   "model_stats/" + env.model_name + ".txt",
                               env.model_name);
  env.dtype = dtype_from_name(env.stats.dtype);
  env.out_path = args.str("out");
  env.no_topology = args.flag_set("no_topology");
  env.backend = args.str("backend");
  env.pjrt_plugin = args.str("pjrt_plugin");
  env.devices = parse_device_list(args.str("devices"));
  env.coordinator = args.str("coordinator");
  env.proc_rank = static_cast<int>(args.integer("rank"));
  env.procs = static_cast<int>(args.integer("procs"));
  if (env.backend != "shm" && env.backend != "pjrt" &&
      env.backend != "tcp")
    throw std::runtime_error("unknown --backend '" + env.backend +
                             "' (shm | pjrt | tcp)");
  if (env.backend == "tcp" && env.world > 1 && env.coordinator.empty())
    throw std::runtime_error(
        "--backend tcp needs --coordinator host:port (rank 0 listens "
        "there) and --rank");
  if (env.world <= 0) throw std::runtime_error("--world must be positive");
  if (env.procs < 1) throw std::runtime_error("--procs must be >= 1");
  if (env.procs > 1) {
    if (env.backend != "pjrt")
      throw std::runtime_error(
          "--procs > 1 requires --backend pjrt (the hierarchical ICI+DCN "
          "fabric; the tcp backend is one-rank-per-process already)");
    if (env.world < env.procs)
      throw std::runtime_error(
          "--world must be >= --procs (every process hosts at least one "
          "rank; uneven worlds take the balanced layout)");
    if (env.coordinator.empty())
      throw std::runtime_error(
          "--procs > 1 needs --coordinator host:port and --rank");
    if (env.proc_rank < 0 || env.proc_rank >= env.procs)
      throw std::runtime_error("--rank must be in [0, --procs)");
  }
  // fault plan: --fault wins over the env channel; either way the plan
  // (and its policy) must be IDENTICAL on every process of a run —
  // it is part of the protocol, like the ring threshold
  {
    std::string plan_text = args.str("fault");
    std::string policy = args.str("fault_policy");
    if (plan_text.empty())
      if (const char* e = std::getenv("DLNB_FAULT_PLAN"); e && *e)
        plan_text = e;
    if (policy.empty())
      if (const char* e = std::getenv("DLNB_FAULT_POLICY"); e && *e)
        policy = e;
    fault::Plan::instance().load(plan_text, policy, env.world);
  }
  // with multiple processes, each process drives its balanced share of
  // the world (uneven when world does not divide procs)
  int local_world = static_cast<int>(
      balanced_local(env.world, env.procs, env.proc_rank));
  if (!env.devices.empty()) {
    if (env.backend != "pjrt")
      throw std::runtime_error(
          "--devices only applies to --backend pjrt (the shm fabric has no "
          "devices)");
    if (static_cast<int>(env.devices.size()) < local_world)
      throw std::runtime_error("--devices lists " +
                               std::to_string(env.devices.size()) +
                               " device(s) for local world " +
                               std::to_string(local_world));
    std::set<int> uniq(env.devices.begin(), env.devices.end());
    if (uniq.size() != env.devices.size())
      throw std::runtime_error(
          "--devices has duplicate indices (two replicas cannot share a "
          "device)");
  }
  return env;
}

inline std::unique_ptr<Fabric> make_fabric(const ProxyEnv& env) {
  if (env.backend == "pjrt" && env.procs > 1)
    return std::make_unique<HierFabric>(
        env.coordinator, env.procs, env.proc_rank, env.world, env.dtype,
        // this process's share of the balanced layout — uneven when
        // world does not divide procs (hier_fabric.hpp)
        make_pjrt_executor(
            static_cast<int>(balanced_local(env.world, env.procs,
                                            env.proc_rank)),
            env.pjrt_plugin, env.devices, std::cerr));
  if (env.backend == "pjrt")
    return std::make_unique<PjrtFabric>(
        env.world, env.dtype,
        make_pjrt_executor(env.world, env.pjrt_plugin, env.devices,
                           std::cerr));
  if (env.backend == "tcp")
    return std::make_unique<TcpFabric>(env.coordinator, env.world,
                                       env.proc_rank, env.dtype);
  return std::make_unique<ShmFabric>(env.world, env.dtype);
}

// One component of a timer's communication model (analysis/bandwidth.py
// schema: the bytes a timed region moves per iteration, with the group
// size for the busbw correction factor).  Declared only on BLOCKING
// timers — wait-tail timers (dp's barrier, fsdp's allgather waits)
// measure exposure, not transfer time, and would misreport bandwidth.
inline Json comm_component(const std::string& kind,
                           std::int64_t group, std::int64_t bytes,
                           const std::string& bound = "",
                           std::int64_t ops = 1,
                           std::int64_t span = 0) {
  Json c = Json::object();
  c["kind"] = kind;
  c["group"] = group;
  c["bytes"] = bytes;
  // "lower" marks a deliberately conservative declaration (e.g. middle
  // pipeline stages timing recv+send against one direction's bytes);
  // analysis/bandwidth.py surfaces it as a table column
  if (!bound.empty()) c["bound"] = bound;
  // how many same-size operations the bytes aggregate over — the
  // per-MESSAGE size (bytes/ops) is what algorithm-selection thresholds
  // compare against, not the per-iteration total
  c["ops"] = ops;
  // span > 0: the max OS processes any group of this split spans on the
  // hier fabric (axis_span_procs) — the DCN mesh width the full-mesh
  // refusal should key on; 0 = single-process fabric, field omitted
  if (span > 0) c["span"] = span;
  return c;
}

inline Json comm_timer(const Json& first) {
  Json arr = Json::array();
  arr.push_back(first);
  return arr;
}

inline ModelCard load_card_for(const ProxyEnv& env) {
  std::string arch = arch_name_from_stats_name(env.model_name);
  return load_model_card(
      env.base_path + "/dlnetbench_tpu/data/models/" + arch + ".json", arch);
}

// Per-rank body: receives (rank, fabric, timers) and returns the rank's
// extra identity fields (stage_id/dp_id/... as a Json object).  It must
// call run_measured itself so proxies control communicator setup.
using RankBody = std::function<Json(int rank, Fabric& fab, TimerSet& ts,
                                    RankRun& run_out)>;

inline int run_proxy_main(const std::string& section, const ProxyEnv& env,
                          const Json& global_meta, const RankBody& body) {
  if (!env.no_topology)
    print_topology(env.world, std::cerr,
                   env.backend + "-rank[" + dtype_name(env.dtype) + "]");

  std::unique_ptr<Fabric> fab_ptr = make_fabric(env);
  Fabric& fab = *fab_ptr;
  // host energy channel: the process's first local rank brackets its
  // runs (reference PROXY_ENERGY_PROFILING role; see energy.hpp scope)
  auto& meter = energy::Meter::instance();
  if (meter.available())
    meter.recording_rank.store(fab.local_ranks().front());
  std::vector<TimerSet> timers(env.world);
  std::vector<RankRun> runs(env.world);
  std::vector<Json> extras(env.world);
  auto& plan = fault::Plan::instance();
  bool degraded = false;
  try {
    fab.launch([&](int r) { extras[r] = body(r, fab, timers[r], runs[r]); });
  } catch (const fault::RankFailure& e) {
    // A scripted crash surfaced from launch.  Under `shrink` the
    // in-process survivors finished the run degraded (their threads
    // completed on the survivor group); the victim's death is DATA —
    // emit the survivors' record with degraded_world instead of dying.
    // Any other policy, or a process owning no survivor (the tcp
    // victim process), dies like a real crash: record-less, nonzero.
    if (plan.policy() != "shrink") throw;
    auto surv = plan.survivors();
    bool any_local_survivor = false;
    for (int r : fab.local_ranks())
      if (std::find(surv.begin(), surv.end(), r) != surv.end())
        any_local_survivor = true;
    if (!any_local_survivor) throw;
    (void)e;
    degraded = true;
  }

  // emit only the ranks THIS process measured (cross-process fabrics own
  // one rank each; dlnetbench_tpu.metrics.merge reassembles the run)
  std::vector<int> local = fab.local_ranks();
  if (plan.active() && plan.policy() == "shrink" &&
      !plan.crash_victims().empty()) {
    // crash victims emit no rows — they died; parser/merge accept the
    // shrunken rank set through the degraded_world pathway
    auto surv = plan.survivors();
    std::vector<int> kept;
    for (int r : local)
      if (std::find(surv.begin(), surv.end(), r) != surv.end())
        kept.push_back(r);
    local = kept;
    degraded = true;
  }
  if (plan.active() && plan.policy() == "shrink" && plan.has_preempt()) {
    // an eviction that never grew back degrades the run to its end:
    // the drained evictee's rows are local replay (no fabric work) —
    // drop them and declare survivor membership below, mirroring the
    // python tier's preempt-without-rejoin record.  A fired rejoin
    // (every live rank's report says so) keeps full coverage instead.
    bool rejoined_any = false;
    for (int r : fab.local_ranks())
      rejoined_any = rejoined_any || plan.report(r).rejoined.load();
    if (!rejoined_any) {
      auto ev = plan.preempt_victims();
      std::vector<int> kept;
      for (int r : local)
        if (std::find(ev.begin(), ev.end(), r) == ev.end())
          kept.push_back(r);
      local = kept;
      degraded = true;
    }
  }
  if (local.empty())
    // every locally-owned rank drained out of the run (the tcp evictee
    // process of an unrejoined preempt): alive, exit 0, no record —
    // merge's degraded pathway tolerates the absent process
    return 0;
  std::string host = local_hostname();
  if (plan.active())
    for (int r : local)
      // per-rank injected latency as a scalar row field (straggler
      // post-mortems want WHERE the delay landed), stamped before the
      // reports copy the extras
      extras[r]["fault_injected_delay_us"] =
          plan.report(r).injected_delay_us.load();
  std::vector<RankReport> reports;
  for (int r : local) {
    RankReport rep;
    rep.rank = r;
    rep.device_id = r;
    rep.process_index = fab.process_index();
    rep.hostname = host;
    rep.extra = extras[r];
    rep.timers = &timers[r];
    reports.push_back(rep);
  }

  Json meta = global_meta;
  meta["model"] = env.model_name;
  meta["world_size"] = env.world;
  meta["dtype"] = dtype_name(env.dtype);
  // external-launcher job variables (the reference's sbatchman
  // job.variables role, plots/parser.py:221-237): scheduler identity
  // env + DLNB_TAG_<name>=<value> sweep axes, mirrored from the Python
  // tier's metrics.emit.scheduler_variables so both tiers' records
  // carry the same columns
  {
    Json vars = Json::object();
    for (char** e = ::environ; e && *e; ++e) {  // unistd.h via harness.hpp
      std::string kv(*e);
      auto eq = kv.find('=');
      if (eq == std::string::npos) continue;
      std::string k = kv.substr(0, eq), v = kv.substr(eq + 1);
      if (v.empty()) continue;
      if (k.rfind("DLNB_TAG_", 0) == 0) {
        std::string name = k.substr(9);
        for (char& c : name) c = static_cast<char>(::tolower(c));
        vars[name] = v;
      }
    }
    for (const char* k : {"SLURM_JOB_ID", "SLURM_PROCID", "SLURM_NNODES",
                          "JOB_COMPLETION_INDEX", "TPU_WORKER_ID",
                          "MEGASCALE_SLICE_ID"}) {
      if (const char* v = std::getenv(k); v && *v) {
        std::string name(k);
        for (char& c : name) c = static_cast<char>(::tolower(c));
        vars[name] = std::string(v);
      }
    }
    if (!vars.fields().empty()) meta["variables"] = vars;
  }
  if (meter.available()) {
    // which sensor produced energy_consumed — misattribution must be
    // visible in the record, not silent (energy.py run_proxy parity)
    meta["energy_source"] = meter.source();
    meta["energy_scope"] = "process";
  }
  meta["time_scale"] = env.cfg.time_scale;
  meta["size_scale"] = env.cfg.size_scale;
  if (plan.active()) {
    // fault provenance: the plan itself + run-wide drop/retry counters
    plan.describe(meta);
    double inj = 0, det = 0, rec = 0, rej = 0;
    bool shrunk = false, rejoined = false;
    for (int r : local) {
      auto& rep = plan.report(r);
      inj += rep.injected_delay_us.load();
      det = std::max(det, rep.detection_us.load());
      rec = std::max(rec, rep.recovery_us.load());
      rej = std::max(rej, rep.rejoin_us.load());
      shrunk = shrunk || rep.shrunk.load();
      rejoined = rejoined || rep.rejoined.load();
    }
    meta["fault_injected_delay_us"] = inj;
    if (degraded && !rejoined) {
      // a rejoined run ended FULL world: degraded_world stays CLEARED
      // (preempt victims are alive and emit rows, so the record covers
      // range(world) again).  elastic_survivors: crash victims are
      // gone forever AND an unrejoined evictee drained out for good.
      Json dw = Json::array();
      for (int r : plan.elastic_survivors()) dw.push_back(r);
      meta["degraded_world"] = dw;
    }
    if (shrunk) {
      meta["detection_ms"] = det / 1e3;
      meta["recovery_ms"] = rec / 1e3;
    }
    if (rejoined) {
      meta["fault_rejoin_step"] =
          static_cast<std::int64_t>(plan.rejoin_iteration());
      meta["rejoin_ms"] = rej / 1e3;
    }
  }
  Json mesh = Json::object();
  fab.describe(meta, mesh);  // backend/platform identity + cache stats
  // continuous telemetry (ISSUE 14): the per-step flight ring as a
  // record section — schema-matched to the Python tier's telemetry
  // block (volatile at merge; each process emits its own ring)
  if (TelemetryRing::instance().enabled())
    meta["telemetry"] = TelemetryRing::instance().to_json();

  int rep_rank = local.at(0);  // the rank whose harness counters we hold
  Json rec = make_record(section, meta, mesh, runs[rep_rank].runs,
                         runs[rep_rank].warmup_us, reports);
  rec["process"] = fab.process_index();
  if (!env.out_path.empty()) {
    std::ofstream f(env.out_path, std::ios::app);
    f << rec.dump() << "\n";
  } else {
    std::cout << rec.dump() << std::endl;
  }
  return 0;
}

}  // namespace dlnb
