// fault_selftest — deterministic fault-plan correctness check across the
// shm (in-process threads) and tcp (one process per rank) fabrics.
//
// Runs --iters allreduce steps through a fault::Session and VERIFIES THE
// MATH at every step against the live membership: sum of (r+1) over the
// full world before a shrink, over the survivor group after.  Covers:
//
//   * delay/jitter — injected latency, run completes, sums exact,
//     injected_delay_us reported;
//   * drop + retry — every frame eventually delivered (backoff counted),
//     sums exact;
//   * drop + fail_fast — the first loss aborts (exit != 0);
//   * crash + fail_fast — the victim dies at its trigger and EVERY
//     survivor raises (not hangs): shm ranks via the group abort, tcp
//     ranks via the per-peer death tracking + suppressed Bye — the
//     controlled end-to-end proof of the PR-2 dying_/transitive path;
//   * crash + shrink — survivors regroup on the pre-split survivor comm,
//     finish all remaining iterations with exact survivor-group sums,
//     and report detection/recovery wall time (exit 0; the tcp victim
//     process still exits != 0 — it is dead).
//
//   fault_selftest --backend shm --world 4 --iters 6
//       --fault '{"events":[{"kind":"crash","ranks":[2],"iteration":3}]}'
//       --fault_policy shrink
#include <algorithm>
#include <cstdio>
#include <iostream>
#include <string>
#include <vector>

#include "dlnb/args.hpp"
#include "dlnb/fault_session.hpp"
#include "dlnb/shm_backend.hpp"
#include "dlnb/tcp_backend.hpp"
#include "dlnb/tensor.hpp"
#include "dlnb/timers.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args("fault_selftest — fault-plan policies on the shm/tcp fabrics");
  args.required_int("world", "total rank count")
      .optional_str("backend", "shm", "shm (threads) | tcp (processes)")
      .optional_int("rank", 0, "tcp: this process's rank")
      .optional_str("coordinator", "127.0.0.1:0",
                    "tcp: rank 0's listen host:port")
      .optional_int("iters", 6, "allreduce steps to run")
      .optional_int("count", 64, "elements per allreduce")
      .optional_str("fault", "", "JSON fault plan (fault_plan.hpp schema)")
      .optional_str("fault_policy", "", "fail_fast | retry | shrink");
  args.parse(argc, argv);
  const int world = static_cast<int>(args.integer("world"));
  const int iters = static_cast<int>(args.integer("iters"));
  const std::int64_t count = args.integer("count");
  const std::string backend = args.str("backend");

  try {
    auto& plan = fault::Plan::instance();
    plan.load(args.str("fault"), args.str("fault_policy"), world);

    std::unique_ptr<Fabric> fab;
    if (backend == "tcp")
      fab = std::make_unique<TcpFabric>(args.str("coordinator"), world,
                                        static_cast<int>(args.integer("rank")),
                                        DType::F32);
    else
      fab = std::make_unique<ShmFabric>(world, DType::F32);

    std::vector<int> checks_ok(world, 0);
    std::vector<int> done(world, 0);

    auto body = [&](int r) {
      auto comm = fab->world_comm(r);
      fault::Session fses(*fab, r);
      TimerSet ts;
      Tensor src(count, DType::F32), dst(count, DType::F32);
      src.fill(static_cast<float>(r + 1));
      bool ok = true;
      for (int i = 0; i < iters; ++i) {
        fses.step(ts, *comm, [&](ProxyCommunicator& c) {
          c.Allreduce(src.data(), dst.data(), count);
          // expected sum over the LIVE membership of this step
          float expect = 0;
          if (fses.rejoined())
            expect = world * (world + 1) / 2.0f;  // full world again
          else if (fses.evicted_now())
            expect = static_cast<float>(r + 1);   // singleton replay
          else if (fses.shrunk())
            for (int s : plan.survivors()) expect += s + 1;
          else if (fses.degraded_now())
            for (int s : plan.elastic_survivors()) expect += s + 1;
          else
            expect = world * (world + 1) / 2.0f;
          if (dst.get(0) != expect ||
              dst.get(static_cast<std::size_t>(count - 1)) != expect)
            ok = false;
        });
        done[r] = i + 1;
      }
      checks_ok[r] = ok ? 1 : 0;
    };

    auto report = [&](int r) {
      auto& rep = plan.report(r);
      Json j = Json::object();
      j["rank"] = r;
      j["world"] = world;
      j["backend"] = backend;
      j["iters_done"] = done[r];
      j["checks"] = checks_ok[r] ? "OK" : "FAILED";
      if (plan.active()) {
        j["policy"] = plan.policy();
        j["shrunk"] = rep.shrunk.load();
        j["detection_us"] = rep.detection_us.load();
        j["recovery_us"] = rep.recovery_us.load();
        j["injected_delay_us"] = rep.injected_delay_us.load();
        j["drops"] = static_cast<std::int64_t>(plan.drops());
        j["retries"] = static_cast<std::int64_t>(plan.retries());
        j["rejoined"] = rep.rejoined.load();
        j["rejoin_us"] = rep.rejoin_us.load();
        Json dw = Json::array();
        // a rejoined run ended full-world: degraded_world is cleared
        for (int s : (rep.rejoined.load() ? plan.survivors()
                                          : plan.elastic_survivors()))
          dw.push_back(s);
        j["degraded_world"] = dw;
      }
      std::cout << j.dump() << std::endl;
    };

    bool victim_died = false;
    try {
      fab->launch(body);
    } catch (const fault::RankFailure& e) {
      // the scripted victim's death: under shrink the surviving rank
      // threads (shm) finished degraded — report them and exit by
      // their checks; any other policy is a real (provoked) crash
      if (plan.policy() != "shrink") throw;
      auto surv = plan.survivors();
      bool any = false;
      for (int r : fab->local_ranks())
        if (std::find(surv.begin(), surv.end(), r) != surv.end()) any = true;
      if (!any) throw;  // tcp victim process: dead is dead
      (void)e;
      victim_died = true;
    }

    auto surv = plan.active() ? plan.survivors() : std::vector<int>();
    bool all_ok = true;
    for (int r : fab->local_ranks()) {
      bool is_victim =
          victim_died &&
          std::find(surv.begin(), surv.end(), r) == surv.end();
      if (is_victim) continue;  // died on schedule; no report row
      report(r);
      if (!checks_ok[r] || done[r] != iters) all_ok = false;
    }
    if (!all_ok) {
      std::cerr << "fault_selftest: checks failed\n";
      return 1;
    }
    if (backend == "tcp")
      std::printf("fault_selftest rank %lld OK\n", args.integer("rank"));
    else
      std::printf("fault_selftest all ranks OK\n");
    return 0;
  } catch (const std::exception& e) {
    std::cerr << "fault_selftest: " << e.what() << "\n";
    return 1;
  }
}
