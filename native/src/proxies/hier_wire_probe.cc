// hier_wire_probe — deterministic DCN wire-byte accounting for the
// hierarchical fabric.
//
// Runs a fixed, known collective sequence on the world group and prints
// the process's actual socket bytes (TcpFabric's send_frame counter), so
// pytest can assert the EXACT wire cost of every block-routed DCN
// algorithm (hier_fabric.hpp header) with no timing involved — the
// busbw-admissibility proof VERDICT r3 asked for.  The reference's
// counterpart guarantee is structural (alltoall composed from
// per-destination p2p blocks, cpp/proxy_classes.hpp:160-182); here the
// byte count itself is pinned.
//
//   hier_wire_probe --world 8 --procs 4 --rank 0 \
//       --coordinator 127.0.0.1:9310 --count 1024 --iters 3
#include <cstdio>
#include <iostream>

#include "dlnb/args.hpp"
#include "dlnb/hier_fabric.hpp"
#include "dlnb/tensor.hpp"

using namespace dlnb;

int main(int argc, char** argv) {
  Args args("hier_wire_probe — DCN wire-byte accounting");
  args.required_int("world", "total GLOBAL rank count")
      .required_int("procs", "number of OS processes")
      .required_int("rank", "this process's rank")
      .optional_str("coordinator", "127.0.0.1:0", "rank 0 listen host:port")
      .optional_int("count", 256, "elements per destination block")
      .optional_int("iters", 2, "iterations of the collective sequence");
  args.parse(argc, argv);
  const int world = static_cast<int>(args.integer("world"));
  const int procs = static_cast<int>(args.integer("procs"));
  const int prank = static_cast<int>(args.integer("rank"));
  const std::int64_t count = args.integer("count");
  const int iters = static_cast<int>(args.integer("iters"));

  try {
    const int local = world / procs;
    HierFabric fab(args.str("coordinator"), procs, prank, world, DType::F32,
                   make_pjrt_executor(local, "", {}, std::cerr));
    fab.launch([&](int g) {
      auto comm = fab.world_comm(g);
      const int G = comm->size();
      Tensor a2a_s(G * count, DType::F32), a2a_d(G * count, DType::F32);
      Tensor rs_s(G * count, DType::F32), rs_d(count, DType::F32);
      Tensor ag_s(count, DType::F32), ag_d(G * count, DType::F32);
      Tensor ring_s(count, DType::F32), ring_d(count, DType::F32);
      Tensor ar_s(count, DType::F32), ar_d(count, DType::F32);
      a2a_s.fill(static_cast<float>(g));
      rs_s.fill(1.0f);
      ag_s.fill(static_cast<float>(g));
      ring_s.fill(static_cast<float>(g));
      ar_s.fill(1.0f);
      comm->Barrier();
      for (int i = 0; i < iters; ++i) {
        comm->Alltoall(a2a_s.data(), a2a_d.data(), count);
        comm->ReduceScatterBlock(rs_s.data(), rs_d.data(), count);
        comm->Allgather(ag_s.data(), ag_d.data(), count);
        comm->RingShift(ring_s.data(), ring_d.data(), count);
        comm->Allreduce(ar_s.data(), ar_d.data(), count);
      }
      comm->Barrier();
      // spot-check sums so byte accounting cannot pass on wrong data
      float expect_ar = static_cast<float>(world);
      if (ar_d.get(0) != expect_ar)
        throw std::runtime_error("allreduce sum wrong");
      if (ring_d.get(0) != static_cast<float>((g + world - 1) % world))
        throw std::runtime_error("ringshift block wrong");
    });
    Json meta = Json::object(), mesh = Json::object();
    fab.describe(meta, mesh);
    Json out = Json::object();
    out["proc"] = prank;
    out["world"] = world;
    out["procs"] = procs;
    out["count"] = count;
    out["iters"] = iters;
    out["tcp_bytes_sent"] = meta["tcp_bytes_sent"];
    out["dcn_algo"] = meta["dcn_algo"];
    std::cout << out.dump() << std::endl;
    return 0;
  } catch (const std::exception& e) {
    std::cerr << "hier_wire_probe process " << prank << ": " << e.what()
              << "\n";
    return 1;
  }
}
