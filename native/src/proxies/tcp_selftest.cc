// tcp_selftest — cross-process correctness check of the TCP fabric.
//
// Launched once per rank (the MPI model): every collective and the p2p
// path run across REAL OS processes and every rank verifies the math
// (the "correct sums" proof for the native multi-process path; reference
// role: the mpi_cpu build running under mpirun).  Exit 0 = all checks
// passed on this rank.
//
//   tcp_selftest --world 2 --rank 0 --coordinator 127.0.0.1:9310
#include <cstdio>
#include <iostream>

#include "dlnb/args.hpp"
#include "dlnb/tcp_backend.hpp"
#include "dlnb/tensor.hpp"

using namespace dlnb;

#define REQUIRE(cond)                                                   \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::cerr << "tcp_selftest rank " << rank << " FAILED: " << #cond \
                << " (" << __FILE__ << ":" << __LINE__ << ")\n";        \
      return 1;                                                         \
    }                                                                   \
  } while (0)

int main(int argc, char** argv) {
  Args args("tcp_selftest — cross-process fabric correctness");
  args.required_int("world", "total process count")
      .required_int("rank", "this process's rank")
      .optional_str("coordinator", "127.0.0.1:0", "rank 0 listen host:port")
      .flag("final_ring",
            "run ONLY one big ring allreduce and exit immediately — no "
            "trailing barrier, so fast ranks leave the fabric while a "
            "delayed rank is still mid-ring (clean-early-exit coverage "
            "with DLNB_TEST_RING_FINAL_RECV_DELAY_MS)");
  args.parse(argc, argv);
  int world = static_cast<int>(args.integer("world"));
  int rank = static_cast<int>(args.integer("rank"));

  try {
    TcpFabric fab(args.str("coordinator"), world, rank, DType::F32);
    auto comm = fab.world_comm(rank);

    if (args.flag_set("final_ring")) {
      const std::int64_t big = 40001;  // >= ring threshold, odd tail
      Tensor src(big, DType::F32), dst(big, DType::F32);
      for (std::int64_t i = 0; i < big; ++i)
        src.set(static_cast<std::size_t>(i),
                static_cast<float>(rank + (i % 7)));
      comm->Allreduce(src.data(), dst.data(), big);
      for (std::int64_t i : {std::int64_t{0}, big / 2, big - 1}) {
        float expect = static_cast<float>(
            world * (world - 1) / 2 + world * (i % 7));
        REQUIRE(dst.get(static_cast<std::size_t>(i)) == expect);
      }
      std::printf("tcp_selftest rank %d OK\n", rank);
      return 0;
    }

    // allreduce: sum of (r+1) over ranks
    {
      Tensor src(8, DType::F32), dst(8, DType::F32);
      src.fill(static_cast<float>(rank + 1));
      comm->Allreduce(src.data(), dst.data(), 8);
      float expect = world * (world + 1) / 2.0f;
      REQUIRE(dst.get(0) == expect && dst.get(7) == expect);
    }
    // allgather: rank-major concat
    {
      Tensor src(2, DType::F32), dst(2 * world, DType::F32);
      src.set(0, static_cast<float>(rank));
      src.set(1, static_cast<float>(10 * rank));
      comm->Allgather(src.data(), dst.data(), 2);
      for (int r = 0; r < world; ++r) {
        REQUIRE(dst.get(2 * r) == static_cast<float>(r));
        REQUIRE(dst.get(2 * r + 1) == static_cast<float>(10 * r));
      }
    }
    // reduce-scatter-block: each block sums ranks
    {
      Tensor src(2 * world, DType::F32), dst(2, DType::F32);
      src.fill(static_cast<float>(rank));
      comm->ReduceScatterBlock(src.data(), dst.data(), 2);
      float expect = world * (world - 1) / 2.0f;
      REQUIRE(dst.get(0) == expect && dst.get(1) == expect);
    }
    // alltoall: dst block q = 100*q + rank
    {
      Tensor src(world, DType::F32), dst(world, DType::F32);
      for (int q = 0; q < world; ++q)
        src.set(q, static_cast<float>(100 * rank + q));
      comm->Alltoall(src.data(), dst.data(), 1);
      for (int q = 0; q < world; ++q)
        REQUIRE(dst.get(q) == static_cast<float>(100 * q + rank));
    }
    // async slot discipline: two in-flight Iallreduce + WaitAll
    {
      Tensor a(4, DType::F32), b(4, DType::F32);
      Tensor oa(4, DType::F32), ob(4, DType::F32);
      a.fill(1.0f);
      b.fill(2.0f);
      comm->Iallreduce(a.data(), oa.data(), 4, 0);
      comm->Iallreduce(b.data(), ob.data(), 4, 1);
      comm->WaitAll(2);
      REQUIRE(oa.get(0) == static_cast<float>(world));
      REQUIRE(ob.get(0) == static_cast<float>(2 * world));
    }
    // p2p ring: send to next, receive from previous
    if (world > 1) {
      Tensor out(4, DType::F32), in(4, DType::F32);
      out.fill(static_cast<float>(rank));
      comm->RingShift(out.data(), in.data(), 4);
      REQUIRE(in.get(0) == static_cast<float>((rank + world - 1) % world));
    }
    // ring allreduce: a count crossing the ring threshold (64 KiB) on a
    // group of >2 exercises the reduce-scatter + allgather rotation,
    // including the shorter tail block (count not divisible by world)
    if (world > 2) {
      const std::int64_t big = 40001;  // 160 KB of f32, odd tail
      Tensor src(big, DType::F32), dst(big, DType::F32);
      for (std::int64_t i = 0; i < big; ++i)
        src.set(static_cast<std::size_t>(i),
                static_cast<float>(rank + (i % 7)));
      comm->Allreduce(src.data(), dst.data(), big);
      for (std::int64_t i : {std::int64_t{0}, big / 2, big - 1}) {
        float expect = static_cast<float>(
            world * (world - 1) / 2 + world * (i % 7));
        REQUIRE(dst.get(static_cast<std::size_t>(i)) == expect);
      }
    }
    // comm split: pairs {2k, 2k+1} reduce independently
    if (world % 2 == 0) {
      auto pair = fab.split(rank, rank / 2, "pair");
      REQUIRE(pair->size() == (world >= 2 ? 2 : 1));
      Tensor src(2, DType::F32), dst(2, DType::F32);
      src.fill(static_cast<float>(rank));
      pair->Allreduce(src.data(), dst.data(), 2);
      float expect = static_cast<float>(2 * (rank / 2) * 2 + 1) / 1.0f;
      // ranks 2k and 2k+1 sum to 4k+1
      REQUIRE(dst.get(0) == static_cast<float>(4 * (rank / 2) + 1));
      (void)expect;
    }
    comm->Barrier();
    std::printf("tcp_selftest rank %d OK\n", rank);
    return 0;
  } catch (const std::exception& e) {
    std::cerr << "tcp_selftest rank " << rank << ": " << e.what() << "\n";
    return 1;
  }
}
