// Unit tests: JSON, model data parsing, schedule algebra, dtype math.
#include "dlnb_test.hpp"

#include "dlnb/json.hpp"
#include "dlnb/model_data.hpp"
#include "dlnb/schedule.hpp"
#include "dlnb/tensor.hpp"

using namespace dlnb;

// --------------------------------------------------------------- JSON
TEST(json_roundtrip) {
  Json v = Json::parse(
      R"({"a": 1, "b": [1, 2.5, "x"], "c": {"d": true, "e": null},)"
      R"( "big": 4785074604081152, "s": "q\"\né"})");
  CHECK_EQ(v.at("a").as_int(), 1);
  CHECK_EQ(v.at("b").items().size(), std::size_t{3});
  CHECK_NEAR(v.at("b").items()[1].as_double(), 2.5, 1e-12);
  CHECK(v.at("c").at("d").as_bool());
  CHECK(v.at("c").at("e").is_null());
  CHECK_EQ(v.at("big").as_int(), 4785074604081152LL);
  Json back = Json::parse(v.dump());
  CHECK_EQ(back.at("big").as_int(), 4785074604081152LL);
  CHECK_EQ(back.at("s").as_string(), v.at("s").as_string());
}

TEST(json_errors) {
  CHECK_THROWS(Json::parse("{"));
  CHECK_THROWS(Json::parse("[1,]"));
  CHECK_THROWS(Json::parse("1 2"));
  CHECK_THROWS(Json::parse("{\"a\" 1}"));
}

TEST(json_double_format) {
  // doubles must round-trip and stay doubles
  Json v(1234.5);
  Json back = Json::parse(v.dump());
  CHECK_NEAR(back.as_double(), 1234.5, 0);
  Json whole(2.0);
  CHECK(Json::parse(whole.dump()).type() == Json::Type::Double);
}

// --------------------------------------------------------- model data
static const char* kStatsText =
    "Forward_Flops:2392537302040576\n"
    "Backward_Flops:4785074604081152\n"
    "Model_Size:8030261248\n"
    "Non_Expert_size:0\n"
    "Average_Forward_Time (us):5212499.57\n"
    "Average_Backward_Time (us):10424999.14\n"
    "Batch_size:16\n"
    "FFN_Average_Forward_Time (us):3219485.03\n"
    "FFN_Average_Backward_Time (us):6438970.06\n"
    "Experts:1\n"
    "Seq_len:8192\n"
    "Embedded_dim:4096\n"
    "Device:TPU v5p\n"
    "Dtype:bfloat16\n"
    "Bytes_per_element:2.0\n";

TEST(stats_keyed_parse) {
  ModelStats st = parse_model_stats(kStatsText, "llama3_8b_16_bfloat16");
  CHECK_EQ(st.model_size, 8030261248LL);
  CHECK_NEAR(st.fwd_us, 5212499.57, 0.01);
  CHECK_NEAR(st.bwd_us, 10424999.14, 0.01);
  CHECK_EQ(st.batch_size, 16);
  CHECK_EQ(st.seq_len, 8192);
  CHECK_EQ(st.embed_dim, 4096);
  CHECK_EQ(st.dtype, std::string("bfloat16"));
  CHECK_NEAR(st.bytes_per_element, 2.0, 0);
  CHECK_EQ(st.model_bytes(), 16060522496LL);
}

TEST(stats_reordered_and_case_drift) {
  // keyed parsing must survive the drift the reference mis-parses
  // (SURVEY.md §7.4: reordered lines, non_expert_size capitalization)
  std::string reordered =
      "dtype:float8\n"
      "non_expert_size:123\n"
      "Model_Size:1000\n"
      "Average_Backward_Time (us):20.0\n"
      "Average_Forward_Time (us):10.0\n"
      "Batch_size:4\nSeq_len:128\nEmbedded_dim:64\n"
      "Forward_Flops:1\nBackward_Flops:2\n";
  ModelStats st = parse_model_stats(reordered, "t");
  CHECK_EQ(st.non_expert_size, 123);
  CHECK_EQ(st.dtype, std::string("float8"));
  CHECK_NEAR(st.fwd_us, 10.0, 0);
}

TEST(stats_missing_required) {
  CHECK_THROWS(parse_model_stats("Model_Size:10\n", "bad"));
}

TEST(model_card_parse) {
  Json j = Json::parse(R"({"embed_dim": 4096, "num_heads": 32,
    "num_kv_heads": 8, "ff_dim": 14336, "seq_len": 32768,
    "num_encoder_blocks": 0, "num_decoder_blocks": 32,
    "vocab_size": 32000, "gated_mlp": true,
    "moe_params": {"num_experts": 8, "num_experts_per_tok": 2}})");
  ModelCard c = parse_model_card(j, "mixtral_8x7b");
  CHECK_EQ(c.num_layers(), 32);
  CHECK_EQ(c.num_experts, 8);
  CHECK_EQ(c.top_k, 2);
  CHECK_EQ(c.kv_dim(), 1024);  // 4096/32*8
}

TEST(arch_name_stripping) {
  CHECK_EQ(arch_name_from_stats_name("llama3_8b_16_bfloat16"),
           std::string("llama3_8b"));
  CHECK_EQ(arch_name_from_stats_name("vit_h_128_float8"),
           std::string("vit_h"));
}

// ----------------------------------------------------------- schedule
TEST(bucket_split) {
  auto b = split_buckets(10, 3);
  CHECK_EQ(b.size(), std::size_t{3});
  CHECK_EQ(b[0], 4);
  CHECK_EQ(b[1], 3);
  CHECK_EQ(b[2], 3);
  i64 total = 0;
  for (i64 x : split_buckets(8030261248LL, 7)) total += x;
  CHECK_EQ(total, 8030261248LL);
  CHECK_THROWS(split_buckets(10, 0));
}

TEST(fsdp_padding_and_replicas) {
  ModelStats st = parse_model_stats(kStatsText, "llama3_8b_16_bfloat16");
  auto f = fsdp_schedule(st, 8, 8, 4);
  CHECK_EQ(f.num_replicas, 2);
  CHECK_EQ(f.sharding_factor, 4);
  CHECK(f.shard_size * 4 >= f.unit_sizes[0]);  // padded
  CHECK_EQ(f.padded_unit_size(), f.shard_size * 4);
  CHECK_THROWS(fsdp_schedule(st, 8, 6, 4));  // 6 % 4 != 0
}

TEST(grid3d_coords_colors) {
  Grid3D g{2, 4, 2};  // dp=2 pp=4 tp=2, world 16
  CHECK_EQ(g.world_size(), 16);
  // tp fastest-varying (hybrid_3d.cpp:283-285)
  auto c = g.coords(13);  // 13 = dp1, (13/2)%4 = 2, tp 1
  CHECK_EQ(c.dp_id, 1);
  CHECK_EQ(c.pp_id, 2);
  CHECK_EQ(c.tp_id, 1);
  CHECK_EQ(g.rank(1, 2, 1), 13);
  // all ranks in one tp group share dp_id,pp_id
  for (i64 r1 = 0; r1 < 16; ++r1)
    for (i64 r2 = 0; r2 < 16; ++r2)
      if (g.tp_color(r1) == g.tp_color(r2)) {
        CHECK_EQ(g.coords(r1).dp_id, g.coords(r2).dp_id);
        CHECK_EQ(g.coords(r1).pp_id, g.coords(r2).pp_id);
      }
}

TEST(pipeline_schedule_math) {
  ModelStats st = parse_model_stats(kStatsText, "llama3_8b_16_bfloat16");
  ModelCard card;
  card.embed_dim = 4096;
  card.num_heads = 32;
  card.seq_len = 8192;
  card.num_decoder_blocks = 32;
  auto p = pipeline_schedule(st, card, 4, 8, 1, 2);
  CHECK_EQ(p.layers_per_stage, 8);
  // pipe msg = seq*embed*samples_per_mb = 8192*4096*2
  CHECK_EQ(p.pipe_msg_elems, 8192LL * 4096 * 2);
  CHECK_EQ(p.tp_msg_elems, p.pipe_msg_elems / 2);
  CHECK_EQ(p.dp_sync_elems, st.model_size / 8);
  CHECK_NEAR(p.fwd_us_per_stage_mb, st.fwd_us / (4 * 8 * 2), 0.01);
  CHECK_THROWS(pipeline_schedule(st, card, 5, 8));   // 32 % 5
  CHECK_THROWS(pipeline_schedule(st, card, 4, 3));   // 16 % 3
}

TEST(moe_schedule_math) {
  std::string moe_stats =
      "Forward_Flops:1\nBackward_Flops:2\nModel_Size:46702792704\n"
      "Non_Expert_size:1605654528\n"
      "Average_Forward_Time (us):1000.0\nAverage_Backward_Time (us):2000.0\n"
      "Batch_size:16\nSeq_len:32768\nEmbedded_dim:4096\nDtype:bfloat16\n"
      "Bytes_per_element:2.0\n";
  ModelStats st = parse_model_stats(moe_stats, "mixtral_8x7b_16_bfloat16");
  ModelCard card;
  card.embed_dim = 4096;
  card.seq_len = 32768;
  card.num_decoder_blocks = 32;
  card.num_experts = 8;
  card.top_k = 2;
  auto m = moe_schedule(st, card, 4, 8, 4);
  // tokens/mb = 2*32768; a2a = tokens*topk*embed/shards
  CHECK_EQ(m.a2a_elems, 2LL * 32768 * 2 * 4096 / 4);
  CHECK_EQ(m.a2a_per_direction, 2 * 8);
  CHECK_EQ(m.nonexpert_sync_elems, 1605654528LL / 4);
  CHECK_EQ(m.expert_sync_elems, (46702792704LL - 1605654528LL) / (4 * 4));
  CHECK_THROWS(moe_schedule(st, card, 4, 8, 3));  // 8 % 3
}

TEST(sequence_schedule_math) {
  ModelStats st = parse_model_stats(kStatsText, "llama3_8b_16_bfloat16");
  ModelCard card;
  card.embed_dim = 4096;
  card.num_heads = 32;
  card.num_kv_heads = 8;
  card.seq_len = 8192;
  card.num_decoder_blocks = 32;
  auto s = sequence_schedule(st, card, 4);
  CHECK_EQ(s.seq_per_rank, 2048);
  CHECK_EQ(s.num_ring_hops, 3);
  CHECK_EQ(s.kv_block_elems, 2LL * 16 * 2048 * 1024);
  CHECK_EQ(s.a2a_elems, 16LL * 2048 * 4096);
  CHECK_THROWS(sequence_schedule(st, card, 3));
}

// -------------------------------------------------------------- dtypes
TEST(bf16_roundtrip) {
  CHECK_NEAR(bf16_to_f32(f32_to_bf16(1.0f)), 1.0, 0);
  CHECK_NEAR(bf16_to_f32(f32_to_bf16(-2.5f)), -2.5, 0);
  // bf16 represents small integers exactly
  for (float v : {0.0f, 1.0f, 2.0f, 128.0f, 256.0f})
    CHECK_NEAR(bf16_to_f32(f32_to_bf16(v)), v, 0);
}

TEST(f8_roundtrip) {
  for (float v : {0.0f, 0.5f, 1.0f, -1.0f, 2.0f, 8.0f, -16.0f})
    CHECK_NEAR(f8e4m3_to_f32(f32_to_f8e4m3(v)), v, 0);
  CHECK_NEAR(f8e4m3_to_f32(f32_to_f8e4m3(1000.0f)), 448.0, 0);  // clamp
}

TEST(json_copy_is_deep) {
  Json global = Json::object();
  global["model"] = "a";
  Json rec = Json::object();
  rec["global"] = global;          // copy
  global["model"] = "b";           // mutate original
  CHECK_EQ(rec.at("global").at("model").as_string(), std::string("a"));
  Json arr = Json::array();
  arr.push_back(1);
  Json arr2 = arr;
  arr2.push_back(2);
  CHECK_EQ(arr.items().size(), std::size_t{1});
}

TEST(bf16_nan_stays_nan) {
  std::uint32_t payload_nan = 0x7F800001;  // NaN with low-bits payload
  float f;
  std::memcpy(&f, &payload_nan, 4);
  float back = bf16_to_f32(f32_to_bf16(f));
  CHECK(back != back);  // still NaN, not Inf
}

TEST(tensor_zero_init) {
  Tensor t(1024, DType::BF16);
  CHECK_EQ(t.bytes(), std::size_t{2048});
  for (int i = 0; i < 1024; i += 97) CHECK_NEAR(t.get(i), 0.0, 0);
  t.set(5, 3.5f);
  CHECK_NEAR(t.get(5), 3.5, 0);
}
