// Unit tests: StableHLO program generation, cache keys, options proto.
// (Device-free — the semantic compile+execute validation of the same
// programs runs in tests/test_pjrt_programs.py against a multi-device
// CPU PJRT client.)
#include "dlnb_test.hpp"

#include "dlnb/stablehlo_gen.hpp"

using namespace dlnb;

static bool contains(const std::string& hay, const std::string& needle) {
  return hay.find(needle) != std::string::npos;
}

TEST(allreduce_module_text) {
  CollectiveProgram p;
  p.op = CollOp::AllReduce;
  p.dtype = DType::BF16;
  p.in_count = 128;
  p.num_replicas = 4;
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "mhlo.num_replicas = 4 : i32"));
  CHECK(contains(m, "mhlo.num_partitions = 1 : i32"));
  CHECK(contains(m, "tensor<128xbf16>"));
  CHECK(contains(m, "stablehlo.all_reduce"));
  CHECK(contains(m, "replica_groups = dense<[[0, 1, 2, 3]]> : "
                    "tensor<1x4xi64>"));
  CHECK(contains(m, "stablehlo.add"));
}

TEST(split_becomes_multiple_groups) {
  // MPI_Comm_split analogue: one module, several replica groups
  CollectiveProgram p;
  p.op = CollOp::AllReduce;
  p.in_count = 8;
  p.num_replicas = 4;
  p.groups = {{0, 1}, {2, 3}};
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "dense<[[0, 1], [2, 3]]> : tensor<2x2xi64>"));
}

TEST(allgather_shapes) {
  CollectiveProgram p;
  p.op = CollOp::AllGather;
  p.in_count = 4;
  p.num_replicas = 4;
  CHECK_EQ(p.out_count(), 16);
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "(tensor<4xf32>) -> tensor<16xf32>"));
  CHECK(contains(m, "all_gather_dim = 0"));
}

TEST(reduce_scatter_shapes) {
  CollectiveProgram p;
  p.op = CollOp::ReduceScatter;
  p.in_count = 16;
  p.num_replicas = 4;
  CHECK_EQ(p.out_count(), 4);
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "(tensor<16xf32>) -> tensor<4xf32>"));
  CHECK(contains(m, "scatter_dimension = 0"));
  CHECK(contains(m, "stablehlo.add"));
}

TEST(all_to_all_split_count_from_group) {
  CollectiveProgram p;
  p.op = CollOp::AllToAll;
  p.in_count = 16;
  p.num_replicas = 8;
  p.groups = {{0, 1, 2, 3}, {4, 5, 6, 7}};
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "split_count = 4 : i64"));
  CHECK(contains(m, "(tensor<16xf32>) -> tensor<16xf32>"));
}

TEST(collective_permute_pairs) {
  CollectiveProgram p;
  p.op = CollOp::CollectivePermute;
  p.in_count = 8;
  p.num_replicas = 4;
  p.pairs = {{0, 1}, {1, 2}, {2, 3}, {3, 0}};
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "source_target_pairs = dense<[[0, 1], [1, 2], [2, 3], "
                    "[3, 0]]> : tensor<4x2xi64>"));
}

TEST(f8_dtype_name) {
  CollectiveProgram p;
  p.op = CollOp::AllReduce;
  p.dtype = DType::F8E4M3;
  p.in_count = 8;
  p.num_replicas = 2;
  CHECK(contains(generate_stablehlo(p), "tensor<8xf8E4M3FN>"));
}

TEST(cache_keys_distinguish) {
  CollectiveProgram a;
  a.op = CollOp::AllReduce;
  a.in_count = 8;
  a.num_replicas = 4;
  CollectiveProgram b = a;
  CHECK_EQ(a.cache_key(), b.cache_key());
  b.in_count = 16;
  CHECK(a.cache_key() != b.cache_key());
  b = a;
  b.dtype = DType::BF16;
  CHECK(a.cache_key() != b.cache_key());
  b = a;
  b.groups = {{0, 1}, {2, 3}};
  CHECK(a.cache_key() != b.cache_key());
  b = a;
  b.op = CollOp::AllGather;
  CHECK(a.cache_key() != b.cache_key());
}

TEST(compile_options_proto_wire_format) {
  // field 3 (executable_build_options, length-delimited) wrapping
  // field 4 (num_replicas) and field 5 (num_partitions) varints
  std::string p = compile_options_proto(4);
  CHECK_EQ(static_cast<unsigned char>(p[0]), 0x1Au);  // (3<<3)|2
  CHECK_EQ(static_cast<unsigned char>(p[1]), 4u);     // payload length
  CHECK_EQ(static_cast<unsigned char>(p[2]), 0x20u);  // (4<<3)|0
  CHECK_EQ(static_cast<unsigned char>(p[3]), 4u);     // num_replicas = 4
  CHECK_EQ(static_cast<unsigned char>(p[4]), 0x28u);  // (5<<3)|0
  CHECK_EQ(static_cast<unsigned char>(p[5]), 1u);     // num_partitions = 1
  // multi-byte varint
  std::string big = compile_options_proto(300);
  CHECK_EQ(static_cast<unsigned char>(big[3]), 0xACu);  // 300 = 0xAC 0x02
  CHECK_EQ(static_cast<unsigned char>(big[4]), 0x02u);
}
