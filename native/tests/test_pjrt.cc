// Unit tests: StableHLO program generation, cache keys, options proto,
// and the PjrtFabric communicator stack over the host executor.
// (Device-free — the semantic compile+execute validation of the same
// programs runs in tests/test_pjrt_programs.py against a multi-device
// CPU PJRT client.)
#include "dlnb_test.hpp"

#include <atomic>

#include "dlnb/pjrt_fabric.hpp"
#include "dlnb/stablehlo_gen.hpp"

using namespace dlnb;

static bool contains(const std::string& hay, const std::string& needle) {
  return hay.find(needle) != std::string::npos;
}

TEST(allreduce_module_text) {
  CollectiveProgram p;
  p.op = CollOp::AllReduce;
  p.dtype = DType::BF16;
  p.in_count = 128;
  p.num_replicas = 4;
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "mhlo.num_replicas = 4 : i32"));
  CHECK(contains(m, "mhlo.num_partitions = 1 : i32"));
  CHECK(contains(m, "tensor<128xbf16>"));
  CHECK(contains(m, "stablehlo.all_reduce"));
  CHECK(contains(m, "replica_groups = dense<[[0, 1, 2, 3]]> : "
                    "tensor<1x4xi64>"));
  CHECK(contains(m, "stablehlo.add"));
}

TEST(split_becomes_multiple_groups) {
  // MPI_Comm_split analogue: one module, several replica groups
  CollectiveProgram p;
  p.op = CollOp::AllReduce;
  p.in_count = 8;
  p.num_replicas = 4;
  p.groups = {{0, 1}, {2, 3}};
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "dense<[[0, 1], [2, 3]]> : tensor<2x2xi64>"));
}

TEST(allgather_shapes) {
  CollectiveProgram p;
  p.op = CollOp::AllGather;
  p.in_count = 4;
  p.num_replicas = 4;
  CHECK_EQ(p.out_count(), 16);
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "(tensor<4xf32>) -> tensor<16xf32>"));
  CHECK(contains(m, "all_gather_dim = 0"));
}

TEST(reduce_scatter_shapes) {
  CollectiveProgram p;
  p.op = CollOp::ReduceScatter;
  p.in_count = 16;
  p.num_replicas = 4;
  CHECK_EQ(p.out_count(), 4);
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "(tensor<16xf32>) -> tensor<4xf32>"));
  CHECK(contains(m, "scatter_dimension = 0"));
  CHECK(contains(m, "stablehlo.add"));
}

TEST(all_to_all_split_count_from_group) {
  CollectiveProgram p;
  p.op = CollOp::AllToAll;
  p.in_count = 16;
  p.num_replicas = 8;
  p.groups = {{0, 1, 2, 3}, {4, 5, 6, 7}};
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "split_count = 4 : i64"));
  CHECK(contains(m, "(tensor<16xf32>) -> tensor<16xf32>"));
}

TEST(collective_permute_pairs) {
  CollectiveProgram p;
  p.op = CollOp::CollectivePermute;
  p.in_count = 8;
  p.num_replicas = 4;
  p.pairs = {{0, 1}, {1, 2}, {2, 3}, {3, 0}};
  std::string m = generate_stablehlo(p);
  CHECK(contains(m, "source_target_pairs = dense<[[0, 1], [1, 2], [2, 3], "
                    "[3, 0]]> : tensor<4x2xi64>"));
}

TEST(f8_dtype_name) {
  CollectiveProgram p;
  p.op = CollOp::AllReduce;
  p.dtype = DType::F8E4M3;
  p.in_count = 8;
  p.num_replicas = 2;
  CHECK(contains(generate_stablehlo(p), "tensor<8xf8E4M3FN>"));
}

TEST(cache_keys_distinguish) {
  CollectiveProgram a;
  a.op = CollOp::AllReduce;
  a.in_count = 8;
  a.num_replicas = 4;
  CollectiveProgram b = a;
  CHECK_EQ(a.cache_key(), b.cache_key());
  b.in_count = 16;
  CHECK(a.cache_key() != b.cache_key());
  b = a;
  b.dtype = DType::BF16;
  CHECK(a.cache_key() != b.cache_key());
  b = a;
  b.groups = {{0, 1}, {2, 3}};
  CHECK(a.cache_key() != b.cache_key());
  b = a;
  b.op = CollOp::AllGather;
  CHECK(a.cache_key() != b.cache_key());
}

TEST(device_assignment_proto) {
  // with device ids, build options carry field 9 (DeviceAssignmentProto):
  // replica_count, computation_count=1, computation_devices{ids}
  std::string p = compile_options_proto(2, 1, {0, 2});
  // outer: field 3 msg
  CHECK_EQ(static_cast<unsigned char>(p[0]), 0x1Au);
  std::string inner = p.substr(2);
  // skip num_replicas + num_partitions (4 bytes)
  CHECK_EQ(static_cast<unsigned char>(inner[4]), 0x4Au);  // (9<<3)|2
  std::string assign = inner.substr(6);
  CHECK_EQ(static_cast<unsigned char>(assign[0]), 0x08u);  // replica_count
  CHECK_EQ(static_cast<unsigned char>(assign[1]), 2u);
  CHECK_EQ(static_cast<unsigned char>(assign[2]), 0x10u);  // computation_count
  CHECK_EQ(static_cast<unsigned char>(assign[3]), 1u);
  CHECK_EQ(static_cast<unsigned char>(assign[4]), 0x1Au);  // devices msg
  // ComputationDevice: packed replica_device_ids = [0, 2]
  CHECK_EQ(static_cast<unsigned char>(assign[6]), 0x0Au);  // (1<<3)|2
  CHECK_EQ(static_cast<unsigned char>(assign[7]), 2u);     // 2 varint bytes
  CHECK_EQ(static_cast<unsigned char>(assign[8]), 0u);
  CHECK_EQ(static_cast<unsigned char>(assign[9]), 2u);
  // no list -> no field 9 anywhere
  CHECK(compile_options_proto(2).find(static_cast<char>(0x4A)) ==
        std::string::npos);
}

// ---------------------------------------------------------------------
// PjrtFabric over the host executor: the full --backend pjrt stack minus
// the plugin (reference role: dp.cpp:183-189 wiring the vendor backend
// into the hot loop).

TEST(pjrt_fabric_world_allreduce) {
  PjrtFabric fab(4, DType::F32, std::make_unique<HostExecutor>());
  std::atomic<int> ok{0};
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(8, DType::F32), dst(8, DType::F32);
    src.fill(static_cast<float>(r + 1));
    comm->Allreduce(src.data(), dst.data(), 8);
    if (dst.get(0) == 10.0f && dst.get(7) == 10.0f) ++ok;
  });
  CHECK_EQ(ok.load(), 4);
}

TEST(pjrt_fabric_split_groups_reduce_independently) {
  PjrtFabric fab(4, DType::F32, std::make_unique<HostExecutor>());
  std::atomic<int> ok{0};
  fab.launch([&](int r) {
    auto comm = fab.split(r, r / 2, "pair");
    CHECK_EQ(comm->size(), 2);
    CHECK_EQ(comm->rank(), r % 2);
    Tensor src(4, DType::F32), dst(4, DType::F32);
    src.fill(static_cast<float>(r));
    comm->Allreduce(src.data(), dst.data(), 4);
    // group {0,1} sums to 1, group {2,3} sums to 5
    float expect = r < 2 ? 1.0f : 5.0f;
    if (dst.get(0) == expect) ++ok;
  });
  CHECK_EQ(ok.load(), 4);
}

TEST(pjrt_fabric_allgather_reduce_scatter_alltoall) {
  PjrtFabric fab(4, DType::F32, std::make_unique<HostExecutor>());
  std::atomic<int> ok{0};
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    // allgather
    Tensor src(2, DType::F32), gathered(8, DType::F32);
    src.set(0, static_cast<float>(r));
    src.set(1, static_cast<float>(10 * r));
    comm->Allgather(src.data(), gathered.data(), 2);
    bool g_ok = true;
    for (int k = 0; k < 4; ++k)
      g_ok = g_ok && gathered.get(2 * k) == static_cast<float>(k) &&
             gathered.get(2 * k + 1) == static_cast<float>(10 * k);
    // reduce-scatter-block: every rank contributes [r, r, r, r, ...] over
    // 4 blocks of 2; each block sums to 0+1+2+3 = 6
    Tensor rs_src(8, DType::F32), rs_dst(2, DType::F32);
    rs_src.fill(static_cast<float>(r));
    comm->ReduceScatterBlock(rs_src.data(), rs_dst.data(), 2);
    bool rs_ok = rs_dst.get(0) == 6.0f && rs_dst.get(1) == 6.0f;
    // alltoall: src block j on rank r = 10r + j; dst block q = 10q + r
    Tensor a_src(4, DType::F32), a_dst(4, DType::F32);
    for (int j = 0; j < 4; ++j)
      a_src.set(j, static_cast<float>(10 * r + j));
    comm->Alltoall(a_src.data(), a_dst.data(), 1);
    bool a_ok = true;
    for (int q = 0; q < 4; ++q)
      a_ok = a_ok && a_dst.get(q) == static_cast<float>(10 * q + r);
    if (g_ok && rs_ok && a_ok) ++ok;
  });
  CHECK_EQ(ok.load(), 4);
}

TEST(pjrt_fabric_ring_shift_is_collective_permute) {
  PjrtFabric fab(4, DType::F32, std::make_unique<HostExecutor>());
  std::atomic<int> ok{0};
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(4, DType::F32), dst(4, DType::F32);
    src.fill(static_cast<float>(r));
    comm->RingShift(src.data(), dst.data(), 4, 1);
    // rank r receives predecessor's block
    float expect = static_cast<float>((r + 3) % 4);
    if (dst.get(0) == expect && dst.get(3) == expect) ++ok;
  });
  CHECK_EQ(ok.load(), 4);
}

TEST(pjrt_fabric_slot_overlap_and_waitall) {
  // the dp bucket pattern: async Iallreduce per slot, WaitAll drains
  PjrtFabric fab(2, DType::BF16, std::make_unique<HostExecutor>());
  std::atomic<int> ok{0};
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor a(4, DType::BF16), b(4, DType::BF16);
    Tensor out_a(4, DType::BF16), out_b(4, DType::BF16);
    a.fill(1.0f);
    b.fill(2.0f);
    comm->Iallreduce(a.data(), out_a.data(), 4, 0);
    comm->Iallreduce(b.data(), out_b.data(), 4, 1);
    comm->WaitAll(2);
    if (out_a.get(0) == 2.0f && out_b.get(0) == 4.0f) ++ok;
  });
  CHECK_EQ(ok.load(), 2);
}

TEST(pjrt_fabric_mismatch_detected) {
  PjrtFabric fab(2, DType::F32, std::make_unique<HostExecutor>());
  CHECK_THROWS(fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(4, DType::F32), dst(4, DType::F32);
    // ranks disagree on count -> must abort, not hang or mis-execute
    comm->Allreduce(src.data(), dst.data(), r == 0 ? 4 : 2);
  }));
}

TEST(pjrt_fabric_p2p_host_mailbox) {
  PjrtFabric fab(2, DType::F32, std::make_unique<HostExecutor>());
  std::atomic<int> ok{0};
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor buf(4, DType::F32);
    if (r == 0) {
      buf.fill(7.0f);
      comm->Send(buf.data(), 4, 1);
      ++ok;
    } else {
      comm->Recv(buf.data(), 4, 0);
      if (buf.get(0) == 7.0f) ++ok;
    }
  });
  CHECK_EQ(ok.load(), 2);
}

TEST(pjrt_fabric_cache_counts) {
  auto exec = std::make_unique<HostExecutor>();
  auto* exec_raw = exec.get();
  PjrtFabric fab(2, DType::F32, std::move(exec));
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(4, DType::F32), dst(4, DType::F32);
    comm->Allreduce(src.data(), dst.data(), 4);  // miss
    comm->Allreduce(src.data(), dst.data(), 4);  // hit
    comm->Allgather(src.data(), dst.data(), 2);  // miss (different op)
  });
  CHECK_EQ(exec_raw->cache_misses(), 2u);
  CHECK_EQ(exec_raw->cache_hits(), 1u);
}

TEST(pjrt_fabric_uneven_split_rejected) {
  PjrtFabric fab(3, DType::F32, std::make_unique<HostExecutor>());
  CHECK_THROWS(fab.launch([&](int r) {
    // colors {0,0,1}: groups of 2 and 1 — replica_groups must be uniform
    fab.split(r, r / 2, "bad");
  }));
}

TEST(compile_options_proto_wire_format) {
  // field 3 (executable_build_options, length-delimited) wrapping
  // field 4 (num_replicas) and field 5 (num_partitions) varints
  std::string p = compile_options_proto(4);
  CHECK_EQ(static_cast<unsigned char>(p[0]), 0x1Au);  // (3<<3)|2
  CHECK_EQ(static_cast<unsigned char>(p[1]), 4u);     // payload length
  CHECK_EQ(static_cast<unsigned char>(p[2]), 0x20u);  // (4<<3)|0
  CHECK_EQ(static_cast<unsigned char>(p[3]), 4u);     // num_replicas = 4
  CHECK_EQ(static_cast<unsigned char>(p[4]), 0x28u);  // (5<<3)|0
  CHECK_EQ(static_cast<unsigned char>(p[5]), 1u);     // num_partitions = 1
  // multi-byte varint
  std::string big = compile_options_proto(300);
  CHECK_EQ(static_cast<unsigned char>(big[3]), 0xACu);  // 300 = 0xAC 0x02
  CHECK_EQ(static_cast<unsigned char>(big[4]), 0x02u);
}
