// Minimal check/assert test harness for the native tier's unit tests.
// Each test binary registers TESTs and main() runs them all, printing
// one PASS/FAIL line per test — exit code is the failure count (ctest
// integration needs nothing more).
#pragma once

#include <cmath>
#include <functional>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

namespace dlnb_test {

struct Case {
  std::string name;
  std::function<void()> fn;
};

inline std::vector<Case>& registry() {
  static std::vector<Case> r;
  return r;
}

struct Registrar {
  Registrar(std::string name, std::function<void()> fn) {
    registry().push_back({std::move(name), std::move(fn)});
  }
};

struct Failure {
  std::string msg;
};

#define TEST(name)                                                     \
  static void test_##name();                                           \
  static ::dlnb_test::Registrar reg_##name{#name, test_##name};        \
  static void test_##name()

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::ostringstream os_;                                          \
      os_ << __FILE__ << ":" << __LINE__ << ": CHECK failed: " #cond;  \
      throw ::dlnb_test::Failure{os_.str()};                           \
    }                                                                  \
  } while (0)

#define CHECK_EQ(a, b)                                                 \
  do {                                                                 \
    auto va_ = (a);                                                    \
    auto vb_ = (b);                                                    \
    if (!(va_ == vb_)) {                                               \
      std::ostringstream os_;                                          \
      os_ << __FILE__ << ":" << __LINE__ << ": CHECK_EQ failed: " #a   \
          << " (" << va_ << ") != " #b << " (" << vb_ << ")";          \
      throw ::dlnb_test::Failure{os_.str()};                           \
    }                                                                  \
  } while (0)

#define CHECK_NEAR(a, b, tol)                                          \
  do {                                                                 \
    double va_ = (a);                                                  \
    double vb_ = (b);                                                  \
    if (std::fabs(va_ - vb_) > (tol)) {                                \
      std::ostringstream os_;                                          \
      os_ << __FILE__ << ":" << __LINE__ << ": CHECK_NEAR failed: " #a \
          << " (" << va_ << ") vs " #b << " (" << vb_ << ") tol "      \
          << (tol);                                                    \
      throw ::dlnb_test::Failure{os_.str()};                           \
    }                                                                  \
  } while (0)

#define CHECK_THROWS(expr)                                             \
  do {                                                                 \
    bool threw_ = false;                                               \
    try {                                                              \
      (void)(expr);                                                    \
    } catch (const ::dlnb_test::Failure&) {                            \
      throw;                                                           \
    } catch (...) {                                                    \
      threw_ = true;                                                   \
    }                                                                  \
    if (!threw_) {                                                     \
      std::ostringstream os_;                                          \
      os_ << __FILE__ << ":" << __LINE__                               \
          << ": CHECK_THROWS failed: " #expr " did not throw";         \
      throw ::dlnb_test::Failure{os_.str()};                           \
    }                                                                  \
  } while (0)

inline int run_all() {
  int failures = 0;
  for (const auto& c : registry()) {
    try {
      c.fn();
      std::cout << "PASS " << c.name << "\n";
    } catch (const Failure& f) {
      std::cout << "FAIL " << c.name << ": " << f.msg << "\n";
      ++failures;
    } catch (const std::exception& e) {
      std::cout << "FAIL " << c.name << ": unexpected exception: " << e.what()
                << "\n";
      ++failures;
    }
  }
  std::cout << registry().size() - failures << "/" << registry().size()
            << " tests passed\n";
  return failures;
}

}  // namespace dlnb_test

int main() { return ::dlnb_test::run_all(); }
