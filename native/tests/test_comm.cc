// Unit tests: threaded shm fabric — collectives, p2p, slots, splits.
#include "dlnb_test.hpp"

#include <atomic>
#include <chrono>
#include <thread>

#include "dlnb/harness.hpp"
#include "dlnb/shm_backend.hpp"
#include "dlnb/tensor.hpp"

using namespace dlnb;

TEST(allreduce_f32) {
  ShmFabric fab(4, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(16, DType::F32), dst(16, DType::F32);
    src.fill(static_cast<float>(r + 1));
    comm->Allreduce(src.data(), dst.data(), 16);
    for (int i = 0; i < 16; ++i) CHECK_NEAR(dst.get(i), 10.0, 0);  // 1+2+3+4
  });
}

TEST(allreduce_bf16) {
  ShmFabric fab(8, DType::BF16);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(32, DType::BF16), dst(32, DType::BF16);
    src.fill(2.0f);
    comm->Allreduce(src.data(), dst.data(), 32);
    for (int i = 0; i < 32; ++i) CHECK_NEAR(dst.get(i), 16.0, 0);
  });
}

TEST(allgather) {
  ShmFabric fab(4, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(8, DType::F32), dst(32, DType::F32);
    src.fill(static_cast<float>(r));
    comm->Allgather(src.data(), dst.data(), 8);
    for (int blk = 0; blk < 4; ++blk)
      for (int i = 0; i < 8; ++i)
        CHECK_NEAR(dst.get(blk * 8 + i), static_cast<double>(blk), 0);
  });
}

TEST(reduce_scatter_block) {
  ShmFabric fab(4, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(16, DType::F32), dst(4, DType::F32);
    // src block b holds value b+1 on every rank -> reduced block r = 4*(r+1)
    for (int b = 0; b < 4; ++b)
      for (int i = 0; i < 4; ++i) src.set(b * 4 + i, static_cast<float>(b + 1));
    comm->ReduceScatterBlock(src.data(), dst.data(), 4);
    for (int i = 0; i < 4; ++i) CHECK_NEAR(dst.get(i), 4.0 * (r + 1), 0);
  });
}

TEST(alltoall) {
  ShmFabric fab(4, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor src(4, DType::F32), dst(4, DType::F32);
    // rank r sends value 10*r + dest to dest
    for (int d = 0; d < 4; ++d) src.set(d, static_cast<float>(10 * r + d));
    comm->Alltoall(src.data(), dst.data(), 1);
    for (int s = 0; s < 4; ++s) CHECK_NEAR(dst.get(s), 10.0 * s + r, 0);
  });
}

TEST(p2p_ring) {
  ShmFabric fab(4, DType::BF16);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor out(8, DType::BF16), in(8, DType::BF16);
    out.fill(static_cast<float>(r));
    int next = (r + 1) % 4, prev = (r + 3) % 4;
    // even ranks send first (classic deadlock-free pairing)
    if (r % 2 == 0) {
      comm->Send(out.data(), 8, next);
      comm->Recv(in.data(), 8, prev);
    } else {
      comm->Recv(in.data(), 8, prev);
      comm->Send(out.data(), 8, next);
    }
    CHECK_NEAR(in.get(0), static_cast<double>(prev), 0);
  });
}

TEST(isend_irecv_slots) {
  ShmFabric fab(2, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor a(4, DType::F32), b(4, DType::F32);
    a.fill(static_cast<float>(r + 1));
    if (r == 0) {
      comm->Isend(a.data(), 4, 1, 0);
      comm->Irecv(b.data(), 4, 1, 1);
    } else {
      comm->Isend(a.data(), 4, 0, 1);
      comm->Irecv(b.data(), 4, 0, 0);
    }
    comm->WaitAll(2);
    CHECK_NEAR(b.get(0), r == 0 ? 2.0 : 1.0, 0);
  });
}

TEST(iallreduce_overlap) {
  // nonblocking allreduces on distinct slots complete out of band while
  // the rank "computes" — the DP proxy's core overlap pattern
  ShmFabric fab(4, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    constexpr int kBuckets = 4;
    std::vector<Tensor> grads, sums;
    for (int b = 0; b < kBuckets; ++b) {
      grads.emplace_back(64, DType::F32);
      sums.emplace_back(64, DType::F32);
      grads.back().fill(static_cast<float>(b + 1));
    }
    for (int b = 0; b < kBuckets; ++b) {
      burn_us(200);  // simulated bwd compute of bucket b
      comm->Iallreduce(grads[b].data(), sums[b].data(), 64, b);
    }
    comm->WaitAll(kBuckets);
    for (int b = 0; b < kBuckets; ++b)
      CHECK_NEAR(sums[b].get(0), 4.0 * (b + 1), 0);
  });
}

TEST(wait_single_slot) {
  ShmFabric fab(2, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor a(4, DType::F32), s0(4, DType::F32), s1(4, DType::F32);
    a.fill(1.0f);
    comm->Iallreduce(a.data(), s0.data(), 4, 0);
    comm->Iallreduce(a.data(), s1.data(), 4, 3);
    comm->Wait(3);
    CHECK_NEAR(s1.get(0), 2.0, 0);
    comm->Wait(0);
    CHECK_NEAR(s0.get(0), 2.0, 0);
    comm->Wait(2);  // idle slot: immediate no-op
  });
}

TEST(split_groups) {
  // 8 ranks, 2x2x2 grid (dp,pp,tp): split along tp_color; each pair
  // allreduces independently (reference comm-color math,
  // hybrid_3d.cpp:287-300)
  ShmFabric fab(8, DType::F32);
  fab.launch([&](int r) {
    // tp fastest-varying: pairs (0,1),(2,3),(4,5),(6,7)
    int color = r / 2;
    auto tp = fab.split(r, color, "tp");
    CHECK_EQ(tp->size(), 2);
    CHECK_EQ(tp->rank(), r % 2);
    Tensor src(4, DType::F32), dst(4, DType::F32);
    src.fill(static_cast<float>(r));
    tp->Allreduce(src.data(), dst.data(), 4);
    // pair sums: r + partner = 2*color*2+1 = 4*color+1
    CHECK_NEAR(dst.get(0), 4.0 * color + 1.0, 0);
  });
}

TEST(two_splits_sequential) {
  // fsdp's two communicators: intra-shard then inter-replica
  ShmFabric fab(8, DType::F32);
  fab.launch([&](int r) {
    auto unit = fab.split(r, r / 4, "unit");       // shards of 4
    auto repl = fab.split(r, r % 4, "allreduce");  // replicas of 2
    CHECK_EQ(unit->size(), 4);
    CHECK_EQ(repl->size(), 2);
    Tensor a(2, DType::F32), b(2, DType::F32);
    a.fill(1.0f);
    unit->Allreduce(a.data(), b.data(), 2);
    CHECK_NEAR(b.get(0), 4.0, 0);
    repl->Allreduce(a.data(), b.data(), 2);
    CHECK_NEAR(b.get(0), 2.0, 0);
  });
}

TEST(mismatch_detected) {
  ShmFabric fab(2, DType::F32);
  bool caught = false;
  try {
    fab.launch([&](int r) {
      auto comm = fab.world_comm(r);
      Tensor a(8, DType::F32), b(8, DType::F32);
      // ranks disagree on count -> must abort, not hang
      comm->Allreduce(a.data(), b.data(), r == 0 ? 8 : 4);
    });
  } catch (const std::exception&) {
    caught = true;
  }
  CHECK(caught);
}

TEST(mismatch_then_reuse) {
  // the rendezvous must fully reset after a mismatch so later matched
  // collectives on the same group still work (no wedge)
  ShmFabric fab(2, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor a(8, DType::F32), b(8, DType::F32);
    a.fill(1.0f);
    bool threw = false;
    try {
      comm->Allreduce(a.data(), b.data(), r == 0 ? 8 : 4);
    } catch (const std::exception&) {
      threw = true;
    }
    CHECK(threw);
    comm->Allreduce(a.data(), b.data(), 8);  // matched retry succeeds
    CHECK_NEAR(b.get(0), 2.0, 0);
  });
}

TEST(slot_p2p_no_cross_match) {
  // two concurrent slot-tagged transfers between the same rank pair with
  // DIFFERENT sizes must pair by slot, never cross-match
  ShmFabric fab(2, DType::F32);
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    Tensor big(64, DType::F32), small(4, DType::F32);
    Tensor rbig(64, DType::F32), rsmall(4, DType::F32);
    big.fill(7.0f);
    small.fill(9.0f);
    for (int iter = 0; iter < 20; ++iter) {  // race repeatedly
      if (r == 0) {
        comm->Isend(big.data(), 64, 1, 0);
        comm->Isend(small.data(), 4, 1, 1);
      } else {
        comm->Irecv(rbig.data(), 64, 0, 0);
        comm->Irecv(rsmall.data(), 4, 0, 1);
      }
      comm->WaitAll(2);
      if (r == 1) {
        CHECK_NEAR(rbig.get(63), 7.0, 0);
        CHECK_NEAR(rsmall.get(3), 9.0, 0);
      }
    }
  });
}

TEST(barrier_sequencing) {
  ShmFabric fab(4, DType::F32);
  std::atomic<int> phase{0};
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    if (r == 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(20));
      phase.store(1);
    }
    comm->Barrier();
    CHECK_EQ(phase.load(), 1);  // nobody passes before rank 0 arrives
  });
}

// -------------------------------------------------------------- harness
TEST(estimate_runs_math) {
  // mean of [., ., 100us, 100us] -> 0.1ms; 1s floor -> 10000 runs
  std::vector<double> w{1000.0, 500.0, 100.0, 100.0};
  CHECK_EQ(estimate_runs(w, 1.0), 10000);
  CHECK_EQ(estimate_runs(w, 0.0001), 1);
  CHECK_EQ(estimate_runs({50.0}, 0.001), 20);  // falls back to last entry
  CHECK_EQ(estimate_runs({}, 1.0), 1);
}

TEST(measured_run_loop) {
  ShmFabric fab(2, DType::F32);
  std::vector<TimerSet> timers(2);
  std::vector<RankRun> runs(2);
  HarnessConfig cfg;
  cfg.warmup = 3;
  cfg.runs = 4;
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    runs[r] = run_measured(cfg, *comm, timers[r], [&](TimerSet& ts) {
      auto t = ts.scoped("work_time");
      burn_us(100);
    });
  });
  for (int r = 0; r < 2; ++r) {
    CHECK_EQ(runs[r].runs, 4);
    CHECK_EQ(timers[r].values("runtimes").size(), std::size_t{4});
    CHECK_EQ(timers[r].values("work_time").size(), std::size_t{4});
    CHECK_EQ(runs[r].warmup_us.size(), std::size_t{3});
    for (double t : timers[r].values("runtimes")) CHECK(t >= 90.0);
  }
}

TEST(min_exectime_agreement) {
  ShmFabric fab(4, DType::F32);
  std::vector<RankRun> runs(4);
  HarnessConfig cfg;
  cfg.warmup = 3;
  cfg.min_exectime_s = 0.01;  // 10ms of ~1ms steps -> ~10 runs
  fab.launch([&](int r) {
    auto comm = fab.world_comm(r);
    TimerSet ts;
    runs[r] = run_measured(cfg, *comm, ts,
                           [&](TimerSet&) { burn_us(1000); });
  });
  // all ranks agreed on the same count
  CHECK_EQ(runs[0].runs, runs[1].runs);
  CHECK_EQ(runs[0].runs, runs[3].runs);
  CHECK(runs[0].runs >= 5);
  CHECK(runs[0].runs <= 30);
}

TEST(record_schema) {
  TimerSet ts;
  ts.record("runtimes", 10.5);
  ts.record("runtimes", 11.5);
  ts.record("barrier_time", 1.0);
  ts.record("barrier_time", 2.0);
  Json global = Json::object();
  global["model"] = "gpt2_l_16_bfloat16";
  global["world_size"] = 1;
  Json mesh = Json::object();
  mesh["platform"] = "shm";
  RankReport rep;
  rep.rank = 0;
  rep.hostname = "test";
  rep.timers = &ts;
  Json rec = make_record("dp", global, mesh, 2, {100.0, 90.0}, {rep});
  CHECK_EQ(rec.at("section").as_string(), std::string("dp"));
  CHECK_EQ(rec.at("num_runs").as_int(), 2);
  CHECK_EQ(rec.at("ranks").items().size(), std::size_t{1});
  const Json& row = rec.at("ranks").items()[0];
  CHECK_EQ(row.at("runtimes").items().size(), std::size_t{2});
  CHECK_NEAR(row.at("runtimes").items()[1].as_double(), 11.5, 0);
  // round-trips through the parser
  Json back = Json::parse(rec.dump());
  CHECK_EQ(back.at("global").at("model").as_string(),
           std::string("gpt2_l_16_bfloat16"));
}

TEST(timer_merge_entries) {
  // middle-stage PP merge: 6 raw entries grouped by 2 -> 3 totals
  // (reference hybrid_2d.cpp:416-439)
  TimerSet ts;
  for (double v : {1.0, 2.0, 3.0, 4.0, 5.0, 6.0}) ts.record("pp_comm", v);
  ts.merge_entries("pp_comm", 2);
  const auto& v = ts.values("pp_comm");
  CHECK_EQ(v.size(), std::size_t{3});
  CHECK_NEAR(v[0], 3.0, 0);
  CHECK_NEAR(v[2], 11.0, 0);
}
