// Minimal JSON value: parse + serialize.
//
// The reference links nlohmann/json for model-card parsing
// (reference cpp/utils.hpp:17); this rebuild ships a small self-contained
// reader/writer so the native tier has zero external dependencies.  It
// covers the full JSON grammar the framework needs: objects, arrays,
// strings (with escapes), numbers (kept as int64 when integral so model
// sizes and FLOP counts round-trip exactly), booleans, null.
#pragma once

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace dlnb {

class Json;
using JsonArray = std::vector<Json>;
// std::map keeps key order deterministic for golden-file tests.
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Int, Double, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Int), int_(v) {}
  Json(long v) : type_(Type::Int), int_(v) {}
  Json(long long v) : type_(Type::Int), int_(v) {}
  Json(unsigned long long v) : type_(Type::Int),
                               int_(static_cast<std::int64_t>(v)) {}
  Json(std::size_t v) : type_(Type::Int), int_(static_cast<std::int64_t>(v)) {}
  Json(double v) : type_(Type::Double), dbl_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array),
                      arr_(std::make_shared<JsonArray>(std::move(a))) {}
  Json(JsonObject o) : type_(Type::Object),
                       obj_(std::make_shared<JsonObject>(std::move(o))) {}

  // Value semantics: copying deep-copies containers so a record assembled
  // from shared metadata never aliases it (nlohmann-style behavior).
  Json(const Json& o)
      : type_(o.type_), bool_(o.bool_), int_(o.int_), dbl_(o.dbl_),
        str_(o.str_) {
    if (o.arr_) arr_ = std::make_shared<JsonArray>(*o.arr_);
    if (o.obj_) obj_ = std::make_shared<JsonObject>(*o.obj_);
  }
  Json& operator=(const Json& o) {
    if (this != &o) {
      Json tmp(o);
      *this = std::move(tmp);
    }
    return *this;
  }
  Json(Json&&) = default;
  Json& operator=(Json&&) = default;

  static Json array() { return Json(JsonArray{}); }
  static Json object() { return Json(JsonObject{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_number() const {
    return type_ == Type::Int || type_ == Type::Double;
  }
  bool is_string() const { return type_ == Type::String; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_object() const { return type_ == Type::Object; }

  bool as_bool() const { expect(Type::Bool); return bool_; }
  std::int64_t as_int() const {
    if (type_ == Type::Double) return static_cast<std::int64_t>(dbl_);
    expect(Type::Int);
    return int_;
  }
  double as_double() const {
    if (type_ == Type::Int) return static_cast<double>(int_);
    expect(Type::Double);
    return dbl_;
  }
  const std::string& as_string() const { expect(Type::String); return str_; }

  JsonArray& items() { expect(Type::Array); return *arr_; }
  const JsonArray& items() const { expect(Type::Array); return *arr_; }
  JsonObject& fields() { expect(Type::Object); return *obj_; }
  const JsonObject& fields() const { expect(Type::Object); return *obj_; }

  bool contains(const std::string& key) const {
    return is_object() && obj_->count(key) > 0;
  }
  const Json& at(const std::string& key) const {
    expect(Type::Object);
    auto it = obj_->find(key);
    if (it == obj_->end()) throw std::out_of_range("json: no key '" + key + "'");
    return it->second;
  }
  Json& operator[](const std::string& key) {
    if (type_ == Type::Null) {
      type_ = Type::Object;
      obj_ = std::make_shared<JsonObject>();
    }
    expect(Type::Object);
    return (*obj_)[key];
  }
  void push_back(Json v) {
    if (type_ == Type::Null) {
      type_ = Type::Array;
      arr_ = std::make_shared<JsonArray>();
    }
    expect(Type::Array);
    arr_->push_back(std::move(v));
  }

  // -------------------------------------------------------------- dump
  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

  void write(std::ostream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Int: os << int_; break;
      case Type::Double: write_double(os, dbl_); break;
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[';
        bool first = true;
        for (const auto& v : *arr_) {
          if (!first) os << ", ";
          first = false;
          v.write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& [k, v] : *obj_) {
          if (!first) os << ", ";
          first = false;
          write_string(os, k);
          os << ": ";
          v.write(os);
        }
        os << '}';
        break;
      }
    }
  }

  // -------------------------------------------------------------- parse
  static Json parse(const std::string& text) {
    Parser p{text, 0};
    Json v = p.value();
    p.skip_ws();
    if (p.pos != text.size())
      throw std::runtime_error("json: trailing characters at " +
                               std::to_string(p.pos));
    return v;
  }

 private:
  struct Parser {
    const std::string& s;
    std::size_t pos;

    [[noreturn]] void fail(const std::string& what) {
      throw std::runtime_error("json: " + what + " at offset " +
                               std::to_string(pos));
    }
    void skip_ws() {
      while (pos < s.size() && (s[pos] == ' ' || s[pos] == '\t' ||
                                s[pos] == '\n' || s[pos] == '\r'))
        ++pos;
    }
    char peek() {
      if (pos >= s.size()) fail("unexpected end");
      return s[pos];
    }
    char next() {
      char c = peek();
      ++pos;
      return c;
    }
    void expect_lit(const char* lit) {
      for (const char* p = lit; *p; ++p)
        if (pos >= s.size() || s[pos++] != *p) fail("bad literal");
    }

    Json value() {
      skip_ws();
      char c = peek();
      switch (c) {
        case '{': return object();
        case '[': return array();
        case '"': return Json(string());
        case 't': expect_lit("true"); return Json(true);
        case 'f': expect_lit("false"); return Json(false);
        case 'n': expect_lit("null"); return Json(nullptr);
        default: return number();
      }
    }

    Json object() {
      next();  // '{'
      JsonObject out;
      skip_ws();
      if (peek() == '}') { next(); return Json(std::move(out)); }
      while (true) {
        skip_ws();
        std::string key = string();
        skip_ws();
        if (next() != ':') fail("expected ':'");
        out[key] = value();
        skip_ws();
        char c = next();
        if (c == '}') break;
        if (c != ',') fail("expected ',' or '}'");
      }
      return Json(std::move(out));
    }

    Json array() {
      next();  // '['
      JsonArray out;
      skip_ws();
      if (peek() == ']') { next(); return Json(std::move(out)); }
      while (true) {
        out.push_back(value());
        skip_ws();
        char c = next();
        if (c == ']') break;
        if (c != ',') fail("expected ',' or ']'");
      }
      return Json(std::move(out));
    }

    std::string string() {
      if (next() != '"') fail("expected string");
      std::string out;
      while (true) {
        char c = next();
        if (c == '"') break;
        if (c == '\\') {
          char e = next();
          switch (e) {
            case '"': out += '"'; break;
            case '\\': out += '\\'; break;
            case '/': out += '/'; break;
            case 'b': out += '\b'; break;
            case 'f': out += '\f'; break;
            case 'n': out += '\n'; break;
            case 'r': out += '\r'; break;
            case 't': out += '\t'; break;
            case 'u': {
              unsigned cp = 0;
              for (int i = 0; i < 4; ++i) {
                char h = next();
                cp <<= 4;
                if (h >= '0' && h <= '9') cp |= h - '0';
                else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
                else fail("bad \\u escape");
              }
              // UTF-8 encode (BMP only; surrogate pairs unneeded here)
              if (cp < 0x80) {
                out += static_cast<char>(cp);
              } else if (cp < 0x800) {
                out += static_cast<char>(0xC0 | (cp >> 6));
                out += static_cast<char>(0x80 | (cp & 0x3F));
              } else {
                out += static_cast<char>(0xE0 | (cp >> 12));
                out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
                out += static_cast<char>(0x80 | (cp & 0x3F));
              }
              break;
            }
            default: fail("bad escape");
          }
        } else {
          out += c;
        }
      }
      return out;
    }

    Json number() {
      std::size_t start = pos;
      if (peek() == '-') next();
      while (pos < s.size() && std::isdigit(static_cast<unsigned char>(s[pos])))
        ++pos;
      bool integral = true;
      if (pos < s.size() && s[pos] == '.') {
        integral = false;
        ++pos;
        while (pos < s.size() &&
               std::isdigit(static_cast<unsigned char>(s[pos])))
          ++pos;
      }
      if (pos < s.size() && (s[pos] == 'e' || s[pos] == 'E')) {
        integral = false;
        ++pos;
        if (pos < s.size() && (s[pos] == '+' || s[pos] == '-')) ++pos;
        while (pos < s.size() &&
               std::isdigit(static_cast<unsigned char>(s[pos])))
          ++pos;
      }
      std::string tok = s.substr(start, pos - start);
      if (tok.empty() || tok == "-") fail("bad number");
      try {
        if (integral) return Json(static_cast<long long>(std::stoll(tok)));
        return Json(std::stod(tok));
      } catch (const std::exception&) {
        fail("unparseable number '" + tok + "'");
      }
    }
  };

  static void write_string(std::ostream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\b': os << "\\b"; break;
        case '\f': os << "\\f"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof buf, "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  static void write_double(std::ostream& os, double d) {
    if (std::isnan(d) || std::isinf(d)) { os << "null"; return; }
    char buf[32];
    std::snprintf(buf, sizeof buf, "%.17g", d);
    // trim to shortest round-trip-safe form
    for (int prec = 1; prec < 17; ++prec) {
      char t[32];
      std::snprintf(t, sizeof t, "%.*g", prec, d);
      if (std::stod(t) == d) { std::snprintf(buf, sizeof buf, "%s", t); break; }
    }
    os << buf;
    // ensure it reads back as a double, not an int
    if (!std::strpbrk(buf, ".eE")) os << ".0";
  }

  void expect(Type t) const {
    if (type_ != t) throw std::runtime_error("json: wrong type access");
  }

  Type type_;
  bool bool_ = false;
  std::int64_t int_ = 0;
  double dbl_ = 0.0;
  std::string str_;
  std::shared_ptr<JsonArray> arr_;
  std::shared_ptr<JsonObject> obj_;
};

}  // namespace dlnb
