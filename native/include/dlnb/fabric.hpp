// Abstract rank fabric — backend selection point for the native tier.
//
// Plays the role of the reference's compile-time backend ladder (reference
// cpp/data_parallel/dp.cpp:183-224: #ifdef NCCL / oneCCL / MPI communicator
// construction): a Fabric owns the world, hands each rank its
// ProxyCommunicator, and arbitrates communicator splits.  Unlike the
// reference, the backend is a RUNTIME choice (--backend shm|pjrt), so one
// binary serves both the in-process test fabric and the TPU runtime.
//
// Implementations:
//   * ShmFabric  (shm_backend.hpp)  — threaded rank fabric, the testable
//     fake (reference `mpi_cpu` role).
//   * PjrtFabric (pjrt_fabric.hpp) — collectives execute as single
//     multi-group XLA modules through a CollectiveExecutor (the PJRT
//     plugin on real TPU devices, or a host reference executor).
#pragma once

#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "dlnb/communicator.hpp"
#include "dlnb/harness.hpp"
#include "dlnb/json.hpp"
#include "dlnb/tensor.hpp"

namespace dlnb {

class Fabric {
 public:
  virtual ~Fabric() = default;

  virtual int world_size() const = 0;
  virtual DType dtype() const = 0;
  virtual std::string backend() const = 0;  // "shm" | "pjrt"

  virtual std::unique_ptr<ProxyCommunicator> world_comm(int rank) = 0;
  // Collective split: all world ranks must call with their color
  // (MPI_Comm_split role, key = world rank — reference comm-color math,
  // hybrid_3d.cpp:287-300).
  virtual std::unique_ptr<ProxyCommunicator> split(
      int world_rank, int color, const std::string& name) = 0;

  // Run body(rank) on world_size threads; rethrows the first rank failure.
  // (Cross-process fabrics run body once, for this process's rank.)
  virtual void launch(const std::function<void(int)>& body) = 0;

  // Rank `world_rank` is dying mid-run (fault_plan.hpp crash events, or
  // any rank-body exception): make the death OBSERVABLE to the others —
  // in-process fabrics abort the dead rank's groups so blocked
  // rendezvous throw instead of hanging forever; cross-process fabrics
  // suppress the clean-departure goodbye so peers read the EOF as a
  // death (tcp_backend.hpp `dying_`).  Default: nothing to do.
  virtual void mark_rank_dead(int world_rank) { (void)world_rank; }

  // Ranks measured BY THIS PROCESS (record rows to emit); in-process
  // fabrics own the whole world, cross-process fabrics their one rank.
  virtual std::vector<int> local_ranks() const {
    std::vector<int> all(world_size());
    for (int i = 0; i < world_size(); ++i) all[i] = i;
    return all;
  }
  // This process's index in a multi-process run (metrics.merge key).
  virtual int process_index() const { return 0; }

  // Simulated compute for rank `rank`: `us` microseconds, scaled by
  // `time_scale`.  Default is the host sleep (the reference's usleep,
  // cpp/data_parallel/dp.cpp:93); device-backed fabrics override this to
  // burn REAL device cycles via a calibrated compiled kernel in the same
  // slot (the JAX tier's proxies/burn.py equivalent), so "compute" loads
  // the same execution stream the collectives run on.
  virtual void burn(int rank, double us, double time_scale) {
    (void)rank;
    burn_us(us, time_scale);
  }

  // Enrich the emitted record: backend/platform identity into `meta`,
  // device fabric description (and compile-cache stats) into `mesh`.
  virtual void describe(Json& meta, Json& mesh) const = 0;
};

}  // namespace dlnb
