// Interval timers + structured section emission — the ccutils equivalent.
//
// The reference leans on the external ccutils header library for
// `CCUTILS_MPI_TIMER_DEF` (a per-rank std::vector<float> of interval
// timings), `CCUTILS_MPI_SECTION_*` named output sections, and
// `CCUTILS_*_JSON_PUT` key/value emission (reference
// cpp/data_parallel/dp.cpp:28-30, 69-70, 275-295; SURVEY.md §1
// "out-of-repo dependencies").  The rebuild owns this layer: a TimerSet
// holds named per-iteration microsecond vectors per rank, and
// `make_record` assembles the same JSON schema the Python tier's
// metrics.emit writes, so dlnetbench_tpu.metrics.parser ingests native
// runs unchanged.
#pragma once

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "dlnb/json.hpp"

namespace dlnb {

using Clock = std::chrono::steady_clock;

inline double us_since(Clock::time_point t0) {
  return std::chrono::duration<double, std::micro>(Clock::now() - t0).count();
}

// Named per-iteration timer vectors for one rank.  Equivalent of ccutils
// `__timer_vals_<name>` declared by CCUTILS_MPI_TIMER_DEF; `clear()` is the
// reference's pre-measured-run timer reset (dp.cpp:258, fsdp.cpp:384-389).
class TimerSet {
 public:
  void record(const std::string& name, double us) { vals_[name].push_back(us); }

  // Scoped START/STOP (CCUTILS_MPI_TIMER_START/STOP equivalent).
  class Scoped {
   public:
    Scoped(TimerSet& ts, std::string name)
        : ts_(ts), name_(std::move(name)), t0_(Clock::now()) {}
    ~Scoped() { ts_.record(name_, us_since(t0_)); }

   private:
    TimerSet& ts_;
    std::string name_;
    Clock::time_point t0_;
  };
  Scoped scoped(std::string name) { return Scoped(*this, std::move(name)); }

  const std::vector<double>& values(const std::string& name) const {
    static const std::vector<double> kEmpty;
    auto it = vals_.find(name);
    return it == vals_.end() ? kEmpty : it->second;
  }
  const std::map<std::string, std::vector<double>>& all() const {
    return vals_;
  }
  void clear() { vals_.clear(); }

  // Snapshot/rollback for retried step attempts (fault_session.hpp):
  // a step abandoned mid-flight leaves partial scoped-timer entries
  // behind; rolling back to the pre-attempt snapshot keeps every timer
  // array per-iteration aligned when the step is re-run.
  std::map<std::string, std::size_t> sizes() const {
    std::map<std::string, std::size_t> out;
    for (const auto& [name, v] : vals_) out[name] = v.size();
    return out;
  }
  void truncate(const std::map<std::string, std::size_t>& snapshot) {
    for (auto& [name, v] : vals_) {
      auto it = snapshot.find(name);
      std::size_t keep = it == snapshot.end() ? 0 : it->second;
      if (v.size() > keep) v.resize(keep);
    }
  }

  // Merge raw per-hop entries into per-iteration totals of `group` entries
  // each — the reference's middle-stage PP timer merge
  // (hybrid_2d.cpp:416-439 collapses recv+send entries per microbatch).
  void merge_entries(const std::string& name, std::size_t group) {
    auto it = vals_.find(name);
    if (it == vals_.end() || group <= 1) return;
    std::vector<double>& v = it->second;
    std::vector<double> merged;
    merged.reserve(v.size() / group + 1);
    for (std::size_t i = 0; i < v.size(); i += group) {
      double s = 0;
      for (std::size_t j = i; j < std::min(i + group, v.size()); ++j) s += v[j];
      merged.push_back(s);
    }
    v = std::move(merged);
  }

 private:
  std::map<std::string, std::vector<double>> vals_;
};

// Continuous telemetry (ISSUE 14) — the native twin of the Python
// tier's metrics/telemetry.py FlightRecorder: a fixed-capacity ring of
// per-step samples {rank, step, t_s, step_wall_us}, fed by the
// measured loop (harness.hpp run_measured) when DLNB_TELEMETRY is set
// and emitted as the record's "telemetry" global (a per-process
// measurement: metrics/merge.py treats the block as volatile, and
// analysis/critical_path.matrix_from_flights merges the per-rank
// samples into the blame engine's step matrix).  Off by default: the
// disabled path is one atomic-free bool test per step.
class TelemetryRing {
 public:
  static TelemetryRing& instance() {
    static TelemetryRing ring;
    return ring;
  }

  bool enabled() const { return enabled_; }

  void record(int rank, int step, double wall_us) {
    if (!enabled_) return;
    std::lock_guard<std::mutex> lock(mu_);
    Sample s;
    s.rank = rank;
    s.step = step;
    s.t_s = std::chrono::duration<double>(Clock::now() - origin_).count();
    s.wall_us = wall_us;
    buf_[recorded_ % buf_.size()] = s;
    ++recorded_;
  }

  // The record's "telemetry" global, schema-matched to the Python
  // tier's FlightRecorder.telemetry_block (plus full resident samples
  // — the native tier has no separate flight-dump channel, the record
  // IS the artifact).
  Json to_json() const {
    std::lock_guard<std::mutex> lock(mu_);
    Json block = Json::object();
    block["capacity"] = static_cast<std::int64_t>(buf_.size());
    block["recorded"] = static_cast<std::int64_t>(recorded_);
    block["dropped"] = static_cast<std::int64_t>(
        recorded_ > buf_.size() ? recorded_ - buf_.size() : 0);
    Json arr = Json::array();
    const std::size_t n = std::min(recorded_, buf_.size());
    const std::size_t head = recorded_ > buf_.size()
                                 ? recorded_ % buf_.size()
                                 : 0;
    for (std::size_t i = 0; i < n; ++i) {
      const Sample& s = buf_[(head + i) % buf_.size()];
      Json j = Json::object();
      j["rank"] = s.rank;
      j["step"] = s.step;
      j["t_s"] = s.t_s;
      j["step_wall_us"] = s.wall_us;
      arr.push_back(j);
    }
    block["samples"] = arr;
    return block;
  }

 private:
  TelemetryRing() : origin_(Clock::now()) {
    const char* on = std::getenv("DLNB_TELEMETRY");
    enabled_ = on && *on && std::string(on) != "0";
    std::size_t cap = 512;
    if (const char* c = std::getenv("DLNB_TELEMETRY_CAPACITY"); c && *c) {
      long v = std::atol(c);
      if (v > 0) cap = static_cast<std::size_t>(v);
    }
    buf_.resize(cap);
  }

  struct Sample {
    int rank = 0;
    int step = 0;
    double t_s = 0;
    double wall_us = 0;
  };

  bool enabled_ = false;
  Clock::time_point origin_;
  std::vector<Sample> buf_;
  std::size_t recorded_ = 0;
  mutable std::mutex mu_;
};

// One per-rank output row: identity + this rank's timers.
struct RankReport {
  int rank = 0;
  int device_id = 0;
  int process_index = 0;
  std::string hostname;
  Json extra = Json::object();  // stage_id / dp_id / tp_id etc.
  const TimerSet* timers = nullptr;
};

// Band summary of one timer array — the Python tier's
// metrics.stats.summarize mirrored exactly ({value: median, best: min,
// band: [lo, hi], n}), so records from both tiers self-describe their
// statistics the same way (schema v2).
inline Json band_summary(const std::vector<double>& vals) {
  Json s = Json::object();
  if (vals.empty()) {
    s["value"] = 0.0;
    s["best"] = 0.0;
    Json band = Json::array();
    band.push_back(0.0);
    band.push_back(0.0);
    s["band"] = band;
    s["n"] = std::int64_t{0};
    return s;
  }
  std::vector<double> v(vals);
  std::sort(v.begin(), v.end());
  const std::size_t n = v.size();
  s["value"] = n % 2 ? v[n / 2] : (v[n / 2 - 1] + v[n / 2]) / 2.0;
  s["best"] = v.front();
  Json band = Json::array();
  band.push_back(v.front());
  band.push_back(v.back());
  s["band"] = band;
  s["n"] = static_cast<std::int64_t>(n);
  return s;
}

// Assemble the run record in the exact schema of the Python tier's
// metrics.emit.result_to_record (section/version/global/mesh/num_runs/
// warmup_times/ranks) so one parser serves both tiers.
inline Json make_record(const std::string& section, const Json& global_meta,
                        const Json& mesh_meta, int num_runs,
                        const std::vector<double>& warmup_us,
                        const std::vector<RankReport>& ranks) {
  Json rec = Json::object();
  rec["section"] = section;
  rec["version"] = 2;
  rec["global"] = global_meta;
  rec["mesh"] = mesh_meta;
  rec["num_runs"] = num_runs;
  Json warm = Json::array();
  for (double w : warmup_us) warm.push_back(w);
  rec["warmup_times"] = warm;
  Json rows = Json::array();
  for (const auto& r : ranks) {
    Json row = Json::object();
    row["rank"] = r.rank;
    row["device_id"] = r.device_id;
    row["process_index"] = r.process_index;
    row["hostname"] = r.hostname;
    if (r.extra.is_object())
      for (const auto& [k, v] : r.extra.fields()) row[k] = v;
    if (r.timers) {
      Json summary = Json::object();
      for (const auto& [name, vals] : r.timers->all()) {
        Json arr = Json::array();
        for (double v : vals) arr.push_back(v);
        row[name] = arr;
        summary[name] = band_summary(vals);
      }
      row["summary"] = summary;  // schema v2: stats ride the record
    }
    rows.push_back(row);
  }
  rec["ranks"] = rows;
  return rec;
}

}  // namespace dlnb
