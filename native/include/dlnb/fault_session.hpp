// Per-rank fault session: the degradation-policy harness around a
// proxy's step (fault_plan.hpp has the plan/injection layer; this file
// is the part that needs the Fabric).
//
// Usage (see src/proxies/dp.cc):
//
//   fault::Session fses(fab, rank);              // pre-splits survivors
//   run = run_measured(cfg, *comm, ts, [&](TimerSet& t) {
//     fses.step(t, *comm, [&](ProxyCommunicator& c) { ...schedule...(c) });
//   });
//
// Behavior per policy when the plan scripts a crash:
//   * The victim rank throws RankFailure at its trigger iteration,
//     AFTER marking the fabric (shm: abort the victim's groups so
//     blocked survivors throw; tcp/hier: suppress the Bye so the EOF
//     reads as a death).  The throw propagates — a crashed rank emits
//     nothing, exactly like a real death.
//   * fail_fast (default): survivors' next collective on a group
//     containing the victim throws (the existing detection paths,
//     provoked deterministically for the first time) and the run dies.
//   * shrink: the constructor pre-split a survivor communicator while
//     everyone was alive (the plan is deterministic — every rank knows
//     the victims up front, so no runtime agreement protocol is
//     needed).  A survivor catches the failed step, rolls its timers
//     back to the pre-attempt snapshot, stamps detection wall time
//     (step start -> failure surfaced), re-runs the step on the
//     survivor group, stamps recovery wall time, and continues the
//     remaining iterations degraded.  The failed attempt's cost stays
//     visible: that iteration's recorded runtime includes detection +
//     recovery + the re-run.
//
// Delay/jitter sleeps and the step-boundary crash trigger ride
// Plan::on_step_begin; drop/partition events live in the transport
// hooks and need nothing from this layer.
#pragma once

#include <algorithm>
#include <memory>
#include <vector>

#include "dlnb/fabric.hpp"
#include "dlnb/fault_plan.hpp"
#include "dlnb/timers.hpp"

namespace dlnb {
namespace fault {

// Step-boundary injection WITHOUT the shrink machinery: delay/jitter
// sleeps + the crash trigger, with the fabric marked before a scripted
// death propagates (so survivors fail fast instead of hanging).  For
// proxies whose communicator grid cannot shrink (fsdp's
// sharding_factor x replicas, the pipelines) — they support
// fail_fast/retry plans and must REFUSE a crash+shrink plan loudly
// (guard below) rather than half-apply it.
inline void step_guard(Fabric& fab, int rank) {
  auto& plan = Plan::instance();
  if (!plan.active()) return;
  try {
    plan.on_step_begin(rank);
  } catch (const RankFailure&) {
    fab.mark_rank_dead(rank);
    throw;
  }
}

inline void require_no_shrink(const char* proxy) {
  auto& plan = Plan::instance();
  if (plan.active() && plan.policy() == "shrink" &&
      (!plan.crash_victims().empty() || plan.has_preempt()))
    throw std::runtime_error(
        std::string(proxy) +
        ": the shrink policy (and the preempt/rejoin elastic arc) "
        "needs a survivor regrouping this proxy's communicator grid "
        "does not support — use the dp proxy (or the python tier's "
        "rebuild path), or policy fail_fast/retry");
}

// For proxies with NO step-boundary fault driver at all: refuse plans
// whose events could only fire at step boundaries.  Without this, the
// record would stamp the plan (run_proxy_main describes it for every
// proxy) while the faults silently never fired — and the analysis
// layer would refuse busbw on runs that were actually clean.
inline void require_collective_scope_only(const char* proxy) {
  auto& plan = Plan::instance();
  if (plan.active() && plan.has_step_events())
    throw std::runtime_error(
        std::string(proxy) +
        ": this proxy has no step-boundary fault driver — only "
        "collective-scoped delay/jitter (where == \"collective\") and "
        "drop events apply here; step-scoped delay/jitter, crash and "
        "partition plans are wired for dp (full policies) and fsdp "
        "(injection + fail-fast)");
}

class Session {
 public:
  Session(Fabric& fab, int world_rank)
      : fab_(fab), rank_(world_rank), plan_(Plan::instance()) {
    if (!plan_.active()) return;
    auto victims = plan_.crash_victims();
    victim_ = std::find(victims.begin(), victims.end(), rank_) !=
              victims.end();
    auto evictees = plan_.preempt_victims();
    evictee_ = std::find(evictees.begin(), evictees.end(), rank_) !=
               evictees.end();
    if (plan_.policy() == "shrink" &&
        (!victims.empty() || !evictees.empty())) {
      // collective split while everyone is still alive: survivors get
      // color 0 — a new comm id everywhere, so stale frames of a
      // failed world-comm step can never match the survivor group's
      // traffic.  Crash victims share color 1 (their group is never
      // used — they die); each PREEMPT victim gets its own singleton
      // group (color 2 + rank): an evicted rank keeps replaying its
      // schedule locally while drained (staying hot to rejoin
      // quickly), so its timer arrays keep one sample per iteration —
      // the record shape every parser validates — while it moves no
      // fabric bytes.  The faulted-window busbw refusal keeps those
      // local samples out of every bandwidth figure.
      int color = 0;
      if (victim_) color = 1;
      if (evictee_) color = 2 + rank_;
      surv_ = fab.split(world_rank, color, "fault_survivors");
    }
    if (plan_.policy() == "shrink" && plan_.rejoin_iteration() >= 0)
      // the grow half, pre-split like shrink's: every rank (including
      // the future evictee) takes color 0 on a FRESH comm id, so the
      // returning rank is accepted deterministically — no runtime
      // agreement protocol, the plan already told everyone
      rejoin_ = fab.split(world_rank, 0, "fault_rejoin");
  }

  template <typename Body>
  void step(TimerSet& t, ProxyCommunicator& world, Body&& body) {
    if (!plan_.active()) {
      body(world);
      return;
    }
    try {
      plan_.on_step_begin(rank_);
    } catch (const RankFailure&) {
      fab_.mark_rank_dead(rank_);
      throw;
    }
    long long it = plan_.iteration_of(rank_) - 1;  // the step running now
    // ---- elastic eviction window (preempt -> rejoin) ----
    evicted_now_ = plan_.evicted(rank_, it);
    long long rejoin_at = plan_.rejoin_iteration();
    if (rejoin_ && rejoin_at >= 0 && it >= rejoin_at) {
      // grow back: everyone — the returning evictee included — runs on
      // the pre-split full-world comm from the rejoin trigger on.  The
      // first step's wall time is the measured grow cost (the
      // rendezvous waits for the returning rank) and degraded_world is
      // cleared by the emitter (proxy_runner.hpp).
      evicted_now_ = false;
      if (!rejoined_) {
        auto r0 = Clock::now();
        body(*rejoin_);
        auto& rep = plan_.report(rank_);
        rep.rejoin_us.store(us_since(r0));
        rep.rejoined.store(true);
        rejoined_ = true;
        return;
      }
      body(*rejoin_);
      return;
    }
    if (evicted_now_ && surv_) {
      // the drained victim: local singleton replay (see ctor comment)
      body(*surv_);
      return;
    }
    ProxyCommunicator& c =
        ((shrunk_ || (plan_.any_evicted(it) && !evictee_)) && surv_)
            ? *surv_ : world;
    auto snapshot = t.sizes();
    auto t0 = Clock::now();
    try {
      body(c);
    } catch (const RankFailure&) {
      throw;  // scripted deaths never degrade into a shrink
    } catch (const std::exception&) {
      if (victim_ || shrunk_ || !surv_ || plan_.policy() != "shrink")
        throw;
      double detection = us_since(t0);
      t.truncate(snapshot);  // drop the failed attempt's partial timers
      shrunk_ = true;
      auto r0 = Clock::now();
      body(*surv_);
      auto& rep = plan_.report(rank_);
      rep.detection_us.store(detection);
      rep.recovery_us.store(us_since(r0));
      rep.shrunk.store(true);
    }
  }

  bool shrunk() const { return shrunk_; }
  bool victim() const { return victim_; }
  // elastic-eviction state as of the LAST step() call — the selftest's
  // expected-sum oracle
  bool evicted_now() const { return evicted_now_; }
  bool rejoined() const { return rejoined_; }
  // degraded membership while any rank is drained (survivor view)
  bool degraded_now() const {
    return !rejoined_ && !evicted_now_ &&
           plan_.any_evicted(plan_.iteration_of(rank_) - 1);
  }

 private:
  Fabric& fab_;
  int rank_;
  Plan& plan_;
  bool victim_ = false;    // scripted crash victim
  bool evictee_ = false;   // scripted preempt victim
  bool shrunk_ = false;
  bool evicted_now_ = false;
  bool rejoined_ = false;
  std::unique_ptr<ProxyCommunicator> surv_;
  std::unique_ptr<ProxyCommunicator> rejoin_;
};

}  // namespace fault
}  // namespace dlnb
