// Per-rank fault session: the degradation-policy harness around a
// proxy's step (fault_plan.hpp has the plan/injection layer; this file
// is the part that needs the Fabric).
//
// Usage (see src/proxies/dp.cc):
//
//   fault::Session fses(fab, rank);              // pre-splits survivors
//   run = run_measured(cfg, *comm, ts, [&](TimerSet& t) {
//     fses.step(t, *comm, [&](ProxyCommunicator& c) { ...schedule...(c) });
//   });
//
// Behavior per policy when the plan scripts a crash:
//   * The victim rank throws RankFailure at its trigger iteration,
//     AFTER marking the fabric (shm: abort the victim's groups so
//     blocked survivors throw; tcp/hier: suppress the Bye so the EOF
//     reads as a death).  The throw propagates — a crashed rank emits
//     nothing, exactly like a real death.
//   * fail_fast (default): survivors' next collective on a group
//     containing the victim throws (the existing detection paths,
//     provoked deterministically for the first time) and the run dies.
//   * shrink: the constructor pre-split a survivor communicator while
//     everyone was alive (the plan is deterministic — every rank knows
//     the victims up front, so no runtime agreement protocol is
//     needed).  A survivor catches the failed step, rolls its timers
//     back to the pre-attempt snapshot, stamps detection wall time
//     (step start -> failure surfaced), re-runs the step on the
//     survivor group, stamps recovery wall time, and continues the
//     remaining iterations degraded.  The failed attempt's cost stays
//     visible: that iteration's recorded runtime includes detection +
//     recovery + the re-run.
//
// Delay/jitter sleeps and the step-boundary crash trigger ride
// Plan::on_step_begin; drop/partition events live in the transport
// hooks and need nothing from this layer.
#pragma once

#include <algorithm>
#include <memory>
#include <vector>

#include "dlnb/fabric.hpp"
#include "dlnb/fault_plan.hpp"
#include "dlnb/timers.hpp"

namespace dlnb {
namespace fault {

// Step-boundary injection WITHOUT the shrink machinery: delay/jitter
// sleeps + the crash trigger, with the fabric marked before a scripted
// death propagates (so survivors fail fast instead of hanging).  For
// proxies whose communicator grid cannot shrink (fsdp's
// sharding_factor x replicas, the pipelines) — they support
// fail_fast/retry plans and must REFUSE a crash+shrink plan loudly
// (guard below) rather than half-apply it.
inline void step_guard(Fabric& fab, int rank) {
  auto& plan = Plan::instance();
  if (!plan.active()) return;
  try {
    plan.on_step_begin(rank);
  } catch (const RankFailure&) {
    fab.mark_rank_dead(rank);
    throw;
  }
}

inline void require_no_shrink(const char* proxy) {
  auto& plan = Plan::instance();
  if (plan.active() && plan.policy() == "shrink" &&
      !plan.crash_victims().empty())
    throw std::runtime_error(
        std::string(proxy) +
        ": the shrink policy needs a survivor regrouping this proxy's "
        "communicator grid does not support — use the dp proxy (or the "
        "python tier's rebuild path), or policy fail_fast/retry");
}

// For proxies with NO step-boundary fault driver at all: refuse plans
// whose events could only fire at step boundaries.  Without this, the
// record would stamp the plan (run_proxy_main describes it for every
// proxy) while the faults silently never fired — and the analysis
// layer would refuse busbw on runs that were actually clean.
inline void require_collective_scope_only(const char* proxy) {
  auto& plan = Plan::instance();
  if (plan.active() && plan.has_step_events())
    throw std::runtime_error(
        std::string(proxy) +
        ": this proxy has no step-boundary fault driver — only "
        "collective-scoped delay/jitter (where == \"collective\") and "
        "drop events apply here; step-scoped delay/jitter, crash and "
        "partition plans are wired for dp (full policies) and fsdp "
        "(injection + fail-fast)");
}

class Session {
 public:
  Session(Fabric& fab, int world_rank)
      : fab_(fab), rank_(world_rank), plan_(Plan::instance()) {
    if (!plan_.active()) return;
    auto victims = plan_.crash_victims();
    victim_ = std::find(victims.begin(), victims.end(), rank_) !=
              victims.end();
    if (plan_.policy() == "shrink" && !victims.empty())
      // collective split while everyone is still alive: survivors get
      // color 0, victims color 1 (their group is never used) — a new
      // comm id everywhere, so stale frames of a failed world-comm
      // step can never match the survivor group's traffic
      surv_ = fab.split(world_rank, victim_ ? 1 : 0, "fault_survivors");
  }

  template <typename Body>
  void step(TimerSet& t, ProxyCommunicator& world, Body&& body) {
    if (!plan_.active()) {
      body(world);
      return;
    }
    try {
      plan_.on_step_begin(rank_);
    } catch (const RankFailure&) {
      fab_.mark_rank_dead(rank_);
      throw;
    }
    ProxyCommunicator& c = (shrunk_ && surv_) ? *surv_ : world;
    auto snapshot = t.sizes();
    auto t0 = Clock::now();
    try {
      body(c);
    } catch (const RankFailure&) {
      throw;  // scripted deaths never degrade into a shrink
    } catch (const std::exception&) {
      if (victim_ || shrunk_ || !surv_ || plan_.policy() != "shrink")
        throw;
      double detection = us_since(t0);
      t.truncate(snapshot);  // drop the failed attempt's partial timers
      shrunk_ = true;
      auto r0 = Clock::now();
      body(*surv_);
      auto& rep = plan_.report(rank_);
      rep.detection_us.store(detection);
      rep.recovery_us.store(us_since(r0));
      rep.shrunk.store(true);
    }
  }

  bool shrunk() const { return shrunk_; }
  bool victim() const { return victim_; }

 private:
  Fabric& fab_;
  int rank_;
  Plan& plan_;
  bool victim_ = false;
  bool shrunk_ = false;
  std::unique_ptr<ProxyCommunicator> surv_;
};

}  // namespace fault
}  // namespace dlnb
