// Model data layer: keyed stats-file parser + architecture-card parser.
//
// Counterpart of the reference's `get_model_stats` / `count_layers`
// (reference cpp/utils.hpp:200-294).  The reference parses stat files by
// LINE ORDER and silently mis-parses drifted files (SURVEY.md §7.4); this
// parser is keyed and case-insensitive, matching the Python tier
// (dlnetbench_tpu/core/model_stats.py) so both tiers read the same 72+
// data files identically.
#pragma once

#include <algorithm>
#include <cstdint>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>

#include "dlnb/json.hpp"

namespace dlnb {

struct ModelStats {
  std::string name;  // e.g. "llama3_8b_16_bfloat16"
  std::int64_t forward_flops = 0;
  std::int64_t backward_flops = 0;
  std::int64_t model_size = 0;  // parameter count (elements)
  double fwd_us = 0.0;
  double bwd_us = 0.0;
  std::int64_t batch_size = 0;
  std::int64_t seq_len = 0;
  std::int64_t embed_dim = 0;
  std::string dtype;
  std::int64_t non_expert_size = 0;
  double ffn_fwd_us = 0.0;
  double ffn_bwd_us = 0.0;
  std::int64_t experts = 1;
  std::string device = "unknown";
  double bytes_per_element = 2.0;
  // backward-aware step roofline (core/roofline.py train_step_time_s);
  // 0 in files predating r4
  double step_us = 0.0;

  std::int64_t model_bytes() const {
    return static_cast<std::int64_t>(model_size * bytes_per_element);
  }
};

struct ModelCard {
  std::string name;
  std::int64_t embed_dim = 0;
  std::int64_t num_heads = 0;
  std::int64_t num_kv_heads = 0;  // 0 -> num_heads (MHA)
  std::int64_t ff_dim = 0;
  std::int64_t seq_len = 0;
  std::int64_t num_encoder_blocks = 0;
  std::int64_t num_decoder_blocks = 0;
  std::int64_t vocab_size = 0;
  bool gated_mlp = false;
  std::int64_t num_experts = 1;
  std::int64_t top_k = 1;

  std::int64_t num_layers() const {
    // reference count_layers sums encoder+decoder blocks (utils.hpp:279-294)
    return num_encoder_blocks + num_decoder_blocks;
  }
  std::int64_t kv_dim() const {
    std::int64_t kvh = num_kv_heads > 0 ? num_kv_heads : num_heads;
    return num_heads > 0 ? embed_dim / num_heads * kvh : embed_dim;
  }
};

namespace detail {
inline std::string lower(std::string s) {
  std::transform(s.begin(), s.end(), s.begin(),
                 [](unsigned char c) { return std::tolower(c); });
  return s;
}
inline std::string strip(const std::string& s) {
  auto b = s.find_first_not_of(" \t\r\n");
  if (b == std::string::npos) return "";
  auto e = s.find_last_not_of(" \t\r\n");
  return s.substr(b, e - b + 1);
}
}  // namespace detail

// Parse the flat `Key:value` stat-file text (keyed; tolerates reordered or
// case-drifted lines, unlike reference utils.hpp:211-253).
inline ModelStats parse_model_stats(const std::string& text,
                                    const std::string& name) {
  ModelStats st;
  st.name = name;
  bool have_fwd = false, have_bwd = false, have_size = false;
  std::istringstream is(text);
  std::string line;
  while (std::getline(is, line)) {
    line = detail::strip(line);
    if (line.empty() || line[0] == '#') continue;
    auto colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = detail::lower(detail::strip(line.substr(0, colon)));
    std::string val = detail::strip(line.substr(colon + 1));
    try {
      if (key == "forward_flops") st.forward_flops = std::stoll(val);
      else if (key == "backward_flops") st.backward_flops = std::stoll(val);
      else if (key == "model_size") { st.model_size = std::stoll(val); have_size = true; }
      else if (key == "non_expert_size") st.non_expert_size = std::stoll(val);
      else if (key == "average_forward_time (us)") { st.fwd_us = std::stod(val); have_fwd = true; }
      else if (key == "average_backward_time (us)") { st.bwd_us = std::stod(val); have_bwd = true; }
      else if (key == "batch_size") st.batch_size = std::stoll(val);
      else if (key == "ffn_average_forward_time (us)") st.ffn_fwd_us = std::stod(val);
      else if (key == "ffn_average_backward_time (us)") st.ffn_bwd_us = std::stod(val);
      else if (key == "experts") st.experts = std::stoll(val);
      else if (key == "seq_len") st.seq_len = std::stoll(val);
      else if (key == "embedded_dim" || key == "embed_dim") st.embed_dim = std::stoll(val);
      else if (key == "device") st.device = val;
      else if (key == "dtype") st.dtype = val;
      else if (key == "bytes_per_element") st.bytes_per_element = std::stod(val);
      else if (key == "train_step_time (us)") st.step_us = std::stod(val);
      // unknown keys ignored: files may grow fields
    } catch (const std::exception&) {
      throw std::runtime_error("stats '" + name + "': bad value for key '" +
                               key + "': '" + val + "'");
    }
  }
  if (!have_size || !have_fwd || !have_bwd)
    throw std::runtime_error("stats '" + name +
                             "': missing required field(s) "
                             "(Model_Size / forward / backward time)");
  return st;
}

inline ModelStats load_model_stats(const std::string& path,
                                   const std::string& name = "") {
  std::ifstream f(path);
  if (!f) throw std::runtime_error("cannot open stats file: " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  std::string n = name;
  if (n.empty()) {
    auto slash = path.find_last_of('/');
    n = slash == std::string::npos ? path : path.substr(slash + 1);
    auto dot = n.rfind(".txt");
    if (dot != std::string::npos) n = n.substr(0, dot);
  }
  return parse_model_stats(ss.str(), n);
}

// Architecture-card JSON (same schema as dlnetbench_tpu/data/models/*.json
// and the reference's models/*.json).
inline ModelCard parse_model_card(const Json& j, const std::string& name) {
  ModelCard c;
  c.name = name;
  auto geti = [&](const char* key, std::int64_t dflt) -> std::int64_t {
    return j.contains(key) ? j.at(key).as_int() : dflt;
  };
  c.embed_dim = geti("embed_dim", 0);
  c.num_heads = geti("num_heads", 0);
  c.num_kv_heads = geti("num_kv_heads", 0);
  c.ff_dim = geti("ff_dim", 0);
  c.seq_len = geti("seq_len", 0);
  c.num_encoder_blocks = geti("num_encoder_blocks", 0);
  c.num_decoder_blocks = geti("num_decoder_blocks", 0);
  c.vocab_size = geti("vocab_size", 0);
  if (j.contains("gated_mlp")) c.gated_mlp = j.at("gated_mlp").as_bool();
  if (j.contains("moe_params")) {
    const Json& m = j.at("moe_params");
    c.num_experts = m.contains("num_experts") ? m.at("num_experts").as_int() : 1;
    c.top_k = m.contains("num_experts_per_tok")
                  ? m.at("num_experts_per_tok").as_int() : 1;
  }
  return c;
}

inline ModelCard load_model_card(const std::string& path,
                                 const std::string& name = "") {
  std::ifstream f(path);
  if (!f) throw std::runtime_error("cannot open model card: " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  std::string n = name;
  if (n.empty()) {
    auto slash = path.find_last_of('/');
    n = slash == std::string::npos ? path : path.substr(slash + 1);
    auto dot = n.rfind(".json");
    if (dot != std::string::npos) n = n.substr(0, dot);
  }
  return parse_model_card(Json::parse(ss.str()), n);
}

// "llama3_8b_16_bfloat16" -> "llama3_8b" (strip batch + dtype suffixes,
// reference hybrid_2d.cpp:214-216 semantics, keyed on the last two '_').
inline std::string arch_name_from_stats_name(const std::string& stats_name) {
  auto p1 = stats_name.find_last_of('_');
  if (p1 == std::string::npos) return stats_name;
  auto p2 = stats_name.find_last_of('_', p1 - 1);
  if (p2 == std::string::npos) return stats_name;
  return stats_name.substr(0, p2);
}

}  // namespace dlnb
