// StableHLO program generation for TPU collectives.
//
// On TPU there is no NCCL-style imperative collective API: the native
// backend compiles one tiny XLA (StableHLO) module per (collective, dtype,
// shape, group layout) and replays it (SURVEY.md §5.8 — "that compilation
// cache is a genuinely new architectural element with no reference
// counterpart").  This header is the pure text-generation half: replica-
// mode modules (mhlo.num_replicas = N) whose semantics were validated
// op-by-op against the XLA CPU runtime (tests/test_pjrt_programs.py
// compiles and executes every generated program on a multi-device CPU
// client and checks the math).
#pragma once

#include <cstdint>
#include <sstream>
#include <string>
#include <vector>

#include "dlnb/tensor.hpp"

namespace dlnb {

enum class CollOp {
  AllReduce,
  AllGather,
  ReduceScatter,
  AllToAll,
  CollectivePermute,
};

inline const char* coll_op_name(CollOp op) {
  switch (op) {
    case CollOp::AllReduce: return "all_reduce";
    case CollOp::AllGather: return "all_gather";
    case CollOp::ReduceScatter: return "reduce_scatter";
    case CollOp::AllToAll: return "all_to_all";
    case CollOp::CollectivePermute: return "collective_permute";
  }
  return "?";
}

inline const char* mlir_dtype(DType d) {
  switch (d) {
    case DType::F32: return "f32";
    case DType::BF16: return "bf16";
    case DType::F8E4M3: return "f8E4M3FN";
  }
  return "f32";
}

struct CollectiveProgram {
  CollOp op;
  DType dtype = DType::F32;
  std::int64_t in_count = 0;   // per-replica input elements
  int num_replicas = 1;
  // replica groups (each inner vector = one group of replica ids); empty
  // means one group of all replicas
  std::vector<std::vector<int>> groups;
  // for CollectivePermute only: (source, target) replica pairs
  std::vector<std::pair<int, int>> pairs;

  int group_size() const {
    return groups.empty() ? num_replicas : static_cast<int>(groups[0].size());
  }
  std::int64_t out_count() const {
    switch (op) {
      case CollOp::AllGather: return in_count * group_size();
      case CollOp::ReduceScatter: return in_count / group_size();
      default: return in_count;
    }
  }

  // Stable identity for the executable cache.
  std::string cache_key() const {
    std::ostringstream os;
    os << coll_op_name(op) << "/" << mlir_dtype(dtype) << "/" << in_count
       << "/r" << num_replicas << "/g";
    for (const auto& g : groups) {
      for (int r : g) os << r << ",";
      os << ";";
    }
    os << "/p";
    for (const auto& [s, t] : pairs) os << s << ">" << t << ";";
    return os.str();
  }
};

namespace detail {

inline std::string replica_groups_attr(const CollectiveProgram& p) {
  std::vector<std::vector<int>> groups = p.groups;
  if (groups.empty()) {
    groups.emplace_back();
    for (int r = 0; r < p.num_replicas; ++r) groups[0].push_back(r);
  }
  std::ostringstream os;
  os << "dense<[";
  for (std::size_t g = 0; g < groups.size(); ++g) {
    os << (g ? ", [" : "[");
    for (std::size_t i = 0; i < groups[g].size(); ++i)
      os << (i ? ", " : "") << groups[g][i];
    os << "]";
  }
  os << "]> : tensor<" << groups.size() << "x" << groups[0].size() << "xi64>";
  return os.str();
}

inline std::string sum_body(const std::string& et) {
  std::ostringstream os;
  os << " ({\n"
     << "    ^bb0(%a: tensor<" << et << ">, %b: tensor<" << et << ">):\n"
     << "      %s = stablehlo.add %a, %b : tensor<" << et << ">\n"
     << "      stablehlo.return %s : tensor<" << et << ">\n"
     << "  })";
  return os.str();
}

}  // namespace detail

// Generate the full replica-mode module text for one collective.
inline std::string generate_stablehlo(const CollectiveProgram& p) {
  const std::string et = mlir_dtype(p.dtype);
  const std::string in_t =
      "tensor<" + std::to_string(p.in_count) + "x" + et + ">";
  const std::string out_t =
      "tensor<" + std::to_string(p.out_count()) + "x" + et + ">";
  const std::string sig = "(" + in_t + ") -> " + out_t;

  std::ostringstream body;
  switch (p.op) {
    case CollOp::AllReduce:
      body << "%0 = \"stablehlo.all_reduce\"(%arg0) <{replica_groups = "
           << detail::replica_groups_attr(p) << "}>"
           << detail::sum_body(et) << " : " << sig;
      break;
    case CollOp::AllGather:
      body << "%0 = \"stablehlo.all_gather\"(%arg0) <{all_gather_dim = 0 : "
              "i64, replica_groups = "
           << detail::replica_groups_attr(p) << "}> : " << sig;
      break;
    case CollOp::ReduceScatter:
      body << "%0 = \"stablehlo.reduce_scatter\"(%arg0) <{scatter_dimension "
              "= 0 : i64, replica_groups = "
           << detail::replica_groups_attr(p) << "}>"
           << detail::sum_body(et) << " : " << sig;
      break;
    case CollOp::AllToAll:
      body << "%0 = \"stablehlo.all_to_all\"(%arg0) <{split_dimension = 0 : "
              "i64, concat_dimension = 0 : i64, split_count = "
           << p.group_size()
           << " : i64, replica_groups = " << detail::replica_groups_attr(p)
           << "}> : " << sig;
      break;
    case CollOp::CollectivePermute: {
      std::ostringstream pairs;
      pairs << "dense<[";
      for (std::size_t i = 0; i < p.pairs.size(); ++i)
        pairs << (i ? ", [" : "[") << p.pairs[i].first << ", "
              << p.pairs[i].second << "]";
      pairs << "]> : tensor<" << p.pairs.size() << "x2xi64>";
      body << "%0 = \"stablehlo.collective_permute\"(%arg0) "
              "<{source_target_pairs = "
           << pairs.str() << "}> : " << sig;
      break;
    }
  }

  std::ostringstream os;
  os << "module @dlnb_" << coll_op_name(p.op) << " attributes "
     << "{mhlo.num_replicas = " << p.num_replicas
     << " : i32, mhlo.num_partitions = 1 : i32} {\n"
     << "  func.func public @main(%arg0: " << in_t << ") -> " << out_t
     << " {\n"
     << "    " << body.str() << "\n"
     << "    return %0 : " << out_t << "\n"
     << "  }\n"
     << "}\n";
  return os.str();
}

// Generate the single-device compute-burn module: a dynamic-trip-count
// while loop advancing `state <- tanh(state @ state / width)` — the same
// chained-matmul burn the JAX tier calibrates (dlnetbench_tpu/proxies/
// burn.py): strictly sequential (each iteration consumes the previous
// state) so XLA can neither shrink nor parallelize it, values bounded by
// tanh.  Signature: (iters: tensor<i32>, state: tensor<WxWxf32>) ->
// tensor<WxWxf32>; the runtime trip count means ONE cached executable
// serves every microsecond budget.
inline std::string generate_burn_stablehlo(int width = 256) {
  const std::string mat = "tensor<" + std::to_string(width) + "x" +
                          std::to_string(width) + "xf32>";
  std::ostringstream os;
  os << "module @dlnb_burn attributes {mhlo.num_replicas = 1 : i32, "
        "mhlo.num_partitions = 1 : i32} {\n"
     << "  func.func public @main(%arg0: tensor<i32>, %arg1: " << mat
     << ") -> " << mat << " {\n"
     << "    %c0 = stablehlo.constant dense<0> : tensor<i32>\n"
     << "    %c1 = stablehlo.constant dense<1> : tensor<i32>\n"
     << "    %scale = stablehlo.constant dense<"
     << (1.0 / static_cast<double>(width)) << "> : " << mat << "\n"
     << "    %r:2 = stablehlo.while(%i = %c0, %x = %arg1) : tensor<i32>, "
     << mat << "\n"
     << "     cond {\n"
     << "      %cmp = stablehlo.compare  LT, %i, %arg0 : (tensor<i32>, "
        "tensor<i32>) -> tensor<i1>\n"
     << "      stablehlo.return %cmp : tensor<i1>\n"
     << "    } do {\n"
     << "      %d = stablehlo.dot_general %x, %x, contracting_dims = [1] "
        "x [0] : (" << mat << ", " << mat << ") -> " << mat << "\n"
     << "      %s = stablehlo.multiply %d, %scale : " << mat << "\n"
     << "      %t = stablehlo.tanh %s : " << mat << "\n"
     << "      %ip1 = stablehlo.add %i, %c1 : tensor<i32>\n"
     << "      stablehlo.return %ip1, %t : tensor<i32>, " << mat << "\n"
     << "    }\n"
     << "    return %r#1 : " << mat << "\n"
     << "  }\n"
     << "}\n";
  return os.str();
}

// Serialized xla CompileOptionsProto carrying {executable_build_options
// {num_replicas, num_partitions: 1, device_assignment?}} — the options
// blob PJRT_Client_Compile expects.  Hand-encoded protobuf wire format;
// field numbers from xla/pjrt/proto/compile_options.proto
// (executable_build_options = 3; num_replicas = 4, num_partitions = 5,
// device_assignment = 9) and xla_data.proto DeviceAssignmentProto
// (replica_count = 1, computation_count = 2, computation_devices = 3;
// ComputationDevice.replica_device_ids = 1).  A non-empty `device_ids`
// pins replica r to global device id device_ids[r] — the runtime
// equivalent of the reference's `-d 0,2,3` device-list selection
// (reference cpp/utils.hpp:62-71).
inline std::string compile_options_proto(
    int num_replicas, int num_partitions = 1,
    const std::vector<int>& device_ids = {}) {
  auto varint = [](std::uint64_t v) {
    std::string out;
    do {
      std::uint8_t b = v & 0x7F;
      v >>= 7;
      if (v) b |= 0x80;
      out.push_back(static_cast<char>(b));
    } while (v);
    return out;
  };
  auto length_delimited = [&](int field, const std::string& payload) {
    std::string out;
    out += static_cast<char>((field << 3) | 2);
    out += varint(payload.size());
    out += payload;
    return out;
  };
  std::string build_opts;
  build_opts += static_cast<char>((4 << 3) | 0);  // num_replicas, varint
  build_opts += varint(static_cast<std::uint64_t>(num_replicas));
  build_opts += static_cast<char>((5 << 3) | 0);  // num_partitions, varint
  build_opts += varint(static_cast<std::uint64_t>(num_partitions));
  if (!device_ids.empty()) {
    // repeated int64 replica_device_ids = 1 (packed)
    std::string ids;
    for (int id : device_ids) ids += varint(static_cast<std::uint64_t>(id));
    std::string computation_device = length_delimited(1, ids);
    std::string assignment;
    assignment += static_cast<char>((1 << 3) | 0);  // replica_count
    assignment += varint(static_cast<std::uint64_t>(num_replicas));
    assignment += static_cast<char>((2 << 3) | 0);  // computation_count
    assignment += varint(1);
    assignment += length_delimited(3, computation_device);
    build_opts += length_delimited(9, assignment);
  }
  return length_delimited(3, build_opts);
}

}  // namespace dlnb
