// Schedule algebra — pure math of the proxy workloads, native tier.
//
// Mirrors dlnetbench_tpu/core/schedule.py exactly (the Python tier is the
// executable spec; tests/test_native.py cross-checks the two).  Reference
// counterparts:
//   bucket split             reference cpp/data_parallel/dp.cpp:159-164
//   FSDP units/shards/grid   reference cpp/data_parallel/fsdp.cpp:217-265
//   2D pipe grid + messages  reference cpp/hybrid_parallel/hybrid_2d.cpp:236-276
//   3D grid + TP messages    reference cpp/hybrid_parallel/hybrid_3d.cpp:283-325
//   MoE A2A + two-level sync reference cpp/hybrid_parallel/hybrid_3d_moe.cpp:291-363
#pragma once

#include <algorithm>
#include <cstdint>
#include <map>
#include <set>
#include <stdexcept>
#include <string>
#include <vector>

#include "dlnb/model_data.hpp"

namespace dlnb {

using i64 = std::int64_t;

// ----------------------------------------------------------------- DP
// Near-equal split, remainder spread one-per-bucket from the front
// (reference dp.cpp:159-164 semantics).  sum(result) == total always.
inline std::vector<i64> split_buckets(i64 total, i64 num_buckets) {
  if (num_buckets <= 0) throw std::invalid_argument("num_buckets must be > 0");
  i64 base = total / num_buckets, rem = total % num_buckets;
  std::vector<i64> out(num_buckets);
  for (i64 i = 0; i < num_buckets; ++i) out[i] = base + (i < rem ? 1 : 0);
  return out;
}

struct DPSchedule {
  i64 num_buckets;
  std::vector<i64> bucket_sizes;  // elements per bucket
  double fwd_us;                  // whole-model forward compute
  double bwd_us_per_bucket;
  double bytes_per_element;

  std::vector<i64> bucket_bytes() const {
    std::vector<i64> out;
    out.reserve(bucket_sizes.size());
    for (i64 s : bucket_sizes)
      out.push_back(static_cast<i64>(s * bytes_per_element));
    return out;
  }
};

inline DPSchedule dp_schedule(const ModelStats& st, i64 num_buckets) {
  return DPSchedule{num_buckets, split_buckets(st.model_size, num_buckets),
                    st.fwd_us, st.bwd_us / num_buckets, st.bytes_per_element};
}

// ----------------------------------------------------------------- FSDP
struct FSDPSchedule {
  i64 num_units;
  i64 sharding_factor;
  i64 num_replicas;
  std::vector<i64> unit_sizes;  // full (unsharded) unit sizes, elements
  i64 shard_size;               // padded per-rank shard of one unit
  double fwd_us_per_unit;
  double bwd_us_per_unit;
  double bytes_per_element;

  i64 padded_unit_size() const { return shard_size * sharding_factor; }
};

// World = sharding_factor x num_replicas (reference fsdp.cpp:217,258);
// shard sizes padded so every rank holds an equal slice (fsdp.cpp:251-255).
inline FSDPSchedule fsdp_schedule(const ModelStats& st, i64 num_units,
                                  i64 world_size, i64 sharding_factor = 0) {
  i64 sf = sharding_factor > 0 ? sharding_factor : world_size;
  if (world_size % sf != 0)
    throw std::invalid_argument("world_size " + std::to_string(world_size) +
                                " not divisible by sharding_factor " +
                                std::to_string(sf));
  auto units = split_buckets(st.model_size, num_units);
  i64 max_unit = 0;
  for (i64 u : units) max_unit = std::max(max_unit, u);
  i64 shard = (max_unit + sf - 1) / sf;  // ceil
  return FSDPSchedule{num_units, sf, world_size / sf, units, shard,
                      st.fwd_us / num_units, st.bwd_us / num_units,
                      st.bytes_per_element};
}

// ----------------------------------------------------------------- grids
// 3D process grid, fastest-varying axis LAST (tp/ep): `tp_id = rank % tp;
// stage_id = (rank/tp) % pp; dp_id = rank/(tp*pp)` (hybrid_3d.cpp:283-285).
struct Grid3D {
  i64 dp, pp, tp;

  i64 world_size() const { return dp * pp * tp; }

  struct Coords { i64 dp_id, pp_id, tp_id; };
  Coords coords(i64 rank) const {
    return {rank / (tp * pp), (rank / tp) % pp, rank % tp};
  }
  i64 rank(i64 dp_id, i64 pp_id, i64 tp_id) const {
    return (dp_id * pp + pp_id) * tp + tp_id;
  }
  // Communicator "colors" — ranks sharing a color form one group
  // (reference hybrid_3d.cpp:287-300).
  i64 dp_color(i64 r) const { auto c = coords(r); return c.pp_id * tp + c.tp_id; }
  i64 pp_color(i64 r) const { auto c = coords(r); return c.dp_id * tp + c.tp_id; }
  i64 tp_color(i64 r) const { auto c = coords(r); return c.dp_id * pp + c.pp_id; }
};

// The hier fabric's balanced contiguous rank->process layout: process p
// hosts world/procs ranks, the first world%procs processes one extra
// (uneven locals).  DERIVED identically everywhere it is needed — the
// fabric (hier_fabric.hpp), the span stamping below, and tests — never
// exchanged on the wire.
inline i64 balanced_local(i64 world, i64 procs, i64 p) {
  return world / procs + (p < world % procs ? 1 : 0);
}

inline i64 balanced_start(i64 world, i64 procs, i64 p) {
  const i64 base = world / procs, rem = world % procs;
  return p * base + (p < rem ? p : rem);
}

inline i64 balanced_proc_of(i64 world, i64 procs, i64 rank) {
  for (i64 p = procs - 1; p >= 0; --p)
    if (rank >= balanced_start(world, procs, p)) return p;
  return 0;
}

// Max OS processes any single group of an axis split spans, under the
// hier fabric's balanced contiguous rank->process layout (above;
// handles uneven locals).  Stamped into comm-model components ("span")
// so the small-allreduce full-mesh busbw refusal
// (analysis/bandwidth.py) keys on the group's REAL DCN mesh width: a
// group contained in one process (span 1) never touches the DCN and
// must not be refused on the record-global process count (advisor r4).
// `color_of` maps world rank -> group color (Grid3D::*_color).
template <typename ColorFn>
inline i64 axis_span_procs(i64 world, i64 procs, ColorFn color_of) {
  if (procs <= 1 || world <= 0) return 1;
  std::map<i64, std::set<i64>> procs_by_color;
  for (i64 r = 0; r < world; ++r)
    procs_by_color[color_of(r)].insert(balanced_proc_of(world, procs, r));
  i64 mx = 1;
  for (const auto& kv : procs_by_color)
    mx = std::max<i64>(mx, static_cast<i64>(kv.second.size()));
  return mx;
}

// ----------------------------------------------------------------- PP(+TP)
struct PipelineSchedule {
  Grid3D grid;
  i64 num_microbatches;
  i64 layers_per_stage;
  i64 pipe_msg_elems;   // activations per microbatch hop
  i64 dp_sync_elems;    // per-stage gradient shard for DP allreduce
  i64 tp_msg_elems;     // per-microbatch TP allreduce (0 if tp==1)
  double fwd_us_per_stage_mb;
  double bwd_us_per_stage_mb;
  double bytes_per_element;

  i64 num_stages() const { return grid.pp; }
};

// Invariants from the reference: layers divisible by stages and batch by
// microbatches (hybrid_2d.cpp:264-265); pipe message = seq_len x embed_dim
// x samples-per-microbatch (hybrid_2d.cpp:244-247); DP allreduce =
// model/(num_stages*tp) (hybrid_2d.cpp:250, hybrid_3d.cpp:325); with TP the
// per-microbatch compute divides by tp and the TP message is pipe_msg/tp
// (hybrid_3d.cpp:314-315, 322).
inline PipelineSchedule pipeline_schedule(const ModelStats& st,
                                          const ModelCard& card,
                                          i64 num_stages, i64 num_microbatches,
                                          i64 dp = 1, i64 tp = 1) {
  if (card.num_layers() % num_stages != 0)
    throw std::invalid_argument(std::to_string(card.num_layers()) +
                                " layers not divisible by " +
                                std::to_string(num_stages) + " stages");
  if (st.batch_size % num_microbatches != 0)
    throw std::invalid_argument("batch " + std::to_string(st.batch_size) +
                                " not divisible by " +
                                std::to_string(num_microbatches) +
                                " microbatches");
  i64 samples_per_mb = st.batch_size / num_microbatches;
  i64 pipe_msg = st.seq_len * st.embed_dim * samples_per_mb;
  return PipelineSchedule{
      Grid3D{dp, num_stages, tp},
      num_microbatches,
      card.num_layers() / num_stages,
      pipe_msg,
      st.model_size / (num_stages * tp),
      tp > 1 ? pipe_msg / tp : 0,
      st.fwd_us / (num_stages * num_microbatches * tp),
      st.bwd_us / (num_stages * num_microbatches * tp),
      st.bytes_per_element};
}

// ------------------------------------------------- zero-bubble pipeline
// ZB-H1 per-stage op program (rebuild extension; the reference models
// only GPipe).  Same tick-synchronous greedy as the JAX tier
// (dlnetbench_tpu/core/schedule.py zb_tables): one unit op per stage per
// tick, priority B > F > W, cross-stage deps land strictly after the
// tick that produced them.  F = forward microbatch (hops up), B =
// input-grad half (hops down), W = local weight-grad half (no hop; fills
// the drain bubble).  Returns stage `s`'s ops in execution order — the
// blocking recv/async send discipline of the engine realizes the timing.
struct ZBOp {
  char kind;  // 'F' | 'B' | 'W'
  i64 mb;     // microbatch index
};

// Core greedy simulation; returns the makespan in ticks and, when
// `stage` >= 0, that stage's ops in execution order via `mine`.  When
// `tick_kinds` is given, appends one entry per tick: bit0 = some stage
// ran F, bit1 = some stage ran B or W (for the weighted unit makespan).
inline i64 zb_simulate(i64 num_stages, i64 num_microbatches, i64 stage,
                       std::vector<ZBOp>* mine,
                       std::vector<unsigned char>* tick_kinds = nullptr) {
  const i64 S = num_stages, M = num_microbatches;
  if (S <= 0 || M <= 0)
    throw std::invalid_argument("zb_ops: S and M must be positive");
  std::vector<std::vector<i64>> f_tick(S, std::vector<i64>(M, -1));
  std::vector<std::vector<i64>> b_tick(S, std::vector<i64>(M, -1));
  std::vector<i64> nf(S, 0), nb(S, 0), nw(S, 0);
  i64 t = 0;
  auto done = [&] {
    for (i64 s = 0; s < S; ++s)
      if (nw[s] < M) return false;
    return true;
  };
  while (!done()) {
    unsigned char kinds = 0;
    for (i64 s = 0; s < S; ++s) {
      i64 k = nb[s];
      if (k < nf[s] &&
          (s == S - 1 || (b_tick[s + 1][k] >= 0 && b_tick[s + 1][k] < t))) {
        b_tick[s][k] = t;
        ++nb[s];
        kinds |= 2;
        if (s == stage && mine) mine->push_back({'B', k});
        continue;
      }
      k = nf[s];
      if (k < M &&
          (s == 0 || (f_tick[s - 1][k] >= 0 && f_tick[s - 1][k] < t))) {
        f_tick[s][k] = t;
        ++nf[s];
        kinds |= 1;
        if (s == stage && mine) mine->push_back({'F', k});
        continue;
      }
      if (nw[s] < nb[s]) {
        ++nw[s];
        kinds |= 2;
        if (s == stage && mine) mine->push_back({'W', nw[s] - 1});
      }
    }
    if (tick_kinds) tick_kinds->push_back(kinds);
    if (++t > 4 * (M + S))
      throw std::runtime_error("zb_simulate failed to converge");
  }
  return t;
}

inline std::vector<ZBOp> zb_ops(i64 num_stages, i64 num_microbatches,
                                i64 stage) {
  std::vector<ZBOp> mine;
  zb_simulate(num_stages, num_microbatches, stage, &mine);
  return mine;
}

// Makespan of the greedy program in unit ticks (== the JAX tier's
// zb_tables(...).ticks; 3M + S - 1 when M >= S-ish, longer for tiny M).
inline i64 zb_ticks(i64 num_stages, i64 num_microbatches) {
  return zb_simulate(num_stages, num_microbatches, -1, nullptr);
}

// Weighted makespan in FORWARD units (== the JAX tier's zb_unit_ticks):
// F costs 1, B and W each cost half a backward (bwd_units / 2, DERIVED
// from the stats' bwd/fwd ratio rather than hardcoding the 2x
// convention); the engine is tick-synchronous, so each tick costs its
// largest resident op.  Equals zb_ticks when bwd_units == 2.
inline double zb_unit_ticks(i64 num_stages, i64 num_microbatches,
                            double bwd_units) {
  std::vector<unsigned char> kinds;
  zb_simulate(num_stages, num_microbatches, -1, nullptr, &kinds);
  const double half = bwd_units / 2.0;
  double total = 0.0;
  for (unsigned char k : kinds)
    total += std::max((k & 1) ? 1.0 : 0.0, (k & 2) ? half : 0.0);
  return total;
}

// ----------------------------------------------------------------- MoE/EP
struct MoESchedule {
  PipelineSchedule pipe;
  i64 num_expert_shards;
  i64 top_k;
  i64 a2a_elems;             // one all-to-all dispatch/combine message
  i64 a2a_per_direction;     // A2As per microbatch per direction
  i64 nonexpert_sync_elems;  // level-1 grad sync over the EP group
  i64 expert_sync_elems;     // level-2 expert-param stage shard over DP

  Grid3D grid() const {
    return Grid3D{pipe.grid.dp, pipe.grid.pp, num_expert_shards};
  }
};

// A2A message = tokens_per_microbatch x top_k x embed_dim /
// num_expert_shards (reference hybrid_3d_moe.cpp:354-359); two A2As per MoE
// layer per direction (:161-165); two-level grad sync sizes from
// non_expert_size (:278, 361-363).  Unlike TP, EP does not divide the
// per-microbatch compute or the pipe message (hybrid_3d_moe.cpp:339-347).
inline MoESchedule moe_schedule(const ModelStats& st, const ModelCard& card,
                                i64 num_stages, i64 num_microbatches,
                                i64 num_expert_shards, i64 dp = 1) {
  if (card.num_experts % num_expert_shards != 0)
    throw std::invalid_argument(std::to_string(card.num_experts) +
                                " experts not divisible by " +
                                std::to_string(num_expert_shards) + " shards");
  auto pipe = pipeline_schedule(st, card, num_stages, num_microbatches, dp, 1);
  i64 samples_per_mb = st.batch_size / num_microbatches;
  i64 tokens_per_mb = samples_per_mb * st.seq_len;
  i64 a2a = tokens_per_mb * card.top_k * st.embed_dim / num_expert_shards;
  i64 layers_per_stage = card.num_layers() / num_stages;
  i64 non_expert = st.non_expert_size;
  i64 expert_params = st.model_size - non_expert;
  return MoESchedule{pipe,
                     num_expert_shards,
                     card.top_k,
                     a2a,
                     2 * layers_per_stage,
                     non_expert / std::max<i64>(num_stages, 1),
                     expert_params / (num_stages * num_expert_shards)};
}

// ------------------------------------------------- sequence parallelism
// Rebuild extension (SURVEY.md §5.7): ring attention + Ulysses.
struct SequenceSchedule {
  i64 sp;
  i64 seq_per_rank;
  i64 kv_block_elems;  // ring: one K+V block exchanged per hop
  i64 a2a_elems;       // ulysses: one head<->seq reshard message
  i64 num_ring_hops;   // sp - 1 per attention layer
  double attn_us_per_block;
  // "ffn_stats" | "even_split_fallback" — which estimator produced
  // attn_us_per_block (emitted into the record, mirrors the JAX tier's
  // core/schedule.py SequenceSchedule.attn_time_source)
  std::string attn_time_source;
  i64 layers;
  double bytes_per_element;
};

inline SequenceSchedule sequence_schedule(const ModelStats& st,
                                          const ModelCard& card, i64 sp,
                                          i64 batch = 0) {
  if (card.seq_len % sp != 0)
    throw std::invalid_argument("seq_len " + std::to_string(card.seq_len) +
                                " not divisible by sp=" + std::to_string(sp));
  i64 b = batch > 0 ? batch : st.batch_size;
  i64 n_local = card.seq_len / sp;
  bool have_ffn = st.fwd_us > 0 && st.ffn_fwd_us > 0;
  double attn_frac = have_ffn ? 1.0 - st.ffn_fwd_us / st.fwd_us : 0.5;
  double attn_us = st.fwd_us * attn_frac /
                   std::max<i64>(card.num_layers(), 1) /
                   static_cast<double>(sp * sp);
  return SequenceSchedule{sp,
                          n_local,
                          2 * b * n_local * card.kv_dim(),
                          b * n_local * card.embed_dim,
                          sp - 1,
                          attn_us,
                          have_ffn ? "ffn_stats" : "even_split_fallback",
                          card.num_layers(),
                          st.bytes_per_element};
}

}  // namespace dlnb
