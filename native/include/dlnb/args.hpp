// Declarative CLI parsing — the easyargs equivalent.
//
// The reference's binaries declare a macro table of required/optional args
// before including ccutils/easyargs.hpp (reference
// cpp/data_parallel/dp.cpp:108-124).  The rebuild uses a small runtime
// registry instead of macros: same capability (required/optional
// string/int/double/bool flags, auto --help), no preprocessor tricks.
#pragma once

#include <cstdlib>
#include <iostream>
#include <stdexcept>
#include <map>
#include <sstream>
#include <string>
#include <vector>

namespace dlnb {

class Args {
 public:
  explicit Args(std::string prog_desc) : desc_(std::move(prog_desc)) {}

  Args& required_str(const std::string& name, const std::string& help) {
    specs_.push_back({name, Kind::Str, true, "", help});
    return *this;
  }
  Args& optional_str(const std::string& name, std::string dflt,
                     const std::string& help) {
    specs_.push_back({name, Kind::Str, false, std::move(dflt), help});
    return *this;
  }
  Args& required_int(const std::string& name, const std::string& help) {
    specs_.push_back({name, Kind::Int, true, "", help});
    return *this;
  }
  Args& optional_int(const std::string& name, long long dflt,
                     const std::string& help) {
    specs_.push_back({name, Kind::Int, false, std::to_string(dflt), help});
    return *this;
  }
  Args& optional_double(const std::string& name, double dflt,
                        const std::string& help) {
    std::ostringstream os;
    os << dflt;
    specs_.push_back({name, Kind::Double, false, os.str(), help});
    return *this;
  }
  Args& flag(const std::string& name, const std::string& help) {
    specs_.push_back({name, Kind::Flag, false, "0", help});
    return *this;
  }

  // Parse --name value / --name=value / bare --flag.  Exits with usage on
  // error or --help (the easyargs behavior).
  void parse(int argc, char** argv) {
    prog_ = argc > 0 ? argv[0] : "proxy";
    for (const auto& s : specs_)
      if (!s.required) values_[s.name] = s.dflt;
    for (int i = 1; i < argc; ++i) {
      std::string tok = argv[i];
      if (tok == "--help" || tok == "-h") usage_and_exit(0);
      if (tok.rfind("--", 0) != 0) die("unexpected positional '" + tok + "'");
      std::string name = tok.substr(2), val;
      auto eq = name.find('=');
      bool has_val = false;
      if (eq != std::string::npos) {
        val = name.substr(eq + 1);
        name = name.substr(0, eq);
        has_val = true;
      }
      const Spec* spec = find(name);
      if (!spec) die("unknown option --" + name);
      if (spec->kind == Kind::Flag) {
        values_[name] = has_val ? val : "1";
      } else {
        if (!has_val) {
          if (i + 1 >= argc) die("--" + name + " needs a value");
          val = argv[++i];
        }
        validate(*spec, val);
        values_[name] = val;
      }
    }
    for (const auto& s : specs_)
      if (s.required && values_.find(s.name) == values_.end())
        die("missing required --" + s.name);
  }

  std::string str(const std::string& name) const { return values_.at(name); }
  long long integer(const std::string& name) const {
    return std::stoll(values_.at(name));
  }
  double number(const std::string& name) const {
    return std::stod(values_.at(name));
  }
  bool flag_set(const std::string& name) const {
    const std::string& v = values_.at(name);
    return v == "1" || v == "true";
  }

 private:
  enum class Kind { Str, Int, Double, Flag };
  struct Spec {
    std::string name;
    Kind kind;
    bool required;
    std::string dflt;
    std::string help;
  };

  // numeric values are checked at parse time so a bad value dies with
  // usage instead of throwing from integer()/number() later
  void validate(const Spec& spec, const std::string& val) const {
    try {
      std::size_t used = 0;
      if (spec.kind == Kind::Int) {
        (void)std::stoll(val, &used);
      } else if (spec.kind == Kind::Double) {
        (void)std::stod(val, &used);
      } else {
        return;
      }
      if (used != val.size()) throw std::invalid_argument(val);
    } catch (const std::exception&) {
      die("--" + spec.name + " expects a " +
          (spec.kind == Kind::Int ? "integer" : "number") + ", got '" + val +
          "'");
    }
  }

  const Spec* find(const std::string& name) const {
    for (const auto& s : specs_)
      if (s.name == name) return &s;
    return nullptr;
  }

  [[noreturn]] void die(const std::string& msg) const {
    std::cerr << prog_ << ": " << msg << "\n";
    usage_and_exit(2);
  }

  [[noreturn]] void usage_and_exit(int code) const {
    std::ostream& os = code == 0 ? std::cout : std::cerr;
    os << desc_ << "\nusage: " << prog_;
    for (const auto& s : specs_)
      os << (s.required ? " --" + s.name + " <v>"
                        : " [--" + s.name +
                              (s.kind == Kind::Flag ? "]" : " <v>]"));
    os << "\n";
    for (const auto& s : specs_)
      os << "  --" << s.name << (s.required ? "  (required)  " : "  ")
         << s.help
         << (s.required || s.dflt.empty() ? "" : "  [default " + s.dflt + "]")
         << "\n";
    std::exit(code);
  }

  std::string desc_, prog_;
  std::vector<Spec> specs_;
  std::map<std::string, std::string> values_;
};

}  // namespace dlnb
