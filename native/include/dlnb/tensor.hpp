// Memory & dtype layer: DType, element conversion, RAII Tensor.
//
// Counterpart of the reference's compile-time `_FLOAT` selection
// (reference cpp/data_types.hpp:36-79) and `Tensor<T, Device>` RAII buffer
// (reference cpp/proxy_classes.hpp:349-444).  Differences by design:
//   * dtype is a RUNTIME value, not a build config — one binary serves
//     bfloat16 / float8 / float32, erasing the reference quirk where GPU
//     builds silently used 4-byte floats while telling NCCL bf16
//     (SURVEY.md §7.4).
//   * buffers are 64-byte aligned host memory, zero-initialized like the
//     reference's calloc path; the PJRT backend owns device (HBM) buffers
//     separately.
#pragma once

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>
#include <stdexcept>
#include <string>

namespace dlnb {

enum class DType { F32, BF16, F8E4M3 };

inline std::size_t dtype_bytes(DType d) {
  switch (d) {
    case DType::F32: return 4;
    case DType::BF16: return 2;
    case DType::F8E4M3: return 1;
  }
  return 4;
}

inline const char* dtype_name(DType d) {
  switch (d) {
    case DType::F32: return "float32";
    case DType::BF16: return "bfloat16";
    case DType::F8E4M3: return "float8";
  }
  return "?";
}

inline DType dtype_from_name(const std::string& s) {
  if (s == "bfloat16" || s == "bf16") return DType::BF16;
  if (s == "float8" || s == "fp8" || s == "f8e4m3") return DType::F8E4M3;
  if (s == "float32" || s == "f32" || s == "float") return DType::F32;
  throw std::invalid_argument("unknown dtype '" + s + "'");
}

// ---- element conversion (for real reduction math on narrow types) ------
inline float bf16_to_f32(std::uint16_t v) {
  std::uint32_t bits = static_cast<std::uint32_t>(v) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline std::uint16_t f32_to_bf16(float f) {
  std::uint32_t bits;
  std::memcpy(&bits, &f, 4);
  if (f != f)  // NaN: canonical quiet bf16 NaN, else rounding can make Inf
    return static_cast<std::uint16_t>((bits >> 16) | 0x0040);
  // round-to-nearest-even, the TPU convention
  std::uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
  return static_cast<std::uint16_t>((bits + rounding) >> 16);
}

inline float f8e4m3_to_f32(std::uint8_t v) {
  int sign = (v >> 7) & 1;
  int exp = (v >> 3) & 0xF;
  int man = v & 0x7;
  float mag;
  if (exp == 0) {
    mag = man / 8.0f / 64.0f;  // subnormal: man/2^3 * 2^-6
  } else if (exp == 0xF && man == 0x7) {
    mag = __builtin_nanf("");  // e4m3fn: only 0xff/0x7f is NaN
  } else {
    mag = (1.0f + man / 8.0f) * std::exp2f(static_cast<float>(exp - 7));
  }
  return sign ? -mag : mag;
}

inline std::uint8_t f32_to_f8e4m3(float f) {
  if (f != f) return 0x7F;
  std::uint8_t sign = f < 0 ? 0x80 : 0;
  float mag = f < 0 ? -f : f;
  if (mag == 0) return sign;
  // clamp to e4m3fn max (448)
  if (mag >= 448.0f) return sign | 0x7E;
  int exp;
  float frac = std::frexp(mag, &exp);  // mag = frac * 2^exp, frac in [0.5,1)
  int e = exp - 1 + 7;                 // biased exponent for 1.m form
  if (e <= 0) {
    // subnormal: value = man/8 * 2^-6
    int man = static_cast<int>(mag * 8.0f * 64.0f + 0.5f);
    if (man > 7) man = 7;
    return sign | static_cast<std::uint8_t>(man);
  }
  int man = static_cast<int>((frac * 2.0f - 1.0f) * 8.0f + 0.5f);
  if (man == 8) {
    man = 0;
    ++e;
    if (e > 0xF) return sign | 0x7E;
  }
  return sign | static_cast<std::uint8_t>(e << 3) |
         static_cast<std::uint8_t>(man);
}

inline float load_element(const void* buf, std::size_t i, DType d) {
  switch (d) {
    case DType::F32: return static_cast<const float*>(buf)[i];
    case DType::BF16:
      return bf16_to_f32(static_cast<const std::uint16_t*>(buf)[i]);
    case DType::F8E4M3:
      return f8e4m3_to_f32(static_cast<const std::uint8_t*>(buf)[i]);
  }
  return 0;
}

inline void store_element(void* buf, std::size_t i, DType d, float v) {
  switch (d) {
    case DType::F32: static_cast<float*>(buf)[i] = v; break;
    case DType::BF16:
      static_cast<std::uint16_t*>(buf)[i] = f32_to_bf16(v);
      break;
    case DType::F8E4M3:
      static_cast<std::uint8_t*>(buf)[i] = f32_to_f8e4m3(v);
      break;
  }
}

// ---- Tensor -------------------------------------------------------------
// RAII zero-initialized buffer (reference Tensor<T,Device>,
// proxy_classes.hpp:381-444).  Host-side; 64-byte aligned for vectorized
// reduction loops.
class Tensor {
 public:
  Tensor() = default;
  Tensor(std::int64_t count, DType dtype) : count_(count), dtype_(dtype) {
    if (count < 0) throw std::invalid_argument("negative tensor size");
    bytes_ = static_cast<std::size_t>(count) * dtype_bytes(dtype);
    if (bytes_ > 0) {
      data_ = std::aligned_alloc(64, (bytes_ + 63) / 64 * 64);
      if (!data_) throw std::bad_alloc();
      std::memset(data_, 0, bytes_);
    }
  }
  Tensor(Tensor&& o) noexcept { swap(o); }
  Tensor& operator=(Tensor&& o) noexcept {
    if (this != &o) {
      release();
      swap(o);
    }
    return *this;
  }
  Tensor(const Tensor&) = delete;
  Tensor& operator=(const Tensor&) = delete;
  ~Tensor() { release(); }

  void* data() { return data_; }
  const void* data() const { return data_; }
  std::int64_t count() const { return count_; }
  std::size_t bytes() const { return bytes_; }
  DType dtype() const { return dtype_; }

  float get(std::size_t i) const { return load_element(data_, i, dtype_); }
  void set(std::size_t i, float v) { store_element(data_, i, dtype_, v); }
  void fill(float v) {
    for (std::int64_t i = 0; i < count_; ++i)
      store_element(data_, static_cast<std::size_t>(i), dtype_, v);
  }

 private:
  void release() {
    std::free(data_);
    data_ = nullptr;
    count_ = 0;
    bytes_ = 0;
  }
  void swap(Tensor& o) {
    std::swap(data_, o.data_);
    std::swap(count_, o.count_);
    std::swap(bytes_, o.bytes_);
    std::swap(dtype_, o.dtype_);
  }

  void* data_ = nullptr;
  std::int64_t count_ = 0;
  std::size_t bytes_ = 0;
  DType dtype_ = DType::F32;
};

}  // namespace dlnb
