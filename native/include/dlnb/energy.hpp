// Host-side energy sampling for the native tier — the reference's
// power_profiler role.
//
// The reference optionally links a vendor power profiler into its native
// proxies (-DPROXY_ENERGY_PROFILING -lpower_profiler, reference
// Makefile.flags.mk:119-124) sampling at POWER_SAMPLING_RATE_MS 5
// (dp.cpp:67), and its parser ingests per-rank `energy_consumed` arrays
// (plots/parser.py:172) feeding the runtime-energy Pareto analysis.
//
// This is the C++ port of the rebuild's Python sampling chain
// (dlnetbench_tpu/metrics/energy.py — selection and wraparound logic kept
// identical so both tiers attribute energy the same way):
//
//   * RAPL   — Linux cumulative counters
//              (/sys/class/powercap/intel-rapl:*/energy_uj), top-level
//              zones only (subzones are included in their parent), psys
//              preferred over summed packages, wraparound-safe via
//              max_energy_range_uj.
//   * hwmon  — /sys/class/hwmon/*/power*_input (uW) from ONE device
//              (DLNB_HWMON_DEVICE selects by name substring; otherwise
//              CPU-package-like names are preferred over the
//              alphabetically-first device, which could be a battery or
//              NVMe sensor), integrated by a 5 ms background thread.
//   * none   — energy is absent from the record, as when the reference
//              is built without the profiler.
//
// Scope: energy is a HOST counter, so exactly one rank per process — the
// process's first local rank, set by proxy_runner — brackets its runs
// and records the per-run joule deltas; records stamp
// `energy_scope: "process"`.  In one-rank-per-process fabrics (tcp,
// hier) this reproduces the reference's per-rank channel exactly.
//
// Roots are overridable (DLNB_RAPL_ROOT / DLNB_HWMON_ROOT) so tests can
// point the chain at a fake sysfs tree on rigs with no counters.
#pragma once

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdlib>
#include <filesystem>
#include <fstream>
#include <iostream>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace dlnb {
namespace energy {

constexpr int kSamplingRateMs = 5;  // reference dp.cpp:67

inline bool read_number(const std::filesystem::path& p, double& out) {
  std::ifstream f(p);
  return static_cast<bool>(f) && static_cast<bool>(f >> out);
}

inline std::string read_word(const std::filesystem::path& p) {
  std::ifstream f(p);
  std::string s;
  f >> s;
  return s;
}

// Cumulative joules from Linux RAPL package domains (energy.py:35-87).
class RaplReader {
 public:
  explicit RaplReader(const std::string& root) {
    namespace fs = std::filesystem;
    std::error_code ec;
    std::vector<fs::path> zones;
    for (fs::directory_iterator it(root, ec), end; !ec && it != end; ++it) {
      std::string name = it->path().filename().string();
      // top-level zones only: intel-rapl:0, not intel-rapl:0:0
      if (name.rfind("intel-rapl:", 0) == 0 &&
          std::count(name.begin(), name.end(), ':') == 1)
        zones.push_back(it->path());
    }
    std::sort(zones.begin(), zones.end());
    std::vector<Domain> packages, psys;
    for (const auto& z : zones) {
      Domain d;
      if (!read_number(z / "energy_uj", d.last)) continue;
      d.path = (z / "energy_uj").string();
      if (!read_number(z / "max_energy_range_uj", d.range))
        d.range = 0.0;  // unknown range: drop wrapped samples
      // psys already contains the packages — never sum both
      (read_word(z / "name") == "psys" ? psys : packages).push_back(d);
    }
    domains_ = psys.empty() ? packages : psys;
  }

  bool available() const { return !domains_.empty(); }

  // Monotonic cumulative joules across domains (wraparound-safe).
  double read_joules() {
    for (auto& d : domains_) {
      double cur;
      if (!read_number(d.path, cur)) continue;
      double delta = cur - d.last;
      if (delta < 0) delta = d.range > 0 ? delta + d.range : 0.0;
      acc_ += delta;
      d.last = cur;
    }
    return acc_ / 1e6;
  }

 private:
  struct Domain {
    std::string path;
    double range = 0.0;
    double last = 0.0;
  };
  std::vector<Domain> domains_;
  double acc_ = 0.0;
};

// Integrate instantaneous hwmon power (uW) in a background thread at the
// reference's 5 ms period (energy.py:90-184).
class HwmonReader {
 public:
  explicit HwmonReader(const std::string& root) {
    namespace fs = std::filesystem;
    std::error_code ec;
    // channels from ONE device only — summing across devices
    // double-counts when aggregate and component sensors coexist
    std::vector<std::vector<std::string>> inputs_by_dev;
    std::vector<std::string> names;
    std::vector<fs::path> devdirs;
    for (fs::directory_iterator it(root, ec), end; !ec && it != end; ++it)
      if (it->path().filename().string().rfind("hwmon", 0) == 0)
        devdirs.push_back(it->path());
    std::sort(devdirs.begin(), devdirs.end());
    for (const auto& dd : devdirs) {
      std::vector<std::string> ins;
      for (fs::directory_iterator jt(dd, ec), end; !ec && jt != end; ++jt) {
        std::string f = jt->path().filename().string();
        double v;
        if (f.rfind("power", 0) == 0 &&
            f.size() > 6 && f.substr(f.size() - 6) == "_input" &&
            read_number(jt->path(), v))
          ins.push_back(jt->path().string());
      }
      if (ins.empty()) continue;
      inputs_by_dev.push_back(std::move(ins));
      std::string n = read_word(dd / "name");
      names.push_back(n.empty() ? dd.filename().string() : n);
    }
    int chosen = -1;
    const char* want = std::getenv("DLNB_HWMON_DEVICE");
    if (want && *want) {
      // explicit selection: no match means unavailable, never a silent
      // fallback to some other sensor
      for (std::size_t i = 0; i < names.size(); ++i)
        if (names[i].find(want) != std::string::npos) {
          chosen = static_cast<int>(i);
          break;
        }
      if (chosen < 0 && !names.empty())
        std::cerr << "[energy] DLNB_HWMON_DEVICE=" << want
                  << " matches no hwmon device; sampling disabled\n";
    } else {
      // prefer CPU-package-like sensors over battery/NVMe/wifi
      static const char* kPreferred[] = {"cpu", "package", "core", "soc",
                                         "rapl"};
      for (std::size_t i = 0; i < names.size() && chosen < 0; ++i) {
        std::string low = names[i];
        std::transform(low.begin(), low.end(), low.begin(), ::tolower);
        for (const char* p : kPreferred)
          if (low.find(p) != std::string::npos) {
            chosen = static_cast<int>(i);
            break;
          }
      }
      if (chosen < 0 && !names.empty()) chosen = 0;
    }
    if (chosen >= 0) {
      inputs_ = inputs_by_dev[static_cast<std::size_t>(chosen)];
      source_ = "hwmon:" + names[static_cast<std::size_t>(chosen)];
    }
  }

  ~HwmonReader() { stop(); }

  bool available() const { return !inputs_.empty(); }
  const std::string& source() const { return source_; }

  double read_joules() {
    ensure_running();
    std::lock_guard<std::mutex> lk(m_);
    return joules_;
  }

  // Stop the poller between measured phases; the next read restarts it.
  void stop() {
    stop_.store(true, std::memory_order_release);
    if (thread_.joinable()) thread_.join();
  }

 private:
  void ensure_running() {
    if (inputs_.empty()) return;
    std::lock_guard<std::mutex> lk(start_m_);
    if (thread_.joinable() && !stop_.load(std::memory_order_acquire)) return;
    if (thread_.joinable()) thread_.join();
    stop_.store(false, std::memory_order_release);
    thread_ = std::thread([this] { loop(); });
  }

  void loop() {
    auto prev = std::chrono::steady_clock::now();
    while (!stop_.load(std::memory_order_acquire)) {
      std::this_thread::sleep_for(
          std::chrono::milliseconds(kSamplingRateMs));
      auto now = std::chrono::steady_clock::now();
      double watts = 0.0;
      for (const auto& p : inputs_) {
        double uw;
        if (read_number(p, uw)) watts += uw / 1e6;
      }
      double dt = std::chrono::duration<double>(now - prev).count();
      {
        std::lock_guard<std::mutex> lk(m_);
        joules_ += watts * dt;
      }
      prev = now;
    }
  }

  std::vector<std::string> inputs_;
  std::string source_;
  double joules_ = 0.0;
  std::mutex m_;
  std::mutex start_m_;
  std::atomic<bool> stop_{false};
  std::thread thread_;
};

// Best available host energy source, one per process (energy.py
// detect_sampler role).  Thread-safe reads; `recording_rank` names the
// ONE global rank whose harness loop brackets runs (set by
// proxy_runner, -1 = disabled).
class Meter {
 public:
  static Meter& instance() {
    static Meter m;
    return m;
  }

  bool available() const { return kind_ != Kind::None; }

  std::string source() const {
    if (kind_ == Kind::Rapl) return "rapl";
    if (kind_ == Kind::Hwmon) return hwmon_->source();
    return "";
  }

  double read_joules() {
    std::lock_guard<std::mutex> lk(m_);
    if (kind_ == Kind::Rapl) return rapl_->read_joules();
    if (kind_ == Kind::Hwmon) return hwmon_->read_joules();
    return 0.0;
  }

  // Release background polling after a measured phase (restartable).
  void relax() {
    if (kind_ == Kind::Hwmon) hwmon_->stop();
  }

  std::atomic<int> recording_rank{-1};

 private:
  Meter() {
    const char* rr = std::getenv("DLNB_RAPL_ROOT");
    rapl_.reset(new RaplReader(rr && *rr ? rr : "/sys/class/powercap"));
    if (rapl_->available()) {
      kind_ = Kind::Rapl;
      return;
    }
    rapl_.reset();
    const char* hr = std::getenv("DLNB_HWMON_ROOT");
    hwmon_.reset(new HwmonReader(hr && *hr ? hr : "/sys/class/hwmon"));
    if (hwmon_->available())
      kind_ = Kind::Hwmon;
    else
      hwmon_.reset();
  }

  enum class Kind { None, Rapl, Hwmon };
  Kind kind_ = Kind::None;
  std::unique_ptr<RaplReader> rapl_;
  std::unique_ptr<HwmonReader> hwmon_;
  std::mutex m_;
};

}  // namespace energy
}  // namespace dlnb
