// Measurement harness: warmup -> estimate runs -> timed runs -> record.
//
// Reproduces the reference's skeleton (reference
// cpp/data_parallel/dp.cpp:234-264): barrier, warm-up loop (default 3),
// optional run-count estimation from warm-up times to hit a minimum total
// execution time (`-m`, reference cpp/utils.hpp:121-135 — intent kept,
// its divide-by-warmup-count bug fixed, SURVEY.md §7.4), timer reset,
// timed runs (default 5), and the infinite `PROXY_LOOP` congestor mode
// (dp.cpp:251-256).  Compute is simulated per the proxy schedule with a
// scaled sleep, the host-side analogue of the reference's `usleep`
// (dp.cpp:93) — the JAX tier replaces this with calibrated on-device burn
// kernels; the native PJRT backend can layer those in the same slot.
#pragma once

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstring>
#include <functional>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

#include "dlnb/communicator.hpp"
#include "dlnb/energy.hpp"
#include "dlnb/timers.hpp"

namespace dlnb {

struct HarnessConfig {
  int warmup = 3;              // reference dp.cpp:65
  int runs = 5;                // reference dp.cpp:66
  double min_exectime_s = 0;   // reference -m flag
  bool loop = false;           // reference PROXY_LOOP
  double time_scale = 1.0;     // shrink simulated compute for dev boxes
  double size_scale = 1.0;     // shrink buffers for dev boxes
};

// Simulated compute for `us` microseconds, pre-scaled by the harness
// time_scale (reference usleep(t), dp.cpp:93).
inline void burn_us(double us, double time_scale = 1.0) {
  double scaled = us * time_scale;
  if (scaled <= 0) return;
  std::this_thread::sleep_for(
      std::chrono::duration<double, std::micro>(scaled));
}

// Scale an element count for dev boxes, keeping it positive.
inline std::int64_t scale_count(std::int64_t count, double size_scale) {
  if (size_scale >= 1.0) return count;
  auto scaled = static_cast<std::int64_t>(count * size_scale);
  return scaled > 0 ? scaled : 1;
}

// Runs needed so total measured time reaches min_exectime, from the mean
// warm-up time excluding the first `skip` iterations (reference
// utils.hpp:121-135 semantics, corrected mean).
inline int estimate_runs(const std::vector<double>& warmup_us,
                         double min_exectime_s, int skip = 2) {
  std::vector<double> usable(
      warmup_us.begin() +
          std::min<std::size_t>(skip, warmup_us.empty() ? 0
                                                        : warmup_us.size() - 1),
      warmup_us.end());
  if (usable.empty()) return 1;
  double sum = 0;
  for (double t : usable) sum += t;
  double mean_s = sum / usable.size() / 1e6;
  if (mean_s <= 0) return 1;
  return std::max(1, static_cast<int>(std::ceil(min_exectime_s / mean_s)));
}

// Per-rank measurement driver.  `step(timers)` runs one full iteration of
// the proxy schedule, instrumenting its collectives into `timers`; the
// whole iteration is timed as "runtimes".  `sync_comm` is the world (or
// widest) communicator used for the startup barrier and the cross-rank
// agreement on the estimated run count (reference allreduces warm-up means
// and broadcasts rank 0's decision, utils.hpp:121-135).
struct RankRun {
  std::vector<double> warmup_us;
  int runs = 0;
};

inline RankRun run_measured(
    const HarnessConfig& cfg, ProxyCommunicator& sync_comm, TimerSet& timers,
    const std::function<void(TimerSet&)>& step) {
  RankRun out;
  sync_comm.Barrier();

  for (int w = 0; w < std::max(cfg.warmup, 1); ++w) {
    auto t0 = Clock::now();
    step(timers);
    out.warmup_us.push_back(us_since(t0));
  }

  out.runs = cfg.runs;
  if (cfg.min_exectime_s > 0) {
    // agree across ranks: allreduce the local estimate, take the mean
    int local = estimate_runs(out.warmup_us, cfg.min_exectime_s);
    float in = static_cast<float>(local), sum = 0;
    // dtype-independent 1-element agreement via p2p-free allreduce: use
    // a dedicated f32 side channel through the same rendezvous
    std::vector<float> tmp_in(1, in), tmp_out(1, 0);
    if (sync_comm.dtype() == DType::F32) {
      sync_comm.Allreduce(tmp_in.data(), tmp_out.data(), 1);
      sum = tmp_out[0];
    } else {
      // narrow dtypes round-trip small integers exactly (bf16 up to 256,
      // fp8 up to 16) — convert through the comm dtype honestly
      Tensor a(1, sync_comm.dtype()), b(1, sync_comm.dtype());
      a.set(0, in);
      sync_comm.Allreduce(a.data(), b.data(), 1);
      sum = b.get(0);
    }
    out.runs = std::max(1, static_cast<int>(
                               std::lround(sum / sync_comm.size())));
  }

  if (cfg.loop) {  // reference PROXY_LOOP congestor mode
    while (true) step(timers);
  }

  timers.clear();  // reference clears timer vectors pre-measurement

  // Per-run energy brackets (reference per-rank energy_consumed arrays,
  // plots/parser.py:172): energy is a HOST counter, so only the process's
  // designated rank records it — proxies pass the world communicator
  // here, whose rank() is the global rank proxy_runner designated.
  auto& meter = energy::Meter::instance();
  bool record_energy =
      meter.available() && meter.recording_rank.load() == sync_comm.rank();
  auto& ring = TelemetryRing::instance();
  for (int r = 0; r < out.runs; ++r) {
    double e0 = record_energy ? meter.read_joules() : 0.0;
    auto t0 = Clock::now();
    step(timers);
    double wall_us = us_since(t0);
    timers.record("runtimes", wall_us);
    if (record_energy)
      timers.record("energy_consumed",
                    std::max(0.0, meter.read_joules() - e0));
    // continuous telemetry (ISSUE 14): per-step flight ring, step
    // index in fault-plan units (warmup included) — the per-rank step
    // series analysis/critical_path.py merges into blame
    if (ring.enabled())
      ring.record(sync_comm.rank(), std::max(cfg.warmup, 1) + r,
                  wall_us);
  }
  if (record_energy) meter.relax();
  return out;
}

inline std::string local_hostname() {
  char buf[256] = {0};
  if (gethostname(buf, sizeof buf - 1) != 0) std::strcpy(buf, "localhost");
  return buf;
}

}  // namespace dlnb
