// Deterministic fault plans — provoking the failures the detection layer
// can only observe.
//
// The fabrics already DETECT failure (tcp_backend.hpp per-peer death
// tracking + transitive ring fail-fast, the `dying_` Bye suppression,
// SURVEY §5.3's missing watchdog rebuilt in utils/watchdog.py) — but
// until now nothing could *provoke* one on purpose, survive it, or price
// it.  A FaultPlan is a JSON-serializable schedule of fault events
// (shared schema with the Python tier's dlnetbench_tpu/faults/plan.py):
//
//   {"policy": "fail_fast" | "retry" | "shrink",
//    "events": [{"kind": "delay|jitter|drop|crash|partition|preempt|rejoin",
//                "ranks": [..], "iteration": K, "until": -1,
//                "magnitude_us": 20000, "rate": 0.05, "seed": 7,
//                "where": "step" | "collective",
//                "group": [..]  // partition: the ranks on THIS side
//               }, ...]}
//
// Elastic eviction (policy `shrink` required, like the python tier):
//   preempt — a scripted GRACEFUL drain: the victim sleeps its
//             magnitude_us grace window at the trigger, then idles out
//             of the run (no Bye-less death — the departure is
//             plan-known to every rank, so survivors pre-split their
//             degraded communicator like shrink does).
//   rejoin  — the evicted ranks return at `iteration`: fault::Session
//             re-splits everyone onto a pre-built FULL-world
//             communicator with a fresh comm id (the grow half of
//             shrink) and the record clears degraded_world.
//
// Injection points (all driven through the process-global Plan
// singleton, loaded from --fault / DLNB_FAULT_PLAN):
//   * on_step_begin(rank)    — harness step boundary: delay/jitter
//                              sleeps on target ranks inside the
//                              [iteration, until) window; crash targets
//                              throw RankFailure at their trigger.
//   * on_collective(rank)    — per-collective injected latency
//                              (events with where == "collective"),
//                              called by ShmCommunicator /
//                              HierCommunicator at collective entry.
//   * on_send(rank, dst)     — TCP frame-drop injection at the sender:
//                              a dropped transmission is retried with
//                              exponential backoff under policy
//                              "retry" (counts stamped into the
//                              record), or aborts the run under
//                              "fail_fast".  Also enforces partitions:
//                              sends across the partition boundary fail
//                              once the event triggers.
//
// Degradation policy on a detected rank death:
//   fail_fast — today's behavior: every survivor raises (the
//               transitive fail-fast path, now provokable on demand).
//   retry     — applies to drop events (bounded re-send with backoff);
//               a dead rank still fails fast.
//   shrink    — survivors regroup WITHOUT the dead rank(s) mid-run:
//               fault::Session pre-splits a survivor communicator
//               (a normal collective split while everyone is alive —
//               the plan is deterministic, so every rank knows who
//               dies), detects the death through the fabric's own
//               failure path, stamps detection/recovery wall time, and
//               re-runs the failed step on the survivor group.  The
//               record carries degraded_world; metrics.merge accepts
//               the shrunken rank set through its degraded pathway.
//
// Determinism: per-rank iteration counters + a splitmix64 RNG seeded
// from (seed, rank), so a plan replays identically across runs and
// across the two tiers' studies.
#pragma once

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "dlnb/json.hpp"

namespace dlnb {
namespace fault {

// A plan-triggered rank death.  Distinct from generic runtime errors so
// the policy layer can tell "this rank is the scripted victim" from
// "a collective failed under me" (the survivor-side signal).
struct RankFailure : std::runtime_error {
  RankFailure(int rank, long long iteration)
      : std::runtime_error("rank " + std::to_string(rank) +
                           " crashed by fault plan (iteration " +
                           std::to_string(iteration) + ")"),
        rank(rank),
        iteration(iteration) {}
  int rank;
  long long iteration;
};

enum class Kind { Delay, Jitter, Drop, Crash, Partition, Preempt, Rejoin };

inline const char* kind_name(Kind k) {
  switch (k) {
    case Kind::Delay: return "delay";
    case Kind::Jitter: return "jitter";
    case Kind::Drop: return "drop";
    case Kind::Crash: return "crash";
    case Kind::Partition: return "partition";
    case Kind::Preempt: return "preempt";
    case Kind::Rejoin: return "rejoin";
  }
  return "?";
}

inline Kind kind_from_name(const std::string& s) {
  if (s == "delay") return Kind::Delay;
  if (s == "jitter") return Kind::Jitter;
  if (s == "drop") return Kind::Drop;
  if (s == "crash") return Kind::Crash;
  if (s == "partition") return Kind::Partition;
  if (s == "preempt") return Kind::Preempt;
  if (s == "rejoin") return Kind::Rejoin;
  throw std::runtime_error("fault plan: unknown kind '" + s + "'");
}

struct Event {
  Kind kind = Kind::Delay;
  std::vector<int> ranks;      // target ranks (crash victims, stragglers,
                               // lossy senders); empty = every rank
  long long iteration = 0;     // first step index the event is live at
  long long until = -1;        // first step index it stops (-1 = never)
  double magnitude_us = 0.0;   // delay/jitter sleep; drop backoff base
  double rate = 0.0;           // drop probability per send
  std::uint64_t seed = 0;      // jitter/drop determinism
  std::string where = "step";  // "step" | "collective" (delay/jitter)
  std::vector<int> group;      // partition: ranks on the target's side

  bool targets(int rank) const {
    return ranks.empty() ||
           std::find(ranks.begin(), ranks.end(), rank) != ranks.end();
  }
  bool live_at(long long iter) const {
    return iter >= iteration && (until < 0 || iter < until);
  }
};

// splitmix64 — deterministic, seedable, no global state.
inline std::uint64_t splitmix64(std::uint64_t& s) {
  std::uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// Per-rank fault outcome, written by fault::Session (shrink path) and
// the drop injector; read by proxy_runner when assembling the record.
struct Report {
  std::atomic<long long> steps{0};
  std::atomic<double> detection_us{0.0};
  std::atomic<double> recovery_us{0.0};
  std::atomic<bool> shrunk{false};
  std::atomic<double> injected_delay_us{0.0};
  // elastic grow (preempt -> rejoin): did this rank reach the
  // full-world re-split, and what did its first rejoined step cost
  // (the grow-side recovery — waiting for the returning rank to
  // rendezvous on the fresh comm)?
  std::atomic<bool> rejoined{false};
  std::atomic<double> rejoin_us{0.0};
};

class Plan {
 public:
  static Plan& instance() {
    static Plan p;
    return p;
  }

  // Parse and install a plan for `world` ranks.  Empty text clears it.
  void load(const std::string& text, const std::string& policy, int world) {
    std::lock_guard<std::mutex> lk(m_);
    events_.clear();
    policy_ = policy.empty() ? "fail_fast" : policy;
    world_ = world;
    iters_ = std::vector<std::atomic<long long>>(world < 1 ? 1 : world);
    for (auto& it : iters_) it.store(0);
    reports_ = std::vector<Report>(world < 1 ? 1 : world);
    drops_.store(0);
    retries_.store(0);
    active_ = false;
    if (text.empty()) return;
    Json j = Json::parse(text);
    if (j.contains("policy") && policy.empty())
      policy_ = j.at("policy").as_string();
    if (!j.contains("events"))
      throw std::runtime_error("fault plan: missing 'events'");
    for (const auto& e : j.at("events").items()) {
      Event ev;
      ev.kind = kind_from_name(e.at("kind").as_string());
      if (e.contains("ranks"))
        for (const auto& r : e.at("ranks").items())
          ev.ranks.push_back(static_cast<int>(r.as_int()));
      if (e.contains("iteration")) ev.iteration = e.at("iteration").as_int();
      if (e.contains("until")) ev.until = e.at("until").as_int();
      if (e.contains("magnitude_us"))
        ev.magnitude_us = e.at("magnitude_us").as_double();
      if (e.contains("rate")) ev.rate = e.at("rate").as_double();
      if (e.contains("seed"))
        ev.seed = static_cast<std::uint64_t>(e.at("seed").as_int());
      if (e.contains("where")) ev.where = e.at("where").as_string();
      if (e.contains("group"))
        for (const auto& r : e.at("group").items())
          ev.group.push_back(static_cast<int>(r.as_int()));
      if (ev.kind == Kind::Drop && !(ev.rate > 0.0 && ev.rate < 1.0))
        throw std::runtime_error(
            "fault plan: drop rate must be in (0, 1) — rate 1 never "
            "delivers and would hang any policy");
      if (ev.kind == Kind::Partition && ev.group.empty())
        throw std::runtime_error(
            "fault plan: partition needs 'group' (the ranks on one side)");
      if (ev.kind == Kind::Preempt && ev.ranks.empty())
        throw std::runtime_error(
            "fault plan: preempt needs explicit 'ranks' (the evicted "
            "ranks must be plan-known on every tier)");
      events_.push_back(std::move(ev));
    }
    if (policy_ != "fail_fast" && policy_ != "retry" && policy_ != "shrink")
      throw std::runtime_error("fault plan: unknown policy '" + policy_ +
                               "' (fail_fast | retry | shrink)");
    {
      bool has_pre = false, has_rej = false;
      for (const auto& e : events_) {
        has_pre = has_pre || e.kind == Kind::Preempt;
        has_rej = has_rej || e.kind == Kind::Rejoin;
      }
      if ((has_pre || has_rej) && policy_ != "shrink")
        throw std::runtime_error(
            "fault plan: preempt/rejoin model elastic eviction and "
            "recovery — they need policy 'shrink' (an eviction under "
            "fail_fast is just a crash; script that instead)");
      if (has_rej && !has_pre)
        throw std::runtime_error(
            "fault plan: rejoin without a preempt — nobody left to "
            "return");
      for (const auto& r : events_) {
        if (r.kind != Kind::Rejoin) continue;
        for (const auto& p : events_) {
          if (p.kind != Kind::Preempt) continue;
          bool related = r.ranks.empty();
          for (int rr : r.ranks)
            for (int pp : p.ranks) related = related || rr == pp;
          if (related && r.iteration <= p.iteration)
            throw std::runtime_error(
                "fault plan: rejoin at iteration " +
                std::to_string(r.iteration) +
                " does not follow its preempt at " +
                std::to_string(p.iteration));
        }
      }
    }
    raw_ = j;
    active_ = !events_.empty();
  }

  bool active() const { return active_; }
  const std::string& policy() const { return policy_; }
  const Json& raw() const { return raw_; }
  std::uint64_t drops() const { return drops_.load(); }
  std::uint64_t retries() const { return retries_.load(); }
  Report& report(int rank) { return reports_.at(clamp_rank(rank)); }

  // Does the plan carry events that need a STEP-boundary driver
  // (fault::Session / fault::step_guard)?  Collective-scoped
  // delay/jitter and drop events ride the fabric hooks and apply to
  // every proxy; step-scoped events only fire where a proxy wired the
  // step hook — a proxy that did not must refuse such a plan instead
  // of stamping fault provenance onto an actually-clean run.
  bool has_step_events() const {
    for (const auto& e : events_) {
      if (e.kind == Kind::Crash || e.kind == Kind::Partition ||
          e.kind == Kind::Preempt || e.kind == Kind::Rejoin)
        return true;
      if ((e.kind == Kind::Delay || e.kind == Kind::Jitter) &&
          e.where == "step")
        return true;
    }
    return false;
  }

  // Ranks that a crash event will remove (the survivor split's color
  // key) — deterministic, known to every rank up front.
  std::vector<int> crash_victims() const {
    std::vector<int> out;
    for (const auto& e : events_)
      if (e.kind == Kind::Crash)
        for (int r : e.ranks)
          if (std::find(out.begin(), out.end(), r) == out.end())
            out.push_back(r);
    std::sort(out.begin(), out.end());
    return out;
  }

  std::vector<int> survivors() const {
    auto dead = crash_victims();
    std::vector<int> out;
    for (int r = 0; r < world_; ++r)
      if (std::find(dead.begin(), dead.end(), r) == dead.end())
        out.push_back(r);
    return out;
  }

  // ---- elastic eviction (preempt/rejoin) queries -------------------
  std::vector<int> preempt_victims() const {
    std::vector<int> out;
    for (const auto& e : events_)
      if (e.kind == Kind::Preempt)
        for (int r : e.ranks)
          if (std::find(out.begin(), out.end(), r) == out.end())
            out.push_back(r);
    std::sort(out.begin(), out.end());
    return out;
  }

  bool has_preempt() const { return !preempt_victims().empty(); }

  // first step index at which evicted ranks return (-1: never grows)
  long long rejoin_iteration() const {
    long long at = -1;
    for (const auto& e : events_)
      if (e.kind == Kind::Rejoin && (at < 0 || e.iteration < at))
        at = e.iteration;
    return at;
  }

  // Is `rank` out of the run at `iter` — inside a preempt window no
  // rejoin (or `until`) has closed yet?  Mirrors faults/plan.py.
  bool evicted(int rank, long long iter) const {
    for (const auto& e : events_) {
      if (e.kind != Kind::Preempt ||
          std::find(e.ranks.begin(), e.ranks.end(), rank) == e.ranks.end())
        continue;
      long long end = e.until;
      for (const auto& r : events_) {
        if (r.kind != Kind::Rejoin || r.iteration <= e.iteration) continue;
        if (!r.targets(rank)) continue;
        end = end < 0 ? r.iteration : std::min(end, r.iteration);
      }
      if (iter >= e.iteration && (end < 0 || iter < end)) return true;
    }
    return false;
  }

  bool any_evicted(long long iter) const {
    for (int r : preempt_victims())
      if (evicted(r, iter)) return true;
    return false;
  }

  // Survivor set of the elastic eviction window (crash victims are
  // gone forever, preempt victims only inside their window).
  std::vector<int> elastic_survivors() const {
    auto pre = preempt_victims();
    std::vector<int> out;
    for (int r : survivors())
      if (std::find(pre.begin(), pre.end(), r) == pre.end())
        out.push_back(r);
    return out;
  }

  // ---- step boundary: delay/jitter sleeps, crash throw -------------
  // Returns the injected sleep in microseconds (already slept).
  double on_step_begin(int rank) {
    if (!active_) return 0.0;
    long long iter = iters_.at(clamp_rank(rank)).fetch_add(1);
    report(rank).steps.store(iter + 1);
    double slept = 0.0;
    for (const auto& e : events_) {
      if (!e.targets(rank) || !e.live_at(iter)) continue;
      switch (e.kind) {
        case Kind::Delay:
          if (e.where == "step") slept += sleep_us(e.magnitude_us);
          break;
        case Kind::Jitter:
          if (e.where == "step")
            slept += sleep_us(jitter_draw(e, rank, iter));
          break;
        case Kind::Crash:
          if (iter == e.iteration) throw RankFailure(rank, iter);
          break;
        case Kind::Preempt:
          // the scripted graceful drain: the victim spends its grace
          // window at the eviction trigger (the SIGTERM-notice cost),
          // then fault::Session idles it out — no throw, no Bye-less
          // death; the departure is announced
          if (iter == e.iteration) slept += sleep_us(e.magnitude_us);
          break;
        case Kind::Drop:
        case Kind::Partition:
        case Kind::Rejoin:
          break;  // transport-layer / Session-driven events
      }
    }
    if (slept > 0) add_delay(rank, slept);
    return slept;
  }

  long long iteration_of(int rank) const {
    if (!active_) return 0;
    return iters_.at(clamp_rank(rank)).load();
  }

  // ---- collective entry: per-collective injected latency -----------
  void on_collective(int rank) {
    if (!active_) return;
    long long iter = iters_.at(clamp_rank(rank)).load();
    double slept = 0.0;
    for (const auto& e : events_) {
      if (e.where != "collective" || !e.targets(rank) || !e.live_at(iter))
        continue;
      if (e.kind == Kind::Delay)
        slept += sleep_us(e.magnitude_us);
      else if (e.kind == Kind::Jitter)
        slept += sleep_us(jitter_draw(e, rank, iter));
    }
    if (slept > 0) add_delay(rank, slept);
  }

  // ---- TCP sender: frame drop + backoff, partition enforcement -----
  // Called before each physical frame transmission.  A "dropped" send
  // never actually skips the write (that would desync the framing
  // protocol); it models the LOSS + RETRANSMIT cost: under `retry` the
  // sender backs off exponentially per consecutive loss and then
  // transmits (drops/retries counted into the record), under
  // `fail_fast` the first loss aborts the run.  Partition events make
  // sends across the boundary fail outright once triggered.
  void on_send(int rank, int dst) {
    if (!active_) return;
    long long iter = iters_.at(clamp_rank(rank)).load();
    for (const auto& e : events_) {
      if (!e.live_at(iter)) continue;
      if (e.kind == Kind::Partition) {
        bool src_in = std::find(e.group.begin(), e.group.end(), rank) !=
                      e.group.end();
        bool dst_in = std::find(e.group.begin(), e.group.end(), dst) !=
                      e.group.end();
        if (src_in != dst_in)
          throw std::runtime_error(
              "tcp: send failed (peer gone?) — fault plan partitioned "
              "rank " + std::to_string(rank) + " from rank " +
              std::to_string(dst));
      }
      if (e.kind != Kind::Drop || !e.targets(rank)) continue;
      int losses = 0;
      std::uint64_t s = e.seed ^ (0x517cc1b727220a95ULL *
                                  static_cast<std::uint64_t>(rank + 1)) ^
                        send_draws_.fetch_add(1);
      while (uniform(s) < e.rate) {
        ++losses;
        drops_.fetch_add(1);
        if (policy_ == "fail_fast")
          throw std::runtime_error(
              "injected frame drop (fault plan, policy fail_fast): rank " +
              std::to_string(rank) + " -> " + std::to_string(dst));
        retries_.fetch_add(1);
        // exponential backoff: base * 2^(losses-1), capped
        double backoff = e.magnitude_us > 0 ? e.magnitude_us : 100.0;
        double us = std::min(backoff * static_cast<double>(1ULL << std::min(
                                 losses - 1, 10)),
                             50'000.0);
        add_delay(rank, sleep_us(us));
      }
    }
  }

  // Is `rank` partitioned from `dst` at its current iteration?  (Used
  // by receive-side checks wanting symmetric failure.)
  bool partitioned(int rank, int dst) const {
    if (!active_) return false;
    long long iter = iters_.at(clamp_rank(rank)).load();
    for (const auto& e : events_) {
      if (e.kind != Kind::Partition || !e.live_at(iter)) continue;
      bool a = std::find(e.group.begin(), e.group.end(), rank) !=
               e.group.end();
      bool b = std::find(e.group.begin(), e.group.end(), dst) !=
               e.group.end();
      if (a != b) return true;
    }
    return false;
  }

  // Record stamps (proxy_runner): the plan itself plus run-wide
  // counters; per-rank detection/recovery ride the Report slots.
  void describe(Json& meta) const {
    if (!active_) return;
    meta["fault_plan"] = raw_;
    meta["fault_policy"] = policy_;
    meta["fault_drops"] = static_cast<std::int64_t>(drops_.load());
    meta["fault_retries"] = static_cast<std::int64_t>(retries_.load());
  }

 private:
  Plan() {
    // env fallback so layered launchers (pod_study's hier points) can
    // inject without threading a flag through every argv
    if (const char* e = std::getenv("DLNB_FAULT_PLAN"); e && *e) {
      const char* p = std::getenv("DLNB_FAULT_POLICY");
      const char* w = std::getenv("DLNB_FAULT_WORLD");
      load(e, p ? p : "", w ? std::atoi(w) : 1);
    }
  }

  std::size_t clamp_rank(int rank) const {
    if (rank < 0) return 0;
    std::size_t r = static_cast<std::size_t>(rank);
    return r < iters_.size() ? r : iters_.size() - 1;
  }

  static double sleep_us(double us) {
    if (us <= 0) return 0.0;
    std::this_thread::sleep_for(std::chrono::duration<double, std::micro>(us));
    return us;
  }

  static double uniform(std::uint64_t& s) {
    return static_cast<double>(splitmix64(s) >> 11) /
           static_cast<double>(1ULL << 53);
  }

  double jitter_draw(const Event& e, int rank, long long iter) const {
    std::uint64_t s = e.seed ^ (0x9e3779b97f4a7c15ULL *
                                static_cast<std::uint64_t>(rank + 1)) ^
                      static_cast<std::uint64_t>(iter);
    return e.magnitude_us * uniform(s);
  }

  void add_delay(int rank, double us) {
    auto& slot = report(rank).injected_delay_us;
    double cur = slot.load();
    while (!slot.compare_exchange_weak(cur, cur + us)) {
    }
  }

  mutable std::mutex m_;
  std::vector<Event> events_;
  std::string policy_ = "fail_fast";
  int world_ = 1;
  // written once at startup (load, before the fabric launches rank
  // threads), read by every hook: atomic so a late loader can never
  // race the hot-path check
  std::atomic<bool> active_{false};
  Json raw_;
  // parenthesized copy-init (NOT braces: atomics have no copy ctor for
  // an initializer_list) — one slot until load() sizes them to world
  std::vector<std::atomic<long long>> iters_ =
      std::vector<std::atomic<long long>>(1);
  std::vector<Report> reports_ = std::vector<Report>(1);
  std::atomic<std::uint64_t> drops_{0};
  std::atomic<std::uint64_t> retries_{0};
  std::atomic<std::uint64_t> send_draws_{0};
};

}  // namespace fault
}  // namespace dlnb
