// Abstract communicator — the API every native proxy programs against.
//
// Counterpart of the reference's pure-virtual `ProxyCommunicator`
// (reference cpp/proxy_classes.hpp:30-51): Allreduce / Iallreduce /
// Allgather / Iallgather / Reduce_Scatter_block / Alltoall / Barrier /
// send / recv / Isend / Irecv with the request/stream *index* discipline —
// `Wait(i)` completes whatever was issued on slot i, `WaitAll(n)` slots
// 0..n-1 (reference proxy_classes.hpp:42-43, stream-per-index NCCL
// semantics :143-147).
//
// Backends in the rebuild:
//   * ShmCommunicator (shm_backend.hpp) — in-process rank threads, the
//     testable fake (role of the reference's `mpi_cpu` build, SURVEY.md §4).
//   * PjrtCollectiveRunner (pjrt_backend.hpp) — XLA collectives over real
//     TPU devices through the PJRT C API; the "communicator" is a set of
//     replica groups and each op replays a cached compiled module
//     (SURVEY.md §5.8).
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

#include "dlnb/tensor.hpp"

namespace dlnb {

class ProxyCommunicator {
 public:
  virtual ~ProxyCommunicator() = default;

  virtual int rank() const = 0;
  virtual int size() const = 0;
  virtual std::string name() const = 0;
  virtual DType dtype() const = 0;

  // ---- blocking collectives (counts are elements of dtype()) ----
  virtual void Allreduce(const void* src, void* dst, std::int64_t count) = 0;
  // dst receives size() * count_per_rank elements, rank-major.
  virtual void Allgather(const void* src, void* dst,
                         std::int64_t count_per_rank) = 0;
  // src holds size() * count_per_rank elements; dst gets this rank's
  // reduced block (MPI_Reduce_scatter_block semantics).
  virtual void ReduceScatterBlock(const void* src, void* dst,
                                  std::int64_t count_per_rank) = 0;
  // classic square all-to-all: src/dst are size() blocks of count_per_rank.
  virtual void Alltoall(const void* src, void* dst,
                        std::int64_t count_per_rank) = 0;
  virtual void Barrier() = 0;

  // ---- point-to-point ----
  // `tag` disambiguates concurrent transfers between the same rank pair
  // (MPI-tag role).  Blocking ops default to tag 0; nonblocking ops with
  // tag < 0 derive the tag from their slot, which pairs naturally when
  // both sides use the same slot.  A send only matches a recv with the
  // same effective tag.
  virtual void Send(const void* src, std::int64_t count, int dst_rank,
                    int tag = 0) = 0;
  virtual void Recv(void* dst, std::int64_t count, int src_rank,
                    int tag = 0) = 0;

  // ---- nonblocking, slot-indexed ----
  virtual void Iallreduce(const void* src, void* dst, std::int64_t count,
                          int slot) = 0;
  virtual void Iallgather(const void* src, void* dst,
                          std::int64_t count_per_rank, int slot) = 0;
  virtual void Isend(const void* src, std::int64_t count, int dst_rank,
                     int slot, int tag = -1) = 0;
  virtual void Irecv(void* dst, std::int64_t count, int src_rank,
                     int slot, int tag = -1) = 0;
  virtual void Wait(int slot) = 0;
  virtual void WaitAll(int num_slots) = 0;

  // ---- ring rotation ----
  // Every group member simultaneously sends `src` to rank (rank+shift) mod
  // size and receives its predecessor's block into `dst` — the ppermute /
  // collective_permute idiom (ring attention's KV rotation).  Blocking.
  // Default: paired Isend/Irecv on slots 0 and 1 (reserved for the call's
  // duration); device backends override with a native collective_permute.
  virtual void RingShift(const void* src, void* dst, std::int64_t count,
                         int shift = 1) {
    int n = size(), me = rank();
    if (n <= 1 || shift % n == 0) {
      if (dst != src)
        std::memcpy(dst, src, static_cast<std::size_t>(count) *
                                  dtype_bytes(dtype()));
      return;
    }
    int to = (me + shift % n + n) % n;
    int from = (me - shift % n + 2 * n) % n;
    Isend(src, count, to, 0, kRingShiftTag);
    Irecv(dst, count, from, 1, kRingShiftTag);
    WaitAll(2);
  }

  virtual void finalize() {}

 protected:
  static constexpr int kRingShiftTag = 7001;
};

}  // namespace dlnb
