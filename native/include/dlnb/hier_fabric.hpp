// Hierarchical rank fabric: per-process PJRT devices (ICI role) composed
// with an inter-process TCP mesh (DCN role) — the native tier's
// multi-host DEVICE path.
//
// The reference's native tier goes multi-node by bootstrapping a vendor
// communicator over MPI ranks and running device-buffer collectives
// across nodes (reference cpp/data_parallel/dp.cpp:166-189: MPI_Init +
// ncclUniqueId broadcast -> ncclCommInitRank; cpp/proxy_classes.hpp:
// 136-253 drives NCCL on GPU memory).  On TPU the same composition is a
// two-level fabric, matching how real TPU pods are wired (ICI inside a
// slice, DCN between slices):
//
//   * each OS process owns a PjrtFabric over its LOCAL devices — every
//     local collective phase executes as one compiled XLA module on
//     device (PluginExecutor on real libtpu; HostExecutor in CI, same
//     CollectiveProgram semantics);
//   * processes are joined by the TcpFabric's bootstrap + full-mesh
//     sockets (tcp_backend.hpp, the ncclUniqueId role);
//   * a collective on a group spanning processes runs hierarchically:
//     intra-process collective on device -> ONE thread per (process,
//     group) combines the partials over TCP -> the result is scattered
//     back to every local member.  Groups contained in one process never
//     touch the wire.
//
// Per-op composition (G = group size, m = local members, P = processes
// hosting the group):
//   Allreduce        local AR (device) -> TCP AR of the m-way partial
//                    (count elements on the wire, the bandwidth-optimal
//                    two-level reduction) -> copy to members.
//   ReduceScatter    local AR of all G blocks -> TCP AR of the partial ->
//                    each member takes its block.  (DCN moves G blocks —
//                    an AR-based reduce-scatter; records stamp
//                    dcn_algo so bandwidth analyses can tell.)
//   Allgather /      local AG (device) -> TCP AG of the process's packed
//   Alltoall /       member blocks (padded to the group's max local
//   RingShift        membership so counts are uniform) -> reassemble in
//                    global group-rank order -> distribute.
//   Barrier          local barrier -> TCP barrier among the group's
//                    processes.
//   Send/Recv        local pairs ride the in-process mailbox; cross-
//                    process pairs ride a TCP frame tagged with both
//                    endpoints' group ranks (p2p_transport "host+tcp").
//
// Communicator splits are collective over the GLOBAL world: every local
// rank thread calls split, the local PjrtFabric split partitions the
// local devices, local colors are allgathered across processes over a
// control communicator, and every process derives the same global groups
// and the same TCP comm-id sequence (the MPI_Comm_split contract, as in
// tcp_backend.hpp's split).
//
// CLI: --backend pjrt --procs P --coordinator host:port --rank p
// (world stays the GLOBAL rank count; each process runs world/P rank
// threads over its own devices).  Records carry this process's ranks
// only; dlnetbench_tpu.metrics.merge reassembles the run.
#pragma once

#include <algorithm>
#include <condition_variable>
#include <cstring>
#include <functional>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <vector>

#include "dlnb/communicator.hpp"
#include "dlnb/fabric.hpp"
#include "dlnb/pjrt_fabric.hpp"
#include "dlnb/tcp_backend.hpp"
#include "dlnb/tensor.hpp"

namespace dlnb {
namespace hier {

// All local members of one group arrive with their (op, count, extra)
// and buffer pointers; the LAST arriver runs the DCN phase exactly once
// (it sees every member's src/dst/scratch and writes the results);
// everyone departs only after it finished.  Mismatched op/count/extra
// across the local members aborts — same contract as the shm and pjrt
// rendezvous.
class Rendezvous {
 public:
  explicit Rendezvous(int n) : n_(n), dsts_(n), scratch_(n) {}

  // The DCN phase consumes only dsts (local-phase results) and scratches
  // (gathered/reduced staging); member src buffers were already folded in
  // by the local device collective.
  using ExecFn = std::function<void(const std::vector<void*>&,
                                    const std::vector<void*>&)>;

  void collective(int midx, int op, std::int64_t count, std::int64_t extra,
                  void* dst, void* scratch, const ExecFn& exec) {
    std::unique_lock<std::mutex> lk(m_);
    std::uint64_t my_gen = gen_;
    dsts_[midx] = dst;
    scratch_[midx] = scratch;
    if (arrived_ == 0) {
      op_ = op;
      count_ = count;
      extra_ = extra;
    } else if (op_ != op || count_ != count || extra_ != extra) {
      mismatch_ = true;
    }
    if (++arrived_ == n_) {
      if (!mismatch_) {
        lk.unlock();
        try {
          exec(dsts_, scratch_);
        } catch (...) {
          lk.lock();
          error_ = std::current_exception();
          lk.unlock();
        }
        lk.lock();
      }
      exec_done_ = true;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] {
        return gen_ == my_gen && arrived_ == n_ && exec_done_;
      });
    }
    bool bad = mismatch_;
    std::exception_ptr err = error_;
    if (++departed_ == n_) {
      arrived_ = 0;
      departed_ = 0;
      mismatch_ = false;
      exec_done_ = false;
      error_ = nullptr;
      ++gen_;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] { return gen_ != my_gen; });
    }
    lk.unlock();
    if (bad)
      throw std::runtime_error(
          "hier collective mismatch: local members disagree on op/count");
    if (err) std::rethrow_exception(err);
  }

 private:
  int n_;
  std::mutex m_;
  std::condition_variable cv_;
  std::vector<void*> dsts_;
  std::vector<void*> scratch_;
  int arrived_ = 0;
  int departed_ = 0;
  bool exec_done_ = false;
  bool mismatch_ = false;
  int op_ = 0;
  std::int64_t count_ = 0;
  std::int64_t extra_ = 0;
  std::exception_ptr error_;
  std::uint64_t gen_ = 0;
};

// One split's shared state in THIS process: the global group map plus,
// per locally-hosted group, the rendezvous and (for spanning groups)
// the TCP communicator among the group's processes.
struct GroupSet {
  struct Info {
    std::vector<int> procs;                        // ascending proc ranks
    std::vector<std::vector<int>> members_by_proc; // parallel to procs
    int maxm = 0;                                  // max local membership
  };
  struct LocalGroup {
    std::vector<int> local_members;  // global ranks here, ascending
    std::unique_ptr<TcpCommunicator> tcp;  // null for single-proc groups
    std::vector<std::unique_ptr<Rendezvous>> rdv;  // [0 .. num_slots]
  };

  int world = 0, local = 0, nprocs = 1, my_proc = 0;
  std::vector<std::vector<int>> groups;  // global ranks, by color asc
  std::vector<int> group_of, grank_of;   // by global rank
  std::vector<Info> info;                // by group index
  std::vector<std::unique_ptr<LocalGroup>> local_groups;  // null if none

  int proc_of(int global_rank) const { return global_rank / local; }
};

}  // namespace hier

class HierFabric;

// Per-rank-thread view of one group: ProxyCommunicator over the
// two-level fabric.  `sub_` is this rank's communicator on the local
// device fabric (same color partition restricted to local ranks).
class HierCommunicator : public ProxyCommunicator {
 public:
  HierCommunicator(std::shared_ptr<hier::GroupSet> set,
                   std::unique_ptr<ProxyCommunicator> sub, int global_rank,
                   DType dtype, int num_slots, std::string name)
      : set_(std::move(set)),
        sub_(std::move(sub)),
        grk_(global_rank),
        dtype_(dtype),
        num_slots_(num_slots),
        name_(std::move(name)),
        workers_(num_slots) {
    gidx_ = set_->group_of[grk_];
    lg_ = set_->local_groups[gidx_].get();
    for (std::size_t k = 0; k < lg_->local_members.size(); ++k)
      if (lg_->local_members[k] == grk_) midx_ = static_cast<int>(k);
  }

  ~HierCommunicator() override {
    for (auto& w : workers_) w.stop();
  }

  int rank() const override { return set_->grank_of[grk_]; }
  int size() const override {
    return static_cast<int>(set_->groups[gidx_].size());
  }
  std::string name() const override { return name_; }
  DType dtype() const override { return dtype_; }

  void Allreduce(const void* src, void* dst, std::int64_t count) override {
    run_collective(num_slots_, pjrtfab::Op::Allreduce, count, 0, src, dst);
  }
  void Allgather(const void* src, void* dst, std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::Allgather, cpr, 0, src, dst);
  }
  void ReduceScatterBlock(const void* src, void* dst,
                          std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::ReduceScatterBlock, cpr, 0, src,
                   dst);
  }
  void Alltoall(const void* src, void* dst, std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::Alltoall, cpr, 0, src, dst);
  }
  void Barrier() override {
    run_collective(num_slots_, pjrtfab::Op::Barrier, 0, 0, nullptr, nullptr);
  }
  void RingShift(const void* src, void* dst, std::int64_t count,
                 int shift = 1) override {
    run_collective(num_slots_, pjrtfab::Op::RingShift, count, shift, src,
                   dst);
  }

  // ---- p2p: in-process mailbox or cross-process TCP frame ----
  void Send(const void* src, std::int64_t count, int dst_rank,
            int tag = 0) override {
    int dst_global = set_->groups[gidx_].at(dst_rank);
    if (set_->proc_of(dst_global) == set_->my_proc) {
      sub_->Send(src, count, local_index(dst_global), tag);
    } else {
      require_tcp("Send");
      lg_->tcp->Send(src, count, proc_index(set_->proc_of(dst_global)),
                     p2p_tag(rank(), dst_rank, tag));
    }
  }
  void Recv(void* dst, std::int64_t count, int src_rank,
            int tag = 0) override {
    int src_global = set_->groups[gidx_].at(src_rank);
    if (set_->proc_of(src_global) == set_->my_proc) {
      sub_->Recv(dst, count, local_index(src_global), tag);
    } else {
      require_tcp("Recv");
      lg_->tcp->Recv(dst, count, proc_index(set_->proc_of(src_global)),
                     p2p_tag(src_rank, rank(), tag));
    }
  }

  // ---- nonblocking, slot-indexed ----
  void Iallreduce(const void* src, void* dst, std::int64_t count,
                  int slot) override {
    enqueue(slot, [=] {
      run_collective(slot, pjrtfab::Op::Allreduce, count, 0, src, dst);
    });
  }
  void Iallgather(const void* src, void* dst, std::int64_t cpr,
                  int slot) override {
    enqueue(slot, [=] {
      run_collective(slot, pjrtfab::Op::Allgather, cpr, 0, src, dst);
    });
  }
  void Isend(const void* src, std::int64_t count, int dst_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] { Send(src, count, dst_rank, t); });
  }
  void Irecv(void* dst, std::int64_t count, int src_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] { Recv(dst, count, src_rank, t); });
  }
  void Wait(int slot) override {
    try {
      worker(slot).wait();
    } catch (...) {
      shm::quiesce(workers_);
      throw;
    }
  }
  void WaitAll(int num_slots) override {
    for (int i = 0; i < num_slots && i < num_slots_; ++i) {
      try {
        workers_[i].wait();
      } catch (...) {
        shm::quiesce(workers_);
        throw;
      }
    }
  }

 private:
  shm::SlotWorker& worker(int slot) {
    if (slot < 0 || slot >= num_slots_)
      throw std::out_of_range("slot " + std::to_string(slot) +
                              " out of range");
    return workers_[slot];
  }
  void enqueue(int slot, std::function<void()> fn) {
    worker(slot).enqueue(std::move(fn));
  }
  void require_tcp(const char* what) const {
    if (!lg_->tcp)
      throw std::logic_error(std::string("hier ") + what +
                             ": group has no TCP comm (single-process "
                             "group asked for a remote peer?)");
  }
  // group rank of `global` within the local sub-communicator (local
  // members ascend by global rank in both partitions)
  int local_index(int global) const {
    for (std::size_t k = 0; k < lg_->local_members.size(); ++k)
      if (lg_->local_members[k] == global) return static_cast<int>(k);
    throw std::logic_error("hier: rank not local");
  }
  // this group's TCP comm indexes its member processes in ascending order
  int proc_index(int proc) const {
    const auto& procs = set_->info[gidx_].procs;
    for (std::size_t i = 0; i < procs.size(); ++i)
      if (procs[i] == proc) return static_cast<int>(i);
    throw std::logic_error("hier: process not in group");
  }
  // cross-process p2p frames carry both endpoints so concurrent member
  // threads of one process never cross-match.  User tags must stay below
  // the 8192 stride (slot-derived tags are small, kRingShiftTag = 7001)
  // and the encoding must fit the frame's uint32 op field — both are
  // enforced, not assumed, or aliased tags would match wrong frames.
  int p2p_tag(int src_grank, int dst_grank, int tag) const {
    if (tag < 0 || tag >= 8192)
      throw std::invalid_argument(
          "hier p2p: tag " + std::to_string(tag) +
          " outside [0, 8192) cannot cross the process boundary");
    std::int64_t enc =
        (static_cast<std::int64_t>(src_grank) * size() + dst_grank) * 8192 +
        tag;
    if (enc > std::numeric_limits<int>::max())
      throw std::invalid_argument(
          "hier p2p: encoded tag overflows for group size " +
          std::to_string(size()));
    return static_cast<int>(enc);
  }

  // Local device phase, slot-aligned: blocking Hier calls ride the sub
  // comm's blocking path; slotted calls ride the SAME sub slot so
  // concurrent Hier slots map onto distinct local rendezvous (the
  // stream-per-index discipline end to end).
  void sub_allreduce(int slot, const void* s, void* d, std::int64_t n) {
    if (slot >= num_slots_) {
      sub_->Allreduce(s, d, n);
    } else {
      sub_->Iallreduce(s, d, n, slot);
      sub_->Wait(slot);
    }
  }
  void sub_allgather(int slot, const void* s, void* d, std::int64_t n) {
    if (slot >= num_slots_) {
      sub_->Allgather(s, d, n);
    } else {
      sub_->Iallgather(s, d, n, slot);
      sub_->Wait(slot);
    }
  }
  void tcp_allreduce(int slot, const void* s, void* d, std::int64_t n) {
    if (slot >= num_slots_) {
      lg_->tcp->Allreduce(s, d, n);
    } else {
      lg_->tcp->Iallreduce(s, d, n, slot);
      lg_->tcp->Wait(slot);
    }
  }
  void tcp_allgather(int slot, const void* s, void* d, std::int64_t n) {
    if (slot >= num_slots_) {
      lg_->tcp->Allgather(s, d, n);
    } else {
      lg_->tcp->Iallgather(s, d, n, slot);
      lg_->tcp->Wait(slot);
    }
  }

  // Resolve a pointer to every GLOBAL group member's gathered block of
  // `block_bytes`, from the local sub-allgather result (single-process
  // groups) or a padded TCP allgather of each process's packed members
  // (spanning groups).  `storage` owns the wire buffer.
  void gather_member_blocks(int slot, const void* local_gathered,
                            std::size_t block_bytes,
                            std::vector<char>& storage,
                            std::vector<const char*>& ptrs) {
    const auto& gi = set_->info[gidx_];
    const auto& members = lg_->local_members;
    const int G = size();
    ptrs.assign(G, nullptr);
    if (gi.procs.size() == 1) {
      const char* base = static_cast<const char*>(local_gathered);
      for (std::size_t k = 0; k < members.size(); ++k)
        ptrs[set_->grank_of[members[k]]] = base + k * block_bytes;
      return;
    }
    const std::size_t pad = static_cast<std::size_t>(gi.maxm) * block_bytes;
    std::vector<char> packed(pad, 0);
    std::memcpy(packed.data(), local_gathered,
                members.size() * block_bytes);
    storage.resize(gi.procs.size() * pad);
    const std::size_t esz = dtype_bytes(dtype_);
    tcp_allgather(slot, packed.data(), storage.data(),
                  static_cast<std::int64_t>(pad / esz));
    for (std::size_t qi = 0; qi < gi.procs.size(); ++qi) {
      const auto& mems = gi.members_by_proc[qi];
      for (std::size_t k = 0; k < mems.size(); ++k)
        ptrs[set_->grank_of[mems[k]]] =
            storage.data() + qi * pad + k * block_bytes;
    }
  }

  void run_collective(int slot, pjrtfab::Op op, std::int64_t count,
                      std::int64_t extra, const void* src, void* dst) {
    const std::int64_t G = size();
    const std::size_t esz = dtype_bytes(dtype_);
    const std::size_t m = lg_->local_members.size();
    const bool spanning = set_->info[gidx_].procs.size() > 1;

    // ---- phase 1: local device collective (every member thread) ----
    std::vector<char> scratch;
    switch (op) {
      case pjrtfab::Op::Allreduce:
        sub_allreduce(slot, src, dst, count);
        break;
      case pjrtfab::Op::Allgather:
        scratch.resize(m * count * esz);
        sub_allgather(slot, src, scratch.data(), count);
        break;
      case pjrtfab::Op::ReduceScatterBlock:
        scratch.resize(static_cast<std::size_t>(G) * count * esz);
        sub_allreduce(slot, src, scratch.data(), G * count);
        break;
      case pjrtfab::Op::Alltoall:
        scratch.resize(m * G * count * esz);
        sub_allgather(slot, src, scratch.data(), G * count);
        break;
      case pjrtfab::Op::RingShift:
        scratch.resize(m * count * esz);
        sub_allgather(slot, src, scratch.data(), count);
        break;
      case pjrtfab::Op::Barrier:
        sub_->Barrier();
        break;
    }

    // ---- phase 2: rendezvous; last arriver runs the DCN combine ----
    auto* self = this;
    lg_->rdv[slot < num_slots_ ? slot : num_slots_]->collective(
        midx_, static_cast<int>(op), count, extra, dst, scratch.data(),
        [self, slot, op, count, extra, G, esz, spanning](
            const std::vector<void*>& dsts,
            const std::vector<void*>& scratches) {
          self->dcn_phase(slot, op, count, extra, G, esz, spanning, dsts,
                          scratches);
        });
  }

  void dcn_phase(int slot, pjrtfab::Op op, std::int64_t count,
                 std::int64_t extra, std::int64_t G, std::size_t esz,
                 bool spanning, const std::vector<void*>& dsts,
                 const std::vector<void*>& scratches) {
    const auto& members = lg_->local_members;
    switch (op) {
      case pjrtfab::Op::Barrier:
        if (spanning) lg_->tcp->Barrier();
        break;
      case pjrtfab::Op::Allreduce: {
        if (!spanning) break;  // local sum IS the group sum
        std::vector<char> tmp(count * esz);
        tcp_allreduce(slot, dsts[0], tmp.data(), count);
        for (void* d : dsts) std::memcpy(d, tmp.data(), tmp.size());
        break;
      }
      case pjrtfab::Op::ReduceScatterBlock: {
        const char* full = static_cast<const char*>(scratches[0]);
        std::vector<char> tmp;
        if (spanning) {  // AR-based reduce-scatter on the DCN leg
          tmp.resize(static_cast<std::size_t>(G) * count * esz);
          tcp_allreduce(slot, full, tmp.data(), G * count);
          full = tmp.data();
        }
        for (std::size_t k = 0; k < members.size(); ++k)
          std::memcpy(dsts[k],
                      full + static_cast<std::size_t>(
                                 set_->grank_of[members[k]]) *
                                 count * esz,
                      count * esz);
        break;
      }
      case pjrtfab::Op::Allgather: {
        std::vector<char> storage;
        std::vector<const char*> ptrs;
        gather_member_blocks(slot, scratches[0], count * esz, storage, ptrs);
        for (void* d : dsts)
          for (std::int64_t j = 0; j < G; ++j)
            std::memcpy(static_cast<char*>(d) + j * count * esz, ptrs[j],
                        count * esz);
        break;
      }
      case pjrtfab::Op::Alltoall: {
        std::vector<char> storage;
        std::vector<const char*> ptrs;  // each member's FULL src (G blocks)
        gather_member_blocks(slot, scratches[0],
                             static_cast<std::size_t>(G) * count * esz,
                             storage, ptrs);
        for (std::size_t k = 0; k < members.size(); ++k) {
          std::size_t gk = static_cast<std::size_t>(
              set_->grank_of[members[k]]);
          for (std::int64_t j = 0; j < G; ++j)
            std::memcpy(static_cast<char*>(dsts[k]) + j * count * esz,
                        ptrs[j] + gk * count * esz, count * esz);
        }
        break;
      }
      case pjrtfab::Op::RingShift: {
        std::vector<char> storage;
        std::vector<const char*> ptrs;
        gather_member_blocks(slot, scratches[0], count * esz, storage, ptrs);
        for (std::size_t k = 0; k < members.size(); ++k) {
          std::int64_t gk = set_->grank_of[members[k]];
          std::int64_t from = ((gk - extra) % G + G) % G;
          std::memcpy(dsts[k], ptrs[from], count * esz);
        }
        break;
      }
    }
  }

  std::shared_ptr<hier::GroupSet> set_;
  std::unique_ptr<ProxyCommunicator> sub_;
  int grk_;
  int gidx_ = 0;
  int midx_ = 0;
  hier::GroupSet::LocalGroup* lg_ = nullptr;
  DType dtype_;
  int num_slots_;
  std::string name_;
  std::vector<shm::SlotWorker> workers_;
};

// The two-level world: local device fabric + TCP process mesh.
class HierFabric : public Fabric {
 public:
  HierFabric(const std::string& coordinator, int nprocs, int proc_rank,
             int global_world, DType dtype,
             std::unique_ptr<CollectiveExecutor> exec, int num_slots = 32)
      : world_(global_world),
        nprocs_(nprocs),
        proc_rank_(proc_rank),
        dtype_(dtype),
        num_slots_(num_slots),
        tcp_(coordinator, nprocs, proc_rank, dtype),
        local_(checked_local(global_world, nprocs), dtype, std::move(exec),
               num_slots) {
    L_ = global_world / nprocs;
    base_ = proc_rank * L_;
    // control comm (f32 — exact for small split colors) created first so
    // every process's comm-id sequence aligns
    ctrl_ = make_tcp_comm(all_procs(), DType::F32, "hier_ctrl");
    world_set_ = build_set(std::vector<int>(world_, 0), "hier_world");
  }

  int world_size() const override { return world_; }
  DType dtype() const override { return dtype_; }
  std::string backend() const override { return "pjrt"; }
  CollectiveExecutor& executor() { return local_.executor(); }

  std::unique_ptr<ProxyCommunicator> world_comm(int rank) override {
    return std::make_unique<HierCommunicator>(
        world_set_, local_.world_comm(rank - base_), rank, dtype_,
        num_slots_, "hier_world");
  }

  // Collective over the GLOBAL world: local split on the device fabric,
  // colors allgathered across processes, same groups + same TCP comm ids
  // derived everywhere.
  std::unique_ptr<ProxyCommunicator> split(
      int world_rank, int color, const std::string& name) override {
    auto sub = local_.split(world_rank - base_, color, name + "_ici");
    std::shared_ptr<hier::GroupSet> set;
    std::uint64_t seq;
    {
      std::unique_lock<std::mutex> lk(split_m_);
      if (split_arrived_ == 0) split_colors_.assign(L_, 0);
      split_colors_[world_rank - base_] = color;
      seq = split_seq_;
      if (++split_arrived_ == L_) {
        try {
          std::vector<int> world_colors(world_, 0);
          if (nprocs_ > 1) {
            std::vector<float> mine(L_), all(world_);
            for (int i = 0; i < L_; ++i)
              mine[i] = static_cast<float>(split_colors_[i]);
            ctrl_->Allgather(mine.data(), all.data(), L_);
            for (int r = 0; r < world_; ++r)
              world_colors[r] = static_cast<int>(all[r]);
          } else {
            world_colors = split_colors_;
          }
          split_sets_[seq] = build_set(world_colors, name);
        } catch (...) {
          split_sets_[seq] = nullptr;
          split_arrived_ = 0;
          ++split_seq_;
          split_cv_.notify_all();
          throw;
        }
        split_arrived_ = 0;
        ++split_seq_;
        split_cv_.notify_all();
      } else {
        split_cv_.wait(lk, [&] { return split_seq_ > seq; });
      }
      set = split_sets_.at(seq);
    }
    if (!set)
      throw std::runtime_error(
          "hier split: group construction failed on another thread");
    return std::make_unique<HierCommunicator>(std::move(set), std::move(sub),
                                              world_rank, dtype_, num_slots_,
                                              name);
  }

  // This process runs its local ranks as threads (global rank = base + t).
  void launch(const std::function<void(int)>& body) override {
    local_.launch([&](int lr) { body(base_ + lr); });
  }

  std::vector<int> local_ranks() const override {
    std::vector<int> out(L_);
    for (int i = 0; i < L_; ++i) out[i] = base_ + i;
    return out;
  }
  int process_index() const override { return proc_rank_; }

  void burn(int rank, double us, double time_scale) override {
    local_.burn(rank - base_, us, time_scale);
  }

  void describe(Json& meta, Json& mesh) const override {
    local_.describe(meta, mesh);
    meta["backend"] = "pjrt";
    meta["num_processes"] = nprocs_;
    meta["local_world"] = L_;
    meta["dcn_transport"] = "tcp";
    meta["p2p_transport"] = "host+tcp";
    // the DCN leg of gather-style ops moves padded member blocks and the
    // reduce-scatter leg moves all G blocks — busbw math must not apply
    // ring correction factors to these records
    meta["dcn_algo"] = "hierarchical";
    mesh["hierarchy"] = "ici+dcn";
  }

 private:
  static int checked_local(int world, int nprocs) {
    if (nprocs <= 0 || world <= 0 || world % nprocs != 0)
      throw std::invalid_argument(
          "hier fabric: world must be a positive multiple of --procs");
    return world / nprocs;
  }

  std::vector<int> all_procs() const {
    std::vector<int> p(nprocs_);
    for (int i = 0; i < nprocs_; ++i) p[i] = i;
    return p;
  }

  std::unique_ptr<TcpCommunicator> make_tcp_comm(std::vector<int> procs,
                                                 DType dt,
                                                 const std::string& name) {
    std::uint32_t id = tcp_.allocate_comm_id();
    bool mine = false;
    for (int p : procs) mine |= (p == proc_rank_);
    if (!mine) return nullptr;  // id stays allocated to keep alignment
    return std::make_unique<TcpCommunicator>(&tcp_, id, std::move(procs),
                                             proc_rank_, dt, num_slots_,
                                             name);
  }

  std::shared_ptr<hier::GroupSet> build_set(
      const std::vector<int>& world_colors, const std::string& name) {
    auto set = std::make_shared<hier::GroupSet>();
    set->world = world_;
    set->local = L_;
    set->nprocs = nprocs_;
    set->my_proc = proc_rank_;
    set->group_of.resize(world_);
    set->grank_of.resize(world_);
    std::map<int, std::vector<int>> by_color;
    for (int r = 0; r < world_; ++r) by_color[world_colors[r]].push_back(r);
    for (auto& [c, members] : by_color) {
      int gi = static_cast<int>(set->groups.size());
      hier::GroupSet::Info info;
      for (std::size_t k = 0; k < members.size(); ++k) {
        set->group_of[members[k]] = gi;
        set->grank_of[members[k]] = static_cast<int>(k);
        int p = members[k] / L_;
        if (info.procs.empty() || info.procs.back() != p) {
          info.procs.push_back(p);
          info.members_by_proc.emplace_back();
        }
        info.members_by_proc.back().push_back(members[k]);
      }
      for (const auto& mems : info.members_by_proc)
        info.maxm = std::max(info.maxm, static_cast<int>(mems.size()));
      set->groups.push_back(members);
      set->info.push_back(std::move(info));
    }
    set->local_groups.resize(set->groups.size());
    for (std::size_t gi = 0; gi < set->groups.size(); ++gi) {
      const auto& info = set->info[gi];
      // spanning groups allocate a TCP comm id in every process (even
      // non-members) so the id sequence stays aligned fabric-wide
      std::unique_ptr<TcpCommunicator> tcp;
      if (info.procs.size() > 1)
        tcp = make_tcp_comm(info.procs, dtype_,
                            name + "_dcn" + std::to_string(gi));
      bool mine = false;
      for (int p : info.procs) mine |= (p == proc_rank_);
      if (!mine) continue;
      auto lg = std::make_unique<hier::GroupSet::LocalGroup>();
      for (int r : set->groups[gi])
        if (set->proc_of(r) == proc_rank_) lg->local_members.push_back(r);
      lg->tcp = std::move(tcp);
      for (int s = 0; s <= num_slots_; ++s)
        lg->rdv.push_back(std::make_unique<hier::Rendezvous>(
            static_cast<int>(lg->local_members.size())));
      set->local_groups[gi] = std::move(lg);
    }
    return set;
  }

  int world_;
  int nprocs_;
  int proc_rank_;
  int L_ = 1;
  int base_ = 0;
  DType dtype_;
  int num_slots_;
  TcpFabric tcp_;
  PjrtFabric local_;
  std::unique_ptr<TcpCommunicator> ctrl_;
  std::shared_ptr<hier::GroupSet> world_set_;

  std::mutex split_m_;
  std::condition_variable split_cv_;
  std::vector<int> split_colors_;
  int split_arrived_ = 0;
  std::uint64_t split_seq_ = 0;
  std::map<std::uint64_t, std::shared_ptr<hier::GroupSet>> split_sets_;
};

}  // namespace dlnb
