// Hierarchical rank fabric: per-process PJRT devices (ICI role) composed
// with an inter-process TCP mesh (DCN role) — the native tier's
// multi-host DEVICE path.
//
// The reference's native tier goes multi-node by bootstrapping a vendor
// communicator over MPI ranks and running device-buffer collectives
// across nodes (reference cpp/data_parallel/dp.cpp:166-189: MPI_Init +
// ncclUniqueId broadcast -> ncclCommInitRank; cpp/proxy_classes.hpp:
// 136-253 drives NCCL on GPU memory).  On TPU the same composition is a
// two-level fabric, matching how real TPU pods are wired (ICI inside a
// slice, DCN between slices):
//
//   * each OS process owns a PjrtFabric over its LOCAL devices — every
//     local collective phase executes as one compiled XLA module on
//     device (PluginExecutor on real libtpu; HostExecutor in CI, same
//     CollectiveProgram semantics);
//   * processes are joined by the TcpFabric's bootstrap + full-mesh
//     sockets (tcp_backend.hpp, the ncclUniqueId role);
//   * a collective on a group spanning processes runs hierarchically:
//     intra-process collective on device -> ONE thread per (process,
//     group) combines the partials over TCP -> the result is scattered
//     back to every local member.  Groups contained in one process never
//     touch the wire.
//
// Per-op composition (G = group size, m = this process's local members,
// m_q = process q's members, P = processes hosting the group).  Every
// DCN leg is BANDWIDTH-TRUE: it moves the bytes the canonical direct
// algorithm moves (the reference composes alltoall the same way, from
// per-destination p2p blocks: cpp/proxy_classes.hpp:160-182), so the
// recorded tcp_bytes_sent — and busbw derived from the timers — describe
// an algorithm a real DCN would run (dcn_algo: "blocked").
//   Allreduce        local AR (device) -> TCP AR of the m-way partial
//                    (count elements on the wire, the bandwidth-optimal
//                    two-level reduction; ring/mesh per the TCP
//                    threshold) -> copy to members.
//   ReduceScatter    local AR of all G blocks -> block-routed exchange:
//                    each process sends peer q only q's members' partial
//                    blocks (m_q x count; (G-m) x count total sent) ->
//                    each process sums the P partials of its own blocks.
//   Allgather        local AG -> each process sends its packed m blocks
//                    to every peer (exact sizes, no padding) ->
//                    reassemble in global group-rank order.
//   Alltoall         local AG of full sources -> each process sends
//                    peer q only the blocks destined to q's members
//                    (m x m_q x count; m x (G-m) x count total sent).
//   RingShift        local AG -> each process sends peer q only the
//                    source blocks q's members rotate in (boundary
//                    blocks only).
//   Barrier          local barrier -> TCP barrier among the group's
//                    processes.
//   Send/Recv        local pairs ride the in-process mailbox; cross-
//                    process pairs ride a TCP frame tagged with both
//                    endpoints' group ranks (p2p_transport "host+tcp").
//
// Communicator splits are collective over the GLOBAL world: every local
// rank thread calls split, the local PjrtFabric split partitions the
// local devices, local colors are allgathered across processes over a
// control communicator, and every process derives the same global groups
// and the same TCP comm-id sequence (the MPI_Comm_split contract, as in
// tcp_backend.hpp's split).
//
// CLI: --backend pjrt --procs P --coordinator host:port --rank p
// (world stays the GLOBAL rank count; each process runs world/P rank
// threads over its own devices).  Records carry this process's ranks
// only; dlnetbench_tpu.metrics.merge reassembles the run.
#pragma once

#include <algorithm>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <vector>

#include "dlnb/communicator.hpp"
#include "dlnb/fabric.hpp"
#include "dlnb/fault_plan.hpp"
#include "dlnb/pjrt_fabric.hpp"
#include "dlnb/schedule.hpp"  // balanced_local/start: the rank layout
#include "dlnb/tcp_backend.hpp"
#include "dlnb/tensor.hpp"

namespace dlnb {
namespace hier {

// All local members of one group arrive with their (op, count, extra)
// and buffer pointers; the LAST arriver runs the DCN phase exactly once
// (it sees every member's src/dst/scratch and writes the results);
// everyone departs only after it finished.  Mismatched op/count/extra
// across the local members aborts — same contract as the shm and pjrt
// rendezvous.
class Rendezvous {
 public:
  explicit Rendezvous(int n) : n_(n), dsts_(n), scratch_(n) {}

  // The DCN phase consumes only dsts (local-phase results) and scratches
  // (gathered/reduced staging); member src buffers were already folded in
  // by the local device collective.
  using ExecFn = std::function<void(const std::vector<void*>&,
                                    const std::vector<void*>&)>;

  void collective(int midx, int op, std::int64_t count, std::int64_t extra,
                  void* dst, void* scratch, const ExecFn& exec) {
    std::unique_lock<std::mutex> lk(m_);
    std::uint64_t my_gen = gen_;
    dsts_[midx] = dst;
    scratch_[midx] = scratch;
    if (arrived_ == 0) {
      op_ = op;
      count_ = count;
      extra_ = extra;
    } else if (op_ != op || count_ != count || extra_ != extra) {
      mismatch_ = true;
    }
    if (++arrived_ == n_) {
      if (!mismatch_) {
        lk.unlock();
        try {
          exec(dsts_, scratch_);
        } catch (...) {
          lk.lock();
          error_ = std::current_exception();
          lk.unlock();
        }
        lk.lock();
      }
      exec_done_ = true;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] {
        return gen_ == my_gen && arrived_ == n_ && exec_done_;
      });
    }
    bool bad = mismatch_;
    std::exception_ptr err = error_;
    if (++departed_ == n_) {
      arrived_ = 0;
      departed_ = 0;
      mismatch_ = false;
      exec_done_ = false;
      error_ = nullptr;
      ++gen_;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] { return gen_ != my_gen; });
    }
    lk.unlock();
    if (bad)
      throw std::runtime_error(
          "hier collective mismatch: local members disagree on op/count");
    if (err) std::rethrow_exception(err);
  }

 private:
  int n_;
  std::mutex m_;
  std::condition_variable cv_;
  std::vector<void*> dsts_;
  std::vector<void*> scratch_;
  int arrived_ = 0;
  int departed_ = 0;
  bool exec_done_ = false;
  bool mismatch_ = false;
  int op_ = 0;
  std::int64_t count_ = 0;
  std::int64_t extra_ = 0;
  std::exception_ptr error_;
  std::uint64_t gen_ = 0;
};

// One split's shared state in THIS process: the global group map plus,
// per locally-hosted group, the rendezvous and (for spanning groups)
// the TCP communicator among the group's processes.
struct GroupSet {
  struct Info {
    std::vector<int> procs;                        // ascending proc ranks
    std::vector<std::vector<int>> members_by_proc; // parallel to procs
  };
  struct LocalGroup {
    std::vector<int> local_members;  // global ranks here, ascending
    std::unique_ptr<TcpCommunicator> tcp;  // null for single-proc groups
    std::vector<std::unique_ptr<Rendezvous>> rdv;  // [0 .. num_slots]
    // host mailbox for local p2p when the split has no device sub
    // communicator (local_uniform == false)
    std::unique_ptr<shm::Mailboxes> mbox;
  };

  int world = 0, nprocs = 1, my_proc = 0;
  // rank layout: process p hosts global ranks [starts[p], starts[p+1]) —
  // contiguous but NOT necessarily equal-sized (balanced_locals gives
  // the first world%procs processes one extra rank when world does not
  // divide evenly)
  std::vector<int> starts;
  // All groups the same size?  The local DEVICE phase of G-dependent
  // ops (Alltoall / ReduceScatter move G x count locally) rides ONE
  // compiled XLA module per process, whose shapes cannot differ across
  // co-resident groups — when sizes are uneven those ops fall back to
  // a host-side local phase (same DCN wire layout).  Set-wide so every
  // rank of every process takes the same path.
  bool uniform = true;
  // This PROCESS's restriction of the split has equal-size color
  // groups, so one compiled XLA module (uniform replica_groups) can run
  // the local device phase.  False — possible with uneven locals even
  // when the GLOBAL groups are all equal (a group crossing the ragged
  // process boundary leaves different-size remainders in each process)
  // — routes the local phase through host staging instead: members
  // stage raw sources and the rendezvous combines on host.  The DCN
  // wire format is IDENTICAL either way, so processes may take
  // different paths within one collective.
  bool local_uniform = true;
  std::vector<std::vector<int>> groups;  // global ranks, by color asc
  std::vector<int> group_of, grank_of;   // by global rank
  std::vector<Info> info;                // by group index
  std::vector<std::unique_ptr<LocalGroup>> local_groups;  // null if none

  int proc_of(int global_rank) const {
    // starts is ascending and small (nprocs entries): linear scan
    for (int p = nprocs - 1; p >= 0; --p)
      if (global_rank >= starts[p]) return p;
    return 0;
  }
};

}  // namespace hier

class HierFabric;

// Per-rank-thread view of one group: ProxyCommunicator over the
// two-level fabric.  `sub_` is this rank's communicator on the local
// device fabric (same color partition restricted to local ranks).
class HierCommunicator : public ProxyCommunicator {
 public:
  HierCommunicator(std::shared_ptr<hier::GroupSet> set,
                   std::unique_ptr<ProxyCommunicator> sub, int global_rank,
                   DType dtype, int num_slots, std::string name)
      : set_(std::move(set)),
        sub_(std::move(sub)),
        grk_(global_rank),
        dtype_(dtype),
        num_slots_(num_slots),
        name_(std::move(name)),
        workers_(num_slots) {
    gidx_ = set_->group_of[grk_];
    lg_ = set_->local_groups[gidx_].get();
    for (std::size_t k = 0; k < lg_->local_members.size(); ++k)
      if (lg_->local_members[k] == grk_) midx_ = static_cast<int>(k);
  }

  ~HierCommunicator() override {
    for (auto& w : workers_) w.stop();
  }

  int rank() const override { return set_->grank_of[grk_]; }
  int size() const override {
    return static_cast<int>(set_->groups[gidx_].size());
  }
  std::string name() const override { return name_; }
  DType dtype() const override { return dtype_; }

  void Allreduce(const void* src, void* dst, std::int64_t count) override {
    run_collective(num_slots_, pjrtfab::Op::Allreduce, count, 0, src, dst);
  }
  void Allgather(const void* src, void* dst, std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::Allgather, cpr, 0, src, dst);
  }
  void ReduceScatterBlock(const void* src, void* dst,
                          std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::ReduceScatterBlock, cpr, 0, src,
                   dst);
  }
  void Alltoall(const void* src, void* dst, std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::Alltoall, cpr, 0, src, dst);
  }
  void Barrier() override {
    run_collective(num_slots_, pjrtfab::Op::Barrier, 0, 0, nullptr, nullptr);
  }
  void RingShift(const void* src, void* dst, std::int64_t count,
                 int shift = 1) override {
    run_collective(num_slots_, pjrtfab::Op::RingShift, count, shift, src,
                   dst);
  }

  // ---- p2p: in-process mailbox or cross-process TCP frame ----
  void Send(const void* src, std::int64_t count, int dst_rank,
            int tag = 0) override {
    int dst_global = set_->groups[gidx_].at(dst_rank);
    if (set_->proc_of(dst_global) == set_->my_proc) {
      if (sub_)
        sub_->Send(src, count, local_index(dst_global), tag);
      else  // host-local split: mailbox p2p, local member indices
        lg_->mbox->send(local_index(grk_), local_index(dst_global), tag,
                        src, static_cast<std::size_t>(count) *
                                 dtype_bytes(dtype_));
    } else {
      require_tcp("Send");
      lg_->tcp->Send(src, count, proc_index(set_->proc_of(dst_global)),
                     p2p_tag(rank(), dst_rank, tag));
    }
  }
  void Recv(void* dst, std::int64_t count, int src_rank,
            int tag = 0) override {
    int src_global = set_->groups[gidx_].at(src_rank);
    if (set_->proc_of(src_global) == set_->my_proc) {
      if (sub_)
        sub_->Recv(dst, count, local_index(src_global), tag);
      else
        lg_->mbox->recv(local_index(src_global), local_index(grk_), tag,
                        dst, static_cast<std::size_t>(count) *
                                 dtype_bytes(dtype_));
    } else {
      require_tcp("Recv");
      lg_->tcp->Recv(dst, count, proc_index(set_->proc_of(src_global)),
                     p2p_tag(src_rank, rank(), tag));
    }
  }

  // ---- nonblocking, slot-indexed ----
  void Iallreduce(const void* src, void* dst, std::int64_t count,
                  int slot) override {
    enqueue(slot, [=] {
      run_collective(slot, pjrtfab::Op::Allreduce, count, 0, src, dst);
    });
  }
  void Iallgather(const void* src, void* dst, std::int64_t cpr,
                  int slot) override {
    enqueue(slot, [=] {
      run_collective(slot, pjrtfab::Op::Allgather, cpr, 0, src, dst);
    });
  }
  void Isend(const void* src, std::int64_t count, int dst_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] { Send(src, count, dst_rank, t); });
  }
  void Irecv(void* dst, std::int64_t count, int src_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] { Recv(dst, count, src_rank, t); });
  }
  void Wait(int slot) override {
    try {
      worker(slot).wait();
    } catch (...) {
      shm::quiesce(workers_);
      throw;
    }
  }
  void WaitAll(int num_slots) override {
    for (int i = 0; i < num_slots && i < num_slots_; ++i) {
      try {
        workers_[i].wait();
      } catch (...) {
        shm::quiesce(workers_);
        throw;
      }
    }
  }

 private:
  shm::SlotWorker& worker(int slot) {
    if (slot < 0 || slot >= num_slots_)
      throw std::out_of_range("slot " + std::to_string(slot) +
                              " out of range");
    return workers_[slot];
  }
  void enqueue(int slot, std::function<void()> fn) {
    worker(slot).enqueue(std::move(fn));
  }
  void require_tcp(const char* what) const {
    if (!lg_->tcp)
      throw std::logic_error(std::string("hier ") + what +
                             ": group has no TCP comm (single-process "
                             "group asked for a remote peer?)");
  }
  // group rank of `global` within the local sub-communicator (local
  // members ascend by global rank in both partitions)
  int local_index(int global) const {
    for (std::size_t k = 0; k < lg_->local_members.size(); ++k)
      if (lg_->local_members[k] == global) return static_cast<int>(k);
    throw std::logic_error("hier: rank not local");
  }
  // this group's TCP comm indexes its member processes in ascending order
  int proc_index(int proc) const {
    const auto& procs = set_->info[gidx_].procs;
    for (std::size_t i = 0; i < procs.size(); ++i)
      if (procs[i] == proc) return static_cast<int>(i);
    throw std::logic_error("hier: process not in group");
  }
  // cross-process p2p frames carry both endpoints so concurrent member
  // threads of one process never cross-match.  User tags must stay below
  // the 8192 stride (slot-derived tags are small, kRingShiftTag = 7001)
  // and the encoding must fit the frame's uint32 op field — both are
  // enforced, not assumed, or aliased tags would match wrong frames.
  int p2p_tag(int src_grank, int dst_grank, int tag) const {
    if (tag < 0 || tag >= 8192)
      throw std::invalid_argument(
          "hier p2p: tag " + std::to_string(tag) +
          " outside [0, 8192) cannot cross the process boundary");
    std::int64_t enc =
        (static_cast<std::int64_t>(src_grank) * size() + dst_grank) * 8192 +
        tag;
    if (enc > std::numeric_limits<int>::max())
      throw std::invalid_argument(
          "hier p2p: encoded tag overflows for group size " +
          std::to_string(size()));
    return static_cast<int>(enc);
  }

  // Local device phase, slot-aligned: blocking Hier calls ride the sub
  // comm's blocking path; slotted calls ride the SAME sub slot so
  // concurrent Hier slots map onto distinct local rendezvous (the
  // stream-per-index discipline end to end).
  void sub_allreduce(int slot, const void* s, void* d, std::int64_t n) {
    if (slot >= num_slots_) {
      sub_->Allreduce(s, d, n);
    } else {
      sub_->Iallreduce(s, d, n, slot);
      sub_->Wait(slot);
    }
  }
  void sub_allgather(int slot, const void* s, void* d, std::int64_t n) {
    if (slot >= num_slots_) {
      sub_->Allgather(s, d, n);
    } else {
      sub_->Iallgather(s, d, n, slot);
      sub_->Wait(slot);
    }
  }
  void tcp_allreduce(int slot, const void* s, void* d, std::int64_t n) {
    if (slot >= num_slots_) {
      lg_->tcp->Allreduce(s, d, n);
    } else {
      lg_->tcp->Iallreduce(s, d, n, slot);
      lg_->tcp->Wait(slot);
    }
  }

  // Where each group rank lives: process slot qi (index into
  // info.procs) and position within that process's member list.
  struct MemberLoc {
    int qi = 0;
    int idx = 0;
  };
  std::vector<MemberLoc> member_locs() const {
    const auto& gi = set_->info[gidx_];
    std::vector<MemberLoc> loc(size());
    for (std::size_t qi = 0; qi < gi.procs.size(); ++qi) {
      const auto& mems = gi.members_by_proc[qi];
      for (std::size_t k = 0; k < mems.size(); ++k)
        loc[set_->grank_of[mems[k]]] = {static_cast<int>(qi),
                                        static_cast<int>(k)};
    }
    return loc;
  }

  // DCN-exchange p2p tags: one tag per (op, slot) keeps concurrent
  // slots' frames apart; member-level p2p tags (p2p_tag) are always
  // >= 8192 for cross-process pairs, so this space is collision-free.
  int dcn_tag(pjrtfab::Op op, int slot) const {
    int stride = num_slots_ + 1;
    int tag = static_cast<int>(op) * stride +
              (slot < num_slots_ ? slot : num_slots_);
    if (tag >= 8192)
      throw std::logic_error("hier: dcn tag space exhausted (num_slots "
                             "too large)");
    return tag;
  }

  // Block-routed direct exchange on the DCN leg: send exactly one
  // tagged frame (possibly empty) to every other member process, then
  // receive one from each.  `out[qi]`/`recv_elems[qi]` are ignored for
  // this process's own slot.  Blocking sends cannot deadlock: every
  // process's per-peer reader threads drain sockets independently.
  std::vector<std::vector<char>> dcn_exchange(
      pjrtfab::Op op, int slot, const std::vector<std::vector<char>>& out,
      const std::vector<std::int64_t>& recv_elems) {
    const auto& gi = set_->info[gidx_];
    const std::size_t P = gi.procs.size();
    const std::size_t esz = dtype_bytes(dtype_);
    const int me = proc_index(set_->my_proc);
    const int tag = dcn_tag(op, slot);
    for (std::size_t qi = 0; qi < P; ++qi) {
      if (static_cast<int>(qi) == me) continue;
      lg_->tcp->Send(out[qi].data(),
                     static_cast<std::int64_t>(out[qi].size() / esz),
                     static_cast<int>(qi), tag);
    }
    std::vector<std::vector<char>> in(P);
    for (std::size_t qi = 0; qi < P; ++qi) {
      if (static_cast<int>(qi) == me) continue;
      in[qi].resize(static_cast<std::size_t>(recv_elems[qi]) * esz);
      lg_->tcp->Recv(in[qi].data(), recv_elems[qi], static_cast<int>(qi),
                     tag);
    }
    return in;
  }

  void run_collective(int slot, pjrtfab::Op op, std::int64_t count,
                      std::int64_t extra, const void* src, void* dst) {
    // per-rank injected latency (fault_plan.hpp collective-scoped
    // events) — fires per global rank thread, before the local phase,
    // so a straggler rank delays its whole hierarchical collective;
    // drop injection rides the TCP mesh's send_frame hook underneath
    fault::Plan::instance().on_collective(grk_);
    const std::int64_t G = size();
    const std::size_t esz = dtype_bytes(dtype_);
    const std::size_t m = lg_->local_members.size();
    const bool spanning = set_->info[gidx_].procs.size() > 1;

    // ---- phase 1: local collective (every member thread) ----
    // Device sub-communicator when the process's color restriction is
    // uniform (ldev); otherwise members stage RAW sources and the
    // rendezvous combines on host — same DCN wire format either way.
    const bool ldev = set_->local_uniform;
    std::vector<char> scratch;
    switch (op) {
      case pjrtfab::Op::Allreduce:
        if (ldev) {
          sub_allreduce(slot, src, dst, count);
        } else {
          scratch.resize(count * esz);
          std::memcpy(scratch.data(), src, scratch.size());
        }
        break;
      case pjrtfab::Op::Allgather:
        if (ldev) {
          scratch.resize(m * count * esz);
          sub_allgather(slot, src, scratch.data(), count);
        } else {
          scratch.resize(count * esz);
          std::memcpy(scratch.data(), src, scratch.size());
        }
        break;
      case pjrtfab::Op::ReduceScatterBlock:
        scratch.resize(static_cast<std::size_t>(G) * count * esz);
        if (set_->uniform && ldev) {
          sub_allreduce(slot, src, scratch.data(), G * count);
        } else {
          // uneven group sizes (or no device sub): the G x count local
          // module shape is unavailable — stage the raw source;
          // dcn_phase sums the members on host
          std::memcpy(scratch.data(), src, scratch.size());
        }
        break;
      case pjrtfab::Op::Alltoall:
        if (set_->uniform && ldev) {
          scratch.resize(m * G * count * esz);
          sub_allgather(slot, src, scratch.data(), G * count);
        } else {
          scratch.resize(static_cast<std::size_t>(G) * count * esz);
          std::memcpy(scratch.data(), src, scratch.size());
        }
        break;
      case pjrtfab::Op::RingShift:
        if (ldev) {
          scratch.resize(m * count * esz);
          sub_allgather(slot, src, scratch.data(), count);
        } else {
          scratch.resize(count * esz);
          std::memcpy(scratch.data(), src, scratch.size());
        }
        break;
      case pjrtfab::Op::Barrier:
        if (ldev) sub_->Barrier();
        // !ldev: the rendezvous below IS the local barrier
        break;
    }

    // ---- phase 2: rendezvous; last arriver runs the DCN combine ----
    auto* self = this;
    lg_->rdv[slot < num_slots_ ? slot : num_slots_]->collective(
        midx_, static_cast<int>(op), count, extra, dst, scratch.data(),
        [self, slot, op, count, extra, G, esz, spanning](
            const std::vector<void*>& dsts,
            const std::vector<void*>& scratches) {
          self->dcn_phase(slot, op, count, extra, G, esz, spanning, dsts,
                          scratches);
        });
  }

  void dcn_phase(int slot, pjrtfab::Op op, std::int64_t count,
                 std::int64_t extra, std::int64_t G, std::size_t esz,
                 bool spanning, const std::vector<void*>& dsts,
                 const std::vector<void*>& scratches) {
    const auto& members = lg_->local_members;
    const auto& gi = set_->info[gidx_];
    const std::size_t m = members.size();
    const std::size_t blk = static_cast<std::size_t>(count) * esz;
    const bool ldev = set_->local_uniform;
    // with a device local phase every member's scratch holds the same
    // local-phase result (scratches[0] canonical); in host-local mode
    // each scratch is that member's RAW source and the combines below
    // assemble/sum them here
    const char* local_res = static_cast<const char*>(scratches[0]);
    // m packed member blocks in group-rank order, host-assembled from
    // the raw per-member sources (Allgather/RingShift host-local mode)
    std::vector<char> packed;
    auto pack_members = [&]() {
      packed.resize(m * blk);
      for (std::size_t k = 0; k < m; ++k)
        std::memcpy(packed.data() + k * blk, scratches[k], blk);
      local_res = packed.data();
    };
    switch (op) {
      case pjrtfab::Op::Barrier:
        if (spanning) lg_->tcp->Barrier();
        break;
      case pjrtfab::Op::Allreduce: {
        const void* lsum = dsts[0];  // device local phase: partial in dst
        std::vector<char> hostsum;
        if (!ldev) {  // host local phase: sum the raw member sources
          hostsum.assign(static_cast<const char*>(scratches[0]),
                         static_cast<const char*>(scratches[0]) + blk);
          for (std::size_t k = 1; k < m; ++k)
            for (std::size_t i = 0; i < static_cast<std::size_t>(count);
                 ++i)
              store_element(
                  hostsum.data(), i, dtype_,
                  load_element(hostsum.data(), i, dtype_) +
                      load_element(scratches[k], i, dtype_));
          lsum = hostsum.data();
          if (!spanning) {  // device mode wrote dsts already; host must
            for (void* d : dsts) std::memcpy(d, lsum, blk);
            break;
          }
        } else if (!spanning) {
          break;  // local sum IS the group sum, already in every dst
        }
        std::vector<char> tmp(count * esz);
        tcp_allreduce(slot, lsum, tmp.data(), count);
        for (void* d : dsts) std::memcpy(d, tmp.data(), tmp.size());
        break;
      }
      case pjrtfab::Op::ReduceScatterBlock: {
        // local_res: this process's full G-block partial sum — from the
        // device AR, or summed here when the split is uneven or has no
        // device sub (the staged raw sources, see run_collective)
        std::vector<char> staged;
        if (!set_->uniform || !ldev) {
          staged.assign(local_res,
                        local_res + static_cast<std::size_t>(G) * blk);
          for (std::size_t k = 1; k < m; ++k) {
            const char* s = static_cast<const char*>(scratches[k]);
            for (std::size_t i = 0;
                 i < static_cast<std::size_t>(G) *
                         static_cast<std::size_t>(count);
                 ++i)
              store_element(staged.data(), i, dtype_,
                            load_element(staged.data(), i, dtype_) +
                                load_element(s, i, dtype_));
          }
          local_res = staged.data();
        }
        if (!spanning) {
          for (std::size_t k = 0; k < m; ++k)
            std::memcpy(dsts[k],
                        local_res + static_cast<std::size_t>(
                                        set_->grank_of[members[k]]) *
                                        blk,
                        blk);
          break;
        }
        // block-routed reduce-scatter: peer q gets only its members'
        // partial blocks ((G-m) x count sent); sum arriving partials of
        // OUR blocks over the member processes
        std::vector<std::vector<char>> out(gi.procs.size());
        std::vector<std::int64_t> want(gi.procs.size(), 0);
        const int me = proc_index(set_->my_proc);
        for (std::size_t qi = 0; qi < gi.procs.size(); ++qi) {
          if (static_cast<int>(qi) == me) continue;
          const auto& mems = gi.members_by_proc[qi];
          out[qi].resize(mems.size() * blk);
          for (std::size_t j = 0; j < mems.size(); ++j)
            std::memcpy(out[qi].data() + j * blk,
                        local_res + static_cast<std::size_t>(
                                        set_->grank_of[mems[j]]) *
                                        blk,
                        blk);
          want[qi] = static_cast<std::int64_t>(m) * count;
        }
        auto in = dcn_exchange(op, slot, out, want);
        std::vector<char> acc(m * blk);
        for (std::size_t k = 0; k < m; ++k)
          std::memcpy(acc.data() + k * blk,
                      local_res + static_cast<std::size_t>(
                                      set_->grank_of[members[k]]) *
                                      blk,
                      blk);
        for (std::size_t qi = 0; qi < gi.procs.size(); ++qi) {
          if (in[qi].empty()) continue;
          for (std::size_t i = 0; i < m * static_cast<std::size_t>(count);
               ++i)
            store_element(acc.data(), i, dtype_,
                          load_element(acc.data(), i, dtype_) +
                              load_element(in[qi].data(), i, dtype_));
        }
        for (std::size_t k = 0; k < m; ++k)
          std::memcpy(dsts[k], acc.data() + k * blk, blk);
        break;
      }
      case pjrtfab::Op::Allgather: {
        // local_res: this process's m packed member blocks (ascending
        // global rank = group-rank order within the process)
        if (!ldev) pack_members();
        if (!spanning) {
          for (void* d : dsts) std::memcpy(d, local_res, m * blk);
          break;
        }
        // exact-size direct allgather: the packed m blocks go to every
        // peer unpadded; reassemble in global group-rank order
        std::vector<std::vector<char>> out(gi.procs.size());
        std::vector<std::int64_t> want(gi.procs.size(), 0);
        const int me = proc_index(set_->my_proc);
        for (std::size_t qi = 0; qi < gi.procs.size(); ++qi) {
          if (static_cast<int>(qi) == me) continue;
          out[qi].assign(local_res, local_res + m * blk);
          want[qi] = static_cast<std::int64_t>(
                         gi.members_by_proc[qi].size()) *
                     count;
        }
        auto in = dcn_exchange(op, slot, out, want);
        auto loc = member_locs();
        for (void* d : dsts)
          for (std::int64_t j = 0; j < G; ++j) {
            const char* src_blk =
                loc[j].qi == me
                    ? local_res + static_cast<std::size_t>(loc[j].idx) * blk
                    : in[loc[j].qi].data() +
                          static_cast<std::size_t>(loc[j].idx) * blk;
            std::memcpy(static_cast<char*>(d) + j * blk, src_blk, blk);
          }
        break;
      }
      case pjrtfab::Op::Alltoall: {
        // local_res: m members x their FULL G-block sources
        // (member-major, ascending global rank) — from the device AG,
        // or packed here from the staged raw sources when uneven or
        // host-local
        std::vector<char> staged;
        if (!set_->uniform || !ldev) {
          staged.resize(m * static_cast<std::size_t>(G) * blk);
          for (std::size_t k = 0; k < m; ++k)
            std::memcpy(staged.data() +
                            k * static_cast<std::size_t>(G) * blk,
                        scratches[k], static_cast<std::size_t>(G) * blk);
          local_res = staged.data();
        }
        auto src_of = [&](std::size_t k_local, std::int64_t dest_g) {
          return local_res +
                 (k_local * static_cast<std::size_t>(G) +
                  static_cast<std::size_t>(dest_g)) *
                     blk;
        };
        if (!spanning) {
          for (std::size_t k = 0; k < m; ++k) {
            std::int64_t gk = set_->grank_of[members[k]];
            for (std::int64_t j = 0; j < G; ++j)
              std::memcpy(static_cast<char*>(dsts[k]) + j * blk,
                          src_of(static_cast<std::size_t>(j), gk), blk);
          }
          break;
        }
        // block-routed alltoall (the reference's per-destination p2p
        // composition, proxy_classes.hpp:160-182): peer q receives only
        // the m x m_q blocks destined to its members, packed
        // [my member asc][q's member asc]
        std::vector<std::vector<char>> out(gi.procs.size());
        std::vector<std::int64_t> want(gi.procs.size(), 0);
        const int me = proc_index(set_->my_proc);
        for (std::size_t qi = 0; qi < gi.procs.size(); ++qi) {
          if (static_cast<int>(qi) == me) continue;
          const auto& mems = gi.members_by_proc[qi];
          out[qi].resize(m * mems.size() * blk);
          char* w = out[qi].data();
          for (std::size_t k = 0; k < m; ++k)
            for (std::size_t j = 0; j < mems.size(); ++j) {
              std::memcpy(w, src_of(k, set_->grank_of[mems[j]]), blk);
              w += blk;
            }
          want[qi] = static_cast<std::int64_t>(m * mems.size()) * count;
        }
        auto in = dcn_exchange(op, slot, out, want);
        auto loc = member_locs();
        for (std::size_t k = 0; k < m; ++k) {
          std::int64_t gk = set_->grank_of[members[k]];
          for (std::int64_t j = 0; j < G; ++j) {
            const char* src_blk =
                loc[j].qi == me
                    ? src_of(static_cast<std::size_t>(loc[j].idx), gk)
                    : in[loc[j].qi].data() +
                          (static_cast<std::size_t>(loc[j].idx) * m + k) *
                              blk;
            std::memcpy(static_cast<char*>(dsts[k]) + j * blk, src_blk,
                        blk);
          }
        }
        break;
      }
      case pjrtfab::Op::RingShift: {
        // local_res: m packed member blocks; member gk rotates in the
        // block of grank (gk - extra) mod G
        if (!ldev) pack_members();
        auto from_of = [&](std::int64_t gk) {
          return ((gk - extra) % G + G) % G;
        };
        if (!spanning) {
          for (std::size_t k = 0; k < m; ++k) {
            std::int64_t from = from_of(set_->grank_of[members[k]]);
            std::memcpy(dsts[k],
                        local_res + static_cast<std::size_t>(from) * blk,
                        blk);
          }
          break;
        }
        // boundary-only routing: peer q gets exactly the source blocks
        // its members rotate in from OUR members, in q's member order
        auto loc = member_locs();
        std::vector<std::vector<char>> out(gi.procs.size());
        std::vector<std::int64_t> want(gi.procs.size(), 0);
        const int me = proc_index(set_->my_proc);
        for (std::size_t qi = 0; qi < gi.procs.size(); ++qi) {
          if (static_cast<int>(qi) == me) continue;
          const auto& mems = gi.members_by_proc[qi];
          for (std::size_t j = 0; j < mems.size(); ++j) {
            std::int64_t from = from_of(set_->grank_of[mems[j]]);
            if (loc[from].qi != me) continue;
            std::size_t old = out[qi].size();
            out[qi].resize(old + blk);
            std::memcpy(out[qi].data() + old,
                        local_res +
                            static_cast<std::size_t>(loc[from].idx) * blk,
                        blk);
          }
          for (std::size_t k = 0; k < m; ++k)
            if (loc[from_of(set_->grank_of[members[k]])].qi ==
                static_cast<int>(qi))
              want[qi] += count;
        }
        auto in = dcn_exchange(op, slot, out, want);
        std::vector<std::size_t> cursor(gi.procs.size(), 0);
        for (std::size_t k = 0; k < m; ++k) {
          std::int64_t from = from_of(set_->grank_of[members[k]]);
          if (loc[from].qi == me) {
            std::memcpy(dsts[k],
                        local_res +
                            static_cast<std::size_t>(loc[from].idx) * blk,
                        blk);
          } else {
            std::memcpy(dsts[k],
                        in[loc[from].qi].data() + cursor[loc[from].qi],
                        blk);
            cursor[loc[from].qi] += blk;
          }
        }
        break;
      }
    }
  }

  std::shared_ptr<hier::GroupSet> set_;
  std::unique_ptr<ProxyCommunicator> sub_;
  int grk_;
  int gidx_ = 0;
  int midx_ = 0;
  hier::GroupSet::LocalGroup* lg_ = nullptr;
  DType dtype_;
  int num_slots_;
  std::string name_;
  std::vector<shm::SlotWorker> workers_;
};

// The two-level world: local device fabric + TCP process mesh.
class HierFabric : public Fabric {
 public:
  HierFabric(const std::string& coordinator, int nprocs, int proc_rank,
             int global_world, DType dtype,
             std::unique_ptr<CollectiveExecutor> exec, int num_slots = 32)
      : world_(global_world),
        nprocs_(nprocs),
        proc_rank_(proc_rank),
        dtype_(dtype),
        num_slots_(num_slots),
        tcp_(coordinator, nprocs, proc_rank, dtype),
        local_(checked_local(global_world, nprocs, proc_rank), dtype,
               std::move(exec), num_slots) {
    L_ = static_cast<int>(balanced_local(world_, nprocs_, proc_rank_));
    base_ = static_cast<int>(balanced_start(world_, nprocs_, proc_rank_));
    // control comm (f32 — exact for small split colors) created first so
    // every process's comm-id sequence aligns
    ctrl_ = make_tcp_comm(all_procs(), DType::F32, "hier_ctrl");
    world_set_ = build_set(std::vector<int>(world_, 0), "hier_world");
  }

  int world_size() const override { return world_; }
  DType dtype() const override { return dtype_; }
  std::string backend() const override { return "pjrt"; }
  CollectiveExecutor& executor() { return local_.executor(); }

  std::unique_ptr<ProxyCommunicator> world_comm(int rank) override {
    return std::make_unique<HierCommunicator>(
        world_set_, local_.world_comm(rank - base_), rank, dtype_,
        num_slots_, "hier_world");
  }

  // Collective over the GLOBAL world: local split on the device fabric,
  // colors allgathered across processes, same groups + same TCP comm ids
  // derived everywhere.
  std::unique_ptr<ProxyCommunicator> split(
      int world_rank, int color, const std::string& name) override {
    std::shared_ptr<hier::GroupSet> set;
    std::uint64_t seq;
    {
      std::unique_lock<std::mutex> lk(split_m_);
      if (split_arrived_ == 0) split_colors_.assign(L_, 0);
      split_colors_[world_rank - base_] = color;
      seq = split_seq_;
      if (++split_arrived_ == L_) {
        try {
          std::vector<int> world_colors(world_, 0);
          if (nprocs_ > 1) {
            // uneven locals: the TCP allgather moves EQUAL counts per
            // process, so every process contributes Lmax slots (its
            // own colors, zero-padded) and the reassembly skips each
            // process's padding via the balanced layout — process 0
            // always holds the max local count
            const int Lmax = static_cast<int>(balanced_local(world_, nprocs_, 0));
            std::vector<float> mine(Lmax, 0.0f);
            std::vector<float> all(static_cast<std::size_t>(nprocs_) *
                                   Lmax);
            for (int i = 0; i < L_; ++i)
              mine[i] = static_cast<float>(split_colors_[i]);
            ctrl_->Allgather(mine.data(), all.data(), Lmax);
            for (int p = 0; p < nprocs_; ++p) {
              const int s = static_cast<int>(balanced_start(world_, nprocs_, p));
              const int lp = static_cast<int>(balanced_local(world_, nprocs_, p));
              for (int i = 0; i < lp; ++i)
                world_colors[s + i] =
                    static_cast<int>(all[static_cast<std::size_t>(p) *
                                         Lmax + i]);
            }
          } else {
            world_colors = split_colors_;
          }
          split_sets_[seq] =
              build_set(world_colors, name, colors_uniform(split_colors_));
        } catch (...) {
          split_sets_[seq] = nullptr;
          // the builder throws before the retrieval below, so account
          // its share here or the last waiter's `== L_` eviction never
          // fires and the failed seq's entries leak
          if (++split_taken_[seq] == L_) {
            split_sets_.erase(seq);
            split_taken_.erase(seq);
          }
          split_arrived_ = 0;
          ++split_seq_;
          split_cv_.notify_all();
          throw;
        }
        split_arrived_ = 0;
        ++split_seq_;
        split_cv_.notify_all();
      } else {
        split_cv_.wait(lk, [&] { return split_seq_ > seq; });
      }
      set = split_sets_.at(seq);
      // last local thread to retrieve this split's set erases the cache
      // entry — a looping proxy that re-splits per iteration must not
      // grow the map (and its live TcpCommunicators) without bound
      if (++split_taken_[seq] == L_) {
        split_sets_.erase(seq);
        split_taken_.erase(seq);
      }
    }
    if (!set)
      throw std::runtime_error(
          "hier split: group construction failed on another thread");
    // local device sub-communicator only when this process's color
    // restriction is uniform (XLA replica_groups constraint); all local
    // threads agree on the flag, so either all of them enter the local
    // split rendezvous or none does, keeping the local fabric's split
    // sequence aligned.  Non-uniform: the local phase runs on host
    // (set->local_uniform routing in run_collective/dcn_phase).
    std::unique_ptr<ProxyCommunicator> sub;
    if (set->local_uniform)
      sub = local_.split(world_rank - base_, color, name + "_ici");
    return std::make_unique<HierCommunicator>(std::move(set), std::move(sub),
                                              world_rank, dtype_, num_slots_,
                                              name);
  }

  // This process runs its local ranks as threads (global rank = base + t).
  void launch(const std::function<void(int)>& body) override {
    local_.launch([&](int lr) {
      try {
        body(base_ + lr);
      } catch (...) {
        // latch death IN the rank thread: the local fabric's launch
        // catches this to rethrow on the main thread, where the TCP
        // destructor's thread-local uncaught_exceptions() check alone
        // would read 0 if the rethrown error is caught before teardown
        // — the flag keeps the Bye suppressed either way (advisor r5)
        tcp_.mark_dying();
        throw;
      }
    });
  }

  std::vector<int> local_ranks() const override {
    std::vector<int> out(L_);
    for (int i = 0; i < L_; ++i) out[i] = base_ + i;
    return out;
  }
  int process_index() const override { return proc_rank_; }

  // Fault-plan crash of a local rank thread: the whole process is
  // going down (the local fabric's launch rethrows), so suppress the
  // DCN goodbye — peers must read this process's EOF as a death.
  void mark_rank_dead(int /*world_rank*/) override { tcp_.mark_dying(); }

  void burn(int rank, double us, double time_scale) override {
    local_.burn(rank - base_, us, time_scale);
  }

  void describe(Json& meta, Json& mesh) const override {
    local_.describe(meta, mesh);
    meta["backend"] = "pjrt";
    meta["num_processes"] = nprocs_;
    meta["local_world"] = L_;
    // full layout so analyses of uneven-locals runs (world % procs != 0)
    // can reconstruct every process's share, not just this one's
    Json lw = Json::array();
    for (int p = 0; p < nprocs_; ++p)
      lw.push_back(
          static_cast<std::int64_t>(balanced_local(world_, nprocs_, p)));
    meta["local_worlds"] = lw;
    meta["dcn_transport"] = "tcp";
    meta["p2p_transport"] = "host+tcp";
    // composed provenance, overriding the local fabric's stamp: the
    // ICI (or host-executor) leg plus the TCP DCN leg, loopback-labeled
    // when the process mesh never leaves this machine
    meta["transport"] =
        std::string(local_.executor().platform() == "host" ? "host"
                                                           : "ici") +
        (tcp_.loopback() ? "+tcp:loopback" : "+tcp:ethernet");
    // every DCN leg is a block-routed direct exchange moving the
    // canonical algorithm's bytes (header comment), so busbw correction
    // factors apply; the allreduce leg rides the TCP ring/mesh per the
    // threshold, which analysis/bandwidth.py needs to refuse small
    // full-mesh allreduces — same contract as TcpFabric::describe
    meta["dcn_algo"] = "blocked";
    meta["tcp_ring_threshold_bytes"] =
        static_cast<std::int64_t>(tcp_.ring_threshold_bytes());
    // this process's actual socket bytes: lets tests pin each DCN
    // algorithm's wire cost without timing flakiness
    meta["tcp_bytes_sent"] = static_cast<std::int64_t>(tcp_.bytes_sent());
    mesh["hierarchy"] = "ici+dcn";
  }

 private:
  static int checked_local(int world, int nprocs, int proc_rank) {
    // world need NOT divide procs: the balanced layout gives the first
    // world%procs processes one extra local rank (uneven locals — the
    // real-pod case of a ragged last host).  Every process must still
    // host at least one rank.
    if (nprocs <= 0 || world < nprocs)
      throw std::invalid_argument(
          "hier fabric: need world >= procs >= 1 (every process hosts "
          "at least one rank)");
    return static_cast<int>(balanced_local(world, nprocs, proc_rank));
  }

  std::vector<int> all_procs() const {
    std::vector<int> p(nprocs_);
    for (int i = 0; i < nprocs_; ++i) p[i] = i;
    return p;
  }

  std::unique_ptr<TcpCommunicator> make_tcp_comm(std::vector<int> procs,
                                                 DType dt,
                                                 const std::string& name) {
    std::uint32_t id = tcp_.allocate_comm_id();
    bool mine = false;
    for (int p : procs) mine |= (p == proc_rank_);
    if (!mine) return nullptr;  // id stays allocated to keep alignment
    return std::make_unique<TcpCommunicator>(&tcp_, id, std::move(procs),
                                             proc_rank_, dt, num_slots_,
                                             name);
  }

  // Equal-size color classes?  (The local device phase needs ONE
  // XLA module shape across this process's co-resident groups.)
  static bool colors_uniform(const std::vector<int>& colors) {
    std::map<int, int> cnt;
    for (int c : colors) ++cnt[c];
    for (const auto& kv : cnt)
      if (kv.second != cnt.begin()->second) return false;
    return true;
  }

  std::shared_ptr<hier::GroupSet> build_set(
      const std::vector<int>& world_colors, const std::string& name,
      bool local_uniform = true) {
    auto set = std::make_shared<hier::GroupSet>();
    set->world = world_;
    set->nprocs = nprocs_;
    set->my_proc = proc_rank_;
    set->local_uniform = local_uniform;
    set->starts.resize(nprocs_);
    for (int p = 0; p < nprocs_; ++p)
      set->starts[p] = static_cast<int>(balanced_start(world_, nprocs_, p));
    set->group_of.resize(world_);
    set->grank_of.resize(world_);
    std::map<int, std::vector<int>> by_color;
    for (int r = 0; r < world_; ++r) by_color[world_colors[r]].push_back(r);
    for (auto& [c, members] : by_color) {
      int gi = static_cast<int>(set->groups.size());
      hier::GroupSet::Info info;
      for (std::size_t k = 0; k < members.size(); ++k) {
        set->group_of[members[k]] = gi;
        set->grank_of[members[k]] = static_cast<int>(k);
        int p = set->proc_of(members[k]);
        if (info.procs.empty() || info.procs.back() != p) {
          info.procs.push_back(p);
          info.members_by_proc.emplace_back();
        }
        info.members_by_proc.back().push_back(members[k]);
      }
      set->groups.push_back(members);
      set->info.push_back(std::move(info));
    }
    for (const auto& grp : set->groups)
      if (grp.size() != set->groups[0].size()) set->uniform = false;
    set->local_groups.resize(set->groups.size());
    for (std::size_t gi = 0; gi < set->groups.size(); ++gi) {
      const auto& info = set->info[gi];
      // spanning groups allocate a TCP comm id in every process (even
      // non-members) so the id sequence stays aligned fabric-wide
      std::unique_ptr<TcpCommunicator> tcp;
      if (info.procs.size() > 1)
        tcp = make_tcp_comm(info.procs, dtype_,
                            name + "_dcn" + std::to_string(gi));
      bool mine = false;
      for (int p : info.procs) mine |= (p == proc_rank_);
      if (!mine) continue;
      auto lg = std::make_unique<hier::GroupSet::LocalGroup>();
      for (int r : set->groups[gi])
        if (set->proc_of(r) == proc_rank_) lg->local_members.push_back(r);
      lg->tcp = std::move(tcp);
      if (!local_uniform)  // host mailbox replaces the device sub's p2p
        lg->mbox = std::make_unique<shm::Mailboxes>();
      for (int s = 0; s <= num_slots_; ++s)
        lg->rdv.push_back(std::make_unique<hier::Rendezvous>(
            static_cast<int>(lg->local_members.size())));
      set->local_groups[gi] = std::move(lg);
    }
    return set;
  }

  int world_;
  int nprocs_;
  int proc_rank_;
  int L_ = 1;
  int base_ = 0;
  DType dtype_;
  int num_slots_;
  TcpFabric tcp_;
  PjrtFabric local_;
  std::unique_ptr<TcpCommunicator> ctrl_;
  std::shared_ptr<hier::GroupSet> world_set_;

  std::mutex split_m_;
  std::condition_variable split_cv_;
  std::vector<int> split_colors_;
  int split_arrived_ = 0;
  std::uint64_t split_seq_ = 0;
  std::map<std::uint64_t, std::shared_ptr<hier::GroupSet>> split_sets_;
  std::map<std::uint64_t, int> split_taken_;
};

}  // namespace dlnb
