// PJRT rank fabric — the native proxies' TPU-runtime backend.
//
// The reference wires its vendor backend into every proxy binary
// (reference cpp/data_parallel/dp.cpp:183-189 builds a CCLCommunicator the
// hot loop drives; cpp/proxy_classes.hpp:136-253).  This is the TPU
// equivalent: PjrtFabric implements Fabric, PjrtCommunicator implements
// the same slot-indexed ProxyCommunicator API, and every collective
// executes as ONE multi-group XLA module over all devices at once.
//
// How the imperative API maps onto the SPMD runtime:
//
//   * A communicator split does not create a new execution context: the
//     full partition of world ranks into colors becomes the module's
//     `replica_groups` (GroupSet).  Consequence (and constraint — it is
//     the XLA SPMD model): every world rank must reach the same
//     collective on the same slot; all colors ride one execution.
//     Mismatched (op, count) across ranks is detected and aborts.
//   * Nonblocking slot ops run on per-(rank, slot) worker threads — the
//     NCCL stream-per-request-index discipline (reference
//     proxy_classes.hpp:143-147) — and rendezvous with the other ranks'
//     same-slot workers; the LAST arriver executes the cached module
//     (ExecRendezvous), so compute/comm overlap is real.
//   * RingShift (ring attention's KV rotation) compiles to a native
//     collective_permute with per-group rotation pairs.
//   * Point-to-point Send/Recv stays on a host mailbox rendezvous: PJRT
//     exposes no p2p primitive; stage-asymmetric GPipe hops on TPU belong
//     in whole-step compiled programs (the JAX tier's masked-ppermute
//     pipelines, SURVEY.md §7.3 hard part 3).  Records carry
//     `p2p_transport: "host"` so analyses can tell.
//
// The executor is pluggable: PluginExecutor drives a real PJRT plugin
// (libtpu.so); HostExecutor implements identical CollectiveProgram
// semantics in portable C++ (validated against XLA's execution of the
// same generated modules by tests/test_pjrt_programs.py), so the entire
// --backend pjrt path — rendezvous, group math, slot workers, cache keys
// — runs in CI without a TPU.
#pragma once

#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "dlnb/communicator.hpp"
#include "dlnb/fabric.hpp"
#include "dlnb/pjrt_backend.hpp"
#include "dlnb/shm_backend.hpp"
#include "dlnb/stablehlo_gen.hpp"
#include "dlnb/tensor.hpp"

namespace dlnb {

// ------------------------------------------------------------- executor
// Execution core under PjrtFabric: run one compiled collective program
// across all devices, srcs/dsts indexed by replica id (== world rank).
class CollectiveExecutor {
 public:
  virtual ~CollectiveExecutor() = default;
  virtual void run(const CollectiveProgram& prog,
                   const std::vector<const void*>& srcs,
                   const std::vector<void*>& dsts, DType dtype) = 0;
  virtual std::string platform() const = 0;
  virtual std::size_t cache_hits() const = 0;
  virtual std::size_t cache_misses() const = 0;
  // Burn ~`us` microseconds of REAL device compute on `rank`'s device
  // (calibrated chained-matmul kernel, the JAX tier's proxies/burn.py
  // analogue).  Returns false when the executor has no device to burn on
  // (host executor) — callers fall back to the host sleep.
  virtual bool device_burn(int rank, double us) {
    (void)rank;
    (void)us;
    return false;
  }
  // "device_burn" | "host_sleep" — recorded so analyses can tell which
  // compute simulation produced a record.
  virtual std::string compute_mode() const { return "host_sleep"; }
  // ns per burn iteration once calibrated (0 until then / host executor).
  virtual double burn_ns_per_iter() const { return 0.0; }
  // Executor provenance for the record ("HostExecutor" |
  // "PluginExecutor"): which implementation produced the measured
  // collectives — a host-memory stand-in's numbers must never be read
  // as device-fabric numbers downstream (analysis/bandwidth.py keys
  // its transport column on this).
  virtual std::string executor_kind() const = 0;
};

// Host reference executor: the same CollectiveProgram semantics computed
// in portable C++ (replica_groups and all), plus a simulated executable
// cache so record fields behave identically.  The CI stand-in for the
// plugin — XLA-vs-host agreement on these semantics is pinned by
// tests/test_pjrt_programs.py executing the same generated modules.
class HostExecutor : public CollectiveExecutor {
 public:
  std::string executor_kind() const override { return "HostExecutor"; }

  void run(const CollectiveProgram& prog,
           const std::vector<const void*>& srcs,
           const std::vector<void*>& dsts, DType dtype) override {
    {
      std::lock_guard<std::mutex> lk(m_);
      if (!seen_.insert(prog.cache_key()).second)
        ++hits_;
      else
        ++misses_;
    }
    std::vector<std::vector<int>> groups = prog.groups;
    if (groups.empty()) {
      groups.emplace_back();
      for (int r = 0; r < prog.num_replicas; ++r) groups[0].push_back(r);
    }
    const std::size_t esz = dtype_bytes(dtype);
    const std::int64_t n_in = prog.in_count;
    switch (prog.op) {
      case CollOp::AllReduce:
        for (const auto& g : groups)
          for (std::int64_t i = 0; i < n_in; ++i) {
            float acc = 0.0f;
            for (int r : g) acc += load_element(srcs[r], i, dtype);
            for (int r : g) store_element(dsts[r], i, dtype, acc);
          }
        break;
      case CollOp::AllGather:
        for (const auto& g : groups)
          for (std::size_t k = 0; k < g.size(); ++k)
            for (int r : g)
              std::memcpy(static_cast<char*>(dsts[r]) + k * n_in * esz,
                          srcs[g[k]], n_in * esz);
        break;
      case CollOp::ReduceScatter: {
        for (const auto& g : groups) {
          // a non-divisible count would silently truncate the tail; the
          // reference pads explicitly (fsdp.cpp:251-255) and the schedule
          // layer here does too — the executor must not paper over a
          // caller that didn't
          check_divisible(n_in, g.size(), "ReduceScatter");
          std::int64_t block = n_in / static_cast<std::int64_t>(g.size());
          for (std::size_t k = 0; k < g.size(); ++k)
            for (std::int64_t i = 0; i < block; ++i) {
              float acc = 0.0f;
              for (int r : g)
                acc += load_element(srcs[r], k * block + i, dtype);
              store_element(dsts[g[k]], i, dtype, acc);
            }
        }
        break;
      }
      case CollOp::AllToAll: {
        for (const auto& g : groups) {
          check_divisible(n_in, g.size(), "AllToAll");
          std::int64_t block = n_in / static_cast<std::int64_t>(g.size());
          for (std::size_t p = 0; p < g.size(); ++p)
            for (std::size_t q = 0; q < g.size(); ++q)
              std::memcpy(static_cast<char*>(dsts[g[p]]) + q * block * esz,
                          static_cast<const char*>(srcs[g[q]]) +
                              p * block * esz,
                          block * esz);
        }
        break;
      }
      case CollOp::CollectivePermute: {
        // replicas that are not a target receive zeros (XLA semantics)
        std::vector<bool> targeted(prog.num_replicas, false);
        for (const auto& [s, t] : prog.pairs) targeted[t] = true;
        for (int r = 0; r < prog.num_replicas; ++r)
          if (!targeted[r]) std::memset(dsts[r], 0, n_in * esz);
        for (const auto& [s, t] : prog.pairs)
          std::memcpy(dsts[t], srcs[s], n_in * esz);
        break;
      }
    }
  }

  std::string platform() const override { return "host"; }
  std::size_t cache_hits() const override {
    std::lock_guard<std::mutex> lk(m_);
    return hits_;
  }
  std::size_t cache_misses() const override {
    std::lock_guard<std::mutex> lk(m_);
    return misses_;
  }

 private:
  static void check_divisible(std::int64_t n_in, std::size_t group,
                              const char* op) {
    if (group && n_in % static_cast<std::int64_t>(group) != 0)
      throw std::invalid_argument(
          std::string("HostExecutor ") + op + ": count " +
          std::to_string(n_in) + " not divisible by group size " +
          std::to_string(group) + " (pad the buffer like the schedule "
          "layer does)");
  }

  mutable std::mutex m_;
  std::set<std::string> seen_;
  std::size_t hits_ = 0, misses_ = 0;
};

#ifdef DLNB_HAVE_PJRT
// Real-plugin executor: compile-cache + execute through the PJRT C API.
class PluginExecutor : public CollectiveExecutor {
 public:
  explicit PluginExecutor(const std::string& plugin_path,
                          std::vector<int> device_indices = {})
      : ctx_(plugin_path, std::move(device_indices)) {}

  std::string executor_kind() const override { return "PluginExecutor"; }

  void run(const CollectiveProgram& prog,
           const std::vector<const void*>& srcs,
           const std::vector<void*>& dsts, DType dtype) override {
    PjrtCollectiveRunner{ctx_}.run(prog, srcs, dsts, dtype);
  }

  // Calibrated on-device burn: the per-iteration cost is measured once
  // (on device 0; a fabric's devices are one kind) by differencing two
  // trip counts, cancelling dispatch and loop overheads — the same
  // two-point scheme as the JAX tier (proxies/burn.py calibrate()).
  bool device_burn(int rank, double us) override {
    if (us <= 0) return true;
    if (rank < 0 || rank >= ctx_.num_devices()) {
      // caller will host-sleep instead; the record must not claim pure
      // device burn if that ever happens (unreachable via proxy_runner,
      // which sizes the executor to the world, but PluginExecutor is
      // also a library API)
      fell_back_.store(true, std::memory_order_relaxed);
      return false;
    }
    calibrate_once();
    auto iters = static_cast<std::int32_t>(
        std::max(1.0, std::round(us * 1000.0 / ns_per_iter_)));
    ctx_.run_burn(rank, iters, kBurnWidth);
    return true;
  }
  std::string compute_mode() const override {
    return fell_back_.load(std::memory_order_relaxed)
               ? "device_burn+host_sleep"
               : "device_burn";
  }
  double burn_ns_per_iter() const override { return ns_per_iter_; }

  int num_devices() const { return ctx_.num_devices(); }
  std::string platform() const override {
    return const_cast<PjrtContext&>(ctx_).platform_name();
  }
  std::size_t cache_hits() const override { return ctx_.cache_hits(); }
  std::size_t cache_misses() const override { return ctx_.cache_misses(); }

 private:
  static constexpr int kBurnWidth = 256;  // proxies/burn.py DEFAULT_SHAPE

  void calibrate_once() {
    std::call_once(calibrated_, [&] {
      ctx_.run_burn(0, 1, kBurnWidth);  // compile + warm the dispatch path
      const std::int32_t lo = 64, hi = 512;
      auto time_iters = [&](std::int32_t n) {
        auto t0 = std::chrono::steady_clock::now();
        ctx_.run_burn(0, n, kBurnWidth);
        return std::chrono::duration<double, std::nano>(
                   std::chrono::steady_clock::now() - t0)
            .count();
      };
      time_iters(lo);  // second warmup (buffer path now resident)
      double t_lo = time_iters(lo), t_hi = time_iters(hi);
      double nspi = (t_hi - t_lo) / static_cast<double>(hi - lo);
      // guard against clock jitter producing a nonpositive slope
      ns_per_iter_ = nspi > 0 ? nspi : std::max(t_hi / hi, 1.0);
    });
  }

  PjrtContext ctx_;
  std::once_flag calibrated_;
  double ns_per_iter_ = 0.0;
  mutable std::atomic<bool> fell_back_{false};
};
#endif  // DLNB_HAVE_PJRT

namespace pjrtfab {

enum class Op : int {
  Allreduce, Allgather, ReduceScatterBlock, Alltoall, RingShift, Barrier
};

// All world participants arrive with their (op, count, src, dst); the
// LAST arriver executes the fused multi-group program exactly once;
// everyone departs only after execution completed (blocking-collective
// semantics).  Mismatched op/count/extra across ranks aborts the round.
class ExecRendezvous {
 public:
  explicit ExecRendezvous(int n) : n_(n), srcs_(n), dsts_(n) {}

  using ExecFn = std::function<void(Op, std::int64_t,
                                    const std::vector<const void*>&,
                                    const std::vector<void*>&)>;

  void collective(int idx, Op op, std::int64_t count, std::int64_t extra,
                  const void* src, void* dst, const ExecFn& exec) {
    std::unique_lock<std::mutex> lk(m_);
    std::uint64_t my_gen = gen_;
    srcs_[idx] = src;
    dsts_[idx] = dst;
    if (arrived_ == 0) {
      op_ = op;
      count_ = count;
      extra_ = extra;
    } else if (op_ != op || count_ != count || extra_ != extra) {
      mismatch_ = true;
    }
    if (++arrived_ == n_) {
      if (!mismatch_ && op_ != Op::Barrier) {
        lk.unlock();
        try {
          exec(op, count, srcs_, dsts_);
        } catch (...) {
          lk.lock();
          error_ = std::current_exception();
          lk.unlock();
        }
        lk.lock();
      }
      exec_done_ = true;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] {
        return gen_ == my_gen && arrived_ == n_ && exec_done_;
      });
    }
    bool bad = mismatch_;
    std::exception_ptr err = error_;
    if (++departed_ == n_) {
      arrived_ = 0;
      departed_ = 0;
      mismatch_ = false;
      exec_done_ = false;
      error_ = nullptr;
      ++gen_;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] { return gen_ != my_gen; });
    }
    lk.unlock();
    if (bad)
      throw std::runtime_error(
          "pjrt collective mismatch: world ranks disagree on op/count — "
          "every rank must reach the same collective (XLA SPMD constraint)");
    if (err) std::rethrow_exception(err);
  }

 private:
  int n_;
  std::mutex m_;
  std::condition_variable cv_;
  std::vector<const void*> srcs_;
  std::vector<void*> dsts_;
  int arrived_ = 0;
  int departed_ = 0;
  bool exec_done_ = false;
  bool mismatch_ = false;
  Op op_ = Op::Barrier;
  std::int64_t count_ = 0;
  std::int64_t extra_ = 0;
  std::exception_ptr error_;
  std::uint64_t gen_ = 0;
};

// One communicator split's shared state: the full partition of world
// ranks into color groups (the module's replica_groups), per-slot
// rendezvous, and per-group host mailboxes for p2p.
struct GroupSet {
  // `colors[r]` = color of world rank r; groups ordered by color,
  // members ascending world rank (MPI_Comm_split with key = rank).
  GroupSet(const std::vector<int>& colors, int num_slots) {
    std::map<int, std::vector<int>> by_color;
    int world = static_cast<int>(colors.size());
    for (int r = 0; r < world; ++r) by_color[colors[r]].push_back(r);
    group_of.resize(world);
    grank_of.resize(world);
    for (auto& [c, members] : by_color) {
      int gi = static_cast<int>(groups.size());
      for (std::size_t k = 0; k < members.size(); ++k) {
        group_of[members[k]] = gi;
        grank_of[members[k]] = static_cast<int>(k);
      }
      groups.push_back(members);
      mailboxes.push_back(std::make_unique<shm::Mailboxes>());
    }
    std::size_t gsize = groups[0].size();
    for (const auto& g : groups)
      if (g.size() != gsize)
        throw std::runtime_error(
            "pjrt split: unequal color-group sizes (replica_groups must be "
            "uniform)");
    for (int i = 0; i <= num_slots; ++i)
      rendezvous.push_back(std::make_unique<ExecRendezvous>(world));
  }

  int world_size() const { return static_cast<int>(group_of.size()); }
  int group_size() const { return static_cast<int>(groups[0].size()); }

  std::vector<std::vector<int>> groups;
  std::vector<int> group_of;   // world rank -> group index
  std::vector<int> grank_of;   // world rank -> rank within group
  std::vector<std::unique_ptr<ExecRendezvous>> rendezvous;
  std::vector<std::unique_ptr<shm::Mailboxes>> mailboxes;
};

}  // namespace pjrtfab

// Per-rank view of one group set — implements ProxyCommunicator on the
// PJRT execution model.
class PjrtCommunicator : public ProxyCommunicator {
 public:
  PjrtCommunicator(std::shared_ptr<pjrtfab::GroupSet> set,
                   CollectiveExecutor* exec, int world_rank, DType dtype,
                   int num_slots, std::string name)
      : set_(std::move(set)),
        exec_(exec),
        wrank_(world_rank),
        dtype_(dtype),
        num_slots_(num_slots),
        name_(std::move(name)),
        workers_(num_slots) {}

  ~PjrtCommunicator() override {
    for (auto& w : workers_) w.stop();
  }

  int rank() const override { return set_->grank_of[wrank_]; }
  int size() const override { return set_->group_size(); }
  std::string name() const override { return name_; }
  DType dtype() const override { return dtype_; }

  // ---- blocking collectives ----
  void Allreduce(const void* src, void* dst, std::int64_t count) override {
    run_collective(num_slots_, pjrtfab::Op::Allreduce, count, 0, src, dst);
  }
  void Allgather(const void* src, void* dst, std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::Allgather, cpr, 0, src, dst);
  }
  void ReduceScatterBlock(const void* src, void* dst,
                          std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::ReduceScatterBlock, cpr, 0, src,
                   dst);
  }
  void Alltoall(const void* src, void* dst, std::int64_t cpr) override {
    run_collective(num_slots_, pjrtfab::Op::Alltoall, cpr, 0, src, dst);
  }
  void Barrier() override {
    run_collective(num_slots_, pjrtfab::Op::Barrier, 0, 0, nullptr, nullptr);
  }
  void RingShift(const void* src, void* dst, std::int64_t count,
                 int shift = 1) override {
    run_collective(num_slots_, pjrtfab::Op::RingShift, count, shift, src,
                   dst);
  }

  // ---- p2p: host mailbox rendezvous (see header comment) ----
  void Send(const void* src, std::int64_t count, int dst_rank,
            int tag = 0) override {
    mailbox().send(rank(), dst_rank, tag, src,
                   count * dtype_bytes(dtype_));
  }
  void Recv(void* dst, std::int64_t count, int src_rank,
            int tag = 0) override {
    mailbox().recv(src_rank, rank(), tag, dst,
                   count * dtype_bytes(dtype_));
  }

  // ---- nonblocking, slot-indexed ----
  void Iallreduce(const void* src, void* dst, std::int64_t count,
                  int slot) override {
    enqueue(slot, [=] {
      run_collective(slot, pjrtfab::Op::Allreduce, count, 0, src, dst);
    });
  }
  void Iallgather(const void* src, void* dst, std::int64_t cpr,
                  int slot) override {
    enqueue(slot, [=] {
      run_collective(slot, pjrtfab::Op::Allgather, cpr, 0, src, dst);
    });
  }
  void Isend(const void* src, std::int64_t count, int dst_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] {
      mailbox().send(rank(), dst_rank, t, src, count * dtype_bytes(dtype_));
    });
  }
  void Irecv(void* dst, std::int64_t count, int src_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] {
      mailbox().recv(src_rank, rank(), t, dst, count * dtype_bytes(dtype_));
    });
  }
  void Wait(int slot) override {
    try {
      worker(slot).wait();
    } catch (...) {
      shm::quiesce(workers_);
      throw;
    }
  }
  void WaitAll(int num_slots) override {
    for (int i = 0; i < num_slots && i < num_slots_; ++i) {
      try {
        workers_[i].wait();
      } catch (...) {
        shm::quiesce(workers_);
        throw;
      }
    }
  }

 private:
  shm::Mailboxes& mailbox() {
    return *set_->mailboxes[set_->group_of[wrank_]];
  }
  shm::SlotWorker& worker(int slot) {
    if (slot < 0 || slot >= num_slots_)
      throw std::out_of_range("slot " + std::to_string(slot) +
                              " out of range (num_slots=" +
                              std::to_string(num_slots_) + ")");
    return workers_[slot];
  }
  void enqueue(int slot, std::function<void()> fn) {
    worker(slot).enqueue(std::move(fn));
  }

  // Map the imperative call onto one whole-world program: user count ->
  // per-replica module in_count per op, color groups -> replica_groups.
  void run_collective(int slot, pjrtfab::Op op, std::int64_t user_count,
                      std::int64_t extra, const void* src, void* dst) {
    const std::int64_t g = set_->group_size();
    std::int64_t in_count = user_count;
    CollOp cop = CollOp::AllReduce;
    switch (op) {
      case pjrtfab::Op::Allreduce:
        cop = CollOp::AllReduce;
        break;
      case pjrtfab::Op::Allgather:
        cop = CollOp::AllGather;  // out = in * G
        break;
      case pjrtfab::Op::ReduceScatterBlock:
        cop = CollOp::ReduceScatter;  // src holds G blocks
        in_count = user_count * g;
        break;
      case pjrtfab::Op::Alltoall:
        cop = CollOp::AllToAll;  // src/dst hold G blocks
        in_count = user_count * g;
        break;
      case pjrtfab::Op::RingShift:
        cop = CollOp::CollectivePermute;
        break;
      case pjrtfab::Op::Barrier:
        break;
    }
    auto* exec = exec_;
    auto* set = set_.get();
    DType dt = dtype_;
    set_->rendezvous[slot]->collective(
        wrank_, op, user_count, extra, src, dst,
        [exec, set, cop, in_count, dt, extra](
            pjrtfab::Op o, std::int64_t, const std::vector<const void*>& srcs,
            const std::vector<void*>& dsts) {
          CollectiveProgram prog;
          prog.op = cop;
          prog.dtype = dt;
          prog.in_count = in_count;
          prog.num_replicas = set->world_size();
          if (o == pjrtfab::Op::RingShift) {
            // per-group rotation pairs: member k -> member (k+shift) mod G
            for (const auto& grp : set->groups) {
              int G = static_cast<int>(grp.size());
              int s = ((static_cast<int>(extra) % G) + G) % G;
              for (int k = 0; k < G; ++k)
                prog.pairs.emplace_back(grp[k], grp[(k + s) % G]);
            }
          } else {
            prog.groups = set->groups;
          }
          exec->run(prog, srcs, dsts, dt);
        });
  }

  std::shared_ptr<pjrtfab::GroupSet> set_;
  CollectiveExecutor* exec_;
  int wrank_;
  DType dtype_;
  int num_slots_;
  std::string name_;
  std::vector<shm::SlotWorker> workers_;
};

// The world: owns the executor, spawns rank threads, arbitrates splits.
class PjrtFabric : public Fabric {
 public:
  PjrtFabric(int world_size, DType dtype,
             std::unique_ptr<CollectiveExecutor> exec, int num_slots = 32)
      : world_size_(world_size),
        dtype_(dtype),
        num_slots_(num_slots),
        exec_(std::move(exec)) {
    if (world_size <= 0) throw std::invalid_argument("world_size must be > 0");
    world_set_ = std::make_shared<pjrtfab::GroupSet>(
        std::vector<int>(world_size, 0), num_slots_);
  }

  int world_size() const override { return world_size_; }
  DType dtype() const override { return dtype_; }
  std::string backend() const override { return "pjrt"; }
  CollectiveExecutor& executor() { return *exec_; }
  const CollectiveExecutor& executor() const { return *exec_; }

  std::unique_ptr<ProxyCommunicator> world_comm(int rank) override {
    return std::make_unique<PjrtCommunicator>(world_set_, exec_.get(), rank,
                                              dtype_, num_slots_,
                                              "pjrt_world");
  }

  std::unique_ptr<ProxyCommunicator> split(
      int world_rank, int color, const std::string& name) override {
    std::shared_ptr<pjrtfab::GroupSet> set;
    std::uint64_t seq;
    {
      std::unique_lock<std::mutex> lk(split_m_);
      if (split_arrived_ == 0) split_colors_.assign(world_size_, 0);
      split_colors_[world_rank] = color;
      seq = split_seq_;
      if (++split_arrived_ == world_size_) {
        // even on failure the round must complete (reset + bump + notify)
        // or the other ranks wait forever
        try {
          split_sets_[seq] = std::make_shared<pjrtfab::GroupSet>(
              split_colors_, num_slots_);
        } catch (...) {
          split_sets_[seq] = nullptr;
          split_arrived_ = 0;
          ++split_seq_;
          split_cv_.notify_all();
          throw;
        }
        split_arrived_ = 0;
        ++split_seq_;
        split_cv_.notify_all();
      } else {
        split_cv_.wait(lk, [&] { return split_seq_ > seq; });
      }
      set = split_sets_.at(seq);
    }
    if (!set)
      throw std::runtime_error(
          "pjrt split: group construction failed on another rank");
    return std::make_unique<PjrtCommunicator>(std::move(set), exec_.get(),
                                              world_rank, dtype_, num_slots_,
                                              name);
  }

  void launch(const std::function<void(int)>& body) override {
    std::vector<std::thread> threads;
    std::mutex err_m;
    std::exception_ptr first_error;
    threads.reserve(world_size_);
    for (int r = 0; r < world_size_; ++r)
      threads.emplace_back([&, r] {
        try {
          body(r);
        } catch (...) {
          std::lock_guard<std::mutex> lk(err_m);
          if (!first_error) first_error = std::current_exception();
        }
      });
    for (auto& t : threads) t.join();
    if (first_error) std::rethrow_exception(first_error);
  }

  // Real device cycles when the executor has devices (rank r burns on
  // device r — the replica assignment the collectives use too), host
  // sleep otherwise (host executor in CI).
  void burn(int rank, double us, double time_scale) override {
    double scaled = us * time_scale;
    if (scaled <= 0) return;
    if (!exec_->device_burn(rank, scaled)) burn_us(scaled);
  }

  void describe(Json& meta, Json& mesh) const override {
    meta["backend"] = "pjrt";
    meta["pjrt_executor"] = exec_->platform();
    // the plugin's own platform name ("tpu", "cpu", ...) — never guess,
    // or CPU-plugin runs would be recorded as TPU measurements
    std::string plat = exec_->platform();
    meta["device"] = plat == "host" ? "cpu" : plat;
    meta["p2p_transport"] = "host";
    // executor/transport provenance: which implementation moved the
    // timed bytes, and over what.  A HostExecutor collective is host
    // memory traffic; only the real plugin's collectives ride the
    // device interconnect.  analysis/bandwidth.py surfaces this as the
    // summary table's `transport` column.
    meta["executor"] = exec_->executor_kind();
    meta["transport"] = plat == "host" ? "host" : "ici";
    meta["compute_mode"] = exec_->compute_mode();
    if (exec_->burn_ns_per_iter() > 0)
      meta["burn_ns_per_iter"] = exec_->burn_ns_per_iter();
    meta["cache_hits"] = static_cast<std::int64_t>(exec_->cache_hits());
    meta["cache_misses"] = static_cast<std::int64_t>(exec_->cache_misses());
    mesh["platform"] = exec_->platform();
    mesh["device_kind"] = "pjrt-replica";
  }

 private:
  int world_size_;
  DType dtype_;
  int num_slots_;
  std::unique_ptr<CollectiveExecutor> exec_;
  std::shared_ptr<pjrtfab::GroupSet> world_set_;

  std::mutex split_m_;
  std::condition_variable split_cv_;
  std::vector<int> split_colors_;
  int split_arrived_ = 0;
  std::uint64_t split_seq_ = 0;
  std::map<std::uint64_t, std::shared_ptr<pjrtfab::GroupSet>> split_sets_;
};

// Build the executor for --backend pjrt.  Selection: DLNB_PJRT_EXECUTOR =
// "plugin" | "host" | "auto" (default).  auto prefers the real plugin
// when one is present with enough devices, else falls back to the host
// executor with a stderr note (CI boxes).  `device_indices` is the parsed
// --devices list (reference -d, utils.hpp:62-71).
inline std::unique_ptr<CollectiveExecutor> make_pjrt_executor(
    int world_size, const std::string& plugin_flag,
    const std::vector<int>& device_indices, std::ostream& diag) {
  const char* sel_env = std::getenv("DLNB_PJRT_EXECUTOR");
  std::string sel = sel_env && *sel_env ? sel_env : "auto";
  if (sel == "host") return std::make_unique<HostExecutor>();
#ifdef DLNB_HAVE_PJRT
  std::string plugin =
      !plugin_flag.empty() ? plugin_flag : default_pjrt_plugin_path();
  if (!plugin.empty()) {
    try {
      auto exec = std::make_unique<PluginExecutor>(plugin, device_indices);
      if (exec->num_devices() < world_size)
        throw std::runtime_error(
            "plugin has " + std::to_string(exec->num_devices()) +
            " device(s) for world " + std::to_string(world_size));
      return exec;
    } catch (const std::exception& e) {
      if (sel == "plugin")
        throw std::runtime_error(std::string("pjrt plugin required but "
                                             "unusable: ") +
                                 e.what());
      diag << "pjrt: plugin unusable (" << e.what()
           << ") — using host executor\n";
    }
  } else if (sel == "plugin") {
    throw std::runtime_error(
        "pjrt plugin required but none found (set DLNB_PJRT_PLUGIN)");
  }
#else
  (void)plugin_flag;
  (void)device_indices;
  if (sel == "plugin")
    throw std::runtime_error(
        "pjrt plugin required but this build has no PJRT support "
        "(DLNB_HAVE_PJRT unset)");
#endif
  diag << "pjrt: using host reference executor\n";
  return std::make_unique<HostExecutor>();
}

}  // namespace dlnb
