// ASCII fabric-topology graph, native tier.
//
// Counterpart of the reference's switch-tree visualizer (reference
// cpp/netcommunicators.hpp:79-290), which allgathers per-rank
// SLURM_TOPOLOGY_ADDR dot-paths and draws switch -> node -> process.  On a
// TPU fabric the hierarchy is slice (ICI domain) -> host -> chip; rank
// placement comes from the environment instead of SLURM:
//   DLNB_TOPOLOGY   comma-separated dot-paths, one per rank,
//                   e.g. "s0.h0,s0.h0,s0.h1,s0.h1" (slice.host)
//   otherwise       a synthetic two-level tree is drawn, mirroring the
//                   reference's non-SLURM fallback
//                   (netcommunicators.hpp:148-157).
// Output format matches the Python tier's utils/topology.py tree.
#pragma once

#include <cstdlib>
#include <map>
#include <ostream>
#include <sstream>
#include <string>
#include <vector>

namespace dlnb {

struct RankPlacement {
  std::string slice_name;
  std::string host_name;
  int rank;
};

inline std::vector<std::string> split_csv(const std::string& s, char sep) {
  std::vector<std::string> out;
  std::string cur;
  for (char c : s) {
    if (c == sep) {
      out.push_back(cur);
      cur.clear();
    } else {
      cur += c;
    }
  }
  out.push_back(cur);
  return out;
}

// Resolve per-rank placements from DLNB_TOPOLOGY or synthesize a balanced
// two-level tree (4 ranks per host, 2 hosts per slice by default).
inline std::vector<RankPlacement> resolve_placements(int world_size) {
  std::vector<RankPlacement> out;
  const char* env = std::getenv("DLNB_TOPOLOGY");
  if (env && *env) {
    auto paths = split_csv(env, ',');
    for (int r = 0; r < world_size; ++r) {
      std::string p = r < static_cast<int>(paths.size()) ? paths[r] : "s0.h0";
      auto parts = split_csv(p, '.');
      out.push_back({parts.empty() ? "s0" : parts[0],
                     parts.size() > 1 ? parts[1] : "h0", r});
    }
    return out;
  }
  for (int r = 0; r < world_size; ++r) {
    int host = r / 4;
    int slice = host / 2;
    out.push_back({"slice" + std::to_string(slice),
                   "host" + std::to_string(host), r});
  }
  return out;
}

inline std::string format_topology(int world_size,
                                   const std::string& kind = "shm-rank") {
  auto placements = resolve_placements(world_size);
  // slice -> host -> ranks, insertion-ordered by first appearance
  std::vector<std::string> slice_order;
  std::map<std::string, std::vector<std::string>> host_order;
  std::map<std::string, std::vector<int>> host_ranks;
  for (const auto& p : placements) {
    if (host_ranks.find(p.slice_name + "/" + p.host_name) ==
        host_ranks.end()) {
      if (host_order.find(p.slice_name) == host_order.end())
        slice_order.push_back(p.slice_name);
      host_order[p.slice_name].push_back(p.host_name);
    }
    host_ranks[p.slice_name + "/" + p.host_name].push_back(p.rank);
  }

  std::ostringstream os;
  std::size_t n_hosts = host_ranks.size();
  os << "fabric: " << world_size << " x " << kind << " (" << n_hosts
     << " host" << (n_hosts != 1 ? "s" : "") << ", " << slice_order.size()
     << " slice" << (slice_order.size() != 1 ? "s" : "")
     << (slice_order.size() > 1 ? ", DCN-linked" : "") << ")\n";
  for (std::size_t si = 0; si < slice_order.size(); ++si) {
    const auto& s = slice_order[si];
    bool s_last = si == slice_order.size() - 1;
    const auto& hosts = host_order[s];
    os << (s_last ? "└── " : "├── ") << "slice " << s << "  [ICI domain, "
       << hosts.size() << " host(s)]\n";
    std::string s_pad = s_last ? "    " : "│   ";
    for (std::size_t hi = 0; hi < hosts.size(); ++hi) {
      bool h_last = hi == hosts.size() - 1;
      const auto& ranks = host_ranks[s + "/" + hosts[hi]];
      os << s_pad << (h_last ? "└── " : "├── ") << "host " << hosts[hi]
         << "  (" << ranks.size() << " rank(s))\n";
      std::string h_pad = s_pad + (h_last ? "    " : "│   ");
      for (std::size_t di = 0; di < ranks.size(); ++di) {
        os << h_pad << (di == ranks.size() - 1 ? "└── " : "├── ")
           << "rank id=" << ranks[di] << "\n";
      }
    }
  }
  return os.str();
}

inline void print_topology(int world_size, std::ostream& os,
                           const std::string& kind = "shm-rank") {
  os << format_topology(world_size, kind);
}

}  // namespace dlnb
