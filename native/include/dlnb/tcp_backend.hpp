// Cross-process rank fabric over TCP sockets — the native tier's
// multi-process path.
//
// The reference goes multi-process by launching N MPI ranks and
// bootstrapping vendor communicators over them (reference
// cpp/data_parallel/dp.cpp:166-189: MPI_Init + ncclUniqueId broadcast).
// There is no MPI on a TPU host image, so the rebuild bootstraps the way
// NCCL itself does under the hood: rank 0 listens on a well-known
// address (the ncclUniqueId role), every rank announces itself and its
// own listen port, rank 0 broadcasts the address book, and the ranks
// dial each other into a FULL MESH of pairwise sockets.
//
// Collectives are symmetric (no coordinator in the data path): every
// group member sends its buffer to every other member and reduces
// locally — the same each-rank-computes-its-own-output model as the
// in-process ShmFabric, so the two fabrics are behaviorally
// interchangeable behind the Fabric interface.  Framing carries
// (comm id, slot, sequence, op, element count, tag), a per-peer reader
// thread demultiplexes frames into an inbox, and mismatched op/count
// across ranks aborts with a clear error instead of hanging.
//
// Communicator splits need no extra round-trips: colors are allgathered
// over the world communicator and every process derives the same group
// memberships and the same new comm id (splits are collective and
// ordered, exactly MPI_Comm_split's contract).
#pragma once

#include <arpa/inet.h>
#include <ifaddrs.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <exception>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "dlnb/communicator.hpp"
#include "dlnb/fabric.hpp"
#include "dlnb/fault_plan.hpp"
#include "dlnb/shm_backend.hpp"  // SlotWorker (stream-per-slot discipline)
#include "dlnb/tensor.hpp"

namespace dlnb {
namespace tcp {

// ------------------------------------------------------------- framing
// Bye is the clean-goodbye frame a departing fabric sends every peer
// before closing its sockets: it lets the receiver distinguish "rank X
// finished its run and left" (everything X was supposed to send is
// already ordered before the Bye) from "rank X died mid-run" (frames
// may be lost) — the distinction the transitive ring-dependency check
// needs to avoid false-positive aborts when a fast rank legitimately
// exits while slower ranks are still mid-collective.
enum class FrameKind : std::uint32_t { Coll = 1, P2P = 2, Bye = 3 };

struct FrameHeader {
  std::uint32_t kind;     // FrameKind
  std::uint32_t comm_id;  // 0 = world; splits count up identically everywhere
  std::uint32_t slot;     // slot index (num_slots = blocking ops' slot)
  std::uint32_t seq;      // per-(comm, slot) sequence number at the sender
  std::uint32_t op;       // OpKind for Coll; tag for P2P
  std::uint32_t src;      // sender's WORLD rank
  std::uint64_t count;    // element count (both Coll and P2P)
  std::uint64_t bytes;    // payload size
};

inline void send_all(int fd, const void* data, std::size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) throw std::runtime_error("tcp: send failed (peer gone?)");
    p += w;
    n -= static_cast<std::size_t>(w);
  }
}

// Deterministic-interleaving test hook: delays this process's FINAL
// allgather-phase ring receive so every peer finishes its ring and
// exits first — the clean-early-exit interleaving the Bye protocol
// exists for.  No-op unless the env var is set (pytest sets it on one
// process only: test_native_tcp_ring_survives_clean_early_exit).
inline void test_delay_final_recv() {
  static const int ms = [] {
    const char* e = std::getenv("DLNB_TEST_RING_FINAL_RECV_DELAY_MS");
    return e && *e ? std::atoi(e) : 0;
  }();
  if (ms > 0) ::usleep(static_cast<useconds_t>(ms) * 1000);
}

inline bool recv_all(int fd, void* data, std::size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r == 0) return false;  // orderly shutdown
    if (r < 0) throw std::runtime_error("tcp: recv failed");
    p += r;
    n -= static_cast<std::size_t>(r);
  }
  return true;
}

// Received frames, keyed for matching.  Collectives match on
// (comm, slot, seq, src); p2p matches on (comm, tag, src) in FIFO order.
//
// Failure is tracked PER PEER: a take waiting on rank X fails only when
// X itself is gone, never because some OTHER rank finished its work and
// exited cleanly.  (The subtle race this kills: rank A blocks on rank
// B's barrier frame, still in flight, while rank C — already done —
// exits; a global error flag would fail A's wait even though B is alive
// and its frame lands a moment later.  Per-peer tracking is sound
// because TCP orders a socket's FIN after its data: by the time we see
// X's EOF, everything X sent US has been pushed.)
class Inbox {
 public:
  struct Frame {
    FrameHeader h;
    std::vector<char> payload;
  };

  void push(Frame f) {
    std::lock_guard<std::mutex> lk(m_);
    frames_.push_back(std::move(f));
    cv_.notify_all();
  }

  // Mark `peer` dead (EOF or reader error); takes waiting on that peer
  // fail after draining any frames it already delivered.
  void fail(int peer, const std::string& why) {
    std::lock_guard<std::mutex> lk(m_);
    dead_.emplace(peer, why);
    cv_.notify_all();
  }

  // Mark `peer` cleanly departed (Bye frame): everything it owed the
  // fabric was sent before the Bye, so waits that merely DEPEND on it
  // transitively must keep waiting (their data rides other, still-alive
  // ranks), while a direct wait for one of its frames that never
  // matched is a protocol desync and must error rather than hang.
  void depart(int peer) {
    std::lock_guard<std::mutex> lk(m_);
    departed_.emplace(peer);
    cv_.notify_all();
  }

  // Blocking take of the first frame matching `pred`, which must only
  // accept frames from world rank `want_src` (all matching here is
  // per-source).  Queued frames are matched BEFORE the death flag is
  // consulted, so an op whose frames already landed still completes.
  // `also_dep` lists ranks the awaited frame TRANSITIVELY depends on
  // (a ring step's data has passed through every group member): their
  // DEATH fails the wait too, even though want_src itself is alive —
  // otherwise a mid-ring death would hang non-neighbors until the
  // failure cascaded around the ring via process exits.  A CLEAN
  // departure of a dep rank does NOT fail the wait: the departed rank
  // finished its contribution before leaving, so the awaited frame is
  // still coming from the (alive) want_src.
  template <typename Pred>
  Frame take(int want_src, const Pred& pred,
             const std::vector<int>& also_dep = {}) {
    std::unique_lock<std::mutex> lk(m_);
    std::deque<Frame>::iterator it;
    auto find = [&] {
      for (it = frames_.begin(); it != frames_.end(); ++it)
        if (pred(it->h)) return true;
      return false;
    };
    const int* dead_dep = nullptr;  // null at throw time => departed src
    auto failed = [&] {
      dead_dep = nullptr;
      if (dead_.count(want_src)) {
        dead_dep = &want_src;
        return true;
      }
      if (departed_.count(want_src)) return true;
      for (const int& d : also_dep)
        if (dead_.count(d)) {
          dead_dep = &d;
          return true;
        }
      return false;
    };
    cv_.wait(lk, [&] { return find() || failed(); });
    if (!find()) {
      if (dead_dep)
        throw std::runtime_error("tcp fabric: " + dead_.at(*dead_dep));
      throw std::runtime_error(
          "tcp fabric: rank " + std::to_string(want_src) +
          " finished its run and left, but a frame expected from it "
          "never arrived (collective schedules desynchronized?)");
    }
    Frame f = std::move(*it);
    frames_.erase(it);
    return f;
  }

 private:
  std::mutex m_;
  std::condition_variable cv_;
  std::deque<Frame> frames_;
  std::map<int, std::string> dead_;
  std::set<int> departed_;
};

}  // namespace tcp

class TcpFabric;

// One process's view of a communicator group over the TCP mesh.
class TcpCommunicator : public ProxyCommunicator {
 public:
  TcpCommunicator(TcpFabric* fab, std::uint32_t comm_id,
                  std::vector<int> members, int world_rank, DType dtype,
                  int num_slots, std::string name)
      : fab_(fab),
        comm_id_(comm_id),
        members_(std::move(members)),
        wrank_(world_rank),
        dtype_(dtype),
        num_slots_(num_slots),
        name_(std::move(name)),
        seq_(num_slots + 1, 0),
        workers_(num_slots) {
    for (std::size_t i = 0; i < members_.size(); ++i)
      if (members_[i] == wrank_) grank_ = static_cast<int>(i);
  }

  ~TcpCommunicator() override {
    for (auto& w : workers_) w.stop();
  }

  int rank() const override { return grank_; }
  int size() const override { return static_cast<int>(members_.size()); }
  std::string name() const override { return name_; }
  DType dtype() const override { return dtype_; }

  void Allreduce(const void* src, void* dst, std::int64_t count) override {
    collective(num_slots_, shm::OpKind::Allreduce, count, src, dst);
  }
  void Allgather(const void* src, void* dst, std::int64_t cpr) override {
    collective(num_slots_, shm::OpKind::Allgather, cpr, src, dst);
  }
  void ReduceScatterBlock(const void* src, void* dst,
                          std::int64_t cpr) override {
    collective(num_slots_, shm::OpKind::ReduceScatterBlock, cpr, src, dst);
  }
  void Alltoall(const void* src, void* dst, std::int64_t cpr) override {
    collective(num_slots_, shm::OpKind::Alltoall, cpr, src, dst);
  }
  void Barrier() override {
    collective(num_slots_, shm::OpKind::Barrier, 0, nullptr, nullptr);
  }

  void Send(const void* src, std::int64_t count, int dst_rank,
            int tag = 0) override;
  void Recv(void* dst, std::int64_t count, int src_rank,
            int tag = 0) override;

  void Iallreduce(const void* src, void* dst, std::int64_t count,
                  int slot) override {
    enqueue(slot, [=] {
      collective(slot, shm::OpKind::Allreduce, count, src, dst);
    });
  }
  void Iallgather(const void* src, void* dst, std::int64_t cpr,
                  int slot) override {
    enqueue(slot, [=] {
      collective(slot, shm::OpKind::Allgather, cpr, src, dst);
    });
  }
  void Isend(const void* src, std::int64_t count, int dst_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] { Send(src, count, dst_rank, t); });
  }
  void Irecv(void* dst, std::int64_t count, int src_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] { Recv(dst, count, src_rank, t); });
  }
  void Wait(int slot) override {
    try {
      worker(slot).wait();
    } catch (...) {
      shm::quiesce(workers_);
      throw;
    }
  }
  void WaitAll(int num_slots) override {
    for (int i = 0; i < num_slots && i < num_slots_; ++i) {
      try {
        workers_[i].wait();
      } catch (...) {
        shm::quiesce(workers_);
        throw;
      }
    }
  }

 private:
  friend class TcpFabric;
  void collective(int slot, shm::OpKind op, std::int64_t count,
                  const void* src, void* dst);
  void ring_allreduce(int slot, std::int64_t count, const void* src,
                      void* dst);

  shm::SlotWorker& worker(int slot) {
    if (slot < 0 || slot >= num_slots_)
      throw std::out_of_range("slot " + std::to_string(slot) +
                              " out of range");
    return workers_[slot];
  }
  void enqueue(int slot, std::function<void()> fn) {
    worker(slot).enqueue(std::move(fn));
  }

  TcpFabric* fab_;
  std::uint32_t comm_id_;
  std::vector<int> members_;  // world ranks, ascending (group rank order)
  int wrank_;
  int grank_ = 0;
  DType dtype_;
  int num_slots_;
  std::string name_;
  std::vector<std::uint32_t> seq_;  // per-slot collective sequence
  std::mutex seq_m_;
  std::vector<shm::SlotWorker> workers_;
};

// The world: bootstrap, pairwise sockets, reader threads, comm registry.
class TcpFabric : public Fabric {
 public:
  // Rank 0 listens on `coordinator` ("host:port"); everyone else dials
  // it.  After the address-book exchange all ranks hold one socket per
  // peer.  One fabric = one process = one rank (the MPI model).
  TcpFabric(const std::string& coordinator, int world_size, int rank,
            DType dtype, int num_slots = 32)
      : world_(world_size),
        rank_(rank),
        dtype_(dtype),
        num_slots_(num_slots),
        fds_(world_size, -1) {
    if (world_size <= 0 || rank < 0 || rank >= world_size)
      throw std::invalid_argument("tcp fabric: bad world/rank");
    // NOTE: the override must be set identically on every process — the
    // algorithm choice is part of the collective's wire protocol
    if (const char* env = std::getenv("DLNB_TCP_RING_THRESHOLD");
        env && *env)
      ring_threshold_bytes_ = static_cast<std::size_t>(std::stoll(env));
    if (world_size > 1) bootstrap(coordinator);
    // Transport provenance from the CONNECTED peer sockets (not the
    // coordinator string, which could be a hostname resolving to
    // loopback): the mesh is loopback only when every peer is THIS
    // machine — a 127/8 (or ::1) address, or one of this host's own
    // interface addresses (co-hosted ranks dialing the eth0 IP still
    // move kernel memory, not wire bytes).  This classifies uniformly
    // across processes — co-hosted worlds see all-local peers
    // everywhere, and in any world with a remote host the full mesh
    // gives EVERY process a remote peer — so the per-process records a
    // multi-host merge compares always agree.
    for (int fd : fds_)
      if (fd >= 0 && !fd_peer_is_local(fd)) {
        loopback_ = false;
        break;
      }
    for (int r = 0; r < world_; ++r)
      if (r != rank_) start_reader(r);
  }

  static bool sockaddr_is_loopback(const sockaddr_storage& ss) {
    if (ss.ss_family == AF_INET) {
      const auto& a = reinterpret_cast<const sockaddr_in&>(ss);
      return (ntohl(a.sin_addr.s_addr) >> 24) == 127;
    }
    if (ss.ss_family == AF_INET6) {
      const auto& a6 = reinterpret_cast<const sockaddr_in6&>(ss);
      if (IN6_IS_ADDR_LOOPBACK(&a6.sin6_addr)) return true;
      if (IN6_IS_ADDR_V4MAPPED(&a6.sin6_addr))
        return a6.sin6_addr.s6_addr[12] == 127;  // ::ffff:127.x.y.z
    }
    return false;
  }

  static bool fd_peer_is_local(int fd) {
    sockaddr_storage ss{};
    socklen_t len = sizeof ss;
    if (::getpeername(fd, reinterpret_cast<sockaddr*>(&ss), &len) != 0)
      return true;  // unknowable: never over-credit as network physics
    if (sockaddr_is_loopback(ss)) return true;
    // same-host via a non-loopback interface address: compare against
    // this machine's own addresses (kernel-routed either way)
    ifaddrs* ifs = nullptr;
    bool local = false;
    if (::getifaddrs(&ifs) == 0) {
      for (const ifaddrs* i = ifs; i; i = i->ifa_next) {
        if (!i->ifa_addr || i->ifa_addr->sa_family != ss.ss_family)
          continue;
        if (ss.ss_family == AF_INET) {
          const auto* ia = reinterpret_cast<const sockaddr_in*>(i->ifa_addr);
          if (ia->sin_addr.s_addr ==
              reinterpret_cast<const sockaddr_in&>(ss).sin_addr.s_addr) {
            local = true;
            break;
          }
        } else if (ss.ss_family == AF_INET6) {
          const auto* ia6 =
              reinterpret_cast<const sockaddr_in6*>(i->ifa_addr);
          if (std::memcmp(&ia6->sin6_addr,
                          &reinterpret_cast<const sockaddr_in6&>(ss)
                               .sin6_addr,
                          sizeof(in6_addr)) == 0) {
            local = true;
            break;
          }
        }
      }
      ::freeifaddrs(ifs);
    }
    return local;
  }

  ~TcpFabric() override {
    // clean goodbye first (FrameKind::Bye): TCP orders it after every
    // data frame this process sent, so peers can tell "finished and
    // left" from "died mid-run" — a slower rank must keep waiting for
    // frames from STILL-ALIVE ranks after a fast rank legitimately
    // exits (the ring's transitive-dependency check would otherwise
    // false-positive on the Bye'd rank's EOF).  But ONLY on clean
    // completion: if this destructor runs during exception unwinding,
    // the rank is DYING mid-run, and advertising that as a clean
    // departure would disarm the transitive (also_dep) fail-fast on
    // every waiter — failure would then surface only as a serial
    // cascade of direct-wait desync errors masking the real cause
    // (advisor r4).  Skipping the Bye lets peers see the EOF for what
    // it is: a death.  ``uncaught_exceptions()`` is THREAD-LOCAL: when
    // the failing rank's exception was caught on another thread (a
    // launch wrapper storing it to rethrow, a test harness swallowing
    // it) and the fabric is destroyed later on the main thread, the
    // count here reads 0 — so the rank-thread exception handlers also
    // latch the ``dying_`` flag, and a dying fabric never says Bye
    // regardless of which thread runs the destructor (advisor r5).
    if (std::uncaught_exceptions() == 0 &&
        !dying_.load(std::memory_order_acquire)) {
      for (int r = 0; r < world_; ++r) {
        if (r == rank_ || fds_[r] < 0) continue;
        tcp::FrameHeader h{};
        h.kind = static_cast<std::uint32_t>(tcp::FrameKind::Bye);
        h.src = static_cast<std::uint32_t>(rank_);
        try {
          send_frame(r, h, nullptr);
        } catch (...) {
          // peer already gone: nothing to tell it
        }
      }
    }
    closing_.store(true, std::memory_order_release);
    for (int fd : fds_)
      if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
    for (auto& t : readers_)
      if (t.joinable()) t.join();
    for (int fd : fds_)
      if (fd >= 0) ::close(fd);
  }

  int world_size() const override { return world_; }
  int rank() const { return rank_; }
  DType dtype() const override { return dtype_; }
  std::string backend() const override { return "tcp"; }

  std::unique_ptr<ProxyCommunicator> world_comm(int /*rank*/) override {
    return std::make_unique<TcpCommunicator>(this, 0, all_ranks(), rank_,
                                             dtype_, num_slots_, "tcp_world");
  }

  // Collective split: colors are allgathered over an internal world
  // communicator; every process derives the same groups and the same
  // comm id (splits are ordered, the MPI_Comm_split contract).
  std::unique_ptr<ProxyCommunicator> split(
      int /*world_rank*/, int color, const std::string& name) override {
    std::vector<std::int32_t> colors(world_);
    {
      // f32 allgather of colors — exact for |color| < 2^24.  NOTE the
      // seq-matching contract: every process must create and use its
      // communicators in the same order (the SPMD/MPI discipline the
      // proxies already follow), since sequence counters are per object.
      Tensor s(1, DType::F32), d(world_, DType::F32);
      s.set(0, static_cast<float>(color));
      TcpCommunicator tmp(this, 0, all_ranks(), rank_, DType::F32,
                          num_slots_, "split_tmp");
      tmp.Allgather(s.data(), d.data(), 1);
      for (int r = 0; r < world_; ++r)
        colors[r] = static_cast<std::int32_t>(d.get(r));
    }
    std::vector<int> members;
    for (int r = 0; r < world_; ++r)
      if (colors[r] == colors[rank_]) members.push_back(r);
    std::uint32_t id = ++next_comm_id_;
    return std::make_unique<TcpCommunicator>(this, id, std::move(members),
                                             rank_, dtype_, num_slots_, name);
  }

  // The rank is dying mid-run: suppress the clean-departure Bye even if
  // the destructor later runs on a thread with no in-flight exception.
  void mark_dying() { dying_.store(true, std::memory_order_release); }

  // Fault-plan crash path (one process = one rank): a scripted death
  // must look exactly like a real one — no Bye, so every peer reads the
  // EOF as a mid-run death and the transitive fail-fast fires.
  void mark_rank_dead(int /*world_rank*/) override { mark_dying(); }

  // One process = one rank: body runs once, in this thread.
  void launch(const std::function<void(int)>& body) override {
    try {
      body(rank_);
    } catch (...) {
      mark_dying();  // fail-fast must survive destruction elsewhere
      throw;
    }
  }

  std::vector<int> local_ranks() const override { return {rank_}; }
  int process_index() const override { return rank_; }

  void describe(Json& meta, Json& mesh) const override {
    meta["backend"] = "tcp";
    meta["device"] = "cpu";
    meta["compute_mode"] = "host_sleep";
    meta["num_processes"] = world_;
    // loopback sockets move kernel memory at memcpy speed; only the
    // ethernet classification is network physics (analysis/bandwidth.py
    // surfaces this as the summary table's `transport` column)
    meta["transport"] = loopback_ ? "tcp:loopback" : "tcp:ethernet";
    // allreduces at/above this many bytes ride the bandwidth-optimal
    // ring (2(n-1)/n x count on the wire); smaller ones and the
    // gather-style ops use the pairwise full mesh (which for
    // allgather/reduce-scatter/alltoall already moves the optimal
    // (n-1)/n x bytes).  analysis/bandwidth.py refuses busbw for
    // allreduce timers below the threshold — full-mesh allreduce moves
    // (n-1) x count and is not an algorithm any real fabric runs.
    meta["tcp_ring_threshold_bytes"] =
        static_cast<std::int64_t>(ring_threshold_bytes_);
    // this process's payload+header bytes actually written to sockets —
    // lets tests pin the algorithm's wire cost without timing flakiness
    meta["tcp_bytes_sent"] = static_cast<std::int64_t>(
        bytes_sent_.load(std::memory_order_relaxed));
    mesh["platform"] = "tcp";
    mesh["device_kind"] = "process-rank";
  }

  std::size_t ring_threshold_bytes() const { return ring_threshold_bytes_; }
  bool loopback() const { return loopback_; }

  // payload+header bytes this process actually wrote to sockets —
  // layered fabrics (hier_fabric.hpp) stamp it into their own records
  std::uint64_t bytes_sent() const {
    return bytes_sent_.load(std::memory_order_relaxed);
  }

  tcp::Inbox& inbox() { return inbox_; }

  // Reserve the next communicator id without creating a communicator —
  // layered fabrics (hier_fabric.hpp) construct TcpCommunicators with
  // explicit member lists and keep ids aligned across processes by
  // allocating in the same deterministic order everywhere.
  std::uint32_t allocate_comm_id() { return ++next_comm_id_; }

  void send_frame(int dst, const tcp::FrameHeader& h, const void* payload) {
    if (dst == rank_) {  // self-delivery (degenerate groups, self-sends)
      tcp::Inbox::Frame f;
      f.h = h;
      f.payload.assign(static_cast<const char*>(payload),
                       static_cast<const char*>(payload) + h.bytes);
      inbox_.push(std::move(f));
      return;
    }
    // fault injection at the transmission point (fault_plan.hpp): drop
    // events model loss + sender-side retransmission — backoff sleeps
    // under policy `retry` (counted into the record), an abort under
    // `fail_fast`; partition events fail sends across the boundary.
    // Applies to every frame this process writes, including the DCN
    // legs a HierFabric routes through this mesh.
    fault::Plan::instance().on_send(rank_, dst);
    std::lock_guard<std::mutex> lk(send_m_[dst]);
    tcp::send_all(fds_[dst], &h, sizeof h);
    if (h.bytes) tcp::send_all(fds_[dst], payload, h.bytes);
    bytes_sent_.fetch_add(sizeof h + h.bytes, std::memory_order_relaxed);
  }

 private:
  std::vector<int> all_ranks() const {
    std::vector<int> all(world_);
    for (int i = 0; i < world_; ++i) all[i] = i;
    return all;
  }

  static int dial(const std::string& host, int port, int timeout_s = 30) {
    // resolve names as well as dotted quads (multi-host address books
    // carry whatever the peer's kernel reported)
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<std::uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      if (::getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res)
        throw std::runtime_error("tcp: cannot resolve " + host);
      addr.sin_addr =
          reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      ::freeaddrinfo(res);
    }
    for (int attempt = 0; attempt < timeout_s * 10; ++attempt) {
      int fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) throw std::runtime_error("tcp: socket() failed");
      if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) ==
          0) {
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        return fd;
      }
      ::close(fd);
      ::usleep(100 * 1000);  // coordinator may not be up yet
    }
    throw std::runtime_error("tcp: cannot reach " + host + ":" +
                             std::to_string(port));
  }

  static int listen_any(int& port_out) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) throw std::runtime_error("tcp: socket() failed");
    int one = 1;
    ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<std::uint16_t>(port_out));
    if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0)
      throw std::runtime_error("tcp: bind failed (port " +
                               std::to_string(port_out) + ")");
    socklen_t len = sizeof addr;
    ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
    port_out = ntohs(addr.sin_port);
    if (::listen(fd, 64) != 0) throw std::runtime_error("tcp: listen failed");
    return fd;
  }

  void bootstrap(const std::string& coordinator) {
    auto colon = coordinator.rfind(':');
    if (colon == std::string::npos)
      throw std::runtime_error("tcp: coordinator must be host:port, got " +
                               coordinator);
    std::string host = coordinator.substr(0, colon);
    int coord_port = std::stoi(coordinator.substr(colon + 1));
    send_m_ = std::vector<std::mutex>(world_);

    // address book entry: the host each rank is reachable at (learned
    // by rank 0 from the accepted connection's peer address — always a
    // routable address, unlike a self-reported hostname) + listen port
    struct Entry {
      char host[64];
      std::int32_t port;
    };

    if (rank_ == 0) {
      // the ncclUniqueId role: accept every rank, note where it dialed
      // from and its own listen port, then broadcast the address book
      int port = coord_port;
      int lfd = listen_any(port);
      std::vector<Entry> book(world_);
      std::memset(book.data(), 0, book.size() * sizeof(Entry));
      for (int n = 1; n < world_; ++n) {
        sockaddr_in peer_addr{};
        socklen_t alen = sizeof peer_addr;
        int fd = ::accept(lfd, reinterpret_cast<sockaddr*>(&peer_addr),
                          &alen);
        if (fd < 0) throw std::runtime_error("tcp: accept failed");
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        std::int32_t hello[2];  // {rank, my own listen port}
        if (!tcp::recv_all(fd, hello, sizeof hello))
          throw std::runtime_error("tcp: rank hello truncated");
        fds_[hello[0]] = fd;
        Entry& e = book[hello[0]];
        ::inet_ntop(AF_INET, &peer_addr.sin_addr, e.host, sizeof e.host);
        e.port = hello[1];
      }
      ::close(lfd);
      for (int r = 1; r < world_; ++r)
        tcp::send_all(fds_[r], book.data(), book.size() * sizeof(Entry));
      // rank 0 reuses its accepted sockets; higher ranks dial each other:
      // rank i accepts from ranks j > i on its own listener
    } else {
      // listen for higher ranks first so the book can be acted on
      int my_port = 0;
      int lfd = listen_any(my_port);
      int fd0 = dial(host, coord_port);
      std::int32_t hello[2] = {static_cast<std::int32_t>(rank_),
                               static_cast<std::int32_t>(my_port)};
      tcp::send_all(fd0, hello, sizeof hello);
      fds_[0] = fd0;
      std::vector<Entry> book(world_);
      if (!tcp::recv_all(fd0, book.data(), book.size() * sizeof(Entry)))
        throw std::runtime_error("tcp: address book truncated");
      // dial every lower-ranked peer (except 0, already connected) AT ITS
      // OWN HOST; accept from every higher-ranked peer
      for (int r = 1; r < rank_; ++r) {
        int fd = dial(book[r].host, book[r].port);
        std::int32_t me = rank_;
        tcp::send_all(fd, &me, sizeof me);
        fds_[r] = fd;
      }
      for (int r = rank_ + 1; r < world_; ++r) {
        int fd = ::accept(lfd, nullptr, nullptr);
        if (fd < 0) throw std::runtime_error("tcp: accept failed");
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        std::int32_t peer;
        if (!tcp::recv_all(fd, &peer, sizeof peer))
          throw std::runtime_error("tcp: peer hello truncated");
        fds_[peer] = fd;
      }
      ::close(lfd);
    }
  }

  void start_reader(int peer) {
    readers_.emplace_back([this, peer] {
      bool bye = false;
      try {
        while (true) {
          tcp::FrameHeader h;
          if (!tcp::recv_all(fds_[peer], &h, sizeof h)) {
            // EOF: silent when the peer said goodbye (clean departure,
            // already recorded) or during our own orderly teardown — a
            // peer dying mid-run must fail waits on THAT peer (its own
            // sent frames were delivered before the FIN), without
            // poisoning waits on still-alive ranks
            if (!bye && !closing_.load(std::memory_order_acquire))
              inbox_.fail(peer, "rank " + std::to_string(peer) +
                                    " disconnected mid-run");
            return;
          }
          if (h.kind == static_cast<std::uint32_t>(tcp::FrameKind::Bye)) {
            bye = true;
            inbox_.depart(peer);
            continue;  // keep draining until the FIN
          }
          tcp::Inbox::Frame f;
          f.h = h;
          f.payload.resize(h.bytes);
          if (h.bytes && !tcp::recv_all(fds_[peer], f.payload.data(), h.bytes))
            throw std::runtime_error("payload truncated");
          inbox_.push(std::move(f));
        }
      } catch (const std::exception& e) {
        // post-Bye socket errors (e.g. an RST racing the FIN) carry no
        // information: everything the peer owed us already arrived
        if (!bye && !closing_.load(std::memory_order_acquire))
          inbox_.fail(peer, std::string("reader for rank ") +
                                std::to_string(peer) + ": " + e.what());
      }
    });
  }

  int world_;
  int rank_;
  DType dtype_;
  int num_slots_;
  std::vector<int> fds_;
  std::vector<std::mutex> send_m_{1};
  std::vector<std::thread> readers_;
  tcp::Inbox inbox_;
  std::atomic<std::uint32_t> next_comm_id_{0};
  std::atomic<bool> closing_{false};
  // set by the rank-thread exception handlers (launch wrappers here and
  // in HierFabric): the destructor must not send Bye for a dying rank
  // even when it runs on a thread whose uncaught_exceptions() is 0
  std::atomic<bool> dying_{false};
  bool loopback_ = true;
  std::size_t ring_threshold_bytes_ = 64 * 1024;
  std::atomic<std::uint64_t> bytes_sent_{0};
};

// ---- TcpCommunicator method bodies needing the fabric ----

inline void TcpCommunicator::Send(const void* src, std::int64_t count,
                                  int dst_rank, int tag) {
  tcp::FrameHeader h{};
  h.kind = static_cast<std::uint32_t>(tcp::FrameKind::P2P);
  h.comm_id = comm_id_;
  h.op = static_cast<std::uint32_t>(tag);
  h.src = static_cast<std::uint32_t>(wrank_);
  h.count = static_cast<std::uint64_t>(count);
  h.bytes = static_cast<std::uint64_t>(count) * dtype_bytes(dtype_);
  fab_->send_frame(members_.at(dst_rank), h, src);
}

inline void TcpCommunicator::Recv(void* dst, std::int64_t count,
                                  int src_rank, int tag) {
  std::uint32_t want_src = static_cast<std::uint32_t>(members_.at(src_rank));
  std::uint32_t want_tag = static_cast<std::uint32_t>(tag);
  std::uint32_t cid = comm_id_;
  auto f = fab_->inbox().take(
      static_cast<int>(want_src), [&](const tcp::FrameHeader& h) {
        return h.kind == static_cast<std::uint32_t>(tcp::FrameKind::P2P) &&
               h.comm_id == cid && h.src == want_src && h.op == want_tag;
      });
  std::size_t want = static_cast<std::size_t>(count) * dtype_bytes(dtype_);
  if (f.payload.size() != want)
    throw std::runtime_error("tcp p2p size mismatch: got " +
                             std::to_string(f.payload.size()) + "B, want " +
                             std::to_string(want) + "B");
  std::memcpy(dst, f.payload.data(), want);
}

inline void TcpCommunicator::collective(int slot, shm::OpKind op,
                                        std::int64_t count, const void* src,
                                        void* dst) {
  // per-rank injected latency (fault_plan.hpp collective-scoped events)
  fault::Plan::instance().on_collective(wrank_);
  const int n = size();
  const std::size_t esz = dtype_bytes(dtype_);
  // Large allreduces ride the bandwidth-optimal ring: the full mesh
  // moves (n-1) x count per rank where a ring moves 2(n-1)/n x count —
  // at n=8 a 4x difference no real fabric's algorithm would show.  The
  // gather-style ops keep the pairwise mesh (already (n-1)/n-optimal);
  // small allreduces stay full-mesh (latency-bound: 1 round trip vs the
  // ring's 2(n-1) serial steps).
  if (op == shm::OpKind::Allreduce && n > 2 &&
      static_cast<std::size_t>(count) * esz >= fab_->ring_threshold_bytes())
    return ring_allreduce(slot, count, src, dst);
  std::uint32_t seq;
  {
    std::lock_guard<std::mutex> lk(seq_m_);
    seq = seq_[static_cast<std::size_t>(slot)]++;
  }
  // payload per op: what the OTHER side needs from us
  std::size_t bytes = 0;
  switch (op) {
    case shm::OpKind::Barrier: bytes = 0; break;
    case shm::OpKind::Allreduce:
    case shm::OpKind::Allgather:
      bytes = static_cast<std::size_t>(count) * esz;  // my full contribution
      break;
    case shm::OpKind::ReduceScatterBlock:
    case shm::OpKind::Alltoall:
      bytes = static_cast<std::size_t>(count) * esz;  // one block per peer
      break;
  }
  tcp::FrameHeader h{};
  h.kind = static_cast<std::uint32_t>(tcp::FrameKind::Coll);
  h.comm_id = comm_id_;
  h.slot = static_cast<std::uint32_t>(slot);
  h.seq = seq;
  h.op = static_cast<std::uint32_t>(op);
  h.src = static_cast<std::uint32_t>(wrank_);
  h.count = static_cast<std::uint64_t>(count);
  const char* me = static_cast<const char*>(src);
  for (int g = 0; g < n; ++g) {
    int peer = members_[g];
    if (peer == wrank_) continue;
    const void* payload = me;
    // scatter-style ops send peer g its own block
    if (op == shm::OpKind::ReduceScatterBlock ||
        op == shm::OpKind::Alltoall)
      payload = me + static_cast<std::size_t>(g) * bytes;
    h.bytes = bytes;
    fab_->send_frame(peer, h, payload);
  }

  // gather everyone's frame for (comm, slot, seq), then combine locally
  std::map<int, std::vector<char>> got;
  for (int g = 0; g < n; ++g) {
    int peer = members_[g];
    if (peer == wrank_) continue;
    std::uint32_t want_src = static_cast<std::uint32_t>(peer);
    auto f = fab_->inbox().take(peer, [&](const tcp::FrameHeader& fh) {
      return fh.kind == static_cast<std::uint32_t>(tcp::FrameKind::Coll) &&
             fh.comm_id == comm_id_ &&
             fh.slot == static_cast<std::uint32_t>(slot) && fh.seq == seq &&
             fh.src == want_src;
    });
    if (static_cast<shm::OpKind>(f.h.op) != op ||
        static_cast<std::int64_t>(f.h.count) != count)
      throw std::runtime_error(
          "tcp collective mismatch: ranks disagree on op/count (got op " +
          std::to_string(f.h.op) + " count " + std::to_string(f.h.count) +
          ", expected op " + std::to_string(static_cast<int>(op)) +
          " count " + std::to_string(count) + ")");
    got[g] = std::move(f.payload);
  }

  switch (op) {
    case shm::OpKind::Barrier:
      break;
    case shm::OpKind::Allreduce: {
      for (std::int64_t i = 0; i < count; ++i) {
        float acc = load_element(src, static_cast<std::size_t>(i), dtype_);
        for (auto& [g, buf] : got)
          acc += load_element(buf.data(), static_cast<std::size_t>(i),
                              dtype_);
        store_element(dst, static_cast<std::size_t>(i), dtype_, acc);
      }
      break;
    }
    case shm::OpKind::Allgather: {
      char* out = static_cast<char*>(dst);
      std::size_t blk = static_cast<std::size_t>(count) * esz;
      std::memcpy(out + static_cast<std::size_t>(grank_) * blk, src, blk);
      for (auto& [g, buf] : got)
        std::memcpy(out + static_cast<std::size_t>(g) * blk, buf.data(),
                    blk);
      break;
    }
    case shm::OpKind::ReduceScatterBlock: {
      // my own block g=grank_ from src, plus each peer's sent block
      const char* mine =
          static_cast<const char*>(src) +
          static_cast<std::size_t>(grank_) * static_cast<std::size_t>(count) *
              esz;
      for (std::int64_t i = 0; i < count; ++i) {
        float acc = load_element(mine, static_cast<std::size_t>(i), dtype_);
        for (auto& [g, buf] : got)
          acc += load_element(buf.data(), static_cast<std::size_t>(i),
                              dtype_);
        store_element(dst, static_cast<std::size_t>(i), dtype_, acc);
      }
      break;
    }
    case shm::OpKind::Alltoall: {
      char* out = static_cast<char*>(dst);
      std::size_t blk = static_cast<std::size_t>(count) * esz;
      std::memcpy(out + static_cast<std::size_t>(grank_) * blk,
                  static_cast<const char*>(src) +
                      static_cast<std::size_t>(grank_) * blk,
                  blk);
      for (auto& [g, buf] : got)
        std::memcpy(out + static_cast<std::size_t>(g) * blk, buf.data(), blk);
      break;
    }
  }
}

// Ring allreduce (the NCCL/ICI algorithm): n-1 reduce-scatter steps —
// each rank passes a partial-sum block to its successor, accumulating
// the block it receives — then n-1 allgather steps rotating the
// completed blocks.  After the first phase rank r owns the fully
// reduced block (r+1) mod n (the standard rotation).  Each step is one
// frame to the successor matched by (comm, slot, seq, src); every rank
// advances the slot's sequence counter by the same 2(n-1), so later
// collectives on the slot stay aligned.  The per-peer reader threads
// drain sockets independently of this rank's send, so a blocking
// send_all can never deadlock against a peer doing the same.
inline void TcpCommunicator::ring_allreduce(int slot, std::int64_t count,
                                            const void* src, void* dst) {
  const int n = size();
  const std::size_t esz = dtype_bytes(dtype_);
  const std::int64_t block = (count + n - 1) / n;
  auto blen = [&](std::int64_t bi) {
    std::int64_t left = count - bi * block;
    return left < 0 ? 0 : (left > block ? block : left);
  };
  if (dst != src)
    std::memcpy(dst, src, static_cast<std::size_t>(count) * esz);
  std::uint32_t base;
  {
    std::lock_guard<std::mutex> lk(seq_m_);
    base = seq_[static_cast<std::size_t>(slot)];
    seq_[static_cast<std::size_t>(slot)] +=
        2 * static_cast<std::uint32_t>(n - 1);
  }
  const int to = members_[(grank_ + 1) % n];
  const int from = members_[(grank_ - 1 + n) % n];

  auto send_block = [&](std::int64_t bi, std::uint32_t seq) {
    tcp::FrameHeader h{};
    h.kind = static_cast<std::uint32_t>(tcp::FrameKind::Coll);
    h.comm_id = comm_id_;
    h.slot = static_cast<std::uint32_t>(slot);
    h.seq = seq;
    h.op = static_cast<std::uint32_t>(shm::OpKind::Allreduce);
    h.src = static_cast<std::uint32_t>(wrank_);
    h.count = static_cast<std::uint64_t>(count);
    std::int64_t len = blen(bi);
    h.bytes = static_cast<std::uint64_t>(len) * esz;
    // a zero-length tail block (count small vs n) must not even FORM the
    // out-of-range bi*block offset pointer — UB the UBSan preset exists
    // to catch; the frame still goes out so seq counters stay aligned
    fab_->send_frame(to, h,
                     len == 0 ? dst
                              : static_cast<const char*>(dst) +
                                    static_cast<std::size_t>(bi) * block *
                                        esz);
  };
  // ring data has passed through every member: any member's death must
  // fail this wait, not just the immediate predecessor's
  std::vector<int> ring_deps;
  for (int m : members_)
    if (m != wrank_ && m != from) ring_deps.push_back(m);
  auto recv_block = [&](std::uint32_t seq) {
    auto f = fab_->inbox().take(
        from,
        [&](const tcp::FrameHeader& fh) {
          return fh.kind ==
                     static_cast<std::uint32_t>(tcp::FrameKind::Coll) &&
                 fh.comm_id == comm_id_ &&
                 fh.slot == static_cast<std::uint32_t>(slot) &&
                 fh.seq == seq &&
                 fh.src == static_cast<std::uint32_t>(from);
        },
        ring_deps);
    if (static_cast<shm::OpKind>(f.h.op) != shm::OpKind::Allreduce ||
        static_cast<std::int64_t>(f.h.count) != count)
      throw std::runtime_error(
          "tcp ring allreduce mismatch: ranks disagree on op/count "
          "(is DLNB_TCP_RING_THRESHOLD set identically everywhere?)");
    return f;
  };

  for (int step = 0; step < n - 1; ++step) {  // reduce-scatter phase
    std::int64_t sb = ((grank_ - step) % n + n) % n;
    std::int64_t rb = ((grank_ - step - 1) % n + n) % n;
    send_block(sb, base + static_cast<std::uint32_t>(step));
    auto f = recv_block(base + static_cast<std::uint32_t>(step));
    std::int64_t len = blen(rb);
    if (f.payload.size() != static_cast<std::size_t>(len) * esz)
      throw std::runtime_error("tcp ring allreduce: block size mismatch");
    if (len == 0) continue;  // zero tail block: no valid rb offset exists
    char* d = static_cast<char*>(dst) +
              static_cast<std::size_t>(rb) * block * esz;
    for (std::int64_t i = 0; i < len; ++i)
      store_element(d, static_cast<std::size_t>(i), dtype_,
                    load_element(d, static_cast<std::size_t>(i), dtype_) +
                        load_element(f.payload.data(),
                                     static_cast<std::size_t>(i), dtype_));
  }
  for (int step = 0; step < n - 1; ++step) {  // allgather phase
    std::int64_t sb = ((grank_ + 1 - step) % n + n) % n;
    std::int64_t rb = ((grank_ - step) % n + n) % n;
    send_block(sb, base + static_cast<std::uint32_t>(n - 1 + step));
    if (step == n - 2) tcp::test_delay_final_recv();
    auto f = recv_block(base + static_cast<std::uint32_t>(n - 1 + step));
    std::int64_t len = blen(rb);
    if (f.payload.size() != static_cast<std::size_t>(len) * esz)
      throw std::runtime_error("tcp ring allreduce: block size mismatch");
    if (len == 0) continue;  // zero tail block: no valid rb offset exists
    std::memcpy(static_cast<char*>(dst) +
                    static_cast<std::size_t>(rb) * block * esz,
                f.payload.data(), static_cast<std::size_t>(len) * esz);
  }
}

}  // namespace dlnb
