// In-process threaded rank fabric — the testable fake backend.
//
// The reference's `mpi_cpu` build config runs every proxy on plain CPU
// buffers over ordinary MPI ranks, which is what makes the whole suite
// runnable on a laptop (reference README.md:96, SURVEY.md §4).  There is
// no MPI on a TPU host image, so the rebuild's equivalent is an
// in-process fabric: N rank *threads* share one `ShmFabric`, collectives
// rendezvous through shared memory, and nonblocking ops run on per-slot
// worker threads — reproducing the NCCL stream-per-request-index
// discipline (reference cpp/proxy_classes.hpp:143-147) with real
// asynchrony, so compute/comm overlap is genuinely exercised in tests.
//
// Collective algorithm: all group members publish (src, dst) into a
// per-(group, slot) Rendezvous; once everyone arrived, each rank computes
// its own output from the published inputs (sum-reduction in float via
// dtype conversion, gather/scatter/alltoall as copies); a second phase
// releases the round.  Mismatched op/count across ranks is detected and
// aborts — the debugging check MPI never gave the reference.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <list>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "dlnb/communicator.hpp"
#include "dlnb/fabric.hpp"
#include "dlnb/fault_plan.hpp"
#include "dlnb/tensor.hpp"

namespace dlnb {

namespace shm {

// ------------------------------------------------------------------ ops
enum class OpKind : int {
  Allreduce, Allgather, ReduceScatterBlock, Alltoall, Barrier
};

// One reusable all-arrive/compute/all-depart synchronization point.
class Rendezvous {
 public:
  explicit Rendezvous(int n) : n_(n), srcs_(n), dsts_(n) {}

  // fn(grank, srcs, dsts) runs on every rank after all pointers are
  // published; inputs stay stable until the last rank departs.
  void collective(
      int grank, OpKind op, std::int64_t count, const void* src, void* dst,
      const std::function<void(int, const std::vector<const void*>&,
                               const std::vector<void*>&)>& fn) {
    std::unique_lock<std::mutex> lk(m_);
    if (aborted_) throw std::runtime_error(abort_why_);
    std::uint64_t my_gen = gen_;
    srcs_[grank] = src;
    dsts_[grank] = dst;
    if (arrived_ == 0) {
      op_ = op;
      count_ = count;
    } else if (op_ != op || count_ != count) {
      mismatch_ = true;
    }
    if (++arrived_ == n_) cv_.notify_all();
    cv_.wait(lk, [&] {
      return aborted_ || (gen_ == my_gen && arrived_ == n_);
    });
    // abort fails ONLY a round that cannot complete (a member died
    // before arriving); a fully-arrived round still runs — otherwise
    // survivors could abandon different rounds and desync
    if (!(gen_ == my_gen && arrived_ == n_))
      throw std::runtime_error(abort_why_);
    bool bad = mismatch_;
    lk.unlock();
    // on mismatch still complete the round (skip the math) so the
    // rendezvous resets and later collectives error instead of hanging
    if (!bad) fn(grank, srcs_, dsts_);
    lk.lock();
    if (++departed_ == n_) {
      arrived_ = 0;
      departed_ = 0;
      mismatch_ = false;
      ++gen_;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] { return aborted_ || gen_ != my_gen; });
      // the round completed (every member, including a subsequently
      // dead one, already departed it) — only a stuck reset aborts
      if (gen_ == my_gen) throw std::runtime_error(abort_why_);
    }
    lk.unlock();
    if (bad)
      throw std::runtime_error(
          "shm collective mismatch: ranks disagree on op/count");
  }

  // Permanently poison the rendezvous: every blocked and future wait
  // throws instead of waiting for a rank that will never arrive (the
  // fail-fast a dead in-process rank needs — a rendezvous group is
  // never reused after a member dies).
  void abort(const std::string& why) {
    std::lock_guard<std::mutex> lk(m_);
    if (aborted_) return;
    aborted_ = true;
    abort_why_ = why;
    cv_.notify_all();
  }

 private:
  int n_;
  std::mutex m_;
  std::condition_variable cv_;
  std::vector<const void*> srcs_;
  std::vector<void*> dsts_;
  int arrived_ = 0;
  int departed_ = 0;
  bool mismatch_ = false;
  bool aborted_ = false;
  std::string abort_why_;
  OpKind op_ = OpKind::Barrier;
  std::int64_t count_ = 0;
  std::uint64_t gen_ = 0;
};

// Synchronous-rendezvous point-to-point mailbox for one group.  The
// sender publishes a pointer and blocks until the receiver copies (NCCL
// send/recv pairing semantics); entries live in a std::list so references
// stay valid while both sides rendezvous, and the sender erases its own
// entry after the ack.  Messages match on (from, to, tag): nonblocking
// ops tag with their slot index and blocking ops with tag 0, so
// concurrent slot workers between the same rank pair never cross-match
// (the stream-per-index discipline, reference proxy_classes.hpp:143-147).
class Mailboxes {
 public:
  void send(int from, int to, int tag, const void* data, std::size_t bytes) {
    std::unique_lock<std::mutex> lk(m_);
    if (aborted_) throw std::runtime_error(abort_why_);
    Key k{from, to, tag};
    auto& box = boxes_[k];
    box.push_back(Msg{data, bytes, false});
    auto mine = std::prev(box.end());
    cv_.notify_all();
    cv_.wait(lk, [&] { return aborted_ || mine->consumed; });
    if (aborted_ && !mine->consumed) {
      box.erase(mine);
      throw std::runtime_error(abort_why_);
    }
    box.erase(mine);
  }

  void recv(int from, int to, int tag, void* out, std::size_t bytes) {
    std::unique_lock<std::mutex> lk(m_);
    Key k{from, to, tag};
    std::list<Msg>::iterator it;
    bool found = false;
    cv_.wait(lk, [&] {
      auto& box = boxes_[k];
      for (it = box.begin(); it != box.end(); ++it)
        if (!it->consumed) {
          found = true;
          return true;
        }
      return aborted_;
    });
    // an already-delivered message still completes (the sender made it
    // before dying); only an empty box aborts
    if (!found) throw std::runtime_error(abort_why_);
    if (it->bytes != bytes)
      throw std::runtime_error("shm p2p size mismatch: send " +
                               std::to_string(it->bytes) + "B vs recv " +
                               std::to_string(bytes) + "B");
    std::memcpy(out, it->data, bytes);
    it->consumed = true;
    cv_.notify_all();
  }

  // Poison the mailbox (dead member): blocked and future p2p throws.
  void abort(const std::string& why) {
    std::lock_guard<std::mutex> lk(m_);
    if (aborted_) return;
    aborted_ = true;
    abort_why_ = why;
    cv_.notify_all();
  }

 private:
  struct Key {
    int from, to, tag;
    bool operator<(const Key& o) const {
      if (from != o.from) return from < o.from;
      if (to != o.to) return to < o.to;
      return tag < o.tag;
    }
  };
  struct Msg {
    const void* data;
    std::size_t bytes;
    bool consumed;
  };
  std::mutex m_;
  std::condition_variable cv_;
  std::map<Key, std::list<Msg>> boxes_;
  bool aborted_ = false;
  std::string abort_why_;
};

// Shared state of one communicator group (all member ranks).
struct Group {
  explicit Group(std::vector<int> world_ranks, int num_slots)
      : members(std::move(world_ranks)), mailboxes() {
    int n = static_cast<int>(members.size());
    // slot rendezvous 0..num_slots-1; extra slot for blocking ops
    for (int i = 0; i <= num_slots; ++i)
      rendezvous.push_back(std::make_unique<Rendezvous>(n));
  }
  std::vector<int> members;  // world ranks, ascending == group rank order
  std::vector<std::unique_ptr<Rendezvous>> rendezvous;
  Mailboxes mailboxes;

  bool contains(int world_rank) const {
    for (int m : members)
      if (m == world_rank) return true;
    return false;
  }

  // A member died: poison every synchronization point so survivors
  // fail fast instead of waiting for a rank that will never arrive.
  void abort_all(const std::string& why) {
    for (auto& r : rendezvous) r->abort(why);
    mailboxes.abort(why);
  }
};

// Single-thread ordered task queue — one per (rank, slot); the analogue of
// one CUDA stream per request index (reference proxy_classes.hpp:143-147).
class SlotWorker {
 public:
  SlotWorker() = default;
  ~SlotWorker() { stop(); }

  void enqueue(std::function<void()> fn) {
    ensure_started();
    {
      std::lock_guard<std::mutex> lk(m_);
      q_.push_back(std::move(fn));
      ++outstanding_;
    }
    cv_.notify_all();
  }

  // Block until every enqueued task has completed (stream synchronize).
  void wait() {
    std::unique_lock<std::mutex> lk(m_);
    cv_done_.wait(lk, [&] { return outstanding_ == 0; });
    if (error_) {
      auto e = error_;
      error_ = nullptr;
      std::rethrow_exception(e);
    }
  }

  void stop() {
    // stop() is invoked concurrently when several error paths quiesce
    // one communicator's SHARED workers at once (e.g. multiple async
    // hier DCN legs failing together): joining the same std::thread
    // from two callers is UB that deadlocks in practice, so stoppers
    // serialize here and late arrivals find started_ already false.
    std::lock_guard<std::mutex> sl(stop_m_);
    {
      std::lock_guard<std::mutex> lk(m_);
      if (!started_) return;
      stopping_ = true;
    }
    cv_.notify_all();
    if (thread_.joinable()) thread_.join();
    std::lock_guard<std::mutex> lk(m_);
    started_ = false;
    stopping_ = false;
  }

 private:
  void ensure_started() {
    std::lock_guard<std::mutex> lk(m_);
    if (started_) return;
    started_ = true;
    thread_ = std::thread([this] { run(); });
  }

  void run() {
    while (true) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_.wait(lk, [&] { return stopping_ || !q_.empty(); });
        if (stopping_ && q_.empty()) return;
        fn = std::move(q_.front());
        q_.pop_front();
      }
      try {
        fn();
      } catch (...) {
        std::lock_guard<std::mutex> lk(m_);
        error_ = std::current_exception();
      }
      {
        std::lock_guard<std::mutex> lk(m_);
        --outstanding_;
      }
      cv_done_.notify_all();
    }
  }

  std::mutex m_;
  std::mutex stop_m_;  // serializes concurrent stop() callers
  std::condition_variable cv_, cv_done_;
  std::deque<std::function<void()>> q_;
  int outstanding_ = 0;
  bool started_ = false;
  bool stopping_ = false;
  std::exception_ptr error_;
  std::thread thread_;
};

// Stop every worker, completing any in-flight task first.  Wait paths
// call this before rethrowing a slot's error so no sibling worker is
// still touching caller-owned buffers while the error unwinds them (the
// use-after-free window otherwise opened by one async op failing while
// others run).  stop() is restartable: later enqueues bring a worker
// back.
inline void quiesce(std::vector<SlotWorker>& workers) {
  for (auto& w : workers) w.stop();
}


}  // namespace shm

class ShmFabric;

// Per-rank view of a group — implements ProxyCommunicator.
class ShmCommunicator : public ProxyCommunicator {
 public:
  ShmCommunicator(std::shared_ptr<shm::Group> group, int group_rank,
                  DType dtype, int num_slots, std::string name)
      : group_(std::move(group)),
        grank_(group_rank),
        dtype_(dtype),
        num_slots_(num_slots),
        name_(std::move(name)),
        workers_(num_slots) {}

  ~ShmCommunicator() override {
    for (auto& w : workers_) w.stop();
  }

  int rank() const override { return grank_; }
  int size() const override {
    return static_cast<int>(group_->members.size());
  }
  std::string name() const override { return name_; }
  DType dtype() const override { return dtype_; }

  // ---- blocking ----
  void Allreduce(const void* src, void* dst, std::int64_t count) override {
    run_collective(num_slots_, shm::OpKind::Allreduce, count, src, dst);
  }
  void Allgather(const void* src, void* dst, std::int64_t cpr) override {
    run_collective(num_slots_, shm::OpKind::Allgather, cpr, src, dst);
  }
  void ReduceScatterBlock(const void* src, void* dst,
                          std::int64_t cpr) override {
    run_collective(num_slots_, shm::OpKind::ReduceScatterBlock, cpr, src, dst);
  }
  void Alltoall(const void* src, void* dst, std::int64_t cpr) override {
    run_collective(num_slots_, shm::OpKind::Alltoall, cpr, src, dst);
  }
  void Barrier() override {
    run_collective(num_slots_, shm::OpKind::Barrier, 0, nullptr, nullptr);
  }

  // ---- p2p (group-rank addressed; see communicator.hpp tag rules) ----
  void Send(const void* src, std::int64_t count, int dst_rank,
            int tag = 0) override {
    group_->mailboxes.send(grank_, dst_rank, tag, src,
                           count * dtype_bytes(dtype_));
  }
  void Recv(void* dst, std::int64_t count, int src_rank,
            int tag = 0) override {
    group_->mailboxes.recv(src_rank, grank_, tag, dst,
                           count * dtype_bytes(dtype_));
  }

  // ---- nonblocking, slot-indexed ----
  void Iallreduce(const void* src, void* dst, std::int64_t count,
                  int slot) override {
    enqueue(slot, [=] {
      run_collective(slot, shm::OpKind::Allreduce, count, src, dst);
    });
  }
  void Iallgather(const void* src, void* dst, std::int64_t cpr,
                  int slot) override {
    enqueue(slot, [=] {
      run_collective(slot, shm::OpKind::Allgather, cpr, src, dst);
    });
  }
  void Isend(const void* src, std::int64_t count, int dst_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] {
      group_->mailboxes.send(grank_, dst_rank, t, src,
                             count * dtype_bytes(dtype_));
    });
  }
  void Irecv(void* dst, std::int64_t count, int src_rank, int slot,
             int tag = -1) override {
    int t = tag >= 0 ? tag : 1 + slot;
    enqueue(slot, [=] {
      group_->mailboxes.recv(src_rank, grank_, t, dst,
                             count * dtype_bytes(dtype_));
    });
  }
  void Wait(int slot) override {
    try {
      worker(slot).wait();
    } catch (...) {
      shm::quiesce(workers_);
      throw;
    }
  }
  void WaitAll(int num_slots) override {
    for (int i = 0; i < num_slots && i < num_slots_; ++i) {
      try {
        workers_[i].wait();
      } catch (...) {
        shm::quiesce(workers_);
        throw;
      }
    }
  }

 private:
  shm::SlotWorker& worker(int slot) {
    if (slot < 0 || slot >= num_slots_)
      throw std::out_of_range("slot " + std::to_string(slot) +
                              " out of range (num_slots=" +
                              std::to_string(num_slots_) + ")");
    return workers_[slot];
  }
  void enqueue(int slot, std::function<void()> fn) {
    worker(slot).enqueue(std::move(fn));
  }

  void run_collective(int slot, shm::OpKind op, std::int64_t count,
                      const void* src, void* dst) {
    // per-rank injected latency (fault_plan.hpp delay/jitter events
    // scoped where == "collective"); no-op without an active plan
    fault::Plan::instance().on_collective(group_->members[grank_]);
    int n = size();
    DType dt = dtype_;
    auto& rz = *group_->rendezvous[slot];
    rz.collective(
        grank_, op, count, src, dst,
        [n, dt, count, op](int g, const std::vector<const void*>& srcs,
                           const std::vector<void*>& dsts) {
          std::size_t esz = dtype_bytes(dt);
          switch (op) {
            case shm::OpKind::Barrier:
              break;
            case shm::OpKind::Allreduce: {
              // each rank computes its own full output (tree-free, but the
              // arithmetic is the real sum in float via dtype conversion)
              void* out = dsts[g];
              for (std::int64_t i = 0; i < count; ++i) {
                float acc = 0.0f;
                for (int r = 0; r < n; ++r)
                  acc += load_element(srcs[r], i, dt);
                store_element(out, i, dt, acc);
              }
              break;
            }
            case shm::OpKind::Allgather: {
              char* out = static_cast<char*>(dsts[g]);
              for (int r = 0; r < n; ++r)
                std::memcpy(out + r * count * esz, srcs[r], count * esz);
              break;
            }
            case shm::OpKind::ReduceScatterBlock: {
              void* out = dsts[g];
              for (std::int64_t i = 0; i < count; ++i) {
                float acc = 0.0f;
                for (int r = 0; r < n; ++r)
                  acc += load_element(srcs[r], g * count + i, dt);
                store_element(out, i, dt, acc);
              }
              break;
            }
            case shm::OpKind::Alltoall: {
              char* out = static_cast<char*>(dsts[g]);
              for (int r = 0; r < n; ++r)
                std::memcpy(out + r * count * esz,
                            static_cast<const char*>(srcs[r]) + g * count * esz,
                            count * esz);
              break;
            }
          }
        });
  }

  std::shared_ptr<shm::Group> group_;
  int grank_;
  DType dtype_;
  int num_slots_;
  std::string name_;
  std::vector<shm::SlotWorker> workers_;
};

// The world: spawns rank threads and arbitrates group splits.
class ShmFabric : public Fabric {
 public:
  ShmFabric(int world_size, DType dtype, int num_slots = 32)
      : world_size_(world_size), dtype_(dtype), num_slots_(num_slots) {
    if (world_size <= 0) throw std::invalid_argument("world_size must be > 0");
    std::vector<int> all(world_size);
    for (int i = 0; i < world_size; ++i) all[i] = i;
    world_group_ = std::make_shared<shm::Group>(all, num_slots_);
  }

  int world_size() const override { return world_size_; }
  DType dtype() const override { return dtype_; }
  std::string backend() const override { return "shm"; }
  int num_slots() const { return num_slots_; }

  std::unique_ptr<ProxyCommunicator> world_comm(int rank) override {
    return std::make_unique<ShmCommunicator>(world_group_, rank, dtype_,
                                             num_slots_, "shm_world");
  }

  // Returns this rank's communicator for its color group (see Fabric).
  std::unique_ptr<ProxyCommunicator> split(int world_rank, int color,
                                           const std::string& name) override {
    std::uint64_t seq;
    {
      std::unique_lock<std::mutex> lk(split_m_);
      // pair up with the ongoing round, or start a new one
      if (split_arrived_ == 0) split_colors_.assign(world_size_, 0);
      split_colors_[world_rank] = color;
      seq = split_seq_;
      if (++split_arrived_ == world_size_) {
        // build groups for this round
        std::map<int, std::vector<int>> by_color;
        for (int r = 0; r < world_size_; ++r)
          by_color[split_colors_[r]].push_back(r);
        for (auto& [c, members] : by_color)
          split_groups_[{seq, c}] =
              std::make_shared<shm::Group>(members, num_slots_);
        split_arrived_ = 0;
        ++split_seq_;
        split_cv_.notify_all();
      } else {
        split_cv_.wait(lk, [&] { return split_seq_ > seq; });
      }
    }
    std::shared_ptr<shm::Group> g;
    {
      std::lock_guard<std::mutex> lk(split_m_);
      g = split_groups_.at({seq, color});
    }
    int grank = 0;
    for (std::size_t i = 0; i < g->members.size(); ++i)
      if (g->members[i] == world_rank) grank = static_cast<int>(i);
    return std::make_unique<ShmCommunicator>(g, grank, dtype_, num_slots_,
                                             name);
  }

  void describe(Json& meta, Json& mesh) const override {
    meta["backend"] = "shm";
    meta["device"] = "cpu";
    meta["compute_mode"] = "host_sleep";
    // in-process thread fabric: the timed bytes never leave this
    // process's memory — the provenance that keeps these rows from
    // reading as fabric bandwidth (analysis/bandwidth.py `transport`)
    meta["transport"] = "shm";
    mesh["platform"] = "shm";
    mesh["device_kind"] = "thread-rank";
  }

  // A rank thread died mid-run: poison every group containing it so
  // survivors blocked in a rendezvous/mailbox THROW instead of hanging
  // forever — the in-process analogue of the TCP fabric's per-peer
  // death tracking (fail-fast on the threaded fabric).  Groups without
  // the dead rank (e.g. a fault plan's pre-split survivor group) keep
  // working, which is what lets the `shrink` policy continue the run.
  void mark_rank_dead(int world_rank) override {
    std::string why = "rank " + std::to_string(world_rank) +
                      " died during a collective (shm fail-fast)";
    std::vector<std::shared_ptr<shm::Group>> groups;
    groups.push_back(world_group_);
    {
      std::lock_guard<std::mutex> lk(split_m_);
      for (auto& [key, g] : split_groups_) groups.push_back(g);
    }
    for (auto& g : groups)
      if (g && g->contains(world_rank)) g->abort_all(why);
  }

  // Run body(rank) on world_size threads; rethrows the first rank failure.
  void launch(const std::function<void(int)>& body) override {
    std::vector<std::thread> threads;
    std::mutex err_m;
    std::exception_ptr first_error;
    threads.reserve(world_size_);
    for (int r = 0; r < world_size_; ++r)
      threads.emplace_back([&, r] {
        try {
          body(r);
        } catch (...) {
          // fail-fast: the sibling rank threads must observe the death
          // (abort shared groups) rather than wait forever on a rank
          // that will never arrive
          mark_rank_dead(r);
          std::lock_guard<std::mutex> lk(err_m);
          if (!first_error) first_error = std::current_exception();
        }
      });
    for (auto& t : threads) t.join();
    if (first_error) std::rethrow_exception(first_error);
  }

 private:
  int world_size_;
  DType dtype_;
  int num_slots_;
  std::shared_ptr<shm::Group> world_group_;

  std::mutex split_m_;
  std::condition_variable split_cv_;
  std::vector<int> split_colors_;
  int split_arrived_ = 0;
  std::uint64_t split_seq_ = 0;
  std::map<std::pair<std::uint64_t, int>, std::shared_ptr<shm::Group>>
      split_groups_;
};

}  // namespace dlnb
