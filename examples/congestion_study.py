#!/usr/bin/env python3
"""Interference study — what the `_loop` congestor binaries exist for.

The reference builds a `_loop` variant of every proxy (infinite run loop,
`-DPROXY_LOOP`, reference Makefile.common:96-109, dp.cpp:251-256) to
generate *sustained* background traffic for interference studies
(SURVEY.md §5.3).  This script runs that study shape end to end on one
machine using the native TCP fabric, whose frames share the kernel
loopback path the way cluster jobs share fabric links:

  1. measure the dp proxy across two OS processes (solo baseline),
  2. start a dp_loop congestor pair on the same host,
  3. measure dp again under load,
  4. report runtime and exposed-comm (barrier) inflation.

    python examples/congestion_study.py --out_dir /tmp/congestion

On a real cluster the same pairing applies unchanged: launch the `_loop`
binary on neighboring hosts and point both jobs' coordinators at their
own ranks-0.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

# runnable from a clone without installation
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from dlnetbench_tpu.utils import congest  # noqa: E402

REPO = Path(__file__).resolve().parent.parent
from dlnetbench_tpu.utils.native_build import native_bin as _locate  # noqa: E402
BIN = _locate(REPO, build=False)  # resolved for real (with build) in main()


def measure(tag: str, out_dir: Path, args) -> dict:
    # one record file per rank (the multi-host emission model; concurrent
    # appends to one file could interleave), merged afterwards
    outs = [out_dir / f"{tag}_p{r}.jsonl" for r in range(2)]
    for o in outs:
        o.unlink(missing_ok=True)
    procs = congest.launch_pair(
        BIN, "dp", args.model, REPO, args.time_scale, args.size_scale,
        extra=["--num_buckets", str(args.num_buckets),
               "--runs", str(args.runs), "--warmup", "1"], outs=outs)
    try:
        for p in procs:
            if p.wait(timeout=600) != 0:
                raise SystemExit(f"{tag}: dp rank exited {p.returncode}")
    finally:
        congest.kill_group(procs)  # reap survivors on any failure
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import load_records
    merged = merge_records([r for o in outs for r in load_records(o)])
    runtimes = [t for row in merged["ranks"] for t in row["runtimes"]]
    barriers = [t for row in merged["ranks"] for t in row["barrier_time"]]
    out = {"tag": tag,
           "runtime_us": sum(runtimes) / len(runtimes),
           "barrier_us": sum(barriers) / len(barriers)}
    # per-process host energy when the native chain found a counter
    # (energy.hpp: RAPL/hwmon; absent on rigs without one)
    joules = [j for row in merged["ranks"]
              for j in row.get("energy_consumed", [])]
    if joules:
        out["energy_j_per_run"] = sum(joules) / len(joules)
        out["energy_source"] = merged["global"].get("energy_source")
    return out


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out_dir", type=Path, default=Path("/tmp/congestion"))
    ap.add_argument("--model", default="gpt2_l_16_bfloat16")
    ap.add_argument("--num_buckets", type=int, default=4)
    ap.add_argument("--runs", type=int, default=10)
    ap.add_argument("--time_scale", type=float, default=1e-3)
    ap.add_argument("--size_scale", type=float, default=3e-3,
                    help="large enough buckets that loopback bandwidth, "
                         "not latency, dominates the allreduce")
    args = ap.parse_args()

    global BIN
    # always (re)build: incremental ninja is a no-op when current, and
    # a silently stale cached binary would poison the study
    try:
        BIN = _locate(REPO)
    except Exception as e:
        raise SystemExit(f"could not build the native binaries: {e}")
    args.out_dir.mkdir(parents=True, exist_ok=True)

    solo = measure("solo", args.out_dir, args)

    # sustained background traffic: the _loop binary never returns —
    # start it (fresh-port retry inside), measure under load, kill it
    congestors = congest.launch_pair_retry(
        BIN, "dp_loop", args.model, REPO, args.time_scale,
        args.size_scale, extra=["--num_buckets", str(args.num_buckets)])
    try:
        congested = measure("congested", args.out_dir, args)
    finally:
        congest.kill_group(congestors)

    report = {
        "solo": solo, "congested": congested,
        "runtime_inflation":
            congested["runtime_us"] / max(solo["runtime_us"], 1e-9),
        "barrier_inflation":
            congested["barrier_us"] / max(solo["barrier_us"], 1e-9),
    }
    if "energy_j_per_run" in solo and "energy_j_per_run" in congested:
        # the study's energy question: how many extra joules does the
        # same work cost under interference (reference Pareto axis)
        report["energy_inflation"] = (
            congested["energy_j_per_run"]
            / max(solo["energy_j_per_run"], 1e-9))
    (args.out_dir / "report.json").write_text(json.dumps(report, indent=2))
    print(f"solo:      runtime {solo['runtime_us']:12.1f} us   "
          f"barrier {solo['barrier_us']:10.1f} us")
    print(f"congested: runtime {congested['runtime_us']:12.1f} us   "
          f"barrier {congested['barrier_us']:10.1f} us")
    print(f"inflation: runtime x{report['runtime_inflation']:.2f}   "
          f"barrier x{report['barrier_inflation']:.2f}")
    if "energy_inflation" in report:
        print(f"energy:    solo {solo['energy_j_per_run']:.3f} J/run   "
              f"congested {congested['energy_j_per_run']:.3f} J/run   "
              f"x{report['energy_inflation']:.2f} "
              f"({solo.get('energy_source')})")
    print(f"wrote {args.out_dir}/report.json")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
