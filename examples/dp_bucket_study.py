#!/usr/bin/env python3
"""End-to-end DP bucket-count study — the reference's headline workflow
(sweep -> parse -> plot) on one dev box.

The reference's analogous loop is: sbatchman submits a job grid over NCCL
knobs, parser.py walks the completed jobs into DataFrames, plot_dp.py
draws runtime scaling and barrier scatter (reference plots/plot_dp.py:29,
:80).  Here the same loop runs locally on the virtual CPU mesh:

    python examples/dp_bucket_study.py --out_dir /tmp/dp_study

sweeps the dp proxy over bucket counts, ingests the tagged records, prints
the per-bucket exposed-communication table, and writes scaling + barrier
+ Pareto PNGs.  Swap ``--platform cpu`` out and raise the scales to run
the identical study on a TPU slice.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

# runnable from a clone without installation
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out_dir", type=Path, default=Path("/tmp/dp_study"))
    ap.add_argument("--model", default="gpt2_l_16_bfloat16")
    ap.add_argument("--buckets", default="2,4,8")
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    args.out_dir.mkdir(parents=True, exist_ok=True)
    records = args.out_dir / "records.jsonl"
    records.unlink(missing_ok=True)

    # 1. sweep (each point is a fresh subprocess; see dlnetbench_tpu/sweep.py)
    import os
    if not os.environ.get("XLA_FLAGS"):   # empty counts as unset
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.devices}")
    # sweep points are subprocesses: make the package importable for them
    # regardless of cwd / installation
    repo = str(Path(__file__).resolve().parent.parent)
    os.environ["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, os.environ.get("PYTHONPATH")) if p)
    from dlnetbench_tpu import sweep
    rc = sweep.main([
        "dp", "--model", args.model, "--out", str(records),
        "--axis", f"num_buckets={args.buckets}", "--",
        "--platform", "cpu", "-r", "3", "-w", "1",
        "--size_scale", "1e-5", "--time_scale", "1e-4", "--no_topology"])
    if rc != 0:
        return rc

    # 2. ingest (reference plots/parser.py:213-256 shape: rank x run rows)
    from dlnetbench_tpu.metrics.parser import load_records, records_to_dataframe
    recs = load_records(records, "dp")
    df = records_to_dataframe(recs)
    summary = (df.groupby("num_buckets")[["runtime", "barrier_time"]]
               .mean().sort_index())
    print("\nmean per bucket count (us):")
    print(summary.to_string(float_format=lambda v: f"{v:12.1f}"))

    # 2b. effective bandwidth (north-star table, analysis/bandwidth.py),
    # kept per sweep point — blending bucket counts would erase the axis
    # the study exists to compare
    import pandas as pd
    from dlnetbench_tpu.analysis.bandwidth import bandwidth_summary
    per_point = []
    for rec in recs:
        s = bandwidth_summary([rec])
        if not s.empty:
            s.insert(0, "num_buckets", rec["global"].get("num_buckets"))
            per_point.append(s)
    if per_point:
        bw = pd.concat(per_point).sort_values("num_buckets")
        print("\neffective bandwidth (comm-only allreduce schedule):")
        print(bw[["num_buckets", "collective", "group_size", "time_us",
                  "algbw_GBps", "busbw_GBps"]].to_string(index=False))

    # 3. plots (reference plots/plot_dp.py, plots_pareto_energy.py)
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from dlnetbench_tpu.analysis import plots

    fig, ax = plt.subplots(figsize=(6, 4))
    for nb, sub in df.groupby("num_buckets"):
        ax.plot(sub.groupby("run")["runtime"].mean(), marker="o",
                label=f"{nb} buckets")
    ax.set_xlabel("run"), ax.set_ylabel("runtime (us)"), ax.legend()
    fig.savefig(args.out_dir / "runtime_by_bucket.png", dpi=120)

    ax = plots.plot_barrier_scatter_by_bucket(df)
    ax.figure.savefig(args.out_dir / "barrier_by_bucket.png", dpi=120)

    ax = plots.plot_pareto(df, x="runtime", group_by="num_buckets",
                           y="barrier_time")
    ax.figure.savefig(args.out_dir / "pareto.png", dpi=120)

    print(f"\nwrote {args.out_dir}/{{runtime_by_bucket,barrier_by_bucket,"
          f"pareto}}.png")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
