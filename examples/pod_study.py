#!/usr/bin/env python3
"""North-star pod study — every proxy workload on llama3_70b + mixtral,
one command producing the effective-bandwidth table and the three plot
families (SURVEY.md §7.2 step 7; reference BASELINE.md's "effective bus
GB/s + iter time per collective").

The reference runs this as a SLURM grid (sbatchman) over
dp/fsdp/hybrid_3d/hybrid_3d_moe and parses the job outputs back into
DataFrames (reference plots/parser.py:213-256).  Here the same study is
one script with no scheduler:

    python examples/pod_study.py --out_dir /tmp/pod_study

runs all 7 proxies (dp, fsdp, hybrid_2d/3d/3d-moe, ring_attention,
ulysses) on an 8-device virtual CPU mesh at reduced buffer/time scale,
then prints per-collective effective bandwidth and writes
scaling / barrier-scatter / Pareto PNGs plus bandwidth_summary.csv.

On a real TPU pod slice, drop the shrink factors and let the runtime's
devices be the mesh:

    python examples/pod_study.py --platform tpu --full_scale \
        --devices 16 --out_dir ~/pod_study_v5p

Every point is a fresh subprocess (compilation caches and backend state
cannot leak between grid points), tagged with ``proxy=<name>`` so the
combined records file remains one flat, parseable study.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from pathlib import Path

# runnable from a clone without installation
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from dlnetbench_tpu.utils.net import free_port  # noqa: E402

DENSE = "llama3_70b_16_bfloat16"
MOE = "mixtral_8x7b_16_bfloat16"


def build_plan(models: list[str], devices: int) -> list[tuple[str, dict]]:
    """(proxy, flags) for every study point.

    Grid shapes mirror the reference's study configurations scaled to the
    available world size: dp scaling over world sizes and bucket counts
    (reference plots/plot_dp.py:29, :80), fsdp with hybrid sharding
    (sharding_factor x replicas = world, reference
    cpp/data_parallel/fsdp.cpp:217), the three hybrids on stagexdp(xtp/ep)
    grids (reference cpp/hybrid_parallel/*.cpp), and the two
    sequence-parallel extensions on sp x dp grids.
    """
    half = max(devices // 2, 1)
    quarter = max(devices // 4, 1)
    plan: list[tuple[str, dict]] = []

    for model in models:
        # dp runtime scaling over world sizes (last point = full world)
        w = devices
        worlds = []
        while w >= 2:
            worlds.append(w)
            w //= 2
        for w in sorted(worlds):
            plan.append(("dp", {"model": model, "num_buckets": 4, "d": w}))
        # dp bucket study at full world (barrier-scatter axis)
        for nb in (2, 8):
            plan.append(("dp", {"model": model, "num_buckets": nb,
                                "d": devices}))
        plan.append(("fsdp", {"model": model, "num_units": 8,
                              "sharding_factor": half}))
        # pipeline-schedule comparison: reference GPipe vs the rebuild's
        # 1F1B and ZB-H1 extras, same grid and microbatch totals
        for sch in ("gpipe", "1f1b", "zb"):
            plan.append(("hybrid_2d", {"model": model, "num_stages": 4,
                                       "num_microbatches": 8,
                                       "dp": quarter, "schedule": sch}))
        plan.append(("hybrid_3d", {"model": model, "num_stages": 2,
                                   "num_microbatches": 8, "tp": 2,
                                   "dp": quarter}))
        if model == MOE:
            plan.append(("hybrid_3d_moe", {"model": model, "num_stages": 2,
                                           "num_microbatches": 8,
                                           "num_expert_shards": 2,
                                           "dp": quarter}))
        plan.append(("ring_attention", {"model": model, "sp": 4,
                                        "dp": quarter, "max_layers": 2}))
        plan.append(("ulysses", {"model": model, "sp": 4, "dp": quarter,
                                 "max_layers": 2}))
    return plan


def run_plan(plan, args, records: Path) -> int:
    env = dict(os.environ)
    if args.platform == "cpu" and not env.get("XLA_FLAGS"):
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.devices}")
    repo = str(Path(__file__).resolve().parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)

    from dlnetbench_tpu.utils.native_build import native_bin as _locate
    if args.tier == "native":
        # always (re)build: incremental ninja is a no-op when current,
        # and a silently stale cached binary would poison the study
        try:
            native_bin = _locate(repo)
        except Exception as e:
            raise SystemExit(f"--tier native could not build: {e}")
    else:
        native_bin = _locate(repo, build=False)

    failed = 0
    for i, (proxy, flags) in enumerate(plan):
        desc = " ".join(f"{k}={v}" for k, v in flags.items())
        flags = dict(flags)
        if args.tier == "native":
            # same study on the C++ tier: per-proxy binary, explicit
            # --world (the python tier infers it from the device mesh;
            # the dp scaling axis "d" IS the world)
            world = flags.pop("d", args.devices)
            argv = [str(native_bin / proxy),
                    "--model", flags.pop("model"),
                    "--world", str(world), "--out", str(records),
                    "--runs", str(args.runs), "--warmup", "1",
                    "--no_topology", "--base_path", repo]
        else:
            argv = [sys.executable, "-m", "dlnetbench_tpu.cli", proxy,
                    "--out", str(records), "--platform", args.platform,
                    "-r", str(args.runs), "-w", "1", "--no_topology",
                    "--tag", f"proxy={proxy}"]
        if not args.full_scale:
            argv += ["--size_scale", str(args.size_scale),
                     "--time_scale", str(args.time_scale)]
        for k, v in flags.items():
            argv += [f"--{k}", str(v)]
        print(f"[{i + 1}/{len(plan)}] {proxy} {desc}", flush=True)
        if args.tier == "native" and args.backend == "pjrt-hier":
            rc = _run_hier_point(argv, world, records, env, args.procs)
        else:
            rc = subprocess.run(argv, env=env,
                                stdout=subprocess.DEVNULL).returncode
        if rc != 0:
            print(f"  FAILED rc={rc}", file=sys.stderr)
            failed += 1
    return failed


def _run_hier_point(argv: list[str], world, records: Path, env,
                    nprocs: int = 2) -> int:
    """One study point over the hierarchical ICI x DCN fabric: --procs
    OS processes, each driving its own executor (libtpu when usable,
    host otherwise) over world/procs ranks, combined over the TCP mesh;
    their per-process records are merged into the study's record stream
    (the reference's multi-node operating mode, dp.cpp:166-189).
    Returns a nonzero code for ANY per-point failure (signal death,
    timeout, bad records) so run_plan's per-point FAILED accounting
    sees it."""
    if int(world) < nprocs:
        # uneven worlds are fine (the fabric's balanced layout gives the
        # first world%procs processes one extra rank); only a process
        # with NO rank to host is impossible
        print(f"  skipped (world {world} < {nprocs} processes)",
              file=sys.stderr)
        return 0
    # strip the single-record --out; each process writes its own file
    base = [a for j, a in enumerate(argv)
            if argv[j - 1] != "--out" and a != "--out"]
    parts = [records.parent / f".hier_p{r}.jsonl" for r in range(nprocs)]
    # the freshly-probed port can be stolen before rank 0 binds it
    # (TOCTOU) — retry on a fresh port, same discipline as the tcp
    # fabric tests
    for attempt in range(3):
        for p in parts:
            p.unlink(missing_ok=True)
        port = free_port()
        procs = [subprocess.Popen(
            base + ["--backend", "pjrt", "--procs", str(nprocs),
                    "--rank", str(r),
                    "--coordinator", f"127.0.0.1:{port}", "--out",
                    str(parts[r])],
            env=env, stdout=subprocess.DEVNULL) for r in range(nprocs)]
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(timeout=900))
            except subprocess.TimeoutExpired:
                rcs.append(124)
        if any(rcs):  # reap the sibling before retrying or reporting
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        if all(rc == 0 for rc in rcs):
            break
        if attempt == 2:
            return next((abs(rc) for rc in rcs if rc != 0), 1)
    from dlnetbench_tpu.metrics.merge import merge_files
    try:
        merge_files(records, parts)
    except ValueError as e:
        print(f"  merge failed: {e}", file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------
# --serving mode: the latency-vs-offered-load study (ISSUE 8,
# docs/SERVING.md).  Offered load is swept as a FRACTION of this
# machine's measured capacity (a saturating calibration run first), so
# the knee lands inside the sweep on any box; each load point runs
# SERVING_SEEDS arrival-plan seeds and the report bands p99/goodput
# over them.  One extra point injects a straggler delay into the decode
# loop at mid load — the fault-composition proof: the same fault-plan
# JSON that drives the training tier measurably inflates serving p99.

SERVING_FRACTIONS = (0.25, 0.5, 1.0, 1.5, 2.0)
# 5 seeds per load point: each point's p99 is the MEDIAN over seeds
# with the full band shown — on a small shared box a single co-tenant
# stall lands squarely in one run's nearest-rank p99, and 3 seeds give
# that outlier veto power over the knee shape
SERVING_SEEDS = (0, 1, 2, 3, 4)
# long enough that sustained overload accumulates a real backlog: at
# 2x capacity the LAST arrival waits ~half the arrival span, so the
# span must dwarf a single request's clean service time or the queue
# never shows in p99
SERVING_REQUESTS = 120
SERVING_FLAGS = [
    "--slots", "4", "--page_size", "8", "--num_pages", "64",
    "--max_seq_len", "64", "--embed", "64", "--heads", "4",
    "--kv_heads", "2", "--ff", "128", "--layers", "2", "--vocab", "256",
    "--slo_ttft_ms", "100", "--slo_tpot_ms", "30",
]
SERVING_FAULT_DELAY_US = 20000  # straggler sleep per engine step


def serving_arrival(rate: float, seed: int,
                    n: int = SERVING_REQUESTS) -> str:
    return json.dumps({"kind": "poisson", "rate_rps": round(rate, 3),
                       "num_requests": n, "seed": seed,
                       "prompt_len": [8, 16], "output_len": [4, 8]})


# --disagg (ISSUE 16): the same sweep over the disaggregated engine —
# the prefill mesh and decode mesh split the two capacity ranks, KV
# pages migrate in the stored dtype, and the report's serving_summary
# carries the migration_* columns next to the latency bands
DISAGG_FLAGS = [
    "--disaggregate", "--world", "2", "--prefill_ranks", "1",
    "--decode_ranks", "1", "--multi_step_n", "4",
]

# --fleet (ISSUE 18): the same sweep over a two-replica FLEET — the
# seeded router places every arrival (p2c on the live load score), each
# replica keeps its own page pool, and the report's serving_summary
# carries the fleet_routing/fleet_replicas/fleet_goodput_per_chip_s
# columns next to the latency bands.  Capacity doubles (2 engines), so
# the same calibrate-then-sweep protocol finds this arm's own knee.
FLEET_FLAGS = [
    "--replicas", "2", "--routing", "p2c",
]


def _serve_argv(records: Path, arrival: str, tags: list[str],
                extra: list[str] | None = None) -> list:
    argv = [sys.executable, "-m", "dlnetbench_tpu.cli", "serve",
            "--arrival", arrival, "--platform", "cpu",
            "--out", str(records)] + SERVING_FLAGS + (extra or [])
    for t in tags:
        argv += ["--tag", t]
    return argv


def run_serving_plan(args, records: Path) -> int:
    from dlnetbench_tpu.metrics.parser import load_records

    repo = str(Path(__file__).resolve().parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    # the disagg/fleet arms need a multi-device mesh; honor a caller's
    # own XLA_FLAGS (same discipline as run_plan)
    if not env.get("XLA_FLAGS"):
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.devices}")
    failed = 0
    disagg = bool(getattr(args, "disagg", False))
    fleet = bool(getattr(args, "fleet", False))
    if disagg:
        extra, eng = DISAGG_FLAGS, "disagg"
    elif fleet:
        extra, eng = FLEET_FLAGS, "fleet"
    else:
        extra, eng = None, "mono"
    eng_tag = f"engine={eng}"

    # 1. capacity calibration: a saturating rate (every request queued
    # at t~0) — measured_rps IS the engine's drain capacity here
    calib = records.parent / ".serving_calib.jsonl"
    calib.unlink(missing_ok=True)
    print("[serving 0] capacity calibration (saturating arrivals)",
          flush=True)
    rc = subprocess.run(
        _serve_argv(calib, serving_arrival(10000.0, 0),
                    ["load_frac=calib", eng_tag], extra),
        env=env, stdout=subprocess.DEVNULL).returncode
    if rc != 0 or not calib.exists():
        raise SystemExit(f"serving calibration failed rc={rc}")
    capacity = load_records(calib)[0]["global"]["serving"]["measured_rps"]
    calib.unlink(missing_ok=True)
    print(f"  capacity ~{capacity:.1f} req/s on this box", flush=True)

    # 2. the load sweep: fractions of capacity x arrival seeds
    n_pts = len(SERVING_FRACTIONS) * len(SERVING_SEEDS)
    for i, frac in enumerate(SERVING_FRACTIONS):
        for seed in SERVING_SEEDS:
            print(f"[serving {i + 1}/{len(SERVING_FRACTIONS)}] "
                  f"load {frac:.2f}x capacity, seed {seed} "
                  f"({n_pts} runs total)", flush=True)
            rc = subprocess.run(
                _serve_argv(records,
                            serving_arrival(capacity * frac, seed),
                            [f"load_frac={frac}",
                             f"serving_seed={seed}", eng_tag], extra),
                env=env, stdout=subprocess.DEVNULL).returncode
            if rc != 0:
                print(f"  FAILED frac={frac} seed={seed} rc={rc}",
                      file=sys.stderr)
                failed += 1

    # 3. the faulted point: a straggler delay on every decode-loop step
    # at mid load — same FaultPlan JSON as the training tier
    fault = json.dumps({"events": [{
        "kind": "delay", "iteration": 0,
        "magnitude_us": SERVING_FAULT_DELAY_US}]})
    print(f"[serving fault] 0.50x capacity + "
          f"{SERVING_FAULT_DELAY_US / 1000:.0f} ms straggler per "
          f"decode step", flush=True)
    rc = subprocess.run(
        _serve_argv(records, serving_arrival(capacity * 0.5, 0),
                    ["load_frac=0.5", "serving_fault=straggler",
                     eng_tag], extra)
        + ["--fault", fault],
        env=env, stdout=subprocess.DEVNULL).returncode
    if rc != 0:
        print("  FAILED", file=sys.stderr)
        failed += 1
    return failed


def serving_report(args, records: Path) -> int:
    """The latency-vs-load table with stat bands over seeds, the knee
    verdict, and the straggler-composition verdict — enforced at
    generation time like the goodput study's Daly check."""
    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.parser import load_records
    from dlnetbench_tpu.metrics.stats import summarize

    recs = load_records(records)
    rows = []
    for rec in recs:
        g = rec.get("global", {})
        srv = g.get("serving")
        if not srv:
            continue
        v = g.get("variables", {})
        rows.append({
            "frac": v.get("load_frac", "?"),
            "fault": v.get("serving_fault", "-"),
            "offered_rps": srv["offered_rps"],
            "p99_ms": srv["e2e_ms"]["p99"],
            "ttft_p99_ms": srv["ttft_ms"]["p99"],
            "goodput_frac": srv["goodput_frac"],
            "goodput_rps": srv["goodput_rps"],
        })
    clean = {}
    for r in rows:
        if r["fault"] == "-":
            clean.setdefault(r["frac"], []).append(r)
    print("\n=== serving: latency vs offered load (bands over "
          f"{len(SERVING_SEEDS)} arrival seeds) ===")
    print(f"{'load':>6} {'offered_rps':>12} {'p99_ms':>24} "
          f"{'ttft_p99_ms':>24} {'goodput@SLO':>22}")
    by_frac = {}
    for frac in sorted(clean, key=lambda f: float(f)):
        pts = clean[frac]
        p99 = summarize([p["p99_ms"] for p in pts], ndigits=3)
        ttft = summarize([p["ttft_p99_ms"] for p in pts], ndigits=3)
        good = summarize([p["goodput_frac"] for p in pts], ndigits=4)
        offered = sum(p["offered_rps"] for p in pts) / len(pts)
        by_frac[float(frac)] = (p99, good)
        print(f"{frac:>6} {offered:>12.1f} "
              f"{p99['value']:>10.1f} {str(p99['band']):>13} "
              f"{ttft['value']:>10.1f} {str(ttft['band']):>13} "
              f"{good['value']:>8.2f} {str(good['band']):>13}")
    rc = 0
    if by_frac:
        lo, hi = min(by_frac), max(by_frac)
        knee = by_frac[hi][0]["value"] / max(by_frac[lo][0]["value"],
                                             1e-9)
        print(f"\nknee: p99({hi}x) / p99({lo}x) = {knee:.1f}x, "
              f"goodput@SLO {by_frac[lo][1]['value']:.2f} -> "
              f"{by_frac[hi][1]['value']:.2f}")
        if knee < 2.0:
            print("VERDICT: no visible saturation knee (p99 inflation "
                  "< 2x across the sweep) — the study failed its "
                  "acceptance bar", file=sys.stderr)
            rc = 1
    faulted = [r for r in rows if r["fault"] != "-"]
    if faulted:
        base = clean.get(faulted[0]["frac"], [])
        base_p99 = (summarize([p["p99_ms"] for p in base])["value"]
                    if base else float("nan"))
        f_p99 = faulted[0]["p99_ms"]
        print(f"straggler composition: clean p99 {base_p99:.1f} ms -> "
              f"faulted p99 {f_p99:.1f} ms at load "
              f"{faulted[0]['frac']}x "
              f"(+{SERVING_FAULT_DELAY_US / 1000:.0f} ms/step delay)")
        if not f_p99 > base_p99:
            print("VERDICT: injected straggler did NOT inflate p99 — "
                  "fault composition broke", file=sys.stderr)
            rc = 1
    ss = serving_summary(recs)
    if not ss.empty:
        ss.to_csv(args.out_dir / "serving_summary.csv", index=False)
        print(f"\nwrote {records} and "
              f"{args.out_dir}/serving_summary.csv")
    return rc


# ---------------------------------------------------------------------
# --kv_density mode: the serving-density study (ISSUE 12,
# docs/SERVING.md "Cache density").  Two halves into one artifact dir:
#
#   1. capacity A/B — bench.py's kv_density_ab line: dense vs int8 vs
#      fp8 paged-KV engines at the SAME pool bytes (scale arrays priced
#      in), one seeded saturating plan, interleaved rounds.  Acceptance
#      (enforced HERE, at generation): both quant recipes inside their
#      stated decode-parity bars, admitted concurrency >= 1.8x dense,
#      and the goodput-at-SLO win band-DISJOINT.
#   2. prefix-sharing A/B — one prefix-heavy arrival plan (seeded
#      shared system prompts, serving/arrivals.py shared_prefix_len/
#      prefix_pool) run through the SAME engine with sharing off/on:
#      token-identical streams (lossless), prefix_hit_rate > 0 and
#      bytes_saved > 0 stamped on the sharing record, TTFT deltas
#      reported.

KV_DENSITY_MIN_CAPACITY_X = 1.8


def run_kv_density_study(out_dir: Path) -> int:
    """Generate docs/studies/kv_density_r15's evidence into
    ``out_dir``; returns non-zero unless the acceptance bars hold."""
    import dataclasses

    import jax

    repo = Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(repo))
    import bench

    from dlnetbench_tpu.metrics.emit import emit_result
    from dlnetbench_tpu.metrics.stats import bands_overlap
    from dlnetbench_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
    from dlnetbench_tpu.serving.arrivals import ArrivalPlan
    from dlnetbench_tpu.serving.scheduler import (Engine,
                                                  ServingConfig,
                                                  run_serving)

    rc = 0
    # ---- half 1: the equal-pool-bytes capacity A/B ------------------
    print("[kv_density 1/2] capacity A/B (dense vs int8 vs fp8 at "
          "equal pool bytes)", flush=True)
    line = bench._bench_kv_density()
    if line is None:
        print("kv_density_ab produced no line", file=sys.stderr)
        return 1
    (out_dir / "kv_density_ab.json").write_text(
        json.dumps(line, indent=1) + "\n")
    base = line["variants"]["bf16"]
    disjoint_wins = []
    for cd in ("int8", "fp8"):
        v = line["variants"][cd]
        cap = v["capacity_x"]["value"]
        disjoint = (bands_overlap(base["goodput_rps"]["band"],
                                  v["goodput_rps"]["band"]) is False
                    and v["goodput_rps"]["value"]
                    > base["goodput_rps"]["value"])
        disjoint_wins.append((cd, disjoint))
        print(f"  {cd}: parity {v['parity_max_err']['value']:.4f} "
              f"(tol {v['parity_tol']}, ok={v['parity_ok']}), "
              f"capacity {cap:.2f}x, goodput@SLO "
              f"{base['goodput_rps']['value']:.1f} -> "
              f"{v['goodput_rps']['value']:.1f} rps "
              f"(band-disjoint={disjoint})")
        # parity + the >= 1.8x capacity bar gate BOTH recipes
        if not v["parity_ok"]:
            print(f"VERDICT: {cd} decode parity exceeded its stated "
                  f"bar", file=sys.stderr)
            rc = 1
        if cap < KV_DENSITY_MIN_CAPACITY_X:
            print(f"VERDICT: {cd} admitted concurrency {cap:.2f}x < "
                  f"{KV_DENSITY_MIN_CAPACITY_X}x at equal pool bytes",
                  file=sys.stderr)
            rc = 1
    # the band-disjoint goodput-at-SLO win gates the recipe a
    # deployment would actually pick (int8 on the CPU mesh, where XLA
    # dequantizes fp8 in slow emulation); the other recipe's number is
    # still committed honestly above
    if not any(d for _, d in disjoint_wins):
        print("VERDICT: no quant recipe shows a band-disjoint "
              "goodput-at-SLO win vs dense at equal pool bytes",
              file=sys.stderr)
        rc = 1

    # ---- half 2: the prefix-heavy sharing A/B -----------------------
    print("[kv_density 2/2] prefix sharing A/B (shared system "
          "prompt, sharing off vs on)", flush=True)
    mc = TransformerConfig(
        vocab_size=256, embed_dim=64, num_heads=4, num_kv_heads=2,
        ff_dim=128, num_layers=2, seq_len=96, gated=True,
        max_positions=0, dtype="float32")
    # page-aligned 32-token system prompt over a 2-prompt pool; the
    # prefill chunk divides the prefix so shared/unshared runs chunk
    # the unshared tail identically (the bit-exactness precondition
    # docs/SERVING.md states)
    plan = ArrivalPlan(kind="poisson", rate_rps=400.0,
                       num_requests=40, seed=0,
                       prompt_len=[40, 56], output_len=[8, 16],
                       shared_prefix_len=32, prefix_pool=2)
    base_cfg = ServingConfig(slots=6, page_size=8, num_pages=96,
                             max_seq_len=96, prefill_chunk=8,
                             slo_ttft_ms=250.0, slo_tpot_ms=100.0,
                             attn_impl="gather")
    params = init_params(jax.random.key(0), mc)
    records = out_dir / "records.jsonl"
    records.unlink(missing_ok=True)
    results = {}
    for tag, cfg in (("off", base_cfg),
                     ("on", dataclasses.replace(base_cfg,
                                                prefix_sharing=True))):
        res = run_serving(mc, cfg, plan, params=params)
        res.global_meta.setdefault("variables", {})["prefix_sharing"] \
            = tag
        rec = emit_result(res, path=records)
        results[tag] = rec["global"]
    # losslessness: re-run both engines capturing token streams
    streams = {}
    for tag, cfg in (("off", base_cfg),
                     ("on", dataclasses.replace(base_cfg,
                                                prefix_sharing=True))):
        eng = Engine(mc, cfg, params=params)
        eng.run(plan.sample())
        streams[tag] = dict(eng.token_streams)
    lossless = streams["on"] == streams["off"]
    srv_off = results["off"]["serving"]
    srv_on = results["on"]["serving"]
    hit_rate = results["on"].get("prefix_hit_rate", 0.0)
    bytes_saved = results["on"].get("prefix_bytes_saved", 0)
    summary = {
        "lossless": lossless,
        "prefix_hit_rate": hit_rate,
        "prefix_bytes_saved": bytes_saved,
        "ttft_p50_ms": {"off": srv_off["ttft_ms"]["p50"],
                        "on": srv_on["ttft_ms"]["p50"]},
        "ttft_p99_ms": {"off": srv_off["ttft_ms"]["p99"],
                        "on": srv_on["ttft_ms"]["p99"]},
        "e2e_p99_ms": {"off": srv_off["e2e_ms"]["p99"],
                       "on": srv_on["e2e_ms"]["p99"]},
        "plan": plan.to_dict(),
    }
    (out_dir / "prefix_sharing_ab.json").write_text(
        json.dumps(summary, indent=1) + "\n")
    print(f"  lossless={lossless} hit_rate={hit_rate} "
          f"bytes_saved={bytes_saved} ttft_p50 "
          f"{srv_off['ttft_ms']['p50']:.1f} -> "
          f"{srv_on['ttft_ms']['p50']:.1f} ms")
    if not lossless:
        print("VERDICT: prefix sharing changed the token streams — "
              "sharing must be lossless", file=sys.stderr)
        rc = 1
    if not (hit_rate > 0 and bytes_saved > 0):
        print("VERDICT: prefix-heavy plan produced no measured "
              "sharing (hit_rate/bytes_saved)", file=sys.stderr)
        rc = 1
    return rc


# ---------------------------------------------------------------------
# --fault mode: the fault-injection & elastic-degradation study
# (docs/RESILIENCE.md).  Five points into ONE records.jsonl — three
# native (straggler / crash+shrink / drop+retry, the r8 set), one
# native preempt->rejoin (the grow half), and a python-tier seeded
# goodput-vs-interval sweep the Daly model is validated against:
#   1. straggler  — fsdp/shm, a 30 ms delay on rank 2 from step 4 on:
#                   the clean window is the in-record baseline, the
#                   summary reports straggler_amp and refuses busbw on
#                   the faulted runs;
#   2. crash      — dp over 3 TCP processes, rank 1 dies at step 4
#                   under policy `shrink`: the victim exits nonzero and
#                   emits nothing (dead is dead), survivors finish on
#                   the pre-split survivor group and their records
#                   merge through the degraded pathway with
#                   detection_ms/recovery_ms/degraded_world;
#   3. drop       — dp over 2 TCP processes at 20 % injected frame
#                   loss under policy `retry`: the run completes,
#                   backoff counts ride the record.

FAULT_MODEL = "gpt2_l_16_bfloat16"

# the seeded goodput sweep (point 5): checkpoint intervals x seeds; each
# seed draws its own preempt trigger, so the triggers are the "failure
# arrivals" the exponential-MTBF fit treats as draws (analysis/goodput)
ELASTIC_INTERVALS = (1, 2, 4, 8)
ELASTIC_SEEDS = (0, 1, 2)
ELASTIC_RUNS = 16  # measured steps per sweep run (+1 warmup)


def elastic_plan(seed: int, *, warmup: int = 1) -> dict:
    """The seeded preempt -> rejoin plan of one sweep run: rank 2 is
    evicted at a seed-drawn step (grace 20 ms) and returns 4 steps
    later.  Deterministic given the seed — the sweep is replayable."""
    import random
    rng = random.Random(seed)
    pre = warmup + 4 + rng.randrange(5)  # plan steps 5..9
    return {"policy": "shrink", "events": [
        {"kind": "preempt", "ranks": [2], "iteration": pre,
         "magnitude_us": 20000, "seed": seed},
        {"kind": "rejoin", "ranks": [2], "iteration": pre + 4}]}


def _fault_base(repo: str, runs: int = 6) -> list[str]:
    return ["--model", FAULT_MODEL, "--time_scale", "0.001",
            "--size_scale", "0.0001", "--runs", str(runs),
            "--warmup", "1", "--no_topology", "--base_path", repo]


def run_fault_plan(args, records: Path) -> int:
    from dlnetbench_tpu.metrics.merge import merge_files
    from dlnetbench_tpu.utils.native_build import native_bin as _locate

    repo = str(Path(__file__).resolve().parent.parent)
    try:
        native = _locate(repo)
    except Exception as e:
        raise SystemExit(f"--fault needs the native tier: {e}")
    failed = 0

    # 1. straggler (shm; fsdp declares a comm_model, so the faulted
    # busbw refusal + straggler_amp surface in the bandwidth table)
    plan = json.dumps({"events": [{"kind": "delay", "ranks": [2],
                                   "iteration": 4,
                                   "magnitude_us": 30000}]})
    print("[fault 1/5] straggler: fsdp/shm world 4, 30 ms delay on "
          "rank 2 from step 4", flush=True)
    rc = subprocess.run(
        [str(native / "fsdp"), "--world", "4", "--num_units", "4",
         "--sharding_factor", "2", "--fault", plan,
         "--out", str(records)] + _fault_base(repo),
        stdout=subprocess.DEVNULL).returncode
    if rc != 0:
        print("  FAILED", file=sys.stderr)
        failed += 1

    # 2. rank crash + shrink (tcp, 3 processes; rank 1 is the victim)
    plan = json.dumps({"events": [{"kind": "crash", "ranks": [1],
                                   "iteration": 4}]})
    print("[fault 2/5] crash+shrink: dp/tcp world 3, rank 1 dies at "
          "step 4, survivors regroup", flush=True)
    port = free_port()
    parts = [records.parent / f".fault_p{r}.jsonl" for r in range(3)]
    for p in parts:
        p.unlink(missing_ok=True)
    procs = [subprocess.Popen(
        [str(native / "dp"), "--world", "3", "--backend", "tcp",
         "--rank", str(r), "--coordinator", f"127.0.0.1:{port}",
         "--num_buckets", "2", "--fault", plan,
         "--fault_policy", "shrink", "--out", str(parts[r])]
        + _fault_base(repo),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for r in range(3)]
    rcs = [p.wait(timeout=300) for p in procs]
    # the victim MUST die (nonzero, record-less); the survivors finish
    if rcs[1] == 0 or rcs[0] != 0 or rcs[2] != 0:
        print(f"  FAILED rcs={rcs}", file=sys.stderr)
        failed += 1
    else:
        try:
            merge_files(records, [parts[0], parts[2]])
        except ValueError as e:
            print(f"  merge failed: {e}", file=sys.stderr)
            failed += 1
    for p in parts:
        p.unlink(missing_ok=True)

    # 3. drop + retry (tcp, 2 processes, 20 % loss with backoff)
    plan = json.dumps({"events": [{"kind": "drop", "ranks": [0],
                                   "iteration": 0, "rate": 0.2,
                                   "magnitude_us": 200, "seed": 42}]})
    print("[fault 3/5] drop+retry: dp/tcp world 2, 20 % injected frame "
          "loss, exponential backoff", flush=True)
    port = free_port()
    parts = [records.parent / f".fault_d{r}.jsonl" for r in range(2)]
    for p in parts:
        p.unlink(missing_ok=True)
    procs = [subprocess.Popen(
        [str(native / "dp"), "--world", "2", "--backend", "tcp",
         "--rank", str(r), "--coordinator", f"127.0.0.1:{port}",
         "--num_buckets", "2", "--fault", plan,
         "--fault_policy", "retry", "--out", str(parts[r])]
        + _fault_base(repo),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for r in range(2)]
    rcs = [p.wait(timeout=300) for p in procs]
    if any(rcs):
        print(f"  FAILED rcs={rcs}", file=sys.stderr)
        failed += 1
    else:
        try:
            merge_files(records, parts)
        except ValueError as e:
            print(f"  merge failed: {e}", file=sys.stderr)
            failed += 1
    for p in parts:
        p.unlink(missing_ok=True)

    # 4. preempt + rejoin (tcp, 3 processes): rank 1 is gracefully
    # evicted at step 4 (20 ms drain), survivors run degraded, everyone
    # re-splits onto the pre-built full-world comm at step 8 — ALL
    # THREE ranks emit records, degraded_world is cleared, rejoin_ms
    # measures the grow rendezvous (fault_session.hpp's grow half)
    plan = json.dumps(elastic_plan(0, warmup=1))
    print("[fault 4/5] preempt+rejoin: dp/tcp world 3, rank 1 evicted "
          "(20 ms grace), rejoins 4 steps later — full world restored",
          flush=True)
    port = free_port()
    parts = [records.parent / f".fault_e{r}.jsonl" for r in range(3)]
    for p in parts:
        p.unlink(missing_ok=True)
    procs = [subprocess.Popen(
        [str(native / "dp"), "--world", "3", "--backend", "tcp",
         "--rank", str(r), "--coordinator", f"127.0.0.1:{port}",
         "--num_buckets", "2", "--fault", plan,
         "--fault_policy", "shrink", "--out", str(parts[r])]
        + _fault_base(repo, runs=ELASTIC_RUNS),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for r in range(3)]
    rcs = [p.wait(timeout=300) for p in procs]
    if any(rcs):
        print(f"  FAILED rcs={rcs}", file=sys.stderr)
        failed += 1
    else:
        try:
            merge_files(records, parts)
        except ValueError as e:
            print(f"  merge failed: {e}", file=sys.stderr)
            failed += 1
    for p in parts:
        p.unlink(missing_ok=True)

    # 5. the seeded goodput-vs-interval sweep (python tier: it owns the
    # checkpoint subsystem): the full preempt -> drain-save -> restore
    # -> shrink -> rejoin arc at every checkpoint interval x seed, each
    # a fresh cli subprocess on the virtual mesh, stall-mode npz saves
    # (the whole durable write on the timed path — the Daly model's d).
    # fault_report fits the model and verdicts measured-vs-predicted.
    n_pts = len(ELASTIC_INTERVALS) * len(ELASTIC_SEEDS)
    print(f"[fault 5/5] goodput sweep: dp x {args.devices} virtual "
          f"devices, intervals {ELASTIC_INTERVALS} x seeds "
          f"{ELASTIC_SEEDS} ({n_pts} runs)", flush=True)
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.devices}")
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    for every in ELASTIC_INTERVALS:
        for seed in ELASTIC_SEEDS:
            ckpt_dir = records.parent / f".ckpt_e{every}_s{seed}"
            rc = subprocess.run(
                [sys.executable, "-m", "dlnetbench_tpu.cli", "dp",
                 "--model", FAULT_MODEL, "--platform", "cpu",
                 "--num_buckets", "2", "-r", str(ELASTIC_RUNS),
                 "-w", "1", "--size_scale", "0.0001",
                 "--time_scale", "0.001", "--no_topology",
                 "--fault", json.dumps(elastic_plan(seed, warmup=1)),
                 "--checkpoint_dir", str(ckpt_dir),
                 "--checkpoint_every", str(every),
                 "--checkpoint_mode", "stall",
                 "--checkpoint_backend", "npz",
                 "--tag", f"elastic_seed={seed}",
                 "--out", str(records)],
                env=env, stdout=subprocess.DEVNULL).returncode
            import shutil
            shutil.rmtree(ckpt_dir, ignore_errors=True)
            if rc != 0:
                print(f"  FAILED every={every} seed={seed} rc={rc}",
                      file=sys.stderr)
                failed += 1
    return failed


def fault_report(args, records: Path) -> int:
    from dlnetbench_tpu.analysis.bandwidth import bandwidth_summary, \
        straggler_amplification
    from dlnetbench_tpu.metrics.parser import load_records

    recs = load_records(records)
    print("\n=== fault study: one row per record "
          "(docs/RESILIENCE.md columns) ===")
    header = (f"{'section':<8} {'fault':<18} {'policy':<10} "
              f"{'straggler_amp':>13} {'detection_ms':>12} "
              f"{'recovery_ms':>11} {'rejoin_ms':>10} {'ckpt_ms':>8} "
              f"{'lost':>5} {'goodput':>8} {'drops':>6} {'retries':>8} "
              f"degraded_world")
    print(header)

    def _f(v, width, prec=3):
        return (f"{v:>{width}.{prec}f}" if isinstance(v, (int, float))
                else f"{'-':>{width}}")

    for rec in recs:
        g = rec.get("global", {})
        plan = g.get("fault_plan") or {}
        kinds = "+".join(sorted({e.get("kind", "?")
                                 for e in plan.get("events", [])})) or "-"
        amp = straggler_amplification(rec)
        print(f"{rec.get('section', '?'):<8} {kinds:<18} "
              f"{g.get('fault_policy', '-'):<10} "
              f"{amp if amp == amp else float('nan'):>13.3f} "
              f"{_f(g.get('detection_ms'), 12)} "
              f"{_f(g.get('recovery_ms'), 11)} "
              f"{_f(g.get('rejoin_ms'), 10)} "
              f"{_f(g.get('checkpoint_ms'), 8)} "
              f"{_f(g.get('lost_steps'), 5, 0)} "
              f"{_f(g.get('goodput'), 8, 2)} "
              f"{g.get('fault_drops', 0):>6} "
              f"{g.get('fault_retries', 0):>8} "
              f"{g.get('degraded_world', '-')}")

    # the Daly-interval validation over the goodput sweep records
    # (analysis/goodput.py): nonzero when the measured optimum falls
    # OUTSIDE the model's prediction band — the study's acceptance
    # criterion, enforced at generation time, not just documented
    rc = 0
    from dlnetbench_tpu.analysis import goodput as goodput_mod
    try:
        verdict = goodput_mod.validate_sweep(recs)
    except ValueError:
        verdict = None  # no sweep records in this artifact
    if verdict is not None:
        print("\n=== checkpoint-interval planning: measured goodput vs "
              "the Daly model (analysis/goodput.py) ===")
        rc = 0 if verdict["in_band"] else 1
        goodput_mod.report(records, verdict=verdict)
        with open(args.out_dir / "goodput_verdict.json", "w") as f:
            json.dump(verdict, f, indent=1)

    bw = bandwidth_summary(recs)
    if not bw.empty:
        print("\n=== bandwidth under fault: faulted runs busbw-refused, "
              "clean runs keep their figures ===")
        cols = ["section", "collective", "bound", "time_us",
                "algbw_GBps", "busbw_GBps", "straggler_amp"]
        print(bw[cols].to_string(
            index=False, float_format=lambda v: f"{v:10.3f}"))
        bw.to_csv(args.out_dir / "fault_bandwidth_summary.csv",
                  index=False)
    print(f"\nwrote {records} and "
          f"{args.out_dir}/fault_bandwidth_summary.csv")
    return rc


def report(args, records: Path) -> None:
    import pandas as pd

    from dlnetbench_tpu.analysis import plots
    from dlnetbench_tpu.analysis.bandwidth import bandwidth_summary
    from dlnetbench_tpu.metrics.parser import load_records, \
        records_to_dataframe

    recs = load_records(records)
    df = records_to_dataframe(recs)

    # honesty note (VERDICT r3 #8): hier points fall back to the HOST
    # executor when no usable TPU plugin is present — those numbers
    # describe a virtual mesh on this machine's CPU, not TPU devices
    hier_hosted = sum(1 for r in recs
                      if r.get("global", {}).get("pjrt_executor") == "host")
    if hier_hosted:
        print(f"note: {hier_hosted}/{len(recs)} study points ran the "
              f"device path on the HOST executor (virtual mesh, no TPU "
              f"plugin) — fabric numbers are loopback, not ICI/DCN")

    # --- north-star table: iter time + effective bus GB/s per collective
    per_point = []
    for rec in recs:
        s = bandwidth_summary([rec])
        if s.empty:
            continue
        g = rec.get("global", {})
        # bandwidth_summary already carries model; add proxy + world size
        s.insert(0, "proxy", g.get("variables", {}).get("proxy",
                                                        rec.get("section")))
        s.insert(1, "world", len(rec.get("ranks", [])))
        s.insert(2, "sched", g.get("schedule", ""))
        per_point.append(s)
    if per_point:
        bw = pd.concat(per_point, ignore_index=True)
        # one line per (proxy, model, world, collective): the per-iteration
        # exposed time and the standard busbw figure
        # 'bound' rides along: "lower" rows (e.g. the native engine's
        # middle-stage pp_comm) must stay labeled in the table and CSV
        cols = ["proxy", "model", "world", "sched", "collective",
                "group_size", "bound", "time_us", "algbw_GBps",
                "busbw_GBps"]
        bw = (bw.groupby(cols[:7], as_index=False)[cols[7:]].mean()
              .sort_values(["proxy", "model", "world", "sched"]))[cols]
        print("\n=== effective bandwidth per collective "
              "(mean over ranks/runs) ===")
        print(bw.to_string(index=False,
                           float_format=lambda v: f"{v:10.2f}"))
        bw.to_csv(args.out_dir / "bandwidth_summary.csv", index=False)

    # --- runtime summary per study point (schedule column distinguishes
    # the hybrid_2d gpipe/1f1b/zb comparison points)
    group_cols = ["proxy", "model", "world_size"]
    if "schedule" in df:
        group_cols.append("schedule")
    summary = (df.groupby(group_cols, dropna=False)["runtime"]
               .mean().rename("runtime_us").reset_index())
    print("\n=== mean iteration runtime (us) ===")
    print(summary.to_string(index=False,
                            float_format=lambda v: f"{v:12.1f}"))

    # --- plots
    import matplotlib
    matplotlib.use("Agg")

    dp = df[df["proxy"] == "dp"]
    scaling = dp[dp["num_buckets"] == 4]
    if not scaling.empty:
        ax = plots.plot_runtime_scaling(scaling, group_by="model")
        ax.figure.savefig(args.out_dir / "dp_runtime_scaling.png", dpi=120)
    full = dp[dp["world_size"] == dp["world_size"].max()]
    if not full.empty:
        ax = plots.plot_barrier_scatter_by_bucket(full)
        ax.figure.savefig(args.out_dir / "dp_barrier_by_bucket.png", dpi=120)
    # cross-proxy exposure Pareto: mean runtime vs mean exposed comm.
    # Exposed-comm column differs per proxy; take the max-information one
    # present per proxy row (barrier_time for dp/fsdp, dp_comm_time for
    # the hybrids, ring/a2a wait for the sequence proxies).
    exposed_cols = [c for c in ("barrier_time", "dp_comm_time",
                                "ring_wait_time", "a2a_time") if c in df]
    if exposed_cols:
        exp = df.assign(exposed=df[exposed_cols].bfill(axis=1)
                        .iloc[:, 0]).dropna(subset=["exposed"])
        if not exp.empty:
            ax = plots.plot_pareto(exp, x="runtime", y="exposed",
                                   group_by="proxy")
            ax.figure.savefig(args.out_dir / "pareto_proxies.png", dpi=120)
    print(f"\nwrote {args.out_dir}/{{bandwidth_summary.csv,"
          f"dp_runtime_scaling,dp_barrier_by_bucket,pareto_proxies}}.png")


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out_dir", type=Path, default=Path("/tmp/pod_study"))
    ap.add_argument("--devices", type=int, default=8,
                    help="world size (CPU: virtual device count)")
    ap.add_argument("--platform", default="cpu", choices=("cpu", "tpu"),
                    help="cpu = virtual mesh dev box; tpu = real slice")
    ap.add_argument("--tier", default="jax", choices=("jax", "native"),
                    help="jax = python CLI over the device mesh; native = "
                         "the C++17 binaries (threaded shm fabric)")
    ap.add_argument("--backend", default="shm",
                    choices=("shm", "pjrt-hier"),
                    help="native tier fabric: shm (threaded, one process) "
                         "or pjrt-hier (--procs OS processes, per-process "
                         "executor + TCP DCN combine — the multi-host "
                         "device path; records merged per point)")
    ap.add_argument("--procs", type=int, default=2,
                    help="pjrt-hier: number of OS processes composing the "
                         "DCN mesh; worlds that do not divide evenly get "
                         "the balanced uneven layout (first world%%procs "
                         "processes host one extra rank)")
    ap.add_argument("--fault", action="store_true",
                    help="run the fault-injection study instead of the "
                         "proxy grid: a straggler point (fsdp/shm, "
                         "measured amplification), a rank-crash point "
                         "(dp/tcp, shrink policy, detection/recovery + "
                         "degraded merge), a drop point (dp/tcp, retry "
                         "policy with backoff counts), a preempt+rejoin "
                         "point (dp/tcp, graceful eviction, full world "
                         "restored, rejoin_ms), and the seeded "
                         "goodput-vs-checkpoint-interval sweep the Daly "
                         "model is validated against (python tier, "
                         "analysis/goodput.py) — one records.jsonl "
                         "artifact; docs/RESILIENCE.md")
    ap.add_argument("--serving", action="store_true",
                    help="run the serving latency-vs-load study instead "
                         "of the proxy grid: capacity calibration, an "
                         "offered-load sweep (fractions of capacity x "
                         "arrival seeds, p99/goodput-at-SLO bands, "
                         "saturation-knee verdict) and a straggler-"
                         "composed point proving fault plans inflate "
                         "serving p99 — one records.jsonl artifact "
                         "(docs/SERVING.md)")
    ap.add_argument("--disagg", action="store_true",
                    help="with --serving: run the sweep over the "
                         "DISAGGREGATED prefill/decode engine "
                         "(ISSUE 16; 2 capacity ranks split 1 prefill "
                         "+ 1 decode, KV pages migrating in the "
                         "stored dtype) — the serving_summary carries "
                         "the migration_* columns; run once without "
                         "and once with into different --out_dir for "
                         "the Pareto comparison (docs/studies/"
                         "disagg_r17 automates exactly that)")
    ap.add_argument("--fleet", action="store_true",
                    help="with --serving: run the sweep over a "
                         "two-replica FLEET (ISSUE 18; seeded p2c "
                         "router over independent engines, each with "
                         "its own page pool) — the serving_summary "
                         "carries the fleet_* columns; compare "
                         "against a plain --serving run into a "
                         "different --out_dir for the equal-chips "
                         "question (docs/studies/fleet_r18 holds the "
                         "committed routing/autoscale/crash bars)")
    ap.add_argument("--kv_density", action="store_true",
                    help="run the serving-density study instead of the "
                         "proxy grid (ISSUE 12): dense vs int8 vs fp8 "
                         "paged-KV at equal pool bytes (admitted "
                         "concurrency + goodput-at-SLO + decode-parity "
                         "bars) and a prefix-heavy shared-system-"
                         "prompt plan with sharing off/on (lossless, "
                         "hit-rate/bytes-saved, TTFT deltas) — "
                         "generation FAILS unless the acceptance bars "
                         "hold (docs/SERVING.md 'Cache density')")
    ap.add_argument("--congest", action="store_true",
                    help="run a dp_loop congestor pair (native TCP fabric) "
                         "for the duration of the sweep — sustained "
                         "background frames sharing the DCN transport "
                         "path, the reference's _loop interference shape "
                         "(Makefile.common:96-109) composed with the "
                         "hier study; the study README/json records it")
    ap.add_argument("--congest_model", default="gpt2_l_16_bfloat16")
    ap.add_argument("--models", default=f"{DENSE},{MOE}",
                    help="comma-separated stats-file names")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--size_scale", type=float, default=1e-4,
                    help="buffer shrink factor (CPU default)")
    ap.add_argument("--time_scale", type=float, default=1e-4,
                    help="burn-time shrink factor (CPU default)")
    ap.add_argument("--full_scale", action="store_true",
                    help="real buffer sizes and burn times (pod runs)")
    ap.add_argument("--report_only", action="store_true",
                    help="skip the sweep; re-analyze an existing "
                         "records.jsonl in --out_dir")
    args = ap.parse_args()
    if args.disagg and args.fleet:
        ap.error("--disagg and --fleet are different serving arms — "
                 "run them into separate --out_dir (the engine refuses "
                 "the composition too)")
    if args.backend == "pjrt-hier" and args.tier != "native":
        ap.error("--backend pjrt-hier applies to --tier native (the jax "
                 "tier composes ICI x DCN through jax.distributed instead)")
    if args.tier == "native" and args.platform != "cpu":
        ap.error("--tier native runs the C++ binaries on the threaded shm "
                 "fabric (host CPU); --platform tpu applies only to the "
                 "jax tier. For TPU runs on the native tier use the "
                 "binaries' --backend pjrt directly.")

    args.out_dir.mkdir(parents=True, exist_ok=True)
    records = args.out_dir / "records.jsonl"
    failed = 0
    if args.kv_density:
        failed = run_kv_density_study(args.out_dir)
        if failed:
            print("\nkv-density study failed its acceptance bars",
                  file=sys.stderr)
        return 1 if failed else 0
    if args.serving:
        if not args.report_only:
            records.unlink(missing_ok=True)
            failed = run_serving_plan(args, records)
        failed += serving_report(args, records)
        if failed:
            print(f"\n{failed} serving study point(s) failed",
                  file=sys.stderr)
        return 1 if failed else 0
    if args.fault:
        if not args.report_only:
            records.unlink(missing_ok=True)
            failed = run_fault_plan(args, records)
        failed += fault_report(args, records)
        if failed:
            print(f"\n{failed} fault study point(s) failed",
                  file=sys.stderr)
        return 1 if failed else 0
    if not args.report_only:
        records.unlink(missing_ok=True)
        # a stale marker from an earlier --congest sweep into the same
        # dir would mislabel THIS solo run's tables
        (args.out_dir / "CONGESTED").unlink(missing_ok=True)
        plan = build_plan([m for m in args.models.split(",") if m],
                          args.devices)
        congestors = _start_congestors(args) if args.congest else []
        try:
            failed = run_plan(plan, args, records)
        finally:
            from dlnetbench_tpu.utils.congest import kill_group
            kill_group(congestors)
    report(args, records)
    if failed:
        print(f"\n{failed} study point(s) failed", file=sys.stderr)
    return 1 if failed else 0


def _start_congestors(args) -> list:
    """A dp_loop pair over the native TCP fabric, running for the whole
    sweep: its frames share the DCN transport path (loopback here, real
    links on a cluster) with every hier point's combine legs — the
    reference's `_loop` interference composition.  Study output marks
    the run so congested tables are never mistaken for solo ones."""
    from dlnetbench_tpu.utils import congest
    from dlnetbench_tpu.utils.native_build import native_bin as _locate

    repo = Path(__file__).resolve().parent.parent
    procs = congest.launch_pair_retry(
        _locate(str(repo)), "dp_loop", args.congest_model, repo,
        args.time_scale, max(args.size_scale * 10, 1e-3),
        extra=["--num_buckets", "4"])
    (args.out_dir / "CONGESTED").write_text(
        f"sweep ran with a dp_loop x2 congestor pair "
        f"(model {args.congest_model}) sharing the DCN transport\n")
    print("congestor pair running (dp_loop x2 over tcp)", flush=True)
    return procs


if __name__ == "__main__":
    raise SystemExit(main())
