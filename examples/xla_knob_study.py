#!/usr/bin/env python3
"""XLA compiler-knob sweep over the headline train step — the TPU
analogue of the reference's NCCL tuning-space study (reference
plots/plot_dp.py:23-26 sweeps protocol x algorithm x threads x
channels and Pareto-plots the result; on one TPU chip the tunable
surface is the XLA compile, reached through per-compile
``compiler_options``).

Knobs (>=2 axes x >=3 values, VERDICT r3 #5):
  * xla_tpu_scoped_vmem_limit_kib: 16 MiB (compiler default) / 24 / 32
    (the r2 winner) / 48 / 64 MiB — how much VMEM the scheduler may
    dedicate to one fusion's tiles;
  * xla_tpu_enable_latency_hiding_scheduler: on/off — the scheduler
    that overlaps DMA with compute across ops.

Each point recompiles the SAME train step (bench.py shape) and runs
K-chained measured rounds; output is a table + CSV, and the winner is
adopted into bench.py or declined with numbers (docs/PERF.md).

    python examples/xla_knob_study.py --out_dir docs/studies/xla_knob_sweep
"""
from __future__ import annotations

import argparse
import itertools
import json
import statistics
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

VMEM_KIB = (16384, 24576, 32768, 49152, 65536)
LHS = ("default", "on", "off")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out_dir", type=Path,
                    default=Path("/tmp/xla_knob_sweep"))
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--k", type=int, default=10,
                    help="train steps chained per program")
    args = ap.parse_args()
    args.out_dir.mkdir(parents=True, exist_ok=True)

    import jax

    from dlnetbench_tpu.models import bench_step
    from dlnetbench_tpu.utils.timing import time_callable

    if jax.default_backend() != "tpu":
        print("needs the real TPU backend (compiler_options are "
              "TPU-compiler flags)", file=sys.stderr)
        return 1

    # EXACTLY the headline step (shared builder — a sweep winner tuned
    # on a drifted copy would be adopted into a different program)
    K = args.k
    train_k, params, tokens, _card, _cfg = bench_step.build(K)

    rows = []
    points = list(itertools.product(VMEM_KIB, LHS))
    for idx, (vmem, lhs) in enumerate(points):
        opts = {"xla_tpu_scoped_vmem_limit_kib": str(vmem)}
        if lhs != "default":
            opts["xla_tpu_enable_latency_hiding_scheduler"] = (
                "true" if lhs == "on" else "false")
        label = f"vmem={vmem//1024}MiB lhs={lhs}"
        try:
            # AOT via the execution engine: compile time is the recorded
            # compile_ms, params are donated to a per-point private copy
            # (each grid point's executable owns its own carry, so the
            # shared params survive the whole sweep)
            from dlnetbench_tpu.core import executor
            f = executor.CompiledProgram(executor.Program(
                fn=train_k, args=(params, tokens),
                donate_argnums=bench_step.DONATE_ARGNUMS,
                compiler_options=opts))
            _, losses = f()
            losses[-1].item()
        except Exception as e:  # an unknown/rejected flag combination
            print(f"[{idx+1}/{len(points)}] {label}: compile FAILED "
                  f"({type(e).__name__}: {str(e)[:120]})", flush=True)
            rows.append({"vmem_kib": vmem, "lhs": lhs,
                         "step_ms": None, "error": str(e)[:200]})
            continue
        compile_s = f.stats["compile_ms"] / 1e3
        samples = [t / K for t in time_callable(f, reps=args.reps)]
        step_ms = statistics.median(samples) * 1e3
        print(f"[{idx+1}/{len(points)}] {label}: {step_ms:.1f} ms "
              f"(compile {compile_s:.0f}s, spread "
              f"{(max(samples)-min(samples))*1e3:.1f} ms)", flush=True)
        rows.append({"vmem_kib": vmem, "lhs": lhs,
                     "step_ms": round(step_ms, 2),
                     "compile_s": round(compile_s, 1)})

    out = args.out_dir / "xla_knob_sweep.json"
    out.write_text(json.dumps(rows, indent=1))
    ok = [r for r in rows if r.get("step_ms")]
    if ok:
        best = min(ok, key=lambda r: r["step_ms"])
        print(f"\nbest: vmem={best['vmem_kib']//1024}MiB "
              f"lhs={best['lhs']} at {best['step_ms']} ms")
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
