"""Native fault-injection subsystem (fault_plan.hpp / fault_session.hpp):
the fault_selftest binary's policy matrix on the shm and tcp fabrics,
plus the dp proxy's faulted records through the analysis pipeline.

Default lane keeps one representative per family (shm shrink, tcp crash
fail-fast — the FIRST controlled end-to-end test of the PR-2 ``dying_``
flag + transitive fail-fast path — and the shm straggler record);
the wider matrix (tcp shrink + merge, drop policies, hier delay) is the
opt-in ``-m native_slow`` lane, and the crash paths also run under TSan
(test_native.py::test_native_tsan_fabrics)."""
from __future__ import annotations

import json
import os
import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(
    not os.environ.get("DLNB_NATIVE_BIN")
    and (shutil.which("cmake") is None or shutil.which("ninja") is None),
    reason="cmake/ninja not available (set DLNB_NATIVE_BIN to a "
           "prebuilt bin dir to run anyway)")

# every survivor must RAISE within this budget, never hang — the
# watchdog-style bound satellite 1 asserts on the provoked death path
WATCHDOG_BUDGET_S = 30

CRASH_PLAN = '{"events":[{"kind":"crash","ranks":[1],"iteration":3}]}'
DELAY_PLAN = ('{"events":[{"kind":"delay","ranks":[2],"iteration":3,'
              '"magnitude_us":30000}]}')
DROP_PLAN = ('{"events":[{"kind":"drop","ranks":[0],"iteration":0,'
             '"rate":0.2,"magnitude_us":200,"seed":42}]}')
REJOIN_PLAN = ('{"policy":"shrink","events":['
               '{"kind":"preempt","ranks":[1],"iteration":3,'
               '"magnitude_us":5000},'
               '{"kind":"rejoin","ranks":[1],"iteration":7}]}')
PREEMPT_ONLY_PLAN = ('{"policy":"shrink","events":['
                     '{"kind":"preempt","ranks":[1],"iteration":3,'
                     '"magnitude_us":5000}]}')


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_tcp(native_bin, binary, world, rank, port, *extra, env=None):
    import os
    return subprocess.Popen(
        [str(native_bin / binary), "--backend", "tcp",
         "--world", str(world), "--rank", str(rank),
         "--coordinator", f"127.0.0.1:{port}", *map(str, extra)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, **(env or {})})


def _communicate_all(procs, timeout=WATCHDOG_BUDGET_S):
    outs = []
    for p in procs:
        try:
            outs.append(p.communicate(timeout=timeout)[0])
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append(p.communicate()[0] + "\n<TIMEOUT: survivor hung "
                        "past the watchdog budget>")
    return outs


# ------------------------------------------------------------- shm lane
def test_shm_crash_shrink_survivors_finish(native_bin):
    """Elastic degradation on the threaded fabric: the scripted victim
    dies, survivors regroup on the pre-split survivor comm, finish all
    iterations with exact survivor-group sums, and report measured
    detection/recovery."""
    out = subprocess.run(
        [str(native_bin / "fault_selftest"), "--world", "4", "--iters",
         "6", "--fault", '{"events":[{"kind":"crash","ranks":[2],'
         '"iteration":3}]}', "--fault_policy", "shrink"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    rows = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    assert [r["rank"] for r in rows] == [0, 1, 3]  # victim emits nothing
    for r in rows:
        assert r["checks"] == "OK" and r["iters_done"] == 6
        assert r["shrunk"] is True
        assert r["degraded_world"] == [0, 1, 3]
        assert r["detection_us"] > 0 and r["recovery_us"] > 0


def test_shm_preempt_rejoin_restores_full_world(native_bin):
    """The grow half (ISSUE 7 tentpole) on the threaded fabric: the
    evictee drains its grace window and replays locally, survivors run
    the degraded window on the pre-split comm, and at the rejoin
    trigger EVERY rank re-splits onto the pre-built full-world comm —
    exact full-world sums again, rejoin cost measured, nobody dies."""
    out = subprocess.run(
        [str(native_bin / "fault_selftest"), "--world", "4", "--iters",
         "10", "--fault", REJOIN_PLAN, "--fault_policy", "shrink"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    rows = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    # ALL ranks emit (the evictee never died) and all rejoined
    assert [r["rank"] for r in rows] == [0, 1, 2, 3]
    for r in rows:
        assert r["checks"] == "OK" and r["iters_done"] == 10
        assert r["rejoined"] is True
        assert r["rejoin_us"] > 0
        assert r["shrunk"] is False  # grow, not shrink: nobody crashed
        assert r["degraded_world"] == [0, 1, 2, 3]  # full again
    by_rank = {r["rank"]: r for r in rows}
    # the evictee slept its grace window; the others did not
    assert by_rank[1]["injected_delay_us"] >= 5000
    assert by_rank[0]["injected_delay_us"] == 0.0


def test_shm_crash_fail_fast_aborts_not_hangs(native_bin):
    """A dead in-process rank must ABORT the run promptly (the new
    group-poisoning path): before this subsystem, survivors blocked in
    a rendezvous waited forever for the dead rank."""
    out = subprocess.run(
        [str(native_bin / "fault_selftest"), "--world", "4", "--iters",
         "6", "--fault", '{"events":[{"kind":"crash","ranks":[2],'
         '"iteration":3}]}'],
        capture_output=True, text=True, timeout=WATCHDOG_BUDGET_S)
    assert out.returncode != 0
    blob = out.stdout + out.stderr
    assert "crashed by fault plan" in blob or "died during a collective" \
        in blob, blob


def test_shm_delay_and_retry_policies(native_bin):
    """Delay: injected straggler latency is accounted per rank; drop +
    retry on the shm fabric resolves locally (no frame layer) and the
    run completes exact."""
    out = subprocess.run(
        [str(native_bin / "fault_selftest"), "--world", "4", "--iters",
         "4", "--fault", DELAY_PLAN],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    rows = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    by_rank = {r["rank"]: r for r in rows}
    assert by_rank[2]["injected_delay_us"] >= 30000  # step 3 in-window
    assert by_rank[0]["injected_delay_us"] == 0.0


def test_fsdp_shm_straggler_record_through_analysis(native_bin, tmp_path):
    """An fsdp run with a straggler plan (fsdp declares a comm_model,
    so it feeds the bandwidth table) emits a v2 record whose faulted
    runs are busbw-refused (bound 'faulted') while the clean runs keep
    their figures, and the summary reports the measured
    straggler-amplification — the study's core readout."""
    from dlnetbench_tpu.analysis.bandwidth import bandwidth_summary, \
        straggler_amplification
    from dlnetbench_tpu.metrics.parser import validate_record

    out = subprocess.run(
        [str(native_bin / "fsdp"), "--model", "gpt2_l_16_bfloat16",
         "--world", "4", "--num_units", "4", "--sharding_factor", "2",
         "--time_scale", "0.001", "--size_scale", "0.0001",
         "--runs", "6", "--warmup", "1",
         "--no_topology", "--base_path", str(REPO),
         "--fault", '{"events":[{"kind":"delay","ranks":[2],'
         '"iteration":4,"magnitude_us":30000}]}'],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    rec = json.loads(out.stdout)
    validate_record(rec)
    g = rec["global"]
    assert g["fault_policy"] == "fail_fast"
    assert g["fault_injected_delay_us"] >= 3 * 30000  # steps 4,5,6
    rows = {r["rank"]: r for r in rec["ranks"]}
    assert rows[2]["fault_injected_delay_us"] >= 3 * 30000
    assert rows[0]["fault_injected_delay_us"] == 0.0
    # runs 3.. (steps 4..) are the faulted window
    amp = straggler_amplification(rec)
    assert 0.5 < amp < 3.0, amp  # the sleep gates every rank's step
    s = bandwidth_summary([rec])
    assert set(s["bound"]) == {"exact", "faulted"}
    faulted = s[s["bound"] == "faulted"]
    assert faulted["busbw_GBps"].isna().all()
    assert (faulted["straggler_amp"] > 0.5).all()
    clean = s[s["bound"] == "exact"]
    assert clean["busbw_GBps"].notna().all()


def test_unwired_proxy_refuses_step_scoped_plan(native_bin):
    """Proxies without a step-boundary fault driver must refuse plans
    whose events could only fire at step boundaries — otherwise the
    record would stamp fault provenance onto an actually-clean run —
    while collective-scoped plans still apply through the fabric
    hooks."""
    base = [str(native_bin / "hybrid_2d"), "--model",
            "gpt2_l_16_bfloat16", "--world", "4", "--num_stages", "4",
            "--num_microbatches", "4", "--runs", "1", "--warmup", "1",
            "--time_scale", "0.0001", "--size_scale", "0.00001",
            "--no_topology", "--base_path", str(REPO)]
    out = subprocess.run(base + ["--fault", DELAY_PLAN],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
    assert "no step-boundary fault driver" in out.stderr
    coll = ('{"events":[{"kind":"delay","ranks":[1],"iteration":0,'
            '"magnitude_us":100,"where":"collective"}]}')
    out = subprocess.run(base + ["--fault", coll],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    rec = json.loads(out.stdout)
    assert rec["global"]["fault_injected_delay_us"] > 0


def test_fsdp_refuses_crash_shrink_plan(native_bin):
    """The ZeRO grid cannot regroup around a dead rank: a crash+shrink
    plan must be refused loudly, never half-applied."""
    out = subprocess.run(
        [str(native_bin / "fsdp"), "--model", "gpt2_l_16_bfloat16",
         "--world", "4", "--num_units", "2", "--sharding_factor", "2",
         "--runs", "1", "--warmup", "1", "--no_topology",
         "--base_path", str(REPO), "--fault", CRASH_PLAN,
         "--fault_policy", "shrink"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode != 0
    assert "shrink" in out.stderr


# ------------------------------------------------------------- tcp lane
def test_tcp_crash_fail_fast_every_survivor_raises(native_bin):
    """SATELLITE 1 — the first CONTROLLED end-to-end exercise of the
    PR-2 ``dying_`` flag + transitive fail-fast: a crash-plan victim
    dies mid-run WITHOUT a Bye (mark_rank_dead -> mark_dying), and
    every survivor must raise (not hang) within the watchdog budget,
    with a death diagnostic."""
    port = _free_port()
    procs = [_spawn_tcp(native_bin, "fault_selftest", 3, r, port,
                        "--iters", 6, "--fault", CRASH_PLAN)
             for r in range(3)]
    outs = _communicate_all(procs)
    assert procs[1].returncode != 0  # the victim
    assert "crashed by fault plan" in outs[1]
    for r in (0, 2):
        assert procs[r].returncode != 0, \
            f"survivor {r} exited 0 after scripted peer death:\n{outs[r]}"
        assert "TIMEOUT" not in outs[r], outs[r]
        assert ("disconnected mid-run" in outs[r]
                or "peer gone" in outs[r]), outs[r]


@pytest.mark.slow
@pytest.mark.native_slow
def test_tcp_crash_fail_fast_wide_world(native_bin):
    """The native_slow half of satellite 1: the same provoked-death
    fail-fast at world 5 — non-neighbor survivors whose signal arrives
    only transitively must also raise within the budget."""
    port = _free_port()
    plan = '{"events":[{"kind":"crash","ranks":[2],"iteration":3}]}'
    procs = [_spawn_tcp(native_bin, "fault_selftest", 5, r, port,
                        "--iters", 8, "--fault", plan)
             for r in range(5)]
    outs = _communicate_all(procs, timeout=60)
    assert procs[2].returncode != 0
    for r in (0, 1, 3, 4):
        assert procs[r].returncode != 0, \
            f"survivor {r} exited 0 after scripted peer death:\n{outs[r]}"
        assert "TIMEOUT" not in outs[r], outs[r]


@pytest.mark.slow
@pytest.mark.native_slow
def test_tcp_crash_shrink_survivors_finish(native_bin):
    port = _free_port()
    procs = [_spawn_tcp(native_bin, "fault_selftest", 3, r, port,
                        "--iters", 6, "--fault", CRASH_PLAN,
                        "--fault_policy", "shrink")
             for r in range(3)]
    outs = _communicate_all(procs, timeout=60)
    assert procs[1].returncode != 0  # dead is dead
    for r in (0, 2):
        assert procs[r].returncode == 0, f"survivor {r}:\n{outs[r]}"
        row = json.loads([ln for ln in outs[r].splitlines()
                          if ln.startswith("{")][0])
        assert row["shrunk"] is True
        assert row["degraded_world"] == [0, 2]
        assert row["iters_done"] == 6 and row["checks"] == "OK"
        assert row["detection_us"] > 0 and row["recovery_us"] > 0


@pytest.mark.slow
@pytest.mark.native_slow
def test_tcp_drop_retry_and_fail_fast(native_bin):
    """Drop + retry: every frame eventually delivered with backoff
    counted; drop + fail_fast: the first loss aborts."""
    port = _free_port()
    procs = [_spawn_tcp(native_bin, "fault_selftest", 2, r, port,
                        "--iters", 5, "--fault", DROP_PLAN,
                        "--fault_policy", "retry")
             for r in range(2)]
    outs = _communicate_all(procs, timeout=60)
    for r in range(2):
        assert procs[r].returncode == 0, f"rank {r}:\n{outs[r]}"
    row0 = json.loads([ln for ln in outs[0].splitlines()
                       if ln.startswith("{")][0])
    assert row0["drops"] >= 1 and row0["retries"] == row0["drops"]
    assert row0["injected_delay_us"] > 0

    port = _free_port()
    procs = [_spawn_tcp(native_bin, "fault_selftest", 2, r, port,
                        "--iters", 5, "--fault", DROP_PLAN)
             for r in range(2)]
    outs = _communicate_all(procs, timeout=60)
    assert any(p.returncode != 0 for p in procs)
    assert any("injected frame drop" in o for o in outs), outs


@pytest.mark.slow
@pytest.mark.native_slow
def test_dp_tcp_crash_shrink_merge_degraded(native_bin, tmp_path):
    """The acceptance chain on the cross-process fabric: dp under a
    crash plan with shrink — the victim process dies record-less, the
    survivors emit degraded records (detection/recovery/degraded_world)
    that metrics.merge reassembles through the degraded pathway."""
    from dlnetbench_tpu.metrics.merge import merge_files
    from dlnetbench_tpu.metrics.parser import records_to_dataframe, \
        validate_record

    port = _free_port()
    world = 3
    outs_p = [tmp_path / f"p{r}.jsonl" for r in range(world)]
    procs = [subprocess.Popen(
        [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
         "--world", str(world), "--backend", "tcp", "--rank", str(r),
         "--coordinator", f"127.0.0.1:{port}", "--num_buckets", "2",
         "--time_scale", "0.001", "--size_scale", "0.0001",
         "--runs", "5", "--warmup", "1", "--no_topology",
         "--base_path", str(REPO), "--fault", CRASH_PLAN,
         "--fault_policy", "shrink", "--out", str(outs_p[r])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(world)]
    texts = _communicate_all(procs, timeout=120)
    assert procs[1].returncode != 0, texts[1]   # the victim
    assert not outs_p[1].exists()               # and it emits NO record
    for r in (0, 2):
        assert procs[r].returncode == 0, f"survivor {r}:\n{texts[r]}"

    merged = merge_files(tmp_path / "merged.jsonl",
                         [outs_p[0], outs_p[2]])
    validate_record(merged)
    assert [row["rank"] for row in merged["ranks"]] == [0, 2]
    g = merged["global"]
    assert g["degraded_world"] == [0, 2]
    assert g["detection_ms"] > 0 and g["recovery_ms"] > 0
    df = records_to_dataframe([merged])
    assert len(df) == 2 * merged["num_runs"]
    assert (df["runtime"] > 0).all()


@pytest.mark.slow
@pytest.mark.native_slow
def test_tcp_preempt_rejoin_all_ranks_finish(native_bin):
    """The grow half across OS processes: the returning rank is
    accepted deterministically on the plan-known fresh comm — all
    three processes finish with exact sums and measured rejoin cost."""
    port = _free_port()
    procs = [_spawn_tcp(native_bin, "fault_selftest", 3, r, port,
                        "--iters", 10, "--fault", REJOIN_PLAN,
                        "--fault_policy", "shrink")
             for r in range(3)]
    outs = _communicate_all(procs, timeout=60)
    for r in range(3):
        assert procs[r].returncode == 0, f"rank {r}:\n{outs[r]}"
        row = json.loads([ln for ln in outs[r].splitlines()
                          if ln.startswith("{")][0])
        assert row["rejoined"] is True and row["rejoin_us"] > 0
        assert row["iters_done"] == 10 and row["checks"] == "OK"


@pytest.mark.slow
@pytest.mark.native_slow
def test_dp_tcp_preempt_rejoin_record_full_world(native_bin, tmp_path):
    """The native-tier end-to-end rejoin acceptance: dp under a
    preempt->rejoin plan — ALL processes emit records (the evictee
    drained, nobody died), the merged record CLEARS degraded_world,
    stamps fault_rejoin_step + rejoin_ms, and parses with full rank
    coverage."""
    from dlnetbench_tpu.metrics.merge import merge_files
    from dlnetbench_tpu.metrics.parser import records_to_dataframe, \
        validate_record

    port = _free_port()
    world = 3
    outs_p = [tmp_path / f"p{r}.jsonl" for r in range(world)]
    procs = [subprocess.Popen(
        [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
         "--world", str(world), "--backend", "tcp", "--rank", str(r),
         "--coordinator", f"127.0.0.1:{port}", "--num_buckets", "2",
         "--time_scale", "0.001", "--size_scale", "0.0001",
         "--runs", "10", "--warmup", "1", "--no_topology",
         "--base_path", str(REPO), "--fault", REJOIN_PLAN,
         "--fault_policy", "shrink", "--out", str(outs_p[r])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(world)]
    texts = _communicate_all(procs, timeout=120)
    for r in range(world):
        assert procs[r].returncode == 0, f"process {r}:\n{texts[r]}"
        assert outs_p[r].exists()  # the evictee emits too

    merged = merge_files(tmp_path / "merged.jsonl", outs_p)
    validate_record(merged)
    assert [row["rank"] for row in merged["ranks"]] == [0, 1, 2]
    g = merged["global"]
    assert "degraded_world" not in g          # the world grew back
    assert g["fault_rejoin_step"] == 7
    assert g["rejoin_ms"] > 0
    df = records_to_dataframe([merged])
    assert len(df) == world * merged["num_runs"]
    assert (df["runtime"] > 0).all()


@pytest.mark.slow
@pytest.mark.native_slow
def test_dp_tcp_preempt_without_rejoin_record_degraded(native_bin,
                                                      tmp_path):
    """An eviction that never grows back mirrors the python tier's
    record: the evictee drains out alive (exit 0) but emits NOTHING —
    its post-eviction rows are local replay, not fabric work — and the
    survivors declare degraded_world, so the merged record rides the
    degraded pathway exactly like a shrink."""
    from dlnetbench_tpu.metrics.merge import merge_files
    from dlnetbench_tpu.metrics.parser import validate_record

    port = _free_port()
    world = 3
    outs_p = [tmp_path / f"p{r}.jsonl" for r in range(world)]
    procs = [subprocess.Popen(
        [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
         "--world", str(world), "--backend", "tcp", "--rank", str(r),
         "--coordinator", f"127.0.0.1:{port}", "--num_buckets", "2",
         "--time_scale", "0.001", "--size_scale", "0.0001",
         "--runs", "8", "--warmup", "1", "--no_topology",
         "--base_path", str(REPO), "--fault", PREEMPT_ONLY_PLAN,
         "--fault_policy", "shrink", "--out", str(outs_p[r])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(world)]
    texts = _communicate_all(procs, timeout=120)
    for r in range(world):   # the evictee drained — nobody dies
        assert procs[r].returncode == 0, f"process {r}:\n{texts[r]}"
    assert not outs_p[1].exists()            # ...but it emits no record

    merged = merge_files(tmp_path / "merged.jsonl",
                         [outs_p[0], outs_p[2]])
    validate_record(merged)
    assert merged["global"]["degraded_world"] == [0, 2]
    assert [row["rank"] for row in merged["ranks"]] == [0, 2]


# ------------------------------------------------------------ hier lane
@pytest.mark.slow
@pytest.mark.native_slow
def test_hier_collective_delay_injected(native_bin, tmp_path):
    """The per-collective delay hook threads through the hierarchical
    fabric: a collective-scoped straggler on one global rank inflates
    the run and is accounted on that rank."""
    import os
    port = _free_port()
    plan = ('{"events":[{"kind":"delay","ranks":[1],"iteration":0,'
            '"magnitude_us":5000,"where":"collective"}]}')
    outs_p = [tmp_path / f"h{r}.jsonl" for r in range(2)]
    procs = [subprocess.Popen(
        [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
         "--world", "4", "--backend", "pjrt", "--procs", "2",
         "--rank", str(r), "--coordinator", f"127.0.0.1:{port}",
         "--num_buckets", "2", "--time_scale", "0.0001",
         "--size_scale", "0.00001", "--runs", "2", "--warmup", "1",
         "--no_topology", "--base_path", str(REPO),
         "--fault", plan, "--out", str(outs_p[r])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "DLNB_PJRT_EXECUTOR": "host"})
        for r in range(2)]
    texts = _communicate_all(procs, timeout=120)
    for r in range(2):
        assert procs[r].returncode == 0, f"process {r}:\n{texts[r]}"
    rec0 = json.loads(outs_p[0].read_text().strip())
    rows = {row["rank"]: row for row in rec0["ranks"]}
    # rank 1 lives on process 0 (locals 2+2); its per-collective delays
    # are accounted there, rank 0's are zero
    assert rows[1]["fault_injected_delay_us"] > 0
    assert rows[0]["fault_injected_delay_us"] == 0.0
