"""utils/native_build.py build-dir claim: the concurrent-wipe retry
(advisor r5).  A racing claimer can wipe the directory between our
mkdir's FileExistsError and the stat — _claim must restart the whole
mkdir/stat/tighten sequence instead of surfacing FileNotFoundError, and
must give up with a diagnostic once the attempts are exhausted.

(The permission-discipline cases live in test_native.py; these run
without cmake/ninja since _claim touches only the filesystem.)
"""
from __future__ import annotations

import pytest

from dlnetbench_tpu.utils.native_build import _claim


class _FlakyDir:
    """Path stand-in emulating a concurrent claimer that wins the first
    ``wipes`` rounds: mkdir sees the dir exist, stat sees it already
    wiped.  After that the real directory claims cleanly."""

    def __init__(self, real, wipes: int):
        self.real = real
        self.wipes = wipes
        self.attempt = 0

    def mkdir(self, mode):
        self.attempt += 1
        if self.attempt <= self.wipes:
            raise FileExistsError(self)  # the racer holds it...
        self.real.mkdir(mode=mode)

    def stat(self):
        if self.attempt <= self.wipes:
            raise FileNotFoundError(self)  # ...and wiped it under us
        return self.real.stat()

    def chmod(self, mode):
        self.real.chmod(mode)

    def __fspath__(self):  # shutil.rmtree compatibility
        return str(self.real)


def test_claim_retries_after_concurrent_wipe(tmp_path):
    target = tmp_path / "bld"
    _claim(_FlakyDir(target, wipes=2))
    assert target.is_dir()
    assert (target.stat().st_mode & 0o777) == 0o700


def test_claim_gives_up_after_bounded_attempts(tmp_path):
    flaky = _FlakyDir(tmp_path / "never", wipes=10**9)
    with pytest.raises(RuntimeError, match="could not claim"):
        _claim(flaky, attempts=3)
    assert flaky.attempt == 3  # bounded, not an infinite spin
