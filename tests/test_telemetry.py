"""Continuous telemetry (metrics/telemetry.py): the per-step flight
recorder, the anomaly engine, and the ISSUE 14 contracts.

Locks the tentpole properties: the ring is fixed-capacity and ordered,
the disabled path allocates nothing per step and leaves records
byte-identical to a pre-telemetry build (committed fixture
``record_no_telemetry.jsonl`` — generated from the pre-PR emitter and
verified byte-equal at generation time), the band-aware step-time
detector fires exactly once per shift, anomaly dumps land as
``flight_<trigger>.json`` with the ring window INTO the trigger, the
serving SLO-breach e2e produces a ``flight_slo.json`` whose window
covers the breach and an ``anomalies`` block that survives
parser -> merge, and the committed two-process fixture
``record_telemetry.jsonl`` round-trips parser -> merge -> bandwidth
with anomalies pooled across processes.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import pytest

from dlnetbench_tpu.metrics import telemetry

pytestmark = pytest.mark.telemetry

DATA = Path(__file__).parent / "data"


@pytest.fixture(autouse=True)
def _clean_recorder():
    """Never leak an enabled recorder into (or out of) a test."""
    telemetry.disable()
    yield
    telemetry.disable()


# ------------------------------------------------------------- the ring
def test_ring_is_fixed_capacity_and_ordered():
    rec = telemetry.FlightRecorder(capacity=4)
    for i in range(7):
        rec.record("proxy", step=i, step_wall_us=float(i))
    assert rec.recorded == 7 and rec.dropped == 3
    samples = rec.samples()
    assert [s["step"] for s in samples] == [3, 4, 5, 6]
    assert [s["step"] for s in rec.last(2)] == [5, 6]
    # t_s is monotone within the ring (the aligned-window invariant
    # flight dumps rely on)
    ts = [s["t_s"] for s in samples]
    assert ts == sorted(ts)


def test_window_selects_by_time():
    rec = telemetry.FlightRecorder(capacity=8)
    for i in range(4):
        rec.record("proxy", step=i)
    t_mid = rec.samples()[1]["t_s"]
    win = rec.window(t_lo=t_mid)
    assert [s["step"] for s in win] == [1, 2, 3]


def test_enable_disable_lifecycle(tmp_path):
    assert not telemetry.is_enabled()
    rec = telemetry.enable(capacity=16, dump_dir=tmp_path)
    assert telemetry.is_enabled() and telemetry.current() is rec
    telemetry.record_step("proxy", step=0, step_wall_us=1.0)
    got = telemetry.disable()
    assert got is rec and not telemetry.is_enabled()
    assert got.recorded == 1


def test_enable_from_env(monkeypatch, tmp_path):
    monkeypatch.delenv("DLNB_TELEMETRY", raising=False)
    assert telemetry.enable_from_env() is None
    monkeypatch.setenv("DLNB_TELEMETRY", "1")
    monkeypatch.setenv("DLNB_TELEMETRY_CAPACITY", "32")
    monkeypatch.setenv("DLNB_FLIGHT_DIR", str(tmp_path / "fl"))
    rec = telemetry.enable_from_env()
    assert rec is not None and rec.capacity == 32
    assert rec.dump_dir == tmp_path / "fl"
    # an active recorder wins — no silent replacement
    assert telemetry.enable_from_env() is rec


# --------------------------------------------- the disabled-path contract
def test_disabled_path_allocates_nothing_per_step():
    """The zero-overhead contract (the spans.py pattern): every hot
    site gates on ``is_enabled()`` BEFORE assembling kwargs, so the
    disabled per-step cost is one global load + one branch — zero
    allocations."""
    import tracemalloc

    assert not telemetry.is_enabled()
    gated = 0

    def loop(n: int) -> None:
        nonlocal gated
        for _ in range(n):
            if telemetry.is_enabled():
                telemetry.record_step("proxy", step=0,
                                      step_wall_us=1.0)
                gated += 1
            telemetry.record_step("also-free-when-disabled")

    loop(10)  # warm interpreter caches (specialization, frame reuse)
    tracemalloc.start()
    try:
        s0 = tracemalloc.take_snapshot()
        loop(1000)
        s1 = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    assert gated == 0
    # judged per-file (other threads may allocate elsewhere
    # concurrently) and by SCALE: a real per-step allocation over 1000
    # iterations is tens of KB; one-time interpreter artifacts
    # (bytecode specialization buffers attributed to lineno 0) are a
    # few dozen bytes and do not grow with the step count
    mod = telemetry.__file__
    grew = sum(st.size_diff for st in s1.compare_to(s0, "filename")
               if st.traceback[0].filename == mod and st.size_diff > 0)
    assert grew < 512, f"{grew} bytes allocated over 1000 disabled steps"


def test_disabled_record_bytes_match_pre_telemetry_fixture(monkeypatch):
    """Telemetry off => the emitted record is byte-identical to the
    pre-PR emitter's output for the same ProxyResult.  The fixture was
    generated from the pre-telemetry ``metrics/emit.py`` (verified
    byte-equal against this build's disabled path at generation time);
    this test locks the disabled path against it forever."""
    import socket

    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.proxies.base import ProxyResult

    monkeypatch.setattr(socket, "gethostname", lambda: "fixedhost")
    monkeypatch.delenv("DLNB_TUNING_DB_DIR", raising=False)
    assert not telemetry.is_enabled()
    result = ProxyResult(
        name="dp",
        global_meta={"proxy": "dp", "model": "gpt2_l_16_bfloat16",
                     "world_size": 2, "num_buckets": 2,
                     "bucket_bytes": [1000, 1000],
                     "mesh": {"platform": "cpu", "device_kind": "host",
                              "num_hosts": 1,
                              "devices": [{"id": 0, "process": 0},
                                          {"id": 1, "process": 0}]}},
        timers_us={"runtimes": [100.0, 110.0, 105.0],
                   "barrier_time": [20.0, 25.0, 22.0]},
        warmup_times_us=[950.0],
        num_runs=3)
    got = json.dumps(result_to_record(result)) + "\n"
    want = (DATA / "record_no_telemetry.jsonl").read_text()
    assert got == want
    assert '"telemetry"' not in got and '"anomalies"' not in got


def test_serving_engine_disabled_path_never_samples(tiny_engine):
    """With telemetry off the engine's step takes the zero-overhead
    branch: no recorder reference, no sample, nothing stamped."""
    engine, requests = tiny_engine
    engine.run(requests)
    assert engine._tele is None
    meta = engine.global_meta(_tiny_plan())
    assert "telemetry" not in meta and "anomalies" not in meta


# --------------------------------------- band-aware step-time detection
def test_step_time_detector_fires_once_per_shift():
    rec = telemetry.FlightRecorder(capacity=64)
    for i in range(telemetry.BASELINE_MIN + 2):
        rec.observe_step_wall("proxy", 100.0 + (i % 3), step=i)
    assert rec.anomalies == []  # stable baseline: no trigger
    for i in range(telemetry.RECENT_K):
        rec.observe_step_wall("proxy", 400.0 + i, step=20 + i)
    assert [a["trigger"] for a in rec.anomalies] == ["step_time"]
    detail = rec.anomalies[0]["detail"]
    assert detail["ratio"] > 1.5
    # re-baselined: the sustained shift does not re-fire every step
    for i in range(telemetry.RECENT_K):
        rec.observe_step_wall("proxy", 400.0, step=30 + i)
    assert len(rec.anomalies) == 1


def test_reset_walls_rebaselines_across_runs():
    """A structurally new run over a live recorder (next engine in a
    bench A/B, next in-process sweep config) must not band-escape the
    PREVIOUS run's walls: reset_walls drops the history, so the new
    steady state is its own baseline, not an anomaly."""
    rec = telemetry.FlightRecorder(capacity=64)
    for i in range(telemetry.BASELINE_MIN + telemetry.RECENT_K):
        rec.observe_step_wall("serving", 100.0 + (i % 3), step=i)
    rec.reset_walls("serving")
    # 16x slower — a fused-N engine's honest per-dispatch wall
    for i in range(telemetry.RECENT_K + 2):
        rec.observe_step_wall("serving", 1600.0 + i, step=i)
    assert rec.anomalies == []


def test_step_time_detector_ignores_band_overlapping_noise():
    rec = telemetry.FlightRecorder(capacity=64)
    vals = [100.0, 130.0, 90.0, 120.0, 105.0, 95.0, 125.0, 110.0] * 4
    for i, v in enumerate(vals):
        rec.observe_step_wall("proxy", v, step=i)
    assert rec.anomalies == []


# ------------------------------------------------------- anomaly engine
def test_trigger_dumps_ring_window(tmp_path):
    rec = telemetry.FlightRecorder(capacity=16, dump_dir=tmp_path)
    for i in range(5):
        rec.record("proxy", step=i, step_wall_us=100.0 + i)
    ev = rec.trigger("fault", step=4, detail={"rank": 2})
    assert ev["dump"] == str(tmp_path / "flight_fault.json")
    dump = json.loads((tmp_path / "flight_fault.json").read_text())
    assert dump["trigger"] == "fault" and dump["step"] == 4
    assert [s["step"] for s in dump["samples"]] == [0, 1, 2, 3, 4]
    # the window is aligned INTO the trigger: nothing after it
    assert all(s["t_s"] <= dump["t_s"] for s in dump["samples"])
    block = rec.anomalies_block()
    assert block["count"] == 1 and block["triggers"] == {"fault": 1}


def test_trigger_cooldown_and_dump_cap(tmp_path):
    rec = telemetry.FlightRecorder(capacity=8, dump_dir=tmp_path,
                                   cooldown_s=0.0,
                                   max_dumps_per_trigger=2)
    assert rec.trigger("slo") is not None
    assert rec.trigger("slo") is not None
    assert rec.trigger("slo")["t_s"] >= 0  # recorded...
    names = sorted(p.name for p in tmp_path.iterdir())
    assert names == ["flight_slo.json", "flight_slo_2.json"]  # ...capped
    throttled = telemetry.FlightRecorder(capacity=8, cooldown_s=60.0)
    assert throttled.trigger("slo") is not None
    assert throttled.trigger("slo") is None  # inside the cooldown
    assert throttled.anomalies_block()["count"] == 1


def test_clean_run_stamps_no_anomalies_block():
    rec = telemetry.FlightRecorder(capacity=8)
    rec.record("proxy", step=0)
    assert rec.anomalies_block() is None
    block = rec.telemetry_block()
    assert block["recorded"] == 1 and block["capacity"] == 8


# --------------------------------------------- serving e2e (acceptance)
def _tiny_plan():
    from dlnetbench_tpu.serving.arrivals import ArrivalPlan
    return ArrivalPlan(kind="poisson", rate_rps=500.0, num_requests=10,
                       seed=1, prompt_len=[4, 8], output_len=[3, 5])


@pytest.fixture(scope="module")
def tiny_engine():
    """One compiled tiny engine shared by the serving telemetry tests
    (compile once; ``run`` resets all run state)."""
    import jax

    from dlnetbench_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
    from dlnetbench_tpu.serving.scheduler import Engine, ServingConfig

    mc = TransformerConfig(vocab_size=64, embed_dim=32, num_heads=2,
                           num_kv_heads=2, ff_dim=64, num_layers=1,
                           seq_len=32, gated=True, max_positions=0,
                           dtype="float32")
    # SLO budgets impossibly tight: every completion breaches, so the
    # rolling-window detector MUST fire (the anomaly e2e's arrival plan)
    cfg = ServingConfig(slots=2, page_size=4, num_pages=24,
                        max_seq_len=16, slo_ttft_ms=0.001,
                        slo_tpot_ms=0.001, warmup_requests=0)
    engine = Engine(mc, cfg, params=init_params(jax.random.key(0), mc))
    return engine, _tiny_plan().sample()


@pytest.mark.serving
def test_slo_breach_e2e_dump_and_record(tiny_engine, tmp_path):
    """ISSUE 14 acceptance: an SLO-breach plan produces a
    ``flight_slo.json`` whose window covers the breach, and the
    record's ``anomalies`` block survives parser -> merge."""
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import validate_record
    from dlnetbench_tpu.serving import metrics as M

    engine, requests = tiny_engine
    rec = telemetry.enable(capacity=128, dump_dir=tmp_path)
    completed, wall = engine.run(requests)
    assert engine._tele is rec
    # the per-step serving series landed in the ring
    serving_samples = [s for s in rec.samples()
                       if s["source"] == "serving"]
    assert serving_samples, "engine steps never sampled"
    for key in ("step_wall_us", "queue_depth", "active_slots",
                "kv_occupancy", "kv_fragmentation"):
        assert key in serving_samples[0]
    # the breach fired and dumped
    dump = json.loads((tmp_path / "flight_slo.json").read_text())
    assert dump["trigger"] == "slo"
    assert dump["detail"]["goodput_frac"] < 0.5
    window = dump["samples"]
    assert window and window[0]["t_s"] <= dump["t_s"]
    assert all(s["t_s"] <= dump["t_s"] for s in window)
    # ... and covers the breach window: ring samples reach back at
    # least one detector window before the trigger
    assert dump["t_s"] - window[0]["t_s"] >= 0.0

    # the record pathway: build -> emit -> validate -> merge
    meta = engine.global_meta(_tiny_plan())
    meta["serving"] = M.serving_block(
        completed, _tiny_plan(), slo_ttft_ms=engine.cfg.slo_ttft_ms,
        slo_tpot_ms=engine.cfg.slo_tpot_ms, wall_s=wall,
        engine_steps=engine.engine_steps)
    result = M.build_result(completed, _tiny_plan(), meta)
    from dlnetbench_tpu.metrics.emit import result_to_record
    record = result_to_record(result)
    assert record["global"]["anomalies"]["triggers"].get("slo", 0) >= 1
    assert record["global"]["telemetry"]["recorded"] == rec.recorded
    validate_record(record)
    merged = merge_records([record])
    assert merged["global"]["anomalies"]["triggers"].get("slo", 0) >= 1


@pytest.mark.serving
def test_live_metrics_stream_from_engine(tiny_engine, tmp_path):
    """The --live-metrics channel: an engine with a writer attached
    streams schema-complete windowed snapshot lines."""
    from dlnetbench_tpu.serving.metrics import LiveMetricsWriter

    engine, requests = tiny_engine
    path = tmp_path / "live.jsonl"
    engine.live = LiveMetricsWriter(path, window_s=0.0)
    try:
        engine.run(requests)
    finally:
        engine.live = None
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert lines
    for ln in lines:
        assert set(ln) == {"run", "t_s", "window_s", "completed",
                           "ttft_ms", "tpot_ms", "queue_depth",
                           "active_slots", "kv_occupancy",
                           "engine_steps"}
    assert any(ln["completed"] >= 1 for ln in lines)


# ------------------------------------------- proxy-tier integration
def test_run_proxy_feeds_ring_with_energy(tmp_path):
    """run_proxy samples one ring entry per fenced chain — step wall,
    the matched compute leg, and (the energy satellite) the per-chain
    joules where a sampler exists."""
    from dlnetbench_tpu.proxies.base import (ProxyConfig, StepBundle,
                                             run_proxy)

    class FakeSampler:
        source = "fake"
        _j = 0.0

        def read_joules(self):
            self._j += 0.25
            return self._j

    import jax.numpy as jnp

    telemetry.enable(capacity=64, dump_dir=tmp_path)
    x = jnp.ones((8,), jnp.float32)
    bundle = StepBundle(full=lambda: x * 2.0,
                        compute=lambda: x + 1.0,
                        comm=None, global_meta={"model": "t"})
    cfg = ProxyConfig(warmup=2, runs=4, measure_comm_only=False,
                      measure_energy=True)
    run_proxy("dp", bundle, cfg, energy_sampler=FakeSampler())
    rec = telemetry.current()
    timed = [s for s in rec.samples() if s.get("phase") == "timed"]
    warm = [s for s in rec.samples() if s.get("phase") == "warmup"]
    assert len(timed) == 4 and len(warm) == 2
    assert all("energy_j" in s and s["energy_j"] > 0 for s in timed)
    # step indices in fault-plan units: warmup included
    assert [s["step"] for s in timed] == [2, 3, 4, 5]


# ------------------------------------ fixture round trip (parser/merge)
def test_committed_fixture_roundtrips_parser_merge_bandwidth():
    """tests/data/record_telemetry.jsonl: two per-process records of
    one faulted 2-rank run, telemetry blocks + a step_time anomaly on
    process 1.  Parser validates both, merge pools the anomalies
    (volatile telemetry: process 0's ring survives), the DataFrame
    hoists anomaly_count, and the bandwidth table carries the blame
    columns pointing at the straggler."""
    from dlnetbench_tpu.analysis.bandwidth import (bandwidth_summary,
                                                   effective_bandwidth)
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe,
                                               validate_record)

    records = load_records(DATA / "record_telemetry.jsonl")
    assert len(records) == 2
    for rec in records:
        validate_record(rec)
        assert rec["global"]["telemetry"]["capacity"] == 64
    merged = merge_records([json.loads(json.dumps(r))
                            for r in records])
    assert merged["global"]["telemetry"] == \
        records[0]["global"]["telemetry"]
    anom = merged["global"]["anomalies"]
    assert anom["count"] == 1 and anom["triggers"] == {"step_time": 1}
    assert anom["events"][0]["process"] == 1
    df = records_to_dataframe([merged])
    assert set(df["anomaly_count"]) == {1}
    bw = effective_bandwidth([merged])
    assert set(bw["blame_rank"]) == {"1"}
    assert (bw["blame_frac"] >= 0.8).all()
    summary = bandwidth_summary([merged])
    assert "blame_rank" in summary.columns
    assert "blame_frac" in summary.columns


def test_no_telemetry_records_still_parse_and_mixed_merge_refused():
    """v1 and pre-telemetry v2 records parse unchanged, and the
    existing v1-with-v2 merge refusal still holds with telemetry
    records in the mix."""
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import load_records, \
        validate_record

    v1 = load_records(DATA / "record_v1.jsonl")[0]
    validate_record(v1)  # pre-telemetry v1 fixture parses unchanged
    v2 = load_records(DATA / "record_telemetry.jsonl")[0]
    # a v1-build sibling of the telemetry record: same run identity,
    # older schema (no summaries, no telemetry) — merge must refuse
    sibling = json.loads(json.dumps(v2))
    sibling["process"] = 1
    sibling["version"] = 1
    for row in sibling["ranks"]:
        row.pop("summary", None)
    sibling["global"].pop("telemetry", None)
    with pytest.raises(ValueError, match="different harness builds"):
        merge_records([v2, sibling])


# ------------------------------------------------ Perfetto export
def test_telemetry_counter_events_render_ring_and_anomalies():
    from dlnetbench_tpu.metrics import spans

    rec = telemetry.FlightRecorder(capacity=8)
    rec.record("serving", step=0, step_wall_us=100.0, queue_depth=3)
    rec.record("serving", step=1, step_wall_us=120.0, queue_depth=5)
    rec.trigger("slo", step=1)
    events = spans.telemetry_counter_events(
        rec.telemetry_block(last=8), rec.anomalies_block())
    counters = [e for e in events if e.get("ph") == "C"]
    names = {e["name"] for e in counters}
    assert names == {"step_wall_us", "queue_depth"}
    instants = [e for e in events if e.get("ph") == "i"]
    assert len(instants) == 1 and instants[0]["name"] == "anomaly: slo"
    # and the record-derived pathway picks them up
    record = {"section": "dp", "ranks": [],
              "global": {"telemetry": rec.telemetry_block(last=8),
                         "anomalies": rec.anomalies_block()}}
    tracked = spans.record_track_events(record)
    assert any(e.get("ph") == "C" for e in tracked)
    assert any(e.get("ph") == "i" and "anomaly" in e.get("name", "")
               for e in tracked)
