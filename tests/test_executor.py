"""AOT execution engine (core/executor.py): compile-time bookkeeping,
donation + rebinding safety, chained-fence timing, and the no-compile-in-
warmup property that keeps estimate_runs honest."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from dlnetbench_tpu.core import executor
from dlnetbench_tpu.parallel.buffers import sharded_zeros
from dlnetbench_tpu.parallel.mesh import make_flat_mesh
from dlnetbench_tpu.proxies.base import ProxyConfig, StepBundle, run_proxy
from dlnetbench_tpu.utils.jax_compat import shard_map


def _mesh4(eight_devices):
    return make_flat_mesh(4, devices=eight_devices[:4])


def _carry_program(mesh, trace_counter=None):
    """A tiny shard_map step with a donated carry: state <- tanh(s@s),
    plus a psum output per buffer (the dp-proxy shape)."""
    state = sharded_zeros(mesh, P(), (16, 16), jnp.float32) + 0.1
    bufs = tuple(sharded_zeros(mesh, P(), (32,), jnp.float32)
                 for _ in range(2))

    def step(s, gs):
        if trace_counter is not None:
            trace_counter.append(1)
        s = jnp.tanh(s @ s)
        outs = [jax.lax.psum(g, "x") for g in gs]
        return (s, *outs)

    fn = shard_map(step, mesh=mesh, in_specs=(P(), (P(), P())),
                   out_specs=P(), check_vma=False)
    return executor.Program(fn=fn, args=(state, bufs),
                            donate_argnums=(0, 1)), state, bufs


def test_compile_stats_recorded(eight_devices):
    mesh = _mesh4(eight_devices)
    prog, _, _ = _carry_program(mesh)
    meta: dict = {}
    compiled = executor.compile_programs({"full": prog}, meta)
    assert meta["compile_ms"]["full"] > 0
    # compile time ships OUTSIDE the timer arrays: it lives in the
    # global_meta channel the emitter serializes under "global"
    stats = compiled["full"].stats
    assert stats["donated_argnums"] == [0, 1]
    # XLA's cost model on CPU reports flops for the matmul
    assert meta["aot"]["full"]["cost_analysis"]["flops"] > 0
    # memory_analysis proves the donation: alias bytes cover the carry
    ma = meta["aot"]["full"]["memory_analysis"]
    assert ma["alias"] > 0


def test_donation_rebinds_and_siblings_survive(eight_devices):
    """Repeated calls must work (the donated buffer is rebound from the
    output), and the ORIGINAL buffers must stay alive for sibling
    programs — the executor clones donated args."""
    mesh = _mesh4(eight_devices)
    prog, state, bufs = _carry_program(mesh)
    compiled = executor.CompiledProgram(prog)
    for _ in range(3):  # would raise "buffer deleted" without rebinding
        outs = compiled()
    assert jnp.all(jnp.isfinite(outs[0]))
    # originals untouched (not donated — their clones were)
    assert float(jnp.max(jnp.abs(bufs[0]))) == 0.0
    assert state.shape == (16, 16) and bool(jnp.isfinite(state).all())


def test_unmatched_donation_dropped_not_fatal(eight_devices):
    """A requested donation whose leaves have no shape-matched output is
    dropped (recorded as ``undonated``), never handed to XLA to warn
    about or die on."""
    mesh = _mesh4(eight_devices)
    x = sharded_zeros(mesh, P(), (8,), jnp.float32)

    def f(v):
        return jnp.sum(v)  # scalar out: no (8,) output to rebind from

    fn = shard_map(f, mesh=mesh, in_specs=(P(),), out_specs=P(),
                   check_vma=False)
    compiled = executor.CompiledProgram(
        executor.Program(fn=fn, args=(x,), donate_argnums=(0,)))
    assert compiled.stats["donated_argnums"] == []
    assert compiled.stats["undonated"] == [0]
    compiled()
    compiled()  # x was never donated, so the second call is fine


def test_no_donation_kill_switch(eight_devices, monkeypatch):
    """DLNB_NO_DONATION=1 disables donation (and therefore cloning) for
    memory-constrained full-scale runs, without touching call sites."""
    monkeypatch.setenv(executor.ENV_NO_DONATION, "1")
    mesh = _mesh4(eight_devices)
    prog, state, bufs = _carry_program(mesh)
    compiled = executor.CompiledProgram(prog)
    assert compiled.stats["donated_argnums"] == []
    compiled()
    compiled()  # nothing donated: same buffers reusable every call
    assert compiled.example_args[0] is state  # no clone was made


def test_run_proxy_never_retraces(eight_devices):
    """The no-compile-in-warmup property behind clean estimate_runs:
    bundles are AOT-compiled at build, so run_proxy's warmup+timed loop
    must never trace (= compile) again.  The trace counter ticks once,
    at Program compile time."""
    mesh = _mesh4(eight_devices)
    traces: list = []
    prog, _, _ = _carry_program(mesh, trace_counter=traces)
    compiled = executor.compile_programs({"full": prog}, {})
    # AOT lowering traces the function (eval_shape + lower each tick it)
    n_build = len(traces)
    assert n_build >= 1
    bundle = StepBundle(full=compiled["full"], compute=None, comm=None,
                        global_meta={"proxy": "t", "world_size": 4})
    cfg = ProxyConfig(warmup=3, runs=4, measure_energy=False)
    result = run_proxy("t", bundle, cfg)
    assert len(traces) == n_build, "run_proxy re-traced an AOT program"
    assert len(result.warmup_times_us) == 3
    assert len(result.timers_us["runtimes"]) == 4


def test_chained_fence_matches_per_rep_mean(eight_devices):
    """K-chained timing must agree with per-rep timing on a steady
    kernel — the chain amortizes dispatch+fence overhead, so its mean
    may sit BELOW the per-rep mean, but the two must be the same
    magnitude (a chain that mistimed k iterations as one would be ~k
    off)."""
    from dlnetbench_tpu.proxies import burn as burnlib
    from dlnetbench_tpu.utils.timing import time_callable, time_chain

    state = burnlib.make_state()
    cal = burnlib.calibrate()
    iters = cal.iters_for_us(3000)  # ~3 ms per rep: stable on CPU

    import functools
    import statistics
    j = jax.jit(functools.partial(burnlib.burn, iters=iters))
    j(state).block_until_ready()  # compile
    # warm the FENCE path too: the first transfer fence lazily compiles
    # the one-element slice for this state shape (~40 ms on CPU — a
    # 13x outlier against a 3 ms kernel), which used to land in the
    # first measured sample and flake this test on loaded hosts
    time_callable(j, state, reps=1)

    # medians: this test pins the chain bookkeeping (a chain that
    # mistimed k iterations as one would be ~k off), not the tail of
    # the host's scheduling-noise distribution
    per_rep = statistics.median(time_callable(j, state, reps=6))
    chained = statistics.median(time_chain(j, state, k=3)
                                for _ in range(3))
    assert chained > 0
    ratio = chained / per_rep
    assert 0.2 < ratio < 2.5, (
        f"chained per-iteration median {chained*1e3:.2f} ms vs per-rep "
        f"{per_rep*1e3:.2f} ms (ratio {ratio:.2f})")


def test_run_proxy_chain_partitioning(eight_devices):
    """reps_per_fence=K: runs partition into ceil(runs/K) fence chains,
    each contributing one per-iteration sample; the A/B barrier pairing
    stays chain-matched; the K lands in the record's global meta."""
    calls = {"full": 0, "comp": 0}

    def full():
        calls["full"] += 1

    def compute():
        calls["comp"] += 1

    bundle = StepBundle(full=full, compute=compute, comm=None,
                        global_meta={"proxy": "t", "world_size": 1})
    cfg = ProxyConfig(warmup=1, runs=5, reps_per_fence=2,
                      measure_energy=False)
    res = run_proxy("t", bundle, cfg)
    assert res.global_meta["reps_per_fence"] == 2
    # 5 runs -> chains of 2+2+1 -> 3 samples per timer
    assert len(res.timers_us["runtimes"]) == 3
    assert len(res.timers_us["barrier_time"]) == 3
    assert res.num_runs == 5
    # every configured iteration really dispatched: 1 warmup + 5 runs
    assert calls["full"] == 6
    # compute: 1 warm + 5 chained A/B iterations
    assert calls["comp"] == 6


def test_persistent_cache_opt_in(tmp_path, monkeypatch, eight_devices):
    """DLNB_COMPILE_CACHE_DIR wires jax's persistent compilation cache:
    compiling through the executor populates the directory."""
    monkeypatch.setenv(executor.ENV_CACHE_DIR, str(tmp_path))
    monkeypatch.setattr(executor, "_CACHE_CONFIGURED", False)
    try:
        mesh = _mesh4(eight_devices)
        prog, _, _ = _carry_program(mesh)
        meta: dict = {}
        executor.compile_programs({"full": prog}, meta)
        assert meta["compile_cache_dir"] == str(tmp_path)
        assert any(f.name.endswith("-cache") or "cache" in f.name
                   for f in tmp_path.iterdir()), \
            "compile cache dir stayed empty"
    finally:  # do not leave the global cache pointed at a dead tmpdir
        jax.config.update("jax_compilation_cache_dir", None)
        executor._CACHE_CONFIGURED = False


def test_estimate_runs_sees_execution_only(eight_devices):
    """End-to-end guard on the estimate_runs channel: with an AOT bundle
    whose program costs ~c per call, the warmup mean feeding
    estimate_runs must be ~c — not c + compile.  Compile for this
    program costs >> one execution on CPU, so warmup[0] sitting within
    a small factor of warmup[-1] proves compilation never leaked in."""
    mesh = _mesh4(eight_devices)
    prog, _, _ = _carry_program(mesh)
    meta: dict = {}
    compiled = executor.compile_programs({"full": prog}, meta)
    bundle = StepBundle(full=compiled["full"], compute=None, comm=None,
                        global_meta=meta)
    cfg = ProxyConfig(warmup=4, runs=1, measure_energy=False)
    result = run_proxy("t", bundle, cfg)
    warm = result.warmup_times_us
    compile_us = meta["compile_ms"]["full"] * 1e3
    steady = min(warm)
    # the first warmup sample must not carry the compile (it is 100s of
    # ms on CPU for this program; execution is ~100 us)
    assert warm[0] < steady + 0.5 * compile_us, (
        f"warmup[0]={warm[0]:.0f}us vs steady {steady:.0f}us and "
        f"compile {compile_us:.0f}us — compilation leaked into warmup")
