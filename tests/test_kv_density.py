"""Serving density (ISSUE 12): quantized paged KV (int8/fp8 pools +
per-page scales, write/dequant parity vs the bf16 cache under the
stated tolerance bars, the dequantizing Pallas kernel) and
cross-request prefix sharing (refcounted allocator, radix trie,
copy-on-write, lossless engine runs, record globals)."""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.models import transformer as tfm
from dlnetbench_tpu.serving import kv_cache as KV
from dlnetbench_tpu.serving.arrivals import ArrivalPlan, Request
from dlnetbench_tpu.serving.kv_cache import (CacheConfig, CacheOOM,
                                             PagedKVCache,
                                             QUANT_DECODE_TOL,
                                             device_buffers,
                                             paged_attention_decode,
                                             pages_for_pool_bytes,
                                             quant_write_span)

DATA = Path(__file__).parent / "data"

pytestmark = [pytest.mark.density, pytest.mark.serving]


def tiny_model(**over) -> tfm.TransformerConfig:
    kw = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
              ff_dim=64, num_layers=2, seq_len=64, gated=True,
              max_positions=0, dtype="float32")
    kw.update(over)
    return tfm.TransformerConfig(**kw)


def tiny_serving(**over):
    from dlnetbench_tpu.serving.scheduler import ServingConfig
    kw = dict(slots=3, page_size=4, num_pages=40, max_seq_len=32,
              prefill_chunk=4, slo_ttft_ms=200.0, slo_tpot_ms=100.0,
              warmup_requests=0)
    kw.update(over)
    return ServingConfig(**kw)


def _cache_cfg(**over) -> CacheConfig:
    kw = dict(num_layers=1, num_kv_heads=2, head_dim=8, num_pages=8,
              page_size=4, max_seqs=2, max_pages_per_seq=4)
    kw.update(over)
    return CacheConfig(**kw)


# ---------------------------------------------------------------------
# config validation + pool-bytes accounting (satellite 1)


def test_cache_config_cache_dtype_validation():
    with pytest.raises(ValueError, match="unknown cache_dtype"):
        _cache_cfg(cache_dtype="int4").validate()
    for cd in KV.CACHE_DTYPES:
        assert _cache_cfg(cache_dtype=cd).validate().cache_dtype == cd
    assert not _cache_cfg().quantized
    assert _cache_cfg(cache_dtype="int8").quantized
    assert _cache_cfg(cache_dtype="fp8").quant_fmt == "float8"


def test_pool_bytes_counts_scale_arrays():
    """The "same pool bytes" axis is honest only if the quantized
    config's scale arrays are priced in: page_bytes = k+v payload at
    the storage dtype PLUS 2 * L * Hkv f32 scales per page."""
    dense = _cache_cfg()                       # f32 payload
    i8 = _cache_cfg(cache_dtype="int8")
    payload_f32 = 2 * 1 * 2 * 4 * 8 * 4
    payload_i8 = 2 * 1 * 2 * 4 * 8 * 1
    scales = 2 * 1 * 2 * 4
    assert dense.page_bytes == payload_f32
    assert i8.page_bytes == payload_i8 + scales
    assert i8.pool_bytes == 8 * i8.page_bytes
    # a byte budget converts to MORE pages for the quantized config
    pages = pages_for_pool_bytes(dense.pool_bytes, i8)
    assert pages > dense.num_pages
    assert pages * i8.page_bytes <= dense.pool_bytes


def test_one_request_guard_covers_quantized_configs():
    """The loud-refusal guard (pool must hold one max-seq request)
    fires on a quantized config exactly like a dense one — the
    byte-budget path can produce too few pages and must fail loud,
    not starve the admission gate."""
    with pytest.raises(ValueError, match="cannot hold even"):
        _cache_cfg(num_pages=3, cache_dtype="int8").validate()
    with pytest.raises(ValueError, match="cannot hold even"):
        tiny_serving(num_pages=3, cache_dtype="int8").validate()


def test_serving_config_cache_knobs():
    from dlnetbench_tpu.serving.scheduler import ServingConfig
    with pytest.raises(ValueError, match="unknown cache_dtype"):
        tiny_serving(cache_dtype="nf4").validate()
    with pytest.raises(ValueError, match="bf16 cache only"):
        tiny_serving(cache_dtype="int8", speculative=True,
                     multi_step_n=2).validate()
    cfg = tiny_serving(cache_dtype="fp8", prefix_sharing=True)
    assert cfg.validate() is cfg


def test_cli_serve_cache_dtype_knob():
    """cli serve grew --cache_dtype/--prefix_sharing; a bad dtype is
    an argparse usage error, never an engine traceback."""
    from dlnetbench_tpu.cli import main
    with pytest.raises(SystemExit) as e:
        main(["serve", "--arrival", '{"kind": "poisson"}',
              "--cache_dtype", "int4"])
    assert e.value.code == 2


# ---------------------------------------------------------------------
# quantized write + dequant read parity (the tolerance bars)


def _write_streams(cache_dtype: str, steps: int = 10, seed: int = 0):
    """Write one seeded decode-style token stream into a dense AND a
    quantized pool (the engine's own write paths); returns both pool
    sets + lengths/block tables."""
    cc_d = _cache_cfg(head_dim=16)
    cc_q = _cache_cfg(head_dim=16, cache_dtype=cache_dtype)
    kd, vd = device_buffers(cc_d)
    kq, vq, ks, vs = device_buffers(cc_q)
    fmt = cc_q.quant_fmt
    bt = jnp.asarray(np.arange(8, dtype=np.int32).reshape(2, 4))
    rng = np.random.RandomState(seed)
    for t in range(steps):
        knew = jnp.asarray(rng.randn(2, 1, 2, 16).astype(np.float32))
        vnew = jnp.asarray(rng.randn(2, 1, 2, 16).astype(np.float32))
        pos = jnp.full((2,), t, jnp.int32)
        ok = jnp.ones((2, 1), bool)
        pid = jnp.take_along_axis(bt, (pos // 4)[:, None], 1)[:, 0]
        kd = kd.at[0, :, pid, pos % 4, :].set(knew[:, 0], mode="drop")
        vd = vd.at[0, :, pid, pos % 4, :].set(vnew[:, 0], mode="drop")
        kq, ks = quant_write_span(kq, ks, 0, knew, pos, ok, bt,
                                  fmt=fmt, page_size=4, num_pages=8)
        vq, vs = quant_write_span(vq, vs, 0, vnew, pos, ok, bt,
                                  fmt=fmt, page_size=4, num_pages=8)
    q = jnp.asarray(rng.randn(2, 4, 16).astype(np.float32)) * 16**-0.5
    lengths = jnp.asarray([steps, steps - 1], jnp.int32)
    return (kd, vd), (kq, vq, ks, vs), q, lengths, bt, fmt


@pytest.mark.parametrize("cache_dtype", ["int8", "fp8"])
def test_quant_decode_parity_within_stated_bar(cache_dtype):
    """Greedy-decode parity vs the bf16 cache, per recipe: the
    dequantizing gather attention over a quantized pool written by the
    engine's own write path stays inside the STATED tolerance bar
    (kv_cache.QUANT_DECODE_TOL) — the bar the bench line and the
    committed study enforce too."""
    (kd, vd), (kq, vq, ks, vs), q, lengths, bt, fmt = _write_streams(
        cache_dtype)
    ref = paged_attention_decode(q, kd[0], vd[0], lengths, bt,
                                 impl="gather")
    got = paged_attention_decode(q, kq[0], vq[0], lengths, bt,
                                 k_scale=ks[0], v_scale=vs[0], fmt=fmt,
                                 impl="gather")
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err <= QUANT_DECODE_TOL[cache_dtype], (cache_dtype, err)
    # and the error is genuinely nonzero — the quant path really ran
    assert err > 0.0


def test_quant_write_masks_stale_page_content():
    """Page reuse: the fresh-amax requant masks rows beyond the
    sequence's own content, so a huge stale value in a reused page can
    never inflate the scale (silent precision loss for the real
    rows)."""
    cc = _cache_cfg(cache_dtype="int8", max_seqs=1, num_pages=4)
    kq, vq, ks, vs = device_buffers(cc)
    bt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    # poison page 0 with a huge stale row + a huge stale scale
    kq = kq.at[0, :, 0, 3, :].set(127)
    ks = ks.at[0, :, 0].set(1e6)
    new = jnp.ones((1, 1, 2, 8), jnp.float32)
    kq, ks = quant_write_span(kq, ks, 0, new, jnp.zeros((1,), jnp.int32),
                              jnp.ones((1, 1), bool), bt, fmt="int8",
                              page_size=4, num_pages=4)
    # the fresh scale reflects ONLY the new row (amax 1.0), and the
    # stale row was zeroed by the rewrite
    assert float(ks[0, 0, 0]) == pytest.approx(1.0 / 127.0, rel=1e-5)
    deq = np.asarray(kq[0, :, 0], np.float32) * float(ks[0, 0, 0])
    np.testing.assert_allclose(deq[:, 0, :], 1.0, rtol=2e-2)
    assert np.all(deq[:, 3, :] == 0.0)


def test_quant_kernel_matches_dequant_gather():
    """The Pallas quantized paged-attention kernel (interpret mode on
    the CPU mesh — the pallas_common backend split) against the
    dequantizing gather fallback: same masked softmax to f32 rounding,
    block-size invariant, non-divisor refused loudly."""
    from dlnetbench_tpu.ops.paged_attention_quant import \
        quant_paged_attention
    (_, _), (kq, vq, ks, vs), q, lengths, bt, fmt = _write_streams(
        "int8")
    ref = paged_attention_decode(q, kq[0], vq[0], lengths, bt,
                                 k_scale=ks[0], v_scale=vs[0], fmt=fmt,
                                 impl="gather")
    for ppcb in (1, 2, 4):
        got = quant_paged_attention(q, kq[0], vq[0], ks[0], vs[0],
                                    lengths, bt, fmt=fmt,
                                    pages_per_compute_block=ppcb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="does not divide"):
        quant_paged_attention(q, kq[0], vq[0], ks[0], vs[0], lengths,
                              bt, fmt=fmt, pages_per_compute_block=3)
    with pytest.raises(ValueError, match="unknown fmt"):
        quant_paged_attention(q, kq[0], vq[0], ks[0], vs[0], lengths,
                              bt, fmt="int4", pages_per_compute_block=1)


def test_quant_tuning_site_is_its_own_key():
    """pages_per_compute_block consults op "paged_attention_quant"
    with the format in the key (ISSUE 12: a dense optimum must never
    answer a quantized consult) — and an explicit non-divisor fails
    loud on the gather path too."""
    from dlnetbench_tpu.tuning.params import (paged_attention_key,
                                              paged_attention_quant_key)
    kd = paged_attention_key(4, 4, 2, 4, 2, 16)
    kq8 = paged_attention_quant_key(4, 4, 2, 4, 2, 16, "int8")
    kf8 = paged_attention_quant_key(4, 4, 2, 4, 2, 16, "float8")
    assert kd != kq8 and kq8 != kf8
    (_, _), (kq, vq, ks, vs), q, lengths, bt, fmt = _write_streams(
        "int8", steps=4)
    with pytest.raises(ValueError, match="does not divide"):
        paged_attention_decode(q, kq[0], vq[0], lengths, bt,
                               k_scale=ks[0], v_scale=vs[0], fmt=fmt,
                               impl="gather", pages_per_compute_block=3)


@pytest.mark.tpu_only
def test_quant_kernel_parity_on_chip():
    """On-chip: the dequantizing Pallas kernel against the gather
    fallback on TPU-friendly shapes (collectable everywhere,
    auto-skipped off-TPU via the conftest hook)."""
    from dlnetbench_tpu.ops.paged_attention_quant import \
        quant_paged_attention
    rng = np.random.RandomState(0)
    hkv, pages, s, dh = 2, 32, 16, 128
    kq = jnp.asarray(rng.randint(-127, 127, (hkv, pages, s, dh)),
                     jnp.int8)
    vq = jnp.asarray(rng.randint(-127, 127, (hkv, pages, s, dh)),
                     jnp.int8)
    ks = jnp.asarray(np.abs(rng.randn(hkv, pages)) * 0.02 + 1e-4,
                     jnp.float32)
    vs = jnp.asarray(np.abs(rng.randn(hkv, pages)) * 0.02 + 1e-4,
                     jnp.float32)
    q = jnp.asarray(rng.randn(4, 8, dh), jnp.float32) * dh**-0.5
    lengths = jnp.asarray([40, 128, 16, 70], jnp.int32)
    pidx = jnp.asarray(np.arange(4 * 8).reshape(4, 8) % pages,
                       jnp.int32)
    ref = paged_attention_decode(q, kq, vq, lengths, pidx,
                                 k_scale=ks, v_scale=vs, fmt="int8",
                                 impl="gather")
    for ppcb in (1, 2, 8):
        got = quant_paged_attention(q, kq, vq, ks, vs, lengths, pidx,
                                    fmt="int8",
                                    pages_per_compute_block=ppcb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------
# engine end-to-end per cache dtype


def _run_engine(cfg, mc, params, reqs):
    from dlnetbench_tpu.serving.scheduler import Engine
    eng = Engine(mc, cfg, params=params)
    done, wall = eng.run(reqs)
    return eng, done


def test_engine_bf16_is_the_default_and_multi_step_quant_parity():
    """cache_dtype="bf16" IS the pre-ISSUE-12 engine (same program
    signature, no scale buffers), and on a quantized cache the fused
    N-step loop emits exactly the 1-step quantized engine's stream
    (same write sequence, so parity holds per cache dtype)."""
    mc = tiny_model()
    params = tfm.init_params(jax.random.key(0), mc)
    plan = ArrivalPlan(kind="poisson", rate_rps=500.0, num_requests=5,
                       seed=2, prompt_len=[5, 9], output_len=[3, 6])
    reqs = plan.sample()
    eng_d, done_d = _run_engine(tiny_serving(), mc, params, reqs)
    assert eng_d.k_scale is None and len(eng_d._pool_argnums) == 2
    eng_b, _ = _run_engine(tiny_serving(cache_dtype="bf16"), mc,
                           params, reqs)
    assert eng_b.token_streams == eng_d.token_streams
    for cd in ("int8", "fp8"):
        eng_1, done_1 = _run_engine(tiny_serving(cache_dtype=cd), mc,
                                    params, reqs)
        assert len(done_1) == len(reqs)
        assert eng_1.k_scale is not None
        eng_n, _ = _run_engine(tiny_serving(cache_dtype=cd,
                                            multi_step_n=4), mc,
                               params, reqs)
        assert eng_n.token_streams == eng_1.token_streams, cd


def test_quant_record_stamps_cache_dtype():
    from dlnetbench_tpu.serving.scheduler import run_serving
    mc = tiny_model()
    plan = ArrivalPlan(kind="poisson", rate_rps=400.0, num_requests=3,
                       seed=0, prompt_len=6, output_len=3)
    res = run_serving(mc, tiny_serving(cache_dtype="int8",
                                       warmup_requests=1), plan)
    g = res.global_meta
    assert g["kv_cache_dtype"] == "int8"
    assert g["serving_config"]["cache_dtype"] == "int8"
    assert g["serving"]["kv_cache"]["cache_dtype"] == "int8"
    assert g["serving"]["kv_cache"]["pool_bytes"] > 0
    assert g["serving"]["admitted_concurrency_peak"] >= 1


def test_merge_refuses_mismatched_cache_dtype():
    """kv_cache_dtype is a COMPARABLE global: records from
    differently-quantized caches are different runs and must refuse to
    merge, exactly like mismatched fault plans."""
    from dlnetbench_tpu.metrics.emit import emit_result
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.serving.scheduler import run_serving
    mc = tiny_model()
    plan = ArrivalPlan(kind="poisson", rate_rps=400.0, num_requests=2,
                       seed=0, prompt_len=6, output_len=2)
    recs = []
    for cd in ("bf16", "int8"):
        res = run_serving(mc, tiny_serving(cache_dtype=cd,
                                           warmup_requests=0), plan)
        recs.append(emit_result(res))
    recs[1]["process"] = 1
    recs[1]["global"]["num_processes"] = 2
    recs[0]["global"]["num_processes"] = 2
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        merge_records(recs)


# ---------------------------------------------------------------------
# refcounted allocator + trie + COW (satellite 2: the property test)


def test_admission_plan_charges_only_unshared_pages():
    cc = _cache_cfg(num_pages=16, max_seqs=4, max_pages_per_seq=4)
    cache = PagedKVCache(cc)
    prompt_a = np.arange(10, dtype=np.int32)       # 10 tokens
    # owner admits cold: full charge
    plan_a = cache.plan_admission(12, prompt_a)
    assert plan_a.need_pages == 3 and plan_a.shared_tokens == 0
    cache.admit(0, plan_a)
    cache.append(0, 10)      # prompt prefilled
    cache.publish(0, prompt_a)
    # same 8-token (2-page) prefix, different tail: 2 pages shared by
    # reference, partial boundary page COW-charged
    prompt_b = np.concatenate([prompt_a[:9], [99, 98, 97]]).astype(
        np.int32)
    plan_b = cache.plan_admission(12, prompt_b)
    # match capped at prompt_len-1 = 11 -> 9 matched tokens (8 full +
    # 1 partial row of A's page 2)
    assert plan_b.shared_tokens == 9
    assert len(plan_b.shared_pages) == 2
    assert plan_b.cow_src is not None and plan_b.cow_rows == 1
    assert plan_b.need_pages == 3 - 2  # only the unshared page count
    cow_dst = cache.admit(1, plan_b)
    assert cow_dst is not None and cow_dst != plan_b.cow_src
    # shared pages now have refcount 2; block tables alias them
    for p in plan_b.shared_pages:
        assert cache.refcount(p) == 2
    assert list(cache.block_tables[1, :2]) == plan_b.shared_pages
    # B's boundary page is PRIVATE — never the shared physical page
    assert cache.block_tables[1, 2] == cow_dst
    # lengths start at the shared token count (content already cached)
    assert cache.lengths[1] == 9
    # growing into the COW'd page is fine...
    cache.append(1, 3)
    # ...but a write into a page with refcount > 1 is refused loudly
    cache.lengths[1] = 7     # force the next append into shared page 1
    with pytest.raises(RuntimeError, match="shared page"):
        cache.append(1, 2)


def test_refcount_frees_on_last_reader_and_trie_drops():
    cc = _cache_cfg(num_pages=8, max_seqs=3, max_pages_per_seq=4)
    cache = PagedKVCache(cc)
    prompt = np.arange(9, dtype=np.int32)
    cache.admit(0, cache.plan_admission(9, prompt))
    cache.append(0, 9)
    cache.publish(0, prompt)
    plan = cache.plan_admission(9, prompt)
    assert plan.shared_tokens == 8 and len(plan.shared_pages) == 2
    cache.admit(1, plan)
    shared = plan.shared_pages
    used_before = cache.pages_in_use
    # owner evicts: shared pages stay (B still reads them)
    cache.free(0)
    for p in shared:
        assert cache.refcount(p) == 1
    assert cache.pages_in_use < used_before
    # B evicts: refcount hits zero, pages return to the free list and
    # leave the trie — a third request can no longer share them
    cache.free(1)
    for p in shared:
        assert cache.refcount(p) == 0
    plan2 = cache.plan_admission(9, prompt)
    assert plan2.shared_tokens == 0 and plan2.need_pages == 3
    assert cache.pages_in_use == 0


def test_allocator_refcount_cow_property():
    """Seeded property test (ISSUE 12 satellite, mirroring the
    device_state round-trip property): arbitrary interleavings of
    admit (with and without shared prefixes), prefill+publish,
    append-past-divergence, and evict — asserting no page leaks, no
    double frees, refcounts hitting zero exactly when the last reader
    evicts, and block tables never aliasing a written page."""
    rng = np.random.RandomState(7)
    cc = _cache_cfg(num_pages=24, max_seqs=4, max_pages_per_seq=4,
                    page_size=4)
    cache = PagedKVCache(cc)
    prompts = {}      # slot -> prompt tokens
    shared_full = {}  # slot -> full pages shared at admit
    # a small pool of system prompts drives real prefix collisions
    pool = [rng.randint(0, 50, size=8).astype(np.int32)
            for _ in range(2)]
    for step in range(300):
        op = rng.randint(0, 3)
        free_slots = [i for i in range(cc.max_seqs)
                      if not cache._pages_of[i]]
        busy = [i for i in range(cc.max_seqs) if cache._pages_of[i]]
        if op == 0 and free_slots:
            slot = free_slots[0]
            pre = pool[rng.randint(0, len(pool))]
            tail = rng.randint(50, 64, size=rng.randint(2, 7)).astype(
                np.int32)
            prompt = (np.concatenate([pre, tail])
                      if rng.rand() < 0.7 else tail)
            n_out = rng.randint(1, 5)
            total = len(prompt) + n_out
            if total > cc.max_seq_len:
                continue
            plan = cache.plan_admission(
                total, prompt if rng.rand() < 0.8 else None)
            if plan.need_pages > cache.free_pages:
                continue
            cache.admit(slot, plan)
            prompts[slot] = prompt
            shared_full[slot] = len(plan.shared_pages)
            # prefill the rest of the prompt, then publish
            cache.append(slot, len(prompt)
                         - int(plan.shared_tokens))
            cache.publish(slot, prompt)
        elif op == 1 and busy:
            slot = busy[rng.randint(0, len(busy))]
            # append past divergence (a decode token) while room holds
            room = (len(cache._pages_of[slot]) * cc.page_size
                    - int(cache.lengths[slot]))
            if room > 0:
                cache.append(slot)
        elif op == 2 and busy:
            slot = busy[rng.randint(0, len(busy))]
            cache.free(slot)
            prompts.pop(slot, None)
            shared_full.pop(slot, None)
        # ---- invariants, every step --------------------------------
        refs = np.zeros(cc.num_pages, np.int64)
        for i in range(cc.max_seqs):
            for p in cache._pages_of[i]:
                refs[p] += 1
        # refcounts == live block-table references, never negative
        assert np.array_equal(refs, np.asarray(cache._ref)), step
        # no leaks / double frees: the free list and the held pages
        # partition the physical pool exactly
        free_set = set(cache._free)
        assert len(free_set) == len(cache._free), "double free"
        held = {p for i in range(cc.max_seqs)
                for p in cache._pages_of[i]}
        assert free_set.isdisjoint(held), "freed page still held"
        assert free_set == set(range(cc.num_pages)) - held, step
        # block tables never alias a WRITTEN page: a page with
        # refcount > 1 can only be a FULL prompt page of each holder
        # (only prompt pages enter the trie; the partial boundary page
        # and every decode page are private — COW replaced the shared
        # one at admission, so writes land on refcount-1 pages only)
        for i in range(cc.max_seqs):
            if i not in prompts:
                continue
            full_prompt_pages = len(prompts[i]) // cc.page_size
            for col, p in enumerate(cache._pages_of[i]):
                if refs[p] > 1:
                    assert col < full_prompt_pages, (step, i, col)
    # drain everything: the pool must come back whole
    for i in range(cc.max_seqs):
        if cache._pages_of[i]:
            cache.free(i)
    assert cache.free_pages == cc.num_pages
    assert not cache.trie._node_of_page
    assert all(r == 0 for r in cache._ref)


# ---------------------------------------------------------------------
# prefix sharing: lossless engine runs + stats


def _prefix_plan(**over):
    kw = dict(kind="poisson", rate_rps=500.0, num_requests=8, seed=3,
              prompt_len=[10, 14], output_len=[3, 5],
              shared_prefix_len=8, prefix_pool=2)
    kw.update(over)
    return ArrivalPlan(**kw)


def test_prefix_sharing_engine_lossless_with_hits():
    """The acceptance lock: a prefix-sharing engine run produces
    TOKEN-IDENTICAL outputs to a non-sharing run on the same plan,
    with measured hits and bytes saved (page-aligned prefix + chunk
    dividing it — the stated exactness conditions)."""
    mc = tiny_model()
    params = tfm.init_params(jax.random.key(0), mc)
    plan = _prefix_plan()
    reqs = plan.sample()
    eng_off, done_off = _run_engine(tiny_serving(), mc, params, reqs)
    eng_on, done_on = _run_engine(tiny_serving(prefix_sharing=True),
                                  mc, params, reqs)
    assert len(done_on) == len(done_off) == len(reqs)
    assert eng_on.token_streams == eng_off.token_streams
    st = eng_on.cache.stats()["prefix"]
    assert st["hits"] > 0 and st["bytes_saved"] > 0
    assert 0 < st["hit_rate"] <= 1


def test_prefix_sharing_lossless_with_cow():
    """Unaligned prefix (9 tokens over 4-token pages): the divergence
    page is shared copy-on-write — still token-identical, with COW
    copies counted."""
    mc = tiny_model()
    params = tfm.init_params(jax.random.key(0), mc)
    plan = _prefix_plan(shared_prefix_len=9, prefix_pool=1)
    reqs = plan.sample()
    eng_off, _ = _run_engine(tiny_serving(), mc, params, reqs)
    eng_on, _ = _run_engine(tiny_serving(prefix_sharing=True), mc,
                            params, reqs)
    assert eng_on.token_streams == eng_off.token_streams
    st = eng_on.cache.stats()["prefix"]
    assert st["cow_copies"] > 0 and st["bytes_saved"] > 0


def test_prefix_sharing_composes_with_int8_cache():
    """Sharing + quantized cache: shared pages hold exactly the bytes
    the sharer's own prefill would have written (same chunking, same
    write sequence), so the combination stays token-identical to the
    non-sharing quantized engine."""
    mc = tiny_model()
    params = tfm.init_params(jax.random.key(0), mc)
    plan = _prefix_plan()
    reqs = plan.sample()
    eng_off, _ = _run_engine(tiny_serving(cache_dtype="int8"), mc,
                             params, reqs)
    eng_on, _ = _run_engine(tiny_serving(cache_dtype="int8",
                                         prefix_sharing=True), mc,
                            params, reqs)
    assert eng_on.token_streams == eng_off.token_streams
    assert eng_on.cache.stats()["prefix"]["hits"] > 0


def test_prefix_sharing_record_globals():
    from dlnetbench_tpu.serving.scheduler import run_serving
    mc = tiny_model()
    res = run_serving(mc, tiny_serving(prefix_sharing=True,
                                       warmup_requests=0),
                      _prefix_plan())
    g = res.global_meta
    assert g["prefix_hit_rate"] > 0
    assert g["prefix_bytes_saved"] > 0
    assert g["serving_config"]["prefix_sharing"] is True


# ---------------------------------------------------------------------
# arrival-plan prefix knobs (satellite 3)


def test_arrival_plan_prefix_knobs_roundtrip_and_validation():
    plan = _prefix_plan()
    d = plan.to_dict()
    assert d["shared_prefix_len"] == 8 and d["prefix_pool"] == 2
    back = ArrivalPlan.from_dict(d)
    assert back.shared_prefix_len == 8 and back.prefix_pool == 2
    assert [dataclasses.astuple(r) for r in back.sample()] \
        == [dataclasses.astuple(r) for r in plan.sample()]
    # no-prefix plans serialize WITHOUT the keys (committed fixtures
    # round-trip byte-identically)
    assert "shared_prefix_len" not in ArrivalPlan(
        kind="poisson", rate_rps=1.0, num_requests=1).to_dict()
    with pytest.raises(ValueError, match="shared_prefix_len"):
        ArrivalPlan(kind="poisson", rate_rps=1.0, num_requests=1,
                    shared_prefix_len=-1).validate()
    with pytest.raises(ValueError, match="prefix_pool"):
        _prefix_plan(prefix_pool=0).validate()
    with pytest.raises(ValueError, match="must be < the minimum"):
        _prefix_plan(shared_prefix_len=10).validate()
    # replay traces with explicit SHORTER prompts cannot sneak past
    # the plan-level range check
    with pytest.raises(ValueError, match="must be < the minimum"):
        ArrivalPlan(kind="replay", prompt_len=[8, 16],
                    shared_prefix_len=4,
                    trace=[{"t": 0.0, "prompt_len": 2,
                            "output_len": 4}]).validate()


def test_arrival_plan_prefix_fixture_roundtrip():
    """Committed prefix-heavy plan fixture beside the existing arrival
    fixtures: loads, validates, and samples deterministically with
    prefix ids drawn from the pool."""
    plan = ArrivalPlan.loads(f"@{DATA / 'arrival_prefix.json'}")
    assert plan.shared_prefix_len == 8 and plan.prefix_pool == 2
    reqs = plan.sample()
    assert all(0 <= r.prefix_id < 2 and r.prefix_len == 8
               for r in reqs)
    assert len({r.prefix_id for r in reqs}) == 2  # both prompts drawn
    # same plan json -> same stream, machine-independent
    again = ArrivalPlan.loads(f"@{DATA / 'arrival_prefix.json'}")
    assert [dataclasses.astuple(r) for r in again.sample()] \
        == [dataclasses.astuple(r) for r in reqs]


def test_prompt_tokens_for_prefix_requests():
    """Requests drawing the same prefix id share their first
    prefix_len tokens exactly; the tails stay rid-specific; prefix-less
    requests reproduce the legacy prompt_tokens stream."""
    from dlnetbench_tpu.serving import decode as D
    a = Request(rid=1, arrival_s=0.0, prompt_len=12, output_len=2,
                prefix_id=0, prefix_len=8)
    b = Request(rid=2, arrival_s=0.0, prompt_len=12, output_len=2,
                prefix_id=0, prefix_len=8)
    c = Request(rid=3, arrival_s=0.0, prompt_len=12, output_len=2,
                prefix_id=1, prefix_len=8)
    ta, tb, tc = (D.prompt_tokens_for(r, 64) for r in (a, b, c))
    assert np.array_equal(ta[:8], tb[:8])
    assert not np.array_equal(ta[:8], tc[:8])
    assert not np.array_equal(ta[8:], tb[8:])
    plain = Request(rid=1, arrival_s=0.0, prompt_len=12, output_len=2)
    assert np.array_equal(D.prompt_tokens_for(plain, 64),
                          D.prompt_tokens(1, 12, 64))
