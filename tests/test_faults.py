"""Fault-injection & elastic degradation (dlnetbench_tpu/faults/):
plan round-trip, step-boundary injection, the three degradation
policies around the dp proxy on the virtual mesh, record provenance,
and the analysis layer's straggler/recovery columns."""
from __future__ import annotations

import json

import pytest

from dlnetbench_tpu.faults.inject import FaultInjector, RankFailure
from dlnetbench_tpu.faults.plan import FaultEvent, FaultPlan


# --------------------------------------------------------------- plan
def test_plan_roundtrip_and_native_args(tmp_path):
    plan = FaultPlan(events=[
        FaultEvent(kind="delay", ranks=[2], iteration=1, until=5,
                   magnitude_us=2000.0),
        FaultEvent(kind="crash", ranks=[3], iteration=4),
    ], policy="shrink").validate()
    text = plan.dumps()
    back = FaultPlan.loads(text)
    assert back.to_dict() == plan.to_dict()
    # @file form
    p = tmp_path / "plan.json"
    p.write_text(text)
    assert FaultPlan.loads(f"@{p}").to_dict() == plan.to_dict()
    argv = plan.native_args()
    assert argv[0] == "--fault" and json.loads(argv[1]) == plan.to_dict()
    assert argv[2:] == ["--fault_policy", "shrink"]
    assert plan.crash_victims() == [3]
    assert plan.survivors(6) == [0, 1, 2, 4, 5]
    assert plan.first_crash_iteration() == 4
    assert plan.fault_window() == (1, -1) or plan.fault_window() == (1, None)


def test_plan_validation_rejects_bad_plans():
    with pytest.raises(ValueError, match="kind"):
        FaultPlan(events=[FaultEvent(kind="meteor")]).validate()
    with pytest.raises(ValueError, match="policy"):
        FaultPlan(policy="hope").validate()
    with pytest.raises(ValueError, match="drop rate"):
        FaultPlan(events=[FaultEvent(kind="drop", rate=1.0)]).validate()
    with pytest.raises(ValueError, match="partition"):
        FaultPlan(events=[FaultEvent(kind="partition")]).validate()


# ----------------------------------------------------------- injector
def test_injector_delay_window_and_counters():
    plan = FaultPlan(events=[FaultEvent(
        kind="delay", ranks=[1], iteration=1, until=3,
        magnitude_us=1000.0)]).validate()
    inj = FaultInjector(plan)
    slept = [inj.before_step() for _ in range(4)]
    # live at iterations 1 and 2 only
    assert slept[0] == 0.0 and slept[3] == 0.0
    assert slept[1] == slept[2] == 1000.0
    assert inj.injected_delay_us == 2000.0
    assert inj.iteration == 4


def test_injector_jitter_is_seeded_and_bounded():
    plan = FaultPlan(events=[FaultEvent(
        kind="jitter", iteration=0, magnitude_us=500.0,
        seed=7)]).validate()
    a = FaultInjector(plan)
    b = FaultInjector(plan)
    sa = [a.before_step() for _ in range(3)]
    sb = [b.before_step() for _ in range(3)]
    assert sa == sb  # deterministic replay
    assert all(0.0 <= v < 500.0 for v in sa)


def test_injector_crash_fires_exactly_at_trigger():
    plan = FaultPlan(events=[FaultEvent(kind="crash", ranks=[2],
                                        iteration=2)]).validate()
    inj = FaultInjector(plan)
    inj.before_step()
    inj.before_step()
    with pytest.raises(RankFailure) as ei:
        inj.before_step()
    assert ei.value.rank == 2 and ei.value.iteration == 2
    # the trigger fires once: the counter moved past it
    inj.before_step()


def test_collectives_fault_hook():
    """The pre-collective hook fires per wrapper invocation (once per
    TRACE for jitted programs — the documented semantics) and clears
    cleanly."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from dlnetbench_tpu.parallel import collectives
    from dlnetbench_tpu.utils.jax_compat import shard_map

    calls = []
    collectives.set_fault_hook(lambda op, axis: calls.append((op, axis)))
    try:
        mesh = Mesh(jax.devices()[:2], ("x",))
        prog = jax.jit(shard_map(
            lambda v: collectives.allreduce(v, "x"), mesh=mesh,
            in_specs=P("x"), out_specs=P()))
        out = prog(jnp.ones((2,), jnp.float32))
        assert float(out[0]) == 2.0
        assert calls == [("allreduce", "x")]  # once, at trace time
        prog(jnp.ones((2,), jnp.float32))
        assert len(calls) == 1  # compiled re-run: no host hook
    finally:
        collectives.set_fault_hook(None)
    collectives._maybe_fault("allreduce", "x")
    assert len(calls) == 1  # cleared


# ------------------------------------------------- policies (dp proxy)
def _dp_bundle(cfg, devices, dtype=None):
    import jax.numpy as jnp

    from dlnetbench_tpu.core.model_stats import load_model_stats
    from dlnetbench_tpu.parallel.mesh import make_flat_mesh
    from dlnetbench_tpu.proxies import dp as dp_proxy

    return dp_proxy.build(load_model_stats("gpt2_l_16_bfloat16"), 2, cfg,
                          mesh=make_flat_mesh(devices=devices),
                          dtype=dtype or jnp.float32)


@pytest.fixture(scope="module")
def proxy_cfg():
    from dlnetbench_tpu.proxies.base import ProxyConfig
    return ProxyConfig(warmup=1, runs=4, size_scale=1e-4, time_scale=1e-3,
                       measure_comm_only=False, measure_compute_only=False,
                       measure_energy=False)


def test_straggler_delay_rides_the_runtime_samples(eight_devices, proxy_cfg):
    """An injected per-step delay must inflate the timed runtime (the
    sleep lands INSIDE the chain) and be accounted in the
    fault_delay_us timer."""
    import dataclasses

    from dlnetbench_tpu.faults.policy import run_faulted

    cfg = dataclasses.replace(proxy_cfg, runs=4)
    plan = FaultPlan(events=[FaultEvent(kind="delay", ranks=[1],
                                        iteration=3,
                                        magnitude_us=20000.0)]).validate()
    bundle = _dp_bundle(cfg, eight_devices)
    res = run_faulted("dp", bundle, cfg, plan)
    g = res.global_meta
    assert g["fault_policy"] == "fail_fast"
    assert g["fault_plan"]["events"][0]["kind"] == "delay"
    assert g["fault_injected_delay_us"] >= 2 * 20000.0
    fd = res.timers_us["fault_delay_us"]
    assert len(fd) == cfg.runs
    # window starts at step 3 = measured run 2 (after the 1-step warmup)
    assert fd[0] == fd[1] == 0.0 and fd[2] >= 19999 and fd[3] >= 19999
    # the faulted samples carry the sleep over the IN-RECORD clean
    # baseline (runs 0-1, adjacent in time — cross-run medians would be
    # at the mercy of host drift)
    import statistics
    rt = res.timers_us["runtimes"]
    assert (statistics.median(rt[2:]) - statistics.median(rt[:2])
            >= 15000)


def test_crash_fail_fast_propagates(eight_devices, proxy_cfg):
    from dlnetbench_tpu.faults.policy import run_faulted

    plan = FaultPlan(events=[FaultEvent(kind="crash", ranks=[2],
                                        iteration=2)]).validate()
    bundle = _dp_bundle(proxy_cfg, eight_devices)
    with pytest.raises(RankFailure, match="rank 2"):
        run_faulted("dp", bundle, proxy_cfg, plan)


def test_crash_retry_recovers_on_same_world(eight_devices, proxy_cfg):
    from dlnetbench_tpu.faults.policy import run_faulted

    plan = FaultPlan(events=[FaultEvent(kind="crash", ranks=[2],
                                        iteration=2)],
                     policy="retry").validate()
    bundle = _dp_bundle(proxy_cfg, eight_devices)
    res = run_faulted("dp", bundle, proxy_cfg, plan)
    g = res.global_meta
    assert g["fault_retries"] == 1
    assert g["recovery_ms"] > 0 and g["detection_ms"] >= 0
    assert "degraded_world" not in g
    assert res.num_runs == proxy_cfg.runs
    assert len(res.timers_us["runtimes"]) == proxy_cfg.runs


def test_crash_shrink_finishes_on_survivors(eight_devices, proxy_cfg):
    """The elastic-degradation acceptance path on the python tier: the
    run finishes on the survivor mesh, the record declares
    degraded_world with ORIGINAL rank ids, detection/recovery are
    stamped, and the emitted record validates + parses."""
    from dlnetbench_tpu.faults.policy import run_faulted
    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.metrics.parser import records_to_dataframe, \
        validate_record

    plan = FaultPlan(events=[FaultEvent(kind="crash", ranks=[2],
                                        iteration=3)],
                     policy="shrink").validate()
    bundle = _dp_bundle(proxy_cfg, eight_devices)

    def rebuild(survivors):
        return _dp_bundle(proxy_cfg, [eight_devices[i] for i in survivors])

    res = run_faulted("dp", bundle, proxy_cfg, plan, rebuild=rebuild)
    g = res.global_meta
    assert g["degraded_world"] == [0, 1, 3, 4, 5, 6, 7]
    assert g["world_size"] == 8
    assert g["recovery_ms"] > 0 and g["detection_ms"] >= 0
    assert res.num_runs == proxy_cfg.runs

    rec = result_to_record(res)
    assert [row["rank"] for row in rec["ranks"]] == [0, 1, 3, 4, 5, 6, 7]
    validate_record(rec)
    df = records_to_dataframe([rec])
    assert len(df) == 7 * proxy_cfg.runs
    assert (df["runtime"] > 0).all()


def test_shrink_without_rebuild_or_bad_trigger_rejected(proxy_cfg):
    import dataclasses

    from dlnetbench_tpu.faults.policy import run_faulted

    class FakeBundle:
        global_meta = {"world_size": 4}

    plan = FaultPlan(events=[FaultEvent(kind="crash", ranks=[1],
                                        iteration=0)],
                     policy="shrink").validate()
    with pytest.raises(ValueError, match="warmup"):
        run_faulted("dp", FakeBundle(), proxy_cfg, plan, rebuild=lambda s: s)
    plan2 = FaultPlan(events=[FaultEvent(kind="crash", ranks=[1],
                                         iteration=2)],
                      policy="shrink").validate()
    cfg = dataclasses.replace(proxy_cfg, reps_per_fence=4)
    with pytest.raises(ValueError, match="reps_per_fence"):
        run_faulted("dp", FakeBundle(), cfg, plan2, rebuild=lambda s: s)
    # run-count estimation could move the measured region past the
    # trigger, letting the crash escape the policy — rejected up front
    cfg2 = dataclasses.replace(proxy_cfg, min_exectime_s=1.0)
    with pytest.raises(ValueError, match="min_exectime"):
        run_faulted("dp", FakeBundle(), cfg2, plan2, rebuild=lambda s: s)


def test_preempt_rejoin_plan_validation_and_queries():
    """The elastic schema (ISSUE 7): preempt needs explicit ranks and
    policy shrink; rejoin must follow its preempt; the eviction-window
    queries and the fault window close at the rejoin."""
    with pytest.raises(ValueError, match="ranks"):
        FaultPlan(events=[FaultEvent(kind="preempt", iteration=3)],
                  policy="shrink").validate()
    with pytest.raises(ValueError, match="shrink"):
        FaultPlan(events=[FaultEvent(kind="preempt", ranks=[1],
                                     iteration=3)]).validate()
    with pytest.raises(ValueError, match="nobody left"):
        FaultPlan(events=[FaultEvent(kind="rejoin", ranks=[1],
                                     iteration=5)],
                  policy="shrink").validate()
    with pytest.raises(ValueError, match="does not follow"):
        FaultPlan(events=[
            FaultEvent(kind="preempt", ranks=[1], iteration=5),
            FaultEvent(kind="rejoin", ranks=[1], iteration=4),
        ], policy="shrink").validate()

    plan = FaultPlan(events=[
        FaultEvent(kind="preempt", ranks=[2], iteration=4,
                   magnitude_us=20000.0),
        FaultEvent(kind="rejoin", ranks=[2], iteration=8),
    ], policy="shrink").validate()
    assert plan.preempt_victims() == [2]
    assert plan.first_preempt_iteration() == 4
    assert plan.rejoin_iteration() == 8
    assert not plan.evicted(2, 3)
    assert plan.evicted(2, 4) and plan.evicted(2, 7)
    assert not plan.evicted(2, 8)  # back in the world
    assert not plan.evicted(0, 5)  # survivors were never out
    # window closes at rejoin + 1: the rejoin step pays the grow
    # re-split and must not pass as clean
    assert plan.fault_window() == (4, 9)
    # round-trips through the shared wire format
    assert FaultPlan.loads(plan.dumps()).to_dict() == plan.to_dict()
    # the segmented python tier needs a degraded step between the two
    with pytest.raises(ValueError, match="preempt \\+ 2"):
        FaultPlan(events=[
            FaultEvent(kind="preempt", ranks=[2], iteration=4),
            FaultEvent(kind="rejoin", ranks=[2], iteration=5),
        ], policy="shrink").validate().check_config(
            ProxyConfigStub())


class ProxyConfigStub:
    warmup = 1
    runs = 8
    reps_per_fence = 1
    min_exectime_s = 0.0


def test_preempt_restore_rejoin_end_to_end(eight_devices, proxy_cfg,
                                           tmp_path):
    """The acceptance arc on the python tier: preempt -> grace-window
    drain -> restore-from-latest -> shrink -> rejoin restores the FULL
    world (degraded_world cleared), with checkpoint costs, lost work,
    and goodput stamped — and the record parses clean."""
    import dataclasses

    from dlnetbench_tpu.faults.policy import CheckpointPolicy, run_faulted
    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.metrics.parser import records_to_dataframe, \
        validate_record

    cfg = dataclasses.replace(proxy_cfg, runs=8)
    plan = FaultPlan(events=[
        FaultEvent(kind="preempt", ranks=[2], iteration=4,
                   magnitude_us=50000.0),
        FaultEvent(kind="rejoin", ranks=[2], iteration=7),
    ], policy="shrink").validate()
    bundle = _dp_bundle(cfg, eight_devices)

    def rebuild(ranks):
        return _dp_bundle(cfg, [eight_devices[i] for i in ranks])

    res = run_faulted("dp", bundle, cfg, plan, rebuild=rebuild,
                      checkpoint=CheckpointPolicy(
                          dir=tmp_path / "ck", every=2, mode="stall",
                          backend="npz"))
    g = res.global_meta
    # the world grew back: NO degraded_world, full rank coverage
    assert "degraded_world" not in g
    assert g["fault_rejoin_step"] == 7
    assert g["rejoin_ms"] > 0
    assert g["world_size"] == 8
    # checkpoint accounting: periodic saves happened, the eviction
    # restored from the latest, and the redone work is priced
    assert g["checkpoint_saves"] >= 1
    assert g["checkpoint_ms"] > 0 and g["checkpoint_stall_ms"] > 0
    assert g["checkpoint_backend"] == "npz"
    assert g["restore_ms"] > 0
    assert 0 <= g["lost_steps"] < cfg.runs
    assert g["goodput"] > 0
    assert g["goodput_useful_steps"] == cfg.runs - g["lost_steps"]
    assert g["detection_ms"] >= 0 and g["recovery_ms"] > 0
    assert res.num_runs == cfg.runs

    rec = result_to_record(res)
    validate_record(rec)
    assert [row["rank"] for row in rec["ranks"]] == list(range(8))
    df = records_to_dataframe([rec])
    assert len(df) == 8 * cfg.runs


def test_preempt_without_rejoin_stays_degraded(eight_devices, proxy_cfg):
    """A plan that never grows back degrades to the end like shrink —
    degraded_world keeps the survivor set."""
    from dlnetbench_tpu.faults.policy import run_faulted

    plan = FaultPlan(events=[
        FaultEvent(kind="preempt", ranks=[2], iteration=3,
                   magnitude_us=1000.0),
    ], policy="shrink").validate()
    bundle = _dp_bundle(proxy_cfg, eight_devices)

    def rebuild(ranks):
        return _dp_bundle(proxy_cfg, [eight_devices[i] for i in ranks])

    res = run_faulted("dp", bundle, proxy_cfg, plan, rebuild=rebuild)
    g = res.global_meta
    assert g["degraded_world"] == [0, 1, 3, 4, 5, 6, 7]
    assert "fault_rejoin_step" not in g
    assert g["goodput"] > 0  # the arc still yields its bottom line


def test_checkpoint_policy_requires_declared_state(eight_devices,
                                                   proxy_cfg, tmp_path):
    """A bundle without StepBundle.state cannot honestly price
    checkpointing — refused up front, never priced at zero bytes."""
    import dataclasses

    from dlnetbench_tpu.faults.policy import CheckpointPolicy, run_faulted

    plan = FaultPlan(events=[FaultEvent(kind="crash", ranks=[2],
                                        iteration=3)],
                     policy="shrink").validate()
    bundle = dataclasses.replace(_dp_bundle(proxy_cfg, eight_devices),
                                 state=None)
    with pytest.raises(ValueError, match="checkpointable state"):
        run_faulted("dp", bundle, proxy_cfg, plan,
                    rebuild=lambda s: s,
                    checkpoint=CheckpointPolicy(dir=tmp_path / "ck"))


def test_parallel_stragglers_gate_on_max_not_sum():
    """Delays on DIFFERENT ranks run in parallel: the per-step injected
    figure (amplification denominator) is the max over target ranks,
    plus everyone-targeted events that stack on every rank."""
    plan = FaultPlan(events=[
        FaultEvent(kind="delay", ranks=[1], magnitude_us=100.0),
        FaultEvent(kind="delay", ranks=[2], magnitude_us=100.0),
        FaultEvent(kind="delay", magnitude_us=10.0),  # every rank
    ]).validate()
    assert plan.delay_per_step_us() == 110.0      # max(100, 100) + 10
    assert plan.delay_per_step_us(rank=1) == 110.0
    assert plan.delay_per_step_us(rank=3) == 10.0

    from dlnetbench_tpu.analysis.bandwidth import straggler_amplification
    rec = _faulted_record(runtimes=[1000.0, 1000.0, 1110.0, 1110.0])
    rec["global"]["fault_plan"]["events"] = [
        {"kind": "delay", "ranks": [0], "iteration": 3,
         "magnitude_us": 100.0},
        {"kind": "delay", "ranks": [1], "iteration": 3,
         "magnitude_us": 100.0},
        {"kind": "delay", "iteration": 3, "magnitude_us": 10.0},
    ]
    # 110 us inflation / max-based 110 us = 1.0 (a summed 210 us
    # denominator would misreport 0.52)
    assert straggler_amplification(rec) == pytest.approx(1.0)


def test_fault_window_respects_reps_per_fence():
    """With reps_per_fence = K each runtime sample covers K measured
    steps: a chain with ANY faulted step must group as faulted, and
    the measured fault_delay_us timer (already per-iteration) is the
    amplification denominator for such records."""
    from dlnetbench_tpu.analysis.bandwidth import effective_bandwidth, \
        straggler_amplification

    # 8 measured steps as 2 chains of 4; delay live from step 5 on
    # (warmup 1 -> measured steps 4..) — only chain 1 intersects
    rec = _faulted_record(iteration=5, runtimes=[1000.0, 6000.0],
                          reps_per_fence=4)
    rec["num_runs"] = 2
    for row in rec["ranks"]:
        row["fault_delay_us"] = [0.0, 5000.0]
    bw = effective_bandwidth([rec])
    assert list(bw[bw["run"] == 0]["bound"].unique()) == ["exact"]
    assert list(bw[bw["run"] == 1]["bound"].unique()) == ["faulted"]
    # (6000 - 1000) / measured 5000 per-iteration injection = 1.0
    assert straggler_amplification(rec) == pytest.approx(1.0)


# ------------------------------------------------------ analysis layer
def _faulted_record(kind="delay", iteration=3, until=-1, magnitude=20000.0,
                    runtimes=None, warmup=1, **extra_globals):
    events = [{"kind": kind, "ranks": [1], "iteration": iteration,
               **({"until": until} if until >= 0 else {}),
               **({"magnitude_us": magnitude}
                  if kind in ("delay", "jitter") else {})}]
    runtimes = runtimes or [1000.0, 1000.0, 21000.0, 21000.0]
    return {
        "section": "dp", "version": 2, "process": 0,
        "global": {"proxy": "dp", "model": "m", "world_size": 2,
                   "fault_plan": {"policy": "fail_fast", "events": events},
                   "fault_policy": "fail_fast",
                   "comm_model": {"runtimes": [
                       {"kind": "allreduce", "group": 2,
                        "bytes": 1_000_000}]},
                   **extra_globals},
        "mesh": {"platform": "cpu"},
        "num_runs": len(runtimes),
        "warmup_times": [1.0] * warmup,
        "ranks": [{"rank": r, "device_id": r, "process_index": 0,
                   "hostname": "h", "runtimes": list(runtimes)}
                  for r in range(2)],
    }


def test_bandwidth_suppresses_faulted_runs_and_reports_amplification():
    from dlnetbench_tpu.analysis.bandwidth import bandwidth_summary, \
        effective_bandwidth, straggler_amplification

    rec = _faulted_record()
    bw = effective_bandwidth([rec])
    # steps 0..: warmup 1 -> measured run window starts at run 2
    clean = bw[bw["run"] < 2]
    faulted = bw[bw["run"] >= 2]
    assert (clean["bound"] == "exact").all()
    assert (faulted["bound"] == "faulted").all()
    assert faulted["busbw_GBps"].isna().all()
    assert clean["busbw_GBps"].notna().all()
    # (21000 - 1000) us inflation / 20000 us injected = 1.0
    amp = straggler_amplification(rec)
    assert amp == pytest.approx(1.0)
    summary = bandwidth_summary([rec])
    srow = summary[summary["bound"] == "faulted"].iloc[0]
    assert srow["straggler_amp"] == pytest.approx(1.0)

    # crash records have no comparable baseline: amplification is NaN
    import math
    crash = _faulted_record(kind="crash", detection_ms=5.0,
                            recovery_ms=7.0)
    assert math.isnan(straggler_amplification(crash))
    bw2 = bandwidth_summary([crash])
    assert (bw2["detection_ms"].dropna() == 5.0).all()
    assert (bw2["recovery_ms"].dropna() == 7.0).all()


def test_bandwidth_elastic_recovery_columns():
    """checkpoint_ms / restore_ms / lost_steps / goodput ride every
    bandwidth row of a record that measured them, NaN otherwise; the
    preempt window's runs still get busbw refused."""
    from dlnetbench_tpu.analysis.bandwidth import bandwidth_summary, \
        effective_bandwidth

    rec = _faulted_record(checkpoint_ms=12.5, restore_ms=3.25,
                          lost_steps=2, goodput=6.125)
    rec["global"]["fault_plan"] = {
        "policy": "shrink",
        "events": [{"kind": "preempt", "ranks": [1], "iteration": 3,
                    "magnitude_us": 20000.0},
                   {"kind": "rejoin", "ranks": [1], "iteration": 5}]}
    bw = effective_bandwidth([rec])
    for col, want in (("checkpoint_ms", 12.5), ("restore_ms", 3.25),
                      ("lost_steps", 2.0), ("goodput", 6.125)):
        assert (bw[col] == want).all()
    # warmup 1: plan steps 3..5 (+1 for the rejoin step) = runs 2..4
    faulted = bw[bw["bound"] == "faulted"]
    assert sorted(faulted["run"].unique()) == [2, 3]
    assert faulted["busbw_GBps"].isna().all()
    summary = bandwidth_summary([rec])
    assert (summary["goodput"].dropna() == 6.125).all()

    clean = _faulted_record()
    bw2 = effective_bandwidth([clean])
    for col in ("checkpoint_ms", "restore_ms", "lost_steps", "goodput"):
        assert bw2[col].isna().all()


def test_clean_records_unaffected_by_fault_columns():
    from dlnetbench_tpu.analysis.bandwidth import effective_bandwidth

    rec = _faulted_record()
    del rec["global"]["fault_plan"]
    del rec["global"]["fault_policy"]
    bw = effective_bandwidth([rec])
    assert (bw["bound"] == "exact").all()
    assert bw["busbw_GBps"].notna().all()
    assert bw["straggler_amp"].isna().all()
