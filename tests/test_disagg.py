"""Disaggregated prefill/decode serving (ISSUE 16): the migration
channel's bit-exact quantized wire, the closed-form byte accounting,
the overlap-leg discipline, the config guards, the adaptive-N ETA cap,
token parity against the monolithic engine per cache dtype, fault
composition (a prefill-replica crash under shrink), and the committed
two-replica record fixture's round trip."""
from __future__ import annotations

import copy
import dataclasses
import json
import math
import time
from collections import deque
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.metrics import telemetry
from dlnetbench_tpu.models import transformer as tfm
from dlnetbench_tpu.ops.page_migration import (MigrationChannel,
                                               bf16_equiv_page_bytes)
from dlnetbench_tpu.serving.arrivals import ArrivalPlan, Request
from dlnetbench_tpu.serving.kv_cache import CacheConfig, device_buffers
from dlnetbench_tpu.serving.scheduler import (Engine, ServingConfig,
                                              _SlotState)

DATA = Path(__file__).parent / "data"

pytestmark = [pytest.mark.serving, pytest.mark.disagg]


@pytest.fixture(autouse=True)
def _clean_recorder():
    """Never leak an enabled recorder into (or out of) a test."""
    telemetry.disable()
    yield
    telemetry.disable()


def tiny_model(**over) -> tfm.TransformerConfig:
    kw = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
              ff_dim=64, num_layers=2, seq_len=32, gated=True,
              max_positions=0, dtype="float32")
    kw.update(over)
    return tfm.TransformerConfig(**kw)


def disagg_serving(**over) -> ServingConfig:
    # page_size=8 so the int8 wire's scale overhead amortizes below the
    # 0.55x bar: bytes ratio = (S*Dh + 4) / (2*S*Dh) per page
    kw = dict(slots=4, page_size=8, num_pages=16, max_seq_len=32,
              slo_ttft_ms=200.0, slo_tpot_ms=100.0, world=2,
              disaggregate=True, prefill_ranks=1, decode_ranks=1,
              multi_step_n=4, adaptive_n=True, warmup_requests=0)
    kw.update(over)
    return ServingConfig(**kw)


def chan_cache(**over) -> CacheConfig:
    kw = dict(num_layers=2, num_kv_heads=2, head_dim=16, num_pages=16,
              page_size=8, max_seqs=2, max_pages_per_seq=4,
              cache_dtype="int8")
    kw.update(over)
    return CacheConfig(**kw).validate()


def _fill(pool, rng):
    """Random content in the pool's STORED dtype (int8 pools get the
    full signed range; float pools get gaussian values cast down)."""
    if pool.dtype == jnp.int8:
        return jnp.asarray(
            rng.randint(-127, 128, pool.shape).astype(np.int8))
    return jnp.asarray(rng.randn(*pool.shape).astype(np.float32),
                       pool.dtype)


# ---------------------------------------------------------------------
# the migration channel: bit-exact payload, closed-form bytes, overlap


@pytest.mark.parametrize("cache_dtype", ["bf16", "int8", "fp8"])
def test_migration_payload_bit_exact(cache_dtype):
    """send -> scatter moves pages (+ scales) in the STORED dtype and
    lands them bit-identical at the destination page ids — the
    token-parity bar's transport half, per cache dtype."""
    cfg = chan_cache(cache_dtype=cache_dtype)
    rng = np.random.RandomState(0)
    src = tuple(_fill(p, rng) for p in device_buffers(cfg))
    dst = device_buffers(cfg)
    ch = MigrationChannel(cfg, jax.devices()[1], chunk_pages=3)
    src_ids, dst_ids = [5, 1, 7, 2], [0, 3, 9, 11]
    pending = ch.send(src, src_ids, fence=True)
    out = ch.scatter(dst, pending, dst_ids)
    assert len(out) == len(src)
    for got, want in zip(out, src):
        assert got.dtype == want.dtype  # never widened to bf16
        g, w = np.asarray(got), np.asarray(want)
        for s, d in zip(src_ids, dst_ids):
            assert np.array_equal(g[:, :, d], w[:, :, s]), \
                (cache_dtype, s, d)
    # 4 pages through chunk_pages=3 is exactly two chunk transfers
    rec = ch._sends[0]
    assert rec.pages == 4 and rec.chunks == 2 and not rec.overlapped
    assert rec.bytes == 4 * cfg.page_bytes


def test_migration_bytes_closed_form():
    """migration_bytes is the pool algebra, not a transport guess:
    n * page_bytes with the per-page-per-head f32 scales INCLUDED, and
    the quantized wire prices under 0.55x of the bf16 equivalent at
    page_size=8 (the ISSUE 16 acceptance bar)."""
    cfg = chan_cache(cache_dtype="int8")
    ch = MigrationChannel(cfg, jax.devices()[1])
    payload = (2 * cfg.num_layers * cfg.num_kv_heads * cfg.page_size
               * cfg.head_dim)                      # int8: 1 B/elem
    scales = 2 * cfg.num_layers * cfg.num_kv_heads * 4
    assert cfg.page_bytes == payload + scales
    assert ch.bytes_for_pages(3) == 3 * cfg.page_bytes
    assert ch.bf16_equiv_bytes(3) == 3 * bf16_equiv_page_bytes(cfg) \
        == 3 * 2 * payload
    ratio = ch.bytes_for_pages(3) / ch.bf16_equiv_bytes(3)
    s_dh = cfg.page_size * cfg.head_dim
    assert ratio == pytest.approx((s_dh + 4) / (2 * s_dh))
    assert ratio <= 0.55


def test_migration_channel_refusals():
    cfg = chan_cache()
    with pytest.raises(ValueError, match="chunk_pages"):
        MigrationChannel(cfg, jax.devices()[1], chunk_pages=0)
    ch = MigrationChannel(cfg, jax.devices()[1])
    src = device_buffers(cfg)
    with pytest.raises(ValueError, match="empty page list"):
        ch.send(src, [])
    pending = ch.send(src, [0, 1], fence=True)
    with pytest.raises(ValueError, match="destination pages"):
        ch.scatter(device_buffers(cfg), pending, [4])


def test_migration_overlap_nan_unless_all_legs():
    """The overlap fraction exists only when comm-solo, compute-solo
    AND together legs were all measured — anything less emits NaN, and
    a channel that never carried a sequence has no stats block."""
    cfg = chan_cache()
    ch = MigrationChannel(cfg, jax.devices()[1])
    assert ch.stats_block() is None
    src = device_buffers(cfg)
    # an OVERLAPPED send alone is not a comm-solo leg
    p = ch.send(src, [0], fence=False, overlapped=True)
    assert p._record is None      # unfenced: not recorded yet
    r1 = p.wait()
    assert p.wait() is r1         # idempotent
    assert r1.overlapped
    ch.note_compute_solo(0.010)
    ch.note_both(0.012)
    assert math.isnan(ch.overlap())     # no fenced (solo) send yet
    ch.send(src, [1], fence=True)       # the comm-solo leg
    assert not math.isnan(ch.overlap())
    blk = ch.stats_block()
    assert blk["sends"] == 2 and blk["overlapped_sends"] == 1
    assert blk["pages"] == 2 and blk["bytes"] == 2 * cfg.page_bytes
    # missing legs -> NaN, not a fabricated number
    ch2 = MigrationChannel(cfg, jax.devices()[1])
    ch2.send(device_buffers(cfg), [0], fence=True)
    assert math.isnan(ch2.overlap())
    assert math.isnan(ch2.stats_block()["overlap"])


# ---------------------------------------------------------------------
# config guards


def test_disagg_config_refusals():
    with pytest.raises(ValueError, match="each phase is a replica"):
        disagg_serving(prefill_ranks=0, world=1).validate()
    with pytest.raises(ValueError, match="disjoint"):
        disagg_serving(world=4).validate()
    with pytest.raises(ValueError, match="divisible"):
        disagg_serving(slots=3, world=3, prefill_ranks=2).validate()
    with pytest.raises(ValueError, match="speculative"):
        disagg_serving(speculative=True).validate()
    with pytest.raises(ValueError, match="prefix_sharing"):
        disagg_serving(prefix_sharing=True).validate()
    with pytest.raises(ValueError, match="kv_shard"):
        disagg_serving(kv_shard=2).validate()
    with pytest.raises(ValueError, match="inline"):
        disagg_serving(prefill="inline").validate()
    with pytest.raises(ValueError, match="migration_chunk_pages"):
        disagg_serving(migration_chunk_pages=0).validate()
    # a disaggregated config drives TWO engines, never one
    with pytest.raises(ValueError, match="run_disagg"):
        Engine(tiny_model(), disagg_serving())
    # and the server refuses a monolithic config right back
    from dlnetbench_tpu.serving.disagg import DisaggServer
    with pytest.raises(ValueError, match="disaggregate=True"):
        DisaggServer(tiny_model(),
                     disagg_serving(disaggregate=False, world=1))


# ---------------------------------------------------------------------
# the adaptive-N migration-ETA cap (unit: no engine build needed)


def _bare_engine(cfg: ServingConfig) -> Engine:
    """_pick_n_steps touches only host-side scheduler state — build
    that state without compiling any programs."""
    eng = object.__new__(Engine)
    eng.cfg = cfg
    eng.pending = deque()
    eng.queue = deque()
    eng._t0 = time.monotonic()
    eng._step_ewma_s = 0.010
    eng._migration_eta_s = None
    st = _SlotState(Request(rid=0, arrival_s=0.0, prompt_len=8,
                            output_len=100), admitted_s=0.0)
    st.prefill_done = 8
    eng.slots = [st, None, None, None]
    return eng


def test_pick_n_steps_migration_eta_cap():
    cfg = ServingConfig(slots=4, page_size=8, num_pages=16,
                        max_seq_len=32, multi_step_n=8, adaptive_n=True)
    eng = _bare_engine(cfg)
    # None (every monolithic engine, always): bit-identical full N
    assert eng._pick_n_steps([0]) == 8
    # a handoff expected NOW caps the trip count to one device step
    eng._migration_eta_s = eng._now()
    assert eng._pick_n_steps([0]) == 1
    # an ETA a few step-EWMAs out caps to roughly that many trips
    eng._migration_eta_s = eng._now() + 2.5 * eng._step_ewma_s
    assert eng._pick_n_steps([0]) == 3
    # a far-future ETA leaves the full fused loop alone
    eng._migration_eta_s = eng._now() + 10.0
    assert eng._pick_n_steps([0]) == 8
    # non-adaptive engines ignore the ETA entirely
    eng2 = _bare_engine(dataclasses.replace(cfg, adaptive_n=False))
    eng2._migration_eta_s = eng2._now()
    assert eng2._pick_n_steps([0]) == 8


# ---------------------------------------------------------------------
# token parity vs the monolithic engine (the tentpole bar)


def _parity_streams(cache_dtype: str):
    mc = tiny_model()
    plan = ArrivalPlan(kind="poisson", rate_rps=200.0, num_requests=8,
                       seed=7, prompt_len=[4, 9], output_len=5)
    params = tfm.init_params(jax.random.PRNGKey(0), mc)
    mono_cfg = disagg_serving(disaggregate=False, world=2,
                              cache_dtype=cache_dtype)
    eng = Engine(mc, mono_cfg, params=params)
    eng.run(plan.sample())
    mono = {rid: list(t) for rid, t in eng.token_streams.items()}

    from dlnetbench_tpu.serving.disagg import DisaggServer
    srv = DisaggServer(mc, disagg_serving(cache_dtype=cache_dtype),
                       params=params)
    completed, _wall = srv.run(plan.sample())
    return mono, srv, completed


def test_token_parity_int8_and_wire_stays_quantized():
    """The quantized representative: disaggregated greedy output is
    token-identical to monolithic int8, TTFT is stamped for every
    completion (prefill-side), and the wire carried the stored-int8
    pages at <= 0.55x the bf16-equivalent bytes."""
    mono, srv, completed = _parity_streams("int8")
    assert srv.token_streams == mono
    assert len(completed) == 8
    assert all(c.first_token_s is not None
               and c.first_token_s <= c.finish_s for c in completed)
    blk = srv.channel.stats_block()
    assert blk["sends"] == 8      # every request crossed the wire
    assert blk["bytes_ratio_vs_bf16"] <= 0.55
    assert blk["bytes"] == blk["pages"] * srv.decode.cache_cfg.page_bytes


@pytest.mark.slow
def test_token_parity_bf16():
    mono, srv, completed = _parity_streams("bf16")
    assert srv.token_streams == mono
    assert len(completed) == 8
    assert srv.channel.stats_block()["sends"] == 8


# ---------------------------------------------------------------------
# fault composition: a prefill-replica crash under shrink


@pytest.mark.slow
def test_prefill_crash_blows_ttft_keeps_tpot(tmp_path):
    """Crash ONE prefill rank mid-plan under shrink: decode survivors
    keep TPOT at the decode SLO while TTFT p99 blows up (re-queued
    requests keep their ORIGINAL arrival stamps, so the rebuild is on
    the record), the degraded/detection/recovery fields stamp, the
    anomaly engine fires the ``slo`` trigger, and the flight dump
    carries the migration provenance next to the stall."""
    from dlnetbench_tpu.faults.plan import FaultEvent, FaultPlan
    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.metrics.parser import validate_record
    from dlnetbench_tpu.serving.disagg import run_disagg

    mc = tiny_model()
    cfg = disagg_serving(world=3, prefill_ranks=2, decode_ranks=1,
                         cache_dtype="int8")
    trace = [{"t": 0.01 * i, "prompt_len": 6, "output_len": 4}
             for i in range(10)]
    trace += [{"t": 4.0 + 0.05 * i, "prompt_len": 6, "output_len": 4}
              for i in range(6)]
    plan = ArrivalPlan(kind="replay", trace=trace)
    params = tfm.init_params(jax.random.PRNGKey(0), mc)

    clean = run_disagg(mc, cfg, plan, params=params) \
        .global_meta["serving"]

    rec = telemetry.enable(capacity=256, dump_dir=tmp_path)
    fp = FaultPlan(events=[FaultEvent(kind="crash", ranks=[0],
                                      iteration=4)], policy="shrink")
    res = run_disagg(mc, cfg, plan, fault_plan=fp, params=params)
    g = res.global_meta
    assert g["degraded_world"] == [1, 2]   # prefill rank 0 is gone
    assert g["degraded_slots"] == 4        # decode share untouched
    assert g["detection_ms"] >= 0 and g["recovery_ms"] > 0
    assert res.num_runs == len(trace)      # every request completes
    srv = g["serving"]
    # the asymmetry the monolithic engine cannot express: admission
    # (TTFT) eats the rebuild while decode survivors hold their SLO
    assert srv["ttft_ms"]["p99"] > clean["ttft_ms"]["p99"]
    # > 10x the TTFT SLO is only reachable if re-queued requests kept
    # their ORIGINAL arrival stamps — a re-stamped arrival would reset
    # TTFT to the clean sub-SLO regime
    assert srv["ttft_ms"]["p99"] > 10 * cfg.slo_ttft_ms
    assert srv["tpot_ms"]["p50"] <= cfg.slo_tpot_ms
    assert srv["completed"] == len(trace)
    # both segments' migrations folded into ONE wire block
    assert srv["migration"]["sends"] >= len(trace)
    # the fault trigger names the replica; the SLO breach fired and
    # dumped a window whose ring holds the migration records
    kinds = {a["trigger"]: a for a in rec.anomalies}
    assert kinds["fault"]["detail"]["replica"] == "prefill"
    assert "slo" in kinds
    dump = json.loads((tmp_path / "flight_slo.json").read_text())
    assert dump["trigger"] == "slo"
    assert any(s["source"] == "migration" for s in dump["samples"])
    mig = [s for s in rec.samples() if s["source"] == "migration"]
    assert mig and all("queue_depth" in s and "bytes" in s
                       for s in mig)
    record = result_to_record(res)  # recorder still live: anomalies stamp
    validate_record(record)
    assert record["global"]["disaggregated"] is True
    assert record["global"]["anomalies"]["triggers"].get("slo", 0) >= 1


# ---------------------------------------------------------------------
# the record pathway: committed two-replica fixture round trip


def test_disagg_record_fixture_roundtrip():
    """The committed disaggregated record (a REAL two-replica int8
    run of serving/disagg.run_disagg) flows parser -> merge -> summary
    with the migration and replica columns populated."""
    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe,
                                               validate_record)
    records = load_records(DATA / "record_disagg.jsonl")
    assert len(records) == 1
    rec = records[0]
    validate_record(rec)
    g = rec["global"]
    assert g["disaggregated"] is True
    sc = g["serving_config"]
    assert sc["prefill_ranks"] == 1 and sc["decode_ranks"] == 1
    mig = g["serving"]["migration"]
    assert mig["sends"] > 0 and mig["bytes"] > 0
    assert mig["bytes_ratio_vs_bf16"] <= 0.55    # int8 wire, page_size=8
    assert mig["bytes"] == pytest.approx(
        mig["bytes_ratio_vs_bf16"] * mig["bf16_equiv_bytes"], rel=1e-3)

    df = records_to_dataframe(records)
    for col in ("serving_migration_bytes", "serving_migration_bytes_ratio",
                "serving_migration_ms_p50", "serving_migration_overlap",
                "disaggregated"):
        assert col in df.columns, col
    assert df["serving_migration_bytes"].iloc[0] == mig["bytes"]

    merged = merge_records(records)   # single-process identity
    validate_record(merged)
    ss = serving_summary([merged])
    row = ss.iloc[0]
    assert bool(row["disaggregated"]) is True
    assert row["prefill_ranks"] == 1 and row["decode_ranks"] == 1
    assert row["migration_bytes"] == mig["bytes"]
    assert row["migration_bytes_ratio"] == mig["bytes_ratio_vs_bf16"]
    assert not math.isnan(row["migration_ms_p50"])


def test_pre_disagg_records_still_parse_and_merge_refuses_mix():
    """Monolithic v2 and v1 records keep parsing (migration columns
    absent/NaN — records are byte-identical to pre-disagg), and a
    disaggregated record never merges with a monolithic one: the
    ``disaggregated`` global is run IDENTITY, not volatile."""
    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe)
    mono = load_records(DATA / "record_serving.jsonl")
    df = records_to_dataframe(mono)
    assert "serving_migration_bytes" not in df.columns
    row = serving_summary(mono).iloc[0]
    assert bool(row["disaggregated"]) is False
    assert math.isnan(row["migration_bytes"])
    v1 = load_records(DATA / "record_v1.jsonl")
    assert "disaggregated" not in records_to_dataframe(v1).columns

    dis = load_records(DATA / "record_disagg.jsonl")[0]
    a = copy.deepcopy(dis)
    b = copy.deepcopy(dis)
    a["global"]["num_processes"] = b["global"]["num_processes"] = 2
    b["process"] = 1
    del b["global"]["disaggregated"]    # "the other arm was monolithic"
    with pytest.raises(ValueError, match="disaggregated"):
        merge_records([a, b])


def test_prefill_stall_blame_from_fixture():
    """analysis.critical_path.prefill_stall_blame prices the exposed
    (non-overlapped) migration time against the decode device wall from
    the committed fixture; a monolithic record yields None."""
    from dlnetbench_tpu.analysis.critical_path import prefill_stall_blame
    from dlnetbench_tpu.metrics.parser import load_records
    rec = load_records(DATA / "record_disagg.jsonl")[0]
    blame = prefill_stall_blame(rec)
    assert blame is not None
    mig = rec["global"]["serving"]["migration"]
    assert blame["migration_ms_total"] == mig["ms"]["total"]
    if math.isnan(mig.get("overlap", float("nan"))):
        assert math.isnan(blame["exposed_ms"])
    else:
        assert 0.0 <= blame["exposed_ms"] <= mig["ms"]["total"]
    mono = load_records(DATA / "record_serving.jsonl")[0]
    assert prefill_stall_blame(mono) is None
