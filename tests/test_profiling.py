"""Profiler-trace collective extraction (metrics/profiling.py)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from dlnetbench_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dlnetbench_tpu.metrics import profiling as prof


def test_classify_op():
    assert prof.classify_op("all-reduce.3") == "allreduce"
    assert prof.classify_op("psum.7") == "allreduce"
    assert prof.classify_op("reduce-scatter.2") == "reduce_scatter"
    assert prof.classify_op("psum-scatter.1") == "reduce_scatter"
    assert prof.classify_op("all-gather.5") == "allgather"
    assert prof.classify_op("all-to-all") == "alltoall"
    assert prof.classify_op("collective-permute.9") == "permute"
    assert prof.classify_op("fusion.12") is None
    assert prof.classify_op("end: psum.7") is None   # completion marker


def test_collective_stats_aggregation():
    events = [
        {"ph": "X", "name": "psum.7", "dur": 10.0},
        {"ph": "X", "name": "psum.7", "dur": 30.0},
        {"ph": "X", "name": "all-gather.1", "dur": 5.0},
        {"ph": "X", "name": "broadcast_multiply_fusion", "dur": 99.0},
    ]
    stats = prof.collective_stats(events)
    assert stats["allreduce"] == {"count": 2, "total_us": 40.0,
                                  "mean_us": 20.0, "max_us": 30.0}
    assert stats["allgather"]["count"] == 1
    assert "fusion" not in str(stats)


def test_missing_trace_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        prof.load_trace_events(tmp_path)


@pytest.mark.slow
def test_profile_real_schedule(eight_devices, tmp_path):
    """Trace a real shard_map program on the CPU mesh: the psum and the
    ppermute must both surface with nonzero device time."""
    mesh = Mesh(jax.devices()[:4], ("x",))

    def step(a):
        b = lax.ppermute(a, "x", [(i, (i + 1) % 4) for i in range(4)])
        return lax.psum(a * b, "x")

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("x"),
                           out_specs=P(), check_vma=False))
    x = jnp.arange(16.0)
    jax.block_until_ready(fn(x))   # compile outside the trace
    stats = prof.profile_collectives(fn, x, trace_dir=tmp_path)
    assert stats["allreduce"]["count"] >= 1
    assert stats["permute"]["count"] >= 1
    assert stats["allreduce"]["total_us"] > 0


@pytest.mark.slow
def test_cli_profile_flag(eight_devices, tmp_path, capsys):
    from dlnetbench_tpu.cli import main
    import json
    out = tmp_path / "rec.jsonl"
    rc = main(["dp", "--model", "gpt2_l_16_bfloat16", "--num_buckets", "2",
               "--platform", "cpu", "-r", "1", "-w", "1",
               "--size_scale", "1e-5", "--time_scale", "1e-4",
               "--no_topology", "--profile", "--out", str(out)])
    assert rc == 0
    rec = json.loads(out.read_text().strip())
    assert rec["global"]["profile"]["allreduce"]["count"] >= 1
