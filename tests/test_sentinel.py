"""Bench regression sentinel (dlnetbench_tpu/sentinel.py + bench.py
--check): stat-band-aware artifact comparison — a regression needs BOTH
a median shift past the threshold AND disjoint bands, the attribution
delta names the resource that moved, and the exit code carries the
verdict to CI.

The integration lane (``-m sentinel``, mirrored by ``make check-bench``)
runs the REAL bench.py pipeline on a tiny CPU config: baseline capture,
a clean re-run that must stay quiet, and a deterministically injected
+10% slowdown (the faults delay injector) that must trip.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from dlnetbench_tpu import sentinel

REPO = Path(__file__).parent.parent


def _line(value, band=None, **extra):
    d = {"metric": "m", "unit": "ms", "value": value}
    if band is not None:
        d["band"] = band
    d.update(extra)
    return d


# ---------------------------------------------------------------------
# bench_lines: headline + aux extraction from every artifact shape


def test_bench_lines_driver_artifact(tmp_path):
    aux = _line(2.0, [1.9, 2.1])
    head = _line(10.0, [9.8, 10.2], fp8_mlp=aux, other="not a line")
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({"parsed": head, "tail": ""}))
    lines = sentinel.bench_lines(p)
    assert lines["headline"]["value"] == 10.0
    assert lines["fp8_mlp"]["value"] == 2.0
    assert set(lines) == {"headline", "fp8_mlp"}


def test_bench_lines_tail_fallback_and_jsonl(tmp_path):
    # driver artifact whose parsed is null (failed parse): last ms line
    # of the tail wins
    p = tmp_path / "a.json"
    tail = "\n".join(["noise", json.dumps(_line(1.0)),
                      json.dumps(_line(5.0))])
    p.write_text(json.dumps({"parsed": None, "tail": tail}))
    assert sentinel.bench_lines(p)["headline"]["value"] == 5.0
    # bench stdout JSONL: last ms line is the headline
    q = tmp_path / "b.jsonl"
    q.write_text("warmup noise\n" + json.dumps(_line(3.0)) + "\n"
                 + json.dumps(_line(7.0)) + "\n")
    assert sentinel.bench_lines(q)["headline"]["value"] == 7.0


def test_bench_lines_empty_artifact(tmp_path):
    p = tmp_path / "dead.json"
    p.write_text(json.dumps({"parsed": None, "tail": "rc=1 boom"}))
    assert sentinel.bench_lines(p) == {}


# ---------------------------------------------------------------------
# compare_line: the two-signal regression definition


def test_regression_needs_shift_and_disjoint_bands():
    base = _line(10.0, [9.9, 10.1])
    # +20% with disjoint bands: regression
    r = sentinel.compare_line("headline", base, _line(12.0, [11.9, 12.1]))
    assert r["regression"] and not r["improvement"]
    assert r["bands_overlap"] is False
    # +20% but bands OVERLAP: run-to-run noise, not a regression
    r = sentinel.compare_line("headline", base, _line(12.0, [10.0, 12.5]))
    assert not r["regression"]
    assert r["bands_overlap"] is True
    # disjoint bands but under the threshold: too small to fail a build
    r = sentinel.compare_line("headline", base, _line(10.3, [10.25, 10.35]))
    assert not r["regression"]
    # -20% disjoint: improvement, never a failure
    r = sentinel.compare_line("headline", base, _line(8.0, [7.9, 8.1]))
    assert r["improvement"] and not r["regression"]


def test_bandless_lines_fall_back_to_threshold():
    r = sentinel.compare_line("headline", _line(10.0), _line(12.0))
    assert r["bands_overlap"] is None
    assert r["regression"]
    assert not sentinel.compare_line("headline", _line(10.0),
                                     _line(10.2))["regression"]


def test_compare_line_threshold_configurable():
    base = _line(10.0, [9.9, 10.1])
    cur = _line(10.8, [10.7, 10.9])   # +8%, disjoint
    assert sentinel.compare_line("h", base, cur, 5.0)["regression"]
    assert not sentinel.compare_line("h", base, cur, 10.0)["regression"]


def test_resource_moved_names_the_mover():
    """The attribution delta: per-resource wall-clock differenced, the
    largest increase named — 'comm grew 3 ms', not just 'slower'."""
    def attributed(value, fractions):
        return _line(value, [value - 0.1, value + 0.1],
                     attribution={"fractions": fractions, "bound": "mxu"})
    base = attributed(10.0, {"compute": 0.8, "hbm": 0.0,
                             "comm_exposed": 0.1, "host": 0.1})
    cur = attributed(13.0, {"compute": 0.62, "hbm": 0.0,
                            "comm_exposed": 0.3, "host": 0.08})
    r = sentinel.compare_line("headline", base, cur)
    assert r["regression"]
    assert r["resource_moved"] == "comm_exposed"
    # 0.3*13 - 0.1*10 = 2.9 ms of new exposed comm
    assert r["resource_delta_ms"] == pytest.approx(2.9, abs=0.01)


# ---------------------------------------------------------------------
# check / scan_dir


def test_check_verdicts():
    base = {"headline": _line(10.0, [9.9, 10.1]),
            "fp8": _line(2.0, [1.9, 2.1])}
    clean = sentinel.check(base, {"headline": _line(10.05, [9.95, 10.15]),
                                  "fp8": _line(2.0, [1.9, 2.1])})
    assert clean["verdict"] == "clean" and clean["regressions"] == []
    bad = sentinel.check(base, {"headline": _line(10.0, [9.9, 10.1]),
                                "fp8": _line(3.0, [2.9, 3.1])})
    assert bad["verdict"] == "regression"
    assert bad["regressions"] == ["fp8"]
    # baseline without a headline: nothing to regress against
    none = sentinel.check({}, {"headline": _line(1.0)})
    assert none["verdict"] == "no-baseline"


def test_check_surfaces_vanished_baseline_lines():
    # a baseline aux line absent from the current run is reported in
    # `missing` (not silently dropped), but does not fail the check —
    # --skip-aux / off-TPU runs legitimately drop aux lines
    base = {"headline": _line(10.0, [9.9, 10.1]),
            "fp8": _line(2.0, [1.9, 2.1])}
    sent = sentinel.check(base, {"headline": _line(10.0, [9.9, 10.1])})
    assert sent["missing"] == ["fp8"]
    assert sent["verdict"] == "clean"
    full = sentinel.check(base, {"headline": _line(10.0, [9.9, 10.1]),
                                 "fp8": _line(2.0, [1.9, 2.1])})
    assert full["missing"] == []


@pytest.mark.sentinel
def test_serving_latency_line_is_comparable():
    """The serving_decode aux line (ISSUE 8) rides the headline like
    every ms line, and the sentinel judges it with the same
    lower-is-better, band-aware semantics: a p99 median that worsens
    past threshold with disjoint bands is a regression; a
    band-overlapping shift is noise."""
    def serving_line(value, band):
        return {"metric": "serving_decode: paged-KV decode e2e p99",
                "value": value, "unit": "ms", "best": band[0],
                "band": band, "n": 3,
                "ttft_p50_ms": {"value": 2.0, "best": 1.9,
                                "band": [1.9, 2.1], "n": 3}}

    base = {"headline": _line(10.0, [9.9, 10.1]),
            "serving_decode": serving_line(20.0, [19.5, 20.5])}
    # engine p99 doubles with disjoint bands while the headline holds:
    # the serving line alone must trip the verdict
    cur = {"headline": _line(10.0, [9.9, 10.1]),
           "serving_decode": serving_line(40.0, [39.0, 41.0])}
    sent = sentinel.check(base, cur)
    assert sent["verdict"] == "regression"
    assert sent["regressions"] == ["serving_decode"]
    # band-overlapping latency wobble is noise, not a regression
    ok = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "serving_decode": serving_line(22.0, [19.0, 24.0])})
    assert ok["verdict"] == "clean"
    # faster p99 with disjoint bands reads as an improvement
    fast = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "serving_decode": serving_line(12.0, [11.5, 12.5])})
    assert fast["improvements"] == ["serving_decode"]


@pytest.mark.sentinel
def test_decode_ab_line_is_comparable():
    """The ISSUE 11 A/B extensions (multi_step/speculative sub-blocks,
    attribution_flip, token_parity) ride INSIDE the serving_decode ms
    line: the sentinel still compares it by its headline e2e p99 with
    the same lower-is-better band-aware semantics, and the nested A/B
    blocks never confuse the comparison."""
    def ab_line(value, band):
        return {"metric": "serving_decode: ... vs fused N=16 vs "
                          "N=16+spec, cpu",
                "value": value, "unit": "ms", "best": band[0],
                "band": band, "n": 3,
                "multi_step": {"tokens_per_s": {"value": 8000.0},
                               "multi_step_n": 16},
                "speculative": {"tokens_per_s": {"value": 9000.0}},
                "attribution_flip": {"band_disjoint_drop": True},
                "token_parity": True}

    base = {"headline": _line(10.0, [9.9, 10.1]),
            "serving_decode": ab_line(20.0, [19.5, 20.5])}
    worse = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "serving_decode": ab_line(40.0, [39.0, 41.0])})
    assert worse["verdict"] == "regression"
    assert worse["regressions"] == ["serving_decode"]
    noise = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "serving_decode": ab_line(22.0, [19.0, 24.0])})
    assert noise["verdict"] == "clean"
    # an OLD baseline without the A/B blocks still compares: the
    # extensions are additive, the ms-line contract is the interface
    old = {"headline": _line(10.0, [9.9, 10.1]),
           "serving_decode": {"metric": "serving_decode: paged-KV",
                              "value": 20.0, "unit": "ms",
                              "best": 19.5, "band": [19.5, 20.5],
                              "n": 3}}
    sent = sentinel.check(old, {
        "headline": _line(10.0, [9.9, 10.1]),
        "serving_decode": ab_line(41.0, [40.0, 42.0])})
    assert sent["verdict"] == "regression"


def _artifact(path, value, band):
    head = _line(value, band)
    path.write_text(json.dumps({"parsed": head, "tail": ""}))


def test_scan_dir_skips_dead_artifacts_and_flags_latest(tmp_path, capsys):
    _artifact(tmp_path / "BENCH_r01.json", 10.0, [9.9, 10.1])
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"parsed": None, "tail": "rc=1"}))  # failed capture
    _artifact(tmp_path / "BENCH_r03.json", 12.0, [11.9, 12.1])
    rc = sentinel.scan_dir(tmp_path)
    out = capsys.readouterr().out
    # r02 skipped with a note; r03 compared against r01, not blinded
    assert "BENCH_r02.json — no comparable headline" in out
    assert "baseline " + str(tmp_path / "BENCH_r01.json") in out
    assert rc == sentinel.RC_REGRESSION


def test_scan_dir_dead_latest_artifact_disarms_loudly(tmp_path, capsys):
    """A dead LATEST capture must not ride an older clean verdict to
    rc 0: the newest round is the one CI asked about, and a tripwire
    that silently disarms is worse than no tripwire."""
    _artifact(tmp_path / "BENCH_r01.json", 10.0, [9.9, 10.1])
    _artifact(tmp_path / "BENCH_r02.json", 10.05, [9.95, 10.15])
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"parsed": None, "tail": "rc=1"}))  # bench.py died
    rc = sentinel.scan_dir(tmp_path)
    out = capsys.readouterr().out
    assert rc == 2
    assert "LATEST artifact has no comparable headline" in out


def test_scan_dir_clean_and_underpopulated(tmp_path, capsys):
    assert sentinel.scan_dir(tmp_path) == 2    # nothing to compare
    _artifact(tmp_path / "BENCH_r01.json", 10.0, [9.9, 10.1])
    _artifact(tmp_path / "BENCH_r02.json", 10.1, [9.95, 10.2])
    assert sentinel.scan_dir(tmp_path) == 0
    capsys.readouterr()


def test_main_baseline_pair(tmp_path, capsys):
    _artifact(tmp_path / "a.json", 10.0, [9.9, 10.1])
    _artifact(tmp_path / "b.json", 14.0, [13.9, 14.1])
    rc = sentinel.main([str(tmp_path / "b.json"),
                        "--baseline", str(tmp_path / "a.json")])
    assert rc == sentinel.RC_REGRESSION
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    # the machine-readable sentinel section rides stdout too
    sent = json.loads(out.strip().splitlines()[-1])["sentinel"]
    assert sent["verdict"] == "regression"
    assert sentinel.main([str(tmp_path / "a.json"),
                          "--baseline", str(tmp_path / "a.json")]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------
# the integration lane: REAL bench.py runs on a tiny CPU config
# (mirrored by `make check-bench`)

TINY_ENV = {
    "JAX_PLATFORMS": "cpu",
    "DLNB_BENCH_BATCH": "2", "DLNB_BENCH_SEQ": "256",
    "DLNB_BENCH_LAYERS": "1", "DLNB_BENCH_VOCAB": "512",
    "DLNB_BENCH_EMBED": "256", "DLNB_BENCH_FF": "1024",
    "DLNB_BENCH_HEADS": "4",
    # K=8 chained steps per fence: amortizes dispatch jitter so the
    # 3-round band is tight enough for a 10% shift to land outside it
    "DLNB_BENCH_K": "8",
}


def _run_bench(tmp_path, out_name, *extra, cache_dir=None):
    env = {**os.environ, **TINY_ENV}
    if cache_dir:
        env["DLNB_COMPILE_CACHE_DIR"] = str(cache_dir)
    out = tmp_path / out_name
    with open(out, "w") as f:
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--skip-aux", *extra],
            stdout=f, stderr=subprocess.PIPE, env=env, cwd=REPO,
            timeout=600, text=True)
    return proc, out


def _headline(path):
    lines = [json.loads(ln) for ln in path.read_text().splitlines()
             if ln.strip().startswith("{")]
    return lines[-1]


@pytest.mark.slow
@pytest.mark.sentinel
def test_bench_check_lane(tmp_path):
    """The CI tripwire, end to end: a clean re-run stays quiet (exit 0,
    verdict in the artifact), an injected +10% slowdown exits non-zero
    and names the regression."""
    cache = tmp_path / "cache"

    # 1. baseline capture
    proc, base = _run_bench(tmp_path, "baseline.jsonl", cache_dir=cache)
    assert proc.returncode == 0, proc.stderr
    base_head = _headline(base)
    assert "attribution" in base_head, "headline must carry a block"

    # 2. clean re-run under --check: must stay quiet.  CPU wall-clock
    # on a shared box can genuinely drift between invocations — that is
    # exactly the shift the bands exist to absorb, but a scheduler
    # outlier round can defeat them; one bounded retry with a fresh
    # baseline keeps the lane honest without making it flaky.
    for attempt in range(2):
        proc, clean = _run_bench(tmp_path, "clean.jsonl",
                                 "--check", str(base), cache_dir=cache)
        if proc.returncode == 0 or attempt == 1:
            break
        proc2, base = _run_bench(tmp_path, "baseline.jsonl",
                                 cache_dir=cache)
        assert proc2.returncode == 0, proc2.stderr
    assert proc.returncode == 0, (proc.stderr, _headline(clean))
    sent = _headline(clean)["sentinel"]
    assert sent["verdict"] in ("clean", "no-baseline")
    assert sent["verdict"] == "clean", sent   # headline was comparable
    assert sent["baseline"] == str(base)

    # 3. deterministically injected headline slowdown: the faults delay
    # injector sleeps inside the timed window, once per chained step.
    # The injection floor is +10% of the baseline median (the acceptance
    # contract); on a noisy box the baseline's own band width is added
    # so the faulted band lands OUTSIDE it — the band veto exists to
    # absorb exactly that noise, and an injection the bands could
    # swallow would be testing the scheduler, not the sentinel.
    for attempt in range(2):
        bh = _headline(base)
        base_ms = float(bh["value"])
        band = bh.get("band") or [base_ms, base_ms]
        width_ms = float(band[1]) - float(band[0])
        delay_ms = (0.10 * base_ms + width_ms if attempt == 0
                    else 0.25 * base_ms + 2 * width_ms)
        plan = json.dumps({"policy": "fail_fast", "events": [
            {"kind": "delay", "iteration": 0,
             "magnitude_us": round(delay_ms * 1e3)}]})
        proc, faulted = _run_bench(tmp_path, "faulted.jsonl",
                                   "--check", str(base), "--fault", plan,
                                   cache_dir=cache)
        if proc.returncode == sentinel.RC_REGRESSION:
            break
        if attempt == 0:
            # a baseline captured on a transiently loaded box can sit
            # so far ABOVE the settled step time that even the bigger
            # injection can't reach it — refresh the baseline (and the
            # delay derived from it) before the second attempt
            proc2, base = _run_bench(tmp_path, "baseline.jsonl",
                                     cache_dir=cache)
            assert proc2.returncode == 0, proc2.stderr
    assert proc.returncode == sentinel.RC_REGRESSION, (
        proc.returncode, proc.stderr, _headline(faulted))
    head = _headline(faulted)
    assert head["sentinel"]["verdict"] == "regression"
    assert "headline" in head["sentinel"]["regressions"]
    # the faulted artifact can never pass as a clean measurement
    assert head["fault_plan"]["events"][0]["kind"] == "delay"
    assert head["attribution"]["bound"] == "faulted"
    assert float(head["value"]) > base_ms


@pytest.mark.sentinel
def test_tuned_ab_line_is_comparable():
    """The tuned_ab aux line (ISSUE 9) rides the headline like every ms
    line and the sentinel judges it band-aware lower-is-better: a tuned
    chain that got slower past threshold with disjoint bands is a
    regression; band-overlapping wobble is noise."""
    def tuned_line(value, band):
        return {"metric": "tuned A/B: fp8 fused swiglu, DB-tuned vs "
                          "frozen", "value": value, "unit": "ms",
                "best": band[0], "band": band, "n": 3,
                "frozen_ms": {"value": 2 * value, "best": 2 * band[0],
                              "band": [2 * b for b in band], "n": 3}}

    assert sentinel.is_ms_line(tuned_line(10.0, [9.5, 10.5]))
    base = {"headline": _line(10.0, [9.9, 10.1]),
            "tuned_ab": tuned_line(10.0, [9.5, 10.5])}
    cur = {"headline": _line(10.0, [9.9, 10.1]),
           "tuned_ab": tuned_line(20.0, [19.5, 20.5])}
    sent = sentinel.check(base, cur)
    assert sent["verdict"] == "regression"
    assert sent["regressions"] == ["tuned_ab"]
    ok = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "tuned_ab": tuned_line(10.3, [9.8, 10.8])})
    assert ok["verdict"] == "clean"


def test_longcontext_line_is_comparable():
    """The longcontext_ab aux line (ISSUE 10) rides the headline like
    every ms line and the sentinel judges it band-aware
    lower-is-better: a splash chain that got slower past threshold
    with disjoint bands is a regression; band-overlapping wobble is
    noise."""
    def lc_line(value, band):
        return {"metric": "longcontext A/B: dense vs splash",
                "value": value, "unit": "ms",
                "best": band[0], "band": band, "n": 3,
                "dense": {"value": 4 * value, "best": 4 * band[0],
                          "band": [4 * b for b in band], "n": 3},
                "masks": {"splash_window": {
                    "attention_mask": "causal&window(4096)",
                    "mask_sparsity": 0.94}}}

    assert sentinel.is_ms_line(lc_line(10.0, [9.5, 10.5]))
    base = {"headline": _line(10.0, [9.9, 10.1]),
            "longcontext_ab": lc_line(10.0, [9.5, 10.5])}
    cur = {"headline": _line(10.0, [9.9, 10.1]),
           "longcontext_ab": lc_line(20.0, [19.5, 20.5])}
    sent = sentinel.check(base, cur)
    assert sent["verdict"] == "regression"
    assert sent["regressions"] == ["longcontext_ab"]
    ok = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "longcontext_ab": lc_line(10.3, [9.8, 10.8])})
    assert ok["verdict"] == "clean"


@pytest.mark.sentinel
def test_kv_density_line_is_comparable():
    """The kv_density_ab aux line (ISSUE 12) rides the headline like
    every ms line: the sentinel compares it by the dense engine's e2e
    p99, band-aware lower-is-better, and the nested per-variant
    capacity/parity blocks never confuse the comparison."""
    def density_line(value, band):
        return {"metric": "kv_density_ab: dense vs int8 vs fp8",
                "value": value, "unit": "ms", "best": band[0],
                "band": band, "n": 3,
                "variants": {"int8": {
                    "capacity_x": {"value": 2.9, "band": [2.8, 3.0]},
                    "parity_ok": True}}}

    base = {"headline": _line(10.0, [9.9, 10.1]),
            "kv_density_ab": density_line(90.0, [88.0, 92.0])}
    cur = {"headline": _line(10.0, [9.9, 10.1]),
           "kv_density_ab": density_line(180.0, [176.0, 184.0])}
    sent = sentinel.check(base, cur)
    assert sent["verdict"] == "regression"
    assert sent["regressions"] == ["kv_density_ab"]
    ok = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "kv_density_ab": density_line(93.0, [87.0, 96.0])})
    assert ok["verdict"] == "clean"


def test_moe_ab_line_is_comparable():
    """The moe_ab aux line (ISSUE 15) rides the headline like every ms
    line and the sentinel judges it band-aware lower-is-better: a MoE
    step that got slower past threshold with disjoint bands is a
    regression; band-overlapping wobble is noise; old baselines
    without the line still compare clean."""
    def moe_line(value, band):
        return {"metric": "moe A/B: dense FFN vs 8-expert MoE",
                "value": value, "unit": "ms", "best": band[0],
                "band": band, "n": 3,
                "dense_ms": {"value": value / 1.5,
                             "best": band[0] / 1.5,
                             "band": [b / 1.5 for b in band], "n": 3}}

    assert sentinel.is_ms_line(moe_line(15.0, [14.0, 16.0]))
    base = {"headline": _line(10.0, [9.9, 10.1]),
            "moe_ab": moe_line(15.0, [14.0, 16.0])}
    cur = {"headline": _line(10.0, [9.9, 10.1]),
           "moe_ab": moe_line(30.0, [29.0, 31.0])}
    sent = sentinel.check(base, cur)
    assert sent["verdict"] == "regression"
    assert sent["regressions"] == ["moe_ab"]
    ok = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "moe_ab": moe_line(15.5, [14.4, 16.5])})
    assert ok["verdict"] == "clean"
    # a baseline predating the line compares clean (new line ignored)
    old = sentinel.check({"headline": _line(10.0, [9.9, 10.1])},
                         cur)
    assert old["verdict"] == "clean"


@pytest.mark.sentinel
def test_fleet_ab_line_is_comparable():
    """The fleet_ab aux line (ISSUE 18) rides the headline like every
    ms line: the sentinel compares it by the prefix_affinity arm's
    TTFT p50, band-aware lower-is-better, and the nested per-policy
    bands never confuse the comparison."""
    def fleet_line(value, band):
        return {"metric": "fleet_ab: round_robin vs p2c vs "
                          "prefix_affinity routing at equal chips",
                "value": value, "unit": "ms", "best": band[0],
                "band": band, "n": 3,
                "round_robin": {"ttft_p50_ms": {
                    "value": value * 1.5, "best": band[0] * 1.5,
                    "band": [b * 1.5 for b in band], "n": 3}},
                "ttft_band_disjoint_drop": True}

    assert sentinel.is_ms_line(fleet_line(5.0, [4.5, 5.5]))
    base = {"headline": _line(10.0, [9.9, 10.1]),
            "fleet_ab": fleet_line(5.0, [4.5, 5.5])}
    cur = {"headline": _line(10.0, [9.9, 10.1]),
           "fleet_ab": fleet_line(10.0, [9.5, 10.5])}
    sent = sentinel.check(base, cur)
    assert sent["verdict"] == "regression"
    assert sent["regressions"] == ["fleet_ab"]
    ok = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "fleet_ab": fleet_line(5.2, [4.6, 5.6])})
    assert ok["verdict"] == "clean"
    # a baseline predating the line compares clean (new line ignored)
    old = sentinel.check({"headline": _line(10.0, [9.9, 10.1])}, cur)
    assert old["verdict"] == "clean"


def test_sampling_ab_line_is_comparable():
    """The sampling_ab aux line (ISSUE 19) rides the headline like
    every ms line: the sentinel compares it by the speculative-sampled
    arm's e2e p99, band-aware lower-is-better, and the nested per-arm
    bands never confuse the comparison."""
    def sampling_line(value, band):
        return {"metric": "sampling_ab: seeded sampling T=0.8 — fused "
                          "decode vs lossless speculative sampling",
                "value": value, "unit": "ms", "best": band[0],
                "band": band, "n": 3,
                "sampled": {"tokens_per_s": {
                    "value": value * 2.0, "best": band[0] * 2.0,
                    "band": [b * 2.0 for b in band], "n": 3}},
                "tokens_per_s_band_disjoint_gain": True}

    assert sentinel.is_ms_line(sampling_line(5.0, [4.5, 5.5]))
    base = {"headline": _line(10.0, [9.9, 10.1]),
            "sampling_ab": sampling_line(5.0, [4.5, 5.5])}
    cur = {"headline": _line(10.0, [9.9, 10.1]),
           "sampling_ab": sampling_line(10.0, [9.5, 10.5])}
    sent = sentinel.check(base, cur)
    assert sent["verdict"] == "regression"
    assert sent["regressions"] == ["sampling_ab"]
    ok = sentinel.check(base, {
        "headline": _line(10.0, [9.9, 10.1]),
        "sampling_ab": sampling_line(5.2, [4.6, 5.6])})
    assert ok["verdict"] == "clean"
    # a baseline predating the line compares clean (new line ignored)
    old = sentinel.check({"headline": _line(10.0, [9.9, 10.1])}, cur)
    assert old["verdict"] == "clean"
