"""Real-compute ring / Ulysses attention vs. full attention (ops/sequence_parallel.py).

Runs on the 8-device virtual CPU mesh (conftest).  Ground truth: the einsum
attention over the gathered sequence, sliced back to each device's shard.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from dlnetbench_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dlnetbench_tpu.models import layers as L
from dlnetbench_tpu.ops.sequence_parallel import (
    ring_attention,
    ulysses_attention,
)

AXIS = "sp"


def _mesh(n):
    return Mesh(jax.devices()[:n], (AXIS,))


def _qkv(key, b, s, hq, hkv, dh):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, s, hq, dh), jnp.float32),
            jax.random.normal(kk, (b, s, hkv, dh), jnp.float32),
            jax.random.normal(kv, (b, s, hkv, dh), jnp.float32))


def _sharded(fn, mesh):
    spec = P(None, AXIS, None, None)
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False))


CASES = [
    # n, b, s, hq, hkv, dh, causal
    (4, 2, 64, 4, 4, 16, True),
    (4, 1, 64, 4, 2, 16, True),    # GQA
    (8, 1, 64, 8, 8, 8, True),
    (4, 2, 64, 4, 4, 16, False),
]


@pytest.mark.parametrize("n,b,s,hq,hkv,dh,causal", CASES)
def test_ring_matches_full(n, b, s, hq, hkv, dh, causal):
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(0), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=causal)
    fn = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                    causal=causal), mesh)
    got = fn(q, k, v)
    assert jnp.max(jnp.abs(got - want)) < 2e-5


@pytest.mark.parametrize("n,b,s,hq,hkv,dh,causal", CASES[:1] + CASES[2:])
def test_ulysses_matches_full(n, b, s, hq, hkv, dh, causal):
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(1), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=causal)
    fn = _sharded(functools.partial(ulysses_attention, axis_name=AXIS,
                                    causal=causal, impl="xla"), mesh)
    got = fn(q, k, v)
    assert jnp.max(jnp.abs(got - want)) < 2e-5


def test_ring_gradients_match_full():
    n, b, s, hq, hkv, dh = 4, 1, 64, 4, 2, 16
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(2), b, s, hq, hkv, dh)
    cot = jax.random.normal(jax.random.key(3), q.shape, q.dtype)

    def ref_loss(q, k, v):
        return jnp.sum(L.attention(q, k, v, causal=True) * cot)

    spec = P(None, AXIS, None, None)

    def ring_loss_local(q, k, v, cot):
        out = ring_attention(q, k, v, axis_name=AXIS, causal=True)
        return lax.psum(jnp.sum(out * cot), AXIS)

    ring_loss = jax.jit(shard_map(
        ring_loss_local, mesh=mesh, in_specs=(spec,) * 4, out_specs=P(),
        check_vma=False))

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda q, k, v: ring_loss(q, k, v, cot),
                      argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_ring):
        assert jnp.max(jnp.abs(a - b_)) < 5e-5


def test_ulysses_gradients_match_full():
    n, b, s, hq, hkv, dh = 4, 1, 64, 4, 4, 16
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(4), b, s, hq, hkv, dh)
    cot = jax.random.normal(jax.random.key(5), q.shape, q.dtype)

    def ref_loss(q, k, v):
        return jnp.sum(L.attention(q, k, v, causal=True) * cot)

    spec = P(None, AXIS, None, None)

    def ul_loss_local(q, k, v, cot):
        out = ulysses_attention(q, k, v, axis_name=AXIS, causal=True,
                                impl="xla")
        return lax.psum(jnp.sum(out * cot), AXIS)

    ul_loss = jax.jit(shard_map(
        ul_loss_local, mesh=mesh, in_specs=(spec,) * 4, out_specs=P(),
        check_vma=False))

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ul = jax.grad(lambda q, k, v: ul_loss(q, k, v, cot),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_ul):
        assert jnp.max(jnp.abs(a - b_)) < 5e-5
