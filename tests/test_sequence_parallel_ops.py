"""Real-compute ring / Ulysses attention vs. full attention (ops/sequence_parallel.py).

Runs on the 8-device virtual CPU mesh (conftest).  Ground truth: the einsum
attention over the gathered sequence, sliced back to each device's shard.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from dlnetbench_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dlnetbench_tpu.models import layers as L
from dlnetbench_tpu.ops.sequence_parallel import (
    ring_attention,
    ulysses_attention,
)

AXIS = "sp"


def _mesh(n):
    return Mesh(jax.devices()[:n], (AXIS,))


def _qkv(key, b, s, hq, hkv, dh):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, s, hq, dh), jnp.float32),
            jax.random.normal(kk, (b, s, hkv, dh), jnp.float32),
            jax.random.normal(kv, (b, s, hkv, dh), jnp.float32))


def _sharded(fn, mesh):
    spec = P(None, AXIS, None, None)
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False))


CASES = [
    # n, b, s, hq, hkv, dh, causal
    (4, 2, 64, 4, 4, 16, True),
    (4, 1, 64, 4, 2, 16, True),    # GQA
    (8, 1, 64, 8, 8, 8, True),
    (4, 2, 64, 4, 4, 16, False),
]


@pytest.mark.parametrize("n,b,s,hq,hkv,dh,causal", CASES)
def test_ring_matches_full(n, b, s, hq, hkv, dh, causal):
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(0), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=causal)
    fn = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                    causal=causal), mesh)
    got = fn(q, k, v)
    assert jnp.max(jnp.abs(got - want)) < 2e-5


@pytest.mark.parametrize("n,b,s,hq,hkv,dh,causal", CASES[:1] + CASES[2:])
def test_ulysses_matches_full(n, b, s, hq, hkv, dh, causal):
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(1), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=causal)
    fn = _sharded(functools.partial(ulysses_attention, axis_name=AXIS,
                                    causal=causal, impl="xla"), mesh)
    got = fn(q, k, v)
    assert jnp.max(jnp.abs(got - want)) < 2e-5


def test_ring_gradients_match_full():
    n, b, s, hq, hkv, dh = 4, 1, 64, 4, 2, 16
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(2), b, s, hq, hkv, dh)
    cot = jax.random.normal(jax.random.key(3), q.shape, q.dtype)

    def ref_loss(q, k, v):
        return jnp.sum(L.attention(q, k, v, causal=True) * cot)

    spec = P(None, AXIS, None, None)

    def ring_loss_local(q, k, v, cot):
        out = ring_attention(q, k, v, axis_name=AXIS, causal=True)
        return lax.psum(jnp.sum(out * cot), AXIS)

    ring_loss = jax.jit(shard_map(
        ring_loss_local, mesh=mesh, in_specs=(spec,) * 4, out_specs=P(),
        check_vma=False))

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda q, k, v: ring_loss(q, k, v, cot),
                      argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_ring):
        assert jnp.max(jnp.abs(a - b_)) < 5e-5


# ----------------------------------------- block-sparse masks (ISSUE 10)

from dlnetbench_tpu.ops import attention_mask as am  # noqa: E402

longcontext = pytest.mark.longcontext

MASK_SPECS = [
    am.MaskSpec(causal=True, window=20),
    am.MaskSpec(causal=True, seg_avg=24, seg_seed=9),
    am.MaskSpec(causal=False, seg_avg=16, seg_seed=2),
    am.MaskSpec(causal=True, window=24, seg_avg=32, seg_seed=4),
]


@longcontext
@pytest.mark.parametrize("spec", MASK_SPECS)
def test_masked_ring_matches_dense_reference(spec):
    """Sparse ring attention (hop-verdict gating + in-hop interval
    masks) vs full attention applying the SAME mask densely on the
    gathered sequence."""
    n, b, s, hq, hkv, dh = 4, 2, 64, 4, 2, 16
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(6), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=spec.causal,
                       dense_mask=jnp.asarray(am.dense_mask(spec, s)))
    fn = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                    causal=spec.causal, spec=spec), mesh)
    got = fn(q, k, v)
    assert jnp.max(jnp.abs(got - want)) < 2e-5
    # the mask must actually skip hops (the point of the gating)
    assert am.ring_skipped_hop_fraction(spec, s, n) > 0


@longcontext
def test_causal_fast_path_gates_future_hops():
    """ISSUE 10 satellite: plain-causal rings now SKIP the compute leg
    of strictly-future hops (they used to run a full _block_scores and
    merge a provably-zero contribution).  The verdict table is the
    causal triangle, and numerics stay identical to the gathered
    reference (the skipped merge was already the exact f32 identity)."""
    import numpy as np
    work = am.ring_hop_work(None, 64, 4)
    me, src = np.indices((4, 4))
    assert (work == (src <= me)).all()
    n, b, s, hq, hkv, dh = 4, 1, 64, 4, 2, 16
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(7), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=True)
    fn = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                    causal=True), mesh)
    assert jnp.max(jnp.abs(fn(q, k, v) - want)) < 2e-5


@longcontext
def test_masked_ring_gradients_match_dense_reference():
    spec = am.MaskSpec(causal=True, window=20)
    n, b, s, hq, hkv, dh = 4, 1, 64, 4, 2, 16
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(8), b, s, hq, hkv, dh)
    cot = jax.random.normal(jax.random.key(9), q.shape, q.dtype)
    dm = jnp.asarray(am.dense_mask(spec, s))

    def ref_loss(q, k, v):
        return jnp.sum(L.attention(q, k, v, causal=True,
                                   dense_mask=dm) * cot)

    sspec = P(None, AXIS, None, None)

    def ring_loss_local(q, k, v, cot):
        out = ring_attention(q, k, v, axis_name=AXIS, causal=True,
                             spec=spec)
        return lax.psum(jnp.sum(out * cot), AXIS)

    ring_loss = jax.jit(shard_map(
        ring_loss_local, mesh=mesh, in_specs=(sspec,) * 4,
        out_specs=P(), check_vma=False))
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda q, k, v: ring_loss(q, k, v, cot),
                      argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_ring):
        assert jnp.max(jnp.abs(a - b_)) < 5e-5


@longcontext
def test_masked_ulysses_matches_dense_reference():
    spec = am.MaskSpec(causal=True, window=20)
    n, b, s, hq, hkv, dh = 4, 2, 64, 4, 4, 16
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(10), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=True,
                       dense_mask=jnp.asarray(am.dense_mask(spec, s)))
    fn = _sharded(functools.partial(ulysses_attention, axis_name=AXIS,
                                    causal=True, impl="xla", spec=spec),
                  mesh)
    assert jnp.max(jnp.abs(fn(q, k, v) - want)) < 2e-5


@longcontext
@pytest.mark.slow
def test_ring_64k_window_locality_and_skip():
    """The S=64k case the machinery was built for (slow lane): a
    sliding-window masked ring over 8 shards at 64k tokens runs, is
    finite, skips >= 70% of the hop grid, and is LOCAL — scrambling
    keys more than a window behind a query must not change its output
    (the dense reference at this length is unbuildable by design, so
    locality is the checkable ground truth)."""
    n, s = 8, 64 * 1024
    s_loc = s // n
    spec = am.MaskSpec(causal=True, window=512)
    assert am.ring_skipped_hop_fraction(spec, s, n) >= 0.7
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(11), 1, s, 1, 1, 8)
    fn = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                    causal=True, spec=spec), mesh)
    out = fn(q, k, v)
    assert bool(jnp.all(jnp.isfinite(out)))
    # scramble shard 0's keys/values: rows whose whole window lies past
    # shard 0 (q >= s_loc + window) must be bit-unchanged
    k2 = k.at[:, :s_loc].set(
        jax.random.normal(jax.random.key(12), (1, s_loc, 1, 8)))
    v2 = v.at[:, :s_loc].set(
        jax.random.normal(jax.random.key(13), (1, s_loc, 1, 8)))
    out2 = fn(k2 * 0 + q, k2, v2)   # same q
    far = s_loc + spec.window
    assert bool(jnp.all(out[:, far:] == out2[:, far:]))
    assert not bool(jnp.all(out[:, :s_loc] == out2[:, :s_loc]))


def test_ulysses_gradients_match_full():
    n, b, s, hq, hkv, dh = 4, 1, 64, 4, 4, 16
    mesh = _mesh(n)
    q, k, v = _qkv(jax.random.key(4), b, s, hq, hkv, dh)
    cot = jax.random.normal(jax.random.key(5), q.shape, q.dtype)

    def ref_loss(q, k, v):
        return jnp.sum(L.attention(q, k, v, causal=True) * cot)

    spec = P(None, AXIS, None, None)

    def ul_loss_local(q, k, v, cot):
        out = ulysses_attention(q, k, v, axis_name=AXIS, causal=True,
                                impl="xla")
        return lax.psum(jnp.sum(out * cot), AXIS)

    ul_loss = jax.jit(shard_map(
        ul_loss_local, mesh=mesh, in_specs=(spec,) * 4, out_specs=P(),
        check_vma=False))

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ul = jax.grad(lambda q, k, v: ul_loss(q, k, v, cot),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_ul):
        assert jnp.max(jnp.abs(a - b_)) < 5e-5
