"""Checkpoint-interval planning (analysis/goodput.py): the Daly
approximation, the exponential efficiency model, the cost fit over
sweep records, and the acceptance verdict against the committed
elastic-study artifact."""
from __future__ import annotations

import math
from pathlib import Path

import pytest

from dlnetbench_tpu.analysis import goodput as gp


# ------------------------------------------------------------ the model
def test_daly_matches_young_at_small_overhead():
    """For d << M the higher-order terms vanish: Daly converges to
    Young's sqrt(2dM)."""
    d, M = 0.001, 1000.0
    assert gp.daly_interval_s(d, M) == pytest.approx(
        math.sqrt(2 * d * M), rel=1e-2)


def test_daly_degenerate_inputs():
    # free saves: eff(tau) is strictly decreasing in tau when d = 0 —
    # saving constantly loses nothing, so the optimum is "save always"
    # (NOT inf: a zero-cost corner of the prediction band must not
    # widen the band to the sparse edge and accept a wrong optimum)
    assert gp.daly_interval_s(0.0, 100.0) == 0.0
    assert gp.daly_interval_s(1.0, 0.0) == 0.0         # constant failure
    # d >= 2M: the approximation's validity edge — checkpoint once per
    # MTBF, never longer
    assert gp.daly_interval_s(10.0, 1.0) == 1.0
    # "save never" emerges only from no failures (M -> inf)
    assert gp.daly_interval_s(1.0, math.inf) == math.inf


def test_daly_is_the_efficiency_argmax():
    """The approximation must sit at (near) the exact exponential
    model's argmax — that is its whole claim."""
    d, M, R = 0.05, 10.0, 0.5
    tau_opt = gp.daly_interval_s(d, M)
    e_opt = gp.efficiency(tau_opt, d, M, R)
    for factor in (0.5, 0.8, 1.25, 2.0):
        assert gp.efficiency(tau_opt * factor, d, M, R) <= e_opt + 1e-9


def test_efficiency_monotone_in_costs():
    assert gp.efficiency(1.0, 0.1, 10.0) > gp.efficiency(1.0, 0.5, 10.0)
    assert gp.efficiency(1.0, 0.1, 10.0) > gp.efficiency(1.0, 0.1, 5.0)
    assert gp.efficiency(1.0, 0.1, 10.0, 0.0) > \
        gp.efficiency(1.0, 0.1, 10.0, 2.0)
    assert gp.efficiency(0.0, 0.1, 10.0) == 0.0


# ------------------------------------------------------- record fitting
def _sweep_record(every: int, *, goodput: float, stall_ms: float = 10.0,
                  preempt_at: int = 8, step_us: float = 20000.0) -> dict:
    return {
        "section": "dp", "version": 2, "process": 0,
        "global": {"proxy": "dp", "world_size": 8,
                   "checkpoint_every": every,
                   "checkpoint_stall_ms": stall_ms,
                   "checkpoint_ms": stall_ms,
                   "restore_ms": 5.0, "detection_ms": 1.0,
                   "recovery_ms": 100.0, "lost_steps": every // 2,
                   "goodput": goodput, "fault_iteration": preempt_at},
        "mesh": {"platform": "cpu"},
        "num_runs": 8,
        "warmup_times": [1.0],
        "ranks": [{"rank": 0, "device_id": 0, "process_index": 0,
                   "hostname": "h", "runtimes": [step_us] * 8}],
    }


def _sweep(goodputs: dict[int, list[float]], **kw) -> list[dict]:
    return [_sweep_record(e, goodput=v, **kw)
            for e, vals in goodputs.items() for v in vals]


def test_fit_costs_reads_the_measured_fields():
    recs = _sweep({1: [4.0, 4.2], 8: [6.0, 6.1]})
    m = gp.fit_costs(recs)
    # step time from the SPARSEST records' pooled median (20 ms here)
    assert m.step_s == pytest.approx(0.02)
    assert m.ckpt_s == pytest.approx(0.010)
    assert m.restart_s == pytest.approx(0.106)
    # MTBF: preempt trigger 8 x 20 ms = 160 ms per draw
    assert m.mtbf_s == pytest.approx(0.16)
    assert m.n_records == 4


def test_fit_costs_refuses_unswept_records():
    with pytest.raises(ValueError, match="goodput"):
        gp.fit_costs([{"global": {}, "ranks": []}])


def test_validate_sweep_in_band_and_outside():
    """A sweep whose measured optimum matches the model's band passes;
    moving the measured peak far outside fails — the verdict is a real
    tripwire, not a formality."""
    # d=10 ms, M ~ 160 ms -> tau_opt ~ sqrt(2*.01*.16) ~ 56.6 ms ~ 2.8
    # steps at 20 ms/step: the band straddles {2, 4}
    good = _sweep({1: [4.0, 4.1], 2: [7.0, 7.1],
                   4: [6.9, 7.05], 8: [5.0, 5.1]})
    v = gp.validate_sweep(good)
    assert v["measured_opt_every"] == 2
    assert 4 in v["candidate_optima"]  # overlapping band
    assert v["in_band"] is True
    assert set(v["predicted_rel"]) == {1, 2, 4, 8}
    assert max(v["predicted_rel"].values()) == 1.0

    # same costs, but the measured curve peaks hard at every=1 with
    # bands DISJOINT from everything the model predicts
    bad = _sweep({1: [20.0, 20.1], 2: [7.0, 7.1],
                  4: [6.0, 6.1], 8: [5.0, 5.1]})
    v2 = gp.validate_sweep(bad)
    assert v2["measured_opt_every"] == 1
    assert v2["candidate_optima"] == [1]
    assert v2["in_band"] is False


def test_band_snap_widens_to_grid_resolution():
    assert gp._snap_band_to_grid((2.5, 3.5), [1, 2, 4, 8]) == (2, 4)
    assert gp._snap_band_to_grid((0.2, 0.4), [1, 2, 4, 8]) == (1, 1)
    assert gp._snap_band_to_grid((9.0, 20.0), [1, 2, 4, 8]) == (8, 8)
    assert gp._snap_band_to_grid((1.0, 8.0), [1, 2, 4, 8]) == (1, 8)


# -------------------------------------------- the committed artifact
STUDY = Path(__file__).resolve().parent.parent / "docs" / "studies" / \
    "elastic_study_r10" / "records.jsonl"


def test_committed_elastic_study_verdict_holds():
    """The acceptance criterion, re-derived from the committed artifact
    on every test run: the measured goodput-vs-interval optimum falls
    inside the Daly prediction band, and every sweep record carries
    the four elastic fields."""
    from dlnetbench_tpu.metrics.parser import load_records

    recs = load_records(STUDY)
    sweep = [r for r in recs
             if r["global"].get("checkpoint_every") is not None]
    assert len(sweep) == 12  # 4 intervals x 3 seeds
    for r in sweep:
        g = r["global"]
        for field in ("checkpoint_ms", "restore_ms", "lost_steps",
                      "goodput"):
            assert isinstance(g.get(field), (int, float)), field
        assert "degraded_world" not in g  # every run rejoined
        assert g["fault_rejoin_step"] > g["fault_iteration"]
    v = gp.validate_sweep(recs)
    assert v["in_band"] is True
    assert v["model"]["n_records"] == 12

    # the native preempt+rejoin point also ended full-world
    native = [r for r in recs
              if r["global"].get("fault_rejoin_step") is not None
              and r["global"].get("checkpoint_every") is None]
    assert len(native) == 1
    assert [row["rank"] for row in native[0]["ranks"]] == [0, 1, 2]
    assert native[0]["global"]["rejoin_ms"] > 0


def test_report_cli_renders_and_exits_by_verdict(tmp_path, capsys):
    assert gp.main(["report", str(STUDY)]) == 0
    out = capsys.readouterr().out
    assert "Daly optimum" in out and "INSIDE" in out
    # no sweep records -> exit 2, not a stack trace
    empty = tmp_path / "none.jsonl"
    empty.write_text('{"section": "dp", "version": 2, "process": 0, '
                     '"global": {}, "mesh": {}, "num_runs": 1, '
                     '"warmup_times": [], "ranks": []}\n')
    assert gp.main(["report", str(empty)]) == 2
