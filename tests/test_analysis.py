"""Analysis layer: byte formatting, style maps, Pareto math, plot smoke
tests (headless Agg backend)."""
from __future__ import annotations

import json

import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt
import pytest

from dlnetbench_tpu.analysis import (
    format_bytes,
    get_metrics_dataframe,
    pareto_front,
    parse_bytes,
    plot_barrier_scatter_by_bucket,
    plot_pareto,
    plot_runtime_scaling,
)
from dlnetbench_tpu.analysis.py_utils import StyleMap, add_zoom_inset


# --- byte formatting --------------------------------------------------------

@pytest.mark.parametrize("n,expect", [
    (0, "0 B"), (512, "512 B"), (1024, "1 KiB"), (1536, "1.5 KiB"),
    (1024 ** 2, "1 MiB"), (3 * 1024 ** 3, "3 GiB"),
])
def test_format_bytes(n, expect):
    assert format_bytes(n) == expect


@pytest.mark.parametrize("s,expect", [
    ("512", 512), ("512 B", 512), ("1 KiB", 1024), ("1.5KB", 1536),
    ("2 MiB", 2 * 1024 ** 2), ("0.5 GiB", 512 * 1024 ** 2),
])
def test_parse_bytes(s, expect):
    assert parse_bytes(s) == expect


def test_bytes_round_trip():
    for n in (1, 512, 1024, 1536, 10 * 1024 ** 2, 7 * 1024 ** 3):
        assert parse_bytes(format_bytes(n, precision=6)) == n


def test_parse_bytes_rejects_garbage():
    with pytest.raises(ValueError):
        parse_bytes("twelve")
    with pytest.raises(ValueError):
        parse_bytes("5 parsecs")


def test_style_map_stable():
    sm = StyleMap()
    a1 = sm["gpt2_l"]
    _ = sm["llama3_8b"]
    assert sm["gpt2_l"] is a1
    assert sm["gpt2_l"]["color"] != sm["llama3_8b"]["color"]


# --- pareto -----------------------------------------------------------------

def test_pareto_front_basic():
    pts = [(1, 10), (2, 5), (3, 6), (4, 1), (2, 20)]
    assert pareto_front(pts) == [(1.0, 10.0), (2.0, 5.0), (4.0, 1.0)]


def test_pareto_front_single_and_dominated():
    assert pareto_front([(3, 3)]) == [(3.0, 3.0)]
    # one point dominates everything
    assert pareto_front([(1, 1), (2, 2), (5, 9)]) == [(1.0, 1.0)]


# --- plot smoke tests over a synthetic run file -----------------------------

def _record(model, world, buckets, runtime, barrier):
    return {
        "section": "dp", "version": 1,
        "global": {"model": model, "world_size": world,
                   "num_buckets": buckets,
                   "bucket_bytes": [4096] * buckets},
        "mesh": {"platform": "cpu", "device_kind": "cpu"},
        "num_runs": len(runtime),
        "warmup_times": [],
        "ranks": [
            {"rank": r, "device_id": r, "process_index": 0,
             "hostname": "h0", "runtimes": runtime,
             "barrier_time": barrier}
            for r in range(world)
        ],
    }


@pytest.fixture()
def run_df(tmp_path):
    recs = [
        _record("gpt2_l", 2, 4, [100.0, 110.0], [10.0, 12.0]),
        _record("gpt2_l", 4, 4, [90.0, 95.0], [20.0, 21.0]),
        _record("gpt2_l", 8, 8, [80.0, 85.0], [30.0, 29.0]),
        _record("llama3_8b", 2, 4, [200.0, 210.0], [15.0, 14.0]),
        _record("llama3_8b", 4, 8, [150.0, 160.0], [22.0, 25.0]),
    ]
    path = tmp_path / "runs.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    return get_metrics_dataframe(path, "dp")


def test_plot_runtime_scaling(run_df):
    ax = plot_runtime_scaling(run_df)
    assert len(ax.get_lines()) == 2  # one per model
    labels = {t.get_text() for t in ax.get_legend().get_texts()}
    assert labels == {"gpt2_l", "llama3_8b"}
    plt.close("all")


def test_plot_barrier_scatter(run_df):
    ax = plot_barrier_scatter_by_bucket(run_df)
    ticklabels = [t.get_text() for t in ax.get_xticklabels()]
    assert len(ticklabels) == 2  # bucket counts 4 and 8
    assert "4 KiB" in ticklabels[0]  # msg-size annotation
    plt.close("all")


def test_plot_pareto(run_df):
    ax = plot_pareto(run_df, config_cols=("world_size",))
    # scatter + staircase per model
    assert len(ax.collections) == 2
    plt.close("all")


def test_plot_missing_column_raises(run_df):
    with pytest.raises(ValueError, match="lacks columns"):
        plot_runtime_scaling(run_df.drop(columns=["runtime"]))
    plt.close("all")


def test_plot_runtime_scaling_agg_min_max(run_df):
    # agg='min'/'max' collide with the variance band columns — must dedupe
    for agg in ("min", "max", "median"):
        ax = plot_runtime_scaling(run_df, agg=agg)
        assert len(ax.get_lines()) == 2
        plt.close("all")


def test_plot_pareto_unknown_config_col_raises(run_df):
    with pytest.raises(ValueError, match="lacks columns"):
        plot_pareto(run_df, config_cols=("nccl_protocol",))
    plt.close("all")


def test_barrier_scatter_mixed_sizes_label(tmp_path):
    # two models share num_buckets=4 with very different wire sizes: the
    # column label must show the range, not whichever row came first
    recs = [_record("gpt2_l", 2, 4, [100.0], [10.0]),
            _record("llama3_8b", 2, 4, [200.0], [15.0])]
    recs[1]["global"]["bucket_bytes"] = [16 * 1024 ** 2] * 4
    path = tmp_path / "mixed.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    df = get_metrics_dataframe(path, "dp")
    ax = plot_barrier_scatter_by_bucket(df)
    label = ax.get_xticklabels()[0].get_text()
    assert "4 KiB" in label and "16 MiB" in label
    plt.close("all")


def test_zoom_inset(run_df):
    ax = plot_runtime_scaling(run_df)
    axins = add_zoom_inset(ax, (0.55, 0.55, 0.4, 0.4), (2, 4), (80, 120))
    assert len(axins.get_lines()) == len(ax.get_lines())
    plt.close("all")
