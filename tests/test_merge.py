"""Multi-host record merge (metrics/merge.py): per-process records ->
one record with true per-process timers, plus the parser's process/host
coverage validation (reference plots/parser.py:102-136 checks the rank
set AND hostname-vs-node count; the rebuild validates process coverage
the same way)."""
from __future__ import annotations

import json

import pytest

from dlnetbench_tpu.metrics.merge import merge_files, merge_records
from dlnetbench_tpu.metrics.parser import records_to_dataframe, validate_record


def _proc_record(proc: int, num_procs: int = 2, world: int = 4,
                 runs: int = 2, runtime: float = 100.0, **overrides):
    """A per-process record the way emit.py writes them on a multi-host
    run: rows for EVERY device of the global mesh, this process's wall
    clock on all of them."""
    per_proc = world // num_procs
    rec = {
        "section": "dp",
        "version": 1,
        "process": proc,
        "global": {"proxy": "dp", "model": "gpt2_l_16_bfloat16",
                   "world_size": world, "num_processes": num_procs,
                   "num_buckets": 2},
        "mesh": {"platform": "cpu", "device_kind": "host"},
        "num_runs": runs,
        "warmup_times": [900.0 + proc],
        "ranks": [
            {"rank": r, "device_id": r, "process_index": r // per_proc,
             "hostname": f"host{proc}",
             "runtimes": [runtime + proc] * runs,
             "barrier_time": [10.0 + proc] * runs}
            for r in range(world)
        ],
    }
    rec.update(overrides)
    return rec


def test_merge_keeps_each_process_own_timers():
    merged = merge_records([_proc_record(0, runtime=100.0),
                            _proc_record(1, runtime=200.0)])
    assert [r["rank"] for r in merged["ranks"]] == [0, 1, 2, 3]
    # rows 0-1 measured by process 0, rows 2-3 by process 1: timers differ
    assert merged["ranks"][0]["runtimes"] == [100.0, 100.0]
    assert merged["ranks"][3]["runtimes"] == [201.0, 201.0]
    assert merged["ranks"][0]["hostname"] == "host0"
    assert merged["ranks"][3]["hostname"] == "host1"
    assert merged["warmup_times_by_process"] == {"0": [900.0], "1": [901.0]}
    validate_record(merged)
    df = records_to_dataframe([merged])
    assert len(df) == 4 * 2
    assert sorted(df["hostname"].unique()) == ["host0", "host1"]


def _replace(rec, **g):
    rec["global"] = {**rec["global"], **g}
    return rec


def test_merge_rejects_mismatched_globals():
    with pytest.raises(ValueError, match="not from the same run"):
        merge_records([_proc_record(0),
                       _replace(_proc_record(1), num_buckets=4)])


def test_merge_tolerates_per_process_measured_globals():
    """Every proxy emits its own measured burn calibration (and the pjrt
    backend its cache counters) into the globals; processes never agree on
    those floats, and the merge must not mistake them for records from
    different runs."""
    merged = merge_records([
        _replace(_proc_record(0), burn_ns_per_iter=101.7, cache_hits=5),
        _replace(_proc_record(1), burn_ns_per_iter=98.2, cache_hits=9),
    ])
    assert [r["rank"] for r in merged["ranks"]] == [0, 1, 2, 3]
    validate_record(merged)


def test_merge_dedupes_cohosted_energy():
    """energy_consumed brackets a HOST counter: with two processes on one
    host (--procs runs, co-hosted congestion pairs) both record the same
    RAPL/hwmon device, and the merge must keep ONE energy row per
    hostname (lowest process wins) so Pareto/averages don't double-count
    (ADVICE r3).  Distinct hosts keep their rows."""
    def with_energy(rec, proc, host):
        for row in rec["ranks"]:
            row["hostname"] = host
        first = min((r for r in rec["ranks"]
                     if r["process_index"] == proc),
                    key=lambda r: r["rank"])
        first["energy_consumed"] = [5.0 + proc, 6.0 + proc]
        return rec

    # co-hosted: processes 0 and 1 share "hostA"
    merged = merge_records([
        with_energy(_proc_record(0), 0, "hostA"),
        with_energy(_proc_record(1), 1, "hostA"),
    ])
    rows = [r for r in merged["ranks"] if "energy_consumed" in r]
    assert len(rows) == 1 and rows[0]["process_index"] == 0
    assert rows[0]["energy_consumed"] == [5.0, 6.0]

    # distinct hosts: both rows survive
    merged = merge_records([
        with_energy(_proc_record(0), 0, "hostA"),
        with_energy(_proc_record(1), 1, "hostB"),
    ])
    rows = [r for r in merged["ranks"] if "energy_consumed" in r]
    assert len(rows) == 2


def test_merge_rejects_mismatched_num_runs():
    bad = _proc_record(1)
    bad["num_runs"] = 5
    bad["ranks"] = [dict(r, runtimes=[1.0] * 5, barrier_time=[1.0] * 5)
                    for r in bad["ranks"]]
    with pytest.raises(ValueError, match="iterations"):
        merge_records([_proc_record(0), bad])


def test_merge_rejects_missing_or_duplicate_process():
    with pytest.raises(ValueError, match="missing"):
        merge_records([_proc_record(0, num_procs=3),
                       _replace(_proc_record(1), num_processes=3)])
    with pytest.raises(ValueError, match="two records claim"):
        merge_records([_proc_record(0), _proc_record(0)])
    with pytest.raises(ValueError, match="process 0"):
        merge_records([_proc_record(1)])


def test_validate_record_process_coverage():
    rec = merge_records([_proc_record(0), _proc_record(1)])
    # drop process 1's rows: coverage check must fire
    rec["ranks"] = [r for r in rec["ranks"] if r["process_index"] == 0]
    rec["global"]["world_size"] = 2
    for i, r in enumerate(rec["ranks"]):
        r["rank"] = i
    with pytest.raises(ValueError, match="process coverage"):
        validate_record(rec)


def test_merge_files_cli(tmp_path):
    for proc in (0, 1):
        p = tmp_path / f"proc{proc}.jsonl"
        p.write_text(json.dumps(_proc_record(proc, runtime=50.0 * (proc + 1)))
                     + "\n")
    out = tmp_path / "merged.jsonl"
    merged = merge_files(out, [tmp_path / "proc0.jsonl",
                               tmp_path / "proc1.jsonl"])
    on_disk = json.loads(out.read_text().strip())
    assert on_disk["ranks"] == merged["ranks"]
    assert len(on_disk["ranks"]) == 4


@pytest.mark.slow
def test_two_process_emit_and_merge(tmp_path):
    """End-to-end VERDICT r1 #8: two real OS processes bootstrap the
    distributed runtime, each runs a tiny measured step and emits ITS OWN
    record (process identity + global mesh rows); the parent merges them
    into one record with genuinely distinct per-process timers."""
    import os
    import socket
    import subprocess
    import sys
    import textwrap

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        pid, n, port, out = sys.argv[1:5]
        pid, n = int(pid), int(n)
        from dlnetbench_tpu.parallel import multihost as mh
        mh.initialize(coordinator_address=f"127.0.0.1:{port}",
                      num_processes=n, process_id=pid)
        from jax.sharding import Mesh
        from dlnetbench_tpu.parallel.mesh import describe_mesh
        from dlnetbench_tpu.proxies.base import ProxyResult
        from dlnetbench_tpu.metrics.emit import emit_result
        mesh = Mesh(jax.devices(), ("dp",))
        result = ProxyResult(
            name="dp",
            global_meta={"proxy": "dp", "model": "m", "world_size": n,
                         "num_buckets": 1, "mesh": describe_mesh(mesh)},
            timers_us={"runtimes": [100.0 + 50 * pid],
                       "barrier_time": [5.0 + pid]},
            warmup_times_us=[1.0], num_runs=1)
        emit_result(result, path=out)
        print(f"OK {pid}")
    """))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {**os.environ, "PYTHONPATH": "/root/repo"}
    env.pop("XLA_FLAGS", None)
    outs = [tmp_path / f"p{i}.jsonl" for i in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), "2", str(port), str(outs[i])],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    texts = [p.communicate(timeout=120)[0] for p in procs]
    for i, (p, txt) in enumerate(zip(procs, texts)):
        assert p.returncode == 0, f"proc {i} failed:\n{txt}"

    merged = merge_files(tmp_path / "merged.jsonl", outs)
    assert merged["global"]["num_processes"] == 2
    assert [r["process_index"] for r in merged["ranks"]] == [0, 1]
    # each process's own clock survived the merge
    assert merged["ranks"][0]["runtimes"] == [100.0]
    assert merged["ranks"][1]["runtimes"] == [150.0]
    validate_record(merged)


def test_scheduler_variables_and_merge_tolerance():
    """External-launcher job tagging (VERDICT r2 missing #5): scheduler
    identity env and DLNB_TAG_* axes are stamped into records, and
    per-PROCESS identity variables never abort a multi-host merge while
    sweep-axis variables still must match."""
    from dlnetbench_tpu.metrics.emit import scheduler_variables
    from dlnetbench_tpu.metrics.merge import merge_records
    import copy

    env = {"DLNB_TAG_protocol": "ring", "SLURM_JOB_ID": "77",
           "SLURM_PROCID": "1", "TPU_WORKER_ID": "1", "PATH": "/bin",
           "DLNB_TAG_EMPTY": ""}
    got = scheduler_variables(env)
    assert got == {"protocol": "ring", "slurm_job_id": "77",
                   "slurm_procid": "1", "tpu_worker_id": "1"}

    def rec(proc, variables):
        return {"section": "dp", "version": 1, "process": proc,
                "global": {"model": "m", "world_size": 2,
                           "num_processes": 2, "variables": variables},
                "num_runs": 1,
                "warmup_times": [1.0],
                "ranks": [{"rank": proc, "device_id": proc,
                           "process_index": proc, "hostname": f"h{proc}",
                           "runtimes": [1.0]}]}

    a = rec(0, {"protocol": "ring", "slurm_job_id": "77",
                "slurm_procid": "0", "tpu_worker_id": "0"})
    b = rec(1, {"protocol": "ring", "slurm_job_id": "77",
                "slurm_procid": "1", "tpu_worker_id": "1"})
    merged = merge_records([a, b])
    assert [r["rank"] for r in merged["ranks"]] == [0, 1]

    # a genuine sweep-axis mismatch still aborts
    c = copy.deepcopy(b)
    c["global"]["variables"]["protocol"] = "fullmesh"
    import pytest
    with pytest.raises(ValueError, match="variables"):
        merge_records([a, c])


# ---------------------------------------------------------------------
# Degraded (shrunk-world) merge pathway — fault-plan crash runs
# (faults/, native fault_plan.hpp): dead ranks emit nothing, and the
# explicit degraded_world declaration is what relaxes the coverage
# checks (VERDICT "ragged merge" lineage).

def _degraded_proc_record(proc: int, *, world: int = 4, num_procs: int = 4,
                          dead: tuple = (1,), runs: int = 2):
    """A tcp-style survivor record: one rank per process, crash victims
    declared via degraded_world."""
    survivors = [r for r in range(world) if r not in dead]
    return {
        "section": "dp", "version": 2, "process": proc,
        "global": {"proxy": "dp", "model": "m", "world_size": world,
                   "num_processes": num_procs,
                   "degraded_world": survivors,
                   "fault_plan": {"policy": "shrink", "events": [
                       {"kind": "crash", "ranks": list(dead),
                        "iteration": 3}]},
                   "fault_policy": "shrink",
                   "detection_ms": 2.0 + proc, "recovery_ms": 3.0 + proc},
        "mesh": {"platform": "tcp", "device_kind": "process-rank"},
        "num_runs": runs,
        "warmup_times": [10.0 + proc],
        "ranks": [{"rank": proc, "device_id": proc, "process_index": proc,
                   "hostname": f"host{proc}",
                   "runtimes": [100.0 + proc] * runs}],
    }


def test_merge_degraded_world_accepts_missing_dead_ranks():
    recs = [_degraded_proc_record(p) for p in (0, 2, 3)]  # rank 1 dead
    merged = merge_records(recs)
    assert [r["rank"] for r in merged["ranks"]] == [0, 2, 3]
    assert merged["global"]["degraded_world"] == [0, 2, 3]
    validate_record(merged)
    df = records_to_dataframe([merged])
    assert len(df) == 3 * 2
    # per-process fault measurements are volatile, never a run mismatch
    assert merged["global"]["detection_ms"] == 2.0


def test_merge_degraded_world_tolerates_dead_process_zero():
    """rank 0's process can BE the victim: the lowest surviving record
    anchors the merge iff it declares the degradation."""
    recs = [_degraded_proc_record(p, dead=(0,)) for p in (1, 2, 3)]
    merged = merge_records(recs)
    assert [r["rank"] for r in merged["ranks"]] == [1, 2, 3]
    validate_record(merged)


def test_merge_without_declaration_still_requires_full_coverage():
    """Missing ranks WITHOUT degraded_world stay an error — only the
    explicit declaration relaxes the checks."""
    recs = [_degraded_proc_record(p) for p in (0, 2, 3)]
    for rec in recs:
        del rec["global"]["degraded_world"]
    with pytest.raises(ValueError, match="missing|rank set"):
        validate_record(merge_records(recs))


def test_merge_degraded_missing_survivor_still_caught():
    """The degraded pathway relaxes DEAD ranks only: a missing SURVIVOR
    record still fails the final rank-coverage validation."""
    recs = [_degraded_proc_record(p) for p in (0, 2)]  # rank 3 missing
    with pytest.raises(ValueError, match="degraded_world"):
        merge_records(recs)


def _rejoin_proc_record(proc: int, runs: int = 6) -> dict:
    """A per-process record of a preempt->rejoin run: every rank emits
    (the evictee drained locally, nobody died), degraded_world is
    CLEARED, and the plan-derived rejoin trigger must agree."""
    return {
        "section": "dp", "version": 2, "process": proc,
        "global": {"proxy": "dp", "model": "m", "world_size": 3,
                   "num_processes": 3,
                   "fault_plan": {"policy": "shrink", "events": [
                       {"kind": "preempt", "ranks": [1], "iteration": 2,
                        "magnitude_us": 20000.0},
                       {"kind": "rejoin", "ranks": [1], "iteration": 4}]},
                   "fault_policy": "shrink",
                   "fault_rejoin_step": 4,
                   # per-process clocks: volatile, never a mismatch
                   "rejoin_ms": 10.0 + proc,
                   "checkpoint_ms": 5.0 + proc,
                   "restore_ms": 2.0 + proc,
                   "lost_steps": proc,
                   "goodput": 6.0 + proc},
        "mesh": {"platform": "tcp", "device_kind": "process-rank"},
        "num_runs": runs,
        "warmup_times": [10.0 + proc],
        "ranks": [{"rank": proc, "device_id": proc, "process_index": proc,
                   "hostname": f"host{proc}",
                   "runtimes": [100.0 + proc] * runs}],
    }


def test_merge_rejoined_run_requires_full_coverage():
    """After a rejoin the world is FULL again: every rank's record is
    required (no degraded relaxation — the evictee is alive and
    emits), the per-process elastic measurements merge as volatile,
    and the plan-derived rejoin trigger must match."""
    recs = [_rejoin_proc_record(p) for p in range(3)]
    merged = merge_records(recs)
    assert [r["rank"] for r in merged["ranks"]] == [0, 1, 2]
    assert "degraded_world" not in merged["global"]
    assert merged["global"]["fault_rejoin_step"] == 4
    # volatile per-process measurements: anchor process's values kept
    assert merged["global"]["rejoin_ms"] == 10.0
    assert merged["global"]["goodput"] == 6.0
    validate_record(merged)
    df = records_to_dataframe([merged])
    assert len(df) == 3 * 6

    # a missing rank is NOT tolerated — the rejoined record declares no
    # degraded_world, so full coverage is enforced
    with pytest.raises(ValueError, match="missing|rank set"):
        validate_record(merge_records(
            [_rejoin_proc_record(p) for p in (0, 2)]))


def test_merge_rejects_mismatched_rejoin_trigger():
    """fault_rejoin_step is PLAN-derived, not a per-process clock: two
    processes disagreeing about when the world grew back are different
    runs and must refuse to merge."""
    recs = [_rejoin_proc_record(p) for p in range(3)]
    recs[2]["global"]["fault_rejoin_step"] = 5
    with pytest.raises(ValueError, match="fault_rejoin_step"):
        merge_records(recs)


def test_rejoin_fixture_roundtrip():
    """Committed elastic artifact (a REAL merged dp-over-tcp
    preempt->rejoin run: rank 1 evicted at step 5 with a 20 ms grace
    drain, back at step 9): coverage is degraded mid-run — the fault
    window says so — yet the record ends FULL world: all three ranks
    emit, degraded_world is cleared, and rejoin_ms prices the grow."""
    from pathlib import Path

    from dlnetbench_tpu.faults.plan import FaultPlan
    from dlnetbench_tpu.metrics.parser import load_records

    fixture = Path(__file__).parent / "data" / "record_rejoin.jsonl"
    recs = load_records(fixture)
    assert len(recs) == 1
    rec = recs[0]
    validate_record(rec)
    g = rec["global"]
    assert "degraded_world" not in g
    assert g["fault_policy"] == "shrink"
    assert {e["kind"] for e in g["fault_plan"]["events"]} == \
        {"preempt", "rejoin"}
    assert g["fault_rejoin_step"] == 9
    assert g["rejoin_ms"] > 0
    assert [r["rank"] for r in rec["ranks"]] == [0, 1, 2]
    df = records_to_dataframe(recs)
    assert len(df) == 3 * rec["num_runs"]
    assert (df["runtime"] > 0).all()
    # the plan parses through the shared schema and the eviction window
    # is visible mid-run: rank 1 out from its preempt to its rejoin
    plan = FaultPlan.from_dict(g["fault_plan"]).validate()
    assert plan.evicted(1, plan.first_preempt_iteration())
    assert not plan.evicted(1, g["fault_rejoin_step"])


def test_faulted_fixture_roundtrip():
    """Committed degraded artifact (a REAL merged dp-over-tcp shrink
    run: crash of rank 1 at iteration 4, survivors finished): parses,
    validates through the degraded pathway, and the fault columns
    surface in the DataFrame."""
    from pathlib import Path

    from dlnetbench_tpu.metrics.parser import load_records

    fixture = Path(__file__).parent / "data" / "record_faulted.jsonl"
    recs = load_records(fixture)
    assert len(recs) == 1
    rec = recs[0]
    validate_record(rec)
    g = rec["global"]
    assert g["degraded_world"] == [0, 2]
    assert g["fault_policy"] == "shrink"
    assert g["fault_plan"]["events"][0]["kind"] == "crash"
    assert g["detection_ms"] > 0 and g["recovery_ms"] > 0
    assert [r["rank"] for r in rec["ranks"]] == [0, 2]
    df = records_to_dataframe(recs)
    assert len(df) == 2 * rec["num_runs"]
    assert (df["fault_policy"] == "shrink").all()
    assert (df["runtime"] > 0).all()
