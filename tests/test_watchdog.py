"""Stall watchdog (utils/watchdog.py)."""
from __future__ import annotations

import time

from dlnetbench_tpu.utils.watchdog import StepWatchdog


def test_fast_section_does_not_fire():
    fired = []
    wd = StepWatchdog(0.5, on_stall=lambda n, e: fired.append((n, e)))
    for _ in range(3):
        with wd:
            pass
    time.sleep(0.7)  # past the deadline of every (disarmed) section
    assert fired == [] and wd.stalls == 0


def test_stalled_section_fires_once_per_arming():
    fired = []
    wd = StepWatchdog(0.05, on_stall=lambda n, e: fired.append((n, e)),
                      name="collective")
    with wd:
        time.sleep(0.15)
    assert wd.stalls == 1
    assert fired[0][0] == "collective" and fired[0][1] >= 0.05


def test_wrap_and_default_message(capsys):
    wd = StepWatchdog(0.05, name="train_step")

    @wd.wrap
    def slow():
        time.sleep(0.12)
        return 42

    assert slow() == 42
    assert wd.stalls == 1
    err = capsys.readouterr().err
    assert "train_step" in err and "deadline" in err
