"""Stall watchdog (utils/watchdog.py)."""
from __future__ import annotations

import time

import pytest

from dlnetbench_tpu.utils.watchdog import StepWatchdog


def test_fast_section_does_not_fire():
    fired = []
    wd = StepWatchdog(0.5, on_stall=lambda n, e: fired.append((n, e)))
    for _ in range(3):
        with wd:
            pass
    time.sleep(0.7)  # past the deadline of every (disarmed) section
    assert fired == [] and wd.stalls == 0


def test_stalled_section_fires_once_per_arming():
    fired = []
    wd = StepWatchdog(0.05, on_stall=lambda n, e: fired.append((n, e)),
                      name="collective")
    with wd:
        time.sleep(0.15)
    assert wd.stalls == 1
    assert fired[0][0] == "collective" and fired[0][1] >= 0.05


def test_wrap_and_default_message(capsys):
    wd = StepWatchdog(0.05, name="train_step")

    @wd.wrap
    def slow():
        time.sleep(0.12)
        return 42

    assert slow() == 42
    assert wd.stalls == 1
    err = capsys.readouterr().err
    assert "train_step" in err and "deadline" in err


def test_heartbeat_ages_and_record_stamp():
    """Satellite: per-key last-progress heartbeat ages stamped into the
    emitted record so post-mortems of hung runs show WHERE progress
    stopped."""
    wd = StepWatchdog(5.0, name="step")
    wd.beat("rank0")
    time.sleep(0.05)
    wd.beat("rank1")
    ages = wd.heartbeat_ages()
    assert set(ages) == {"rank0", "rank1"}
    # rank0's beat is older: that is where progress stopped first
    assert ages["rank0"] > ages["rank1"] >= 0.0
    meta = {}
    wd.stamp(meta)
    stamped = meta["watchdog_heartbeat_age_s"]
    assert stamped["rank0"] >= stamped["rank1"] >= 0.0
    assert meta["watchdog_stalls"] == 0


def test_stall_message_names_the_last_progress(capsys):
    """The stall diagnostic names the MOST RECENT beat — the last
    progress made; the hang sits just past it (the oldest beat would be
    the first phase to complete, the opposite of where it is stuck)."""
    wd = StepWatchdog(0.05, name="collective")
    wd.beat("chain_0")
    time.sleep(0.02)
    wd.beat("chain_1")
    with wd:
        time.sleep(0.12)
    err = capsys.readouterr().err
    assert wd.stalls == 1
    assert "last progress" in err and "'chain_1'" in err


def test_run_proxy_stamps_heartbeats():
    """ProxyConfig.watchdog: the harness beats per phase/chain and the
    record's globals carry the ages at emission."""
    from dlnetbench_tpu.proxies.base import ProxyConfig, StepBundle, \
        run_proxy

    wd = StepWatchdog(30.0, name="dp_step")
    bundle = StepBundle(full=lambda: None, compute=None, comm=None,
                        global_meta={})
    cfg = ProxyConfig(warmup=1, runs=2, measure_energy=False,
                      measure_comm_only=False, measure_compute_only=False,
                      watchdog=wd)
    res = run_proxy("wd_test", bundle, cfg)
    ages = res.global_meta["watchdog_heartbeat_age_s"]
    assert "warmup" in ages and "chain_0" in ages and "chain_1" in ages
    assert all(v >= 0.0 for v in ages.values())
    assert res.global_meta["watchdog_stalls"] == 0


def test_stall_dumps_active_span_stack(capsys):
    """Satellite: on stall the watchdog captures every thread's OPEN
    span stack (metrics/spans.py) — the heartbeat key says which phase
    stopped beating, the span stack says where inside the harness the
    measuring thread was sitting — and stamps it into the record."""
    from dlnetbench_tpu.metrics import spans

    spans.enable()
    try:
        wd = StepWatchdog(0.05, name="timed")
        wd.beat("chain_0")
        with spans.span("timed", what="headline"):
            with spans.span("fence"):
                with wd:
                    time.sleep(0.12)
    finally:
        spans.disable()
    err = capsys.readouterr().err
    assert wd.stalls == 1
    assert "active spans:" in err and "timed > fence" in err
    meta = {}
    wd.stamp(meta)
    assert meta["watchdog_stall_spans"] == ["timed > fence"]


@pytest.mark.telemetry
def test_stall_dumps_telemetry_ring_trend(capsys, tmp_path):
    """ISSUE 14 satellite: a stall report carries the flight ring's
    last-K samples — the TREND into the stall, not just the frozen
    instant — in the message, the record stamp, and a flight_stall.json
    anomaly dump."""
    import json

    from dlnetbench_tpu.metrics import telemetry

    rec = telemetry.enable(capacity=32, dump_dir=tmp_path)
    try:
        for i in range(12):
            telemetry.record_step("proxy", step=i,
                                  step_wall_us=100.0 + 10 * i)
        wd = StepWatchdog(0.05, name="timed")
        wd.beat("chain_0")
        with wd:
            time.sleep(0.12)
    finally:
        telemetry.disable()
    err = capsys.readouterr().err
    assert wd.stalls == 1
    assert "telemetry trend" in err and "step walls us" in err
    assert len(wd.last_stall_telemetry) == wd.stall_telemetry_k
    assert [s["step"] for s in wd.last_stall_telemetry] == \
        list(range(4, 12))  # the LAST K, oldest first
    meta = {}
    wd.stamp(meta)
    assert meta["watchdog_stall_telemetry"] == wd.last_stall_telemetry
    # the stall is an anomaly: ring window dumped alongside
    dump = json.loads((tmp_path / "flight_stall.json").read_text())
    assert dump["trigger"] == "stall"
    assert dump["detail"]["section"] == "timed"
    assert dump["detail"]["elapsed_s"] >= 0.05
    assert [s["step"] for s in dump["samples"]] == list(range(12))
    assert rec.anomalies_block()["triggers"] == {"stall": 1}


@pytest.mark.telemetry
def test_stall_without_telemetry_has_no_trend_noise(capsys):
    """Telemetry off: the stall message carries no telemetry clause and
    the record stamp no ring key (the zero-overhead contract's
    watchdog face)."""
    wd = StepWatchdog(0.05, name="timed")
    with wd:
        time.sleep(0.12)
    err = capsys.readouterr().err
    assert wd.stalls == 1 and "telemetry trend" not in err
    meta = {}
    wd.stamp(meta)
    assert "watchdog_stall_telemetry" not in meta


def test_stall_message_and_record_carry_checkpoint_age(capsys):
    """Satellite (ISSUE 7): a hang report should say how much work a
    kill would lose — the stall message and the record stamp carry the
    step and age of the last COMPLETED checkpoint save (wired by
    utils/checkpoint.SnapshotCheckpointer.checkpoint_saved)."""
    wd = StepWatchdog(0.05, name="step")
    assert wd.last_checkpoint_age_s() is None
    wd.checkpoint_saved(7)
    with wd:
        time.sleep(0.12)
    err = capsys.readouterr().err
    assert wd.stalls == 1
    assert "last completed checkpoint: step 7" in err
    assert "loses the work since" in err
    meta = {}
    wd.stamp(meta)
    assert meta["last_checkpoint_step"] == 7
    assert meta["last_checkpoint_age_s"] >= 0.12


def test_record_stamp_without_checkpoint_has_no_age_keys():
    wd = StepWatchdog(5.0, name="step")
    meta = {}
    wd.stamp(meta)
    assert "last_checkpoint_age_s" not in meta
    assert "last_checkpoint_step" not in meta


def test_snapshot_checkpointer_wires_watchdog(tmp_path):
    """The integration seam: a SnapshotCheckpointer given a watchdog
    reports each COMPLETED save into it — async saves only after the
    durable write lands."""
    import jax.numpy as jnp

    from dlnetbench_tpu.utils.checkpoint import SnapshotCheckpointer

    wd = StepWatchdog(30.0, name="step")
    sc = SnapshotCheckpointer(tmp_path / "c", {"w": jnp.ones((4,))},
                              every=2, mode="async", backend="npz",
                              watchdog=wd)
    sc.on_step(0)  # no save yet (period 2)
    assert wd.last_checkpoint_age_s() is None
    sc.on_step(1)
    sc.wait()
    assert wd.last_checkpoint_age_s() is not None
    meta = {}
    wd.stamp(meta)
    assert meta["last_checkpoint_step"] == 1


def test_stall_without_tracing_has_no_span_noise(capsys):
    """Span tracing off (the default run mode): the stall message keeps
    its shape with no empty 'active spans' suffix and nothing stamped."""
    wd = StepWatchdog(0.05, name="timed")
    with wd:
        time.sleep(0.12)
    err = capsys.readouterr().err
    assert wd.stalls == 1
    assert "active spans:" not in err
    meta = {}
    wd.stamp(meta)
    assert "watchdog_stall_spans" not in meta
