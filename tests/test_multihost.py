"""Multi-host runtime support (parallel/multihost.py), single-controller
degradations + hybrid-mesh axis layout on the 8-device CPU mesh."""
from __future__ import annotations

import numpy as np
import pytest

from dlnetbench_tpu.models import spmd
from dlnetbench_tpu.parallel import multihost as mh


def test_single_process_degradations(eight_devices):
    mh.initialize()            # no-op, must not raise
    assert not mh.is_multihost()
    mh.barrier()               # no-op
    meta = mh.host_metadata()
    assert len(meta) == 1 and meta[0]["process"] == 0
    assert len(meta[0]["local_device_ids"]) >= 8


def test_hybrid_mesh_axis_layout(eight_devices):
    mesh = mh.make_hybrid_mesh(dcn={"dp": 2}, ici={"pp": 2, "tp": 2})
    assert mesh.axis_names == ("dp", "pp", "tp")
    assert mesh.devices.shape == (2, 2, 2)
    # dcn size-1 axes are kept so shard_map specs stay stable
    mesh1 = mh.make_hybrid_mesh(dcn={"dp": 1}, ici={"tp": 4})
    assert mesh1.axis_names == ("dp", "tp")
    assert mesh1.devices.shape == (1, 4)


def test_training_step_on_hybrid_mesh(eight_devices):
    """The SPMD step runs unchanged on a hybrid-constructed mesh (same axis
    names) — dp would ride DCN, pp/tp ICI on a real pod."""
    mesh = mh.make_hybrid_mesh(dcn={"dp": 2}, ici={"pp": 2, "tp": 2})
    cfg = spmd.SpmdConfig(batch=8, num_microbatches=2)
    step = spmd.make_train_step(mesh, cfg)
    import jax
    params = spmd.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1),
                                (cfg.batch, cfg.seq_len + 1), 0,
                                cfg.vocab_size)
    _, loss = step(params, tokens)
    assert np.isfinite(float(loss))


def test_bad_axis_size_rejected(eight_devices):
    with pytest.raises(ValueError):
        mh.make_hybrid_mesh(dcn={"dp": 0}, ici={"tp": 4})


@pytest.mark.slow
def test_two_process_distributed_runtime(tmp_path):
    """Genuine 2-process bootstrap over the loopback coordinator: each
    process clears the pre-pinned backend, joins via initialize(), sees the
    global 2-device world, passes a barrier, gathers both hosts' metadata,
    and psums across processes."""
    import subprocess, sys, os, textwrap
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        # cross-process CPU *computation* collectives need gloo
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
        from dlnetbench_tpu.parallel import multihost as mh
        mh.initialize(coordinator_address=f"127.0.0.1:{port}",
                      num_processes=n, process_id=pid)
        assert mh.is_multihost() and jax.process_count() == n
        mh.barrier()
        meta = mh.host_metadata()
        assert [m["process"] for m in meta] == [0, 1], meta
        # cross-process psum over the global 2-device mesh
        import jax.numpy as jnp
        from jax import lax

        from dlnetbench_tpu.utils.jax_compat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        nd = len(jax.devices())      # spans BOTH processes
        assert nd > len(jax.local_devices()), (nd, jax.local_devices())
        mesh = Mesh(jax.devices(), ("w",))
        fn = shard_map(lambda x: lax.psum(x, "w"), mesh=mesh,
                       in_specs=P("w"), out_specs=P(), check_vma=False)
        total = jax.jit(fn)(jnp.arange(float(nd)))
        # the result is replicated across BOTH processes: read the local
        # replica (float() on a non-fully-addressable array raises)
        local = float(total.addressable_data(0)[0])
        assert local == nd * (nd - 1) / 2, local
        print(f"OK {pid}")
    """))
    import socket
    with socket.socket() as s:   # a free loopback port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {**os.environ, "PYTHONPATH": "/root/repo"}
    env.pop("XLA_FLAGS", None)   # 1 local device per process is enough
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"OK {i}" in out
