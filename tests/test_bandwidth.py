"""Effective-bandwidth derivation (analysis/bandwidth.py) from the
per-proxy comm_model declarations."""
from __future__ import annotations

import pytest

from dlnetbench_tpu.analysis.bandwidth import (
    bandwidth_summary,
    bus_factor,
    effective_bandwidth,
)


def _record(comm_model, timers):
    return {"section": "dp", "global": {"model": "m",
                                        "comm_model": comm_model},
            "ranks": [{"rank": 0, **timers}]}


def test_bus_factors():
    assert bus_factor("allreduce", 8) == pytest.approx(2 * 7 / 8)
    assert bus_factor("allgather", 4) == pytest.approx(3 / 4)
    assert bus_factor("alltoall", 4) == pytest.approx(3 / 4)
    assert bus_factor("p2p", 16) == 1.0
    with pytest.raises(ValueError):
        bus_factor("broadcast", 4)


def test_single_component_allreduce():
    rec = _record({"barrier_time": [
        {"kind": "allreduce", "group": 8, "bytes": 2000}]},
        {"barrier_time": [2.0, 4.0]})
    bw = effective_bandwidth([rec])
    assert len(bw) == 2
    r0 = bw.iloc[0]
    # 2000 B in 2 us = 1 GB/s algbw; busbw scales by 2*(8-1)/8
    assert r0["algbw_GBps"] == pytest.approx(1.0)
    assert r0["busbw_GBps"] == pytest.approx(2 * 7 / 8)
    summary = bandwidth_summary([rec])
    assert summary.iloc[0]["time_us"] == pytest.approx(3.0)


def test_multi_component_two_level_sync():
    """MoE's dp_ep timer: allreduce over ep plus allreduce over dp —
    busbw weights each component by its own group factor."""
    rec = _record({"dp_ep_comm_time": [
        {"kind": "allreduce", "group": 2, "bytes": 1000},
        {"kind": "allreduce", "group": 4, "bytes": 3000}]},
        {"dp_ep_comm_time": [4.0]})
    bw = effective_bandwidth([rec])
    r = bw.iloc[0]
    assert r["msg_bytes"] == 4000
    expect_bus = (1000 * (2 * 1 / 2) + 3000 * (2 * 3 / 4)) / 4e-6 / 1e9
    assert r["busbw_GBps"] == pytest.approx(expect_bus)
    assert r["group_size"] == 4


def test_legacy_hierarchical_records_refused():
    """Records from the r3 gather-based hier DCN legs (dcn_algo
    'hierarchical') moved bytes no real DCN algorithm moves: busbw must
    be refused (NaN, bound marker), never published with ring/mesh
    correction factors (ADVICE r3 medium)."""
    rec = _record({"comm": [
        {"kind": "alltoall", "group": 8, "bytes": 4000}]},
        {"comm": [2.0]})
    rec["global"]["dcn_algo"] = "hierarchical"
    rec["global"]["tcp_ring_threshold_bytes"] = 65536
    bw = effective_bandwidth([rec])
    import math
    assert bw.iloc[0]["bound"] == "hierarchical"
    assert math.isnan(bw.iloc[0]["busbw_GBps"])
    assert bw.iloc[0]["algbw_GBps"] > 0  # algbw is still honest


def test_blocked_hier_records_admissible_with_threshold():
    """Current hier records (dcn_algo 'blocked') are bandwidth-true:
    busbw applies.  The small-allreduce full-mesh refusal keys on the
    PROCESS mesh width (the DCN leg), not the group size."""
    import math

    def hier_rec(bytes_, nprocs):
        rec = _record({"comm": [
            {"kind": "allreduce", "group": 8, "bytes": bytes_}]},
            {"comm": [5.0]})
        rec["global"]["dcn_algo"] = "blocked"
        rec["global"]["num_processes"] = nprocs
        rec["global"]["tcp_ring_threshold_bytes"] = 65536
        return rec

    # large allreduce: ring on the DCN leg -> admissible
    big = effective_bandwidth([hier_rec(1 << 20, 4)])
    assert big.iloc[0]["bound"] == "exact"
    assert big.iloc[0]["busbw_GBps"] > 0
    # small allreduce over >2 processes: DCN full mesh -> refused
    small = effective_bandwidth([hier_rec(4000, 4)])
    assert small.iloc[0]["bound"] == "fullmesh"
    assert math.isnan(small.iloc[0]["busbw_GBps"])
    # 2 processes: mesh == ring at n=2 -> admissible even when small
    two = effective_bandwidth([hier_rec(4000, 2)])
    assert two.iloc[0]["bound"] == "exact"
    assert two.iloc[0]["busbw_GBps"] > 0


def test_blocked_refusal_keys_on_component_span():
    """Components stamped with their split's real spanning process
    count ("span", schedule.hpp axis_span_procs) refuse on THAT mesh
    width, not the record-global num_processes (advisor r4): a small
    allreduce whose group lives inside one process (span 1) never
    touches the DCN and keeps its busbw; one spanning 2 processes rides
    a 2-mesh (== ring wire cost) and keeps it too; only a true >2-wide
    DCN mesh is refused.  Records without the stamp keep the
    conservative num_processes fallback."""
    import math

    def hier_rec(comp):
        rec = _record({"comm": [comp]}, {"comm": [5.0]})
        rec["global"]["dcn_algo"] = "blocked"
        rec["global"]["num_processes"] = 4
        rec["global"]["tcp_ring_threshold_bytes"] = 65536
        return rec

    small = {"kind": "allreduce", "group": 8, "bytes": 4000}
    # span 1: group contained in one process -> never refused
    one = effective_bandwidth([hier_rec({**small, "span": 1})])
    assert one.iloc[0]["bound"] == "exact"
    assert one.iloc[0]["busbw_GBps"] > 0
    # span 2: mesh == ring at n=2 -> admissible
    two = effective_bandwidth([hier_rec({**small, "span": 2})])
    assert two.iloc[0]["bound"] == "exact"
    # span 3: true DCN full mesh -> refused
    three = effective_bandwidth([hier_rec({**small, "span": 3})])
    assert three.iloc[0]["bound"] == "fullmesh"
    assert math.isnan(three.iloc[0]["busbw_GBps"])
    # no span: conservative fallback on num_processes (4) -> refused
    legacy = effective_bandwidth([hier_rec(small)])
    assert legacy.iloc[0]["bound"] == "fullmesh"


def test_transport_column_locked():
    """Acceptance lock: every bandwidth row and summary row carries the
    transport provenance column — a stamped record's value verbatim, a
    legacy record classified from its identity keys — so loopback and
    virtual-mesh figures can never read as fabric physics."""
    from dlnetbench_tpu.analysis.bandwidth import transport_of

    # stamped (schema v2 / current native): verbatim
    stamped = _record({"comm_time": [
        {"kind": "allreduce", "group": 2, "bytes": 2000}]},
        {"comm_time": [2.0]})
    stamped["global"]["transport"] = "tcp:loopback"
    bw = effective_bandwidth([stamped])
    assert (bw["transport"] == "tcp:loopback").all()
    s = bandwidth_summary([stamped])
    assert "transport" in s.columns
    assert (s["transport"] == "tcp:loopback").all()

    # legacy classification paths (records that predate the stamp)
    assert transport_of({"global": {"backend": "shm"}}) == "shm"
    assert transport_of({"global": {"backend": "tcp"}}) == "tcp"
    assert transport_of({"global": {"backend": "pjrt",
                                    "pjrt_executor": "host"}}) == "host"
    assert transport_of({"global": {"backend": "pjrt",
                                    "pjrt_executor": "tpu"}}) == "ici"
    assert transport_of({"global": {"backend": "pjrt",
                                    "pjrt_executor": "host",
                                    "dcn_transport": "tcp"}}) == "host+tcp"
    assert transport_of({"global": {},
                         "mesh": {"platform": "cpu"}}) == "virtual-host"
    assert transport_of({"global": {},
                         "mesh": {"platform": "tpu"}}) == "ici"
    # a legacy multi-host TPU record's collectives have a DCN leg: the
    # fallback must mirror emit.transport_label, not flatten to ici
    assert transport_of({"global": {},
                         "mesh": {"platform": "tpu",
                                  "num_hosts": 4}}) == "ici+dcn"
    assert transport_of({"global": {}}) == "unknown"

    # two transports never average into one summary row
    other = _record({"comm_time": [
        {"kind": "allreduce", "group": 2, "bytes": 2000}]},
        {"comm_time": [4.0]})
    other["global"]["transport"] = "tcp:ethernet"
    s2 = bandwidth_summary([stamped, other])
    assert len(s2) == 2
    assert set(s2["transport"]) == {"tcp:loopback", "tcp:ethernet"}


def test_zero_time_and_missing_model_skipped():
    rec = _record({"barrier_time": [
        {"kind": "allreduce", "group": 8, "bytes": 100}]},
        {"barrier_time": [0.0]})
    assert effective_bandwidth([rec]).empty
    assert effective_bandwidth([{"section": "x", "global": {},
                                 "ranks": []}]).empty
    assert bandwidth_summary([rec]).empty


@pytest.mark.parametrize("argv,timers", [
    (["dp", "--num_buckets", "2"], ["comm"]),
    (["fsdp", "--num_units", "4", "--sharding_factor", "4"],
     ["allgather", "reduce_scatter"]),
    (["hybrid_3d", "--num_stages", "2", "--num_microbatches", "2",
      "--tp", "2"], ["pp_comm", "dp_comm", "tp_comm"]),
    (["hybrid_3d_moe", "--num_stages", "2", "--num_microbatches", "2",
      "--num_expert_shards", "2"], ["pp_comm", "ep_comm", "dp_ep_comm"]),
    (["ring_attention", "--sp", "4", "--max_layers", "2"], ["ring_comm"]),
    (["ulysses", "--sp", "4", "--max_layers", "2"], ["a2a_comm"]),
])
def test_real_records_all_proxies(eight_devices, tmp_path, argv, timers):
    """Every proxy's record must yield nonzero busbw for its declared
    collectives — the north-star table covers the whole suite."""
    from dlnetbench_tpu.cli import main
    from dlnetbench_tpu.metrics.parser import load_records
    model = ("mixtral_8x7b_16_bfloat16" if argv[0] == "hybrid_3d_moe"
             else "llama3_8b_16_bfloat16")
    out = tmp_path / "rec.jsonl"
    extra = [] if "--size_scale" in argv else ["--size_scale", "1e-5"]
    rc = main(argv + extra + ["--model", model, "--platform", "cpu",
                              "-r", "1", "-w", "1",
                              "--time_scale", "1e-4", "--no_topology",
                              "--out", str(out)])
    assert rc == 0
    summary = bandwidth_summary(load_records(out))
    got = set(summary["collective"])
    assert got == set(timers), (got, timers)
    assert (summary["busbw_GBps"] > 0).all()
