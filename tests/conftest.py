"""Test harness config: force an 8-device virtual CPU platform so every
mesh/collective test runs without TPU hardware (the TPU analogue of the
reference's ``mpi_cpu`` build config, reference README.md:96 — the property
that the whole suite runs on a laptop).

Note: some environments (e.g. the axon TPU tunnel) pre-import jax from
sitecustomize and pin ``jax_platforms`` programmatically, so setting the
JAX_PLATFORMS env var here is too late — we must override through
``jax.config`` as well.  XLA_FLAGS is still read at first backend init,
which has not happened yet at conftest time.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """``tpu_only`` tests (on-chip paired A/B harnesses) stay
    COLLECTABLE everywhere — a typo'd import or signature drift fails
    collection on the CPU mesh — but only run on a real TPU backend.
    This conftest pins the platform to cpu above, so in the tier-1 lane
    they always skip; an on-chip session (JAX_PLATFORMS unset on TPU
    hardware, conftest bypassed via pytest -p) runs them."""
    if jax.default_backend() == "tpu":
        return
    skip = pytest.mark.skip(
        reason="tpu_only: CPU mesh (interpret-mode kernels are "
               "correctness-tested elsewhere; this harness measures)")
    for item in items:
        if "tpu_only" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def native_bin():
    """ONE shared native build tree for the whole session, whichever
    lane is running: the default lane and the opt-in ``-m native_slow``
    heavy lane both resolve (and incrementally rebuild) the same
    out-of-tree CMake/Ninja tree via utils.native_build, so splitting
    the suite into lanes never costs a second configure+build.
    ``DLNB_NATIVE_BIN`` (a prebuilt bin dir — hand compiles on boxes
    without cmake/ninja) bypasses the toolchain requirement entirely,
    mirroring utils.native_build."""
    import os
    import shutil
    from pathlib import Path

    if not os.environ.get("DLNB_NATIVE_BIN") and (
            shutil.which("cmake") is None or shutil.which("ninja") is None):
        pytest.skip("cmake/ninja not available")
    from dlnetbench_tpu.utils.native_build import native_bin as _locate
    return _locate(Path(__file__).resolve().parent.parent)
