"""Test harness config: force an 8-device virtual CPU platform so every
mesh/collective test runs without TPU hardware (the TPU analogue of the
reference's ``mpi_cpu`` build config, reference README.md:96 — the property
that the whole suite runs on a laptop)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
