"""End-to-end DP proxy test on the 8-device virtual CPU mesh: schedule ->
jitted shard_map step -> harness -> JSON record -> DataFrame (the minimum
slice of SURVEY.md §7.2 step 3)."""
import json

import jax.numpy as jnp
import pytest

from dlnetbench_tpu.core.model_stats import load_model_stats
from dlnetbench_tpu.metrics.emit import emit_result, result_to_record
from dlnetbench_tpu.metrics.parser import get_metrics_dataframe, load_records
from dlnetbench_tpu.parallel.mesh import make_flat_mesh
from dlnetbench_tpu.proxies import dp as dp_proxy
from dlnetbench_tpu.proxies.base import ProxyConfig, estimate_runs, run_proxy

TINY = dict(size_scale=1e-5, time_scale=2e-4)


@pytest.fixture(scope="module")
def dp_result(eight_devices):
    stats = load_model_stats("gpt2_l_16_bfloat16")
    cfg = ProxyConfig(warmup=1, runs=3, **TINY)
    mesh = make_flat_mesh(4)
    bundle = dp_proxy.build(stats, num_buckets=4, cfg=cfg, mesh=mesh)
    return run_proxy("dp", bundle, cfg), bundle


def test_dp_runs_and_times(dp_result):
    result, bundle = dp_result
    assert result.num_runs == 3
    assert len(result.timers_us["runtimes"]) == 3
    assert all(t > 0 for t in result.timers_us["runtimes"])
    assert "barrier_time" in result.timers_us
    assert "comm_time" in result.timers_us
    assert all(t >= 0 for t in result.timers_us["barrier_time"])


def test_dp_overlap_fraction_measured(dp_result):
    """With both A/B legs measured, run_proxy reports the per-chain
    measured overlap fraction (metrics/stats.overlap_fraction) — one
    dimensionless sample per run, consistent with the timers it was
    derived from."""
    result, _ = dp_result
    ov = result.timers_us["overlap_fraction"]
    assert len(ov) == 3
    from dlnetbench_tpu.metrics.stats import overlap_fraction
    expect = overlap_fraction(result.timers_us["runtimes"],
                              result.timers_us["compute_time"],
                              result.timers_us["comm_time"])
    for got, want in zip(ov, expect):
        assert got == pytest.approx(want, abs=1e-3)


def test_dp_step_correctness(dp_result):
    """The allreduce must actually sum across the 4 ranks: buffers start at
    zero, so outputs stay zero — then rerun the comm-only step on ones via
    the bundle's full step, checking the burn didn't corrupt buffers."""
    _, bundle = dp_result
    outs = bundle.full()
    state = outs[0]
    assert jnp.all(jnp.isfinite(state.astype(jnp.float32)))
    for o in outs[1:]:
        assert float(jnp.max(jnp.abs(o))) == 0.0  # 4 * zeros = zeros


def test_dp_meta(dp_result):
    result, _ = dp_result
    g = result.global_meta
    assert g["proxy"] == "dp" and g["world_size"] == 4
    assert len(g["bucket_bytes"]) == 4
    # true schedule sizes preserved alongside scaled buffers
    assert sum(g["schedule_bucket_bytes"]) == pytest.approx(
        load_model_stats("gpt2_l_16_bfloat16").model_bytes, rel=0.01)


def test_emit_and_parse_roundtrip(dp_result, tmp_path):
    result, _ = dp_result
    out = tmp_path / "runs.jsonl"
    emit_result(result, path=str(out))
    emit_result(result, path=str(out))  # two records, same section

    recs = load_records(out, "dp")
    assert len(recs) == 2
    assert recs[0]["global"]["model"] == "gpt2_l_16_bfloat16"
    assert len(recs[0]["ranks"]) == 4

    df = get_metrics_dataframe(out, "dp")
    # rows = records x ranks x runs
    assert len(df) == 2 * 4 * 3
    assert {"runtime", "barrier_time", "rank", "run", "model"} <= set(df.columns)
    assert (df["runtime"] > 0).all()


def test_record_validation_catches_missing_rank(dp_result):
    from dlnetbench_tpu.metrics.parser import validate_record
    result, _ = dp_result
    rec = result_to_record(result)
    rec["ranks"] = rec["ranks"][:-1]
    with pytest.raises(ValueError, match="rank set"):
        validate_record(rec)


def test_estimate_runs():
    # mean of warmups after skipping first 2 = 0.1 -> 10 runs for 1s
    assert estimate_runs([5.0, 3.0, 0.1, 0.1], 1.0) == 10
    assert estimate_runs([0.5], 1.0) == 2       # falls back to last sample
    assert estimate_runs([0.1, 0.1, 0.0], 1.0) == 1


def test_cli_dp(tmp_path, eight_devices, capsys):
    from dlnetbench_tpu.cli import main
    out = tmp_path / "cli.jsonl"
    rc = main(["dp", "--model", "gpt2_l_16_bfloat16", "--num_buckets", "2",
               "-w", "1", "-r", "2", "--devices", "2",
               "--size_scale", "1e-5", "--time_scale", "1e-4",
               "--out", str(out)])
    assert rc == 0
    rec = json.loads(out.read_text().strip())
    assert rec["section"] == "dp" and rec["global"]["world_size"] == 2
    assert len(rec["ranks"][0]["runtimes"]) == 2


def test_cli_device_list_selection(tmp_path, eight_devices):
    """--devices accepts an arbitrary index list (reference -d 0,2,3,
    utils.hpp:62-71), not just a first-N count."""
    from dlnetbench_tpu.cli import main
    out = tmp_path / "cli.jsonl"
    rc = main(["dp", "--model", "gpt2_l_16_bfloat16", "--num_buckets", "2",
               "-w", "1", "-r", "1", "--devices", "1,3,5",
               "--size_scale", "1e-5", "--time_scale", "1e-4",
               "--no_topology", "--out", str(out)])
    assert rc == 0
    rec = json.loads(out.read_text().strip())
    assert rec["global"]["world_size"] == 3
    assert [r["device_id"] for r in rec["ranks"]] == [1, 3, 5]


def test_cli_device_list_rejects_bad_specs(eight_devices, capsys):
    from dlnetbench_tpu.cli import main
    for spec in ("0,2,99", "0,0", "abc", "0-3"):
        with pytest.raises(SystemExit):
            main(["dp", "--model", "gpt2_l_16_bfloat16", "--num_buckets",
                  "2", "--devices", spec, "--no_topology"])
        capsys.readouterr()


def test_cli_buffer_dtype_stats(eight_devices, tmp_path):
    """--buffer_dtype stats follows the stat file's Dtype (the reference's
    compile-time bf16/fp8 selection as a runtime switch): bfloat16 buffers
    halve the reported bucket bytes vs float32."""
    import json
    from dlnetbench_tpu.cli import main

    recs = {}
    for bd in ("float32", "stats"):
        out = tmp_path / f"{bd}.jsonl"
        rc = main(["dp", "--model", "gpt2_l_16_bfloat16", "--num_buckets",
                   "2", "--platform", "cpu", "-r", "1", "-w", "1",
                   "--size_scale", "1e-5", "--time_scale", "1e-4",
                   "--no_topology", "--buffer_dtype", bd,
                   "--out", str(out)])
        assert rc == 0
        recs[bd] = json.loads(out.read_text().strip())
    f32 = recs["float32"]["global"]["bucket_bytes"]
    bf16 = recs["stats"]["global"]["bucket_bytes"]  # stat file is bfloat16
    assert [b // 2 for b in f32] == list(bf16)


def test_barrier_time_uses_matched_compute_samples():
    """VERDICT r1 #6: barrier_time[i] must be full[i] - compute[i] with an
    ADJACENT (A/B-interleaved) compute sample, not full[i] minus an
    averaged compute time — drifting per-run durations would otherwise
    leak compute variance into the exposed-comm signal."""
    import time as _time
    from dlnetbench_tpu.proxies.base import ProxyConfig, StepBundle, run_proxy

    # Call counts include one warmup (full) / compile (compute) call each,
    # so measured pairs are (20, 18), (30, 28), (40, 38) ms: matched
    # subtraction gives ~2 ms for every run, while subtracting the MEAN
    # compute (28 ms) would give ~[0, 2, 12] ms
    calls = {"full": 0, "comp": 0}

    def full():
        _time.sleep(0.010 + 0.010 * calls["full"])
        calls["full"] += 1

    def compute():
        _time.sleep(0.008 + 0.010 * calls["comp"])
        calls["comp"] += 1

    bundle = StepBundle(full=full, compute=compute, comm=None,
                        global_meta={"proxy": "t", "world_size": 1})
    cfg = ProxyConfig(warmup=1, runs=3, measure_energy=False)
    res = run_proxy("t", bundle, cfg)
    barrier_ms = [t / 1000 for t in res.timers_us["barrier_time"]]
    assert len(barrier_ms) == 3
    # The mean-subtraction bug's signature is the SPREAD ([0, 2, 12]:
    # the per-run drift leaks in, blowing the top sample far past the
    # matched ~2 ms), so the top sample and the median carry the guard.
    # A single low sample is tolerated: under whole-suite host load a
    # sleep pair can inflate unevenly and one matched difference clamps
    # to ~0 (observed flake [0.0, 2.0, 2.8] on the loaded 2-core host).
    assert max(barrier_ms) < 6.0, (
        f"barrier_time {barrier_ms} — matched samples give ~2 ms each; "
        "a spread like [0, 2, 12] means a mean-compute subtraction")
    import statistics as _stats
    assert 1.0 < _stats.median(barrier_ms) < 6.0, (
        f"barrier_time {barrier_ms} — matched samples give ~2 ms each")
    assert sum(1 for b in barrier_ms if b <= 1.0) <= 1, (
        f"barrier_time {barrier_ms} — more than one collapsed sample is "
        "a subtraction bug, not host jitter")
