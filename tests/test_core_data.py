"""Unit tests for the data/config layer: cards, analytic param counts,
stat-file round-trip, and compatibility with reference-format files."""
import pytest

from dlnetbench_tpu.core.model_card import (
    ModelCard, arch_name_from_stats_name, list_model_cards, load_model_card)
from dlnetbench_tpu.core.model_stats import (
    ModelStats, parse_stats_text, save_model_stats, load_model_stats)
from dlnetbench_tpu.core import roofline
from dlnetbench_tpu.stats_gen import generate_stats

ALL_MODELS = ["gpt2_l", "gpt2_xl", "llama3_8b", "llama3_70b", "minerva_7b",
              "mixtral_8x7b", "vit_b", "vit_l", "vit_h"]


def test_card_registry_complete():
    assert set(ALL_MODELS) <= set(list_model_cards())


# Known published parameter counts (the reference gets these by downloading
# full HF weights, python/model_stats.py:144-145; we compute analytically and
# require ±3%).
PARAM_COUNTS = {
    "gpt2_l": 774e6, "gpt2_xl": 1.558e9,
    "llama3_8b": 8.03e9, "llama3_70b": 70.55e9,
    "minerva_7b": 7.40e9, "mixtral_8x7b": 46.70e9,
    "vit_b": 86.4e6, "vit_l": 304.4e6, "vit_h": 632.4e6,
}


@pytest.mark.parametrize("name,expected", sorted(PARAM_COUNTS.items()))
def test_analytic_param_counts(name, expected):
    card = load_model_card(name)
    got = card.num_params()
    assert abs(got - expected) / expected < 0.03, (name, got, expected)


def test_mixtral_non_expert_params():
    card = load_model_card("mixtral_8x7b")
    ne = card.non_expert_params()
    assert 1.4e9 < ne < 1.9e9  # reference records 1.70e9
    assert load_model_card("llama3_8b").non_expert_params() == 0


def test_gqa_dims():
    card = load_model_card("llama3_8b")
    assert card.kv_heads == 8 and card.head_dim == 128 and card.kv_dim == 1024
    vit = load_model_card("vit_b")
    assert vit.kv_heads == vit.num_heads  # MHA default


def test_arch_name_from_stats_name():
    assert arch_name_from_stats_name("llama3_8b_16_bfloat16") == "llama3_8b"
    assert arch_name_from_stats_name("mixtral_8x7b_128_float8") == "mixtral_8x7b"
    with pytest.raises(ValueError):
        arch_name_from_stats_name("nope")


def test_reference_format_card_loads(tmp_path):
    # a card with only the reference's base fields must load
    (tmp_path / "mini.json").write_text(
        '{"embed_dim": 64, "num_heads": 4, "ff_dim": 256, "seq_len": 128,'
        ' "num_decoder_blocks": 2, "memory_seq_len": 1}')
    card = load_model_card("mini", tmp_path)
    assert card.num_layers == 2 and card.vocab_size == 0


def test_stats_roundtrip(tmp_path):
    card = load_model_card("llama3_8b")
    stats = generate_stats(card, 16, "bfloat16", "tpu_v5p")
    save_model_stats(stats, tmp_path)
    loaded = load_model_stats("llama3_8b_16_bfloat16", tmp_path)
    assert loaded.model_size == stats.model_size
    assert loaded.forward_flops == stats.forward_flops
    assert loaded.dtype == stats.dtype and loaded.device == stats.device
    assert loaded.fwd_us == pytest.approx(stats.fwd_us, abs=0.01)
    assert loaded.bwd_us == pytest.approx(stats.bwd_us, abs=0.01)


def test_keyed_parse_tolerates_reorder_and_case():
    # reference files drifted in order and capitalization (SURVEY.md §7.4);
    # our parser must not care
    text = (
        "dtype:bfloat16\n"
        "non_expert_size:123\n"          # lowercased variant seen in reference
        "Model_Size:1000\n"
        "Backward_Flops:200\n"
        "Forward_Flops:100\n"
        "Average_Forward_Time (us):10.5\n"
        "Average_Backward_Time (us):21.0\n"
        "Batch_size:16\n"
        "Seq_len:128\n"
        "Embedded_dim:64\n"
    )
    s = parse_stats_text("x", text)
    assert s.non_expert_size == 123 and s.forward_flops == 100
    assert s.fwd_us == 10.5 and s.dtype == "bfloat16"


def test_parse_missing_key_raises():
    with pytest.raises(ValueError, match="missing required"):
        parse_stats_text("x", "Forward_Flops:1\n")


def test_roofline_monotonic():
    card = load_model_card("llama3_8b")
    t_v5e = roofline.forward_time_s(card, 16, "bfloat16", "tpu_v5e")
    t_v5p = roofline.forward_time_s(card, 16, "bfloat16", "tpu_v5p")
    t_b200 = roofline.forward_time_s(card, 16, "bfloat16", "b200")
    assert t_v5e > t_v5p > t_b200 > 0


def test_roofline_b200_crosscheck_order_of_magnitude():
    """Our family-correct formulas on the B200 preset must land within 2x of
    the reference's committed numbers (it undercounts SwiGLU FLOPs)."""
    card = load_model_card("llama3_8b")
    t = roofline.forward_time_s(card, 16, "bfloat16", "b200")
    ref = 0.938  # model_stats/llama3_8b_16_bfloat16.txt:5 (938 ms)
    assert ref / 2 < t < ref * 2


def test_moe_flops_bill_topk_only():
    mix = load_model_card("mixtral_8x7b")
    dense = ModelCard(name="d", embed_dim=mix.embed_dim, num_heads=mix.num_heads,
                      num_kv_heads=mix.num_kv_heads, ff_dim=mix.ff_dim,
                      seq_len=mix.seq_len, num_decoder_blocks=mix.num_decoder_blocks,
                      vocab_size=mix.vocab_size, gated_mlp=True)
    assert roofline.mlp_flops(mix, 16) == 2 * roofline.mlp_flops(dense, 16)
