"""Sequence-parallel proxy tests (ring attention, Ulysses) — the rebuild's
extension beyond the reference (SURVEY.md §5.7)."""
import pytest

from dlnetbench_tpu.core.model_card import load_model_card
from dlnetbench_tpu.core.model_stats import load_model_stats
from dlnetbench_tpu.proxies import ring_attention, ulysses
from dlnetbench_tpu.proxies.base import ProxyConfig, run_proxy

TINY = dict(size_scale=1e-6, time_scale=5e-5)
CFG = ProxyConfig(warmup=1, runs=2, **TINY)


def test_ring_attention(eight_devices):
    stats = load_model_stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    bundle = ring_attention.build(stats, card, CFG, sp=4, devices=eight_devices,
                                  max_layers=4)
    result = run_proxy("ring_attention", bundle, CFG)
    g = result.global_meta
    assert g["dp"] == 2 and g["sp"] == 4
    assert g["ring_hops_per_layer"] == 3
    assert g["seq_per_rank"] == card.seq_len // 4
    assert "ring_comm_time" in result.timers_us
    assert all(t > 0 for t in result.timers_us["runtimes"])


def test_ring_attention_bad_sp(eight_devices):
    stats = load_model_stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention.build(stats, card, CFG, sp=5, devices=eight_devices[:5])


def test_ulysses(eight_devices):
    stats = load_model_stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    bundle = ulysses.build(stats, card, CFG, sp=8, devices=eight_devices,
                           max_layers=4)
    result = run_proxy("ulysses", bundle, CFG)
    g = result.global_meta
    assert g["dp"] == 1 and g["sp"] == 8
    assert g["a2a_bytes"] > 0 and g["a2a_bytes"] % 8 == 0
    assert "a2a_comm_time" in result.timers_us


def test_ulysses_head_divisibility(eight_devices):
    stats = load_model_stats("vit_b_16_bfloat16")
    card = load_model_card("vit_b")  # 12 heads
    with pytest.raises(ValueError, match="heads"):
        ulysses.build(stats, card, CFG, sp=8, devices=eight_devices)
