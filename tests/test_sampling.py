"""On-device seeded sampling + lossless speculative sampling +
grammar-constrained decode (ISSUE 19): the key-derivation golden
values, the filter/inverse-CDF math, the grammar automaton, the
N-step==1-step bit-identity lock, the crash-shrink/slot-shape replay
property, the chi-square distribution-equality parity locks (plain
sampling AND the rejection-sampling spec verify), composition
(grammar+speculative, grammar+prefix_sharing), the record/merge
identity rules, and the CLI flag surface (mirrors
``make check-sampling``)."""
from __future__ import annotations

import copy
import dataclasses
import math
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.models import transformer as tfm
from dlnetbench_tpu.serving import sampling as SMP
from dlnetbench_tpu.serving.arrivals import ArrivalPlan
from dlnetbench_tpu.serving.scheduler import Engine, ServingConfig

pytestmark = [pytest.mark.sampling, pytest.mark.serving]

DATA = pathlib.Path(__file__).parent / "data"


def tiny_model(**over) -> tfm.TransformerConfig:
    kw = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
              ff_dim=64, num_layers=2, seq_len=64, gated=True,
              max_positions=0, dtype="float32")
    kw.update(over)
    return tfm.TransformerConfig(**kw)


def sampled_serving(**over) -> ServingConfig:
    kw = dict(slots=2, page_size=4, num_pages=64, max_seq_len=64,
              prefill_chunk=8, attn_impl="gather", warmup_requests=0,
              temperature=0.8, top_p=0.9, sample_seed=11)
    kw.update(over)
    return ServingConfig(**kw)


PLAN = ArrivalPlan(kind="poisson", rate_rps=100.0, num_requests=8,
                   seed=3, prompt_len=[4, 8], output_len=[4, 10])


def _streams(cfg, sc, params, plan=PLAN):
    eng = Engine(cfg, sc, params=params)
    completed, _ = eng.run(plan.sample())
    assert len(completed) == plan.num_requests
    return dict(eng.token_streams)


@pytest.fixture(scope="module")
def shared():
    cfg = tiny_model()
    return cfg, tfm.init_params(jax.random.key(0), cfg)


# ---------------------------------------------------------------------
# key derivation: the replay contract


def test_key_bits_golden_values():
    """The fmix32 key chain is a COMMITTED contract: records stamp
    (sample_seed, uid, position) as replay identity, so the mapping to
    draw bits must never silently change.  Golden values pin it."""
    assert SMP.key_bits(0, 0, 0, 0) == 0x37DD7702
    assert SMP.key_bits(7, 3, 11, 1) == 0xE540F20C
    # negative uids (warm rids) fold as two's-complement uint32
    assert SMP.key_bits(2**31, -2, 5, 3) == 0x74B4D306
    assert SMP.key_u01(7, 3, 11, 1) == (0xE540F20C >> 8) / float(1 << 24)


def test_key_u01_range_and_lane_independence():
    us = [SMP.key_u01(s, u, c, lane)
          for s in (0, 7, 2**31) for u in (-3, 0, 5)
          for c in (0, 1, 9) for lane in range(4)]
    assert all(0.0 <= x < 1.0 for x in us)
    # lanes decorrelate: same (seed, uid, counter), different lane
    assert len({SMP.key_bits(7, 1, 4, lane) for lane in range(4)}) == 4


def test_device_u01_matches_host():
    """The in-graph uint32 fmix32 twin computes EXACTLY the host
    chain — the property that lets tests and the re-queue path reason
    about device draws host-side."""
    cfg = SMP.check_sampling_config(temperature=1.0, top_k=0,
                                    top_p=1.0, sample_seed=7,
                                    grammar="")
    s = SMP.DeviceSampler(cfg, 16)
    uids = jnp.asarray(np.array([0, 3, -2, 41], np.int32))
    ctrs = jnp.asarray(np.array([0, 11, 5, 2], np.int32))
    for lane in (SMP.LANE_TOKEN, SMP.LANE_ACCEPT, SMP.LANE_RESID,
                 SMP.LANE_DRAFT):
        dev = np.asarray(s.u01(uids, ctrs, lane))
        host = [SMP.key_u01(7, int(u), int(c), lane)
                for u, c in zip(np.asarray(uids), np.asarray(ctrs))]
        np.testing.assert_allclose(dev, np.float32(host), rtol=0, atol=0)


# ---------------------------------------------------------------------
# the filter pipeline + inverse CDF


def _sampler(**kw):
    base = dict(temperature=1.0, top_k=0, top_p=1.0, sample_seed=0,
                grammar="")
    base.update(kw)
    return SMP.DeviceSampler(SMP.check_sampling_config(**base),
                             kw.pop("vocab", 8))


def test_filter_temperature_zero_is_onehot():
    s = SMP.DeviceSampler(SMP.SamplingConfig(temperature=0.0), 8)
    logits = jnp.asarray([[0.1, 2.0, -1.0, 0.0, 0.5, 0.2, 0.3, 0.4]])
    p = np.asarray(s.probs(logits))
    assert p[0, 1] == 1.0 and p[0].sum() == 1.0


def test_filter_top_k_keeps_ties():
    s = _sampler(top_k=2)
    # tokens 1 and 2 tie at the k-th value: BOTH survive (ties kept)
    logits = jnp.asarray([[0.0, 1.0, 1.0, 3.0, -2.0, 0.0, 0.0, 0.0]])
    p = np.asarray(s.probs(logits))[0]
    assert p[3] > p[1] == p[2] > 0
    assert p[0] == p[4] == p[5] == p[6] == p[7] == 0.0
    assert abs(p.sum() - 1.0) < 1e-6


def test_filter_top_p_keeps_top1_and_cuts_tail():
    s = _sampler(top_p=0.5)
    # one dominant token: top-p keeps it even though its mass alone
    # exceeds p (the exclusive-cumsum rule: cum < p at rank 0)
    logits = jnp.asarray([[10.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]])
    p = np.asarray(s.probs(logits))[0]
    assert p[0] == 1.0
    # near-uniform: only the prefix reaching half the mass survives
    logits = jnp.asarray([[1.0, 0.9, 0.8, 0.7, 0.6, 0.5, 0.4, 0.3]])
    p = np.asarray(s.probs(logits))[0]
    assert p[0] > 0 and p[7] == 0.0 and abs(p.sum() - 1.0) < 1e-6


def test_inverse_cdf_never_draws_zero_prob():
    s = _sampler()
    p = jnp.asarray([[0.0, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]])
    for u in (0.0, 0.3, 0.999999):
        tok = int(np.asarray(s.draw_from_probs(
            p, jnp.asarray([np.float32(u)]))))
        assert tok == 1, u


def test_draw_from_probs_matches_cdf_partition():
    s = _sampler()
    p = jnp.asarray([[0.25, 0.0, 0.5, 0.25, 0.0, 0.0, 0.0, 0.0]])
    picks = [int(np.asarray(s.draw_from_probs(
        p, jnp.asarray([np.float32(u)]))))
        for u in (0.0, 0.2, 0.26, 0.74, 0.76, 0.999)]
    assert picks == [0, 0, 2, 2, 3, 3]


# ---------------------------------------------------------------------
# the grammar automaton


def test_grammar_compile_and_never_empty_masks():
    g = SMP.compile_grammar("json", 64)
    assert g.num_states == 3 * SMP.JSON_MAX_DEPTH + 1
    assert g.mask.shape == (g.num_states, 64)
    assert g.trans.shape == (g.num_states, 64)
    # TOTAL automaton: every state admits at least one token (a
    # constrained slot can never strand with an all-masked vocab)
    assert g.mask.any(axis=1).all()
    assert ((g.trans >= 0) & (g.trans < g.num_states)).all()
    with pytest.raises(ValueError, match="grammar"):
        SMP.compile_grammar("yaml", 64)
    with pytest.raises(ValueError, match="vocab"):
        SMP.compile_grammar("json", 3)


def test_grammar_validate_stream():
    g = SMP.compile_grammar("json", 64)
    # class = token % 4: OPEN=0, CLOSE=1, SCALAR=2, COMMA=3
    assert SMP.validate_stream(g, [2, 6, 10])          # scalars at top
    assert SMP.validate_stream(g, [0, 2, 1])           # { v }
    assert SMP.validate_stream(g, [0, 2, 3, 2, 1])     # { v , v }
    assert SMP.validate_stream(g, [0, 4, 6, 1, 1])     # nest depth 2
    assert not SMP.validate_stream(g, [1])             # close at top
    assert not SMP.validate_stream(g, [0, 3])          # comma after {
    assert not SMP.validate_stream(g, [0, 2, 2])       # v v inside
    # prefixes are valid mid-stream (decode validates INCREMENTALLY)
    assert SMP.validate_stream(g, [0, 2])


def test_grammar_host_device_transitions_agree():
    cfg = SMP.check_sampling_config(temperature=0.8, top_k=0,
                                    top_p=1.0, sample_seed=0,
                                    grammar="json")
    s = SMP.DeviceSampler(cfg, 64)
    g = s.grammar
    rng = np.random.RandomState(0)
    state = np.int32(g.start)
    states = [int(state)]
    toks = []
    for _ in range(40):
        allowed = np.nonzero(g.mask[state])[0]
        tok = int(rng.choice(allowed))
        toks.append(tok)
        state = g.trans[state, tok]
        states.append(int(state))
    # device advance over the same stream lands on the same states
    dev = jnp.full((1,), g.start, jnp.int32)
    for tok, want in zip(toks, states[1:]):
        dev = s.advance(dev, jnp.asarray([tok], jnp.int32))
        assert int(np.asarray(dev)[0]) == want
    # and host_advance IS the same table
    st = g.start
    for tok, want in zip(toks, states[1:]):
        st = s.host_advance(st, tok)
        assert st == want


def test_grammar_mask_zeroes_probs():
    cfg = SMP.check_sampling_config(temperature=1.0, top_k=0,
                                    top_p=1.0, sample_seed=0,
                                    grammar="json")
    s = SMP.DeviceSampler(cfg, 8)
    logits = jnp.zeros((1, 8), jnp.float32)
    gstate = jnp.zeros((1,), jnp.int32)      # S0: CLOSE/COMMA illegal
    p = np.asarray(s.probs(logits, gstate))[0]
    assert p[1] == p[3] == p[5] == p[7] == 0.0   # classes 1 and 3
    assert p[0] > 0 and p[2] > 0 and abs(p.sum() - 1.0) < 1e-6


# ---------------------------------------------------------------------
# config validation (satellite f)


def test_check_sampling_config_errors():
    ok = SMP.check_sampling_config(temperature=0.8, top_k=4,
                                   top_p=0.9, sample_seed=1,
                                   grammar="json")
    assert ok.enabled
    assert not SMP.check_sampling_config(
        temperature=0.0, top_k=0, top_p=1.0, sample_seed=0,
        grammar="").enabled
    err = {"top_k": 0, "top_p": 1.0, "sample_seed": 0, "grammar": ""}
    with pytest.raises(ValueError, match="temperature"):
        SMP.check_sampling_config(temperature=-0.1, **err)
    with pytest.raises(ValueError, match="top_k"):
        SMP.check_sampling_config(temperature=0.8, top_k=-1,
                                  top_p=1.0, sample_seed=0, grammar="")
    for bad_p in (0.0, -0.5, 1.5):
        with pytest.raises(ValueError, match="top_p"):
            SMP.check_sampling_config(temperature=0.8, top_k=0,
                                      top_p=bad_p, sample_seed=0,
                                      grammar="")
    with pytest.raises(ValueError, match="grammar"):
        SMP.check_sampling_config(temperature=0.8, top_k=0, top_p=1.0,
                                  sample_seed=0, grammar="yaml")
    # filters without temperature would silently do nothing — refuse
    with pytest.raises(ValueError, match="temperature"):
        SMP.check_sampling_config(temperature=0.0, top_k=4, top_p=1.0,
                                  sample_seed=0, grammar="")
    with pytest.raises(ValueError, match="temperature"):
        SMP.check_sampling_config(temperature=0.0, top_k=0, top_p=0.9,
                                  sample_seed=0, grammar="")
    # speculative sampling needs drafter probs (ngram has none)
    with pytest.raises(ValueError, match="drafter probs"):
        SMP.check_sampling_config(temperature=0.8, top_k=0, top_p=1.0,
                                  sample_seed=0, grammar="",
                                  speculative=True, drafter="ngram")
    # ... and the truncated drafter composes fine
    SMP.check_sampling_config(temperature=0.8, top_k=0, top_p=1.0,
                              sample_seed=0, grammar="json",
                              speculative=True, drafter="truncated")


def test_engine_level_validation_mirrors_parser_level():
    """The SAME consolidated validator runs at arg-parse time
    (ServingConfig.validate) and at engine build — a config that dodges
    the CLI cannot reach a compiled program."""
    cfg = tiny_model()
    params = tfm.init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="top_p"):
        Engine(cfg, sampled_serving(top_p=1.5), params=params)
    with pytest.raises(ValueError, match="drafter probs"):
        Engine(cfg, sampled_serving(speculative=True, drafter="ngram",
                                    multi_step_n=8), params=params)


# ---------------------------------------------------------------------
# the tentpole locks: bit-identity + replay


def test_nstep_bit_identical_to_1step(shared):
    """The acceptance-criteria lock: fused N-step sampled decode emits
    EXACTLY the classic 1-step engine's tokens — the draw key is
    (seed, uid, position), so N is a pure perf knob."""
    cfg, params = shared
    s1 = _streams(cfg, sampled_serving(multi_step_n=1), params)
    s8 = _streams(cfg, sampled_serving(multi_step_n=8), params)
    s3 = _streams(cfg, sampled_serving(multi_step_n=3), params)
    assert s1 == s8 == s3
    # ... and under grammar constraint too (STATE_GRAMMAR carry vs the
    # classic engine's host-side transitions)
    g1 = _streams(cfg, sampled_serving(top_p=1.0, grammar="json",
                                       multi_step_n=1), params)
    g8 = _streams(cfg, sampled_serving(top_p=1.0, grammar="json",
                                       multi_step_n=8), params)
    assert g1 == g8 and g1 != s1


def test_replay_is_slot_shape_invariant(shared):
    """The crash-shrink re-queue property: draws key by (seed, uid,
    position) — never by slot index or engine geometry — so a request
    re-queued into a REBUILT engine (different slot count, different
    placement) replays its token stream identically."""
    cfg, params = shared
    a = _streams(cfg, sampled_serving(slots=2), params)
    b = _streams(cfg, sampled_serving(slots=4), params)
    c = _streams(cfg, sampled_serving(slots=4), params)  # fresh build
    assert a == b == c
    # different sample_seed = a different (refusing-to-merge) run
    d = _streams(cfg, sampled_serving(slots=2, sample_seed=12), params)
    assert d != a


def test_grammar_streams_validate_everywhere(shared):
    """Constrained outputs validate by construction on every engine
    shape — classic, fused, speculative (out-of-grammar drafts
    auto-reject via p(t) = 0), and with prefix sharing on."""
    cfg, params = shared
    g = SMP.compile_grammar("json", cfg.vocab_size)
    for kw in (dict(multi_step_n=1),
               dict(multi_step_n=8),
               dict(multi_step_n=8, speculative=True, spec_k=3,
                    drafter="truncated", drafter_layers=1),
               dict(multi_step_n=1, prefix_sharing=True)):
        ss = _streams(cfg, sampled_serving(top_p=1.0, grammar="json",
                                           **kw), params)
        for rid, toks in ss.items():
            assert SMP.validate_stream(g, toks), (kw, rid)


# ---------------------------------------------------------------------
# distribution equality: the chi-square parity locks


def _chi_ok(counts, probs):
    stat, df = SMP.chi_square(counts, probs)
    crit = SMP.chi_square_critical(df)
    return stat < crit, (stat, df, crit)


def test_chi_square_helper_math():
    # pooled bins: expected < 5 merge, df = pooled bins - 1
    counts = np.array([50, 48, 2, 0])
    probs = np.array([0.49, 0.49, 0.01, 0.01])
    stat, df = SMP.chi_square(counts, probs)
    # ascending pooling folds exp = [1, 1] into the next bin: 2 bins
    assert df == 1 and stat >= 0.0
    # well-fed bins are left alone: exp = [40, 40, 10, 10] -> df = 3
    _, df4 = SMP.chi_square(np.array([38, 41, 11, 10]),
                            np.array([0.4, 0.4, 0.1, 0.1]))
    assert df4 == 3
    # Wilson–Hilferty critical grows with df and sits near the
    # textbook p=0.001 values (df=10 -> 29.59)
    assert abs(SMP.chi_square_critical(10) - 29.59) < 0.7
    assert SMP.chi_square_critical(20) > SMP.chi_square_critical(5)


def test_sampler_draws_match_filtered_distribution():
    """Distribution-equality lock #1: tokens drawn by the on-device
    sampler over many uids follow EXACTLY the filtered distribution
    the record's (temperature, top_p) identity describes."""
    cfg = SMP.check_sampling_config(temperature=0.8, top_k=0,
                                    top_p=0.9, sample_seed=5,
                                    grammar="")
    s = SMP.DeviceSampler(cfg, 16)
    rng = np.random.RandomState(1)
    logits_row = rng.randn(16).astype(np.float32)
    n = 4096
    logits = jnp.asarray(np.tile(logits_row, (n, 1)))
    uids = jnp.asarray(np.arange(n, dtype=np.int32))
    ctrs = jnp.full((n,), 9, jnp.int32)
    toks = np.asarray(s.draw_tokens(logits, uids, ctrs))
    p = np.asarray(s.probs(jnp.asarray(logits_row[None])))[0]
    counts = np.bincount(toks, minlength=16)
    assert counts[p == 0.0].sum() == 0    # filtered tokens never drawn
    ok, info = _chi_ok(counts, p)
    assert ok, info


def test_spec_rejection_sampling_is_lossless():
    """Distribution-equality lock #2 (the tentpole's correctness
    core): the rejection-sampling verify rule — draft from q, accept
    with prob min(1, p/q), residual-resample on reject — emits tokens
    distributed EXACTLY as the target distribution p, for a drafter q
    it visibly disagrees with.  Mirrors speculative.py's in-loop math
    op for op (same lanes, same counters)."""
    cfg = SMP.check_sampling_config(temperature=0.8, top_k=0,
                                    top_p=1.0, sample_seed=5,
                                    grammar="")
    s = SMP.DeviceSampler(cfg, 16)
    rng = np.random.RandomState(2)
    tlog = rng.randn(16).astype(np.float32)
    dlog = rng.randn(16).astype(np.float32)        # a DIFFERENT dist
    n = 4096
    p = s.probs(jnp.asarray(np.tile(tlog, (n, 1))))
    q = s.probs(jnp.asarray(np.tile(dlog, (n, 1))))
    uids = jnp.asarray(np.arange(n, dtype=np.int32))
    pos = jnp.full((n,), 7, jnp.int32)
    rows = jnp.arange(n)
    # draft (LANE_DRAFT at the draft position), accept test, residual
    d = s.draw_from_probs(q, s.u01(uids, pos, SMP.LANE_DRAFT))
    u_acc = s.u01(uids, pos, SMP.LANE_ACCEPT)
    accept = u_acc * q[rows, d] < p[rows, d]
    resid = jnp.maximum(p - q, 0.0)
    z = jnp.sum(resid, axis=-1, keepdims=True)
    rdist = jnp.where(z > 0, resid / jnp.maximum(z, 1e-30), p)
    r = s.draw_from_probs(rdist, s.u01(uids, pos, SMP.LANE_RESID))
    emitted = np.asarray(jnp.where(accept, d, r))
    counts = np.bincount(emitted, minlength=16)
    ok, info = _chi_ok(counts, np.asarray(p)[0])
    assert ok, info
    # the drafter q must NOT pass the same test (the lock has teeth)
    ok_q, _ = _chi_ok(counts, np.asarray(q)[0])
    assert not ok_q
    # T=0 degenerates to exact-match greedy: only the argmax draft
    # survives the strict accept rule u*q < p
    s0 = SMP.DeviceSampler(SMP.SamplingConfig(temperature=0.0), 16)
    p0 = s0.probs(jnp.asarray(np.tile(tlog, (4, 1))))
    q0 = s0.probs(jnp.asarray(np.tile(dlog, (4, 1))))
    d0 = jnp.asarray([int(np.argmax(dlog))] * 4)
    u0 = s0.u01(jnp.arange(4, dtype=jnp.int32), jnp.zeros(4, jnp.int32),
                SMP.LANE_ACCEPT)
    acc0 = np.asarray(u0 * q0[jnp.arange(4), d0] < p0[jnp.arange(4), d0])
    assert not acc0.any()              # argmaxes differ -> all reject


def test_spec_engine_first_draw_matches_unfused(shared):
    """The end-to-end half of lock #2, on the only comparison that is
    statistically sound for ONE seed: the speculative engine's FIRST
    emitted token per request.  At the first generated position the
    target context is identical in both engines, so across many
    requests the spec engine's first draws and the non-spec engine's
    first draws are two samples of the same per-request distribution.
    (Full-stream equality can't hold pointwise — accept/residual lanes
    consume different randomness — which is exactly why losslessness
    is a DISTRIBUTIONAL claim, locked per-op by
    test_spec_rejection_sampling_is_lossless.)"""
    cfg, params = shared
    plan = ArrivalPlan(kind="poisson", rate_rps=500.0,
                       num_requests=24, seed=9, prompt_len=[4, 6],
                       output_len=[8, 12])
    ns = _streams(cfg, sampled_serving(top_p=1.0, multi_step_n=8),
                  params, plan)
    sp = _streams(cfg, sampled_serving(top_p=1.0, multi_step_n=8,
                                       speculative=True, spec_k=3,
                                       drafter="truncated",
                                       drafter_layers=1),
                  params, plan)
    assert sorted(ns) == sorted(sp) and len(ns) == 24
    firsts_ns = {rid: toks[0] for rid, toks in ns.items()}
    firsts_sp = {rid: toks[0] for rid, toks in sp.items()}
    # same seeded plan, same prompts: both engines draw first tokens
    # from the same per-request target distribution; with a vocab this
    # small most requests must agree outright, and every emitted token
    # is in-vocab
    agree = sum(firsts_ns[r] == firsts_sp[r] for r in firsts_ns)
    assert agree >= len(firsts_ns) // 2, (agree, firsts_ns, firsts_sp)
    assert all(0 <= t < cfg.vocab_size
               for toks in sp.values() for t in toks)


# ---------------------------------------------------------------------
# record identity + merge (satellite b)


def test_sampling_fixture_roundtrip():
    """The committed sampled+speculative+grammar record flows parser
    -> merge -> serving_summary, with the ``sampling`` identity block
    and the volatile acceptance curve intact."""
    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               validate_record)
    records = load_records(DATA / "record_sampling.jsonl")
    assert len(records) == 1
    rec = records[0]
    validate_record(rec)
    g = rec["global"]
    assert g["sampling"] == {"temperature": 0.8, "top_k": 0,
                             "top_p": 0.95, "sample_seed": 7,
                             "grammar": "json"}
    curve = g["spec_acceptance_by_temp"]
    assert len(curve) >= 1
    assert all(0.0 <= pt["acceptance_rate"] <= 1.0 for pt in curve)
    merged = merge_records(records)   # single-process identity
    validate_record(merged)
    assert merged["global"]["sampling"]["sample_seed"] == 7
    row = serving_summary([merged]).iloc[0]
    assert row["completed"] == 6


def test_sampling_merge_identity_vs_volatile():
    """``sampling`` is run IDENTITY: mismatched temperature or seed
    refuses to merge (mixing draw keys would average incomparable
    streams).  The acceptance curve is a MEASUREMENT: differing per
    process is fine."""
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import load_records
    base = load_records(DATA / "record_sampling.jsonl")[0]
    a, b = copy.deepcopy(base), copy.deepcopy(base)
    a["global"]["num_processes"] = b["global"]["num_processes"] = 2
    a["global"]["world_size"] = b["global"]["world_size"] = 2
    b["process"] = 1
    b["ranks"] = [dict(r, process_index=1, rank=1) for r in b["ranks"]]
    b["global"]["spec_acceptance_by_temp"] = [
        {"temperature": 0.8, "acceptance_rate": 0.99}]  # volatile: ok
    merged = merge_records([a, b])
    assert merged["global"]["sampling"]["temperature"] == 0.8

    c = copy.deepcopy(b)
    c["global"]["sampling"] = dict(c["global"]["sampling"],
                                   temperature=1.2)
    with pytest.raises(ValueError, match="sampling"):
        merge_records([a, c])
    d = copy.deepcopy(b)
    d["global"]["sampling"] = dict(d["global"]["sampling"],
                                   sample_seed=8)
    with pytest.raises(ValueError, match="sampling"):
        merge_records([a, d])


def test_pre_sampling_records_still_parse():
    """v1 and pre-sampling serving records parse byte-identically —
    greedy records never grew a ``sampling`` key."""
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe,
                                               validate_record)
    for name in ("record_v1.jsonl", "record_serving.jsonl"):
        recs = load_records(DATA / name)
        for rec in recs:
            validate_record(rec)
            assert "sampling" not in rec["global"], name
        records_to_dataframe(recs)


# ---------------------------------------------------------------------
# CLI flag surface (satellite a)


def _serve_argv(*extra):
    return ["serve", "--arrival",
            '{"kind": "poisson", "rate_rps": 100, "num_requests": 2, '
            '"seed": 0, "prompt_len": [4, 8], "output_len": [2, 4]}',
            *extra]


def test_cli_sampling_flag_validation(capsys):
    from dlnetbench_tpu import cli
    # invalid knobs die as tidy parser errors (exit code 2), never as
    # engine-build tracebacks
    for argv, needle in (
            (_serve_argv("--top_p", "1.5", "--temperature", "0.8"),
             "top_p"),
            (_serve_argv("--sample_top_k", "4"), "temperature"),
            (_serve_argv("--temperature", "-1"), "temperature"),
            (_serve_argv("--temperature", "0.8", "--speculative",
                         "--drafter", "ngram", "--multi_step_n", "8"),
             "drafter probs"),
            (_serve_argv("--grammar", "yaml"), "invalid choice")):
        with pytest.raises(SystemExit) as exc:
            cli.main(argv)
        assert exc.value.code == 2, argv
        assert needle in capsys.readouterr().err, argv


def test_cli_sampling_run_with_grammar_and_spec(tmp_path, capsys):
    """The allowed compositions parse AND run: grammar+speculative and
    grammar+prefix_sharing are first-class, and the record lands with
    the sampling identity."""
    import json

    from dlnetbench_tpu import cli
    out = tmp_path / "rec.jsonl"
    rc = cli.main(_serve_argv(
        "--temperature", "0.8", "--sample_seed", "3",
        "--grammar", "json", "--speculative", "--drafter", "truncated",
        "--multi_step_n", "8", "--prefix_sharing",
        "--slots", "2", "--page_size", "4", "--num_pages", "32",
        "--max_seq_len", "32", "--vocab", "64", "--embed", "32",
        "--ff", "64", "--out", str(out)))
    assert rc == 0
    rec = json.loads(out.read_text().splitlines()[-1])
    assert rec["global"]["sampling"]["grammar"] == "json"
    assert rec["global"]["spec_acceptance_by_temp"]
