"""The persistent seeded autotuner (ISSUE 9, dlnetbench_tpu/tuning/).

Covers, per the issue's satellite checklist:

* TuningDB durability — torn/partial-write recovery (truncate
  mid-record, reopen), newer-schema refusal, the concurrent writer
  claim/retry race (the ``test_native_build.py`` wipe-race pattern);
* the seeded search — deterministic candidate order, band-aware
  pruning, winner committed with its measured band;
* the consult layer — disabled-by-default bit-identity (every tunable
  site reproduces today's frozen defaults on an empty/absent DB),
  freeze-after-first-consult, explicit values winning, loud rejection
  of inapplicable DB configs;
* the committed fixture ``tests/data/tuning_db.jsonl`` round-tripped
  consult -> emit -> parser -> merge -> bandwidth;
* the ``python -m dlnetbench_tpu.tuning tune`` CLI end to end on a
  tiny CPU shape (2 candidates, seconds — the ``make check-tuning``
  lane);
* the ``DLNB_FLASH_BWD_BLOCKS`` freeze check, directly (it was only
  exercised indirectly before), with the old -> new values named.
"""
from __future__ import annotations

import json
import shutil
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from dlnetbench_tpu import tuning
from dlnetbench_tpu.tuning.db import TuningDB

pytestmark = pytest.mark.tuning

FIXTURE = Path(__file__).parent / "data" / "tuning_db.jsonl"


@pytest.fixture(autouse=True)
def _clean_tuning_state(monkeypatch):
    """Every test starts disabled with an empty consult cache, and
    leaves no process-global consult log behind for unrelated tests."""
    monkeypatch.delenv(tuning.ENV_DB_DIR, raising=False)
    tuning.reset()
    yield
    tuning.reset()


def _enable(monkeypatch, tmp_path, with_fixture: bool = False) -> Path:
    root = tmp_path / "tdb"
    root.mkdir(exist_ok=True)
    if with_fixture:
        shutil.copy(FIXTURE, root / tuning.DB_FILENAME)
    monkeypatch.setenv(tuning.ENV_DB_DIR, str(root))
    tuning.reset()
    return root


# ------------------------------------------------------------------ DB

def test_db_put_get_roundtrip(tmp_path):
    db = TuningDB(tmp_path)
    rec = db.put("op", "k=1", "cpu", {"block": 64},
                 band={"value": 1.0, "best": 0.9, "band": [0.9, 1.1],
                       "n": 3},
                 meta={"seed": 7})
    assert rec["schema"] == tuning.SCHEMA_VERSION
    got = db.get("op", "k=1", "cpu")
    assert got["config"] == {"block": 64}
    assert got["band"]["n"] == 3 and got["meta"]["seed"] == 7
    # replace-in-place: same key overwrites, no duplicate lines
    db.put("op", "k=1", "cpu", {"block": 32})
    assert db.get("op", "k=1", "cpu")["config"] == {"block": 32}
    assert len(db.load()) == 1


def test_db_torn_write_recovery(tmp_path):
    """Truncate mid-record and reopen: the damaged line is skipped, the
    intact records stay readable, and a later put() heals the file."""
    db = TuningDB(tmp_path)
    db.put("op", "k=1", "cpu", {"block": 64})
    db.put("op", "k=2", "cpu", {"block": 128})
    raw = db.path.read_bytes()
    db.path.write_bytes(raw[:-20])  # tear the LAST record mid-json
    recs = db.load()
    assert len(recs) == 1
    assert ("op", "k=1", "cpu") in recs
    # write path still works on the torn file, and re-persists clean
    db.put("op", "k=3", "cpu", {"block": 256})
    assert len(db.load()) == 2
    for line in db.path.read_text().splitlines():
        json.loads(line)  # every surviving line is whole again


def test_db_newer_schema_refused(tmp_path):
    db = TuningDB(tmp_path)
    db.path.parent.mkdir(parents=True, exist_ok=True)
    db.path.write_text(json.dumps(
        {"schema": tuning.SCHEMA_VERSION + 1, "op": "op", "key": "k",
         "hw": "cpu", "config": {}}) + "\n")
    with pytest.raises(ValueError, match="newer than this build"):
        db.load()


class _FlakyLock:
    """Lock-dir stand-in emulating a concurrent writer that holds the
    lock for the first ``held`` rounds (the test_native_build.py
    wipe-race pattern): mkdir sees it exist, stat sees it already
    released.  After that the real lock claims cleanly."""

    def __init__(self, real: Path, held: int):
        self.real = real
        self.held = held
        self.attempt = 0

    def mkdir(self):
        self.attempt += 1
        if self.attempt <= self.held:
            raise FileExistsError(self)   # the racer holds it...
        self.real.mkdir()

    def stat(self):
        if self.attempt <= self.held:
            raise FileNotFoundError(self)  # ...and released under us
        return self.real.stat()

    def rmdir(self):
        self.real.rmdir()


def test_db_claim_retries_after_concurrent_release(tmp_path):
    target = tmp_path / "lock"
    TuningDB._claim(_FlakyLock(target, held=2))
    assert target.is_dir()


def test_db_claim_gives_up_after_bounded_attempts(tmp_path):
    flaky = _FlakyLock(tmp_path / "never", held=10**9)
    with pytest.raises(RuntimeError, match="could not claim"):
        TuningDB._claim(flaky, attempts=3, wait_s=0.0)
    assert flaky.attempt == 3  # bounded, not an infinite spin


def test_db_claim_steals_stale_lock(tmp_path):
    lock = tmp_path / "lock"
    lock.mkdir()
    TuningDB._claim(lock, attempts=3, wait_s=0.0, stale_s=0.0)
    assert lock.is_dir()  # stolen from the 'crashed' writer, re-held


# -------------------------------------------------------------- search

def test_seeded_order_deterministic_and_seed_sensitive():
    a = tuning.seeded_order(8, seed=3)
    assert a == tuning.seeded_order(8, seed=3)
    assert sorted(a) == list(range(8))
    assert a != tuning.seeded_order(8, seed=4)


def test_search_elects_min_median_and_commits_band(tmp_path):
    times = {"a": [3.0, 3.1, 3.2], "b": [1.0, 1.1, 1.2],
             "c": [2.0, 2.1, 2.2]}
    calls = {k: 0 for k in times}

    def measure(cfg):
        name = cfg["name"]
        t = times[name][calls[name] % 3]
        calls[name] += 1
        return t

    db = TuningDB(tmp_path)
    res = tuning.tune_and_commit(
        db, "op", "k", "cpu",
        [{"name": "a"}, {"name": "b"}, {"name": "c"}], measure,
        seed=0, rounds=3, k=4)
    assert res["config"] == {"name": "b"}
    assert res["band"]["value"] == 1.1 and res["band"]["n"] == 3
    rec = db.get("op", "k", "cpu")
    assert rec["config"] == {"name": "b"}
    assert rec["band"]["band"] == [1.0, 1.2]
    assert rec["meta"]["reps_per_fence"] == 4


def test_search_prunes_band_disjoint_losers():
    """A candidate whose best-of-two samples lands strictly above the
    incumbent's whole band is cut after two rounds (never one — a
    single draw can hit the slow tunnel mode); a band-ambiguous one
    gets its full rounds."""
    seen = []
    # fast's samples SPREAD (band [1.0, 1.2]); slow's best-of-two is
    # strictly above that whole band (pruned); close lands inside it
    # (band-ambiguous -> full rounds)
    seqs = {"fast": [1.0, 1.2, 1.1], "slow": [9.0, 9.0, 9.0],
            "close": [1.15, 1.15, 1.15]}

    def measure(cfg):
        name = cfg["name"]
        seen.append(name)
        return seqs[name][seen.count(name) - 1]

    # seeded_order(3, seed=0) fixes visit order; find a seed where
    # 'fast' is visited first so the pruning logic is actually hit
    import itertools
    for seed in itertools.count():
        order = tuning.seeded_order(3, seed)
        if order[0] == 0:
            break
    res = tuning.run_search(
        [{"name": "fast"}, {"name": "slow"}, {"name": "close"}],
        measure, seed=seed, rounds=3)
    assert res["config"] == {"name": "fast"}
    assert res["pruned"] == 1
    assert seen.count("slow") == 2      # cut after two samples, not 1
    assert seen.count("close") == 3     # band-ambiguous: full rounds
    pruned = [t for t in res["trials"] if t["pruned"]]
    assert len(pruned) == 1 and pruned[0]["config"]["name"] == "slow"
    assert pruned[0]["summary"]["n"] == 2


def test_search_single_slow_draw_does_not_prune():
    """The exact hazard stats.py documents: the true winner's FIRST
    draw hits the slow mode.  Two-sample pruning lets its later rounds
    elect it anyway."""
    seen = []
    seqs = {"incumbent": [1.0, 1.1, 1.2],
            "winner": [1.5, 0.9, 0.9]}   # slow-mode first draw

    def measure(cfg):
        name = cfg["name"]
        seen.append(name)
        return seqs[name][seen.count(name) - 1]

    import itertools
    for seed in itertools.count():
        if tuning.seeded_order(2, seed) == [0, 1]:
            break
    res = tuning.run_search(
        [{"name": "incumbent"}, {"name": "winner"}], measure,
        seed=seed, rounds=3)
    assert res["config"] == {"name": "winner"}
    assert res["pruned"] == 0
    assert res["band"]["value"] == 0.9


def test_search_refuses_empty_candidates():
    with pytest.raises(ValueError, match="no candidates"):
        tuning.run_search([], lambda cfg: 1.0)


# ---------------------------------------- consult layer: defaults & DB

def test_disabled_consult_returns_default_and_logs_nothing():
    out = tuning.consult("op", "k", {"block": 64})
    assert out == {"block": 64}
    assert tuning.provenance() is None
    assert not tuning.enabled()


def test_consult_hit_miss_and_freeze(monkeypatch, tmp_path):
    root = _enable(monkeypatch, tmp_path)
    TuningDB(root).put("op", "k", tuning.hw_key(), {"block": 32},
                       band={"value": 1.0, "best": 1.0,
                             "band": [1.0, 1.0], "n": 3})
    assert tuning.consult("op", "k", {"block": 64}) == {"block": 32}
    miss = tuning.consult("op", "other", {"block": 64})
    assert miss == {"block": 64}
    prov = tuning.provenance()
    assert prov["hits"] == 1 and prov["misses"] == 1
    assert prov["sites"]["op|k"]["hit"] is True
    assert prov["sites"]["op|k"]["tuned_band"]["n"] == 3
    assert prov["sites"]["op|other"]["hit"] is False
    # freeze-after-first-consult: a DB edit after the first consult is
    # invisible for the process lifetime (the jit-cache hazard)
    TuningDB(root).put("op", "k", tuning.hw_key(), {"block": 8})
    assert tuning.consult("op", "k", {"block": 64}) == {"block": 32}


def test_consult_rejects_inapplicable_db_config(monkeypatch, tmp_path):
    root = _enable(monkeypatch, tmp_path)
    TuningDB(root).put("op", "k", tuning.hw_key(), {"block": -5})

    def check(cfg):
        if cfg["block"] < 1:
            raise ValueError(f"block={cfg['block']} is not positive")

    with pytest.raises(ValueError, match="inapplicable"):
        tuning.consult("op", "k", {"block": 64}, validate=check)


# ------------------------------- tunable sites: empty-DB bit-identity

def test_fused_matmul_empty_db_bit_identical(monkeypatch, tmp_path):
    """With an EMPTY DB enabled, fused_matmul runs the frozen default
    blocks and produces bit-identical int8 results to the explicit-
    default call; the consult is logged as a miss."""
    from dlnetbench_tpu.ops import quantized_matmul as qmm

    x = jax.random.normal(jax.random.key(0), (64, 64), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (64, 64), jnp.bfloat16)
    wq, sw = qmm.quantize_tensor(w, "int8")
    sx = qmm.scale_from_amax(jnp.max(jnp.abs(x.astype(jnp.float32))),
                             "int8")
    baseline = qmm.fused_matmul(x, wq, sw, sx, fmt="int8",
                                **qmm.DEFAULT_BLOCKS)
    _enable(monkeypatch, tmp_path)   # empty DB
    got = qmm.fused_matmul(x, wq, sw, sx, fmt="int8")
    assert jnp.array_equal(baseline, got)
    prov = tuning.provenance()
    assert prov["hits"] == 0 and prov["misses"] == 1


def test_fused_matmul_db_hit_changes_blocks_not_math(monkeypatch,
                                                     tmp_path):
    """A DB hit reroutes the grid blocks (provenance says so) and the
    int8 result stays EXACTLY equal — tiled int32 accumulation is
    associative, so tuning can never change quantized numerics."""
    from dlnetbench_tpu.ops import quantized_matmul as qmm

    x = jax.random.normal(jax.random.key(0), (64, 64), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (64, 64), jnp.bfloat16)
    wq, sw = qmm.quantize_tensor(w, "int8")
    sx = qmm.scale_from_amax(jnp.max(jnp.abs(x.astype(jnp.float32))),
                             "int8")
    baseline = qmm.fused_matmul(x, wq, sw, sx, fmt="int8",
                                **qmm.DEFAULT_BLOCKS)
    root = _enable(monkeypatch, tmp_path)
    key = tuning.params.quantized_matmul_key(64, 64, 64, "int8", x.dtype)
    TuningDB(root).put("quantized_matmul", key, tuning.hw_key(),
                       {"block_m": 32, "block_n": 64, "block_k": 32})
    got = qmm.fused_matmul(x, wq, sw, sx, fmt="int8")
    assert jnp.array_equal(baseline, got)
    assert tuning.provenance()["hits"] == 1


def test_spmd_config_resolution(monkeypatch, tmp_path):
    """None knobs resolve to the frozen defaults on an empty DB, to the
    DB's answer on a hit (only when the knob's mode is LIVE), and
    explicit values always win."""
    from dlnetbench_tpu.models.spmd import SpmdConfig

    cfg = SpmdConfig(tp_overlap="decomposed", grad_sync="bucketed")
    r = cfg.resolve_tuned(2, 1, 2)
    assert r.tp_overlap_chunks == 2 and r.grad_bucket_layers == 1
    root = _enable(monkeypatch, tmp_path)
    TuningDB(root).put(
        "tp_overlap_chunks",
        tuning.params.tp_overlap_chunks_key(cfg.embed_dim, cfg.ff_dim,
                                            cfg.seq_len, 2, cfg.dtype),
        tuning.hw_key(), {"chunks": 4})
    r = cfg.resolve_tuned(2, 1, 2)
    assert r.tp_overlap_chunks == 4     # DB answered
    assert r.grad_bucket_layers == 1    # miss -> frozen default
    explicit = SpmdConfig(tp_overlap="decomposed", grad_sync="bucketed",
                          tp_overlap_chunks=8, grad_bucket_layers=2)
    r = explicit.resolve_tuned(2, 1, 2)
    assert r.tp_overlap_chunks == 8 and r.grad_bucket_layers == 2
    # INERT knobs never consult: tp_overlap='none'/grad_sync=
    # 'monolithic' resolve to the defaults with no provenance logged,
    # even with the same DB entry present — a 'hit' on a knob the
    # compiled program ignores would stamp tuned provenance onto a
    # bit-identical-to-untuned run
    tuning.reset()
    import os
    assert os.environ.get(tuning.ENV_DB_DIR)  # still enabled
    r = SpmdConfig().resolve_tuned(2, 1, 2)
    assert r.tp_overlap_chunks == 2 and r.grad_bucket_layers == 1
    assert tuning.provenance() is None


def test_flash_blocks_empty_db_bit_identical(monkeypatch, tmp_path):
    """Flash attention fwd+grad on an empty enabled DB is bit-identical
    to the disabled path (same _pick_block defaults)."""
    import importlib
    flash_attention = importlib.import_module(
        "dlnetbench_tpu.ops.flash_attention").flash_attention

    q = jax.random.normal(jax.random.key(0), (1, 256, 2, 128),
                          jnp.float32)
    k = jax.random.normal(jax.random.key(1), (1, 256, 2, 128),
                          jnp.float32)
    v = jax.random.normal(jax.random.key(2), (1, 256, 2, 128),
                          jnp.float32)

    def loss(q_, k_, v_):
        return flash_attention(q_, k_, v_).astype(jnp.float32).sum()

    base, base_grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    _enable(monkeypatch, tmp_path)
    got, got_grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert jnp.array_equal(base, got)
    for b, g in zip(base_grads, got_grads):
        assert jnp.array_equal(b, g)
    prov = tuning.provenance()
    assert prov and prov["hits"] == 0
    assert any(s.startswith("flash_fwd|") for s in prov["sites"])
    assert any(s.startswith("flash_bwd|") for s in prov["sites"])


def test_flash_tuned_blocks_must_divide_seq(monkeypatch, tmp_path):
    """An inapplicable DB block config fails LOUD at the flash site
    (the truncated-grid hazard the env knob already guards)."""
    import importlib
    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")

    q = jax.random.normal(jax.random.key(0), (1, 256, 2, 128),
                          jnp.float32)
    root = _enable(monkeypatch, tmp_path)
    key = tuning.params.flash_fwd_key(1, 256, 2, 2, 128, True, q.dtype)
    TuningDB(root).put("flash_fwd", key, tuning.hw_key(),
                       {"block_q": 96, "block_k": 128})
    with pytest.raises(ValueError, match="does not divide"):
        fa.flash_attention(q, q, q)


def test_paged_attention_default_and_validation(monkeypatch, tmp_path):
    """Empty-DB consult reproduces the historical min(pages, 8) block
    pick; explicit non-divisors are refused on every impl."""
    from dlnetbench_tpu.serving.kv_cache import (
        paged_attention_decode, resolve_pages_per_compute_block)

    q = jax.random.normal(jax.random.key(0), (2, 4, 8), jnp.float32)
    kp = jax.random.normal(jax.random.key(1), (2, 8, 4, 8), jnp.float32)
    pidx = jnp.arange(8, dtype=jnp.int32).reshape(2, 4)
    assert resolve_pages_per_compute_block(q, kp, pidx, None) == 4
    _enable(monkeypatch, tmp_path)
    assert resolve_pages_per_compute_block(q, kp, pidx, None) == 4
    assert tuning.provenance()["misses"] == 1
    with pytest.raises(ValueError, match="does not divide"):
        resolve_pages_per_compute_block(q, kp, pidx, 3)
    with pytest.raises(ValueError, match="does not divide"):
        paged_attention_decode(q, kp, kp,
                               jnp.full((2,), 16, jnp.int32), pidx,
                               impl="gather", pages_per_compute_block=3)


# ------------------------- fixture round-trip: consult -> emit -> ...

def test_fixture_roundtrip_consult_emit_parser_merge(monkeypatch,
                                                     tmp_path):
    """The committed tests/data/tuning_db.jsonl drives a real consult
    hit; the provenance block rides emit -> validate -> dataframe
    (tuned column) -> merge (volatile global) -> bandwidth (tuned
    column), and v1/no-tuning records still parse beside it."""
    from dlnetbench_tpu.analysis.bandwidth import (bandwidth_summary,
                                                   effective_bandwidth)
    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (records_to_dataframe,
                                               validate_record)
    from dlnetbench_tpu.ops import quantized_matmul as qmm
    from dlnetbench_tpu.proxies.base import ProxyResult

    _enable(monkeypatch, tmp_path, with_fixture=True)
    # the fixture's quantized_matmul entry: consult must HIT, and the
    # tuned blocks (32, 64, 64) must leave int8 math exactly alone
    x = jax.random.normal(jax.random.key(0), (64, 64), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (64, 64), jnp.bfloat16)
    wq, sw = qmm.quantize_tensor(w, "int8")
    sx = qmm.scale_from_amax(jnp.max(jnp.abs(x.astype(jnp.float32))),
                             "int8")
    baseline = qmm.fused_matmul(x, wq, sw, sx, fmt="int8",
                                **qmm.DEFAULT_BLOCKS)
    got = qmm.fused_matmul(x, wq, sw, sx, fmt="int8")
    assert jnp.array_equal(baseline, got)
    prov = tuning.provenance()
    assert prov["hits"] == 1 and prov["misses"] == 0
    site = prov["sites"]["quantized_matmul|"
                         "fmt=int8,k=64,n=64,t=64,xdtype=bfloat16"]
    assert site["config"]["block_m"] == 32
    assert site["tuned_band"]["n"] == 3

    # emit: the record carries the tuning block
    result = ProxyResult(
        name="dp",
        global_meta={
            "proxy": "dp", "model": "m", "world_size": 2,
            "comm_model": {"runtimes": [
                {"kind": "allreduce", "bytes": 1024, "group": 2}]},
            "mesh": {"platform": "cpu", "device_kind": "host",
                     "num_hosts": 1,
                     "devices": [{"id": 0, "process": 0},
                                 {"id": 1, "process": 0}]}},
        timers_us={"runtimes": [100.0, 110.0, 105.0]},
        warmup_times_us=[500.0], num_runs=3)
    rec = result_to_record(result)
    assert rec["global"]["tuning"]["hits"] == 1
    validate_record(rec)
    json.dumps(rec)  # emitted shape is serializable

    # parser: the tuned column
    df = records_to_dataframe([rec])
    assert set(df["tuned"]) == {"1/1"}

    # merge: tuning is per-process warm state (volatile), so a merged
    # single-process record keeps it and the merge never aborts on it
    merged = merge_records([json.loads(json.dumps(rec))])
    assert merged["global"]["tuning"]["hits"] == 1

    # bandwidth: every row carries the tuned provenance column
    bw = effective_bandwidth([merged])
    assert set(bw["tuned"]) == {"1/1"}
    summary = bandwidth_summary([merged])
    assert "tuned" in summary.columns

    # a v1/no-tuning record parses beside it, tuned column absent/NaN
    old = json.loads(json.dumps(rec))
    old["global"].pop("tuning")
    df2 = records_to_dataframe([old])
    assert "tuned" not in df2.columns
    bw2 = effective_bandwidth([old])
    assert set(bw2["tuned"]) == {"-"}


def test_merge_tolerates_mixed_tuning_globals(monkeypatch, tmp_path):
    """One process tuned, one not (a host without the env set): the
    merge must not read that as 'different runs'."""
    from dlnetbench_tpu.metrics.merge import merge_records

    def rec_for(proc: int, with_tuning: bool):
        r = {"section": "dp", "version": 2, "process": proc,
             "global": {"model": "m", "world_size": 2,
                        "num_processes": 2},
             "mesh": {"platform": "cpu"},
             "num_runs": 2, "warmup_times": [1.0],
             "ranks": [{"rank": proc, "device_id": proc,
                        "process_index": proc,
                        "hostname": f"h{proc}",
                        "runtimes": [1.0, 2.0],
                        "summary": {"runtimes": {
                            "value": 1.5, "best": 1.0,
                            "band": [1.0, 2.0], "n": 2}}}]}
        if with_tuning:
            r["global"]["tuning"] = {"db_dir": "/x", "hits": 1,
                                     "misses": 0, "sites": {}}
        return r

    merged = merge_records([rec_for(0, True), rec_for(1, False)])
    assert merged["global"]["tuning"]["hits"] == 1


# ----------------------------------------------- the tune CLI, end2end

def test_tune_cli_search_commit_consult_hit(monkeypatch, tmp_path,
                                            capsys):
    """The check-tuning lane's proof: a 2-candidate CPU search over a
    tiny int8 fused matmul commits a winner; a consult through the
    REAL site then hits it.  Seconds on CPU."""
    from dlnetbench_tpu.ops import quantized_matmul as qmm
    from dlnetbench_tpu.tuning.__main__ import main as tuning_main

    root = tmp_path / "tdb"
    rc = tuning_main([
        "tune", "--op", "quantized_matmul", "--db", str(root),
        "--fmt", "int8", "--tokens", "64", "--d", "64", "--n", "64",
        "--candidates", "64,64,64;32,64,64", "--rounds", "2", "-k", "2",
    ])
    assert rc == 0
    committed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert committed["op"] == "quantized_matmul"
    assert committed["band"]["n"] == 2
    assert committed["config"]["block_m"] in (64, 32)
    # the committed record is consultable through the real site
    monkeypatch.setenv(tuning.ENV_DB_DIR, str(root))
    tuning.reset()
    x = jax.random.normal(jax.random.key(0), (64, 64), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(1), (64, 64), jnp.bfloat16)
    wq, sw = qmm.quantize_tensor(w, "int8")
    sx = qmm.scale_from_amax(jnp.max(jnp.abs(x.astype(jnp.float32))),
                             "int8")
    qmm.fused_matmul(x, wq, sw, sx, fmt="int8")
    prov = tuning.provenance()
    assert prov["hits"] == 1 and prov["misses"] == 0
    # show lists it
    rc = tuning_main(["show", "--db", str(root)])
    assert rc == 0
    shown = [json.loads(ln)
             for ln in capsys.readouterr().out.strip().splitlines()]
    assert any(r["op"] == "quantized_matmul" for r in shown)


def test_flash_explicit_blocks_bypass_db_in_backward(monkeypatch,
                                                     tmp_path):
    """Explicit flash blocks bind the BACKWARD too: with a flash_bwd
    DB record present, a call with explicit block_q/block_k must never
    consult it (a DB hit silently overriding explicit blocks would
    re-create the 'measured 4 configs while timing one' sweep
    hazard)."""
    import importlib

    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")
    root = _enable(monkeypatch, tmp_path)
    q = jax.random.normal(jax.random.key(0), (1, 256, 2, 128),
                          jnp.float32)
    key = tuning.params.flash_bwd_key(1, 256, 2, 2, 128, True, q.dtype)
    TuningDB(root).put("flash_bwd", key, tuning.hw_key(),
                       {"bq_dq": 64, "bk_dq": 64,
                        "bq_dkv": 64, "bk_dkv": 64})

    def loss(q_):
        return fa.flash_attention(q_, q_, q_, True, 128,
                                  128).astype(jnp.float32).sum()

    jax.grad(loss)(q)
    assert tuning.provenance() is None   # the DB was never asked


def test_bench_tuned_ab_reuses_existing_db_record(monkeypatch,
                                                  tmp_path):
    """A pre-existing DB record (e.g. a richer CLI tune) is MEASURED,
    never overwritten, by the bench tuned A/B."""
    import types

    import bench

    monkeypatch.setattr(bench, "BATCH", 2)
    monkeypatch.setattr(bench, "SEQ", 32)     # 64 tokens
    root = _enable(monkeypatch, tmp_path)
    up_key = tuning.params.quantized_matmul_key(
        64, 64, 128, "float8", jnp.zeros((), jnp.bfloat16).dtype)
    operator_cfg = {"block_m": 256, "block_n": 64, "block_k": 32}
    TuningDB(root).put("quantized_matmul", up_key, tuning.hw_key(),
                       operator_cfg)
    card = types.SimpleNamespace(embed_dim=64, ff_dim=128)
    line = bench._bench_tuned_ab(card, "tpu_v5e", jax.devices()[0])
    assert line is not None
    assert line["db_prior_hit"]["up"] is True
    assert line["search"]["up"] == {"reused_db_record": True,
                                    "tuned_band": None}
    assert line["configs"]["up"] == operator_cfg
    # the operator's record survived untouched
    assert TuningDB(root).get("quantized_matmul", up_key,
                              tuning.hw_key())["config"] == operator_cfg
    # the down shape had no record: searched and committed as before
    assert line["db_prior_hit"]["down"] is False
    assert line["search"]["down"]["candidates"] == 3


def test_tune_cli_flash_key_agrees_with_consult_site(monkeypatch,
                                                     tmp_path, capsys):
    """The CLI's committed flash key must be CONSULTABLE by the real
    flash_attention site (the key-spelling agreement the shared
    params builders exist for)."""
    import importlib

    from dlnetbench_tpu.tuning.__main__ import main as tuning_main

    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")
    root = tmp_path / "tdb"
    rc = tuning_main([
        "tune", "--op", "flash_fwd", "--db", str(root), "--batch", "1",
        "--seq", "256", "--heads", "2", "--kv_heads", "2",
        "--head_dim", "128", "--candidates", "256,256", "--rounds", "1",
        "-k", "1",
    ])
    assert rc == 0
    capsys.readouterr()
    monkeypatch.setenv(tuning.ENV_DB_DIR, str(root))
    tuning.reset()
    q = jax.random.normal(jax.random.key(0), (1, 256, 2, 128),
                          jnp.float32)
    fa.flash_attention(q, q, q)
    prov = tuning.provenance()
    flash_sites = {k: v for k, v in prov["sites"].items()
                   if k.startswith("flash_fwd|")}
    assert flash_sites and all(v["hit"] for v in flash_sites.values())


def test_bench_tuned_ab_end_to_end_tiny(monkeypatch):
    """bench.py's tuned A/B aux line at tiny CPU shapes: the seeded
    search runs, commits to an EPHEMERAL DB (env unset), and the line
    reports both variants' bands + the committed configs + prior
    hit/miss — the CPU half of the acceptance bar (search mechanism +
    provenance proven; the TPU number comes from the driver)."""
    import types

    import bench

    monkeypatch.setattr(bench, "BATCH", 2)
    monkeypatch.setattr(bench, "SEQ", 32)     # 64 tokens
    card = types.SimpleNamespace(embed_dim=64, ff_dim=128)
    line = bench._bench_tuned_ab(card, "tpu_v5e", jax.devices()[0])
    assert line is not None and line["unit"] == "ms"
    json.dumps(line)
    for sub in ("tuned_ms", "frozen_ms", "ratio_tuned_vs_frozen"):
        assert line[sub]["n"] == 3
    assert line["db_prior_hit"] == {"up": False, "down": False}
    assert "[ephemeral]" in line["metric"]
    for stage in ("up", "down"):
        assert set(line["configs"][stage]) == {"block_m", "block_n",
                                               "block_k"}
        assert line["search"][stage]["candidates"] == 3
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)


# ------------------------------- DLNB_FLASH_BWD_BLOCKS freeze, direct

def test_flash_bwd_env_freeze_direct(monkeypatch):
    """The post-import mutation check, exercised DIRECTLY: a changed
    env raises, and the message names the frozen -> attempted values
    (ISSUE 9 satellite)."""
    import importlib
    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")

    assert fa._BWD_BLOCKS_ENV == ""  # tier-1 lane imports without it
    monkeypatch.setenv("DLNB_FLASH_BWD_BLOCKS", "128,128,128,128")
    with pytest.raises(ValueError) as e:
        fa._bwd_blocks_override(256, 256, 1024)
    msg = str(e.value)
    assert "changed after import" in msg
    assert "frozen ''" in msg and "'128,128,128,128'" in msg


def test_flash_bwd_env_wins_over_db(monkeypatch, tmp_path):
    """Env override beats the tuning DB (reproducibility: a sweep that
    sets the env must measure the env's blocks, whatever the DB says).
    Simulated by freezing a module-level env value the way an on-import
    capture would."""
    import importlib
    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")

    root = _enable(monkeypatch, tmp_path)
    q = jax.random.normal(jax.random.key(0), (1, 256, 2, 128),
                          jnp.float32)
    key = tuning.params.flash_bwd_key(1, 256, 2, 2, 128, True, q.dtype)
    TuningDB(root).put("flash_bwd", key, tuning.hw_key(),
                       {"bq_dq": 64, "bk_dq": 64,
                        "bq_dkv": 64, "bk_dkv": 64})
    monkeypatch.setenv("DLNB_FLASH_BWD_BLOCKS", "128,128,128,128")
    monkeypatch.setattr(fa, "_BWD_BLOCKS_ENV", "128,128,128,128")
    blocks = fa._resolve_bwd_blocks(q, q, True, 256, 256)
    assert blocks == ((128, 128), (128, 128))   # env, not the DB's 64s
    assert tuning.provenance() is None          # the DB was never asked


def test_flash_bwd_db_consulted_without_env(monkeypatch, tmp_path):
    import importlib
    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")

    root = _enable(monkeypatch, tmp_path)
    q = jax.random.normal(jax.random.key(0), (1, 256, 2, 128),
                          jnp.float32)
    key = tuning.params.flash_bwd_key(1, 256, 2, 2, 128, True, q.dtype)
    TuningDB(root).put("flash_bwd", key, tuning.hw_key(),
                       {"bq_dq": 64, "bk_dq": 128,
                        "bq_dkv": 128, "bk_dkv": 64})
    blocks = fa._resolve_bwd_blocks(q, q, True, 256, 256)
    assert blocks == ((64, 128), (128, 64))
    assert tuning.provenance()["hits"] == 1


@pytest.mark.longcontext
def test_splash_blocks_empty_db_bit_identical(monkeypatch, tmp_path):
    """Splash attention fwd+grad on an empty enabled DB is bit-identical
    to the disabled path, and the consult logs under the mask-labeled
    splash keys (ISSUE 10: splash blocks are their own tuning site —
    dense flash records must never answer)."""
    import importlib
    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")
    from dlnetbench_tpu.ops.attention_mask import MaskSpec

    spec = MaskSpec(causal=True, window=64)
    q = jax.random.normal(jax.random.key(0), (1, 256, 2, 128),
                          jnp.float32)
    k = jax.random.normal(jax.random.key(1), (1, 256, 2, 128),
                          jnp.float32)
    v = jax.random.normal(jax.random.key(2), (1, 256, 2, 128),
                          jnp.float32)

    def loss(q_, k_, v_):
        return fa.splash_attention(q_, k_, v_,
                                   spec).astype(jnp.float32).sum()

    base, base_grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    _enable(monkeypatch, tmp_path)
    got, got_grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert jnp.array_equal(base, got)
    for b, g in zip(base_grads, got_grads):
        assert jnp.array_equal(b, g)
    prov = tuning.provenance()
    assert prov and prov["hits"] == 0
    splash_sites = [s for s in prov["sites"]
                    if s.startswith(("splash_fwd|", "splash_bwd|"))]
    assert len(splash_sites) == 2
    assert all("mask=causal&window(64)" in s for s in splash_sites)


@pytest.mark.longcontext
def test_splash_tuned_blocks_hit_and_divide_validation(monkeypatch,
                                                       tmp_path):
    """A committed splash record is consulted (numerics unchanged —
    block sizes never change the math) and an inapplicable one fails
    loud at the site."""
    import importlib
    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")
    from dlnetbench_tpu.ops.attention_mask import MaskSpec

    spec = MaskSpec(causal=True, window=64)
    q = jax.random.normal(jax.random.key(0), (1, 256, 2, 128),
                          jnp.float32)
    want = fa.splash_attention(q, q, q, spec, 128, 128)
    root = _enable(monkeypatch, tmp_path)
    key = tuning.params.splash_key(1, 256, 2, 2, 128, spec.label(),
                                   q.dtype)
    TuningDB(root).put("splash_fwd", key, tuning.hw_key(),
                       {"block_q": 128, "block_k": 128})
    got = fa.splash_attention(q, q, q, spec)
    assert jnp.array_equal(want, got)
    assert tuning.provenance()["hits"] == 1
    tuning.reset()
    TuningDB(root).put("splash_fwd", key, tuning.hw_key(),
                       {"block_q": 96, "block_k": 128})
    with pytest.raises(ValueError, match="does not divide"):
        fa.splash_attention(q, q, q, spec)
