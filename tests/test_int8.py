"""int8 MLP compute path (ops/int8.py, r4) — the low precision this
chip actually accelerates (0.99 of the int8 peak measured, vs the fp8
path's MXU upcast)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from dlnetbench_tpu.ops.int8 import _quantize, int8_dot, swiglu_int8


def test_quantize_roundtrip_scale():
    x = jax.random.normal(jax.random.key(0), (64, 32), jnp.bfloat16) * 3.0
    xq, scale = _quantize(x)
    assert xq.dtype == jnp.int8
    back = xq.astype(jnp.float32) * scale
    # symmetric per-tensor int8: worst-case error is half a step
    err = jnp.max(jnp.abs(back - x.astype(jnp.float32)))
    assert err <= 0.6 * scale


def test_int8_dot_close_to_bf16():
    kx, kw = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, (128, 256), jnp.bfloat16)
    w = jax.random.normal(kw, (256, 64), jnp.bfloat16) * 0.05
    got = int8_dot(x, w)
    want = jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))
    rel = (jnp.linalg.norm(got.astype(jnp.float32) - want)
           / jnp.linalg.norm(want))
    assert rel < 0.05, f"int8 dot relative error {rel}"
    assert got.dtype == x.dtype


def test_int8_dot_straight_through_grads():
    kx, kw, kg = jax.random.split(jax.random.key(2), 3)
    x = jax.random.normal(kx, (4, 8, 16), jnp.bfloat16)
    w = jax.random.normal(kw, (16, 12), jnp.bfloat16) * 0.1
    cot = jax.random.normal(kg, (4, 8, 12), jnp.bfloat16)

    def f_int8(x, w):
        return jnp.sum(int8_dot(x, w).astype(jnp.float32) *
                       cot.astype(jnp.float32))

    def f_bf16(x, w):
        return jnp.sum(jnp.dot(x, w, preferred_element_type=jnp.float32) *
                       cot.astype(jnp.float32))

    gx8, gw8 = jax.grad(f_int8, argnums=(0, 1))(x, w)
    gx, gw = jax.grad(f_bf16, argnums=(0, 1))(x, w)
    assert gx8.shape == x.shape and gw8.shape == w.shape
    assert jnp.allclose(gx8.astype(jnp.float32), gx.astype(jnp.float32),
                        atol=1e-2, rtol=1e-2)
    assert jnp.allclose(gw8.astype(jnp.float32), gw.astype(jnp.float32),
                        atol=1e-2, rtol=1e-2)


def test_swiglu_int8_close_to_bf16():
    from dlnetbench_tpu.models.layers import swiglu
    x = jax.random.normal(jax.random.key(3), (64, 32), jnp.bfloat16)
    wg = jax.random.normal(jax.random.key(4), (32, 48), jnp.bfloat16) * 0.1
    wu = jax.random.normal(jax.random.key(5), (32, 48), jnp.bfloat16) * 0.1
    wd = jax.random.normal(jax.random.key(6), (48, 32), jnp.bfloat16) * 0.1
    got = swiglu_int8(x, wg, wu, wd).astype(jnp.float32)
    want = swiglu(x, wg, wu, wd).astype(jnp.float32)
    rel = jnp.linalg.norm(got - want) / jnp.linalg.norm(want)
    assert rel < 0.1, f"int8 swiglu relative error {rel}"


def test_swiglu_int8_fused_vjp_matches_composed():
    """The hand-written whole-SwiGLU backward (which recomputes h
    instead of saving it — the r5 no-remat memory fix) must produce
    EXACTLY the gradients of the composed int8_dot form it replaced;
    a sign error in the silu-derivative term or a d_wg/d_wu swap
    (same shapes) would otherwise pass the suite silently."""
    from dlnetbench_tpu.ops.int8 import int8_dot

    x = jax.random.normal(jax.random.key(7), (48, 32), jnp.bfloat16)
    wg = jax.random.normal(jax.random.key(8), (32, 40), jnp.bfloat16) * 0.1
    wu = jax.random.normal(jax.random.key(9), (32, 40), jnp.bfloat16) * 0.1
    wd = jax.random.normal(jax.random.key(10), (40, 32), jnp.bfloat16) * 0.1
    cot = jax.random.normal(jax.random.key(11), (48, 32), jnp.bfloat16)

    def composed(x, wg, wu, wd):
        g = int8_dot(x, wg)
        u = int8_dot(x, wu)
        h = (jax.nn.silu(g.astype(jnp.float32))
             * u.astype(jnp.float32)).astype(g.dtype)
        return int8_dot(h, wd)

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a).astype(jnp.float32)
                                  * cot.astype(jnp.float32))

    want = jax.grad(loss(composed), argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    got = jax.grad(loss(swiglu_int8), argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    for a, b, name in zip(got, want, ("dx", "dwg", "dwu", "dwd")):
        assert jnp.allclose(a.astype(jnp.float32), b.astype(jnp.float32),
                            atol=1e-3, rtol=1e-3), name


def test_swiglu_int8_residual_contract_no_hidden_h():
    """The r5 OOM fix's CONTRACT, pinned (ISSUE 3 satellite): the fused
    whole-SwiGLU VJP must save exactly TWO [T, F] residuals (the g/u
    pre-activations — the same set the bf16 path saves) and NOT the
    hidden ``h = silu(g)*u``, which is what made the composed int8_dot
    form OOM at the no-remat bench shape (345 MB/layer it re-saves as
    the down-projection residual).  ``jax.vjp``'s returned function is
    a pytree whose leaves ARE the saved residuals, so the contract is
    directly observable in interpret/CPU mode; the composed form is
    measured alongside to prove the counter distinguishes them."""
    t, d, f = 48, 32, 40
    x = jax.random.normal(jax.random.key(30), (t, d), jnp.bfloat16)
    wg = jax.random.normal(jax.random.key(31), (d, f), jnp.bfloat16) * 0.1
    wu = jax.random.normal(jax.random.key(32), (d, f), jnp.bfloat16) * 0.1
    wd = jax.random.normal(jax.random.key(33), (f, d), jnp.bfloat16) * 0.1

    def composed(x, wg, wu, wd):
        g = int8_dot(x, wg)
        u = int8_dot(x, wu)
        h = (jax.nn.silu(g.astype(jnp.float32))
             * u.astype(jnp.float32)).astype(g.dtype)
        return int8_dot(h, wd)

    def tf_residuals(fn):
        out, vjp = jax.vjp(fn, x, wg, wu, wd)
        return out, vjp, sum(1 for l in jax.tree.leaves(vjp)
                             if getattr(l, "shape", None) == (t, f))

    out_f, vjp_f, n_fused = tf_residuals(swiglu_int8)
    out_c, vjp_c, n_comp = tf_residuals(composed)
    assert n_fused == 2, f"fused VJP saves {n_fused} [T,F] residuals " \
                         f"(expected exactly g and u — h must be " \
                         f"recomputed, not saved)"
    assert n_comp > n_fused, "composed form no longer materializes h; " \
                             "the contract test lost its control"
    # and the recompute-instead-of-save backward matches the composed
    # gradients to tolerance (identical math, different residual plan)
    cot = jax.random.normal(jax.random.key(34), out_f.shape, out_f.dtype)
    for a, b, name in zip(vjp_f(cot), vjp_c(cot),
                          ("dx", "dwg", "dwu", "dwd")):
        assert jnp.allclose(a.astype(jnp.float32), b.astype(jnp.float32),
                            atol=1e-3, rtol=1e-3), name


def test_flash_bwd_blocks_override_fails_loud(monkeypatch):
    """The sweep env knob must raise on malformed strings and
    non-divisor blocks — a truncated grid would silently compute wrong
    gradients while recording a plausible time.  The knob is frozen at
    IMPORT time (jit caching is not keyed on the environment, ADVICE
    r5), so parsing is tested through the pure parser and a post-import
    env change must raise instead of silently reusing the stale
    compiled config."""
    from dlnetbench_tpu.ops.flash_attention import (
        _bwd_blocks_override, _parse_bwd_blocks)

    with pytest.raises(ValueError, match="comma-separated"):
        _parse_bwd_blocks("1024;1024,1024,1024", 1024, 1024, 6144)
    with pytest.raises(ValueError, match="does not divide"):
        _parse_bwd_blocks("1280,1024,1024,1024", 1024, 1024, 6144)
    assert _parse_bwd_blocks("2048,512,512,2048", 1024, 1024, 6144) == \
        ((2048, 512), (512, 2048))
    assert _parse_bwd_blocks("", 1024, 1024, 6144) == ((1024, 1024),
                                                       (1024, 1024))
    # the import-time freeze: a live env differing from the frozen value
    # is a configuration error, not a silent stale-cache reuse
    monkeypatch.setenv("DLNB_FLASH_BWD_BLOCKS", "2048,512,512,2048")
    with pytest.raises(ValueError, match="changed after import"):
        _bwd_blocks_override(1024, 1024, 6144)
    monkeypatch.delenv("DLNB_FLASH_BWD_BLOCKS")
    # empty env defers to the tuning layer (ISSUE 9): None = "the DB
    # may answer, else the defaults" — _resolve_bwd_blocks owns that
    # fallback now (tests/test_tuning.py covers both arms)
    assert _bwd_blocks_override(1024, 1024, 6144) is None


def test_swiglu_int8_switchback_grads_close_to_master():
    """The SwitchBack backward (dx-side matmuls quantized) must stay
    CLOSE to the master-dtype backward — the quantization error it
    adds is bounded by the per-tensor int8 step (~1%), far under the
    error already accepted in the int8 forward.  dW grads use the same
    master-dtype math in both, so they agree tightly."""
    from dlnetbench_tpu.ops.int8 import swiglu_int8_sb

    x = jax.random.normal(jax.random.key(12), (48, 32), jnp.bfloat16)
    wg = jax.random.normal(jax.random.key(13), (32, 40), jnp.bfloat16) * 0.1
    wu = jax.random.normal(jax.random.key(14), (32, 40), jnp.bfloat16) * 0.1
    wd = jax.random.normal(jax.random.key(15), (40, 32), jnp.bfloat16) * 0.1
    cot = jax.random.normal(jax.random.key(16), (48, 32), jnp.bfloat16)

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a).astype(jnp.float32)
                                  * cot.astype(jnp.float32))

    gm = jax.grad(loss(swiglu_int8), argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    gs = jax.grad(loss(swiglu_int8_sb), argnums=(0, 1, 2, 3))(x, wg, wu,
                                                              wd)
    for a, b, name in zip(gs, gm, ("dx", "dwg", "dwu", "dwd")):
        af, bf = a.astype(jnp.float32), b.astype(jnp.float32)
        rel = float(jnp.linalg.norm(af - bf)
                    / jnp.maximum(jnp.linalg.norm(bf), 1e-9))
        # dx flows through up to three quantized matmuls; dW through
        # one quantized dh — generous but meaningful bounds
        assert rel < (0.15 if name == "dx" else 0.1), (name, rel)


def test_int8_backward_config_validation():
    from dlnetbench_tpu.models import transformer as tfm
    base = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
                ff_dim=64, num_layers=1, seq_len=16, gated=True,
                max_positions=0)
    with pytest.raises(ValueError, match="int8_backward"):
        tfm.TransformerConfig(**base, int8_backward="sb")
    with pytest.raises(ValueError, match="requires mlp_dtype"):
        tfm.TransformerConfig(**base, int8_backward="switchback")
    # legal: int8 + switchback
    tfm.TransformerConfig(**base, mlp_dtype="int8",
                          int8_backward="switchback")


@pytest.mark.slow  # ~60s/recipe e2e train step; dot/VJP parity rides the fast lane
@pytest.mark.parametrize("int8_backward", ["master", "switchback"])
def test_transformer_int8_mlp_trains(int8_backward):
    """mlp_dtype='int8' plumbs through the dense SwiGLU stack (both
    backward recipes): a tiny train step runs, loss is finite, grads
    flow into the MLP weights."""
    import dataclasses

    from dlnetbench_tpu.core.model_card import load_model_card
    from dlnetbench_tpu.models import transformer as tfm

    card = load_model_card("llama3_8b")
    cfg = tfm.TransformerConfig.from_card(card, seq_len=64, num_layers=2,
                                          vocab_size=512)
    cfg = dataclasses.replace(cfg, mlp_dtype="int8",
                              int8_backward=int8_backward)
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.seq_len + 1),
                                0, cfg.vocab_size)
    step = jax.jit(lambda p, t: jax.value_and_grad(tfm.loss_fn)(p, t, cfg))
    loss, g = step(params, tokens)
    assert jnp.isfinite(loss)
    gmax = jnp.max(jnp.abs(g["layers"]["w_gate"].astype(jnp.float32)))
    assert gmax > 0, "no gradient reached the int8 MLP weights"


def test_int8_config_validation():
    import dataclasses

    from dlnetbench_tpu.core.model_card import load_model_card
    from dlnetbench_tpu.models import transformer as tfm

    card = load_model_card("mixtral_8x7b")
    cfg = tfm.TransformerConfig.from_card(card, seq_len=64, num_layers=2)
    with pytest.raises(ValueError, match="dense SwiGLU"):
        dataclasses.replace(cfg, mlp_dtype="int8")
    # the custom backwards cover only the bf16 path
    card2 = load_model_card("llama3_8b")
    cfg2 = tfm.TransformerConfig.from_card(card2, seq_len=64, num_layers=2)
    with pytest.raises(ValueError, match="bf16 SwiGLU"):
        dataclasses.replace(cfg2, mlp_dtype="int8", mlp_backward="pallas")
