"""Device-resident multi-step decode + speculative decode (ISSUE 11):
the fused loop's token parity with the classic engine, the verify
pass, the host/device state split's sync contract, adaptive N, config
guards, the record/attribution pathway, and the CompiledLoop executor
shape."""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.models import transformer as tfm
from dlnetbench_tpu.serving import decode as D
from dlnetbench_tpu.serving.arrivals import ArrivalPlan
from dlnetbench_tpu.serving.device_state import (DeviceDecodeState,
                                                 SyncContractError)
from dlnetbench_tpu.serving.kv_cache import (CacheConfig, PagedKVCache,
                                             device_buffers)
from dlnetbench_tpu.serving.scheduler import Engine, ServingConfig

pytestmark = [pytest.mark.decode, pytest.mark.serving]


def tiny_model(**over) -> tfm.TransformerConfig:
    kw = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
              ff_dim=64, num_layers=2, seq_len=32, gated=True,
              max_positions=0, dtype="float32")
    kw.update(over)
    return tfm.TransformerConfig(**kw)


def tiny_serving(**over) -> ServingConfig:
    kw = dict(slots=4, page_size=4, num_pages=32, max_seq_len=32,
              slo_ttft_ms=200.0, slo_tpot_ms=100.0)
    kw.update(over)
    return ServingConfig(**kw)


PLAN = ArrivalPlan(kind="poisson", rate_rps=200.0, num_requests=10,
                   seed=7, prompt_len=[4, 9], output_len=[1, 7])


def _run_streams(cfg, sc, params, plan=PLAN):
    eng = Engine(cfg, sc, params=params)
    completed, _ = eng.run(plan.sample())
    assert len(completed) == plan.num_requests
    assert eng.cache.pages_in_use == 0
    return dict(eng.token_streams), eng


# ---------------------------------------------------------------------
# token parity: the acceptance anchor


@pytest.fixture(scope="module")
def shared():
    cfg = tiny_model()
    params = tfm.init_params(jax.random.key(0), cfg)
    base, _ = _run_streams(cfg, tiny_serving(), params)
    return cfg, params, base


def test_multi_step_token_parity(shared):
    """N-step fused greedy == 1-step greedy, exactly, across N values
    and both prefill policies."""
    cfg, params, base = shared
    for n in (2, 8):
        got, eng = _run_streams(cfg, tiny_serving(multi_step_n=n),
                                params)
        assert got == base, f"N={n}"
        blk = eng.decode_loop_block()
        assert blk["multi_step_n"] == n
        assert blk["steps_per_dispatch"] > 1.0
    got, _ = _run_streams(
        cfg, tiny_serving(multi_step_n=4, prefill="inline",
                          prefill_chunk=4), params)
    assert got == base


def test_speculative_token_parity_both_drafters(shared):
    """Speculative decode is LOSSLESS under greedy acceptance: the
    emitted stream equals the 1-step stream whatever the drafter
    proposes — for the ngram table AND the truncated-layer drafter."""
    cfg, params, base = shared
    for drafter, extra in (("ngram", {}),
                           ("truncated", {"drafter_layers": 1})):
        sc = tiny_serving(multi_step_n=4, speculative=True, spec_k=3,
                          drafter=drafter, **extra)
        got, eng = _run_streams(cfg, sc, params)
        assert got == base, drafter
        spec = eng.decode_loop_block()["spec"]
        assert spec["drafter"] == drafter
        assert spec["drafted"] > 0
        assert 0.0 <= spec["acceptance_rate"] <= 1.0


def test_multi_step_n1_is_classic_engine(shared):
    """multi_step_n=1 reproduces today's engine bit-identically — the
    loop program is not even BUILT (the tuning-layer convention: the
    default path is the untouched path), and the classic single-step
    program drives the run."""
    cfg, params, base = shared
    eng = Engine(cfg, tiny_serving(multi_step_n=1), params=params)
    assert eng._loop is None and eng._decode is not None
    assert eng.dstate is None
    assert "decode_step" in eng.meta["compile_ms"]
    completed, _ = eng.run(PLAN.sample())
    assert len(completed) == PLAN.num_requests
    assert dict(eng.token_streams) == base
    blk = eng.decode_loop_block()
    assert blk["steps_per_dispatch"] == 1.0
    assert blk["host_dispatch_us"]["n"] > 0   # the measured before-
    #                                           number (ISSUE 11 sat.)


def test_multi_step_loop_matches_iterated_single_steps():
    """Op-level: the fused program's token block over N steps equals N
    iterated single-step calls on the same starting state (same math,
    same cache writes — the shared ``_step_tokens`` body)."""
    cfg = tiny_model()
    params = tfm.init_params(jax.random.key(0), cfg)
    cc = CacheConfig(num_layers=2, num_kv_heads=2, head_dim=8,
                     num_pages=16, page_size=4, max_seqs=2,
                     max_pages_per_seq=6)
    cache = PagedKVCache(cc)
    k, v = device_buffers(cc)
    prompt = np.array([5, 9, 3, 11, 7], np.int32)
    cache.allocate(0, len(prompt) + 8)
    prefill = D.make_prefill_chunk(cfg, cc, chunk=5)
    row = jnp.asarray(cache.block_tables[0])
    k, v, nxt = prefill(params, k, v, jnp.asarray(prompt),
                        jnp.int32(0), jnp.int32(5), row)
    cache.append(0, 5)
    first = int(nxt)
    bt = jnp.asarray(cache.block_tables)

    # (a) four iterated single steps
    step = D.make_decode_step(cfg, cc)
    k1, v1 = k, v
    last, pos, ref = first, 5, []
    for _ in range(4):
        k1, v1, nx = step(
            params, k1, v1,
            jnp.asarray(np.array([last, 0], np.int32)),
            jnp.asarray(np.array([pos, 0], np.int32)), bt,
            jnp.asarray(np.array([True, False])))
        last = int(np.asarray(nx)[0])
        pos += 1
        ref.append(last)

    # (b) one fused call on the SAME starting state
    loop = D.make_multi_step_decode(cfg, cc, n_max=8)
    state = np.zeros((D.STATE_ROWS, 2), np.int32)
    state[D.STATE_LAST, 0] = first
    state[D.STATE_POS, 0] = 5
    state[D.STATE_REM, 0] = 4
    state[D.STATE_LIMIT, 0] = 13
    k2, v2, st, out, cnt, steps = loop(params, k, v,
                                       jnp.asarray(state), bt,
                                       jnp.int32(4))
    assert int(steps) == 4
    assert int(np.asarray(cnt)[0]) == 4
    assert np.asarray(out)[0, :4].tolist() == ref
    st = np.asarray(st)
    assert st[D.STATE_POS, 0] == 9 and st[D.STATE_REM, 0] == 0
    # the loop exits EARLY once every slot is done
    _, _, _, _, cnt2, steps2 = loop(params, k, v, jnp.asarray(state),
                                    bt, jnp.int32(8))
    assert int(steps2) == 4 and int(np.asarray(cnt2)[0]) == 4


def test_verify_pass_matches_iterated_decode():
    """The speculative verify pass computes, at every fed position,
    exactly the single-step program's greedy continuation — the
    property that makes greedy acceptance lossless."""
    from dlnetbench_tpu.serving.speculative import _verify_tokens
    cfg = tiny_model()
    params = tfm.init_params(jax.random.key(1), cfg)
    cc = CacheConfig(num_layers=2, num_kv_heads=2, head_dim=8,
                     num_pages=16, page_size=4, max_seqs=2,
                     max_pages_per_seq=6)
    cache = PagedKVCache(cc)
    k, v = device_buffers(cc)
    prompt = np.array([1, 8, 2, 60], np.int32)
    cache.allocate(0, 20)
    prefill = D.make_prefill_chunk(cfg, cc, chunk=4)
    row = jnp.asarray(cache.block_tables[0])
    k, v, nxt = prefill(params, k, v, jnp.asarray(prompt),
                        jnp.int32(0), jnp.int32(4), row)
    cache.append(0, 4)
    bt = jnp.asarray(cache.block_tables)
    fed = [int(nxt), 17, 42, 3]         # last token + 3 arbitrary drafts

    # reference: feed them one at a time through the single-step program
    step = D.make_decode_step(cfg, cc)
    k1, v1, ref = k, v, []
    for j, tok in enumerate(fed):
        k1, v1, nx = step(
            params, k1, v1,
            jnp.asarray(np.array([tok, 0], np.int32)),
            jnp.asarray(np.array([4 + j, 0], np.int32)), bt,
            jnp.asarray(np.array([True, False])))
        ref.append(int(np.asarray(nx)[0]))

    # one batched verify pass over the same fed tokens
    tokens = jnp.asarray(np.array([fed, [0] * 4], np.int32))
    write_ok = jnp.asarray(np.array([[True] * 4, [False] * 4]))
    _, _, out = _verify_tokens(cfg, cc, params, k, v, tokens,
                               jnp.asarray(np.array([4, 0], np.int32)),
                               write_ok, bt)
    assert np.asarray(out)[0].tolist() == ref


# ---------------------------------------------------------------------
# the host/device state split (satellite: property + sync contract)


def test_device_state_roundtrip_property():
    """Any interleaving of admit / evict / device-advance / flush /
    pull round-trips device_state <-> host view losslessly: the host
    mirrors after a final pull equal a pure-host reference model that
    applied the same operations."""
    from dlnetbench_tpu.serving.arrivals import _Rng
    slots, pmax, vocab = 4, 6, 32
    ds = DeviceDecodeState(slots, pmax, vocab=vocab)
    ref = {"state": np.zeros((D.STATE_ROWS, slots), np.int32),
           "bt": np.zeros((slots, pmax), np.int32),
           "tab": np.zeros((slots, vocab), np.int32)}

    # a tiny jitted "device advance" mirroring the loop's state update:
    # active slots feed their last token and move forward one step
    @jax.jit
    def advance(state, table):
        last, pos, rem = (state[D.STATE_LAST], state[D.STATE_POS],
                          state[D.STATE_REM])
        act = rem > 0
        nxt = (last * 7 + pos) % vocab
        rows = jnp.arange(state.shape[1])
        table = table.at[rows, jnp.where(act, last, vocab)].set(
            nxt, mode="drop")
        state = state.at[D.STATE_LAST].set(jnp.where(act, nxt, last))
        state = state.at[D.STATE_POS].set(pos + act.astype(jnp.int32))
        state = state.at[D.STATE_REM].set(rem - act.astype(jnp.int32))
        return state, table

    def ref_advance():
        st, tab = ref["state"], ref["tab"]
        for s in range(slots):
            if st[D.STATE_REM, s] > 0:
                last, pos = st[D.STATE_LAST, s], st[D.STATE_POS, s]
                nxt = (last * 7 + pos) % vocab
                tab[s, last] = nxt
                st[D.STATE_LAST, s] = nxt
                st[D.STATE_POS, s] += 1
                st[D.STATE_REM, s] -= 1

    rng = _Rng(123)
    for _ in range(120):
        op = rng.uniform_int(0, 3)
        if op == 0:                       # admit a slot
            ds.pull()
            s = rng.uniform_int(0, slots - 1)
            row = np.asarray([rng.uniform_int(0, 15)
                              for _ in range(pmax)], np.int32)
            tab_row = np.asarray([rng.uniform_int(0, vocab - 1)
                                  for _ in range(vocab)], np.int32)
            kw = dict(last_token=rng.uniform_int(0, vocab - 1),
                      position=rng.uniform_int(0, 10),
                      remaining=rng.uniform_int(1, 6),
                      seq_limit=16,
                      # ISSUE 19 rows (negative uid = a warm rid)
                      uid=rng.uniform_int(0, 20) - 5,
                      grammar_state=rng.uniform_int(0, 9))
            ds.admit(s, block_row=row, ngram_row=tab_row, **kw)
            ref["state"][:, s] = [kw["last_token"], kw["position"],
                                  kw["remaining"], kw["seq_limit"],
                                  kw["uid"], kw["grammar_state"]]
            ref["bt"][s] = row
            ref["tab"][s] = tab_row
        elif op == 1:                     # evict a slot
            ds.pull()
            s = rng.uniform_int(0, slots - 1)
            ds.evict(s)
            ref["state"][D.STATE_REM, s] = 0
        elif op == 2:                     # device advance
            carries = ds.carries()
            st, tab = advance(*carries)
            ds.rebind((st, tab))
            ref_advance()
        else:                             # explicit sync
            ds.pull()
    ds.pull()
    view = ds.host_view()
    np.testing.assert_array_equal(view["last_tokens"],
                                  ref["state"][D.STATE_LAST])
    np.testing.assert_array_equal(view["positions"],
                                  ref["state"][D.STATE_POS])
    np.testing.assert_array_equal(view["remaining"],
                                  ref["state"][D.STATE_REM])
    np.testing.assert_array_equal(view["uids"],
                                  ref["state"][D.STATE_UID])
    np.testing.assert_array_equal(view["grammar_states"],
                                  ref["state"][D.STATE_GRAMMAR])
    np.testing.assert_array_equal(view["block_tables"], ref["bt"])
    np.testing.assert_array_equal(view["ngram_table"], ref["tab"])
    # every crossing was priced
    assert ds.sync_h2d_us and ds.sync_d2h_us


def test_device_state_stale_mutation_refused():
    """The sync contract fails LOUD: mutating a stale mirror (the
    device advanced since the last pull) raises instead of silently
    clobbering device state at the next flush."""
    ds = DeviceDecodeState(2, 4)
    ds.admit(0, last_token=3, position=2, remaining=4, seq_limit=8,
             block_row=np.zeros(4, np.int32))
    carries = ds.carries()
    ds.rebind(carries)                    # device "advanced"
    with pytest.raises(SyncContractError, match="STALE"):
        ds.admit(1, last_token=1, position=0, remaining=2, seq_limit=8,
                 block_row=np.zeros(4, np.int32))
    with pytest.raises(SyncContractError, match="STALE"):
        ds.evict(0)
    ds.pull()
    ds.evict(0)                           # fresh again after the sync
    assert ds.host_view()["remaining"][0] == 0


# ---------------------------------------------------------------------
# adaptive N (satellite: the fused loop must not starve admissions)


def test_pick_n_steps_policy():
    """The deterministic half of the TTFT guard: pending work caps N
    at the shortest remaining output; an imminent arrival caps by the
    measured step rate; an idle queue runs the full N; a prefilling
    slot (inline mode) forces 1."""
    from dlnetbench_tpu.serving.arrivals import Request
    from dlnetbench_tpu.serving.scheduler import _SlotState
    import time
    cfg = tiny_model()
    eng = Engine(cfg, tiny_serving(multi_step_n=8),
                 params=tfm.init_params(jax.random.key(0), cfg))
    eng._reset_state()
    eng._t0 = time.monotonic()    # "now" ~= 0 on the engine clock

    def slot(prompt, out, generated):
        st = _SlotState(Request(rid=0, arrival_s=0.0, prompt_len=prompt,
                                output_len=out), admitted_s=0.0)
        st.prefill_done = prompt
        st.generated = generated
        return st

    eng.slots[0] = slot(4, 6, 1)          # 5 remaining
    eng.slots[1] = slot(4, 4, 1)          # 3 remaining
    assert eng._pick_n_steps([0, 1]) == 8        # nothing waiting
    eng.pending.append(Request(rid=9, arrival_s=0.0, prompt_len=4,
                               output_len=2))
    assert eng._pick_n_steps([0, 1]) == 3        # min remaining caps
    eng.pending.clear()
    # queue head arrives in ~2 measured steps: cap there
    eng._step_ewma_s = 1.0
    eng.queue.append(Request(rid=10, arrival_s=1.5, prompt_len=4,
                             output_len=2))
    assert eng._pick_n_steps([0, 1]) == 2
    eng.queue.clear()
    # a prefilling slot (inline) pins the engine at one step
    eng.slots[2] = slot(4, 4, 0)
    eng.slots[2].prefill_done = 2
    assert eng._pick_n_steps([0, 1]) == 1
    # adaptive off: always the configured N
    eng.cfg = dataclasses.replace(eng.cfg, adaptive_n=False)
    assert eng._pick_n_steps([0, 1]) == 8


def test_adaptive_n_ttft_holds_under_poisson():
    """TTFT p99 under Poisson arrivals with the adaptive fused loop
    must not regress past the 1-step engine's beyond the stat band
    (the satellite's acceptance): same seeds, interleaved rounds, and
    a generous noise margin since this is wall-clock."""
    from dlnetbench_tpu.serving import metrics as M
    cfg = tiny_model()
    params = tfm.init_params(jax.random.key(0), cfg)
    plan = ArrivalPlan(kind="poisson", rate_rps=120.0,
                       num_requests=12, seed=5, prompt_len=[4, 8],
                       output_len=[4, 8])
    reqs = plan.sample()
    engines = {1: Engine(cfg, tiny_serving(multi_step_n=1),
                         params=params),
               8: Engine(cfg, tiny_serving(multi_step_n=8),
                         params=params)}
    for eng in engines.values():
        eng.run(reqs)                     # warm
    p99 = {1: [], 8: []}
    for _ in range(3):
        for n, eng in engines.items():
            completed, _ = eng.run(reqs)
            p99[n].append(M.percentile(
                [c.ttft_ms for c in completed], 99))
    med1 = sorted(p99[1])[1]
    med8 = sorted(p99[8])[1]
    # regression = worse beyond band overlap AND a 2x margin (the
    # starvation failure this guards against is ~N x, not 2x)
    from dlnetbench_tpu.metrics import stats
    band1 = [min(p99[1]), max(p99[1])]
    band8 = [min(p99[8]), max(p99[8])]
    assert stats.bands_overlap(band1, band8) or med8 <= 2.0 * med1, \
        (p99[1], p99[8])


# ---------------------------------------------------------------------
# config guards (satellite)


def test_spec_config_validation():
    with pytest.raises(ValueError, match="multi_step_n"):
        tiny_serving(multi_step_n=0).validate()
    with pytest.raises(ValueError, match="spec_k"):
        tiny_serving(speculative=True, spec_k=0).validate()
    with pytest.raises(ValueError, match="drafter"):
        tiny_serving(speculative=True, drafter="oracle").validate()
    # sampling knobs validate through check_sampling_config (ISSUE 19)
    with pytest.raises(ValueError, match="temperature"):
        tiny_serving(top_p=0.9).validate()
    with pytest.raises(ValueError, match="top_p"):
        tiny_serving(temperature=0.8, top_p=1.5).validate()
    # speculative sampling needs a drafter DISTRIBUTION: the ngram
    # drafter emits argmax tokens only, so rejection sampling has no
    # q(t) to accept against — the old "spec requires greedy" refusal
    # is gone, replaced by this per-drafter guard
    with pytest.raises(ValueError, match="drafter probs"):
        tiny_serving(speculative=True, temperature=0.8,
                     drafter="ngram").validate()
    # a full-depth truncated drafter is refused at build (it IS the
    # target: no draft speedup, double cost)
    cfg = tiny_model()
    with pytest.raises(ValueError, match="drafter_layers"):
        Engine(cfg, tiny_serving(speculative=True, drafter="truncated",
                                 drafter_layers=cfg.num_layers),
               params=tfm.init_params(jax.random.key(0), cfg))


def test_compiled_loop_validates_carry_contract():
    """The fourth executor shape: a loop program that does NOT return
    a donated carry as a leading output fails loud at build instead of
    handing back a dead buffer at the second sync."""
    from dlnetbench_tpu.core.executor import CompiledLoop
    x = jnp.zeros((4,), jnp.float32)
    y = jnp.zeros((4,), jnp.float32)

    def good(a, b):
        return a + 1.0, b * 2.0, jnp.sum(a)

    loop = CompiledLoop(good, (x, y), carry_argnums=(0, 1))
    assert loop.num_carry_outputs == 2
    outs = loop(x, y)
    carries, extras = loop.split(outs)
    assert len(carries) == 2 and len(extras) == 1

    def bad(a, b):
        return jnp.sum(a), b * 2.0       # carry 0 has no matching out

    with pytest.raises(ValueError, match="carry argnum"):
        CompiledLoop(bad, (x, y), carry_argnums=(0, 1))


# ---------------------------------------------------------------------
# fault composition + record pathway


def test_crash_shrink_requeues_with_original_stamps_multi_step():
    """The crash-fault composition survives the engine split: a shrink
    on a MULTI-STEP engine re-queues in-flight requests with their
    ORIGINAL arrival stamps on the rebuilt engine (satellite's
    crash-fault composition case)."""
    from dlnetbench_tpu.faults.plan import FaultEvent, FaultPlan
    from dlnetbench_tpu.serving.scheduler import run_serving
    cfg = tiny_model()
    sc = tiny_serving(world=2, slots=4, multi_step_n=4,
                      slo_ttft_ms=300.0, slo_tpot_ms=100.0)
    trace = [{"t": 0.01 * i, "prompt_len": 6, "output_len": 4}
             for i in range(10)]
    plan = ArrivalPlan(kind="replay", trace=trace)
    fp = FaultPlan(events=[FaultEvent(kind="crash", ranks=[1],
                                     iteration=3)], policy="shrink")
    res = run_serving(cfg, sc, plan, fault_plan=fp)
    g = res.global_meta
    assert g["degraded_world"] == [0] and g["degraded_slots"] == 2
    assert res.num_runs == len(trace)     # every request completed
    # original arrival stamps survived the re-queue: TTFT of the
    # disrupted requests includes the pre-crash wait
    arrivals = sorted(t["t"] for t in trace)
    srv = g["serving"]
    assert srv["completed"] == len(arrivals)
    assert g["serving"]["decode_loop"]["multi_step_n"] == 4


def test_serving_record_carries_decode_loop_and_attribution():
    """run_serving -> emit: the record's serving block carries the
    dispatch decomposition, attribution stamps the serving_dispatch
    block (the ISSUE 11 fold), and the parser hoists the new
    columns."""
    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.metrics.parser import (records_to_dataframe,
                                               validate_record)
    from dlnetbench_tpu.serving.scheduler import run_serving
    cfg = tiny_model()
    sc = tiny_serving(multi_step_n=4, speculative=True, spec_k=2,
                      warmup_requests=0)
    plan = ArrivalPlan(kind="poisson", rate_rps=200.0, num_requests=6,
                       seed=1, prompt_len=[4, 8], output_len=[2, 5])
    res = run_serving(cfg, sc, plan)
    rec = result_to_record(res)
    validate_record(rec)
    dl = rec["global"]["serving"]["decode_loop"]
    assert dl["multi_step_n"] == 4 and dl["speculative"]
    assert dl["dispatches"] >= 1
    assert dl["host_dispatch_us"]["n"] >= 1
    assert dl["sync_h2d_us"]["n"] >= 1
    assert dl["spec"]["k"] == 2
    assert rec["global"]["serving_config"]["multi_step_n"] == 4
    attr = rec["global"]["attribution"]
    assert attr["inputs"]["source"] == "serving_dispatch"
    assert attr["inputs"]["steps_per_dispatch"] == \
        dl["steps_per_dispatch"]
    assert attr["bound"] in ("host", "hbm")   # CPU mesh: never mxu
    assert abs(sum(attr["fractions"].values()) - 1.0) < 1e-6
    df = records_to_dataframe([rec])
    for col in ("serving_steps_per_dispatch", "serving_tokens_per_sync",
                "serving_host_dispatch_us_p50",
                "serving_spec_acceptance"):
        assert col in df.columns, col


def test_dispatch_decomposition_two_point_solve():
    """The paired-round solver recovers the per-dispatch floor from a
    synthetic 1-step vs N-step pair exactly."""
    from dlnetbench_tpu.analysis.attribution import (
        dispatch_decomposition, serving_host_us)
    # silicon 100us/step, floor 400us/dispatch; device_us additionally
    # carries prefill time the solve must NOT divide into decode steps
    # (the decode_device_us split)
    one = {"device_us": {"total": 50 * (100.0 + 400.0) + 9999.0},
           "decode_device_us": {"total": 50 * (100.0 + 400.0)},
           "device_steps": 50, "steps_per_dispatch": 1.0,
           "dispatches": 50}
    multi = {"device_us": {"total": 48 * 100.0 + 6 * 400.0 + 9999.0},
             "decode_device_us": {"total": 48 * 100.0 + 6 * 400.0},
             "device_steps": 48, "steps_per_dispatch": 8.0,
             "dispatches": 6}
    dec = dispatch_decomposition(one, multi)
    assert dec is not None
    assert abs(dec["dispatch_us"] - 400.0) < 1.0
    assert abs(dec["silicon_us_per_step"] - 100.0) < 1.0
    # degenerate pair (no fused amortization) refuses
    assert dispatch_decomposition(one, one) is None
    # the fold: N fused steps pay ONE floor
    h1 = serving_host_us({"host_dispatch_us": {"total": 0.0},
                          "dispatches": 50}, dec["dispatch_us"])
    hn = serving_host_us({"host_dispatch_us": {"total": 0.0},
                          "dispatches": 6}, dec["dispatch_us"])
    assert h1 / hn == pytest.approx(50 / 6)
