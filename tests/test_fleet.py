"""Fleet-scale serving (ISSUE 18): the seeded router (round_robin /
p2c / prefix_affinity), the diurnal arrival shape, the shared re-queue
arc, fleet-vs-single-engine token parity, assignment replay
determinism, the committed two-replica record fixture's parser ->
merge round trip, and the elastic/crash e2e arcs."""
from __future__ import annotations

import copy
import json
import math
import time
import types
from pathlib import Path

import jax
import numpy as np
import pytest

from dlnetbench_tpu.metrics import telemetry
from dlnetbench_tpu.models import transformer as tfm
from dlnetbench_tpu.serving.arrivals import ArrivalPlan, Request
from dlnetbench_tpu.serving.fleet import FleetConfig, FleetServer, run_fleet
from dlnetbench_tpu.serving.kv_cache import CacheConfig, PagedKVCache
from dlnetbench_tpu.serving.router import ROUTING_POLICIES, Router
from dlnetbench_tpu.serving.scheduler import Engine, ServingConfig

DATA = Path(__file__).parent / "data"

pytestmark = [pytest.mark.serving, pytest.mark.fleet]


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.disable()
    yield
    telemetry.disable()


def tiny_model(**over) -> tfm.TransformerConfig:
    kw = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
              ff_dim=64, num_layers=2, seq_len=32, gated=True,
              max_positions=0, dtype="float32")
    kw.update(over)
    return tfm.TransformerConfig(**kw)


def fleet_serving(**over) -> ServingConfig:
    kw = dict(slots=2, page_size=8, num_pages=32, max_seq_len=32,
              slo_ttft_ms=250.0, slo_tpot_ms=100.0, attn_impl="gather",
              warmup_requests=0)
    kw.update(over)
    return ServingConfig(**kw)


def burst_trace(n: int, *, prompt=6, output=3) -> ArrivalPlan:
    """All arrivals at t=0: the whole batch routes before any engine
    step, so the router-visible state evolves identically run over run
    (the replay-determinism precondition router.py documents)."""
    return ArrivalPlan(kind="replay", trace=[
        {"t": 0.0, "prompt_len": prompt + (i % 3),
         "output_len": output + (i % 2)} for i in range(n)])


def _fake_engine(queued=0, pending=0, occupied=0, slots=2):
    """A router-visible engine surface: accepted-but-unfinished work
    plus the slot capacity the bounce condition reads."""
    return types.SimpleNamespace(
        queue=[object()] * queued, pending=[object()] * pending,
        slots=[object()] * occupied + [None] * (slots - occupied),
        cfg=types.SimpleNamespace(slots=slots))


def _req(rid: int):
    return types.SimpleNamespace(rid=rid)


# ---------------------------------------------------------------------
# the router: policies, load signal, seeded replayability


def test_router_refusals_and_policy_set():
    assert ROUTING_POLICIES == ("round_robin", "p2c", "prefix_affinity")
    with pytest.raises(ValueError, match="unknown policy"):
        Router("random", 2)
    with pytest.raises(ValueError, match="num_replicas"):
        Router("round_robin", 0)
    r = Router("round_robin", 2)
    with pytest.raises(RuntimeError, match="no active replica"):
        r.pick(_req(0), [_fake_engine(), _fake_engine()], [])


def test_round_robin_cycles_and_skips_inactive():
    engines = [_fake_engine() for _ in range(3)]
    r = Router("round_robin", 3)
    got = [r.pick(_req(i), engines, [0, 1, 2]) for i in range(6)]
    assert got == [0, 1, 2, 0, 1, 2]
    # replica 1 retired: the pointer keeps advancing, dead index skipped
    got = [r.pick(_req(6 + i), engines, [0, 2]) for i in range(4)]
    assert got == [0, 2, 0, 2]
    assert r.counts == [4, 2, 4]
    assert r.assignments[0] == (0, 0) and len(r.assignments) == 10


def test_p2c_prefers_lighter_and_first_draw_wins_ties():
    # two replicas: both draws always land on {0, 1}, so the pick is
    # purely the load comparison — the heavier replica never wins
    light, heavy = _fake_engine(queued=0), _fake_engine(queued=3)
    r = Router("p2c", 2, seed=11)
    got = {r.pick(_req(i), [light, heavy], [0, 1]) for i in range(8)}
    assert got == {0}
    # equal scores: strict < means the FIRST draw wins every tie, so
    # the seeded stream alone determines the sequence
    a = Router("p2c", 2, seed=3)
    b = Router("p2c", 2, seed=3)
    eng = [_fake_engine(), _fake_engine()]
    seq_a = [a.pick(_req(i), eng, [0, 1]) for i in range(16)]
    seq_b = [b.pick(_req(i), eng, [0, 1]) for i in range(16)]
    assert seq_a == seq_b                      # same seed, same stream
    assert set(seq_a) == {0, 1}                # both replicas drawn
    # one active replica: zero draws consumed (router.py's contract)
    c = Router("p2c", 2, seed=3)
    state0 = c._rng.state
    assert c.pick(_req(0), eng, [1]) == 1
    assert c._rng.state == state0


def test_load_score_counts_all_accepted_work():
    assert Router.load_score(_fake_engine()) == 0
    assert Router.load_score(
        _fake_engine(queued=2, pending=1, occupied=2)) == 5
    # the bounce condition: every slot spoken for by resident OR
    # already-queued work
    assert Router._is_full(_fake_engine(occupied=2, slots=2))
    assert Router._is_full(_fake_engine(queued=2, slots=2))
    assert not Router._is_full(_fake_engine(occupied=1, slots=2))


def test_load_histogram_indexes_by_score():
    engines = [_fake_engine(queued=2), _fake_engine()]
    r = Router("round_robin", 2)
    r.pick(_req(0), engines, [0, 1])   # replica 0, score 2
    r.pick(_req(1), engines, [0, 1])   # replica 1, score 0
    assert r.load_samples == [2, 0]
    assert r.load_histogram() == [1, 0, 1]
    assert Router("round_robin", 2).load_histogram() == []


def test_prefix_match_len_probe_is_readonly():
    """The routing probe reports resident prefix tokens (capped at
    prompt_len - 1, like plan_admission) WITHOUT touching the pool's
    admission-time hit-rate counters — N probes per request across a
    fleet must not dilute the per-pool rate the density study reports."""
    cache = PagedKVCache(CacheConfig(
        num_layers=2, num_kv_heads=2, head_dim=16, num_pages=16,
        page_size=4, max_seqs=2, max_pages_per_seq=4).validate())
    toks = np.arange(8)
    cache.allocate(0, 8)
    cache.publish(0, toks)
    before = cache.prefix_lookups
    # same prompt: 7 of 8 tokens match (the final token always
    # re-prefills); a foreign prompt matches nothing
    assert cache.prefix_match_len(toks) == 7
    assert cache.prefix_match_len(np.arange(50, 58)) == 0
    assert cache.prefix_match_len(None) == 0
    assert cache.prefix_match_len(toks[:1]) == 0
    assert cache.prefix_lookups == before
    assert cache.prefix_hits == 0


# ---------------------------------------------------------------------
# the diurnal arrival shape


def test_diurnal_fixture_roundtrip():
    plan = ArrivalPlan.loads(f"@{DATA / 'arrival_diurnal.json'}")
    assert plan.kind == "diurnal" and plan.num_requests == 24
    assert len(plan.phases) == 4 and plan.phases[0][0] == 0.0
    assert plan.to_dict() == json.loads(
        (DATA / "arrival_diurnal.json").read_text())
    a = plan.sample()
    b = ArrivalPlan.from_dict(json.loads(plan.dumps())).sample()
    assert [(r.arrival_s, r.prompt_len, r.output_len) for r in a] \
        == [(r.arrival_s, r.prompt_len, r.output_len) for r in b]
    assert len(a) == 24
    assert all(a[i].arrival_s <= a[i + 1].arrival_s
               for i in range(len(a) - 1))


def test_diurnal_phases_modulate_arrival_density():
    """A trough-then-peak curve must thin the early arrivals and pack
    the late ones: mean inter-arrival gap in the low phase >> in the
    high phase (the shape the autoscaler study rides)."""
    plan = ArrivalPlan(kind="diurnal", rate_rps=50.0, num_requests=60,
                       seed=9, prompt_len=4, output_len=2,
                       phases=[[0.0, 0.2], [0.5, 4.0]])
    ts = [r.arrival_s for r in plan.sample()]
    span = plan.num_requests / plan.rate_rps   # the plan's day length
    gaps_lo = [b - a for a, b in zip(ts, ts[1:]) if a < 0.5 * span]
    gaps_hi = [b - a for a, b in zip(ts, ts[1:]) if a >= 0.5 * span]
    assert gaps_lo and gaps_hi
    mean = lambda xs: sum(xs) / len(xs)                       # noqa: E731
    assert mean(gaps_lo) > 3 * mean(gaps_hi)


# ---------------------------------------------------------------------
# config refusals


def test_fleet_config_refusals():
    with pytest.raises(ValueError, match="replicas"):
        FleetConfig(replicas=0).validate()
    with pytest.raises(ValueError, match="unknown routing"):
        FleetConfig(routing="sticky").validate()
    with pytest.raises(ValueError, match="min_replicas"):
        FleetConfig(replicas=2, min_replicas=3).validate()
    with pytest.raises(ValueError, match="autoscale"):
        FleetConfig(replicas=1, autoscale=True).validate()
    with pytest.raises(ValueError, match="scale_window_s"):
        FleetConfig(scale_window_s=0.0).validate()
    with pytest.raises(ValueError, match="scale_idle_frac"):
        FleetConfig(scale_idle_frac=1.0).validate()
    # a fleet of monolithic engines: disaggregate has no stated split
    with pytest.raises(ValueError, match="disaggregate"):
        FleetServer(tiny_model(),
                    fleet_serving(world=2, disaggregate=True,
                                  prefill_ranks=1, decode_ranks=1),
                    FleetConfig(replicas=2))
    # affinity without tries is a slower p2c — refuse loudly
    with pytest.raises(ValueError, match="prefix_sharing"):
        FleetServer(tiny_model(), fleet_serving(prefix_sharing=False),
                    FleetConfig(routing="prefix_affinity"))
    with pytest.raises(ValueError, match="devices"):
        FleetServer(tiny_model(), fleet_serving(),
                    FleetConfig(replicas=2),
                    devices=jax.devices()[:1])


# ---------------------------------------------------------------------
# the shared re-queue arc (serving/requeue.py)


def test_requeue_keeps_original_stamps_and_orders_by_arrival():
    from dlnetbench_tpu.serving import requeue

    reqs = [Request(rid=2, arrival_s=0.7, prompt_len=4, output_len=2),
            Request(rid=0, arrival_s=0.1, prompt_len=4, output_len=2),
            Request(rid=1, arrival_s=0.1, prompt_len=4, output_len=2)]
    src = types.SimpleNamespace(drain_unfinished=lambda: list(reqs))
    out = requeue.requeue_unfinished(src)
    assert [(r.rid, r.arrival_s) for r in out] \
        == [(0, 0.1), (1, 0.1), (2, 0.7)]   # ORIGINAL stamps, in order


def test_detect_shrink_classifies_and_rereaises():
    from dlnetbench_tpu.faults.inject import RankFailure
    from dlnetbench_tpu.faults.plan import FaultEvent, FaultPlan
    from dlnetbench_tpu.serving import requeue

    inj = types.SimpleNamespace(crash_raised_at=time.monotonic())
    fp = FaultPlan(events=[FaultEvent(kind="crash", ranks=[0],
                                      iteration=1)], policy="shrink")
    det, surv = requeue.detect_shrink(
        RankFailure(0, 1), injector=inj, fault_plan=fp, world=2, step=3)
    assert det >= 0 and surv == [1]
    # anything else is not this arc's to absorb
    with pytest.raises(ValueError, match="boom"):
        requeue.detect_shrink(ValueError("boom"), injector=inj,
                              fault_plan=fp, world=2, step=3)
    ff = FaultPlan(events=[FaultEvent(kind="crash", ranks=[0],
                                      iteration=1)], policy="fail_fast")
    with pytest.raises(RankFailure):
        requeue.detect_shrink(RankFailure(0, 1), injector=inj,
                              fault_plan=ff, world=2, step=3)


# ---------------------------------------------------------------------
# fleet e2e: lossless routing (token parity) + replayable assignment


def test_fleet_token_parity_and_assignment_replay():
    """Routing is lossless placement: a 2-replica fleet's greedy
    streams are IDENTICAL to a single engine's over the same weights
    and requests, for every policy.  And routing is replayable: the
    same plan + seed + policy reproduces the same assignment log run
    over run (t=0 burst — router.py's determinism precondition)."""
    if len(jax.devices()) < 2:
        pytest.skip("fleet needs >= 2 devices")
    mc = tiny_model()
    cfg = fleet_serving(prefix_sharing=True)
    plan = burst_trace(8)
    params = tfm.init_params(jax.random.PRNGKey(0), mc)

    single = Engine(mc, cfg, params=params)
    single.run(plan.sample())
    ref_streams = {rid: list(t) for rid, t in
                   single.token_streams.items()}
    assert len(ref_streams) == 8

    for policy in ROUTING_POLICIES:
        srv = FleetServer(mc, cfg,
                          FleetConfig(replicas=2, routing=policy,
                                      route_seed=4),
                          params=params, devices=jax.devices()[:2])
        completed, _ = srv.run(plan.sample())
        assert len(completed) == 8
        assert srv.token_streams == ref_streams, policy
        first = list(srv.router.assignments)
        assert sum(srv.router.counts) == 8
        blk = srv.fleet_block(completed)
        assert blk["requests_per_replica"] == srv.router.counts
        assert sum(blk["load_histogram"]) == 8
        assert blk["chip_seconds_used"] > 0
        assert blk["chip_seconds_saved"] == 0.0   # no autoscaler
        # replay: the measured run starts from the seeded origin
        completed2, _ = srv.run(plan.sample())
        assert len(completed2) == 8
        assert list(srv.router.assignments) == first, policy
    # round_robin on a burst splits the batch evenly by construction
    rr = FleetServer(mc, cfg, FleetConfig(replicas=2), params=params,
                     devices=jax.devices()[:2])
    rr.run(plan.sample())
    assert rr.router.counts == [4, 4]


# ---------------------------------------------------------------------
# the record pathway: committed two-replica fixture round trip


def test_fleet_record_fixture_roundtrip():
    """The committed fleet record (a REAL 2-replica p2c run of
    serving/fleet.run_fleet) flows parser -> merge -> summary with the
    routing provenance and chip-second columns populated."""
    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe,
                                               validate_record)
    records = load_records(DATA / "record_fleet.jsonl")
    assert len(records) == 1
    rec = records[0]
    validate_record(rec)
    g = rec["global"]
    assert g["fleet_routing"] == "p2c" and g["fleet_replicas"] == 2
    flt = g["fleet"]
    assert sum(flt["requests_per_replica"]) == 10
    assert flt["chip_seconds_used"] > 0
    assert flt["slo_goodput_per_chip_s"] > 0

    df = records_to_dataframe(records)
    for col in ("fleet_routing", "fleet_replicas",
                "fleet_replica_req_max", "fleet_replica_req_min",
                "fleet_chip_seconds_used", "fleet_chip_seconds_saved",
                "fleet_slo_goodput_per_chip_s", "fleet_scale_events"):
        assert col in df.columns, col
    assert df["fleet_replica_req_max"].iloc[0] == \
        max(flt["requests_per_replica"])

    merged = merge_records(records)   # single-process identity
    validate_record(merged)
    row = serving_summary([merged]).iloc[0]
    assert row["routing"] == "p2c" and row["replicas"] == 2
    assert row["goodput_per_chip_s"] == flt["slo_goodput_per_chip_s"]
    assert not math.isnan(row["chip_seconds_saved"])


def test_fleet_merge_volatile_vs_identity_split():
    """The ``fleet`` measurement block is VOLATILE (live load scores
    and chip-second spend differ per host — merge pools them), but
    ``fleet_routing``/``fleet_replicas`` are run IDENTITY: a p2c
    record must never merge with a round_robin one."""
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import load_records

    base = load_records(DATA / "record_fleet.jsonl")[0]
    a, b = copy.deepcopy(base), copy.deepcopy(base)
    a["global"]["num_processes"] = b["global"]["num_processes"] = 2
    b["process"] = 1
    # split the two replicas' rank rows across the two hosts
    a["ranks"] = [r for r in a["ranks"] if r["rank"] == 0]
    b["ranks"] = [dict(r, process_index=1) for r in b["ranks"]
                  if r["rank"] == 1]
    b["global"]["fleet"] = dict(
        b["global"]["fleet"], chip_seconds_used=99.0,
        load_histogram=[0, 1])     # volatile: differing is fine
    merged = merge_records([a, b])
    assert merged["global"]["fleet_routing"] == "p2c"
    assert sorted(r["rank"] for r in merged["ranks"]) == [0, 1]

    c = copy.deepcopy(b)
    c["global"]["fleet_routing"] = "round_robin"
    with pytest.raises(ValueError, match="fleet_routing"):
        merge_records([a, c])
    d = copy.deepcopy(b)
    d["global"]["fleet_replicas"] = 4
    with pytest.raises(ValueError, match="fleet_replicas"):
        merge_records([a, d])
    # pre-fleet single-engine records never grew the columns
    mono = load_records(DATA / "record_serving.jsonl")
    from dlnetbench_tpu.metrics.parser import records_to_dataframe
    assert "fleet_routing" not in records_to_dataframe(mono).columns


def test_single_engine_serving_summary_defaults():
    """Pre-fleet records summarize with the neutral provenance — one
    replica, no routing policy — so fleet and single-engine rows sit
    in one table."""
    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.parser import load_records

    row = serving_summary(
        load_records(DATA / "record_serving.jsonl")).iloc[0]
    assert row["routing"] == "-" and row["replicas"] == 1
    assert math.isnan(row["goodput_per_chip_s"])


# ---------------------------------------------------------------------
# elastic capacity + crash arcs (heavy: real schedules, wall clocks)


@pytest.mark.slow
def test_autoscale_drains_trough_and_rebuilds_for_peak():
    """Two bursts with a dead trough between them: the autoscaler
    drains a replica in the trough (chip-seconds saved on the meter)
    and rebuilds it for the second burst (recompile priced into the
    scale event); every request still completes."""
    if len(jax.devices()) < 2:
        pytest.skip("fleet needs >= 2 devices")
    mc = tiny_model()
    cfg = fleet_serving()
    trace = [{"t": 0.002 * i, "prompt_len": 6, "output_len": 3}
             for i in range(4)]
    # the second burst lands SIMULTANEOUSLY so one routing tick sees
    # the whole backlog (spaced arrivals would drain one-per-step on a
    # warm survivor and never build queue pressure)
    trace += [{"t": 2.2, "prompt_len": 6, "output_len": 8}
              for _ in range(8)]
    plan = ArrivalPlan(kind="replay", trace=trace)
    srv = FleetServer(
        mc, cfg,
        FleetConfig(replicas=2, autoscale=True, min_replicas=1,
                    scale_window_s=0.15, scale_idle_frac=0.5,
                    scale_cooldown_s=0.3))
    completed, _ = srv.run(plan.sample())
    assert len(completed) == len(trace)        # nothing lost to scaling
    kinds = [e["kind"] for e in srv.scale_events]
    assert "scale_down" in kinds
    assert "scale_up" in kinds
    up = next(e for e in srv.scale_events if e["kind"] == "scale_up")
    assert up["scale_up_ms"] > 0 and up["reason"] in (
        "queue_pressure", "slo_breach")
    # the autoscaler's retiree parks WARM: revival is a state reset,
    # not a recompile, and the event says so
    assert up["warm"] is True
    used, saved = srv.chip_seconds()
    assert saved > 0           # the trough's retired seconds, metered
    assert used > 0
    blk = srv.fleet_block(completed)
    assert blk["chip_seconds_saved"] == round(saved, 4)
    assert blk["scale_events"] == srv.scale_events


@pytest.mark.slow
def test_replica_crash_reroutes_to_survivor():
    """Crash replica 0 mid-plan under shrink: its in-flight work
    re-queues with ORIGINAL stamps, the router stops offering the dead
    replica, the survivor absorbs everything, and the record stamps
    the crash event + fault provenance."""
    if len(jax.devices()) < 2:
        pytest.skip("fleet needs >= 2 devices")
    from dlnetbench_tpu.faults.plan import FaultEvent, FaultPlan
    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.metrics.parser import validate_record

    mc = tiny_model()
    cfg = fleet_serving()
    trace = [{"t": 0.01 * i, "prompt_len": 6, "output_len": 4}
             for i in range(10)]
    plan = ArrivalPlan(kind="replay", trace=trace)
    fp = FaultPlan(events=[FaultEvent(kind="crash", ranks=[0],
                                      iteration=4)], policy="shrink")
    res = run_fleet(mc, cfg, plan, FleetConfig(replicas=2),
                    fault_plan=fp)
    assert res.num_runs == len(trace)          # every request completes
    g = res.global_meta
    assert g["fault_policy"] == "shrink"
    crash = [e for e in g["fleet"]["scale_events"]
             if e["kind"] == "replica_crash"]
    assert len(crash) == 1 and crash[0]["replica"] == 0
    assert crash[0]["detection_ms"] >= 0
    # post-crash requests all landed on the survivor: replica 0's
    # count stops where the crash caught it
    per = g["fleet"]["requests_per_replica"]
    assert per[1] > per[0]
    validate_record(result_to_record(res))
