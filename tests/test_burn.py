"""Burn-kernel calibration tests (CPU: numbers are arbitrary but the
calibration contract — linearity and budget mapping — must hold)."""
import jax
import jax.numpy as jnp
import numpy as np

from dlnetbench_tpu.proxies import burn as burnlib
from dlnetbench_tpu.utils.timing import time_callable


def test_burn_zero_iters_identity():
    s = burnlib.make_state()
    out = burnlib.burn(s, 0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(s))


def test_burn_deterministic_and_bounded():
    s = burnlib.make_state()
    a = jax.jit(lambda v: burnlib.burn(v, 10))(s)
    b = jax.jit(lambda v: burnlib.burn(v, 10))(s)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.all(np.abs(np.asarray(a, dtype=np.float32)) <= 1.0)
    assert np.all(np.isfinite(np.asarray(a, dtype=np.float32)))


def test_calibration_budget_mapping():
    cal = burnlib.calibrate()
    assert cal.ns_per_iter > 0
    assert cal.iters_for_us(0) == 0
    n = cal.iters_for_us(1000.0)
    assert n >= 1
    # round trip within one iteration
    assert abs(cal.us_for_iters(n) - 1000.0) <= cal.ns_per_iter / 1000.0


def test_burn_time_scales_linearly():
    cal = burnlib.calibrate()
    s = burnlib.make_state()
    f1 = jax.jit(lambda v: burnlib.burn(v, 200))
    f4 = jax.jit(lambda v: burnlib.burn(v, 800))
    f1(s).block_until_ready(); f4(s).block_until_ready()
    t1 = min(time_callable(f1, s, reps=5))
    t4 = min(time_callable(f4, s, reps=5))
    ratio = (t4 - t1) / max(t1, 1e-9)
    # 4x iters => ~3x extra time over the base measurement; allow wide
    # tolerance for CI noise but reject constant-time (DCE'd) behavior
    assert t4 > t1 * 1.5, (t1, t4, ratio)
