"""Artifact-grade stat bands (metrics/stats.py) and record schema v2
(metrics/emit.py): band summaries ride every timer, transport provenance
rides every record, and committed v1 records keep parsing/merging.
"""
from __future__ import annotations

import json
from pathlib import Path

import pytest

from dlnetbench_tpu.metrics.stats import flag_low_mode, summarize

V1_FIXTURE = Path(__file__).parent / "data" / "record_v1.jsonl"


# ---------------------------------------------------------------------
# stats.summarize / flag_low_mode


def test_summarize_band_shape():
    s = summarize([3.0, 1.0, 2.0])
    assert s == {"value": 2.0, "best": 1.0, "band": [1.0, 3.0], "n": 3}


def test_summarize_empty_and_rounding():
    assert summarize([]) == {"value": 0.0, "best": 0.0,
                             "band": [0.0, 0.0], "n": 0}
    s = summarize([1.23456, 2.34567], ndigits=2)
    assert s["value"] == pytest.approx(1.79)
    assert s["band"] == [1.23, 2.35]


def test_flag_low_mode_flags_bimodal():
    line = flag_low_mode({"value": 100.0, "best": 40.0, "n": 3})
    assert "bimodal" in line["note"]
    # appends to an existing note (the above-peak flag) instead of
    # clobbering it
    line2 = flag_low_mode({"value": 100.0, "best": 40.0, "n": 3,
                           "note": "above-peak reading"})
    assert line2["note"].startswith("above-peak reading; ")


def test_flag_low_mode_leaves_unimodal_and_tiny_n_alone():
    assert "note" not in flag_low_mode({"value": 100.0, "best": 85.0,
                                        "n": 3})
    # n=1 can't witness bimodality; absent best can't either
    assert "note" not in flag_low_mode({"value": 100.0, "best": 10.0,
                                        "n": 1})
    assert "note" not in flag_low_mode({"value": 100.0})


def test_bench_band_helpers():
    import bench

    s = {"value": 0.002, "best": 0.001, "band": [0.001, 0.003], "n": 3}
    ms = bench._band_ms(s)
    assert ms == {"best": 1.0, "band": [1.0, 3.0], "n": 3}
    comb = bench._combine_linear([(2, s), (1, s)])
    assert comb["value"] == pytest.approx(0.006)
    assert comb["best"] == pytest.approx(0.003)
    assert comb["band"][1] == pytest.approx(0.009)
    assert comb["n"] == 3


# ---------------------------------------------------------------------
# schema v2 emission


def _fake_result(timers=None, mesh=None):
    from dlnetbench_tpu.proxies.base import ProxyResult

    mesh = mesh if mesh is not None else {
        "platform": "cpu", "device_kind": "host", "num_hosts": 1,
        "devices": [{"id": 0, "process": 0}, {"id": 1, "process": 0}]}
    return ProxyResult(
        name="dp",
        global_meta={"proxy": "dp", "model": "m", "world_size": 2,
                     "mesh": mesh},
        timers_us=timers or {"runtimes": [100.0, 50.0, 110.0],
                             "barrier_time": [10.0, 11.0, 12.0]},
        warmup_times_us=[900.0],
        num_runs=3,
    )


def test_v2_record_carries_summaries_and_transport():
    from dlnetbench_tpu.metrics.emit import SCHEMA_VERSION, result_to_record
    from dlnetbench_tpu.metrics.parser import validate_record

    assert SCHEMA_VERSION == 2
    rec = result_to_record(_fake_result())
    assert rec["version"] == 2
    assert rec["global"]["transport"] == "virtual-host"
    for row in rec["ranks"]:
        s = row["summary"]["runtimes"]
        assert s["value"] == 100.0 and s["best"] == 50.0
        assert s["band"] == [50.0, 110.0] and s["n"] == 3
        assert row["summary"]["barrier_time"]["n"] == 3
    validate_record(rec)
    # the record is json-serializable as emitted
    json.dumps(rec)


def test_transport_label_tiers():
    from dlnetbench_tpu.metrics.emit import transport_label

    assert transport_label({"platform": "cpu"}) == "virtual-host"
    assert transport_label({"platform": "tpu", "num_hosts": 1}) == "ici"
    assert transport_label({"platform": "tpu",
                            "num_hosts": 4}) == "ici+dcn"
    assert transport_label({}) == "unknown"


def test_v2_summary_must_match_samples():
    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.metrics.parser import validate_record

    rec = result_to_record(_fake_result())
    rec["ranks"][0]["summary"]["runtimes"]["n"] = 99
    with pytest.raises(ValueError, match="claims n=99"):
        validate_record(rec)


def test_presstamped_transport_wins():
    from dlnetbench_tpu.metrics.emit import result_to_record

    r = _fake_result()
    r.global_meta["transport"] = "tcp:ethernet"
    assert result_to_record(r)["global"]["transport"] == "tcp:ethernet"


# ---------------------------------------------------------------------
# v1 backward compatibility — the committed fixture must keep parsing
# through every consumer for as long as old artifacts exist


def test_committed_v1_fixture_still_parses():
    from dlnetbench_tpu.metrics.parser import (
        load_records, records_to_dataframe, validate_record)

    recs = load_records(V1_FIXTURE)
    assert len(recs) == 1 and recs[0]["version"] == 1
    assert "summary" not in recs[0]["ranks"][0]
    validate_record(recs[0])
    df = records_to_dataframe(recs)
    assert len(df) == 2 * recs[0]["num_runs"]
    assert (df["runtime"] > 0).all()


def test_v1_fixture_flows_through_bandwidth_with_transport():
    from dlnetbench_tpu.analysis.bandwidth import bandwidth_summary
    from dlnetbench_tpu.metrics.parser import load_records

    recs = load_records(V1_FIXTURE)
    summary = bandwidth_summary(recs)
    assert not summary.empty
    # no stamped transport: classified from the backend it does declare
    assert (summary["transport"] == "shm").all()


def test_merge_refuses_mixed_schema_versions():
    from dlnetbench_tpu.metrics.merge import merge_records

    v1 = json.loads(V1_FIXTURE.read_text())
    v1["global"]["num_processes"] = 2
    v1["ranks"][1]["process_index"] = 1
    v2 = json.loads(json.dumps(v1))
    v2["version"] = 2
    v2["process"] = 1
    v2["ranks"][1]["hostname"] = "host1"
    with pytest.raises(ValueError, match="schema version"):
        merge_records([v1, v2])


def test_v2_summary_dicts_are_per_row():
    """Dropping a key from one row's summary (the merge energy dedup
    does exactly this) must not edit sibling rows."""
    from dlnetbench_tpu.metrics.emit import result_to_record

    rec = result_to_record(_fake_result())
    del rec["ranks"][0]["summary"]["runtimes"]
    assert "runtimes" in rec["ranks"][1]["summary"]


def test_merge_energy_dedup_strips_summary_too():
    """Co-hosted processes: the deduped row must lose energy_consumed
    from BOTH channels — the raw array and the v2 band summary readers
    are told to consume — while the surviving row keeps both."""
    from dlnetbench_tpu.metrics.merge import merge_records

    def proc_rec(p):
        rec = json.loads(V1_FIXTURE.read_text())
        rec["version"] = 2
        rec["process"] = p
        rec["global"]["num_processes"] = 2
        for i, row in enumerate(rec["ranks"]):
            row["process_index"] = i
            row["hostname"] = "samehost"  # co-hosted: one counter
            row["energy_consumed"] = [5.0 + p, 6.0 + p]
            row["summary"] = {
                "runtimes": summarize(row["runtimes"]),
                "energy_consumed": summarize(row["energy_consumed"]),
            }
        return rec

    merged = merge_records([proc_rec(0), proc_rec(1)])
    keeper, deduped = merged["ranks"]
    assert "energy_consumed" in keeper
    assert "energy_consumed" in keeper["summary"]
    assert "energy_consumed" not in deduped
    assert "energy_consumed" not in deduped["summary"]
    assert "runtimes" in deduped["summary"]  # only energy was deduped


def test_merge_keeps_v2_summaries_per_process():
    from dlnetbench_tpu.metrics.merge import merge_records

    def proc_rec(p):
        rec = json.loads(V1_FIXTURE.read_text())
        rec["version"] = 2
        rec["process"] = p
        rec["global"]["num_processes"] = 2
        for i, row in enumerate(rec["ranks"]):
            row["process_index"] = i  # rank i owned by process i
            row["hostname"] = f"host{i}"
            row["runtimes"] = [100.0 + 10 * p, 110.0 + 10 * p]
            row["summary"] = {"runtimes": summarize(row["runtimes"])}
        return rec

    merged = merge_records([proc_rec(0), proc_rec(1)])
    assert merged["version"] == 2
    # each process's rows keep ITS summaries (its own clock's bands)
    assert merged["ranks"][0]["summary"]["runtimes"]["best"] == 100.0
    assert merged["ranks"][1]["summary"]["runtimes"]["best"] == 110.0
