"""End-to-end tests for the FSDP and hybrid (2D/3D/3D-MoE) proxies on the
8-device virtual CPU mesh."""
import pytest

from dlnetbench_tpu.core.model_card import load_model_card
from dlnetbench_tpu.core.model_stats import load_model_stats
from dlnetbench_tpu.proxies import fsdp as fsdp_proxy
from dlnetbench_tpu.proxies import hybrid_2d, hybrid_3d, hybrid_3d_moe
from dlnetbench_tpu.proxies.base import ProxyConfig, run_proxy

TINY = dict(size_scale=1e-6, time_scale=5e-5)
CFG = ProxyConfig(warmup=1, runs=2, **TINY)


def _stats(name):
    return load_model_stats(name)


def test_fsdp_sharded_world(eight_devices):
    bundle = fsdp_proxy.build(_stats("llama3_8b_16_bfloat16"), 4, CFG,
                              devices=eight_devices)
    result = run_proxy("fsdp", bundle, CFG)
    g = result.global_meta
    assert g["sharding_factor"] == 8 and g["num_replicas"] == 1
    assert len(result.timers_us["runtimes"]) == 2
    assert "allgather_time" in result.timers_us
    assert "reduce_scatter_time" in result.timers_us
    assert all(t > 0 for t in result.timers_us["allgather_time"])


def test_fsdp_hybrid_replicas(eight_devices):
    bundle = fsdp_proxy.build(_stats("llama3_8b_16_bfloat16"), 3, CFG,
                              devices=eight_devices, sharding_factor=4)
    result = run_proxy("fsdp", bundle, CFG)
    g = result.global_meta
    assert g["sharding_factor"] == 4 and g["num_replicas"] == 2
    assert g["mesh"]["axes"] == {"dp": 2, "tp": 4}


def test_fsdp_bad_factor(eight_devices):
    with pytest.raises(ValueError, match="divisible"):
        fsdp_proxy.build(_stats("llama3_8b_16_bfloat16"), 4, CFG,
                         devices=eight_devices, sharding_factor=3)


def test_hybrid_2d(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    bundle = hybrid_2d.build(stats, card, CFG, num_stages=4,
                             num_microbatches=4, devices=eight_devices)
    result = run_proxy("hybrid_2d", bundle, CFG)
    g = result.global_meta
    assert g["dp"] == 2 and g["num_stages"] == 4  # dp inferred: 8/(4*1)
    assert g["layers_per_stage"] == 8
    assert "pp_comm_time" in result.timers_us
    assert "dp_comm_time" in result.timers_us
    assert all(t > 0 for t in result.timers_us["runtimes"])


def test_hybrid_3d(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    bundle = hybrid_3d.build(stats, card, CFG, num_stages=2,
                             num_microbatches=4, tp=2, devices=eight_devices)
    result = run_proxy("hybrid_3d", bundle, CFG)
    g = result.global_meta
    assert g["dp"] == 2 and g["tp"] == 2
    assert g["tp_msg_bytes"] > 0
    assert "tp_comm_time" in result.timers_us
    assert "pp_comm_time" in result.timers_us


def test_hybrid_3d_world_mismatch(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    with pytest.raises(ValueError, match="not divisible"):
        hybrid_3d.build(stats, card, CFG, num_stages=2, num_microbatches=4,
                        tp=3, devices=eight_devices)


def test_hybrid_3d_moe(eight_devices):
    stats = _stats("mixtral_8x7b_16_bfloat16")
    card = load_model_card("mixtral_8x7b")
    bundle = hybrid_3d_moe.build(stats, card, CFG, num_stages=4,
                                 num_microbatches=2, num_expert_shards=2,
                                 devices=eight_devices)
    result = run_proxy("hybrid_3d_moe", bundle, CFG)
    g = result.global_meta
    assert g["dp"] == 1 and g["num_expert_shards"] == 2
    assert g["a2a_bytes"] > 0
    assert "ep_comm_time" in result.timers_us
    assert "dp_ep_comm_time" in result.timers_us


def test_moe_requires_moe_card(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    with pytest.raises(ValueError, match="moe_params"):
        hybrid_3d_moe.build(stats, card, CFG, num_stages=4,
                            num_microbatches=2, num_expert_shards=2,
                            devices=eight_devices)


@pytest.mark.parametrize("mode_build,kw", [
    (hybrid_2d.build, {}),
    (hybrid_3d.build, {"tp": 2}),
    (hybrid_3d_moe.build, {"num_expert_shards": 2}),
])
def test_1f1b_schedule_runs(eight_devices, mode_build, kw):
    """1F1B (rebuild extra — the reference only has GPipe) must run end to
    end with the same microbatch totals and tag the record."""
    model = ("mixtral_8x7b" if mode_build is hybrid_3d_moe.build
             else "llama3_8b")
    stats = _stats(f"{model}_16_bfloat16")
    card = load_model_card(model)
    bundle = mode_build(stats, card, CFG, num_stages=2, num_microbatches=4,
                        schedule="1f1b", **kw)
    assert bundle.global_meta["schedule"] == "1f1b"
    res = run_proxy(bundle.global_meta["proxy"], bundle, CFG)
    assert len(res.timers_us["runtimes"]) == CFG.runs
    assert all(t > 0 for t in res.timers_us["runtimes"])
    assert "pp_comm_time" in res.timers_us


def test_unknown_schedule_rejected(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    with pytest.raises(ValueError, match="schedule"):
        hybrid_2d.build(stats, card, CFG, num_stages=2, num_microbatches=4,
                        schedule="zb")
