"""End-to-end tests for the FSDP and hybrid (2D/3D/3D-MoE) proxies on the
8-device virtual CPU mesh."""
import pytest

from dlnetbench_tpu.core.model_card import load_model_card
from dlnetbench_tpu.core.model_stats import load_model_stats
from dlnetbench_tpu.proxies import fsdp as fsdp_proxy
from dlnetbench_tpu.proxies import hybrid_2d, hybrid_3d, hybrid_3d_moe
from dlnetbench_tpu.proxies.base import ProxyConfig, run_proxy

TINY = dict(size_scale=1e-6, time_scale=5e-5)
CFG = ProxyConfig(warmup=1, runs=2, **TINY)


def _stats(name):
    return load_model_stats(name)


def test_fsdp_sharded_world(eight_devices):
    bundle = fsdp_proxy.build(_stats("llama3_8b_16_bfloat16"), 4, CFG,
                              devices=eight_devices)
    result = run_proxy("fsdp", bundle, CFG)
    g = result.global_meta
    assert g["sharding_factor"] == 8 and g["num_replicas"] == 1
    assert len(result.timers_us["runtimes"]) == 2
    assert "allgather_time" in result.timers_us
    assert "reduce_scatter_time" in result.timers_us
    assert all(t > 0 for t in result.timers_us["allgather_time"])


def test_fsdp_hybrid_replicas(eight_devices):
    bundle = fsdp_proxy.build(_stats("llama3_8b_16_bfloat16"), 3, CFG,
                              devices=eight_devices, sharding_factor=4)
    result = run_proxy("fsdp", bundle, CFG)
    g = result.global_meta
    assert g["sharding_factor"] == 4 and g["num_replicas"] == 2
    assert g["mesh"]["axes"] == {"dp": 2, "tp": 4}


def test_fsdp_bad_factor(eight_devices):
    with pytest.raises(ValueError, match="divisible"):
        fsdp_proxy.build(_stats("llama3_8b_16_bfloat16"), 4, CFG,
                         devices=eight_devices, sharding_factor=3)


def test_hybrid_2d(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    bundle = hybrid_2d.build(stats, card, CFG, num_stages=4,
                             num_microbatches=4, devices=eight_devices)
    result = run_proxy("hybrid_2d", bundle, CFG)
    g = result.global_meta
    assert g["dp"] == 2 and g["num_stages"] == 4  # dp inferred: 8/(4*1)
    assert g["layers_per_stage"] == 8
    assert "pp_comm_time" in result.timers_us
    assert "dp_comm_time" in result.timers_us
    assert all(t > 0 for t in result.timers_us["runtimes"])


def test_hybrid_3d(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    bundle = hybrid_3d.build(stats, card, CFG, num_stages=2,
                             num_microbatches=4, tp=2, devices=eight_devices)
    result = run_proxy("hybrid_3d", bundle, CFG)
    g = result.global_meta
    assert g["dp"] == 2 and g["tp"] == 2
    assert g["tp_msg_bytes"] > 0
    assert "tp_comm_time" in result.timers_us
    assert "pp_comm_time" in result.timers_us


def test_hybrid_3d_world_mismatch(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    with pytest.raises(ValueError, match="not divisible"):
        hybrid_3d.build(stats, card, CFG, num_stages=2, num_microbatches=4,
                        tp=3, devices=eight_devices)


def test_hybrid_3d_moe(eight_devices):
    stats = _stats("mixtral_8x7b_16_bfloat16")
    card = load_model_card("mixtral_8x7b")
    bundle = hybrid_3d_moe.build(stats, card, CFG, num_stages=4,
                                 num_microbatches=2, num_expert_shards=2,
                                 devices=eight_devices)
    result = run_proxy("hybrid_3d_moe", bundle, CFG)
    g = result.global_meta
    assert g["dp"] == 1 and g["num_expert_shards"] == 2
    assert g["a2a_bytes"] > 0
    assert "ep_comm_time" in result.timers_us
    assert "dp_ep_comm_time" in result.timers_us


def test_moe_requires_moe_card(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    with pytest.raises(ValueError, match="moe_params"):
        hybrid_3d_moe.build(stats, card, CFG, num_stages=4,
                            num_microbatches=2, num_expert_shards=2,
                            devices=eight_devices)


@pytest.mark.parametrize("schedule", ["1f1b", "zb"])
@pytest.mark.parametrize("mode_build,kw", [
    (hybrid_2d.build, {}),
    (hybrid_3d.build, {"tp": 2}),
    (hybrid_3d_moe.build, {"num_expert_shards": 2}),
])
def test_extra_schedules_run(eight_devices, mode_build, kw, schedule):
    """1F1B and ZB-H1 (rebuild extras — the reference only has GPipe)
    must run end to end with the same microbatch totals and tag the
    record."""
    model = ("mixtral_8x7b" if mode_build is hybrid_3d_moe.build
             else "llama3_8b")
    stats = _stats(f"{model}_16_bfloat16")
    card = load_model_card(model)
    bundle = mode_build(stats, card, CFG, num_stages=2, num_microbatches=4,
                        schedule=schedule, **kw)
    assert bundle.global_meta["schedule"] == schedule
    res = run_proxy(bundle.global_meta["proxy"], bundle, CFG)
    assert len(res.timers_us["runtimes"]) == CFG.runs
    assert all(t > 0 for t in res.timers_us["runtimes"])
    assert "pp_comm_time" in res.timers_us


def test_zb_tick_accounting(eight_devices):
    """The zb record advertises the zero-bubble clock: 3M + (S-1) unit
    ticks, vs the 2-phase schedules' 3(M+S-1) (their 2(M+S-1) ticks count
    a 2-unit backward tick double) — and the same edge-message invariant
    as every other schedule."""
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    bundle = hybrid_2d.build(stats, card, CFG, num_stages=4,
                             num_microbatches=8, dp=2, schedule="zb")
    g = bundle.global_meta
    assert g["ticks_total"] == 3 * 8 + 3
    assert g["pp_edge_messages"] == 2 * 8 * 3


def test_unknown_schedule_rejected(eight_devices):
    stats = _stats("llama3_8b_16_bfloat16")
    card = load_model_card("llama3_8b")
    with pytest.raises(ValueError, match="schedule"):
        hybrid_2d.build(stats, card, CFG, num_stages=2, num_microbatches=4,
                        schedule="interleaved")


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_bubble_modeled(eight_devices, schedule):
    """The fill/drain bubble (reference hybrid_2d.cpp:106-133: stage s's
    first compute serialized behind s upstream computes) must show in
    measured runtime: at fixed S*M the per-iteration wall time scales with
    (M + S - 1)/(S*M), NOT with M/(S*M) as a bubble-free steady-state
    schedule would.

    S=2,M=8 -> 9 tick-units of 1/16 model time; S=4,M=4 -> 7 tick-units.
    Bubble modeled: t(S=4)/t(S=2) ~ 7/9 = 0.78; bubble missing: ~ 0.5."""
    import os
    from dlnetbench_tpu.core.model_card import load_model_card
    # the analytic tick model assumes each active stage burns on its own
    # processor; with fewer cores than stages the device threads
    # timeshare and the measured ratio settles ~0.6 regardless of the
    # schedule (observed on a 2-core host) — no discriminating power
    if (os.cpu_count() or 1) < 4:
        pytest.skip(f"needs >= 1 core per stage (S=4) for the "
                    f"tick-parallel timing model; host has "
                    f"{os.cpu_count()} cores")
    stats = _stats("gpt2_l_16_bfloat16")
    card = load_model_card("gpt2_l")
    cfg = ProxyConfig(warmup=2, runs=3, size_scale=1e-6, time_scale=0.5)

    times = {}
    for S, M in ((2, 8), (4, 4)):
        bundle = hybrid_2d.build(stats, card, cfg, num_stages=S,
                                 num_microbatches=M, dp=1,
                                 schedule=schedule,
                                 devices=eight_devices[:S])
        assert bundle.global_meta["ticks_per_direction"] == M + S - 1
        # the masking invariant: every edge still carries exactly one
        # message per microbatch per direction despite the extra ticks
        assert bundle.global_meta["pp_edge_messages"] == 2 * M * (S - 1)
        res = run_proxy("hybrid_2d", bundle, cfg)
        times[S] = min(res.timers_us["runtimes"])

    ratio = times[4] / times[2]
    # analytic: 7/9 = 0.78 with the bubble, 0.5 without.  The LOWER bound
    # is the discriminator (a missing bubble lands at ~0.5); the upper
    # bound only guards against pathology and stays loose — CPU-mesh burn
    # jitter under load has been observed pushing the ratio past 1.1.
    assert 0.62 < ratio < 1.6, (
        f"{schedule}: t(S=4)/t(S=2) = {ratio:.3f}; expected ~0.78 "
        f"(bubble modeled) — 0.5 means the fill/drain bubble is missing")


def test_1f1b_updown_hops_independent_gpipe_chained(eight_devices):
    """VERDICT r1 #5: the 1F1B overlap claim, verified against the program
    rather than asserted.  Whether the up and down pipe hops of a steady
    1F1B pair can ride the bidirectional links together is a dataflow
    property — XLA may only overlap ops with no dependency path between
    them.  This must hold in the traced program (and fail if the
    independent-carry structure regresses); GPipe's hops must instead form
    one serial chain, which is what makes its two phases serial."""
    from dlnetbench_tpu.core.model_card import load_model_card
    from dlnetbench_tpu.metrics.profiling import permute_dependencies

    stats = _stats("gpt2_l_16_bfloat16")
    card = load_model_card("gpt2_l")
    cfg = ProxyConfig(warmup=1, runs=1, size_scale=1e-5, time_scale=1e-5)
    S, M = 4, 8

    deps_of = {}
    for sch in ("gpipe", "1f1b"):
        bundle = hybrid_2d.build(stats, card, cfg, num_stages=S,
                                 num_microbatches=M, dp=1, schedule=sch,
                                 devices=eight_devices[:S])
        n, deps = permute_dependencies(bundle.variants["pp_comm"])
        deps_of[sch] = (n, deps)

    # gpipe: every later hop transitively depends on every earlier one
    n, deps = deps_of["gpipe"]
    assert n > 0
    assert all((i, i + 1) in deps for i in range(n - 1)), \
        "GPipe hops must form a serial chain"

    # 1f1b: the steady phase interleaves up/down on independent carries —
    # most adjacent pairs must be mutually schedulable (no dependency)
    n, deps = deps_of["1f1b"]
    indep = [i for i in range(n - 1) if (i, i + 1) not in deps]
    # S-1 fill hops chain; of the remaining adjacent pairs the steady
    # up/down interleave must be independent (allow edge effects)
    assert len(indep) >= M, \
        f"1F1B lost its up/down overlap structure: only {indep}"

    # the same property must survive in the full comm program (burns and
    # gradient sync included), not just the hop-only variant
    bundle = hybrid_2d.build(stats, card, cfg, num_stages=S,
                             num_microbatches=M, dp=1, schedule="1f1b",
                             devices=eight_devices[:S])
    n_full, deps_full = permute_dependencies(bundle.comm)
    indep_full = [i for i in range(n_full - 1)
                  if (i, i + 1) not in deps_full]
    assert len(indep_full) >= M // 2, \
        f"full 1F1B program serialized its hops: {indep_full}"
