"""bench.py auxiliary-line guard: a failing low-precision line must
degrade to a machine-readable skipped marker, never cost the headline
line (the driver's tail parser reads the LAST stdout line)."""
from __future__ import annotations

import json

import pytest


def test_aux_failure_prints_skipped_marker(capsys):
    import bench

    def boom(*a):
        raise RuntimeError("synthetic compile pathology")

    out = bench._aux("fp8 swiglu chain", boom, "card", "hw", "dev")
    assert out is None
    line = json.loads(capsys.readouterr().out.strip())
    assert line["metric"] == "fp8 swiglu chain"
    assert "synthetic compile pathology" in line["skipped"]


def test_aux_success_passes_through(capsys):
    import bench

    got = bench._aux("x", lambda a: {"metric": a}, "ok")
    assert got == {"metric": "ok"}
    assert capsys.readouterr().out == ""


def test_above_peak_readings_are_flagged():
    """A short-chain line whose ratio exceeds 1.0 (physically
    impossible — fence-RTT over-subtraction) must carry the upper-bound
    note; in-range lines must not."""
    import bench

    hot = bench._flag_above_peak({"metric": "x", "vs_baseline": 1.05})
    assert "note" in hot and "above-peak" in hot["note"]
    ok = bench._flag_above_peak({"metric": "x", "vs_baseline": 0.98})
    assert "note" not in ok


def test_ab_line_schema_locked():
    """The fused-vs-composed A/B lines are BENCH artifacts (VERDICT r5
    top_next: aux results must appear in BENCH, not just session logs)
    — lock the artifact-grade stat-band schema: headline
    {value, unit, best, band, n}, one {value, best, band, n} sub-object
    per variant, and a paired per-round ratio band per non-composed
    variant."""
    import bench

    summaries = {
        "composed": {"value": 2.0, "best": 1.9, "band": [1.9, 2.2], "n": 3},
        "fused": {"value": 1.0, "best": 0.9, "band": [0.9, 1.2], "n": 3},
        "fused_delayed": {"value": 0.8, "best": 0.7, "band": [0.7, 0.9],
                          "n": 3},
    }
    rounds = {"composed": [2.0, 1.9, 2.2], "fused": [1.0, 0.9, 1.2],
              "fused_delayed": [0.8, 0.7, 0.9]}
    line = bench._ab_line("int8 fused-quant A/B (test)", summaries,
                          rounds, flops_per_iter=10 ** 12,
                          roofline_s=0.5)
    # headline band schema in ms
    assert line["unit"] == "ms"
    for key in ("value", "best", "band", "n"):
        assert key in line, key
    assert line["value"] == 1000.0 and line["n"] == 3
    assert line["band"] == [900.0, 1200.0]
    # per-variant sub-objects carry the same band schema
    for name in summaries:
        sub = line[name]
        for key in ("value", "best", "band", "n"):
            assert key in sub, (name, key)
        assert len(sub["band"]) == 2
    # paired ratio bands, fused vs composed pairing per round
    r = line["ratio_fused_vs_composed"]
    for key in ("value", "best", "band", "n"):
        assert key in r, key
    assert r["value"] == 0.5 and r["n"] == 3
    assert "ratio_fused_delayed_vs_composed" in line
    assert "ratio_composed_vs_composed" not in line
    # roofline ratio rides along (and the above-peak guard applies)
    assert line["vs_baseline"] == 0.5


def test_band_ms_schema():
    """Every aux line builds its band keys through _band_ms — lock the
    seconds->ms conversion and key set."""
    import bench

    got = bench._band_ms({"value": 0.0021, "best": 0.002,
                          "band": [0.002, 0.0025], "n": 3})
    assert got == {"best": 2.0, "band": [2.0, 2.5], "n": 3}


def test_overlap_ab_line_schema_locked():
    """The paired overlap-vs-baseline aux line (ISSUE 4: bench.py +
    multichip driver, models/overlap_bench.assemble_line) is a BENCH
    artifact — lock its schema: headline {value, unit, best, band, n}
    from the OVERLAPPED config, per-config band sub-objects, a paired
    per-round ratio band, and the measured overlap-fraction band per
    config."""
    from dlnetbench_tpu.models.overlap_bench import assemble_line

    walls = {"baseline": [0.2, 0.21, 0.19],
             "overlapped": [0.1, 0.12, 0.11]}
    overlaps = {"baseline": [0.05, 0.0, 0.1],
                "overlapped": [0.8, 0.7, 0.9]}
    line = assemble_line("spmd overlap A/B (test)", walls, overlaps)
    assert line["unit"] == "ms"
    for key in ("value", "best", "band", "n"):
        assert key in line, key
    assert line["value"] == 110.0 and line["n"] == 3
    for name in ("baseline", "overlapped"):
        sub = line[name]
        for key in ("value", "best", "band", "n"):
            assert key in sub, (name, key)
        assert len(sub["band"]) == 2
    r = line["ratio_overlapped_vs_baseline"]
    for key in ("value", "best", "band", "n"):
        assert key in r, key
    # per-round pairing: 0.1/0.2, 0.12/0.21, 0.11/0.19 -> median 0.5714
    assert r["value"] == 0.5714 and r["n"] == 3
    ov = line["overlap_fraction"]
    for name in ("baseline", "overlapped"):
        for key in ("value", "best", "band", "n"):
            assert key in ov[name], (name, key)
    assert ov["overlapped"]["value"] == 0.8


def test_recommended_step_line_schema_locked():
    """VERDICT r5 item #1's driver-captured half: the recommended_step
    line names the fastest recipe passing the stated numerics bar, with
    the winner's stat band and every candidate's loss + verdict."""
    import bench

    bf16 = {"value": 0.5375, "best": 0.53, "band": [0.53, 0.55], "n": 3}
    int8 = {"value": 494.3, "best": 490.0, "band": [490.0, 500.0],
            "n": 3, "loss": 10.41}
    sb = {"value": 454.9, "best": 450.0, "band": [450.0, 460.0],
          "n": 3, "loss": 10.45}
    line = bench._recommended_step(bf16, 10.42,
                                   {"int8_master": int8,
                                    "int8_switchback": sb})
    assert line["metric"] == "recommended_step"
    assert line["recipe"] == "int8_switchback"   # fastest, passes 2% bar
    assert line["unit"] == "ms"
    for key in ("value", "best", "band", "n", "numerics_bar"):
        assert key in line, key
    assert line["value"] == 454.9
    cands = line["candidates"]
    assert set(cands) == {"bf16", "int8_master", "int8_switchback"}
    assert all("loss" in c and "passes" in c for c in cands.values())
    # a candidate failing the bar cannot win, however fast
    sb_bad = dict(sb, loss=99.0)
    line2 = bench._recommended_step(bf16, 10.42,
                                    {"int8_master": int8,
                                     "int8_switchback": sb_bad})
    assert line2["recipe"] == "int8_master"
    assert line2["candidates"]["int8_switchback"]["passes"] is False
    # skipped candidates (None) don't compete; bf16 always does
    line3 = bench._recommended_step(bf16, 10.42, {"int8_master": None})
    assert line3["recipe"] == "bf16"
    assert line3["value"] == 537.5


def test_overlap_field_record_roundtrip_with_fixture():
    """Lock the ``overlap_fraction`` field of the record schema against
    the committed fixture: parser validation accepts it (per-rank timer
    array + band summary), the DataFrame carries it, metrics.merge
    round-trips it, and the bandwidth summary surfaces the ``overlap``
    column."""
    from pathlib import Path

    from dlnetbench_tpu.analysis.bandwidth import (bandwidth_summary,
                                                   effective_bandwidth)
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe,
                                               validate_record)

    path = Path(__file__).parent / "data" / "record_overlap.jsonl"
    records = load_records(path)
    assert len(records) == 1
    rec = records[0]
    validate_record(rec)
    # the fixture's overlap values are the formula applied to its timers
    from dlnetbench_tpu.metrics.stats import overlap_fraction
    row = rec["ranks"][0]
    expect = overlap_fraction(row["runtimes"], row["compute_time"],
                              row["comm_time"])
    assert row["overlap_fraction"] == [round(v, 4) for v in expect]

    df = records_to_dataframe(records)
    assert "overlap_fraction" in df.columns
    assert df["overlap_fraction"].tolist() == [0.5, 0.4318, 0.5, 0.4318]

    merged = merge_records(records)     # single-process merge: identity
    validate_record(merged)
    assert merged["ranks"][0]["overlap_fraction"] == [0.5, 0.4318]

    bw = effective_bandwidth([merged])
    assert "overlap" in bw.columns
    assert sorted(bw["overlap"].unique().tolist()) == [0.4318, 0.5]
    summary = bandwidth_summary([merged])
    assert "overlap" in summary.columns
    assert summary["overlap"].iloc[0] == (0.5 + 0.4318) / 2


def test_bandwidth_overlap_nan_without_decomposition():
    """Records that never measured the A/B decomposition get NaN in the
    overlap column — never a fabricated 0."""
    import math

    from dlnetbench_tpu.analysis.bandwidth import effective_bandwidth

    rec = {"section": "dp", "version": 2,
           "global": {"comm_model": {"comm_time": [
               {"kind": "allreduce", "group": 2, "bytes": 1000}]}},
           "mesh": {"platform": "cpu"},
           "ranks": [{"rank": 0, "comm_time": [10.0]}]}
    bw = effective_bandwidth([rec])
    assert math.isnan(bw["overlap"].iloc[0])


def test_serving_decode_line_schema_locked():
    """bench.py's serving_decode aux line (ISSUE 8) is a BENCH
    artifact: lock the stat-band schema — ms headline from the
    round-median e2e p99 (lower-is-better, so the sentinel compares it
    like every latency line), and {value, best, band, n} sub-objects
    for TTFT/TPOT/p99/tokens-per-s/goodput."""
    import bench
    rounds = [
        {"e2e_ms": {"p99": 10.0}, "ttft_ms": {"p50": 2.0},
         "tpot_ms": {"p50": 1.0}, "tokens_per_s": 100.0,
         "goodput_frac": 1.0, "completed": 16, "offered_rps": 80.0},
        {"e2e_ms": {"p99": 12.0}, "ttft_ms": {"p50": 2.2},
         "tpot_ms": {"p50": 1.1}, "tokens_per_s": 90.0,
         "goodput_frac": 0.9, "completed": 16, "offered_rps": 80.0},
        {"e2e_ms": {"p99": 11.0}, "ttft_ms": {"p50": 2.1},
         "tpot_ms": {"p50": 1.05}, "tokens_per_s": 95.0,
         "goodput_frac": 1.0, "completed": 16, "offered_rps": 80.0},
    ]
    line = bench._serving_decode_line(rounds, suffix=", test")
    assert line["unit"] == "ms"
    assert line["value"] == 11.0 and line["n"] == 3
    assert line["band"] == [10.0, 12.0] and line["best"] == 10.0
    for key in ("ttft_p50_ms", "tpot_p50_ms", "p99_ms",
                "tokens_per_s", "goodput_frac"):
        sub = line[key]
        for k in ("value", "best", "band", "n"):
            assert k in sub, (key, k)
    assert line["ttft_p50_ms"]["value"] == 2.1
    assert line["requests"] == 16 and line["offered_rps"] == 80.0
    # sentinel comparability: the line is an ms line, so bench.py
    # --check picks it up as "serving_decode" automatically
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)


def test_disagg_line_schema_locked():
    """bench.py's disagg_ab aux line (ISSUE 16) is a BENCH artifact:
    lock the paired-arm stat-band schema — ms headline from the
    DISAGGREGATED arm's round-median e2e p99 (sentinel-comparable),
    {value, best, band, n} sub-objects for TTFT p50/p99 + TPOT p50 +
    tokens/s on BOTH arms, the migration wire cost on the disagg arm,
    and the band-disjoint interference verdict."""
    import bench

    def _round(p99, ttft50, ttft99, tpot, tps, mig=None):
        r = {"e2e_ms": {"p99": p99},
             "ttft_ms": {"p50": ttft50, "p99": ttft99},
             "tpot_ms": {"p50": tpot}, "tokens_per_s": tps}
        if mig is not None:
            r["migration"] = mig
        return r

    mono = [_round(10.0, 2.0, 5.0, 1.00, 100.0),
            _round(12.0, 2.2, 5.5, 1.10, 90.0),
            _round(11.0, 2.1, 5.2, 1.05, 95.0)]
    mig = {"bytes": 16896, "ms": {"p50": 0.4},
           "bytes_ratio_vs_bf16": 0.5156}
    dis = [_round(8.0, 1.8, 4.0, 0.50, 140.0, mig),
           _round(9.0, 1.9, 4.4, 0.55, 130.0, mig),
           _round(8.5, 1.85, 4.2, 0.52, 135.0, mig)]
    line = bench._disagg_line(mono, dis, suffix=", test",
                              token_parity=True)
    assert line["unit"] == "ms"
    assert line["value"] == 8.5 and line["n"] == 3
    assert line["band"] == [8.0, 9.0] and line["best"] == 8.0
    for arm in ("monolithic", "disaggregated"):
        for key in ("ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms",
                    "tokens_per_s"):
            sub = line[arm][key]
            for k in ("value", "best", "band", "n"):
                assert k in sub, (arm, key, k)
    d = line["disaggregated"]
    for key in ("migration_bytes", "migration_ms_p50"):
        for k in ("value", "best", "band", "n"):
            assert k in d[key], (key, k)
    assert d["migration_bytes"]["value"] == 16896.0
    assert d["migration_bytes_ratio"] == 0.5156
    # TPOT bands [1.0, 1.1] vs [0.5, 0.55]: disjoint AND lower — the
    # interference verdict the disagg study prices
    assert line["tpot_band_disjoint_drop"] is True
    assert line["token_parity"] is True
    # overlapping bands must NOT claim the win
    flat = bench._disagg_line(mono, mono)
    assert flat["tpot_band_disjoint_drop"] is False
    assert "token_parity" not in flat
    # sentinel comparability: an ms line, auto-compared by --check
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)


def test_live_metrics_line_schema_locked(tmp_path):
    """ISSUE 14 satellite: the --live-metrics JSONL stream's snapshot
    line — one per window, rolling TTFT/TPOT percentiles over the
    WINDOW's completions, queue depth, admitted slots, KV occupancy —
    is a machine-read dashboard feed; lock its schema."""
    import json

    from dlnetbench_tpu.serving.metrics import (Completed,
                                                LiveMetricsWriter)

    done = [Completed(rid=i, arrival_s=0.1 * i, admitted_s=0.1 * i,
                      first_token_s=0.1 * i + 0.02,
                      finish_s=0.1 * i + 0.08, prompt_len=8,
                      output_len=4) for i in range(5)]
    line = LiveMetricsWriter.snapshot_line(
        t_s=0.5, window_s=0.5, window_completed=done, queue_depth=3,
        active_slots=2, kv_occupancy=0.625, engine_steps=40, run=1)
    assert set(line) == {"run", "t_s", "window_s", "completed",
                         "ttft_ms", "tpot_ms", "queue_depth",
                         "active_slots", "kv_occupancy",
                         "engine_steps"}
    # single-engine: unattributed — the key is absent so pre-fleet
    # consumers keep parsing byte-identical lines; a fleet replica's
    # stream carries it (ISSUE 18)
    fleet_line = LiveMetricsWriter.snapshot_line(
        t_s=0.5, window_s=0.5, window_completed=done, queue_depth=3,
        active_slots=2, kv_occupancy=0.625, engine_steps=40, run=1,
        replica_id=2)
    assert fleet_line["replica_id"] == 2
    assert set(fleet_line) - set(line) == {"replica_id"}
    assert line["run"] == 1  # (run, t_s) orders the feed — t_s is
    #                          run-relative and restarts per engine run
    assert line["completed"] == 5 and line["queue_depth"] == 3
    assert line["kv_occupancy"] == 0.625
    for base in ("ttft_ms", "tpot_ms"):
        for k in ("p50", "p95", "p99", "mean", "n"):
            assert k in line[base], (base, k)
    assert line["ttft_ms"]["p50"] == 20.0  # 0.02 s to first token
    # the writer emits at window boundaries, JSONL-append, and the
    # bench flag reaches the serving aux line
    path = tmp_path / "live.jsonl"
    w = LiveMetricsWriter(path, window_s=0.5)

    class _Eng:
        completed = done
        pending = [1, 2, 3]
        slots = [object(), object(), None]
        engine_steps = 40

        class cache:
            @staticmethod
            def stats():
                return {"occupancy": 0.625}

    assert w.maybe_emit(_Eng(), 0.5) is not None
    assert w.maybe_emit(_Eng(), 0.6) is None   # inside the window
    assert w.maybe_emit(_Eng(), 1.1) is not None
    got = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(got) == 2 and got[0]["active_slots"] == 2
    import bench
    args = bench._parse_args(["--live-metrics", str(path)])
    assert args.live_metrics == str(path)


def test_fleet_line_schema_locked():
    """bench.py's fleet_ab aux line (ISSUE 18) is a BENCH artifact:
    lock the three-arm routing A/B schema — ms headline from the
    PREFIX_AFFINITY arm's round-median TTFT p50 (sentinel-comparable),
    {value, best, band, n} sub-objects for TTFT p50/p99 + tokens/s on
    ALL THREE arms, the affinity arm's hit-rate and prefix-reuse
    bands, and the band-disjoint routing verdict vs round_robin."""
    import bench

    def _round(ttft50, ttft99, tps, *, hit=None, reuse=None):
        r = {"serving": {"ttft_ms": {"p50": ttft50, "p99": ttft99},
                         "tokens_per_s": tps}}
        if hit is not None:
            r["fleet"] = {"replicas": 2, "affinity_hit_rate": hit,
                          "prefix_reuse_tokens": reuse}
        else:
            r["fleet"] = {"replicas": 2}
        return r

    rr = [_round(10.0, 22.0, 100.0), _round(11.0, 24.0, 95.0),
          _round(10.5, 23.0, 98.0)]
    p2 = [_round(9.0, 20.0, 105.0), _round(9.5, 21.0, 102.0),
          _round(9.2, 20.5, 104.0)]
    pa = [_round(4.0, 12.0, 130.0, hit=0.8, reuse=256.0),
          _round(4.5, 13.0, 125.0, hit=0.75, reuse=224.0),
          _round(4.2, 12.5, 128.0, hit=0.8, reuse=256.0)]
    line = bench._fleet_line(
        {"round_robin": rr, "p2c": p2, "prefix_affinity": pa},
        suffix=", test", token_parity=True)
    assert line["unit"] == "ms"
    assert line["value"] == 4.2 and line["n"] == 3
    assert line["band"] == [4.0, 4.5] and line["best"] == 4.0
    for arm in ("round_robin", "p2c", "prefix_affinity"):
        for key in ("ttft_p50_ms", "ttft_p99_ms", "tokens_per_s"):
            sub = line[arm][key]
            for k in ("value", "best", "band", "n"):
                assert k in sub, (arm, key, k)
    for key in ("affinity_hit_rate", "prefix_reuse_tokens"):
        for k in ("value", "best", "band", "n"):
            assert k in line["prefix_affinity"][key], (key, k)
    assert line["prefix_affinity"]["affinity_hit_rate"]["value"] == 0.8
    # TTFT bands [10.0, 11.0] vs [4.0, 4.5]: disjoint AND lower — the
    # routing verdict the fleet study prices
    assert line["ttft_band_disjoint_drop"] is True
    assert line["token_parity"] is True
    # overlapping bands must NOT claim the win
    flat = bench._fleet_line(
        {"round_robin": rr, "p2c": rr,
         "prefix_affinity": [dict(r, fleet={"replicas": 2,
                                            "affinity_hit_rate": 0.0,
                                            "prefix_reuse_tokens": 0.0})
                             for r in rr]})
    assert flat["ttft_band_disjoint_drop"] is False
    assert "token_parity" not in flat
    # sentinel comparability: an ms line, auto-compared by --check
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)


def _ab_round(e2e_p99, tokens_per_s, *, n=1, spd=1.0, dev_us=50000.0,
              steps=50, disp=50, host_us=500.0, spec=None):
    """A synthetic per-round serving block with a decode_loop section
    (the ISSUE 11 A/B inputs)."""
    dl = {"multi_step_n": n, "steps_per_dispatch": spd,
          "tokens_per_sync": spd * 4, "dispatches": disp,
          "device_steps": steps, "device_us": {"total": dev_us},
          "decode_device_us": {"total": dev_us},
          "host_dispatch_us": {"total": host_us, "p50": host_us / disp,
                               "mean": host_us / disp, "n": disp},
          "sync_h2d_us": {"total": 100.0, "n": 2},
          "sync_d2h_us": {"total": 100.0, "n": 2}}
    if spec:
        dl["spec"] = spec
    return {"e2e_ms": {"p99": e2e_p99}, "ttft_ms": {"p50": 2.0},
            "tpot_ms": {"p50": 1.0}, "tokens_per_s": tokens_per_s,
            "goodput_frac": 1.0, "completed": 8, "offered_rps": 80.0,
            "wall_s": 0.1, "decode_loop": dl}


def test_serving_decode_ab_schema_locked():
    """The ISSUE 11 A/B extensions of the serving_decode line: paired
    variant sub-blocks (tokens/s + TPOT bands, speedup, dispatch
    decomposition), the host-fraction drop with its band-disjoint
    verdict, speculative acceptance, and the token-parity lock — all
    while the ISSUE 8 base schema (sentinel-comparable ms line) stays
    intact."""
    import bench

    # one-step: 500us/dispatch floor hidden in dev (50 steps x 1000us);
    # multi: 8 steps/dispatch amortize it (48*500 + 6*500 = 27000us)
    one = [_ab_round(30.0, 4000.0, dev_us=50 * 1000.0)
           for _ in range(3)]
    multi = [_ab_round(15.0, 8000.0, n=8, spd=8.0,
                       dev_us=48 * 500.0 + 6 * 500.0, steps=48, disp=6,
                       host_us=120.0) for _ in range(3)]
    spec = [_ab_round(14.0, 9000.0, n=8, spd=9.0, dev_us=30000.0,
                      steps=45, disp=5, host_us=110.0,
                      spec={"k": 4, "drafter": "ngram",
                            "acceptance_rate": 0.4, "drafted": 100,
                            "accepted": 40}) for _ in range(3)]
    line = bench._serving_decode_line(one, suffix=", test",
                                      multi_rounds=multi,
                                      spec_rounds=spec,
                                      token_parity=True)
    # ISSUE 8 base schema intact
    assert line["unit"] == "ms" and line["value"] == 30.0
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)
    # the A/B blocks
    for key in ("multi_step", "speculative"):
        blk = line[key]
        for sub in ("tokens_per_s", "tpot_p50_ms", "e2e_p99_ms",
                    "speedup_tokens_per_s", "steps_per_dispatch",
                    "tokens_per_sync"):
            for k in ("value", "best", "band", "n"):
                assert k in blk[sub], (key, sub, k)
        assert blk["multi_step_n"] == 8
    assert line["multi_step"]["speedup_tokens_per_s"]["value"] == 2.0
    assert line["speculative"]["spec"]["acceptance_rate"]["value"] \
        == 0.4
    # the attribution flip: per-dispatch floor solved from the pair
    # (d1=1000, dn=562.5, spd=8 -> floor=500us), host fractions banded,
    # drop verdict band-disjoint
    flip = line["attribution_flip"]
    assert flip["dispatch_us"]["value"] == pytest.approx(500.0, abs=1)
    assert flip["one_step_host_frac"]["value"] > \
        flip["multi_step_host_frac"]["value"]
    assert flip["band_disjoint_drop"] is True
    assert "speculative_host_frac" in flip
    assert line["token_parity"] is True
    # without the A/B inputs the line stays the ISSUE 8 shape (no
    # accidental keys) — the schema the committed BENCH_r01-05
    # artifacts' sentinel walk expects
    base_line = bench._serving_decode_line(one, suffix=", test")
    for key in ("multi_step", "speculative", "attribution_flip",
                "token_parity"):
        assert key not in base_line


def test_aux_deadline_skips_instead_of_running(capsys, monkeypatch):
    """Past the wall-clock deadline the aux fn must not even start —
    the headline line takes precedence over auxiliary coverage."""
    import bench

    monkeypatch.setattr(bench, "_AUX_DEADLINE_S", 0.0)
    ran = []
    got = bench._aux("int8 matmul", lambda: ran.append(1))
    assert got is None and not ran
    line = json.loads(capsys.readouterr().out.strip())
    assert "deadline" in line["skipped"]


def test_checkpoint_ab_line_schema_locked(monkeypatch, tmp_path):
    """The stall-vs-async checkpoint A/B is a BENCH artifact: lock the
    schema — headline {value, unit, n}, the three step bands, the
    measured save-cost band, state size and backend — without paying
    for a real dp build (the proxy step is a stub; the checkpointer
    runs for real over a tiny state, so save costs are measured)."""
    import jax.numpy as jnp

    import bench

    class FakeBundle:
        full = staticmethod(lambda: None)
        state = {"w": jnp.ones((64,), jnp.float32)}

    monkeypatch.setattr(
        "dlnetbench_tpu.proxies.dp.build", lambda *a, **k: FakeBundle())
    monkeypatch.setenv("TMPDIR", str(tmp_path))

    def fake_time_chain(fn, k):
        import time as _t
        t0 = _t.monotonic()
        for _ in range(k):
            fn()
        return 0.001 + (_t.monotonic() - t0) / k

    monkeypatch.setattr("dlnetbench_tpu.utils.timing.time_chain",
                        fake_time_chain)
    line = bench._bench_checkpoint_ab()
    assert line is not None
    assert line["metric"].startswith("checkpoint A/B")
    assert line["unit"].startswith("fraction of save cost")
    for key in ("baseline_ms", "stall_ms", "async_ms", "save_ms"):
        sub = line[key]
        assert set(sub) == {"value", "best", "band", "n"}
        assert sub["band"][0] <= sub["value"] <= sub["band"][1]
    # a stall-mode save rides the step; the async step must sit closer
    # to the baseline than the stall step does
    assert line["stall_ms"]["value"] >= line["async_ms"]["value"]
    assert line["save_ms"]["n"] == 12  # 3 rounds x k=4, every=1
    assert line["state_bytes"] == 64 * 4
    assert line["backend"] in ("npz", "orbax")
    assert line["n"] == 3
    # nothing left behind: the A/B cleans up its checkpoint tree
    assert not list(tmp_path.glob("dlnb_ckpt_ab_*"))


def test_straggler_ab_line_schema_locked(monkeypatch):
    """The faulted-vs-clean straggler A/B is a BENCH artifact: lock the
    schema — amplification headline {value, unit, n}, both step bands
    ({value, best, band, n} in ms), and the injected delay — without
    paying for a real dp build (timing is monkeypatched)."""
    import itertools

    import bench

    class FakeBundle:
        full = staticmethod(lambda: None)

    monkeypatch.setattr(
        "dlnetbench_tpu.proxies.dp.build", lambda *a, **k: FakeBundle())
    # clean chains 1 ms/step; faulted chains ride the injector's sleep
    seq = itertools.cycle([0.001])

    def fake_time_chain(fn, k):
        base = next(seq)
        import time as _t
        t0 = _t.monotonic()
        for _ in range(k):
            fn()
        return base + (_t.monotonic() - t0) / k

    monkeypatch.setattr("dlnetbench_tpu.utils.timing.time_chain",
                        fake_time_chain)
    line = bench._bench_straggler_ab()
    assert line is not None
    assert line["metric"].startswith("straggler A/B")
    assert line["unit"].startswith("x (")
    assert line["injected_ms"] >= 2.0
    for key in ("clean_ms", "faulted_ms"):
        sub = line[key]
        assert set(sub) == {"value", "best", "band", "n"}
        assert sub["band"][0] <= sub["value"] <= sub["band"][1]
    # the faulted band must sit above the clean band by ~the injection
    assert line["faulted_ms"]["value"] > line["clean_ms"]["value"]
    assert 0.5 < line["value"] < 2.0  # measured amplification ~1 here
    assert line["n"] == 3


def test_tuned_ab_line_schema_locked():
    """bench.py's tuned-vs-frozen A/B line (ISSUE 9): the headline
    ``value`` is the TUNED chain's median ms with {value, best, band,
    n} bands, both variants ship sub-objects + of-peak ratios, the
    paired per-round ratio band pairs them, band_disjoint_win states
    the acceptance verdict, and the DB provenance (path, prior
    hit/miss, committed configs, search meta) rides the line."""
    import bench

    summaries = {
        "tuned": {"value": 0.010, "best": 0.009,
                  "band": [0.009, 0.011], "n": 3},
        "frozen": {"value": 0.020, "best": 0.019,
                   "band": [0.019, 0.021], "n": 3},
    }
    rounds = {"tuned": [0.009, 0.010, 0.011],
              "frozen": [0.019, 0.020, 0.021]}
    line = bench._tuned_ab_line(
        summaries, rounds, flops_per_iter=10 ** 12, roofline_s=0.008,
        metric="tuned A/B: test", db_path="/tmp/tdb/tuning_db.jsonl",
        configs={"up": {"block_m": 512}}, db_prior_hit={"up": False},
        search_meta={"up": {"candidates": 3, "pruned": 1, "seed": 0}})
    assert line["unit"] == "ms" and line["value"] == 10.0
    assert line["band"] == [9.0, 11.0] and line["n"] == 3
    assert line["vs_baseline"] == 0.8          # roofline / tuned
    assert line["vs_baseline_frozen"] == 0.4   # roofline / frozen
    for sub in ("tuned_ms", "frozen_ms"):
        for k in ("value", "best", "band", "n"):
            assert k in line[sub], (sub, k)
    r = line["ratio_tuned_vs_frozen"]
    assert r["n"] == 3 and r["value"] == 0.5
    assert line["band_disjoint_win"] is True   # disjoint AND faster
    assert line["db_path"].endswith("tuning_db.jsonl")
    assert line["db_prior_hit"] == {"up": False}
    assert line["configs"]["up"]["block_m"] == 512
    assert line["search"]["up"]["candidates"] == 3
    # an overlapping-band win is NOT band-disjoint
    summaries2 = dict(summaries)
    summaries2["frozen"] = {"value": 0.0105, "best": 0.010,
                            "band": [0.010, 0.011], "n": 3}
    line2 = bench._tuned_ab_line(
        summaries2, rounds, flops_per_iter=10 ** 12, roofline_s=0.008,
        metric="m", db_path="p", configs={}, db_prior_hit={},
        search_meta={})
    assert line2["band_disjoint_win"] is False
    # sentinel comparability: bench.py --check picks it up as
    # "tuned_ab" automatically
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)


def test_longcontext_line_schema_locked():
    """bench.py's dense-vs-splash long-context A/B line (ISSUE 10):
    headline value = the WINDOW-masked splash median ms with {value,
    best, band, n}, every variant a sub-object, masked variants a
    paired per-round ratio band vs dense, speedup_vs_sparsity the
    measured-over-expected consistency ratio, and the mask specs +
    sparsity riding as comparable globals."""
    import bench

    summaries = {
        "dense": {"value": 0.020, "best": 0.019,
                  "band": [0.019, 0.021], "n": 3},
        "splash_causal": {"value": 0.019, "best": 0.018,
                          "band": [0.018, 0.020], "n": 3},
        "splash_window": {"value": 0.005, "best": 0.0045,
                          "band": [0.0045, 0.0055], "n": 3},
        "splash_segment": {"value": 0.010, "best": 0.009,
                           "band": [0.009, 0.011], "n": 3},
    }
    rounds = {
        "dense": [0.019, 0.020, 0.021],
        "splash_causal": [0.018, 0.019, 0.020],
        "splash_window": [0.0045, 0.005, 0.0055],
        "splash_segment": [0.009, 0.010, 0.011],
    }
    mask_info = {
        "splash_causal": {"attention_mask": "causal",
                          "mask_sparsity": 0.499,
                          "block_skip_fraction": 0.48,
                          "expected_speedup": 1.0},
        "splash_window": {"attention_mask": "causal&window(4096)",
                          "mask_sparsity": 0.94,
                          "block_skip_fraction": 0.87,
                          "expected_speedup": 4.0},
        "splash_segment": {"attention_mask": "causal&seg(avg=8192,seed=0)",
                           "mask_sparsity": 0.9,
                           "block_skip_fraction": 0.8,
                           "expected_speedup": 2.0},
    }
    line = bench._longcontext_line(summaries, rounds,
                                   metric="longcontext A/B: test",
                                   mask_info=mask_info)
    assert line["unit"] == "ms" and line["value"] == 5.0
    assert line["band"] == [4.5, 5.5] and line["n"] == 3
    for sub in ("dense", "splash_causal", "splash_window",
                "splash_segment"):
        for k in ("value", "best", "band", "n"):
            assert k in line[sub], (sub, k)
    r = line["ratio_splash_window_vs_dense"]
    assert r["n"] == 3 and r["value"] == 0.25
    # measured speedup 4.0 vs expected 4.0 -> consistency ratio 1.0
    assert line["speedup_vs_sparsity"]["splash_window"] == 1.0
    assert line["masks"]["splash_window"]["attention_mask"] \
        == "causal&window(4096)"
    assert line["band_disjoint_win"] is True
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)
    # an overlapping-band "win" is not band-disjoint
    summaries2 = dict(summaries)
    summaries2["splash_window"] = {"value": 0.0195, "best": 0.019,
                                   "band": [0.019, 0.020], "n": 3}
    line2 = bench._longcontext_line(summaries2, rounds, metric="m",
                                    mask_info=mask_info)
    assert line2["band_disjoint_win"] is False


def test_kv_density_line_schema_locked():
    """bench.py's kv_density_ab aux line (ISSUE 12) is a pure
    assembler: lock the stat-band schema — ms headline from the DENSE
    engine's round-median e2e p99 (lower-is-better, sentinel-
    comparable), per-variant {value, best, band, n} sub-objects for
    admitted slots / tokens-per-s / goodput-at-SLO, capacity ratios
    and the per-recipe parity bars."""
    import bench

    def srv(p99, adm, tps, grps):
        return {"e2e_ms": {"p99": p99}, "tokens_per_s": tps,
                "goodput_frac": 1.0, "goodput_rps": grps,
                "admitted_concurrency_peak": adm,
                "kv_cache": {"num_pages": 25 if adm < 10 else 96,
                             "pool_bytes": 102400}}
    rounds = {
        "bf16": [srv(90.0, 7, 3000.0, 200.0), srv(95.0, 7, 2900.0,
                                                  195.0),
                 srv(92.0, 7, 3100.0, 205.0)],
        "int8": [srv(55.0, 20, 5000.0, 350.0), srv(58.0, 20, 5200.0,
                                                   360.0),
                 srv(56.0, 20, 5100.0, 355.0)],
        "fp8": [srv(100.0, 20, 2900.0, 190.0), srv(105.0, 20, 2800.0,
                                                   185.0),
                srv(102.0, 20, 2850.0, 188.0)],
    }
    parity = {"int8": [0.01, 0.012, 0.011], "fp8": [0.07, 0.08, 0.075]}
    line = bench._kv_density_line(rounds, parity, 102400, suffix=", t")
    assert line["unit"] == "ms" and line["n"] == 3
    assert line["value"] == 92.0 and line["band"] == [90.0, 95.0]
    assert line["pool_bytes_budget"] == 102400
    for name in ("bf16", "int8", "fp8"):
        v = line["variants"][name]
        for key in ("admitted_slots", "tokens_per_s", "e2e_p99_ms",
                    "goodput_frac", "goodput_rps"):
            for k in ("value", "best", "band", "n"):
                assert k in v[key], (name, key, k)
        assert v["num_pages"] in (25, 96) and v["pool_bytes"] == 102400
    i8 = line["variants"]["int8"]
    assert i8["capacity_x"]["value"] == pytest.approx(20 / 7, rel=1e-3)
    assert i8["parity_tol"] == 0.05 and i8["parity_ok"] is True
    assert i8["parity_max_err"]["value"] == 0.011
    # dense carries NO parity keys (it IS the reference)
    assert "parity_ok" not in line["variants"]["bf16"]
    # a parity excursion past the stated bar flips the verdict
    bad = bench._kv_density_line(
        rounds, {"int8": [0.2, 0.2, 0.2], "fp8": parity["fp8"]},
        102400)
    assert bad["variants"]["int8"]["parity_ok"] is False
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)


def test_moe_ab_line_schema_locked():
    """bench.py's dense-FFN-vs-MoE A/B line (ISSUE 15): the headline
    ``value`` is the sparse-MoE median ms with {value, best, band, n},
    every variant a sub-object, the MoE variants a paired per-round
    ratio band vs dense (at matched active params the ratio IS the
    routing/dispatch premium), band_disjoint the separation verdict,
    and the routing knobs + measured router stats riding as record
    globals."""
    import bench

    summaries = {
        "dense": {"value": 0.010, "best": 0.009,
                  "band": [0.009, 0.011], "n": 3},
        "moe": {"value": 0.015, "best": 0.014,
                "band": [0.014, 0.016], "n": 3},
        "moe_grouped": {"value": 0.013, "best": 0.012,
                        "band": [0.012, 0.014], "n": 3},
    }
    rounds = {"dense": [0.009, 0.010, 0.011],
              "moe": [0.0135, 0.015, 0.0165],
              "moe_grouped": [0.0117, 0.013, 0.0143]}
    moe_info = {"moe_experts": 8, "moe_top_k": 2,
                "moe_capacity_factor": 1.25, "moe_drop_seed": None,
                "moe_group_tokens": 0,
                "moe": {"expert_load": [0.125] * 8,
                        "load_imbalance": 1.0, "drop_rate": 0.0,
                        "router_entropy": 1.0}}
    active = {"dense_ffn_params": 100, "moe_active_ffn_params": 100,
              "moe_total_ffn_params": 400, "router_params": 8}
    line = bench._moe_ab_line(summaries, rounds, metric="moe A/B: t",
                              moe_info=moe_info, active_params=active)
    assert line["unit"] == "ms" and line["value"] == 15.0
    assert line["band"] == [14.0, 16.0] and line["n"] == 3
    for sub in ("dense_ms", "moe_ms", "moe_grouped_ms"):
        for k in ("value", "best", "band", "n"):
            assert k in line[sub], (sub, k)
    r = line["ratio_moe_vs_dense"]
    assert r["n"] == 3 and r["value"] == 1.5
    assert line["ratio_moe_grouped_vs_dense"]["value"] == 1.3
    assert line["band_disjoint"] is True
    # matched active params stated, knobs + measured stats ride along
    assert (line["active_params"]["dense_ffn_params"]
            == line["active_params"]["moe_active_ffn_params"])
    assert line["moe_experts"] == 8
    assert line["moe"]["load_imbalance"] == 1.0
    # overlapping bands flip the verdict
    s2 = dict(summaries)
    s2["dense"] = {"value": 0.0145, "best": 0.014,
                   "band": [0.014, 0.015], "n": 3}
    line2 = bench._moe_ab_line(s2, rounds, metric="m",
                               moe_info=moe_info, active_params=active)
    assert line2["band_disjoint"] is False
    # sentinel comparability: --check picks it up as "moe_ab"
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)


def test_sampling_ab_line_schema_locked():
    """bench.py's sampling_ab aux line (ISSUE 19): the headline
    ``value`` is the SPECULATIVE-sampled arm's round-median e2e p99 in
    ms (sentinel-comparable; the bench headline stays greedy), both
    arms ship {value, best, band, n} bands for e2e p99 / TPOT p50 /
    tokens/s, the spec arm adds its measured acceptance-rate band, the
    verdict is the band-disjoint tokens/s gain, and token_identity
    locks the classic-vs-fused sampled bit-identity."""
    import bench

    def _round(p99, tps, *, acc=None):
        r = {"e2e_ms": {"p99": p99}, "tpot_ms": {"p50": 1.0},
             "tokens_per_s": tps}
        if acc is not None:
            r["decode_loop"] = {"spec": {"acceptance_rate": acc}}
        return r

    sampled = [_round(50.0, 100.0), _round(52.0, 95.0),
               _round(51.0, 98.0)]
    spec = [_round(30.0, 150.0, acc=0.5), _round(32.0, 145.0, acc=0.55),
            _round(31.0, 148.0, acc=0.5)]
    line = bench._sampling_ab_line(sampled, spec, suffix=", test",
                                   token_identity=True)
    assert line["unit"] == "ms"
    assert line["value"] == 31.0 and line["n"] == 3
    assert line["band"] == [30.0, 32.0] and line["best"] == 30.0
    for arm in ("sampled", "spec_sampled"):
        for key in ("e2e_p99_ms", "tpot_p50_ms", "tokens_per_s"):
            sub = line[arm][key]
            for k in ("value", "best", "band", "n"):
                assert k in sub, (arm, key, k)
    acc = line["spec_sampled"]["acceptance_rate"]
    assert acc["value"] == 0.5 and acc["n"] == 3
    # tokens/s bands [95, 100] vs [145, 150]: disjoint AND higher —
    # the ISSUE-19 speculation-under-sampling verdict
    assert line["tokens_per_s_band_disjoint_gain"] is True
    assert line["token_identity"] is True
    # overlapping bands must NOT claim the win
    flat = bench._sampling_ab_line(sampled, [
        _round(50.0, 99.0, acc=0.2), _round(51.0, 101.0, acc=0.2),
        _round(50.5, 100.0, acc=0.2)])
    assert flat["tokens_per_s_band_disjoint_gain"] is False
    assert "token_identity" not in flat
    # sentinel comparability: an ms line, auto-compared by --check
    from dlnetbench_tpu.sentinel import is_ms_line
    assert is_ms_line(line)
