"""bench.py auxiliary-line guard: a failing low-precision line must
degrade to a machine-readable skipped marker, never cost the headline
line (the driver's tail parser reads the LAST stdout line)."""
from __future__ import annotations

import json


def test_aux_failure_prints_skipped_marker(capsys):
    import bench

    def boom(*a):
        raise RuntimeError("synthetic compile pathology")

    out = bench._aux("fp8 swiglu chain", boom, "card", "hw", "dev")
    assert out is None
    line = json.loads(capsys.readouterr().out.strip())
    assert line["metric"] == "fp8 swiglu chain"
    assert "synthetic compile pathology" in line["skipped"]


def test_aux_success_passes_through(capsys):
    import bench

    got = bench._aux("x", lambda a: {"metric": a}, "ok")
    assert got == {"metric": "ok"}
    assert capsys.readouterr().out == ""


def test_above_peak_readings_are_flagged():
    """A short-chain line whose ratio exceeds 1.0 (physically
    impossible — fence-RTT over-subtraction) must carry the upper-bound
    note; in-range lines must not."""
    import bench

    hot = bench._flag_above_peak({"metric": "x", "vs_baseline": 1.05})
    assert "note" in hot and "above-peak" in hot["note"]
    ok = bench._flag_above_peak({"metric": "x", "vs_baseline": 0.98})
    assert "note" not in ok


def test_ab_line_schema_locked():
    """The fused-vs-composed A/B lines are BENCH artifacts (VERDICT r5
    top_next: aux results must appear in BENCH, not just session logs)
    — lock the artifact-grade stat-band schema: headline
    {value, unit, best, band, n}, one {value, best, band, n} sub-object
    per variant, and a paired per-round ratio band per non-composed
    variant."""
    import bench

    summaries = {
        "composed": {"value": 2.0, "best": 1.9, "band": [1.9, 2.2], "n": 3},
        "fused": {"value": 1.0, "best": 0.9, "band": [0.9, 1.2], "n": 3},
        "fused_delayed": {"value": 0.8, "best": 0.7, "band": [0.7, 0.9],
                          "n": 3},
    }
    rounds = {"composed": [2.0, 1.9, 2.2], "fused": [1.0, 0.9, 1.2],
              "fused_delayed": [0.8, 0.7, 0.9]}
    line = bench._ab_line("int8 fused-quant A/B (test)", summaries,
                          rounds, flops_per_iter=10 ** 12,
                          roofline_s=0.5)
    # headline band schema in ms
    assert line["unit"] == "ms"
    for key in ("value", "best", "band", "n"):
        assert key in line, key
    assert line["value"] == 1000.0 and line["n"] == 3
    assert line["band"] == [900.0, 1200.0]
    # per-variant sub-objects carry the same band schema
    for name in summaries:
        sub = line[name]
        for key in ("value", "best", "band", "n"):
            assert key in sub, (name, key)
        assert len(sub["band"]) == 2
    # paired ratio bands, fused vs composed pairing per round
    r = line["ratio_fused_vs_composed"]
    for key in ("value", "best", "band", "n"):
        assert key in r, key
    assert r["value"] == 0.5 and r["n"] == 3
    assert "ratio_fused_delayed_vs_composed" in line
    assert "ratio_composed_vs_composed" not in line
    # roofline ratio rides along (and the above-peak guard applies)
    assert line["vs_baseline"] == 0.5


def test_band_ms_schema():
    """Every aux line builds its band keys through _band_ms — lock the
    seconds->ms conversion and key set."""
    import bench

    got = bench._band_ms({"value": 0.0021, "best": 0.002,
                          "band": [0.002, 0.0025], "n": 3})
    assert got == {"best": 2.0, "band": [2.0, 2.5], "n": 3}


def test_aux_deadline_skips_instead_of_running(capsys, monkeypatch):
    """Past the wall-clock deadline the aux fn must not even start —
    the headline line takes precedence over auxiliary coverage."""
    import bench

    monkeypatch.setattr(bench, "_AUX_DEADLINE_S", 0.0)
    ran = []
    got = bench._aux("int8 matmul", lambda: ran.append(1))
    assert got is None and not ran
    line = json.loads(capsys.readouterr().out.strip())
    assert "deadline" in line["skipped"]
