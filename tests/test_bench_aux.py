"""bench.py auxiliary-line guard: a failing low-precision line must
degrade to a machine-readable skipped marker, never cost the headline
line (the driver's tail parser reads the LAST stdout line)."""
from __future__ import annotations

import json


def test_aux_failure_prints_skipped_marker(capsys):
    import bench

    def boom(*a):
        raise RuntimeError("synthetic compile pathology")

    out = bench._aux("fp8 swiglu chain", boom, "card", "hw", "dev")
    assert out is None
    line = json.loads(capsys.readouterr().out.strip())
    assert line["metric"] == "fp8 swiglu chain"
    assert "synthetic compile pathology" in line["skipped"]


def test_aux_success_passes_through(capsys):
    import bench

    got = bench._aux("x", lambda a: {"metric": a}, "ok")
    assert got == {"metric": "ok"}
    assert capsys.readouterr().out == ""


def test_above_peak_readings_are_flagged():
    """A short-chain line whose ratio exceeds 1.0 (physically
    impossible — fence-RTT over-subtraction) must carry the upper-bound
    note; in-range lines must not."""
    import bench

    hot = bench._flag_above_peak({"metric": "x", "vs_baseline": 1.05})
    assert "note" in hot and "above-peak" in hot["note"]
    ok = bench._flag_above_peak({"metric": "x", "vs_baseline": 0.98})
    assert "note" not in ok


def test_aux_deadline_skips_instead_of_running(capsys, monkeypatch):
    """Past the wall-clock deadline the aux fn must not even start —
    the headline line takes precedence over auxiliary coverage."""
    import bench

    monkeypatch.setattr(bench, "_AUX_DEADLINE_S", 0.0)
    ran = []
    got = bench._aux("int8 matmul", lambda: ran.append(1))
    assert got is None and not ran
    line = json.loads(capsys.readouterr().out.strip())
    assert "deadline" in line["skipped"]
