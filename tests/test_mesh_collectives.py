"""Mesh + collective wrapper tests on the 8-device virtual CPU platform
(the rebuild's ``mpi_cpu`` equivalent, SURVEY.md §4.4)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from dlnetbench_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from dlnetbench_tpu.core.schedule import Grid3D
from dlnetbench_tpu.parallel import collectives as col
from dlnetbench_tpu.parallel import mesh as meshlib


def test_flat_mesh(eight_devices):
    m = meshlib.make_flat_mesh(8)
    assert m.devices.shape == (8,) and m.axis_names == ("x",)
    m4 = meshlib.make_flat_mesh(4)
    assert m4.devices.shape == (4,)


def test_grid_mesh_matches_grid3d_ranks(eight_devices):
    g = Grid3D(dp=2, pp=2, tp=2)
    m = meshlib.mesh_from_grid(g)
    assert m.axis_names == ("dp", "pp", "tp")
    # device at mesh coordinate (d,p,t) must be flat rank (d*pp+p)*tp+t
    flat = m.devices.flatten()
    for d in range(2):
        for p in range(2):
            for t in range(2):
                assert m.devices[d, p, t] == flat[g.rank(d, p, t)]


def test_mesh_too_large_raises(eight_devices):
    with pytest.raises(ValueError, match="needs 16 devices"):
        meshlib.make_grid_mesh(dp=4, pp=2, tp=2)


def test_describe_mesh(eight_devices):
    info = meshlib.describe_mesh(meshlib.make_grid_mesh(2, 2, 2))
    assert info["axes"] == {"dp": 2, "pp": 2, "tp": 2}
    assert info["num_devices"] == 8 and len(info["devices"]) == 8


def _smap(mesh, fn, in_spec, out_spec):
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_spec,
                             out_specs=out_spec, check_vma=False))


def test_allreduce_and_barrier(eight_devices):
    m = meshlib.make_flat_mesh(8)
    x = jnp.arange(8.0)
    out = _smap(m, lambda v: col.allreduce(v, "x"), P("x"), P("x"))(x)
    np.testing.assert_allclose(out, np.full(8, 28.0))
    b = _smap(m, lambda v: col.barrier("x"), P("x"), P())(x)
    assert float(b) == 8.0


def test_allgather_reduce_scatter(eight_devices):
    m = meshlib.make_flat_mesh(4)
    x = jnp.arange(8.0)  # 2 elements per rank
    gathered = _smap(m, lambda v: col.allgather(v, "x"), P("x"), P())(x)
    np.testing.assert_allclose(gathered, np.arange(8.0))
    # reduce_scatter of the gathered full vector: every rank contributes the
    # same 8-vector, rank i keeps slice i summed over ranks
    def rs(v):
        full = col.allgather(v, "x")
        return col.reduce_scatter(full, "x")
    out = _smap(m, rs, P("x"), P("x"))(x)
    np.testing.assert_allclose(out, 4.0 * np.arange(8.0))


def test_alltoall(eight_devices):
    m = meshlib.make_flat_mesh(4)
    # per rank: 4 blocks of 2; after A2A rank r holds block r of every rank
    x = jnp.arange(32.0).reshape(4, 8)  # rank r gets row r
    out = _smap(m, lambda v: col.alltoall(v.reshape(4, 2), "x"),
                P("x", None), P("x", None))(x)
    out = np.asarray(out).reshape(4, 4, 2)
    ref = np.arange(32.0).reshape(4, 4, 2).transpose(1, 0, 2)
    np.testing.assert_allclose(out, ref)


def test_ring_shift_and_edge_shifts(eight_devices):
    m = meshlib.make_flat_mesh(4)
    x = jnp.arange(4.0)
    shifted = _smap(m, lambda v: col.ring_shift(v, "x"), P("x"), P("x"))(x)
    np.testing.assert_allclose(shifted, [3, 0, 1, 2])  # rank r receives r-1
    up = _smap(m, lambda v: col.shift_up(v, "x"), P("x"), P("x"))(x)
    np.testing.assert_allclose(up, [0, 0, 1, 2])  # stage 0 gets zeros
    down = _smap(m, lambda v: col.shift_down(v, "x"), P("x"), P("x"))(x)
    np.testing.assert_allclose(down, [1, 2, 3, 0])  # last stage gets zeros


def test_subaxis_grouping(eight_devices):
    """Collectives over one axis of a 3D mesh act within (dp,pp) groups —
    the mesh-native replacement of comm colors (hybrid_3d.cpp:287-300)."""
    m = meshlib.make_grid_mesh(2, 2, 2)
    x = jnp.arange(8.0)

    def tp_sum(v):
        return col.allreduce(v, "tp")

    out = _smap(m, tp_sum, P(("dp", "pp", "tp")), P(("dp", "pp", "tp")))(x)
    # ranks (2k, 2k+1) pair up on the tp axis
    expect = [1, 1, 5, 5, 9, 9, 13, 13]
    np.testing.assert_allclose(out, expect)
