"""SPMD training-step tests: the dp x pp x tp(+sp,+ep) step must compile,
run, learn, and agree with a single-device reference on the 8-device mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.models import spmd


def test_factor_mesh():
    assert spmd.factor_mesh(8) == (2, 2, 2)
    assert spmd.factor_mesh(4) == (1, 2, 2)
    assert spmd.factor_mesh(2) == (1, 1, 2)
    assert spmd.factor_mesh(1) == (1, 1, 1)


def test_validate_errors():
    cfg = spmd.SpmdConfig(num_layers=3)
    with pytest.raises(ValueError, match="layers"):
        cfg.validate(2, 2, 2)


def test_spmd_step_runs_and_learns(eight_devices):
    mesh, cfg, step, params, tokens = spmd.build(8)
    assert mesh.devices.shape == (2, 2, 2)
    losses = []
    for _ in range(5):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # params stayed finite
    for leaf in jax.tree.leaves(params):
        assert np.all(np.isfinite(np.asarray(leaf, dtype=np.float32)))


@pytest.mark.slow  # ~20s sharded train step; bf16 twin covers the fast lane
def test_spmd_int8_mlp_step_runs_and_learns(eight_devices):
    """mlp_int8=True (expert matmuls quantized per-tensor, int32 MXU
    accumulation, straight-through backward) on the full dp x pp x tp
    mesh: the step runs, learns, and stays close to the master-dtype
    loss — the r5 single-chip int8 win certified on the EP-sharded
    path."""
    cfg = spmd.SpmdConfig(mlp_int8=True)
    mesh, cfg, step, params, tokens = spmd.build(8, cfg)
    losses = []
    for _ in range(5):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # quantization must not move the first-step loss far off master
    _, _, step_m, params_m, _ = spmd.build(8, spmd.SpmdConfig())
    _, l_m = step_m(params_m, tokens)
    assert losses[0] == pytest.approx(float(l_m), rel=0.05)


def test_spmd_matches_dataparallel_only(eight_devices):
    """pp=tp=1 (pure dp) must equal full dp x pp x tp on the same data to
    within numerical tolerance — the parallelism must not change the math.
    Capacity is set lossless (cap >= T*k): with finite capacity the EP
    token-drop pattern legitimately depends on the local token pool size,
    so only the no-drop regime is bitwise-comparable across tp."""
    cfg = spmd.SpmdConfig(capacity_factor=8.0)
    _, _, step8, params, tokens = spmd.build(8, cfg)
    _, _, step1, _, _ = spmd.build(1, cfg)
    p8, l8 = step8(params, tokens)
    p1, l1 = step1(params, tokens)
    assert float(l8) == pytest.approx(float(l1), rel=2e-3)
    # spot-check a parameter after one update
    d8 = np.asarray(p8["layers"]["wq"], dtype=np.float32)
    d1 = np.asarray(p1["layers"]["wq"], dtype=np.float32)
    np.testing.assert_allclose(d8, d1, rtol=0.05, atol=2e-4)


@pytest.mark.parametrize("sp_mode", ["ring", "ulysses"])
def test_spmd_sequence_parallel_modes_match(eight_devices, sp_mode):
    """ring / ulysses attention (ops/sequence_parallel.py) must produce the
    same training step as megatron SP and as the single-device reference
    (lossless EP capacity, see test_spmd_matches_dataparallel_only)."""
    cfg = spmd.SpmdConfig(capacity_factor=8.0, sp_mode=sp_mode)
    _, _, step8, params, tokens = spmd.build(8, cfg)
    _, _, step1, _, _ = spmd.build(1, spmd.SpmdConfig(capacity_factor=8.0))
    p8, l8 = step8(params, tokens)
    p1, l1 = step1(params, tokens)
    assert float(l8) == pytest.approx(float(l1), rel=2e-3)
    d8 = np.asarray(p8["layers"]["wq"], dtype=np.float32)
    d1 = np.asarray(p1["layers"]["wq"], dtype=np.float32)
    np.testing.assert_allclose(d8, d1, rtol=0.05, atol=2e-4)


# ---- r7 overlap paths: decomposed collective matmuls + bucketed sync ----
# Small shapes (the parity signal is structural, not scale) so the six
# extra 8-device compiles stay inside the tier-1 wall-time budget.
_SMALL = dict(embed_dim=32, num_heads=4, num_kv_heads=4, ff_dim=32,
              num_layers=2, seq_len=16, vocab_size=64, batch=8,
              capacity_factor=8.0)


@pytest.fixture(scope="module")
def small_baseline(eight_devices):
    """One blocking-baseline step at lossless EP capacity, shared by
    every overlap-parity test below (params/tokens included so all
    variants step the same state)."""
    cfg = spmd.SpmdConfig(**_SMALL)
    _, _, step, params, tokens = spmd.build(8, cfg)
    p0, l0 = step(params, tokens)
    return params, tokens, p0, l0


def _tree_max_diff(pa, pb):
    diffs = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                           - b.astype(jnp.float32)))),
        pa, pb)
    return max(jax.tree.leaves(diffs))


@pytest.mark.parametrize("sp_mode", ["megatron", "ring", "ulysses"])
def test_spmd_decomposed_tp_overlap_matches(small_baseline, sp_mode):
    """tp_overlap=decomposed (ppermute-pipelined collective matmuls,
    ops/collective_matmul.py) must reproduce the blocking psum path: in
    megatron mode every TP projection decomposes (tight tolerance — the
    only reordering is the ring reduce-scatter accumulation); in
    ring/ulysses only the vocab-parallel head does, compared against the
    megatron baseline at the established cross-mode tolerance."""
    params, tokens, p0, l0 = small_baseline
    cfg = spmd.SpmdConfig(sp_mode=sp_mode, tp_overlap="decomposed",
                          tp_overlap_chunks=2, **_SMALL)
    _, _, step, _, _ = spmd.build(8, cfg)
    px, lx = step(params, tokens)
    if sp_mode == "megatron":
        assert float(lx) == pytest.approx(float(l0), rel=1e-5)
        assert _tree_max_diff(px, p0) <= 1e-4
    else:
        assert float(lx) == pytest.approx(float(l0), rel=2e-3)
        d8 = np.asarray(px["layers"]["wq"], dtype=np.float32)
        d1 = np.asarray(p0["layers"]["wq"], dtype=np.float32)
        np.testing.assert_allclose(d8, d1, rtol=0.05, atol=2e-4)


def test_spmd_bucketed_grad_sync_matches(small_baseline):
    """grad_sync=bucketed (reverse-layer-order per-bucket psums chained
    with collectives.tie) is elementwise-identical math to the
    monolithic sync — the whole updated param tree must agree leaf-wise
    (grad-tree equality at fixed lr)."""
    params, tokens, p0, l0 = small_baseline
    cfg = spmd.SpmdConfig(grad_sync="bucketed", grad_bucket_layers=1,
                          **_SMALL)
    _, _, step, _, _ = spmd.build(8, cfg)
    px, lx = step(params, tokens)
    assert float(lx) == pytest.approx(float(l0), rel=1e-6)
    assert _tree_max_diff(px, p0) <= 1e-6


def test_spmd_decomposed_plus_bucketed_matches(small_baseline):
    """Both overlap paths together (the bench/driver 'overlapped'
    config), with a multi-layer bucket group."""
    params, tokens, p0, l0 = small_baseline
    cfg = spmd.SpmdConfig(tp_overlap="decomposed", tp_overlap_chunks=1,
                          grad_sync="bucketed", grad_bucket_layers=2,
                          **_SMALL)
    _, _, step, _, _ = spmd.build(8, cfg)
    px, lx = step(params, tokens)
    assert float(lx) == pytest.approx(float(l0), rel=1e-5)
    assert _tree_max_diff(px, p0) <= 1e-4


def test_spmd_overlap_config_validation():
    with pytest.raises(ValueError, match="tp_overlap"):
        spmd.SpmdConfig(tp_overlap="magic").validate(2, 2, 2)
    with pytest.raises(ValueError, match="grad_sync"):
        spmd.SpmdConfig(grad_sync="eager").validate(2, 2, 2)
    with pytest.raises(ValueError, match="chunks"):
        spmd.SpmdConfig(tp_overlap_chunks=0).validate(2, 2, 2)
    # A/B variants are defined for the megatron split only
    mesh, *_ = spmd.build(8, spmd.SpmdConfig())
    with pytest.raises(ValueError, match="variant"):
        spmd.make_train_step(mesh, spmd.SpmdConfig(), variant="half")
    with pytest.raises(ValueError, match="megatron"):
        spmd.make_train_step(mesh, spmd.SpmdConfig(sp_mode="ring"),
                             variant="comm")


def test_spmd_ring_runs_with_indivisible_heads(eight_devices):
    """ring mode has no heads%tp constraint (all heads stay local)."""
    cfg = spmd.SpmdConfig(num_heads=3, num_kv_heads=3, embed_dim=48,
                          capacity_factor=8.0, sp_mode="ring")
    _, _, step, params, tokens = spmd.build(8, cfg)
    _, loss = step(params, tokens)
    assert np.isfinite(float(loss))
    # megatron rejects the same shape
    with pytest.raises(ValueError, match="heads"):
        spmd.SpmdConfig(num_heads=3, num_kv_heads=3,
                        embed_dim=48).validate(2, 2, 2)


# ----------------------------------------- block-sparse masks (ISSUE 10)

longcontext = pytest.mark.longcontext


@longcontext
@pytest.mark.parametrize("kw", [
    dict(attention_window=8),
    dict(attention_seg_avg=12, attention_seg_seed=4),
    dict(attention_window=12, attention_seg_avg=16),
])
def test_spmd_masked_ring_matches_megatron(eight_devices, kw):
    """The dryrun-matrix certification as a test: for every masked
    config the sparse ring step (hop-verdict gating) must produce the
    SAME training step as megatron applying the identical mask densely
    on the gathered sequence — and the mask must actually skip hops."""
    import dataclasses

    from dlnetbench_tpu.parallel.mesh import make_grid_mesh
    mesh = make_grid_mesh(dp=2, pp=1, tp=4, devices=eight_devices)
    cfg_m = spmd.SpmdConfig(batch=8, num_microbatches=2,
                            capacity_factor=8.0, sp_mode="megatron",
                            **kw)
    cfg_r = dataclasses.replace(cfg_m, sp_mode="ring")
    params = spmd.init_params(jax.random.key(0), cfg_m)
    tokens = jax.random.randint(jax.random.key(1),
                                (8, cfg_m.seq_len + 1), 0,
                                cfg_m.vocab_size)
    p_m, l_m = spmd.make_train_step(mesh, cfg_m)(params, tokens)
    p_r, l_r = spmd.make_train_step(mesh, cfg_r)(params, tokens)
    assert abs(float(l_m) - float(l_r)) <= 1e-4
    for a, b in zip(jax.tree.leaves(p_m), jax.tree.leaves(p_r)):
        assert float(jnp.max(jnp.abs(
            a.astype(jnp.float32) - b.astype(jnp.float32)))) <= 1e-4
    stats = cfg_r.ring_hop_stats(4)
    # strict: the mask must skip hops BEYOND the causal triangle
    from dlnetbench_tpu.ops import attention_mask as amask
    assert stats["ring_skipped_hop_fraction"] \
        > amask.ring_skipped_hop_fraction(None, cfg_r.seq_len, 4)
    assert stats["ring_hops"] == 16


@longcontext
def test_spmd_mask_knob_validation_and_stats():
    with pytest.raises(ValueError, match="attention_window"):
        spmd.SpmdConfig(attention_window=-1).validate(2, 2, 2)
    cfg = spmd.SpmdConfig(attention_window=8)
    assert cfg.mask_spec is not None and cfg.mask_spec.window == 8
    assert spmd.SpmdConfig().mask_spec is None
    # plain causal still skips the strictly-future hop triangle
    frac = spmd.SpmdConfig().ring_hop_stats(4)
    assert frac["ring_skipped_hop_fraction"] == pytest.approx(6 / 16)
